"""Parallel fetch scheduler for the lazy-read data plane.

The serial lazy-read path (daemon/blobcache.py before this module) issued
one blocking ranged GET per miss, duplicate-fetched the same extent under
concurrent readers, and never looked ahead. This module is the data-plane
counterpart of the convert pipeline (parallel/pipeline.py): it turns every
cache miss into *flights* — in-flight ranged fetches tracked in a per-blob
singleflight table — and executes them on a multi-connection worker pool
under a byte-bounded in-flight budget (the same
:class:`~nydus_snapshotter_tpu.parallel.pipeline.MemoryBudget` discipline
the convert path uses):

- **singleflight**: concurrent misses on overlapping extents wait on the
  existing flight instead of re-fetching; only uncovered gaps spawn new
  flights, so no byte is ever fetched twice by racing readers;
- **coalescing**: adjacent miss gaps closer than ``merge_gap`` merge into
  one larger ranged GET (re-fetching the few covered bytes in between is
  cheaper than another HTTP round trip);
- **readahead**: a sequential reader extends its miss window ahead of the
  read as *background* flights, clamped to the blob size and isolated
  from the demand read — a failed readahead never fails a read;
- **prefetch replay**: :class:`PrefetchReplayer` walks prefetch file
  lists / fanotify traces through the bootstrap chunk index and warms the
  cache through the same scheduler at background priority, cancellable on
  umount.

Demand flights always dispatch before background ones; a demand read that
lands on a queued background flight promotes it. Observability lands in
``metrics/registry.default_registry`` as ``ntpu_blobcache_*``;
``failpoint.hit`` fires at the fetch / coalesce / readahead boundaries
(``blobcache.{fetch,coalesce,readahead}``) so the overlap is
chaos-testable (docs/robustness.md).
"""

from __future__ import annotations

import os
import threading
from bisect import bisect_left, bisect_right
from collections import deque
from dataclasses import dataclass
from time import perf_counter
from typing import Callable, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.parallel.pipeline import MemoryBudget

DEFAULT_FETCH_WORKERS = 4
DEFAULT_MERGE_GAP = 128 << 10
DEFAULT_READAHEAD = 1 << 20
DEFAULT_BUDGET_BYTES = 64 << 20
MAX_FETCH_WORKERS = 32

# Flight priorities: demand reads outrank readahead/prefetch warming.
DEMAND = 0
BACKGROUND = 1

_reg = _metrics.default_registry
HIT_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_hit_bytes",
        "Lazy-read bytes served from the local chunk cache",
    )
)
MISS_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_miss_bytes",
        "Lazy-read bytes that required a remote fetch",
    )
)
FETCH_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_fetch_requests",
        "Ranged GETs issued by the fetch scheduler",
    )
)
COALESCED_REQUESTS = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_coalesced_requests",
        "Ranged GETs that merged more than one miss gap",
    )
)
INFLIGHT_BYTES = _reg.register(
    _metrics.Gauge(
        "ntpu_blobcache_inflight_bytes",
        "Bytes currently being fetched by blobcache workers",
    )
)
READAHEAD_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_readahead_bytes",
        "Bytes fetched speculatively ahead of sequential readers",
    )
)
READAHEAD_HIT_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_readahead_hit_bytes",
        "Readahead bytes later served to a real read (accuracy numerator)",
    )
)
PREFETCH_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_prefetch_bytes",
        "Bytes warmed by the background prefetch replayer",
    )
)
SINGLEFLIGHT_WAITS = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_singleflight_waits",
        "Reads that piggybacked on another reader's in-flight fetch",
    )
)
EVICTED_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_evicted_bytes",
        "Bytes removed by capacity-watermark blob cache eviction",
    )
)
EVICTED_ENTRIES = _reg.register(
    _metrics.Counter(
        "ntpu_blobcache_evicted_entries",
        "Whole blob cache entries removed by capacity-watermark eviction",
    )
)
OP_HIST = _reg.register(
    _metrics.Histogram(
        "ntpu_blobcache_op_duration_milliseconds",
        "Latency of lazy-read data-plane operations (read_at / fetch),"
        " metered by the same window the trace spans record",
        ("op",),
    )
)


def snapshot_counters() -> dict:
    """Current cumulative ``ntpu_blobcache_*`` values (bench/tools delta
    these around a run)."""
    ra = READAHEAD_BYTES.value()
    return {
        "hit_bytes": HIT_BYTES.value(),
        "miss_bytes": MISS_BYTES.value(),
        "fetch_requests": FETCH_REQUESTS.value(),
        "coalesced_requests": COALESCED_REQUESTS.value(),
        "readahead_bytes": ra,
        "readahead_hit_bytes": READAHEAD_HIT_BYTES.value(),
        "readahead_accuracy": (
            READAHEAD_HIT_BYTES.value() / ra if ra else None
        ),
        "prefetch_bytes": PREFETCH_BYTES.value(),
        "singleflight_waits": SINGLEFLIGHT_WAITS.value(),
        "evicted_bytes": EVICTED_BYTES.value(),
        "evicted_entries": EVICTED_ENTRIES.value(),
    }


# ---------------------------------------------------------------------------
# Sorted-interval coverage
# ---------------------------------------------------------------------------


class IntervalSet:
    """Disjoint, sorted, half-open ``[start, end)`` intervals with
    bisect-based point/range queries — O(log n + k) where the previous
    blobcache scan was O(n) per read. Touching intervals merge."""

    __slots__ = ("_starts", "_ends")

    def __init__(self):
        self._starts: list[int] = []
        self._ends: list[int] = []

    def __len__(self) -> int:
        return len(self._starts)

    def add(self, start: int, end: int) -> None:
        if end <= start:
            return
        # Intervals whose end >= start and whose start <= end overlap or
        # touch [start, end): one contiguous run in the sorted lists.
        i = bisect_left(self._ends, start)
        j = bisect_right(self._starts, end)
        if i < j:
            start = min(start, self._starts[i])
            end = max(end, self._ends[j - 1])
        self._starts[i:j] = [start]
        self._ends[i:j] = [end]

    def covered(self, start: int, end: int) -> bool:
        if end <= start:
            return True
        i = bisect_right(self._starts, start) - 1
        return i >= 0 and self._ends[i] >= end

    def missing(self, start: int, end: int) -> list[tuple[int, int]]:
        """Sub-ranges of ``[start, end)`` not covered, in order."""
        if end <= start:
            return []
        gaps: list[tuple[int, int]] = []
        i = bisect_right(self._starts, start) - 1
        if i < 0 or self._ends[i] <= start:
            i += 1
        pos = start
        while pos < end and i < len(self._starts):
            s, e = self._starts[i], self._ends[i]
            if s >= end:
                break
            if pos < s:
                gaps.append((pos, s))
            pos = max(pos, e)
            i += 1
        if pos < end:
            gaps.append((pos, end))
        return gaps

    def spans(self) -> list[tuple[int, int]]:
        return list(zip(self._starts, self._ends))

    def total_bytes(self) -> int:
        return sum(e - s for s, e in zip(self._starts, self._ends))

    def clear(self) -> None:
        self._starts.clear()
        self._ends.clear()

    def remove(self, start: int, end: int) -> int:
        """Uncover ``[start, end)``; returns bytes actually removed."""
        if end <= start:
            return 0
        removed = 0
        keep_s: list[int] = []
        keep_e: list[int] = []
        for s, e in zip(self._starts, self._ends):
            if e <= start or s >= end:
                keep_s.append(s)
                keep_e.append(e)
                continue
            removed += min(e, end) - max(s, start)
            if s < start:
                keep_s.append(s)
                keep_e.append(start)
            if e > end:
                keep_s.append(end)
                keep_e.append(e)
        self._starts, self._ends = keep_s, keep_e
        return removed


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclass
class FetchConfig:
    fetch_workers: int = DEFAULT_FETCH_WORKERS
    merge_gap: int = DEFAULT_MERGE_GAP
    readahead: int = DEFAULT_READAHEAD
    budget_bytes: int = DEFAULT_BUDGET_BYTES
    prefetch_replay: bool = True


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
        return v if v >= 0 else default
    except ValueError:
        return default


def _global_blobcache_config():
    """The snapshotter's ``[blobcache]`` section when a global config is
    set (config/config.py); None in the daemon process / library use."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().blobcache
    except Exception:
        return None


def resolve_config() -> FetchConfig:
    """Resolve the lazy-read knobs: env > ``[blobcache]`` config > defaults.

    Environment overrides (``NTPU_BLOBCACHE*``) matter doubly here: the
    daemon is a separate process with no global snapshotter config, so the
    spawned environment is how the section reaches the data plane.
    """
    bc = _global_blobcache_config()
    workers = _env_int(
        "NTPU_BLOBCACHE_WORKERS",
        getattr(bc, "fetch_workers", 0) or DEFAULT_FETCH_WORKERS,
    )
    merge_gap = _env_int(
        "NTPU_BLOBCACHE_MERGE_GAP_KIB",
        -1,
    )
    if merge_gap < 0:
        gap_kib = getattr(bc, "merge_gap_kib", None)
        merge_gap = gap_kib if gap_kib is not None else (DEFAULT_MERGE_GAP >> 10)
    readahead = _env_int("NTPU_BLOBCACHE_READAHEAD_KIB", -1)
    if readahead < 0:
        ra_kib = getattr(bc, "readahead_kib", None)
        readahead = ra_kib if ra_kib is not None else (DEFAULT_READAHEAD >> 10)
    budget = _env_int(
        "NTPU_BLOBCACHE_BUDGET_MIB",
        getattr(bc, "inflight_budget_mib", 0) or (DEFAULT_BUDGET_BYTES >> 20),
    )
    prefetch_env = os.environ.get("NTPU_BLOBCACHE_PREFETCH", "")
    if prefetch_env:
        prefetch = prefetch_env not in ("0", "off", "false")
    else:
        prefetch = bool(getattr(bc, "prefetch_replay", True))
    return FetchConfig(
        fetch_workers=min(MAX_FETCH_WORKERS, max(1, workers)),
        merge_gap=merge_gap << 10,
        readahead=readahead << 10,
        budget_bytes=max(1, budget) << 20,
        prefetch_replay=prefetch,
    )


def resolve_watermark_bytes(config_mib: int) -> int:
    """``[blobcache].eviction_watermark_mib`` with its documented
    ``NTPU_BLOBCACHE_WATERMARK_MIB`` env override (env > config, like
    every other blobcache knob; 0 disables capacity eviction)."""
    mib = _env_int("NTPU_BLOBCACHE_WATERMARK_MIB", -1)
    if mib < 0:
        mib = max(0, int(config_mib))
    return mib << 20


_shared_budget: Optional[MemoryBudget] = None
_shared_budget_lock = threading.Lock()


def shared_budget() -> MemoryBudget:
    """Process-wide in-flight byte budget every scheduler without an
    explicit budget shares, so aggregate fetch memory is independent of
    how many blobs are being lazily read at once."""
    global _shared_budget
    with _shared_budget_lock:
        if _shared_budget is None:
            _shared_budget = MemoryBudget(resolve_config().budget_bytes)
        return _shared_budget


# ---------------------------------------------------------------------------
# Flights + scheduler
# ---------------------------------------------------------------------------


class Flight:
    """One in-flight ranged fetch covering ``[start, end)``."""

    __slots__ = ("start", "end", "priority", "coalesced", "done", "error", "ctx")

    def __init__(self, start: int, end: int, priority: int, coalesced: int = 1):
        self.start = start
        self.end = end
        self.priority = priority
        self.coalesced = coalesced  # miss gaps merged into this fetch
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        # Trace context of the read that PLANNED this flight — a
        # background readahead fetch thereby records which trace spawned
        # it, even though it executes on a worker thread later.
        self.ctx = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class FetchScheduler:
    """Per-blob singleflight table + coalescing planner + worker pool.

    The scheduler shares its caller's lock (the CachedBlob lock): every
    ``plan_locked`` call and every delivery runs under that one lock, so
    interval state, the flight table and the cache file never disagree.
    ``fetch_range(offset, size)`` runs concurrently on worker threads and
    must be thread-safe; ``deliver(offset, data)`` is called back under
    the lock to persist a completed fetch.
    """

    def __init__(
        self,
        lock: threading.Lock,
        intervals: IntervalSet,
        fetch_range: Callable[[int, int], bytes],
        deliver: Callable[[int, bytes], None],
        config: Optional[FetchConfig] = None,
        budget: Optional[MemoryBudget] = None,
        name: str = "",
    ):
        self.cfg = config or resolve_config()
        self.budget = budget or shared_budget()
        self.name = name
        self._lock = lock
        self._cv = threading.Condition(lock)
        self._intervals = intervals
        self._fetch_range = fetch_range
        self._deliver = deliver
        self._flights: list[Flight] = []  # active (queued or fetching)
        self._queue: deque[Flight] = deque()  # demand FIFO
        self._queue_bg: deque[Flight] = deque()  # background FIFO
        # Lockset annotation: flight table + queues must only ever be
        # touched under the shared lock (NTPU_ANALYZE=1 verifies).
        self._flights_shared = _an.shared(f"fetch.flights[{name}]")
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._closed = False

    # -- planning (caller holds the shared lock) ----------------------------

    def overlapping_flights(self, start: int, end: int) -> list[Flight]:
        return [f for f in self._flights if f.start < end and f.end > start]

    def plan_locked(
        self, start: int, end: int, priority: int = DEMAND
    ) -> list[Flight]:
        """Ensure ``[start, end)`` becomes resident: returns every flight
        the caller must wait on (pre-existing overlaps + newly created
        gap fetches). Caller holds the shared lock."""
        if self._closed:
            raise OSError(f"fetch scheduler {self.name!r} is closed")
        self._flights_shared.write()
        waiters = self.overlapping_flights(start, end)
        if waiters and priority == DEMAND:
            SINGLEFLIGHT_WAITS.inc()
            self._promote(waiters)
        # Gaps = uncovered minus already in flight.
        gaps: list[tuple[int, int]] = []
        for s, e in self._intervals.missing(start, end):
            pos = s
            for f in sorted(self.overlapping_flights(s, e), key=lambda f: f.start):
                if f.start > pos:
                    gaps.append((pos, f.start))
                pos = max(pos, f.end)
            if pos < e:
                gaps.append((pos, e))
        new = self._coalesce(gaps, priority)
        ctx = trace.capture() if new else None
        for f in new:
            f.ctx = ctx
            self._flights.append(f)
            (self._queue if priority == DEMAND else self._queue_bg).append(f)
        if new:
            self._spawn_workers(len(new))
            self._cv.notify_all()
        return waiters + new

    def _coalesce(self, gaps: list[tuple[int, int]], priority: int) -> list[Flight]:
        flights: list[Flight] = []
        for s, e in gaps:
            if (
                flights
                and s - flights[-1].end <= self.cfg.merge_gap
                and flights[-1].priority == priority
            ):
                failpoint.hit("blobcache.coalesce")
                flights[-1].end = e
                flights[-1].coalesced += 1
            else:
                flights.append(Flight(s, e, priority))
        return flights

    def _promote(self, flights: list[Flight]) -> None:
        """A demand read waits on these: background flights still queued
        jump to the demand queue so the reader isn't stuck behind other
        warming work."""
        for f in flights:
            if f.priority == BACKGROUND and f in self._queue_bg:
                self._queue_bg.remove(f)
                f.priority = DEMAND
                self._queue.append(f)

    # -- worker pool ---------------------------------------------------------

    def _spawn_workers(self, backlog: int) -> None:
        if self._idle >= backlog:
            return
        want = min(self.cfg.fetch_workers, len(self._threads) + backlog - self._idle)
        while len(self._threads) < want:
            t = threading.Thread(
                target=self._worker,
                name=f"ntpu-fetch-{self.name}-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._closed and not self._queue and not self._queue_bg:
                    self._idle += 1
                    try:
                        self._cv.wait()
                    finally:
                        self._idle -= 1
                if self._closed and not self._queue and not self._queue_bg:
                    return
                self._flights_shared.write()
                flight = (self._queue or self._queue_bg).popleft()
            self._run_flight(flight)

    def _run_flight(self, flight: Flight) -> None:
        n = flight.end - flight.start
        acquired = False
        t0 = perf_counter()
        with trace.with_context(flight.ctx), trace.span(
            "blobcache.fetch",
            blob=self.name,
            offset=flight.start,
            bytes=n,
            coalesced=flight.coalesced,
            background=flight.priority == BACKGROUND,
        ) as sp:
            try:
                self.budget.acquire(n, aborted=lambda: self._closed)
                acquired = True
                INFLIGHT_BYTES.set(self.budget.held)
                failpoint.hit("blobcache.fetch")
                data = self._fetch_range(flight.start, n)
                FETCH_REQUESTS.inc()
                if flight.coalesced > 1:
                    COALESCED_REQUESTS.inc()
                MISS_BYTES.inc(n)
                with self._lock:
                    if not self._closed:
                        self._deliver(flight.start, data)
            except BaseException as e:  # noqa: BLE001 — surfaced to waiters
                flight.error = e if isinstance(e, Exception) else OSError(str(e))
                sp.annotate(error=repr(flight.error))
            finally:
                if acquired:
                    self.budget.release(n)
                    INFLIGHT_BYTES.set(self.budget.held)
                with self._cv:
                    self._flights_shared.write()
                    try:
                        self._flights.remove(flight)
                    except ValueError:
                        pass
                    self._cv.notify_all()
                flight.done.set()
        OP_HIST.labels("fetch").observe((perf_counter() - t0) * 1000.0)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Abort queued flights, wake workers, join the pool. Caller must
        NOT hold the shared lock (workers need it to finish delivering)."""
        with self._cv:
            self._closed = True
            self._flights_shared.write()
            aborted = list(self._queue) + list(self._queue_bg)
            self._queue.clear()
            self._queue_bg.clear()
            for f in aborted:
                try:
                    self._flights.remove(f)
                except ValueError:
                    pass
                f.error = OSError(f"fetch scheduler {self.name!r} closed")
                f.done.set()
            self._cv.notify_all()
        for t in self._threads:
            t.join()
        self._threads.clear()


# ---------------------------------------------------------------------------
# Background prefetch replay
# ---------------------------------------------------------------------------


class PrefetchReplayer:
    """Replays a prefetch file list through the bootstrap chunk index to
    warm blob caches off the critical path.

    ``warm_chunk(rec)`` is provided by the owner (daemon/server.py): for
    registry-backed blobs it routes the chunk's compressed extent through
    the fetch scheduler at BACKGROUND priority; any other backend falls
    back to a plain read. The replayer owns cancellation: ``cancel()``
    (umount/close) stops the walk between chunks and is also observed by
    in-flight waits, so teardown never blocks on a cold registry.
    """

    def __init__(
        self,
        bootstrap,
        by_path: dict,
        warm_chunk: Callable[[object], int],
        name: str = "",
        on_file: Optional[Callable[[], None]] = None,
    ):
        self.bootstrap = bootstrap
        self.by_path = by_path
        self.warm_chunk = warm_chunk
        self.name = name
        self.on_file = on_file  # e.g. one batched chunk-map flush per file
        self.warmed_bytes = 0
        self.files_replayed = 0
        self._cancel = threading.Event()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def cancel(self) -> None:
        self._cancel.set()

    @staticmethod
    def paths_from_trace(trace_path: str, strip_prefix: str = "") -> list[str]:
        """Fanotify/optimizer access trace → ordered path list (first
        access first — that IS the replay priority)."""
        from nydus_snapshotter_tpu.prefetch.prefetch import patterns_from_trace

        text = patterns_from_trace(trace_path, strip_prefix=strip_prefix)
        return [p for p in text.split("\n") if p]

    def replay(self, paths: list[str]) -> int:
        """Warm every chunk of every path, in order; returns bytes warmed.
        Per-file errors are contained (prefetch lists are hints)."""
        import logging

        log = logging.getLogger(__name__)
        for path in paths:
            if self._cancel.is_set():
                break
            failpoint.hit("blobcache.replay")
            inode = self.by_path.get(path)
            if inode is None:
                continue
            if inode.hardlink_target:
                inode = self.by_path.get(inode.hardlink_target) or inode
            try:
                for rec in self.bootstrap.chunks[
                    inode.chunk_index : inode.chunk_index + inode.chunk_count
                ]:
                    if self._cancel.is_set():
                        break
                    n = self.warm_chunk(rec)
                    self.warmed_bytes += n
                    PREFETCH_BYTES.inc(n)
            except Exception:  # noqa: BLE001 — one bad hint must not
                # abandon the rest of the list
                log.warning("prefetch replay of %s failed", path, exc_info=True)
                continue
            if self._cancel.is_set():
                break  # cancelled mid-file: it was not fully replayed
            self.files_replayed += 1
            if self.on_file is not None:
                self.on_file()
        return self.warmed_bytes
