"""FUSE session: kernel-mountable read plane for RAFS instances.

Mounts a RAFS bootstrap as a real filesystem through /dev/fuse — the role
the external Rust nydusd plays for the reference (mount flow
pkg/filesystem/fs.go:268-431; failover keeps the kernel session alive by
passing the /dev/fuse fd through the supervisor, supervisor.go:107-178).

Two entry modes mirror nydusd's:
- ``mount()``  — open /dev/fuse, mount(2) with ``fd=N``, negotiate INIT.
- ``attach(fd)`` — adopt an already-negotiated session fd (takeover after
  failover/upgrade: the previous daemon died, the supervisor kept the fd,
  the kernel mount never noticed).

The server loop is deliberately simple: one reader thread per session,
answering from the in-memory bootstrap + BlobReader chunk path. RAFS is
immutable, so every mutating opcode returns EROFS.
"""

from __future__ import annotations

import ctypes
import errno
import logging
import os
import stat as stat_mod
import threading
from typing import Callable, Optional

from nydus_snapshotter_tpu.fusedev import protocol as fp
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap, Inode

logger = logging.getLogger(__name__)

MS_RDONLY = 1
MS_NOSUID = 2
MS_NODEV = 4
MNT_DETACH = 2


class FuseError(RuntimeError):
    pass


def fuse_available() -> bool:
    """Can this process realistically serve a kernel FUSE mount?"""
    try:
        return os.access("/dev/fuse", os.R_OK | os.W_OK) and os.geteuid() == 0
    except OSError:
        return False


def _libc():
    return ctypes.CDLL("libc.so.6", use_errno=True)


class RafsFuseOps:
    """Resolve FUSE requests against a parsed bootstrap.

    ``read_file(path, offset, size)`` is the chunk-resolving data callback
    (the daemon's _Instance.read — compression/batch/cipher handled there).
    """

    def __init__(self, bootstrap: Bootstrap, read_file: Callable[[str, int, int], bytes]):
        self.read_file = read_file
        self.by_ino: dict[int, Inode] = {}
        self.children: dict[int, dict[bytes, Inode]] = {}
        by_path: dict[str, Inode] = {}
        for inode in bootstrap.inodes:
            self.by_ino[inode.ino] = inode
            by_path[inode.path] = inode
        for inode in bootstrap.inodes:
            if inode.path == "/":
                continue
            parent = self.by_ino.get(inode.parent_ino)
            if parent is None:
                continue
            name = inode.path.rsplit("/", 1)[1].encode()
            self.children.setdefault(parent.ino, {})[name] = inode
        self._by_path = by_path
        # st_nlink: hardlink group sizes (alias + target count as links to
        # the same storage inode — what the reference nydusd reports);
        # directories report 2 + subdirectories.
        self._nlink: dict[int, int] = {}
        for inode in bootstrap.inodes:
            if stat_mod.S_ISDIR(inode.mode):
                self._nlink[inode.ino] = 2 + sum(
                    1
                    for c in self.children.get(inode.ino, {}).values()
                    if stat_mod.S_ISDIR(c.mode)
                )
            else:
                tgt = self.resolve(inode)
                self._nlink[tgt.ino] = self._nlink.get(tgt.ino, 0) + 1

    def resolve(self, inode: Inode) -> Inode:
        """Follow a hardlink to its storage inode."""
        if inode.hardlink_target:
            target = self._by_path.get(inode.hardlink_target)
            if target is not None:
                return target
        return inode

    def attr_bytes(self, inode: Inode) -> bytes:
        target = self.resolve(inode)
        return fp.pack_attr(
            ino=target.ino,
            size=target.size,
            mode=target.mode,
            nlink=self._nlink.get(target.ino, 1),
            uid=target.uid,
            gid=target.gid,
            rdev=target.rdev,
            mtime=target.mtime,
        )


class FuseSession:
    ENTRY_VALID_S = 3600  # immutable fs: cache aggressively
    _MOUNT_LOCK = threading.Lock()

    def __init__(self, ops: RafsFuseOps, mountpoint: str):
        self.ops = ops
        self.mountpoint = mountpoint
        self.fd = -1
        self._owns_mount = False
        self._thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self._wake_r = self._wake_w = -1

    # -- lifecycle ----------------------------------------------------------

    def mount(self) -> None:
        fd = os.open("/dev/fuse", os.O_RDWR)
        opts = f"fd={fd},rootmode=40000,user_id=0,group_id=0,default_permissions,allow_other"
        libc = _libc()
        with self._MOUNT_LOCK:
            rc = libc.mount(
                b"nydus-tpu",
                self.mountpoint.encode(),
                b"fuse.nydus-tpu",
                MS_RDONLY | MS_NOSUID | MS_NODEV,
                opts.encode(),
            )
        if rc != 0:
            err = ctypes.get_errno()
            os.close(fd)
            raise FuseError(f"mount({self.mountpoint}): {os.strerror(err)}")
        self.fd = fd
        self._owns_mount = True
        self._start()

    def attach(self, fd: int) -> None:
        """Adopt an existing (INIT-negotiated) session fd after takeover."""
        self.fd = fd
        self._owns_mount = True  # the mount exists; we answer for it now
        self._start()

    def _start(self) -> None:
        self._closed.clear()
        # Self-pipe: close() writes a byte so a serve thread parked in
        # select() wakes immediately. Closing the session fd alone cannot
        # interrupt a read that is already blocked in the kernel (and during
        # handoff the open file description stays alive via the successor's
        # dup, so a stolen read would silently swallow a request).
        self._wake_r, self._wake_w = os.pipe()
        self._thread = threading.Thread(
            target=self._serve, name=f"fuse:{self.mountpoint}", daemon=True
        )
        self._thread.start()

    def close(self, unmount: bool = True) -> None:
        """Stop serving; optionally tear down the kernel mount.

        ``unmount=False`` is the handoff mode: the serve thread is stopped
        *before* the fd is closed, so any request the kernel has queued
        stays queued for the successor that adopted the fd."""
        self._closed.set()
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=2)
        if unmount and self._owns_mount:
            with self._MOUNT_LOCK:
                _libc().umount2(self.mountpoint.encode(), MNT_DETACH)
        if self.fd >= 0:
            try:
                os.close(self.fd)
            except OSError:
                pass
            self.fd = -1
        for p in (self._wake_r, self._wake_w):
            try:
                os.close(p)
            except OSError:
                pass

    # -- server loop --------------------------------------------------------

    def _serve(self) -> None:
        import select

        bufsize = fp.MAX_WRITE + 8192
        while not self._closed.is_set():
            fd = self.fd
            if fd < 0:
                return
            try:
                ready, _, _ = select.select([fd, self._wake_r], [], [])
            except (OSError, ValueError):
                return
            # Re-check before reading: on handoff the pending request must
            # be left in the kernel queue for the successor, not consumed
            # by a daemon that can no longer reply.
            if self._closed.is_set():
                return
            if fd not in ready:
                continue
            try:
                req = os.read(fd, bufsize)
            except OSError as e:
                if e.errno in (errno.EINTR, errno.EAGAIN):
                    continue
                # ENODEV: unmounted. EBADF: fd released/closed underneath us.
                return
            if not req:
                return
            try:
                self._dispatch(req)
            except OSError:
                return
            except Exception:
                logger.exception("fuse dispatch error on %s", self.mountpoint)

    def _reply(self, unique: int, payload: bytes = b"", error: int = 0) -> None:
        fd = self.fd
        if fd < 0:
            return
        header = fp.OUT_HEADER.pack(fp.OUT_HEADER.size + len(payload), -error, unique)
        os.write(fd, header + payload)

    def _dispatch(self, req: bytes) -> None:
        (_length, opcode, unique, nodeid, _uid, _gid, _pid, _pad) = fp.IN_HEADER.unpack_from(req)
        body = req[fp.IN_HEADER.size :]
        if opcode == fp.INIT:
            self._op_init(unique, body)
        elif opcode in (fp.FORGET, fp.BATCH_FORGET):
            return  # no reply, ever
        elif opcode == fp.INTERRUPT:
            return
        elif opcode == fp.DESTROY:
            self._reply(unique)
            self._closed.set()
        elif opcode == fp.LOOKUP:
            self._op_lookup(unique, nodeid, body)
        elif opcode == fp.GETATTR:
            self._op_getattr(unique, nodeid)
        elif opcode == fp.READLINK:
            self._op_readlink(unique, nodeid)
        elif opcode in (fp.OPEN, fp.OPENDIR):
            self._reply(unique, fp.OPEN_OUT.pack(nodeid, 0, 0))
        elif opcode in (fp.RELEASE, fp.RELEASEDIR, fp.FLUSH, fp.FSYNC, fp.FSYNCDIR, fp.ACCESS):
            self._reply(unique)
        elif opcode == fp.READ:
            self._op_read(unique, nodeid, body)
        elif opcode == fp.READDIR:
            self._op_readdir(unique, nodeid, body)
        elif opcode == fp.READDIRPLUS:
            self._op_readdirplus(unique, nodeid, body)
        elif opcode == fp.STATFS:
            self._op_statfs(unique)
        elif opcode == fp.GETXATTR:
            self._op_getxattr(unique, nodeid, body)
        elif opcode == fp.LISTXATTR:
            self._op_listxattr(unique, nodeid, body)
        elif opcode == fp.LSEEK:
            self._op_lseek(unique, nodeid, body)
        elif opcode in fp.WRITE_OPCODES:
            self._reply(unique, error=fp.EROFS)
        else:
            self._reply(unique, error=fp.ENOSYS)

    # -- operations ---------------------------------------------------------

    def _op_init(self, unique: int, body: bytes) -> None:
        major, minor, max_readahead, _flags = fp.INIT_IN_PREFIX.unpack_from(body)
        if major != fp.FUSE_KERNEL_VERSION:
            self._reply(unique, error=fp.EIO)
            return
        out = fp.INIT_OUT.pack(
            fp.FUSE_KERNEL_VERSION,
            min(minor, fp.FUSE_KERNEL_MINOR),
            min(max_readahead, fp.MAX_READAHEAD),
            0,  # no feature flags: plain synchronous read-only serving
            16,  # max_background
            12,  # congestion_threshold
            fp.MAX_WRITE,
            1,  # time_gran
            0,
            0,
            0,
            0, 0, 0, 0, 0, 0, 0,
        )
        self._reply(unique, out)

    def _inode(self, nodeid: int) -> Optional[Inode]:
        return self.ops.by_ino.get(nodeid)

    def _entry_out(self, inode: Inode) -> bytes:
        target = self.ops.resolve(inode)
        return (
            fp.ENTRY_OUT_PREFIX.pack(
                target.ino, 0, self.ENTRY_VALID_S, self.ENTRY_VALID_S, 0, 0
            )
            + self.ops.attr_bytes(inode)
        )

    def _op_lookup(self, unique: int, nodeid: int, body: bytes) -> None:
        name = body.rstrip(b"\x00")
        kids = self.ops.children.get(nodeid)
        child = kids.get(name) if kids else None
        if child is None:
            self._reply(unique, error=fp.ENOENT)
            return
        self._reply(unique, self._entry_out(child))

    def _op_getattr(self, unique: int, nodeid: int) -> None:
        inode = self._inode(nodeid)
        if inode is None:
            self._reply(unique, error=fp.ENOENT)
            return
        out = (
            fp.ATTR_OUT_PREFIX.pack(self.ENTRY_VALID_S, 0, 0) + self.ops.attr_bytes(inode)
        )
        self._reply(unique, out)

    def _op_readlink(self, unique: int, nodeid: int) -> None:
        inode = self._inode(nodeid)
        if inode is None or not stat_mod.S_ISLNK(inode.mode):
            self._reply(unique, error=fp.EINVAL)
            return
        self._reply(unique, inode.symlink_target.encode())

    def _op_read(self, unique: int, nodeid: int, body: bytes) -> None:
        (_fh, offset, size, _rflags, _lock, _flags, _pad) = fp.READ_IN.unpack_from(body)
        inode = self._inode(nodeid)
        if inode is None:
            self._reply(unique, error=fp.ENOENT)
            return
        target = self.ops.resolve(inode)
        if not stat_mod.S_ISREG(target.mode):
            self._reply(unique, error=fp.EISDIR if stat_mod.S_ISDIR(target.mode) else fp.EINVAL)
            return
        try:
            data = self.ops.read_file(target.path, offset, size)
        except FileNotFoundError:
            self._reply(unique, error=fp.ENOENT)
            return
        except Exception:
            logger.exception("fuse read %s failed", target.path)
            self._reply(unique, error=fp.EIO)
            return
        self._reply(unique, data)

    def _dirents(self, nodeid: int) -> Optional[list[tuple[bytes, Inode]]]:
        inode = self._inode(nodeid)
        if inode is None or not stat_mod.S_ISDIR(inode.mode):
            return None
        parent = self.ops.by_ino.get(inode.parent_ino, inode)
        out: list[tuple[bytes, Inode]] = [(b".", inode), (b"..", parent)]
        out.extend(sorted(self.ops.children.get(nodeid, {}).items()))
        return out

    def _op_readdir(self, unique: int, nodeid: int, body: bytes) -> None:
        (_fh, offset, size, _rflags, _lock, _flags, _pad) = fp.READ_IN.unpack_from(body)
        entries = self._dirents(nodeid)
        if entries is None:
            self._reply(unique, error=fp.ENOTDIR)
            return
        out = bytearray()
        for i, (name, child) in enumerate(entries):
            if i < offset:
                continue
            target = self.ops.resolve(child)
            rec = fp.pack_dirent(target.ino, i + 1, name, (target.mode >> 12) & 0xF)
            if len(out) + len(rec) > size:
                break
            out += rec
        self._reply(unique, bytes(out))

    def _op_readdirplus(self, unique: int, nodeid: int, body: bytes) -> None:
        (_fh, offset, size, _rflags, _lock, _flags, _pad) = fp.READ_IN.unpack_from(body)
        entries = self._dirents(nodeid)
        if entries is None:
            self._reply(unique, error=fp.ENOTDIR)
            return
        out = bytearray()
        for i, (name, child) in enumerate(entries):
            if i < offset:
                continue
            target = self.ops.resolve(child)
            # direntplus = entry_out + dirent; "." and ".." carry an empty
            # entry (nodeid 0) so the kernel doesn't double-count lookups.
            if name in (b".", b".."):
                entry = fp.ENTRY_OUT_PREFIX.pack(0, 0, 0, 0, 0, 0) + fp.pack_attr(
                    target.ino, 0, target.mode
                )
            else:
                entry = self._entry_out(child)
            rec = entry + fp.pack_dirent(target.ino, i + 1, name, (target.mode >> 12) & 0xF)
            if len(out) + len(rec) > size:
                break
            out += rec
        self._reply(unique, bytes(out))

    def _op_statfs(self, unique: int) -> None:
        n_files = len(self.ops.by_ino)
        self._reply(unique, fp.KSTATFS.pack(0, 0, 0, n_files, 0, 4096, 255, 4096, 0))

    def _op_getxattr(self, unique: int, nodeid: int, body: bytes) -> None:
        size, _pad = fp.GETXATTR_IN.unpack_from(body)
        name = body[fp.GETXATTR_IN.size :].rstrip(b"\x00").decode("utf-8", "surrogateescape")
        inode = self._inode(nodeid)
        if inode is None:
            self._reply(unique, error=fp.ENOENT)
            return
        value = self.ops.resolve(inode).xattrs.get(name)
        if value is None:
            self._reply(unique, error=fp.ENODATA)
        elif size == 0:
            self._reply(unique, fp.GETXATTR_OUT.pack(len(value), 0))
        elif size < len(value):
            self._reply(unique, error=fp.ERANGE)
        else:
            self._reply(unique, value)

    def _op_listxattr(self, unique: int, nodeid: int, body: bytes) -> None:
        size, _pad = fp.GETXATTR_IN.unpack_from(body)
        inode = self._inode(nodeid)
        if inode is None:
            self._reply(unique, error=fp.ENOENT)
            return
        names = b"".join(
            k.encode("utf-8", "surrogateescape") + b"\x00"
            for k in sorted(self.ops.resolve(inode).xattrs)
        )
        if size == 0:
            self._reply(unique, fp.GETXATTR_OUT.pack(len(names), 0))
        elif size < len(names):
            self._reply(unique, error=fp.ERANGE)
        else:
            self._reply(unique, names)

    def _op_lseek(self, unique: int, nodeid: int, body: bytes) -> None:
        _fh, offset, whence, _pad = fp.LSEEK_IN.unpack_from(body)
        inode = self._inode(nodeid)
        if inode is None:
            self._reply(unique, error=fp.ENOENT)
            return
        size = self.ops.resolve(inode).size
        # SEEK_DATA(3): every byte is data; SEEK_HOLE(4): the hole is at EOF.
        if whence == 3:
            if offset >= size:
                self._reply(unique, error=6)  # ENXIO
            else:
                self._reply(unique, fp.LSEEK_OUT.pack(offset))
        elif whence == 4:
            self._reply(unique, fp.LSEEK_OUT.pack(size))
        else:
            self._reply(unique, error=fp.EINVAL)
