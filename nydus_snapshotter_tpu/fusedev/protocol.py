"""Raw /dev/fuse wire protocol: the subset a read-only RAFS filesystem needs.

The reference's data plane is the external Rust nydusd's FUSE server (driven
from pkg/filesystem/fs.go:268-431); this framework serves the kernel
directly. Struct layouts follow include/uapi/linux/fuse.h; the environment
ships no FUSE userspace library, so the framing lives here in ~200 lines of
struct definitions. Only the read path is implemented — RAFS is immutable,
every mutating opcode is answered with EROFS.
"""

from __future__ import annotations

import struct

FUSE_KERNEL_VERSION = 7
FUSE_KERNEL_MINOR = 36  # highest minor whose layouts are used here

# Opcodes (uapi/linux/fuse.h enum fuse_opcode).
LOOKUP = 1
FORGET = 2
GETATTR = 3
SETATTR = 4
READLINK = 5
MKNOD = 8
MKDIR = 9
UNLINK = 10
RMDIR = 11
RENAME = 12
LINK = 13
OPEN = 14
READ = 15
WRITE = 16
STATFS = 17
RELEASE = 18
FSYNC = 20
SETXATTR = 21
GETXATTR = 22
LISTXATTR = 23
REMOVEXATTR = 24
FLUSH = 25
INIT = 26
OPENDIR = 27
READDIR = 28
RELEASEDIR = 29
FSYNCDIR = 30
ACCESS = 34
CREATE = 35
INTERRUPT = 36
DESTROY = 38
BATCH_FORGET = 42
READDIRPLUS = 44
LSEEK = 46

WRITE_OPCODES = frozenset(
    {SETATTR, MKNOD, MKDIR, UNLINK, RMDIR, RENAME, LINK, WRITE, SETXATTR, REMOVEXATTR, CREATE}
)

IN_HEADER = struct.Struct("<IIQQIIII")  # len opcode unique nodeid uid gid pid pad
OUT_HEADER = struct.Struct("<IiQ")  # len error unique

INIT_IN_PREFIX = struct.Struct("<IIII")  # major minor max_readahead flags
# major minor max_readahead flags | max_background congestion | max_write
# time_gran | max_pages map_alignment | flags2 unused[7]
INIT_OUT = struct.Struct("<IIIIHHIIHHI7I")

# ino size blocks atime mtime ctime atimensec mtimensec ctimensec mode nlink
# uid gid rdev blksize flags
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")
ENTRY_OUT_PREFIX = struct.Struct("<QQQQII")  # nodeid generation entry/attr valid (+nsec)
ATTR_OUT_PREFIX = struct.Struct("<QII")  # attr_valid attr_valid_nsec dummy
OPEN_OUT = struct.Struct("<QII")  # fh open_flags padding
READ_IN = struct.Struct("<QQIIQII")  # fh offset size read_flags lock_owner flags pad
GETATTR_IN = struct.Struct("<IIQ")  # flags dummy fh
GETXATTR_IN = struct.Struct("<II")  # size padding
GETXATTR_OUT = struct.Struct("<II")  # size padding
ACCESS_IN = struct.Struct("<II")  # mask padding
DIRENT_PREFIX = struct.Struct("<QQII")  # ino off namelen type
# blocks bfree bavail files ffree (u64) | bsize namelen frsize padding (u32) | spare[6]
KSTATFS = struct.Struct("<QQQQQIIII24x")
LSEEK_IN = struct.Struct("<QQII")  # fh offset whence padding
LSEEK_OUT = struct.Struct("<Q")

MAX_WRITE = 128 * 1024
MAX_READAHEAD = 128 * 1024

ENOENT = 2
EIO = 5
EACCES = 13
EINVAL = 22
EROFS = 30
ERANGE = 34
ENOSYS = 38
ENODATA = 61
ENOTDIR = 20
EISDIR = 21


def pack_attr(
    ino: int,
    size: int,
    mode: int,
    nlink: int = 1,
    uid: int = 0,
    gid: int = 0,
    rdev: int = 0,
    mtime: int = 0,
    blksize: int = 4096,
) -> bytes:
    blocks = (size + 511) // 512
    return ATTR.pack(
        ino, size, blocks, mtime, mtime, mtime, 0, 0, 0, mode, nlink, uid, gid, rdev, blksize, 0
    )


def pack_dirent(ino: int, off: int, name: bytes, dtype: int) -> bytes:
    rec = DIRENT_PREFIX.pack(ino, off, len(name), dtype) + name
    pad = (-len(rec)) % 8
    return rec + b"\x00" * pad
