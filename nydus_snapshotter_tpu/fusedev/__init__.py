from nydus_snapshotter_tpu.fusedev.session import FuseError, FuseSession, fuse_available

__all__ = ["FuseSession", "FuseError", "fuse_available"]
