"""Adaptive per-chunk compression engine — the codec stage behind the
convert pipeline's speculative-compress workers.

BENCH_r05 shows the full convert path is compression-bound at reference
defaults (0.25 GiB/s uncompressed vs 0.115 GiB/s blake3+zstd), and a
large fraction of real container-layer bytes are *already compressed*
(.so/.a sections, media, wheels, jars) — zstd level 3 burns its full
per-byte cost on them to emit frames *larger* than the input. Per-chunk
frames are independent, so the fix is a per-chunk codec decision:

- **probe**: a cheap compressibility estimate per chunk — a sampled
  trial-compress at level 1 (``probe = "sample"``) or a byte-entropy
  estimate (``"entropy"``) — classifying the chunk into bypass / fast /
  default / best corpus classes;
- **store-raw bypass**: incompressible chunks are stored uncompressed
  (``COMPRESSOR_NONE`` chunk flag — already first-class in the format,
  so every existing reader handles them);
- **per-class levels**: low-gain chunks drop to a fast level (nearly the
  same ratio at a fraction of the cost), high-gain chunks may opt into a
  better level;
- **corpus-trained dictionaries**: a ZDICT dictionary trained from chunk
  samples during batch convert (epoch-stamped, persisted alongside the
  chunk dictionary and shared through ``parallel/dict_service.py``)
  compresses small/medium chunks against shared context;
- **per-worker context reuse**: each compress worker pins ONE
  ``ZSTD_CCtx`` (and one digested ``CDict`` per level) for its whole
  run — no per-chunk context allocation, no pool lock on the hot path.

Everything is OFF by default: with ``[compression] adaptive = false``
(the default) no codec object is even constructed and pack output is
byte-identical to the serial reference lane. Enabling the engine is a
documented chunk-frame format change: bypass chunks read back through
any existing reader, but **trained-dict frames carry a versioned header
(``nZD1`` + dictionary id) and fail loudly without the dictionary**
(see :func:`decode_trained_frame`).

The stage interface is deliberately tiny — ``encode(view) -> (payload,
chunk_flag)``, deterministic in content alone — so a device-offloaded
codec (the "GPUs as Storage System Accelerators" framing: batch
independent per-chunk codec work onto an accelerator) can slot in behind
the same call without touching the converter walk.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Optional

from nydus_snapshotter_tpu import constants, failpoint
from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.utils import zstd as zstd_native

_reg = _metrics.default_registry

PROBE_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_compress_probe_total",
        "Per-chunk compressibility-probe decisions by class "
        "(bypass/fast/default/best; fallback = probe failed, chunk "
        "compressed at the default level)",
        ("decision",),
    )
)
BYPASS_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_compress_bypass_bytes_total",
        "Chunk bytes stored raw by the incompressibility bypass",
    )
)
LEVEL_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_compress_level_bytes_total",
        "Input chunk bytes compressed per zstd level",
        ("level",),
    )
)
DICT_BYTES = _reg.register(
    _metrics.Counter(
        "ntpu_compress_trained_dict_bytes_total",
        "Input chunk bytes compressed against a trained dictionary",
    )
)
CTX_REUSE = _reg.register(
    _metrics.Counter(
        "ntpu_compress_ctx_reuse_total",
        "Encodes served by an already-pinned per-worker compression context",
    )
)
TRAIN_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_compress_train_total",
        "Dictionary training outcomes (trained / failed / skipped)",
        ("outcome",),
    )
)
BATCH_TOTAL = _reg.register(
    _metrics.Counter(
        "ntpu_compress_batch_total",
        "Batched encode calls served by the native batch lane "
        "(one GIL-released ntpu_encode_batch call per level group)",
    )
)
BATCH_CHUNKS = _reg.register(
    _metrics.Counter(
        "ntpu_compress_batch_chunks_total",
        "Chunks whose zstd frame came out of the native batch lane",
    )
)


class CodecError(RuntimeError):
    """Adaptive-codec failure (probe/train/encode/decode)."""


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclass
class CodecConfig:
    """Resolved ``[compression]`` knobs (env > global config > defaults).

    ``adaptive`` is the master switch; with it off nothing below
    applies and pack output stays byte-identical to the reference lane.
    Ratios are predicted ``compressed/uncompressed`` on the probe sample:
    ``>= bypass_ratio`` stores raw, ``>= low_gain_ratio`` compresses at
    ``level_fast``, ``<= high_gain_ratio`` at ``level_best``, the rest at
    ``level_default`` (0 = ``constants.ZSTD_LEVEL``).
    """

    adaptive: bool = False
    probe: str = "sample"  # sample | entropy | off
    probe_sample_kib: int = 16
    bypass_ratio: float = 0.97
    low_gain_ratio: float = 0.85
    high_gain_ratio: float = 0.35
    level_fast: int = 1
    level_default: int = 0  # 0 = constants.ZSTD_LEVEL
    # The high-gain class defaults to the reference level — the default
    # engine is strictly speed-positive (bypass + fast-lane savings,
    # never a costlier level). Raising level_best trades some of that
    # win back into ratio on exactly the chunks where a level is
    # cheapest per saved byte (the profile tool's levels arm measures
    # the trade).
    level_best: int = 3
    dict_path: str = ""  # epoch-stamped trained dictionary to load
    train: bool = False  # train per-namespace during batch convert
    train_dict_kib: int = 112
    train_sample_mib: int = 8
    # Batched codec lane: how many chunks a pipeline compress worker may
    # drain into one encode_batch() call (0 disables draining — every
    # chunk goes through encode() alone). Output is byte-identical either
    # way; the batch only changes how many frames one GIL-released native
    # call produces.
    batch_chunks: int = 16
    # Vectorized CDC scan: auto = use the SIMD lane-parallel scanner when
    # the native library exposes it, on = require it (loud failure when
    # absent), off = always the sequential gear scanner. Cut positions
    # are identical across all three — this is purely a throughput knob.
    vectorized: str = "auto"

    # Chunks below this size skip the probe (probe overhead beats any
    # possible saving) and compress at the default level.
    MIN_PROBE_BYTES = 4096

    def effective_level(self, cls: str) -> int:
        if cls == "fast":
            return self.level_fast
        if cls == "best":
            return self.level_best
        return self.level_default or constants.ZSTD_LEVEL


def _env_str(name: str, default: str) -> str:
    v = os.environ.get(name, "")
    return v if v else default


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if v in ("", None):
        return default
    return v not in ("0", "off", "false", "no")


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
        return v if v > 0 else default
    except ValueError:
        return default


def _env_int0(name: str, default: int) -> int:
    """Like :func:`_env_int` but 0 is a valid (disabling) value."""
    try:
        v = int(os.environ.get(name, ""))
        return v if v >= 0 else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _global_compression_config():
    """The daemon's ``[compression]`` section when a global config is set
    (config/config.py); None in library/tool use."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().compression
    except Exception:
        return None


def resolve_codec_config() -> CodecConfig:
    """env (``NTPU_COMPRESS_*``) > ``[compression]`` config > defaults."""
    c = _global_compression_config()
    cfg = CodecConfig(
        adaptive=_env_bool(
            "NTPU_COMPRESS_ADAPTIVE", bool(getattr(c, "adaptive", False))
        ),
        probe=_env_str("NTPU_COMPRESS_PROBE", getattr(c, "probe", "") or "sample"),
        probe_sample_kib=_env_int(
            "NTPU_COMPRESS_PROBE_SAMPLE_KIB",
            getattr(c, "probe_sample_kib", 0) or 16,
        ),
        bypass_ratio=_env_float(
            "NTPU_COMPRESS_BYPASS_RATIO", getattr(c, "bypass_ratio", 0.97)
        ),
        low_gain_ratio=getattr(c, "low_gain_ratio", 0.85),
        high_gain_ratio=getattr(c, "high_gain_ratio", 0.35),
        dict_path=_env_str("NTPU_COMPRESS_DICT", getattr(c, "dict_path", "") or ""),
        train=_env_bool("NTPU_COMPRESS_TRAIN", bool(getattr(c, "train", False))),
        train_dict_kib=getattr(c, "train_dict_kib", 112) or 112,
        train_sample_mib=getattr(c, "train_sample_mib", 8) or 8,
        level_fast=getattr(c, "level_fast", 1),
        level_default=getattr(c, "level_default", 0),
        level_best=getattr(c, "level_best", 3),
        batch_chunks=_env_int0(
            "NTPU_COMPRESS_BATCH_CHUNKS", getattr(c, "batch_chunks", 16)
        ),
        vectorized=_env_str(
            "NTPU_COMPRESS_VECTORIZED", getattr(c, "vectorized", "") or "auto"
        ),
    )
    if cfg.vectorized not in ("auto", "on", "off"):
        cfg.vectorized = "auto"
    levels = os.environ.get("NTPU_COMPRESS_LEVELS", "")
    if levels:
        try:
            fast, default, best = (int(x) for x in levels.split(","))
            cfg.level_fast, cfg.level_default, cfg.level_best = fast, default, best
        except ValueError:
            pass
    return cfg


def resolve_codec(opt) -> "Optional[AdaptiveCodec]":
    """The pack path's codec hook: an :class:`AdaptiveCodec` when the
    adaptive engine is enabled AND applies to this pack (zstd compressor,
    system libzstd bound), else ``None`` — the byte-identical default."""
    if getattr(opt, "compressor", "") != "zstd":
        return None
    cfg = resolve_codec_config()
    if not cfg.adaptive or not zstd_native.available():
        return None
    trained = None
    if cfg.dict_path:
        trained = TrainedDict.load(cfg.dict_path)
    codec = AdaptiveCodec(cfg, trained=trained)
    if cfg.train and trained is None:
        codec.attach_trainer()
    return codec


# ---------------------------------------------------------------------------
# Trained dictionaries: file format, registry, digested handles
# ---------------------------------------------------------------------------

# Chunk-frame header for trained-dict frames. Versioned: the trailing
# digit is the layout version — readers reject versions they don't know
# LOUDLY instead of feeding libzstd a frame it cannot have the dict for.
TRAINED_FRAME_MAGIC = b"nZD1"
_TRAINED_HEADER = struct.Struct("<4sI")  # magic | dict_id

# Epoch-stamped on-disk format (the v5 chunk-dict discipline:
# header-last is not needed here because the file is written whole, but
# the checksum rejects torn/corrupt writes).
_DICT_FILE_MAGIC = b"NTPUZDCT"
_DICT_FILE_VERSION = 1
_DICT_HDR = struct.Struct("<8sIIQI")  # magic | version | dict_id | epoch | len


class TrainedDict:
    """An epoch-stamped ZDICT dictionary: the trained bytes plus the
    identity (``dict_id``) every frame compressed with it embeds."""

    def __init__(self, dict_bytes: bytes, epoch: int):
        self.bytes = dict_bytes
        self.epoch = int(epoch)
        self.dict_id = zstd_native.dict_id_of(dict_bytes)
        if self.dict_id == 0:
            raise CodecError("trained dictionary carries no ZDICT id")

    # -- wire/disk format ----------------------------------------------------

    def serialize(self) -> bytes:
        hdr = _DICT_HDR.pack(
            _DICT_FILE_MAGIC,
            _DICT_FILE_VERSION,
            self.dict_id,
            self.epoch,
            len(self.bytes),
        )
        return hdr + self.bytes + hashlib.sha256(hdr + self.bytes).digest()[:8]

    @classmethod
    def deserialize(cls, data: bytes) -> "TrainedDict":
        if len(data) < _DICT_HDR.size + 8:
            raise CodecError("trained-dict blob too short")
        magic, version, dict_id, epoch, n = _DICT_HDR.unpack_from(data)
        if magic != _DICT_FILE_MAGIC:
            raise CodecError("not a trained-dict blob (bad magic)")
        if version != _DICT_FILE_VERSION:
            raise CodecError(f"unsupported trained-dict format v{version}")
        end = _DICT_HDR.size + n
        if len(data) < end + 8:
            raise CodecError("trained-dict blob truncated")
        if hashlib.sha256(data[:end]).digest()[:8] != data[end : end + 8]:
            raise CodecError("trained-dict blob checksum mismatch (torn write?)")
        td = cls(data[_DICT_HDR.size : end], epoch)
        if td.dict_id != dict_id:
            raise CodecError(
                f"trained-dict id skew: header says {dict_id}, "
                f"payload says {td.dict_id}"
            )
        return td

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self.serialize())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "TrainedDict":
        with open(path, "rb") as f:
            return cls.deserialize(f.read())


class _DictHandles:
    """Digested handles for one registered dictionary: a DDict for decode
    plus lazily-created per-level CDicts for encode."""

    def __init__(self, td: TrainedDict):
        self.td = td
        self.ddict = zstd_native.DDict(td.bytes)
        self._cdicts: dict[int, zstd_native.CDict] = {}
        self._mu = threading.Lock()

    def cdict(self, level: int) -> zstd_native.CDict:
        with self._mu:
            cd = self._cdicts.get(level)
            if cd is None:
                cd = self._cdicts[level] = zstd_native.CDict(self.td.bytes, level)
            return cd


_registry_mu = threading.Lock()
_dict_registry: dict[int, _DictHandles] = {}


def register_trained_dict(td: TrainedDict) -> _DictHandles:
    """Make a trained dictionary decodable process-wide (keyed by its
    embedded dict id — the id every frame it produced carries)."""
    with _registry_mu:
        h = _dict_registry.get(td.dict_id)
        if h is None or h.td.epoch < td.epoch:
            h = _dict_registry[td.dict_id] = _DictHandles(td)
        return h


def unregister_trained_dict(dict_id: int) -> None:
    with _registry_mu:
        _dict_registry.pop(dict_id, None)


def lookup_trained_dict(dict_id: int) -> Optional[_DictHandles]:
    with _registry_mu:
        return _dict_registry.get(dict_id)


def is_trained_frame(data) -> bool:
    """True when a COMPRESSOR_ZSTD chunk payload is a trained-dict frame
    (``nZD1`` header). A plain zstd frame can never collide: its first
    byte is the zstd magic's 0x28 (or 0x50-0x5f for skippable frames),
    never ``n``."""
    return len(data) >= _TRAINED_HEADER.size and bytes(data[:4]) == TRAINED_FRAME_MAGIC


def decode_trained_frame(data, expect_size: int = 0) -> bytes:
    """Decode one ``nZD1`` trained-dict chunk frame.

    Fails LOUDLY — naming the dictionary id the frame was compressed
    with — when that dictionary is not registered in this process; a
    reader must fetch it (``[compression] dict_path``, or the dict
    service's ``zdict`` endpoint) before it can serve the blob.
    """
    magic, dict_id = _TRAINED_HEADER.unpack_from(bytes(data[: _TRAINED_HEADER.size]))
    if magic != TRAINED_FRAME_MAGIC:
        raise CodecError("not a trained-dict chunk frame")
    h = lookup_trained_dict(dict_id)
    if h is None:
        raise CodecError(
            f"chunk frame was compressed with trained zstd dictionary "
            f"id={dict_id} which is not loaded — load the namespace's "
            f"epoch-stamped dictionary (config [compression] dict_path, "
            f"or GET /api/v1/dict/<ns>/zdict) before reading this blob"
        )
    try:
        return zstd_native.decompress_with_ddict(
            data[_TRAINED_HEADER.size :], h.ddict, expect_size
        )
    except zstd_native.ZstdError as e:
        raise CodecError(str(e)) from e


# ---------------------------------------------------------------------------
# Dictionary training
# ---------------------------------------------------------------------------


class DictTrainer:
    """Bounded, deterministic chunk-sample reservoir for ZDICT training.

    Compress workers ``offer()`` every chunk they encode; the trainer
    keeps a deterministic every-Nth stride of them (clamped per-sample so
    one huge chunk cannot eat the budget) until ``train_sample_mib`` is
    reached. Training runs ONCE, off the converter's ordered path.
    """

    STRIDE = 4  # keep every 4th offered chunk
    SAMPLE_CLAMP = 64 << 10  # per-sample byte cap
    MIN_SAMPLES = 8

    def __init__(self, cfg: CodecConfig):
        self.cfg = cfg
        self._mu = threading.Lock()
        self._samples: list[bytes] = []
        self._bytes = 0
        self._seen = 0
        self._budget = cfg.train_sample_mib << 20

    def offer(self, data) -> None:
        if self._bytes >= self._budget:
            return
        with self._mu:
            self._seen += 1
            if self._seen % self.STRIDE or self._bytes >= self._budget:
                return
            piece = bytes(data[: self.SAMPLE_CLAMP])
            if not piece:
                return
            self._samples.append(piece)
            self._bytes += len(piece)

    def ready(self) -> bool:
        with self._mu:
            return (
                self._bytes >= self._budget and len(self._samples) >= self.MIN_SAMPLES
            )

    def stats(self) -> dict:
        with self._mu:
            return {
                "samples": len(self._samples),
                "bytes": self._bytes,
                "seen": self._seen,
            }

    def train(self, epoch: Optional[int] = None) -> TrainedDict:
        """ZDICT training over the reservoir → an epoch-stamped
        :class:`TrainedDict`. Raises :class:`CodecError` on failure (the
        caller falls back to untrained compression)."""
        failpoint.hit("compress.train")
        with self._mu:
            samples = list(self._samples)
        if len(samples) < self.MIN_SAMPLES:
            raise CodecError(
                f"too few chunk samples to train a dictionary "
                f"({len(samples)} < {self.MIN_SAMPLES})"
            )
        try:
            dict_bytes = zstd_native.train_dict(
                samples, self.cfg.train_dict_kib << 10
            )
        except zstd_native.ZstdError as e:
            raise CodecError(str(e)) from e
        return TrainedDict(dict_bytes, epoch if epoch is not None else int(time.time()))


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


class _WorkerState:
    """One compress worker's pinned codec state: a ZSTD_CCtx taken from
    the pool ONCE (returned when the worker thread dies) — per-chunk
    encode pays neither a context allocation nor the pool lock."""

    __slots__ = ("ctx", "_fin", "__weakref__")

    def __init__(self):
        self.ctx = zstd_native.cctx_acquire()
        self._fin = weakref.finalize(self, zstd_native.cctx_release, self.ctx)


class AdaptiveCodec:
    """The codec stage: ``encode(view) -> (payload, chunk_flag)``.

    Deterministic in chunk content alone (probe, level choice and codec
    output are pure functions of the bytes + config), so the pipeline's
    speculative compress workers and the inline assembler produce
    identical payloads — the same invariant the fixed-level lane holds.
    Thread-safe: per-worker state is thread-local.
    """

    def __init__(
        self,
        cfg: Optional[CodecConfig] = None,
        trained: Optional[TrainedDict] = None,
        trainer: Optional[DictTrainer] = None,
    ):
        if not zstd_native.available():
            raise CodecError("adaptive codec needs the system libzstd")
        self.cfg = cfg or resolve_codec_config()
        self.trained: Optional[TrainedDict] = None
        self._handles: Optional[_DictHandles] = None
        self._trainer = trainer
        self._train_failed = False
        self._tls = threading.local()
        self.counts = {"bypass": 0, "fast": 0, "default": 0, "best": 0, "fallback": 0}
        self.class_bytes = {"bypass": 0, "fast": 0, "default": 0, "best": 0, "fallback": 0}
        self._mu = threading.Lock()
        if trained is not None:
            self.set_trained(trained)

    # -- dictionary lifecycle ------------------------------------------------

    def set_trained(self, td: TrainedDict) -> None:
        """Adopt (and globally register, so this process can decode its
        own output) a trained dictionary."""
        self._handles = register_trained_dict(td)
        self.trained = td

    def attach_trainer(self) -> DictTrainer:
        if self._trainer is None:
            self._trainer = DictTrainer(self.cfg)
        return self._trainer

    @property
    def trainer(self) -> Optional[DictTrainer]:
        return self._trainer

    def maybe_train(self, force: bool = False) -> Optional[TrainedDict]:
        """Train once the sample reservoir is full (or ``force``d with
        whatever it holds). Training failure is NOT fatal: the codec
        falls back to untrained compression permanently and says so in
        ``ntpu_compress_train_total{outcome="failed"}``."""
        if self.trained is not None or self._trainer is None or self._train_failed:
            return None
        if not force and not self._trainer.ready():
            return None
        try:
            td = self._trainer.train()
        except failpoint.Panic:
            raise
        except Exception:
            self._train_failed = True
            TRAIN_TOTAL.labels("failed").inc()
            return None
        self.set_trained(td)
        TRAIN_TOTAL.labels("trained").inc()
        return td

    # -- probe ---------------------------------------------------------------

    def _sample(self, data) -> bytes:
        """Up to ``probe_sample_kib`` KiB as head/middle/tail slices —
        deterministic in content, cheap to assemble."""
        n = len(data)
        budget = self.cfg.probe_sample_kib << 10
        if n <= budget:
            return bytes(data)
        piece = budget // 3
        mid = (n - piece) // 2
        return b"".join(
            (
                bytes(data[:piece]),
                bytes(data[mid : mid + piece]),
                bytes(data[n - piece :]),
            )
        )

    def _predicted_ratio(self, data) -> float:
        sample = self._sample(data)
        if not sample:
            return 0.0
        if self.cfg.probe == "entropy":
            import numpy as np

            counts = np.bincount(
                np.frombuffer(sample, dtype=np.uint8), minlength=256
            )
            p = counts[counts > 0] / len(sample)
            h = float(-(p * np.log2(p)).sum())  # bits/byte
            return h / 8.0
        st = self._state()
        comp = zstd_native.compress_with_ctx(st.ctx, sample, self.cfg.level_fast)
        return len(comp) / len(sample)

    def classify(self, data) -> str:
        """The per-chunk corpus class — bypass / fast / default / best.
        Probe failure (chaos-injectable at ``compress.probe``) degrades
        to ``fallback``: always-compress at the default level."""
        if self.cfg.probe == "off" or len(data) < CodecConfig.MIN_PROBE_BYTES:
            return "default"
        try:
            failpoint.hit("compress.probe")
            r = self._predicted_ratio(data)
        except failpoint.Panic:
            raise
        except Exception:
            return "fallback"
        if r >= self.cfg.bypass_ratio:
            return "bypass"
        if r >= self.cfg.low_gain_ratio:
            return "fast"
        if r <= self.cfg.high_gain_ratio:
            return "best"
        return "default"

    # -- encode --------------------------------------------------------------

    def _state(self) -> _WorkerState:
        st = getattr(self._tls, "st", None)
        if st is None:
            st = self._tls.st = _WorkerState()
        return st

    def _count(self, cls: str, n: int) -> None:
        with self._mu:
            self.counts[cls] += 1
            self.class_bytes[cls] += n

    def _plan(self, data) -> tuple[str, Optional[int]]:
        """Shared per-chunk front half of :meth:`encode` and
        :meth:`encode_batch`: trainer offer, classification, class
        accounting. Returns ``(cls, level)``; ``level is None`` means the
        store-raw bypass already decided the chunk."""
        n = len(data)
        if self._trainer is not None and self.trained is None:
            self._trainer.offer(data)
        cls = self.classify(data)
        self._count(cls, n)
        PROBE_TOTAL.labels(cls).inc()
        if cls == "bypass":
            BYPASS_BYTES.inc(n)
            return cls, None
        return cls, self.cfg.effective_level(cls)

    def _seal(self, data, cls: str, level: int, payload: bytes) -> tuple[bytes, int]:
        """Shared back half: per-level byte accounting plus the
        late-bypass backstop. A frame that grew past the raw bytes stores
        raw. (The probe already catches ~all of these; this is the
        backstop that makes storing a frame never cost ratio. The
        fallback class skips it — probe failure means always-compress.)"""
        n = len(data)
        LEVEL_BYTES.labels(str(level)).inc(n)
        if len(payload) >= n and n > 0 and cls != "fallback":
            BYPASS_BYTES.inc(n)
            return bytes(data), constants.COMPRESSOR_NONE
        return payload, constants.COMPRESSOR_ZSTD

    def _encode_dict(self, data, cls: str, level: int) -> tuple[bytes, int]:
        """The trained-dictionary frame lane (``nZD1`` header + CDict
        body). Per-chunk by nature: digested CDicts are per-frame zstd
        API, so the batch lane below never routes these."""
        if getattr(self._tls, "st", None) is not None:
            CTX_REUSE.inc()
        st = self._state()
        payload = _TRAINED_HEADER.pack(
            TRAINED_FRAME_MAGIC, self.trained.dict_id
        ) + zstd_native.compress_with_cdict(st.ctx, data, self._handles.cdict(level))
        DICT_BYTES.inc(len(data))
        return self._seal(data, cls, level, payload)

    def encode(self, data) -> tuple[bytes, int]:
        """One chunk → ``(payload, chunk_compressor_flag)``.

        The pipeline's speculative compress workers and the serial
        assembler both call exactly this; determinism in content keeps
        them byte-identical.
        """
        failpoint.hit("compress.encode")
        cls, level = self._plan(data)
        if level is None:
            return bytes(data), constants.COMPRESSOR_NONE
        if self._handles is not None:
            return self._encode_dict(data, cls, level)
        if getattr(self._tls, "st", None) is not None:
            CTX_REUSE.inc()
        st = self._state()
        payload = zstd_native.compress_with_ctx(st.ctx, data, level)
        return self._seal(data, cls, level, payload)

    def encode_batch(self, views, n_threads: int = 1) -> list[tuple[bytes, int]]:
        """Many chunks → ``[(payload, chunk_flag)]``, byte-identical to
        ``[encode(v) for v in views]``.

        Per-chunk probe/class/dictionary decisions stay in Python (pure
        in content and cheap); every chunk that lands on the PLAIN zstd
        lane is then compressed by ONE GIL-released native call per level
        group (``ntpu_encode_batch``: pinned per-thread ``ZSTD_CCtx``s in
        C, frames byte-identical to :func:`zstd.compress_with_ctx` —
        libzstd's one-shot ``ZSTD_compressCCtx`` on both sides). Bypass,
        trained-dict and fallback-class chunks take exactly the per-chunk
        path, as does everything when the native arm is unavailable. The
        batch entry is the future device-codec slot: a GPU/TPU codec
        replaces the native call, not the converter walk.
        """
        failpoint.hit("compress.batch")
        results: list[Optional[tuple[bytes, int]]] = [None] * len(views)
        groups: dict[int, list[int]] = {}
        classes: dict[int, str] = {}
        for i, data in enumerate(views):
            failpoint.hit("compress.encode")
            cls, level = self._plan(data)
            if level is None:
                results[i] = (bytes(data), constants.COMPRESSOR_NONE)
            elif self._handles is not None:
                results[i] = self._encode_dict(data, cls, level)
            else:
                classes[i] = cls
                groups.setdefault(level, []).append(i)
        if not groups:
            return results
        from nydus_snapshotter_tpu.ops import native_cdc

        if not native_cdc.encode_batch_available():
            if getattr(self._tls, "st", None) is not None:
                CTX_REUSE.inc()
            st = self._state()
            for level, idxs in groups.items():
                for i in idxs:
                    payload = zstd_native.compress_with_ctx(st.ctx, views[i], level)
                    results[i] = self._seal(views[i], classes[i], level, payload)
            return results
        for level, idxs in sorted(groups.items()):
            buf, ext = native_cdc.concat_extents([views[i] for i in idxs])
            res = native_cdc.encode_batch_native(buf, ext, level, n_threads)
            if res is None:
                # The library raced away mid-run: per-chunk lane.
                st = self._state()
                for i in idxs:
                    payload = zstd_native.compress_with_ctx(st.ctx, views[i], level)
                    results[i] = self._seal(views[i], classes[i], level, payload)
                continue
            payloads, comp, _digests = res
            BATCH_TOTAL.inc()
            BATCH_CHUNKS.inc(len(idxs))
            for k, i in enumerate(idxs):
                coff, csz = int(comp[k, 0]), int(comp[k, 1])
                results[i] = self._seal(
                    views[i], classes[i], level, payloads[coff : coff + csz].tobytes()
                )
        return results

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            out = {
                "counts": dict(self.counts),
                "class_bytes": dict(self.class_bytes),
            }
        out["trained_dict_id"] = self.trained.dict_id if self.trained else 0
        out["trained_epoch"] = self.trained.epoch if self.trained else 0
        if self._trainer is not None:
            out["trainer"] = self._trainer.stats()
        return out
