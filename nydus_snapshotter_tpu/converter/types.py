"""Converter option surfaces — semantic parity with reference types.go:58-145."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.models import layout


class ConvertError(RuntimeError):
    pass


@dataclass
class PackOption:
    """Options for packing one OCI layer tar into a nydus blob.

    Field semantics follow reference PackOption (pkg/converter/types.go:58-90);
    fields that configured the external builder binary are replaced by engine
    selection knobs (``backend``, ``chunking``).
    """

    work_dir: str = ""
    fs_version: str = layout.RAFS_V6
    chunk_dict_path: str = ""
    prefetch_patterns: str = ""
    # lz4_block matches the legacy v5 blob default; modern nydus-image
    # defaults chunk compression to zstd. We default to lz4_block as a
    # deliberate speed-over-ratio choice (zstd opts into better ratio at
    # ~2x the pack cost).
    compressor: str = "lz4_block"  # "none" | "zstd" | "lz4_block"
    # LZ4 acceleration (liblz4 LZ4_compress_fast): 1 = default-codec
    # output (max ratio); each step up trades ratio for speed (~linear).
    # Deterministic for a fixed value, so parallel/serial/native arms all
    # produce identical bytes.
    lz4_acceleration: int = 1
    oci_ref: bool = False
    aligned_chunk: bool = False
    chunk_size: int = constants.CHUNK_SIZE_DEFAULT
    batch_size: int = 0
    timeout: Optional[float] = None
    encrypt: bool = False
    # Engine selection (replaces BuilderPath): hybrid = the fused native
    # host arm (SIMD bitmaps + SHA-NI) — the default, like the reference
    # defaulting to its production builder; jax = force the TPU batch arm
    # (callers such as bench.py race the arms and pick per measurement);
    # numpy = host differential path.
    backend: str = "hybrid"
    chunking: str = "cdc"  # "cdc" | "fixed"
    # "" = engine default for the backend; "jax" routes chunk digests
    # through the device batch path while boundaries stay on the host
    # (bench.py's device_digest arm).
    digest_backend: str = ""
    # Chunk-digest algorithm (reference `nydus-image --digester`,
    # RafsSuperFlags 0x4 blake3 / 0x8 sha256). blake3 is the real
    # toolchain's default — packing with it makes `--chunk-dict
    # bootstrap=<real nydus image>` content hits possible, since dict
    # probes are digest-keyed. The blob ID stays sha256 (OCI convention).
    # sha256 keeps the SHA-NI/device fused fast paths; blake3 digests run
    # on the host blake3 arm (native ntpu_blake3_many or pure Python).
    digester: str = "sha256"

    def validate(self) -> None:
        if self.fs_version not in (layout.RAFS_V5, layout.RAFS_V6):
            raise ConvertError(f"invalid fs version {self.fs_version!r}")
        if self.compressor not in ("none", "zstd", "lz4_block"):
            raise ConvertError(f"unsupported compressor {self.compressor!r}")
        if not 1 <= self.lz4_acceleration <= 65537:
            raise ConvertError(
                f"lz4 acceleration {self.lz4_acceleration} out of range [1, 65537]"
            )
        cs = self.chunk_size
        if cs & (cs - 1) or not (constants.CHUNK_SIZE_MIN <= cs <= constants.CHUNK_SIZE_MAX):
            raise ConvertError(
                f"chunk size must be power of two in "
                f"[{constants.CHUNK_SIZE_MIN:#x}, {constants.CHUNK_SIZE_MAX:#x}]"
            )
        if self.digest_backend not in ("", "host", "jax"):
            raise ConvertError(
                f"unsupported digest backend {self.digest_backend!r}"
            )
        if self.digester not in ("sha256", "blake3"):
            raise ConvertError(f"unsupported digester {self.digester!r}")
        bs = self.batch_size
        # Reference bound (types.go:78-79): power of two in 0x1000-0x1000000
        # or zero (disabled).
        if bs and (
            bs & (bs - 1) or not (constants.CHUNK_SIZE_MIN <= bs <= constants.CHUNK_SIZE_MAX)
        ):
            raise ConvertError(
                f"batch size must be zero or a power of two in "
                f"[{constants.CHUNK_SIZE_MIN:#x}, {constants.CHUNK_SIZE_MAX:#x}]"
            )


@dataclass
class MergeOption:
    """Options for merging layer bootstraps into an image bootstrap
    (reference types.go:92-133)."""

    work_dir: str = ""
    # Empty = inherit the version of the top layer (explicit value overrides).
    fs_version: str = ""
    chunk_dict_path: str = ""
    parent_bootstrap_path: str = ""
    prefetch_patterns: str = ""
    with_tar: bool = False
    oci: bool = False
    oci_ref: bool = False
    with_referrer: bool = False
    timeout: Optional[float] = None
    # "native" (this framework's format), or the reference toolchain's
    # real on-disk layouts: "rafs-v5" / "rafs-v6" (models/nydus_real_write).
    bootstrap_format: str = "native"
    # Inode-digest algorithm when emitting a real layout ("blake3" is the
    # toolchain default; use the same algorithm the layers' CHUNK digests
    # were packed with — PackOption.digester — for a coherent image).
    digester: str = "sha256"


@dataclass
class UnpackOption:
    """Options for unpacking a nydus blob back to an OCI tar
    (reference types.go:135-145)."""

    work_dir: str = ""
    timeout: Optional[float] = None
    stream: bool = False
