"""Batch conversion: many images, one growing cross-image chunk dict.

The reference achieves cross-repo dedup by feeding ``nydus-image`` a chunk
dict bootstrap per conversion (``--chunk-dict bootstrap=…``,
tool/builder.go:122-123) that an operator refreshes out of band. At
BASELINE scale (config #3 top-100 batch, config #5 10k-image cross-repo)
that file-per-invocation cycle is the bottleneck, so here the dict is a
first-class *growing* object: each converted image's new chunks join the
dict before the next image converts, every image after the first dedups
against everything before it, and the result persists as a standard
dict-image bootstrap that interoperates with ``ChunkDict.from_path`` (and
therefore with PackOption.chunk_dict_path and the reference CLI shape).

Ordering discipline: images convert in caller order and the dict grows
between images (first-wins per digest), so the dedup outcome — which blob
each chunk resolves to, and the merged blob-digest lists — is
deterministic regardless of layer-level thread parallelism inside an
image. Multi-host batches shard the image list deterministically
(parallel/multihost.py) and each host grows its own dict partition; the
registry remains the storage boundary exactly as in the reference.
"""

from __future__ import annotations

import io
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.models.bootstrap import (
    Bootstrap,
    BatchRecord,
    ChunkRecord,
    CipherRecord,
    ChunkDict,
)
from nydus_snapshotter_tpu.converter.convert import Merge, Pack, PackResult
from nydus_snapshotter_tpu.converter.types import ConvertError, MergeOption, PackOption


class GrowingChunkDict:
    """A chunk dict that accumulates chunks across conversions.

    Exposes the same probe interface Pack/Merge consume (``get``,
    ``blob_id_for``, ``__contains__``, ``.bootstrap``) backed by a synthetic
    dict-image bootstrap (chunk/blob/batch/cipher tables, no inodes) that
    ``save()`` writes byte-compatible with ``ChunkDict.from_path``.
    """

    def __init__(self, seed: Optional[Bootstrap] = None, chunk_size: int = 0x100000):
        self.bootstrap = Bootstrap(
            chunk_size=seed.chunk_size if seed else chunk_size, inodes=[]
        )
        self._by_digest: dict[bytes, ChunkRecord] = {}
        self._blob_index_of: dict[str, int] = {}
        self._batch_seen: set[tuple[int, int]] = set()
        self._lock = threading.Lock()
        if seed is not None:
            self.add_bootstrap(seed)

    # -- ChunkDict probe interface -----------------------------------------

    def __len__(self) -> int:
        return len(self._by_digest)

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._by_digest

    def get(self, digest: bytes) -> Optional[ChunkRecord]:
        return self._by_digest.get(digest)

    def blob_id_for(self, chunk: ChunkRecord) -> str:
        return self.bootstrap.blobs[chunk.blob_index].blob_id

    def digests_u32(self):
        return self.bootstrap.chunk_digests_u32()

    def blob_ids(self) -> list[str]:
        return [b.blob_id for b in self.bootstrap.blobs]

    # -- growth -------------------------------------------------------------

    def _blob_index(self, source: Bootstrap, src_idx: int) -> int:
        bid = source.blobs[src_idx].blob_id
        idx = self._blob_index_of.get(bid)
        if idx is None:
            idx = len(self.bootstrap.blobs)
            self._blob_index_of[bid] = idx
            self.bootstrap.blobs.append(source.blobs[src_idx])
            cipher = source.cipher_for(src_idx)
            if cipher is not None or self.bootstrap.ciphers:
                # keep the cipher table parallel to blobs once any blob is
                # encrypted (Bootstrap serialization invariant)
                while len(self.bootstrap.ciphers) < idx:
                    self.bootstrap.ciphers.append(CipherRecord())
                self.bootstrap.ciphers.append(cipher or CipherRecord())
        return idx

    def add_bootstrap_bytes(self, data: bytes) -> int:
        """Merge a serialized bootstrap (the shape converter results and
        the dict-service merge RPC both ship)."""
        return self.add_bootstrap(Bootstrap.from_bytes(data))

    def add_bootstrap(self, source: Bootstrap) -> int:
        """Merge a converted image's chunks into the dict (first-wins per
        digest). Returns how many NEW chunks joined."""
        added = 0
        with self._lock:
            src_batches = {
                (b.blob_index, b.compressed_offset): b for b in source.batches
            }
            for rec in source.chunks:
                if rec.digest in self._by_digest:
                    continue
                if rec.blob_index >= len(source.blobs):
                    raise ConvertError(
                        f"chunk references blob index {rec.blob_index} "
                        f"outside the source blob table"
                    )
                new_idx = self._blob_index(source, rec.blob_index)
                rec2 = ChunkRecord(**{**rec.__dict__})
                rec2.blob_index = new_idx
                self._by_digest[rec2.digest] = rec2
                self.bootstrap.chunks.append(rec2)
                added += 1
                batch = src_batches.get((rec.blob_index, rec.compressed_offset))
                if batch is not None and (new_idx, batch.compressed_offset) not in self._batch_seen:
                    self._batch_seen.add((new_idx, batch.compressed_offset))
                    self.bootstrap.batches.append(
                        BatchRecord(
                            new_idx,
                            batch.compressed_offset,
                            batch.uncompressed_base,
                            batch.uncompressed_size,
                        )
                    )
        return added

    def append_records(self, chunks, blobs, batches, ciphers) -> None:
        """VERBATIM append of already-merged record rows (the HA replica
        apply path, dict_service.ServiceDict.apply_replica_tail): unlike
        :meth:`add_bootstrap` there is no per-digest dedup and no blob
        reindexing — the rows arrive exactly as the primary's first-wins
        merge ordered them, and they must land at exactly the same table
        positions for a promoted replica to honor the clients' replay
        cursors. The probe maps are maintained so later (post-promotion)
        merges dedup correctly against the replicated state."""
        with self._lock:
            bs = self.bootstrap
            for rec in blobs:
                self._blob_index_of.setdefault(rec.blob_id, len(bs.blobs))
                bs.blobs.append(rec)
            for rec in chunks:
                if rec.blob_index >= len(bs.blobs):
                    raise ConvertError(
                        f"replica chunk row references blob index "
                        f"{rec.blob_index} outside the replicated blob table"
                    )
                bs.chunks.append(rec)
                self._by_digest.setdefault(rec.digest, rec)
            for rec in batches:
                self._batch_seen.add((rec.blob_index, rec.compressed_offset))
                bs.batches.append(rec)
            for rec in ciphers:
                bs.ciphers.append(rec)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        """Write a dict-image bootstrap loadable by ChunkDict.from_path
        (and by the reference's ``--chunk-dict bootstrap=…`` shape)."""
        with self._lock:
            if self.bootstrap.ciphers:
                while len(self.bootstrap.ciphers) < len(self.bootstrap.blobs):
                    self.bootstrap.ciphers.append(CipherRecord())
            data = self.bootstrap.to_bytes()
        with open(path, "wb") as f:
            f.write(data)

    @classmethod
    def load(cls, path: str) -> "GrowingChunkDict":
        return cls(seed=ChunkDict.from_path(path).bootstrap)


@dataclass
class ImageResult:
    """One converted image: merged bootstrap + referenced blobs + the layer
    blobs this conversion actually produced (already-deduped content is
    referenced, not re-stored)."""

    name: str
    bootstrap: bytes
    blob_digests: list[str]
    layer_blobs: dict[str, bytes] = field(default_factory=dict)  # blob_id -> packed blob
    new_dict_chunks: int = 0


class BatchConverter:
    """Convert an ordered stream of images with cross-image dedup.

    Layers inside one image pack in parallel (the dict is read-only during
    an image); the dict grows between images, so image N dedups against
    images 0..N-1 plus any seeded dict — the top-100/cross-repo shape of
    BASELINE configs #3/#5.

    Multi-layer fan-out runs under ONE aggregate memory budget: every
    layer's stage-parallel pipeline (parallel/pipeline.py) draws its
    speculative-compression bytes from the same
    :class:`~nydus_snapshotter_tpu.parallel.pipeline.MemoryBudget`, so
    batch convert memory is bounded regardless of how many layers the
    fan-out has in flight or how large each is. ``layer_fanout`` caps the
    concurrently packing layers (0/None = the pool default);
    ``memory_budget_mib`` sizes a converter-private budget instead of the
    process-shared one.

    With a dict SERVICE configured (``dict_service=`` UDS address, or the
    ``[chunk_dict] service`` / ``NTPU_DICT_SERVICE`` setting), the dict is
    a :class:`~nydus_snapshotter_tpu.parallel.dict_service.ServiceChunkDict`
    mirror of one registry-wide table instead of a private copy: probes
    stay local (the dict is read-only inside an image), each converted
    image merges through one batched RPC, and the mirror re-syncs by
    replaying the service's append-only record tail — many converter
    processes/hosts then dedup against each other's chunks.
    """

    def __init__(
        self,
        opt: PackOption,
        dict_path: Optional[str] = None,
        max_workers: Optional[int] = None,
        memory_budget_mib: Optional[int] = None,
        layer_fanout: Optional[int] = None,
        dict_service: Optional[str] = None,
        namespace: Optional[str] = None,
        codec=None,
    ):
        if opt.chunk_dict_path:
            raise ConvertError(
                "BatchConverter owns the chunk dict; use dict_path= instead "
                "of PackOption.chunk_dict_path"
            )
        from nydus_snapshotter_tpu.converter import codec as codec_mod
        from nydus_snapshotter_tpu.parallel import dict_service as dict_service_mod
        from nydus_snapshotter_tpu.parallel import pipeline as pipeline_mod

        self.opt = opt
        self.max_workers = max_workers
        self.layer_fanout = layer_fanout
        self.budget = (
            pipeline_mod.MemoryBudget(memory_budget_mib << 20)
            if memory_budget_mib
            else pipeline_mod.shared_budget()
        )
        dcfg = dict_service_mod.resolve_dict_config()
        service = dict_service if dict_service is not None else dcfg.service
        self.namespace = namespace or dcfg.namespace
        # Adaptive codec engine (off by default): one codec for the whole
        # batch so the dict trainer samples across images and the trained
        # dictionary applies to everything converted after it.
        self.codec = codec if codec is not None else codec_mod.resolve_codec(opt)
        if service:
            if dict_path:
                raise ConvertError(
                    "dict_path seeds a private dict; a service-backed batch "
                    "seeds through the service (merge the seed bootstrap "
                    "into the namespace instead)"
                )
            # Comma-separated addresses = a rendezvous-sharded namespace
            # (one DictService process per shard); one address keeps the
            # single-service path byte-for-byte. A service:// /
            # service+ha:// scheme (or "|" failover groups) resolves the
            # HA topology through open_chunk_dict — replica failover and
            # placement-map resolution included (ha/, ISSUE 15).
            if service.startswith(("service://", "service+ha://")) or "|" in service:
                arg = service if service.startswith("service") else (
                    "service://" + service
                )
                if "#" not in arg:
                    arg += "#" + self.namespace
                self.dict = dict_service_mod.open_chunk_dict(arg)
            else:
                self.dict = dict_service_mod.ServiceChunkDict(
                    [
                        dict_service_mod.DictClient(s.strip())
                        for s in service.split(",")
                        if s.strip()
                    ],
                    self.namespace,
                )
            if self.codec is not None and self.codec.trained is None:
                # Cross-host sharing: adopt the namespace's already-trained
                # dictionary (epoch-stamped) before converting anything.
                blob = self.dict.client.get_zdict(self.namespace)
                if blob:
                    self.codec.set_trained(codec_mod.TrainedDict.deserialize(blob))
        else:
            self.dict = (
                GrowingChunkDict.load(dict_path) if dict_path else GrowingChunkDict()
            )

    def convert_image(self, name: str, layer_tars: list[bytes]) -> ImageResult:
        if not layer_tars:
            raise ConvertError(f"image {name}: no layers")

        def pack_one(tar: bytes) -> tuple[bytes, PackResult]:
            ctx = trace.capture()

            def run() -> tuple[bytes, PackResult]:
                with trace.with_context(ctx):
                    out = io.BytesIO()
                    res = Pack(
                        out,
                        tar,
                        self.opt,
                        chunk_dict=self.dict if len(self.dict) else None,
                        budget=self.budget,
                        codec=self.codec,
                    )
                    return out.getvalue(), res

            return run

        with trace.span("convert", image=name, layers=len(layer_tars)):
            thunks = [pack_one(t)
                      for t in layer_tars]
            if len(layer_tars) > 1:
                fanout = self.layer_fanout or self.max_workers
                with ThreadPoolExecutor(max_workers=fanout) as pool:
                    packed = list(pool.map(lambda fn: fn(), thunks))
            else:
                packed = [thunks[0]()]

            merged = Merge(
                [blob for blob, _ in packed],
                MergeOption(fs_version=self.opt.fs_version),
                chunk_dict=self.dict if len(self.dict) else None,
            )
            added = self.dict.add_bootstrap_bytes(merged.bootstrap)
        self._maybe_train_codec()
        layer_blobs = {
            res.blob_id: blob for blob, res in packed if res.blob_id
        }
        return ImageResult(
            name=name,
            bootstrap=merged.bootstrap,
            blob_digests=merged.blob_digests,
            layer_blobs=layer_blobs,
            new_dict_chunks=added,
        )

    def _maybe_train_codec(self, force: bool = False):
        """Between-images dictionary training: once the codec's sample
        reservoir fills, train the namespace dictionary and (when
        service-backed) publish it so converters on other hosts adopt it.
        Training failure is non-fatal — the batch continues untrained
        (chaos-pinned at ``compress.train``)."""
        if self.codec is None:
            return None
        td = self.codec.maybe_train(force=force)
        if td is None:
            return None
        client = getattr(self.dict, "client", None)
        if client is not None:
            try:
                client.put_zdict(td.serialize(), self.namespace)
            except Exception:
                # The dictionary still applies locally; sharing is
                # best-effort (the service may predate the endpoint).
                pass
        return td

    def train_codec_dict(self):
        """Force dictionary training NOW from whatever the sampler holds
        (the between-images path waits for a full sample budget).
        Returns the TrainedDict, or None (no codec / no samples /
        training failed — the batch continues untrained)."""
        return self._maybe_train_codec(force=True)

    def convert_many(self, images: list[tuple[str, list[bytes]]]) -> list[ImageResult]:
        """Caller order IS the dedup order; results come back in it too."""
        return [self.convert_image(name, layers) for name, layers in images]

    def save_dict(self, path: str) -> None:
        self.dict.save(path)

    def save_trained_dict(self, path: str) -> bool:
        """Persist the codec's trained dictionary (epoch-stamped,
        alongside the chunk dict); False when none was trained."""
        if self.codec is None or self.codec.trained is None:
            return False
        self.codec.trained.save(path)
        return True
