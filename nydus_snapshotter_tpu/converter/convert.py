"""Pack / Merge / Unpack — the conversion hot path, TPU-backed.

Reference surface: ``Pack`` (convert_unix.go:325), ``Merge`` (:560),
``Unpack`` (:669). The external ``nydus-image`` process the reference shells
out to (tool/builder.go:148-362) is replaced by in-process stages:

- chunk + digest on device (ops/chunker.ChunkDigestEngine),
- chunk-dict dedup probe (models/bootstrap.ChunkDict host-side, or the
  sharded HBM table parallel/sharded_dict for batch conversion),
- bootstrap emission (models/bootstrap), blob framing (models/nydus_tar).

Output shape per layer (framed per models/nydus_tar):
``image.blob`` (per-chunk-compressed data) | ``image.boot`` (layer
bootstrap) | ``rafs.blob.toc``.
"""

from __future__ import annotations

import hashlib
import io
import stat
import tarfile
from dataclasses import dataclass, field
from typing import BinaryIO, Callable, Optional

import zstandard

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter.types import ConvertError, MergeOption, PackOption, UnpackOption
from nydus_snapshotter_tpu.models import fstree, layout, nydus_tar, toc
from nydus_snapshotter_tpu.models.bootstrap import (
    BlobRecord,
    Bootstrap,
    ChunkDict,
    ChunkRecord,
    Inode,
    parse_chunk_dict_arg,
)
from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine

_ZSTD_LEVEL = 3


@dataclass
class PackResult:
    blob_id: str  # hex sha256 of the image.blob section ("" if fully deduped)
    blob_size: int
    bootstrap: bytes
    referenced_blob_ids: list[str]


@dataclass
class MergeResult:
    bootstrap: bytes
    blob_digests: list[str]  # referenced blob ids after dedup, table order


def _make_compressor(compressor: str):
    """One reusable codec per Pack — a fresh zstd context per chunk costs
    allocation/init for every one of the thousands of chunks in a layer."""
    if compressor == "zstd":
        ctx = zstandard.ZstdCompressor(level=_ZSTD_LEVEL)
        return lambda data: (ctx.compress(data), constants.COMPRESSOR_ZSTD)
    return lambda data: (data, constants.COMPRESSOR_NONE)


def _decompress_chunk(data: bytes, flags: int, expect_size: int) -> bytes:
    comp = flags & constants.COMPRESSOR_MASK
    if comp == constants.COMPRESSOR_ZSTD:
        return zstandard.ZstdDecompressor().decompress(data, max_output_size=max(expect_size, 1))
    if comp in (constants.COMPRESSOR_NONE, 0):
        return data
    raise ConvertError(f"unsupported chunk compressor flags {flags:#x}")


def _make_engine(opt: PackOption) -> ChunkDigestEngine:
    return ChunkDigestEngine(
        chunk_size=opt.chunk_size, mode=opt.chunking, backend=opt.backend
    )


# ---------------------------------------------------------------------------
# Pack
# ---------------------------------------------------------------------------


def Pack(dest: BinaryIO, src_tar: BinaryIO | bytes, opt: PackOption) -> PackResult:
    """Convert one OCI layer tar into a nydus blob stream written to dest.

    Reference semantics (convert_unix.go:325-539): stream in an uncompressed
    layer tar, emit the tar-like nydus blob; chunk-dict hits are not stored,
    only referenced.
    """
    opt.validate()
    if opt.batch_size:
        raise ConvertError("batch chunk packing is not supported yet")
    if opt.encrypt:
        raise ConvertError("blob encryption is not supported yet")

    entries = fstree.ensure_parents(fstree.tree_from_tar(src_tar))
    chunk_dict = (
        ChunkDict.from_path(parse_chunk_dict_arg(opt.chunk_dict_path))
        if opt.chunk_dict_path
        else None
    )
    engine = _make_engine(opt)

    inodes: list[Inode] = []
    chunk_records: list[ChunkRecord] = []  # global table, per-inode slices
    per_file_chunks: list[tuple[Inode, list]] = []

    # Chunk+digest every regular file (per-file chunking, as the reference
    # builder does — dedup needs file-aligned chunk starts).
    files = [e for e in entries if e.is_regular]
    metas_per_file = engine.process_many([e.data for e in files])

    # First pass: intra-layer + dict dedup bookkeeping.
    own_chunks: dict[bytes, int] = {}  # digest -> unique index in this blob
    unique_data: list[bytes] = []
    dict_blobs_used: list[str] = []  # dict blob ids in first-use order
    dict_hits: dict[bytes, ChunkRecord] = {}
    for e, metas in zip(files, metas_per_file):
        for m in metas:
            if chunk_dict is not None and m.digest not in dict_hits:
                hit = chunk_dict.get(m.digest)
                if hit is not None:
                    dict_hits[m.digest] = hit
                    bid = chunk_dict.blob_id_for(hit)
                    if bid not in dict_blobs_used:
                        dict_blobs_used.append(bid)
            if m.digest not in dict_hits and m.digest not in own_chunks:
                own_chunks[m.digest] = len(unique_data)
                unique_data.append(e.data[m.offset : m.offset + m.size])

    # Compress unique chunks, lay out the blob data section.
    align = 4096 if (opt.aligned_chunk and opt.fs_version == layout.RAFS_V5) else 1
    compress = _make_compressor(opt.compressor)
    blob_parts: list[bytes] = []
    comp_extents: list[tuple[int, int, int]] = []  # (offset, csize, flags)
    uncomp_offsets: list[int] = []
    coff = 0
    uoff = 0
    for data in unique_data:
        comp, cflag = compress(data)
        pad = (-coff) % align
        if pad:
            blob_parts.append(b"\x00" * pad)
            coff += pad
        blob_parts.append(comp)
        comp_extents.append((coff, len(comp), cflag))
        uncomp_offsets.append(uoff)
        coff += len(comp)
        uoff += len(data)
    blob_data = b"".join(blob_parts)
    blob_sha = hashlib.sha256(blob_data) if blob_data else None
    blob_id = blob_sha.hexdigest() if blob_sha else ""

    # Blob table: own blob first (if it stores anything), then dict blobs.
    blob_table: list[BlobRecord] = []
    blob_index_of: dict[str, int] = {}
    if blob_data:
        blob_index_of[blob_id] = 0
        blob_table.append(
            BlobRecord(
                blob_id=blob_id,
                compressed_size=len(blob_data),
                uncompressed_size=uoff,
                chunk_count=len(unique_data),
            )
        )
    for bid in dict_blobs_used:
        blob_index_of[bid] = len(blob_table)
        dict_rec = next(b for b in chunk_dict.bootstrap.blobs if b.blob_id == bid)
        blob_table.append(
            BlobRecord(
                blob_id=bid,
                compressed_size=dict_rec.compressed_size,
                uncompressed_size=dict_rec.uncompressed_size,
                chunk_count=dict_rec.chunk_count,
                flags=dict_rec.flags,
            )
        )

    # Second pass: emit inodes + chunk records.
    file_meta = {id(e): m for e, m in zip(files, metas_per_file)}
    for e in entries:
        inode = fstree.entry_to_inode(e)
        if e.is_regular and e.data:
            metas = file_meta[id(e)]
            inode.chunk_index = len(chunk_records)
            inode.chunk_count = len(metas)
            for m in metas:
                hit = dict_hits.get(m.digest)
                if hit is not None:
                    rec = ChunkRecord(
                        digest=m.digest,
                        blob_index=blob_index_of[chunk_dict.blob_id_for(hit)],
                        flags=hit.flags,
                        uncompressed_offset=hit.uncompressed_offset,
                        compressed_offset=hit.compressed_offset,
                        uncompressed_size=hit.uncompressed_size,
                        compressed_size=hit.compressed_size,
                    )
                else:
                    ui = own_chunks[m.digest]
                    off, csize, cflag = comp_extents[ui]
                    rec = ChunkRecord(
                        digest=m.digest,
                        blob_index=blob_index_of[blob_id],
                        flags=cflag,
                        uncompressed_offset=uncomp_offsets[ui],
                        compressed_offset=off,
                        uncompressed_size=m.size,
                        compressed_size=csize,
                    )
                chunk_records.append(rec)
        inodes.append(inode)

    bootstrap = Bootstrap(
        version=opt.fs_version,
        chunk_size=opt.chunk_size,
        inodes=inodes,
        chunks=chunk_records,
        blobs=blob_table,
    )
    boot_bytes = bootstrap.to_bytes()

    # Frame the output stream + trailing TOC.
    toc_entries = []
    sections: list[tuple[str, bytes]] = []
    if blob_data:
        sections.append((toc.ENTRY_BLOB_DATA, blob_data))
        toc_entries.append(
            toc.TOCEntry(
                name=toc.ENTRY_BLOB_DATA,
                flags=constants.COMPRESSOR_NONE,
                uncompressed_digest=blob_sha.digest(),
                compressed_size=len(blob_data),
                uncompressed_size=len(blob_data),
            )
        )
    sections.append((toc.ENTRY_BOOTSTRAP, boot_bytes))
    toc_entries.append(
        toc.TOCEntry(
            name=toc.ENTRY_BOOTSTRAP,
            flags=constants.COMPRESSOR_NONE,
            uncompressed_digest=hashlib.sha256(boot_bytes).digest(),
            compressed_size=len(boot_bytes),
            uncompressed_size=len(boot_bytes),
        )
    )

    offset = 0
    for name, data in sections:
        o, _ = nydus_tar.append_entry(dest, name, data)
        for t in toc_entries:
            if t.name == name:
                t.compressed_offset = o
    nydus_tar.append_entry(dest, toc.ENTRY_BLOB_TOC, toc.pack_toc(toc_entries))

    return PackResult(
        blob_id=blob_id,
        blob_size=len(blob_data),
        bootstrap=boot_bytes,
        referenced_blob_ids=[b.blob_id for b in blob_table],
    )


def pack_layer(src_tar: bytes, opt: PackOption) -> tuple[bytes, PackResult]:
    """Convenience: Pack to bytes."""
    out = io.BytesIO()
    res = Pack(out, src_tar, opt)
    return out.getvalue(), res


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------


@dataclass
class _Node:
    """Overlay node carrying an inode plus its chunks (blob ids resolved)."""

    inode: Inode
    chunks: list[tuple[ChunkRecord, str]] = field(default_factory=list)

    @property
    def path(self) -> str:
        return self.inode.path

    @property
    def is_dir(self) -> bool:
        return stat.S_ISDIR(self.inode.mode)

    @property
    def is_whiteout(self) -> bool:
        from nydus_snapshotter_tpu.models.bootstrap import INODE_FLAG_WHITEOUT

        return bool(self.inode.flags & INODE_FLAG_WHITEOUT)

    @property
    def flags(self) -> int:
        return self.inode.flags


def _layer_nodes(bootstrap: Bootstrap) -> list[_Node]:
    blob_ids = [b.blob_id for b in bootstrap.blobs]
    nodes = []
    for inode in bootstrap.inodes:
        chunks = [
            (c, blob_ids[c.blob_index])
            for c in bootstrap.chunks[inode.chunk_index : inode.chunk_index + inode.chunk_count]
        ]
        nodes.append(_Node(inode=inode, chunks=chunks))
    return nodes


def bootstrap_from_layer_blob(blob: bytes) -> Bootstrap:
    """Extract the layer bootstrap from a packed nydus blob stream."""
    f = io.BytesIO(blob)
    loc = nydus_tar.seek_file_by_tar_header(f, len(blob), toc.ENTRY_BOOTSTRAP)
    if loc is None:
        raise ConvertError("layer blob carries no bootstrap section")
    off, size = loc
    return Bootstrap.from_bytes(blob[off : off + size])


def bootstrap_from_bootstrap_layer(data: bytes) -> Bootstrap:
    """Extract the image bootstrap from a (decompressed) bootstrap *layer*:
    a standard tar carrying ``image/image.boot``
    (constant.go BootstrapFileNameInLayer, written by packToTar)."""
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:") as tf:
            for member in tf:
                if member.name in (layout.BOOTSTRAP_FILE, "./" + layout.BOOTSTRAP_FILE):
                    extracted = tf.extractfile(member)
                    if extracted is None:
                        break
                    return Bootstrap.from_bytes(extracted.read())
    except (tarfile.TarError, OSError) as e:
        raise ConvertError(f"bad bootstrap layer tar: {e}") from e
    raise ConvertError("bootstrap layer carries no image/image.boot")


def Merge(
    layers: list[bytes | Bootstrap],
    opt: MergeOption,
) -> MergeResult:
    """Merge per-layer bootstraps into one image bootstrap.

    ``layers`` are packed layer blobs (or already-parsed bootstraps), lowest
    first. Returns the image bootstrap plus the dedup result: the blob ids
    actually referenced (reference Merge surface convert_unix.go:560-666,
    whose blob-digest list comes from merge-output.json,
    tool/builder.go:278-294).
    """
    if not layers:
        raise ConvertError("merge needs at least one layer")
    chunk_dict = (
        ChunkDict.from_path(parse_chunk_dict_arg(opt.chunk_dict_path))
        if opt.chunk_dict_path
        else None
    )
    parent: Optional[Bootstrap] = None
    if opt.parent_bootstrap_path:
        with open(opt.parent_bootstrap_path, "rb") as f:
            parent = Bootstrap.from_bytes(f.read())

    merged: dict[str, _Node] = {}
    boots: list[Bootstrap] = []
    if parent is not None:
        boots.append(parent)
    for layer in layers:
        boots.append(
            layer if isinstance(layer, Bootstrap) else bootstrap_from_layer_blob(layer)
        )
    chunk_size = boots[-1].chunk_size
    version = opt.fs_version or boots[-1].version
    lower: list[_Node] = []
    for b in boots:
        lower = fstree.apply_overlay(lower, _layer_nodes(b))  # type: ignore[arg-type]

    # Chunk-dict dedup at merge time: chunks whose digest is in the dict are
    # re-pointed at the dict blob.
    inodes: list[Inode] = []
    chunk_records: list[ChunkRecord] = []
    blob_index_of: dict[str, int] = {}
    blob_records: dict[str, BlobRecord] = {}
    for b in boots:
        for rec in b.blobs:
            blob_records.setdefault(rec.blob_id, rec)
    if chunk_dict is not None:
        for rec in chunk_dict.bootstrap.blobs:
            blob_records.setdefault(rec.blob_id, rec)

    def blob_index(bid: str) -> int:
        if bid not in blob_index_of:
            blob_index_of[bid] = len(blob_index_of)
        return blob_index_of[bid]

    for node in lower:  # already path-sorted by apply_overlay
        inode = node.inode
        inode.chunk_index = len(chunk_records)
        inode.chunk_count = len(node.chunks)
        for rec, bid in node.chunks:
            hit = chunk_dict.get(rec.digest) if chunk_dict is not None else None
            if hit is not None:
                chunk_records.append(
                    ChunkRecord(
                        digest=rec.digest,
                        blob_index=blob_index(chunk_dict.blob_id_for(hit)),
                        flags=hit.flags,
                        uncompressed_offset=hit.uncompressed_offset,
                        compressed_offset=hit.compressed_offset,
                        uncompressed_size=hit.uncompressed_size,
                        compressed_size=hit.compressed_size,
                    )
                )
            else:
                rec2 = ChunkRecord(**{**rec.__dict__})
                rec2.blob_index = blob_index(bid)
                chunk_records.append(rec2)
        inodes.append(inode)

    blob_table = []
    for bid, _idx in sorted(blob_index_of.items(), key=lambda kv: kv[1]):
        base = blob_records.get(bid)
        if base is None:
            raise ConvertError(f"chunk references unknown blob {bid}")
        blob_table.append(base)

    bootstrap = Bootstrap(
        version=version,
        chunk_size=chunk_size,
        inodes=inodes,
        chunks=chunk_records,
        blobs=blob_table,
    )
    boot_bytes = bootstrap.to_bytes()
    if opt.with_tar:
        # Standard forward tar carrying image/image.boot — the bootstrap
        # *layer* format every consumer expects (reference packToTar;
        # referrer fetch unpacks it with plain tar, unpack.go:20-56).
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w:", format=tarfile.GNU_FORMAT) as tf:
            info = tarfile.TarInfo(layout.BOOTSTRAP_FILE)
            info.size = len(boot_bytes)
            info.mode = 0o444
            tf.addfile(info, io.BytesIO(boot_bytes))
        boot_bytes = out.getvalue()
    return MergeResult(
        bootstrap=boot_bytes,
        blob_digests=[b.blob_id for b in blob_table],
    )


# ---------------------------------------------------------------------------
# Unpack
# ---------------------------------------------------------------------------


def Unpack(
    bootstrap: bytes | Bootstrap,
    blob_provider: Callable[[str], bytes] | dict[str, bytes],
    opt: UnpackOption | None = None,
) -> bytes:
    """Rebuild the OCI tar from a bootstrap plus its blobs.

    ``blob_provider`` maps blob id → *blob data section* bytes (for a packed
    layer stream, pass the bytes of its ``image.blob`` section, see
    ``blob_data_from_layer_blob``). Reference surface convert_unix.go:669-733.
    """
    bs = bootstrap if isinstance(bootstrap, Bootstrap) else Bootstrap.from_bytes(bootstrap)
    provider = blob_provider.__getitem__ if isinstance(blob_provider, dict) else blob_provider
    blob_cache: dict[str, bytes] = {}

    def blob_bytes(bid: str) -> bytes:
        if bid not in blob_cache:
            blob_cache[bid] = provider(bid)
        return blob_cache[bid]

    entries: list[fstree.FileEntry] = []
    for inode in bs.inodes:
        data = b""
        if stat.S_ISREG(inode.mode) and inode.chunk_count and not inode.hardlink_target:
            parts = []
            for rec in bs.chunks[inode.chunk_index : inode.chunk_index + inode.chunk_count]:
                blob = blob_bytes(bs.blobs[rec.blob_index].blob_id)
                raw = blob[rec.compressed_offset : rec.compressed_offset + rec.compressed_size]
                parts.append(_decompress_chunk(raw, rec.flags, rec.uncompressed_size))
            data = b"".join(parts)
            if len(data) != inode.size:
                raise ConvertError(
                    f"unpacked {inode.path}: got {len(data)} bytes, inode says {inode.size}"
                )
        entries.append(fstree.inode_to_entry(inode, data))
    return fstree.tar_from_tree(entries)


def blob_data_from_layer_blob(blob: bytes) -> bytes:
    """Extract the image.blob section from a packed layer stream ('' if none)."""
    f = io.BytesIO(blob)
    loc = nydus_tar.seek_file_by_tar_header(f, len(blob), toc.ENTRY_BLOB_DATA)
    if loc is None:
        return b""
    off, size = loc
    return blob[off : off + size]
