"""Pack / Merge / Unpack — the conversion hot path, TPU-backed.

Reference surface: ``Pack`` (convert_unix.go:325), ``Merge`` (:560),
``Unpack`` (:669). The external ``nydus-image`` process the reference shells
out to (tool/builder.go:148-362) is replaced by in-process stages:

- chunk + digest on device (ops/chunker.ChunkDigestEngine),
- chunk-dict dedup probe (models/bootstrap.ChunkDict host-side, or the
  sharded HBM table parallel/sharded_dict for batch conversion),
- bootstrap emission (models/bootstrap), blob framing (models/nydus_tar).

Output shape per layer (framed per models/nydus_tar):
``image.blob`` (per-chunk-compressed data) | ``image.boot`` (layer
bootstrap) | ``rafs.blob.toc``.
"""

from __future__ import annotations

import io
import stat
import tarfile
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import BinaryIO, Callable, Optional

from nydus_snapshotter_tpu.utils.zstdcompat import zstandard

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter import crypto
from nydus_snapshotter_tpu.converter.types import ConvertError, MergeOption, PackOption, UnpackOption
from nydus_snapshotter_tpu.models import fstree, layout, nydus_tar, toc
from nydus_snapshotter_tpu.models.bootstrap import (
    CHUNK_FLAG_BATCH,
    BatchRecord,
    BlobRecord,
    Bootstrap,
    ChunkDict,
    ChunkRecord,
    CipherRecord,
    Inode,
    parse_chunk_dict_arg,
)
from nydus_snapshotter_tpu.utils import lz4

_ZSTD_LEVEL = constants.ZSTD_LEVEL


@dataclass
class PackResult:
    blob_id: str  # hex sha256 of the image.blob section ("" if fully deduped)
    blob_size: int
    bootstrap: bytes
    referenced_blob_ids: list[str]


@dataclass
class MergeResult:
    bootstrap: bytes
    blob_digests: list[str]  # referenced blob ids after dedup, table order


def _make_compressor(compressor: str, lz4_accel: int = 1, codec=None):
    """One reusable codec per Pack — a fresh zstd context per chunk costs
    allocation/init for every one of the thousands of chunks in a layer.

    ``codec``: an :class:`~nydus_snapshotter_tpu.converter.codec.AdaptiveCodec`
    takes over the zstd lane (probe/bypass/per-class levels/trained
    dict); ``None`` is the byte-identical fixed-level default."""
    if codec is not None and compressor == "zstd":
        return codec.encode
    if compressor == "zstd":
        from nydus_snapshotter_tpu.utils import zstd as zstd_native

        if zstd_native.available():
            # System libzstd: byte-identical to the fused native section
            # assembly (which dlopens the same library) — the bundled
            # zstandard build can emit different frames (utils/zstd.py).
            return lambda data: (
                zstd_native.compress_block(data, _ZSTD_LEVEL),
                constants.COMPRESSOR_ZSTD,
            )
        ctx = zstandard.ZstdCompressor(level=_ZSTD_LEVEL)
        return lambda data: (ctx.compress(data), constants.COMPRESSOR_ZSTD)
    if compressor == "lz4_block":
        return lambda data: (
            lz4.compress_block(data, lz4_accel),
            constants.COMPRESSOR_LZ4_BLOCK,
        )
    return lambda data: (data, constants.COMPRESSOR_NONE)


class ThreadSafeCompressor:
    """Per-thread codec contexts for parallel speculative compression.

    ZstdCompressor instances are not safe for concurrent calls; output is
    still deterministic across contexts (same level, single-threaded
    contexts), so racing threads produce identical bytes.

    With an adaptive ``codec`` the call routes straight to
    ``codec.encode`` — the codec engine keeps its own per-worker pinned
    contexts and is deterministic in chunk content, so the same racing
    invariant holds.
    """

    def __init__(self, compressor: str, lz4_accel: int = 1, codec=None):
        import threading

        self._kind = compressor
        self._lz4_accel = lz4_accel
        self._codec = codec if (codec is not None and compressor == "zstd") else None
        self._tls = threading.local()

    def __call__(self, data):
        if self._codec is not None:
            return self._codec.encode(data)
        fn = getattr(self._tls, "fn", None)
        if fn is None:
            fn = _make_compressor(self._kind, self._lz4_accel)
            self._tls.fn = fn
        return fn(data)

    def encode_many(self, views, n_threads: int = 1):
        """Batch counterpart of ``__call__``: ``[(payload, flag)]``
        byte-identical to ``[self(v) for v in views]``.

        Routes the adaptive codec's :meth:`AdaptiveCodec.encode_batch`,
        or — on the plain system-libzstd lane — one GIL-released native
        batch call at the fixed level (``ntpu_encode_batch`` is one-shot
        ``ZSTD_compressCCtx`` like ``compress_block``, so frames match).
        Everything else (lz4, store-raw, the bundled-zstandard fallback)
        loops per chunk.
        """
        if self._codec is not None:
            return self._codec.encode_batch(views, n_threads=n_threads)
        if self._kind == "zstd" and views:
            from nydus_snapshotter_tpu.ops import native_cdc
            from nydus_snapshotter_tpu.utils import zstd as zstd_native

            if zstd_native.available() and native_cdc.encode_batch_available():
                buf, ext = native_cdc.concat_extents(views)
                res = native_cdc.encode_batch_native(buf, ext, _ZSTD_LEVEL, n_threads)
                if res is not None:
                    payloads, comp, _digests = res
                    return [
                        (
                            payloads[
                                int(comp[k, 0]) : int(comp[k, 0]) + int(comp[k, 1])
                            ].tobytes(),
                            constants.COMPRESSOR_ZSTD,
                        )
                        for k in range(len(views))
                    ]
        return [self(v) for v in views]


def _decompress_chunk(data: bytes, flags: int, expect_size: int) -> bytes:
    comp = flags & constants.COMPRESSOR_MASK
    if comp == constants.COMPRESSOR_ZSTD:
        from nydus_snapshotter_tpu.converter import codec as codec_mod
        from nydus_snapshotter_tpu.utils import zstdcompat

        if codec_mod.is_trained_frame(data):
            # Versioned trained-dict frame (nZD1 header): decodes only
            # with the dictionary it was trained with — a reader that
            # lacks it must fail loudly, never emit garbage bytes.
            try:
                return codec_mod.decode_trained_frame(data, expect_size)
            except codec_mod.CodecError as e:
                raise ConvertError(str(e)) from e
        try:
            # Pooled-DCtx decode path: no per-call context allocation
            # (the previous per-call ZstdDecompressor() construction was
            # measurable on the lazy-read hot path).
            return zstdcompat.decompress_block(
                data, max_output_size=max(expect_size, 1)
            )
        except Exception:
            # Any conforming frame decodes identically on the package
            # decompressor; keep it as the compatibility net.
            return zstandard.ZstdDecompressor().decompress(
                data, max_output_size=max(expect_size, 1)
            )
    if comp == constants.COMPRESSOR_LZ4_BLOCK:
        return lz4.decompress_block(data, expect_size)
    if comp == constants.COMPRESSOR_GZIP:
        # estargz chunks are whole gzip members left in place by the index
        # builder (stargz/index.py) — the lazy read path inflates them here.
        # The member carries tar padding (and possibly the next entry's
        # header member), so longer-than-expected output is normal and
        # truncated; SHORTER output means a corrupt blob.
        import gzip
        import zlib

        try:
            out = gzip.decompress(data)
        except (OSError, EOFError, zlib.error) as e:
            raise ConvertError(f"corrupt gzip chunk: {e}") from e
        if expect_size:
            if len(out) < expect_size:
                raise ConvertError(
                    f"gzip chunk inflated to {len(out)} bytes < expected {expect_size}"
                )
            return out[:expect_size]
        return out
    if comp in (constants.COMPRESSOR_NONE, 0):
        return data
    raise ConvertError(f"unsupported chunk compressor flags {flags:#x}")


class BlobReader:
    """Random-access chunk reads from one blob's data section.

    Centralizes the three storage transforms a chunk record can carry —
    per-chunk compression, batch packing (CHUNK_FLAG_BATCH: several small
    chunks share one compressed extent), and blob encryption (seekable
    AES-CTR, converter/crypto.py) — so Unpack and the lazy-read daemon
    resolve chunks through identical logic.

    ``read_at(offset, size)`` returns raw (still-encrypted) blob bytes.
    """

    # Decompressed batches kept hot per reader — bounded so a long-lived
    # daemon doesn't pin every batch it ever read.
    BATCH_CACHE_BYTES = 32 << 20

    def __init__(
        self,
        bootstrap: Bootstrap,
        blob_index: int,
        read_at: Callable[[int, int], bytes],
        batch_map: Optional[dict[tuple[int, int], tuple[int, int]]] = None,
        gzip_stream=None,
        zstd_stream=None,
    ):
        self.bootstrap = bootstrap
        self.blob_index = blob_index
        self.read_at = read_at
        self.cipher = bootstrap.cipher_for(blob_index)
        if self.cipher is not None and self.cipher.algo != crypto.CIPHER_AES_256_CTR:
            raise ConvertError(f"unsupported blob cipher algo {self.cipher.algo}")
        # (blob_index, compressed_offset) -> (uncompressed_base, size), from
        # the bootstrap's batch table. Callers constructing several readers
        # can share one batch_map to avoid rebuilding it per blob.
        self._batch_map = bootstrap.batch_map() if batch_map is None else batch_map
        # The daemon shares one reader per blob across request threads.
        self._batch_lock = threading.Lock()
        self._batch_cache: "OrderedDict[int, bytes]" = OrderedDict()
        self._batch_cache_bytes = 0
        # OCIRef blobs: a checkpointed cursor into the original gzip
        # stream. The default is the in-process GzipStreamReader (built
        # lazily, serialized by _gzip_lock — its inflate cursor is
        # stateful); a caller holding a persisted soci index injects a
        # SociStreamReader instead, whose `concurrent` flag skips the
        # lock (each read owns its own inflate state).
        self._gzip_stream = gzip_stream
        self._gzip_lock = threading.Lock()
        # Same arrangement for whole-zstd OCIRef blobs: frame-indexed
        # ZstdStreamReader (concurrent) injected by the daemon, or the
        # in-process sequential cursor built lazily under the lock.
        self._zstd_stream = zstd_stream
        self._zstd_lock = threading.Lock()

    def mount_gzip_stream(self, stream) -> None:
        """Swap in a checkpoint-indexed gzip reader (soci/blob.py) after
        construction: the daemon resolves the index store off its reader
        lock, so the stream arrives late. The attribute swap is atomic;
        reads served before it used the sequential path — identical
        bytes, just without checkpoint resume."""
        self._gzip_stream = stream

    def mount_zstd_stream(self, stream) -> None:
        """Swap in a frame-indexed zstd reader (soci/zblob.py) after
        construction — the zstd mirror of :meth:`mount_gzip_stream`,
        with identical atomicity and identical-bytes semantics."""
        self._zstd_stream = stream

    def _read_plain(self, offset: int, size: int) -> bytes:
        raw = self.read_at(offset, size)
        if len(raw) != size:
            raise ConvertError(
                f"blob {self.blob_index}: short read at {offset} "
                f"({len(raw)} of {size} bytes)"
            )
        if self.cipher is not None:
            raw = crypto.decrypt_range(raw, offset, self.cipher.key, self.cipher.iv)
        return raw

    def chunk_data(self, rec: ChunkRecord) -> bytes:
        """The uncompressed data of one chunk record."""
        if rec.blob_index != self.blob_index:
            raise ConvertError("chunk record belongs to a different blob")
        from nydus_snapshotter_tpu.converter.zran import (
            CHUNK_FLAG_GZIP_STREAM,
            GzipStreamReader,
        )

        if rec.flags & CHUNK_FLAG_GZIP_STREAM:
            # OCIRef: offsets address the decompressed stream of the
            # original .tar.gz blob (converter/zran.py).
            if getattr(self._gzip_stream, "concurrent", False):
                return self._gzip_stream.read_range(
                    rec.uncompressed_offset, rec.uncompressed_size
                )
            with self._gzip_lock:
                if self._gzip_stream is None:
                    self._gzip_stream = GzipStreamReader(
                        self._read_plain,
                        self.bootstrap.blobs[self.blob_index].compressed_size,
                    )
                return self._gzip_stream.read_range(
                    rec.uncompressed_offset, rec.uncompressed_size
                )
        from nydus_snapshotter_tpu.converter.zstd_ref import (
            CHUNK_FLAG_ZSTD_STREAM,
            ZstdSequentialReader,
        )

        if rec.flags & CHUNK_FLAG_ZSTD_STREAM:
            # OCIRef: offsets address the decompressed stream of the
            # original .tar.zst blob (converter/zstd_ref.py).
            if getattr(self._zstd_stream, "concurrent", False):
                return self._zstd_stream.read_range(
                    rec.uncompressed_offset, rec.uncompressed_size
                )
            with self._zstd_lock:
                if self._zstd_stream is None:
                    self._zstd_stream = ZstdSequentialReader(
                        self._read_plain,
                        self.bootstrap.blobs[self.blob_index].compressed_size,
                    )
                return self._zstd_stream.read_range(
                    rec.uncompressed_offset, rec.uncompressed_size
                )
        if rec.flags & CHUNK_FLAG_BATCH:
            extent = self._batch_map.get((self.blob_index, rec.compressed_offset))
            if extent is None:
                raise ConvertError(
                    f"batched chunk at blob {self.blob_index} offset "
                    f"{rec.compressed_offset} has no batch-table entry"
                )
            base, usize = extent
            with self._batch_lock:
                batch = self._batch_cache.get(rec.compressed_offset)
                if batch is not None:
                    self._batch_cache.move_to_end(rec.compressed_offset)
            if batch is None:
                raw = self._read_plain(rec.compressed_offset, rec.compressed_size)
                batch = _decompress_chunk(raw, rec.flags, usize)
                with self._batch_lock:
                    if rec.compressed_offset not in self._batch_cache:
                        self._batch_cache[rec.compressed_offset] = batch
                        self._batch_cache_bytes += len(batch)
                    while (
                        self._batch_cache_bytes > self.BATCH_CACHE_BYTES
                        and len(self._batch_cache) > 1
                    ):
                        _, evicted = self._batch_cache.popitem(last=False)
                        self._batch_cache_bytes -= len(evicted)
            inner = rec.uncompressed_offset - base
            if inner < 0 or inner + rec.uncompressed_size > len(batch):
                raise ConvertError("batch chunk slice overflows its batch")
            return batch[inner : inner + rec.uncompressed_size]
        raw = self._read_plain(rec.compressed_offset, rec.compressed_size)
        return _decompress_chunk(raw, rec.flags, rec.uncompressed_size)


def make_bytes_reader(
    bootstrap: Bootstrap, blob_index: int, blob: bytes, batch_map=None
) -> BlobReader:
    return BlobReader(
        bootstrap, blob_index, lambda off, size: blob[off : off + size], batch_map=batch_map
    )


# ---------------------------------------------------------------------------
# Pack
# ---------------------------------------------------------------------------


def Pack(
    dest: BinaryIO,
    src_tar: BinaryIO | bytes,
    opt: PackOption,
    chunk_dict=None,
    stats: dict | None = None,
    budget=None,
    codec=None,
) -> PackResult:
    """Convert one OCI layer tar into a nydus blob stream written to dest.

    Reference semantics (convert_unix.go:325-539): stream in an uncompressed
    layer tar, emit the tar-like nydus blob; chunk-dict hits are not stored,
    only referenced. Implementation: the bounded-memory streaming pipeline
    in converter/stream.py (tar stream -> incremental CDC -> batched
    digests -> dedup -> compress -> dest), shared by in-memory and
    streaming callers alike. On multi-worker hosts the per-layer stages
    overlap through the stage-parallel executor (parallel/pipeline.py);
    ``budget`` optionally pins that executor to a caller-owned
    MemoryBudget (batch conversion shares one across layers). ``codec``
    optionally pins an adaptive codec engine (converter/codec.py) for
    the zstd lane; ``None`` resolves from config/env (and stays the
    byte-identical fixed-level lane when the engine is off, the
    default).
    """
    from nydus_snapshotter_tpu import failpoint
    from nydus_snapshotter_tpu.converter.stream import pack_stream

    failpoint.hit("converter.pack")
    return pack_stream(
        dest,
        src_tar,
        opt,
        chunk_dict=chunk_dict,
        stats=stats,
        budget=budget,
        codec=codec,
    )


def pack_layer(
    src_tar: bytes,
    opt: PackOption,
    chunk_dict=None,
    stats: dict | None = None,
    budget=None,
    codec=None,
) -> tuple[bytes, PackResult]:
    """Convenience: Pack to bytes."""
    out = io.BytesIO()
    res = Pack(
        out, src_tar, opt, chunk_dict=chunk_dict, stats=stats, budget=budget,
        codec=codec,
    )
    return out.getvalue(), res


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------


@dataclass
class _Node:
    """Overlay node carrying an inode plus its chunks (blob ids resolved)."""

    inode: Inode
    chunks: list[tuple[ChunkRecord, str]] = field(default_factory=list)

    @property
    def path(self) -> str:
        return self.inode.path

    @property
    def is_dir(self) -> bool:
        return stat.S_ISDIR(self.inode.mode)

    @property
    def is_whiteout(self) -> bool:
        from nydus_snapshotter_tpu.models.bootstrap import INODE_FLAG_WHITEOUT

        return bool(self.inode.flags & INODE_FLAG_WHITEOUT)

    @property
    def flags(self) -> int:
        return self.inode.flags


def _layer_nodes(bootstrap: Bootstrap) -> list[_Node]:
    blob_ids = [b.blob_id for b in bootstrap.blobs]
    nodes = []
    for inode in bootstrap.inodes:
        chunks = [
            (c, blob_ids[c.blob_index])
            for c in bootstrap.chunks[inode.chunk_index : inode.chunk_index + inode.chunk_count]
        ]
        nodes.append(_Node(inode=inode, chunks=chunks))
    return nodes


def bootstrap_from_layer_blob(blob: bytes) -> Bootstrap:
    """Extract the layer bootstrap from a packed nydus blob stream. The
    embedded section may be in either layout — native, or the real
    toolchain's v5/v6 (a reference-built framed layer, convert_unix.go's
    packToTar shape) — and is auto-bridged."""
    from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap

    f = io.BytesIO(blob)
    loc = nydus_tar.seek_file_by_tar_header(f, len(blob), toc.ENTRY_BOOTSTRAP)
    if loc is None:
        raise ConvertError("layer blob carries no bootstrap section")
    off, size = loc
    return load_any_bootstrap(blob[off : off + size])


def bootstrap_from_bootstrap_layer(data: bytes) -> Bootstrap:
    """Extract the image bootstrap from a (decompressed) bootstrap *layer*:
    a standard tar carrying ``image/image.boot``
    (constant.go BootstrapFileNameInLayer, written by packToTar)."""
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:") as tf:
            for member in tf:
                if member.name in (layout.BOOTSTRAP_FILE, "./" + layout.BOOTSTRAP_FILE):
                    extracted = tf.extractfile(member)
                    if extracted is None:
                        break
                    return Bootstrap.from_bytes(extracted.read())
    except (tarfile.TarError, OSError) as e:
        raise ConvertError(f"bad bootstrap layer tar: {e}") from e
    raise ConvertError("bootstrap layer carries no image/image.boot")


def match_prefetch_paths(inodes, patterns: str) -> list[str]:
    """Resolve prefetch patterns to regular-file inode paths, hint order.

    Reference semantics (--prefetch-files, one path per line,
    daemon_adaptor.go:179-185): each line names a file or a directory
    prefix; directories expand to every regular file beneath them. Unknown
    patterns are skipped (hints, not requirements).
    """
    import stat as _stat

    wanted: list[str] = []
    seen: set[str] = set()
    lines = [ln.strip() for ln in patterns.splitlines() if ln.strip()]
    reg_paths = [i.path for i in inodes if _stat.S_ISREG(i.mode)]
    for line in lines:
        norm = "/" + line.strip("/") if line != "/" else "/"
        prefix = norm if norm == "/" else norm + "/"
        for path in reg_paths:
            if (path == norm or path.startswith(prefix)) and path not in seen:
                seen.add(path)
                wanted.append(path)
    return wanted


def Merge(
    layers: list[bytes | Bootstrap],
    opt: MergeOption,
    chunk_dict=None,
) -> MergeResult:
    """Merge per-layer bootstraps into one image bootstrap.

    ``layers`` are packed layer blobs (or already-parsed bootstraps), lowest
    first. Returns the image bootstrap plus the dedup result: the blob ids
    actually referenced (reference Merge surface convert_unix.go:560-666,
    whose blob-digest list comes from merge-output.json,
    tool/builder.go:278-294). ``chunk_dict`` passes an already-loaded dict
    object (batch conversion); ``opt.chunk_dict_path`` is the file fallback.
    """
    if not layers:
        raise ConvertError("merge needs at least one layer")
    if chunk_dict is None and opt.chunk_dict_path:
        from nydus_snapshotter_tpu.parallel.dict_service import open_chunk_dict

        chunk_dict = open_chunk_dict(opt.chunk_dict_path)
    from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap

    parent: Optional[Bootstrap] = None
    if opt.parent_bootstrap_path:
        with open(opt.parent_bootstrap_path, "rb") as f:
            parent = load_any_bootstrap(f.read())

    def _layer_bootstrap(layer: bytes) -> Bootstrap:
        # A framed layer stream (pack output) or a bare bootstrap in
        # either layout — the reference Merge takes per-layer bootstraps
        # (convert_unix.go:560-607), including real-toolchain ones.
        try:
            return bootstrap_from_layer_blob(layer)
        except (ConvertError, nydus_tar.TarFramingError, ValueError) as frame_err:
            try:
                return load_any_bootstrap(layer)
            except Exception as boot_err:
                # keep the framing diagnosis AND the caller-visible type
                raise ConvertError(
                    f"layer is neither a framed blob ({frame_err}) nor a "
                    f"bootstrap ({boot_err})"
                ) from frame_err

    merged: dict[str, _Node] = {}
    boots: list[Bootstrap] = []
    if parent is not None:
        boots.append(parent)
    for layer in layers:
        boots.append(
            layer if isinstance(layer, Bootstrap) else _layer_bootstrap(layer)
        )
    chunk_size = boots[-1].chunk_size
    version = opt.fs_version or boots[-1].version
    lower: list[_Node] = []
    for b in boots:
        lower = fstree.apply_overlay(lower, _layer_nodes(b))  # type: ignore[arg-type]

    # Chunk-dict dedup at merge time: chunks whose digest is in the dict are
    # re-pointed at the dict blob.
    inodes: list[Inode] = []
    chunk_records: list[ChunkRecord] = []
    blob_index_of: dict[str, int] = {}
    blob_records: dict[str, BlobRecord] = {}
    blob_ciphers: dict[str, CipherRecord] = {}
    blob_batches: dict[tuple[str, int], tuple[int, int]] = {}
    source_boots = boots + ([chunk_dict.bootstrap] if chunk_dict is not None else [])
    for b in source_boots:
        for i, rec in enumerate(b.blobs):
            blob_records.setdefault(rec.blob_id, rec)
            cipher = b.cipher_for(i)
            if cipher is not None:
                blob_ciphers.setdefault(rec.blob_id, cipher)
        ids = [r.blob_id for r in b.blobs]
        for br in b.batches:
            if br.blob_index < len(ids):
                blob_batches.setdefault(
                    (ids[br.blob_index], br.compressed_offset),
                    (br.uncompressed_base, br.uncompressed_size),
                )

    def blob_index(bid: str) -> int:
        if bid not in blob_index_of:
            blob_index_of[bid] = len(blob_index_of)
        return blob_index_of[bid]

    for node in lower:  # already path-sorted by apply_overlay
        inode = node.inode
        inode.chunk_index = len(chunk_records)
        inode.chunk_count = len(node.chunks)
        for rec, bid in node.chunks:
            hit = chunk_dict.get(rec.digest) if chunk_dict is not None else None
            if hit is not None:
                chunk_records.append(
                    ChunkRecord(
                        digest=rec.digest,
                        blob_index=blob_index(chunk_dict.blob_id_for(hit)),
                        flags=hit.flags,
                        uncompressed_offset=hit.uncompressed_offset,
                        compressed_offset=hit.compressed_offset,
                        uncompressed_size=hit.uncompressed_size,
                        compressed_size=hit.compressed_size,
                    )
                )
            else:
                rec2 = ChunkRecord(**{**rec.__dict__})
                rec2.blob_index = blob_index(bid)
                chunk_records.append(rec2)
        inodes.append(inode)

    blob_table = []
    cipher_table = []
    for bid, _idx in sorted(blob_index_of.items(), key=lambda kv: kv[1]):
        base = blob_records.get(bid)
        if base is None:
            raise ConvertError(f"chunk references unknown blob {bid}")
        blob_table.append(base)
        cipher_table.append(blob_ciphers.get(bid) or CipherRecord())
    batch_table = sorted(
        (
            BatchRecord(blob_index_of[bid], coff, u_base, usize)
            for (bid, coff), (u_base, usize) in blob_batches.items()
            if bid in blob_index_of
        ),
        key=lambda b: (b.blob_index, b.compressed_offset),
    )

    bootstrap = Bootstrap(
        version=version,
        chunk_size=chunk_size,
        inodes=inodes,
        chunks=chunk_records,
        blobs=blob_table,
        ciphers=cipher_table if any(c.algo for c in cipher_table) else [],
        batches=batch_table,
        prefetch=match_prefetch_paths(inodes, opt.prefetch_patterns)
        if opt.prefetch_patterns
        else [],
    )
    if opt.bootstrap_format in ("rafs-v5", "rafs-v6"):
        # Emit the image bootstrap in the reference toolchain's own
        # layout so its ecosystem can mount what this framework built.
        if bootstrap.ciphers or bootstrap.batches:
            raise ConvertError(
                "encrypted/batched bootstraps have no real-layout "
                "representation; use bootstrap_format='native'"
            )
        from nydus_snapshotter_tpu.models.nydus_real_write import (
            real_from_bootstrap,
            write_real_v5,
            write_real_v6,
        )

        from nydus_snapshotter_tpu.models.nydus_real import RealBootstrapError

        try:
            real = real_from_bootstrap(bootstrap, digester=opt.digester)
            boot_bytes = (
                write_real_v5(real)
                if opt.bootstrap_format == "rafs-v5"
                else write_real_v6(real)
            )
        except RealBootstrapError as e:
            raise ConvertError(f"real-layout emit failed: {e}") from e
    elif opt.bootstrap_format in ("", "native"):
        boot_bytes = bootstrap.to_bytes()
    else:
        raise ConvertError(
            f"unknown bootstrap_format {opt.bootstrap_format!r} "
            "(native | rafs-v5 | rafs-v6)"
        )
    if opt.with_tar:
        # Standard forward tar carrying image/image.boot — the bootstrap
        # *layer* format every consumer expects (reference packToTar;
        # referrer fetch unpacks it with plain tar, unpack.go:20-56).
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w:", format=tarfile.GNU_FORMAT) as tf:
            info = tarfile.TarInfo(layout.BOOTSTRAP_FILE)
            info.size = len(boot_bytes)
            info.mode = 0o444
            tf.addfile(info, io.BytesIO(boot_bytes))
        boot_bytes = out.getvalue()
    return MergeResult(
        bootstrap=boot_bytes,
        blob_digests=[b.blob_id for b in blob_table],
    )


# ---------------------------------------------------------------------------
# Unpack
# ---------------------------------------------------------------------------


def Unpack(
    bootstrap: bytes | Bootstrap,
    blob_provider: Callable[[str], bytes] | dict[str, bytes],
    opt: UnpackOption | None = None,
) -> bytes:
    """Rebuild the OCI tar from a bootstrap plus its blobs.

    ``blob_provider`` maps blob id → *blob data section* bytes (for a packed
    layer stream, pass the bytes of its ``image.blob`` section, see
    ``blob_data_from_layer_blob``). Reference surface convert_unix.go:669-733.
    Accepts REAL nydus-toolchain bootstraps too (auto-detected and bridged
    via models/nydus_real.load_any_bootstrap).
    """
    if isinstance(bootstrap, Bootstrap):
        bs = bootstrap
    else:
        from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap

        bs = load_any_bootstrap(bootstrap)
    provider = blob_provider.__getitem__ if isinstance(blob_provider, dict) else blob_provider
    readers: dict[int, BlobReader] = {}
    batch_map = bs.batch_map()

    def reader_for(blob_index: int) -> BlobReader:
        if blob_index not in readers:
            blob = provider(bs.blobs[blob_index].blob_id)
            readers[blob_index] = make_bytes_reader(bs, blob_index, blob, batch_map)
        return readers[blob_index]

    entries: list[fstree.FileEntry] = []
    for inode in bs.inodes:
        data = b""
        if stat.S_ISREG(inode.mode) and inode.chunk_count and not inode.hardlink_target:
            parts = []
            for rec in bs.chunks[inode.chunk_index : inode.chunk_index + inode.chunk_count]:
                parts.append(reader_for(rec.blob_index).chunk_data(rec))
            data = b"".join(parts)
            if len(data) != inode.size:
                raise ConvertError(
                    f"unpacked {inode.path}: got {len(data)} bytes, inode says {inode.size}"
                )
        entries.append(fstree.inode_to_entry(inode, data))
    return fstree.tar_from_tree(entries)


def frame_bootstrap_only(boot_bytes: bytes) -> bytes:
    """Frame a metadata-only layer stream (image.boot + TOC, no data
    section) — the OCIRef/zran layer shape, consumable by Merge like any
    packed layer."""
    import hashlib as _hashlib

    toc_bytes = toc.pack_toc(
        [
            toc.TOCEntry(
                name=toc.ENTRY_BOOTSTRAP,
                flags=constants.COMPRESSOR_NONE,
                uncompressed_digest=_hashlib.sha256(boot_bytes).digest(),
                compressed_offset=0,
                compressed_size=len(boot_bytes),
                uncompressed_size=len(boot_bytes),
            )
        ]
    )
    return nydus_tar.pack_entries(
        [(toc.ENTRY_BOOTSTRAP, boot_bytes), (toc.ENTRY_BLOB_TOC, toc_bytes)]
    )


def blob_data_from_layer_blob(blob: bytes) -> bytes:
    """Extract the image.blob section from a packed layer stream ('' if none)."""
    f = io.BytesIO(blob)
    loc = nydus_tar.seek_file_by_tar_header(f, len(blob), toc.ENTRY_BLOB_DATA)
    if loc is None:
        return b""
    off, size = loc
    return blob[off : off + size]
