"""Content-store HTTP proxy for streaming unpack.

Reference pkg/converter/cs_proxy_unix.go:33-168: ``Unpack`` with streaming
enabled doesn't buffer whole blobs — it serves the content store over a
local HTTP endpoint and hands the consumer range-addressable blob URLs
(``http://<addr>/readblob/<digest>?offset=..&size=..``). Same contract
here, over TCP on localhost or a UDS.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import Optional

from nydus_snapshotter_tpu.converter.content import LocalContentStore

logger = logging.getLogger(__name__)


class ContentStoreProxy:
    """Serve blobs by digest with Range support (cs_proxy_unix.go:56-117)."""

    def __init__(self, cs: LocalContentStore, host: str = "127.0.0.1", port: int = 0):
        self.cs = cs
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                parsed = urllib.parse.urlsplit(self.path)
                parts = parsed.path.strip("/").split("/")
                if len(parts) != 2 or parts[0] != "readblob":
                    self.send_response(404)
                    self.end_headers()
                    return
                digest = parts[1]
                params = urllib.parse.parse_qs(parsed.query)
                try:
                    data = proxy.cs.read(digest)
                except Exception as e:
                    logger.warning("readblob %s: %s", digest, e)
                    self.send_response(404)
                    self.end_headers()
                    return
                offset = int(params.get("offset", ["0"])[0])
                size = int(params.get("size", [str(len(data))])[0])
                body = data[offset : offset + size]
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.server = Server((host, port), Handler)
        self.thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self.server.server_address[:2]
        return f"{host}:{port}"

    def blob_url(self, digest: str, offset: int = 0, size: int = -1) -> str:
        url = f"http://{self.address}/readblob/{digest}?offset={offset}"
        if size >= 0:
            url += f"&size={size}"
        return url

    def start(self) -> None:
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
