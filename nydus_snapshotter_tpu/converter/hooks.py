"""containerd image-converter hooks: OCI manifest → nydus manifest rewrite.

Reference pkg/converter/convert_unix.go:735-1219. The flow a client
(nydusify / acceld equivalent) drives against the local content store:

1. ``layer_convert_func(opt)`` converts each OCI layer blob to a nydus blob
   (Pack), honoring the conversion cache label ``nydus-target-digest`` so a
   re-converted layer is a metadata no-op (:842-844);
2. ``convert_hook_func(opt)`` rewrites the manifest: all nydus blob layers
   + one merged gzip bootstrap layer, updated config diffIDs/history, GC
   labels on the manifest blob (:933-1070);
3. ``merge_layers`` produces the bootstrap layer descriptor and the
   dedup'd blob descriptor list (:1074-1219).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import logging
from typing import Callable, Optional

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu.converter import convert
from nydus_snapshotter_tpu.converter.content import LocalContentStore
from nydus_snapshotter_tpu.converter.types import MergeOption, PackOption
from nydus_snapshotter_tpu.remote.registry import Descriptor
from nydus_snapshotter_tpu.remote.unpack import decompress_stream
from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)

_LAYER_MEDIA_TYPES = {
    "application/vnd.docker.image.rootfs.diff.tar",
    "application/vnd.docker.image.rootfs.diff.tar.gzip",
    "application/vnd.oci.image.layer.v1.tar",
    "application/vnd.oci.image.layer.v1.tar+gzip",
    "application/vnd.oci.image.layer.v1.tar+zstd",
}

_MANIFEST_MEDIA_TYPES = {
    "application/vnd.oci.image.manifest.v1+json",
    "application/vnd.docker.distribution.manifest.v2+json",
}

_INDEX_MEDIA_TYPES = {
    "application/vnd.oci.image.index.v1+json",
    "application/vnd.docker.distribution.manifest.list.v2+json",
}


def is_layer_type(media_type: str) -> bool:
    return media_type in _LAYER_MEDIA_TYPES or media_type == C.MEDIA_TYPE_NYDUS_BLOB


def is_nydus_blob(desc: Descriptor) -> bool:
    """convert_unix.go:747-755."""
    return C.LAYER_ANNOTATION_NYDUS_BLOB in (desc.annotations or {})


def is_nydus_bootstrap(desc: Descriptor) -> bool:
    """convert_unix.go:757-765."""
    return C.LAYER_ANNOTATION_NYDUS_BOOTSTRAP in (desc.annotations or {})


def is_nydus_image(manifest: dict) -> bool:
    """Last layer is a bootstrap (convert_unix.go:767-778)."""
    layers = manifest.get("layers") or []
    return bool(layers) and C.LAYER_ANNOTATION_NYDUS_BOOTSTRAP in (
        layers[-1].get("annotations") or {}
    )


def _chain_id(ids: list[str]) -> str:
    """OCI identity.ChainID over digest strings."""
    if not ids:
        return ""
    chain = ids[0]
    for d in ids[1:]:
        chain = "sha256:" + hashlib.sha256(f"{chain} {d}".encode()).hexdigest()
    return chain


def make_blob_desc(
    cs: LocalContentStore, opt: PackOption, source_digest: str, target_digest: str
) -> Descriptor:
    """convert_unix.go makeBlobDesc :780-820."""
    info = cs.info(target_digest)
    cs.update_labels(target_digest, {C.LAYER_ANNOTATION_UNCOMPRESSED: target_digest})
    annotations = {
        C.LAYER_ANNOTATION_UNCOMPRESSED: target_digest,
        C.LAYER_ANNOTATION_NYDUS_BLOB: "true",
    }
    if opt.oci_ref:
        annotations[C.NYDUS_REF_LAYER] = source_digest
    if opt.encrypt:
        annotations[C.LAYER_ANNOTATION_NYDUS_ENCRYPTED_BLOB] = "true"
    return Descriptor(
        media_type=C.MEDIA_TYPE_NYDUS_BLOB,
        digest=target_digest,
        size=info.size,
        annotations=annotations,
    )


def layer_convert_func(
    opt: PackOption, backend_push: Optional[Callable] = None
) -> Callable[[LocalContentStore, Descriptor], Optional[Descriptor]]:
    """convert_unix.go LayerConvertFunc :822-928."""

    def convert_layer(cs: LocalContentStore, desc: Descriptor) -> Optional[Descriptor]:
        if not is_layer_type(desc.media_type):
            return None
        if is_nydus_blob(desc) or is_nydus_bootstrap(desc):
            return None

        # Conversion cache: an already-converted layer is a metadata no-op
        # (:842-844, constant.go ManifestNydusCache).
        info = cs.info(desc.digest)
        cached = info.labels.get(C.LAYER_ANNOTATION_NYDUS_TARGET_DIGEST, "")
        if cached.startswith("sha256:") and cs.exists(cached):
            return make_blob_desc(cs, opt, desc.digest, cached)

        raw = cs.read(desc.digest)
        if opt.oci_ref:
            # zran shape (create --type targz-ref, builder.go:180-218): the
            # original .tar.gz stays the only data artifact; the converted
            # "blob" is a bootstrap-only stream indexing its decompressed
            # content (converter/zran.py).
            from nydus_snapshotter_tpu.converter import zran

            bs = zran.pack_gzip_layer(raw, opt)
            blob_stream = convert.frame_bootstrap_only(bs.to_bytes())
        else:
            tar_bytes = decompress_stream(raw)
            blob_stream, _result = convert.pack_layer(tar_bytes, opt)
        blob_digest = "sha256:" + hashlib.sha256(blob_stream).hexdigest()
        cs.write_blob(blob_stream, expected_digest=blob_digest)
        cs.update_labels(
            desc.digest, {C.LAYER_ANNOTATION_NYDUS_TARGET_DIGEST: blob_digest}
        )
        new_desc = make_blob_desc(cs, opt, desc.digest, blob_digest)
        if backend_push is not None:
            backend_push(cs, new_desc)
        return new_desc

    return convert_layer


def merge_layers(
    cs: LocalContentStore, descs: list[Descriptor], opt: MergeOption
) -> tuple[Descriptor, list[Descriptor]]:
    """convert_unix.go MergeLayers :1074-1219: bootstrap layer descriptor +
    dedup'd blob descriptor list."""
    layer_blobs = [cs.read(d.digest) for d in descs]
    result = convert.Merge(layer_blobs, opt)

    # Merge reports the dedup result as inner blob-data ids (the bootstrap
    # blob table). In the reference those equal the layer digests because
    # meta is inline (--blob-inline-meta); here the stored layer stream is
    # tar-framed, so map inner id -> stored stream descriptor.
    desc_by_blob_id: dict[str, Descriptor] = {}
    for d, stream in zip(descs, layer_blobs):
        try:
            for blob in convert.bootstrap_from_layer_blob(stream).blobs:
                desc_by_blob_id.setdefault(blob.blob_id, d)
        except Exception:
            continue

    boot_bytes = result.bootstrap
    uncompressed_digest = "sha256:" + hashlib.sha256(boot_bytes).hexdigest()
    compressed = gzip.compress(boot_bytes, mtime=0)
    compressed_digest = "sha256:" + hashlib.sha256(compressed).hexdigest()
    cs.write_blob(
        compressed,
        labels={C.LAYER_ANNOTATION_UNCOMPRESSED: uncompressed_digest},
        expected_digest=compressed_digest,
    )

    # Dedup result: the blob list the final bootstrap actually references —
    # with OCIRef the original OCI layer blobs stay authoritative.
    blob_descs: list[Descriptor] = []
    if opt.oci_ref:
        for d in descs:
            annotations = {
                C.LAYER_ANNOTATION_UNCOMPRESSED: d.digest,
                C.LAYER_ANNOTATION_NYDUS_BLOB: "true",
            }
            ref = (d.annotations or {}).get(C.NYDUS_REF_LAYER, "")
            if ref:
                annotations[C.NYDUS_REF_LAYER] = ref
            blob_descs.append(
                Descriptor(
                    media_type=C.MEDIA_TYPE_NYDUS_BLOB,
                    digest=d.digest,
                    size=d.size,
                    annotations=annotations,
                )
            )
    else:
        seen: set[str] = set()
        for blob_id in result.blob_digests:
            mapped = desc_by_blob_id.get(blob_id)
            if mapped is not None:
                digest, size = mapped.digest, mapped.size
            elif cs.exists("sha256:" + blob_id):
                digest = "sha256:" + blob_id  # e.g. chunk-dict blob stored raw
                size = cs.info(digest).size
            else:
                raise errdefs.NotFound(
                    f"merged bootstrap references unknown blob {blob_id}"
                )
            if digest in seen:
                continue
            seen.add(digest)
            blob_descs.append(
                Descriptor(
                    media_type=C.MEDIA_TYPE_NYDUS_BLOB,
                    digest=digest,
                    size=size,
                    annotations={
                        C.LAYER_ANNOTATION_UNCOMPRESSED: digest,
                        C.LAYER_ANNOTATION_NYDUS_BLOB: "true",
                    },
                )
            )

    media_type = (
        "application/vnd.oci.image.layer.v1.tar+gzip"
        if opt.oci
        else "application/vnd.docker.image.rootfs.diff.tar.gzip"
    )
    bootstrap_desc = Descriptor(
        media_type=media_type,
        digest=compressed_digest,
        size=len(compressed),
        annotations={
            C.LAYER_ANNOTATION_UNCOMPRESSED: uncompressed_digest,
            C.LAYER_ANNOTATION_FS_VERSION: opt.fs_version or "6",
            C.LAYER_ANNOTATION_NYDUS_BOOTSTRAP: "true",
        },
    )
    return bootstrap_desc, blob_descs


def convert_manifest(
    cs: LocalContentStore,
    old_desc: Descriptor,
    new_desc: Descriptor,
    opt: MergeOption,
    with_backend: bool = False,
) -> Descriptor:
    """convert_unix.go convertManifest :969-1070."""
    manifest = json.loads(cs.read(new_desc.digest))
    manifest_labels = dict(cs.info(new_desc.digest).labels)
    if is_nydus_image(manifest):
        return new_desc

    opt.with_tar = True
    if not opt.oci and old_desc.media_type == "application/vnd.oci.image.manifest.v1+json":
        opt.oci = True

    layer_descs = [Descriptor.from_json(o) for o in manifest.get("layers") or []]
    bootstrap_desc, blob_descs = merge_layers(cs, layer_descs, opt)

    if with_backend:
        # blobs live in external storage: manifest holds only the bootstrap
        manifest["layers"] = [bootstrap_desc.to_json()]
    else:
        for idx, blob_desc in enumerate(blob_descs):
            manifest_labels[f"containerd.io/gc.ref.content.l.{idx}"] = blob_desc.digest
        manifest["layers"] = [d.to_json() for d in blob_descs] + [bootstrap_desc.to_json()]
    manifest_labels[
        f"containerd.io/gc.ref.content.l.{len(manifest['layers']) - 1}"
    ] = bootstrap_desc.digest

    # Rewrite config diffIDs + history (:1016-1040).
    config_desc = Descriptor.from_json(manifest["config"])
    config = json.loads(cs.read(config_desc.digest))
    config_labels = dict(cs.info(config_desc.digest).labels)
    bootstrap_history = {
        "created_by": "Nydus Converter",
        "comment": "Nydus Bootstrap Layer",
    }
    if with_backend:
        config.setdefault("rootfs", {})["diff_ids"] = [
            bootstrap_desc.annotations[C.LAYER_ANNOTATION_UNCOMPRESSED]
        ]
        config["history"] = [bootstrap_history]
    else:
        diff_ids = []
        for layer in manifest["layers"]:
            annos = layer.get("annotations") or {}
            diff_ids.append(annos.get(C.LAYER_ANNOTATION_UNCOMPRESSED, ""))
            annos.pop(C.LAYER_ANNOTATION_UNCOMPRESSED, None)
        config.setdefault("rootfs", {})["diff_ids"] = diff_ids
        config.setdefault("history", []).append(bootstrap_history)

    config_bytes = json.dumps(config).encode()
    new_config_digest = "sha256:" + hashlib.sha256(config_bytes).hexdigest()
    cs.write_blob(config_bytes, labels=config_labels, expected_digest=new_config_digest)
    manifest["config"] = {
        "mediaType": config_desc.media_type,
        "digest": new_config_digest,
        "size": len(config_bytes),
    }
    manifest_labels["containerd.io/gc.ref.content.config"] = new_config_digest

    if opt.with_referrer:
        subject = old_desc.to_json()
        subject.pop("platform", None)
        manifest["subject"] = subject

    manifest_bytes = json.dumps(manifest).encode()
    new_manifest_digest = "sha256:" + hashlib.sha256(manifest_bytes).hexdigest()
    cs.write_blob(manifest_bytes, labels=manifest_labels, expected_digest=new_manifest_digest)
    return Descriptor(
        media_type=new_desc.media_type,
        digest=new_manifest_digest,
        size=len(manifest_bytes),
        annotations=new_desc.annotations,
    )


def convert_hook_func(
    opt: MergeOption, with_backend: bool = False
) -> Callable[[LocalContentStore, Descriptor, Optional[Descriptor]], Descriptor]:
    """convert_unix.go ConvertHookFunc :933-950."""

    def hook(
        cs: LocalContentStore, org_desc: Descriptor, new_desc: Optional[Descriptor]
    ) -> Descriptor:
        if new_desc is None:
            return org_desc
        if new_desc.media_type in _INDEX_MEDIA_TYPES:
            index = json.loads(cs.read(new_desc.digest))
            manifests = index.get("manifests") or []
            if len(manifests) == 1:
                return Descriptor.from_json(manifests[0])
            return new_desc
        if new_desc.media_type in _MANIFEST_MEDIA_TYPES:
            return convert_manifest(cs, org_desc, new_desc, opt, with_backend)
        return new_desc

    return hook


def convert_image(
    cs: LocalContentStore,
    manifest_desc: Descriptor,
    pack_opt: PackOption,
    merge_opt: MergeOption,
) -> Descriptor:
    """End-to-end image conversion driver (the containerd
    images/converter role): convert every layer, then rewrite the
    manifest. Returns the new manifest descriptor."""
    manifest = json.loads(cs.read(manifest_desc.digest))
    convert_one = layer_convert_func(pack_opt)
    new_layers = []
    for layer_json in manifest.get("layers") or []:
        desc = Descriptor.from_json(layer_json)
        converted = convert_one(cs, desc)
        new_layers.append((converted or desc).to_json())
    manifest["layers"] = new_layers
    body = json.dumps(manifest).encode()
    digest = "sha256:" + hashlib.sha256(body).hexdigest()
    cs.write_blob(body, expected_digest=digest)
    intermediate = Descriptor(
        media_type=manifest_desc.media_type, digest=digest, size=len(body)
    )
    return convert_hook_func(merge_opt)(cs, manifest_desc, intermediate)
