"""Streaming Pack: bounded-memory OCI-tar → nydus-blob conversion.

The reference streams a layer through 1 MiB FIFO buffers into the builder
process (pkg/converter/convert_unix.go:56-61,443-539) so conversion memory
is independent of layer size. This module is that discipline rebuilt around
the in-process engine:

    tar stream → per-file incremental CDC (bounded carry) → digest batches
    (device-dispatched double-buffered, or host thread pool) → dedup →
    compress/batch-pack → encrypt → dest

Nothing holds the whole layer: the chunker carries at most ``max_size`` of
lookahead per file, digests travel in fixed-budget batches (one in flight on
device while the host reads the next — JAX's async dispatch is the double
buffer), and blob bytes stream straight to ``dest`` because the nydus
framing puts each tar header *after* its data (models/nydus_tar.py). Only
metadata (inodes + chunk records) accumulates, O(files + chunks).

``converter.convert.Pack`` delegates here — this is the only Pack
implementation, so in-memory and streaming callers share one code path.
"""

from __future__ import annotations

import hashlib
import math
import os
import stat
import tarfile
from dataclasses import dataclass, field
from typing import BinaryIO, Optional

import numpy as np

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter import crypto
from nydus_snapshotter_tpu.converter.types import ConvertError, PackOption
from nydus_snapshotter_tpu.models import fstree, layout, nydus_tar, toc
from nydus_snapshotter_tpu.models.bootstrap import (
    CHUNK_FLAG_BATCH,
    BatchRecord,
    BlobRecord,
    Bootstrap,
    ChunkDict,
    ChunkRecord,
    CipherRecord,
    Inode,
    parse_chunk_dict_arg,
)
from nydus_snapshotter_tpu.ops import cdc

SEGMENT_BYTES = 4 << 20  # tar read granularity
DIGEST_BATCH_BYTES = 32 << 20  # chunk bytes per digest batch


class _CountingWriter:
    """Tracks the write position so ``dest`` needn't be seekable."""

    def __init__(self, f: BinaryIO):
        self.f = f
        self.pos = 0

    def write(self, b: bytes) -> int:
        self.f.write(b)
        self.pos += len(b)
        return len(b)

    def tell(self) -> int:
        return self.pos


class IncrementalChunker:
    """Per-file CDC with bounded carry.

    A FastCDC cut ending the chunk that starts at ``s`` depends only on
    bytes ``[s, s + max_size)``, so any cut whose chunk start has a full
    ``max_size`` of lookahead in the buffer is final; the rest is carried.
    Produces exactly the cuts a whole-stream run produces (ops/cdc.py
    resolution, native or numpy backend).
    """

    def __init__(self, opt: PackOption, engine=None):
        from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine

        # One backend-selection policy: boundaries go through the engine
        # (jax = device two-phase candidates, hybrid = native, numpy = host).
        # Callers packing many files pass one shared engine instance.
        kwargs = {"digest_backend": opt.digest_backend} if opt.digest_backend else {}
        self._engine = engine or ChunkDigestEngine(
            chunk_size=opt.chunk_size,
            mode=opt.chunking,
            backend=opt.backend,
            digester=opt.digester,
            **kwargs,
        )
        self.lookahead = (
            self._engine.params.max_size if self._engine.params else opt.chunk_size
        )
        # Fused single-pass chunk+digest (native SIMD bitmaps + SHA-NI):
        # when the engine's fused arm is available, each drain yields
        # (chunk, digest) pairs directly — no separate digest sweep, no
        # per-chunk batching copies. Digests of carried-over chunks are
        # recomputed next drain (a few % of bytes at the drain cadence).
        self.fused = self._engine._fused_available()
        self._buf = bytearray()

    def _boundaries(self, data: "bytes | bytearray | np.ndarray") -> np.ndarray:
        return self._engine.boundaries(data)

    def feed(self, seg: bytes) -> list[tuple[bytes, Optional[bytes]]]:
        self._buf += seg
        if len(self._buf) < 2 * self.lookahead:
            return []
        return self._drain(final=False)

    def finish(self) -> list[tuple[bytes, Optional[bytes]]]:
        out = self._drain(final=True)
        self._buf = bytearray()
        return out

    def _drain(self, final: bool) -> list[tuple[bytes, Optional[bytes]]]:
        buf = self._buf
        if not buf:
            return []
        # The engine converts bytes/bytearray via a shared-memory
        # frombuffer view — no copy; boundaries (and fused digests) are
        # computed before any mutation of the buffer.
        if self.fused:
            from nydus_snapshotter_tpu.ops import native_cdc

            cuts, digests = native_cdc.chunk_digest_native(
                buf, self._engine.params, digester=self._engine.digester
            )
        else:
            cuts, digests = self._boundaries(buf), None
        out: list[tuple[bytes, Optional[bytes]]] = []
        s = 0
        for i, c in enumerate(cuts):
            c = int(c)
            if not final and s + self.lookahead > len(buf):
                break
            out.append(
                (
                    bytes(buf[s:c]),
                    digests[32 * i : 32 * (i + 1)] if digests is not None else None,
                )
            )
            s = c
        self._buf = bytearray(buf[s:]) if not final else bytearray()
        return out

    def chunk_whole(
        self, view: memoryview
    ) -> list[tuple[memoryview, Optional[bytes]]]:
        """Single-pass chunk(+digest) of a complete in-memory file.

        The in-memory fast path: no bytearray accumulation, no per-chunk
        bytes() materialization — chunks are zero-copy views into the
        caller's tar buffer (the reference avoids these copies by piping
        the raw stream straight into the builder process,
        pkg/converter/convert_unix.go:443-539).
        """
        if len(view) == 0:
            return []
        arr = np.frombuffer(view, dtype=np.uint8)
        if self.fused:
            from nydus_snapshotter_tpu.ops import native_cdc

            cuts, digests = native_cdc.chunk_digest_native(
                arr, self._engine.params, digester=self._engine.digester
            )
        else:
            cuts, digests = self._boundaries(arr), None
        out: list[tuple[memoryview, Optional[bytes]]] = []
        s = 0
        for i, c in enumerate(cuts):
            c = int(c)
            out.append(
                (
                    view[s:c],
                    digests[32 * i : 32 * (i + 1)] if digests is not None else None,
                )
            )
            s = c
        return out


class _HostDigester:
    """Synchronous batch digests on the host.

    Chunks arrive as separate byte strings; packing them into one buffer
    + extent list lets the native SHA-NI arm digest the whole batch in a
    single GIL-dropping call with its pairwise chain interleaving —
    per-chunk calls would forfeit both. hashlib thread pool otherwise.
    """

    def __init__(self, digester: str = "sha256"):
        self.digester = digester

    def submit(self, datas: list[bytes]):
        from nydus_snapshotter_tpu.ops.chunker import host_digests_for

        # One shared buffer so the same-source-array grouping makes a
        # single native call for the whole batch.
        buf = np.frombuffer(b"".join(datas), dtype=np.uint8)
        items = []
        off = 0
        for d in datas:
            items.append((buf, off, len(d)))
            off += len(d)
        return host_digests_for(self.digester)(items)

    def collect(self, handle) -> list[bytes]:
        return handle


class _DeviceDigester:
    """Async device digests: submit dispatches (JAX async), collect blocks.

    Holding exactly one batch in flight while the host reads/chunks the next
    is the double-buffered infeed — device SHA-256 overlaps tar ingest.
    """

    def __init__(self, max_chunk: int):
        # Padded-block bucket clamp at the engine's true max chunk size
        # (a max-size chunk is one block over a power of two; rounding up
        # would double the scan — same reasoning as
        # ops/chunker._digests_bucketed).
        from nydus_snapshotter_tpu.ops import sha256

        self._max_blocks = sha256.n_padded_blocks(max_chunk)

    def submit(self, datas: list[bytes]):
        import jax.numpy as jnp

        from nydus_snapshotter_tpu.ops import sha256
        from nydus_snapshotter_tpu.ops.chunker import _pow2_ceil

        max_blocks = self._max_blocks
        buckets: dict[int, list[int]] = {}
        for i, d in enumerate(datas):
            nb = sha256.n_padded_blocks(len(d))
            cap = min(1 << (nb - 1).bit_length() if nb > 1 else 1, max_blocks)
            buckets.setdefault(cap, []).append(i)
        parts = []
        for cap, idxs in sorted(buckets.items()):
            blocks, counts = sha256.pack_messages_np([datas[i] for i in idxs], block_capacity=cap)
            m_pad = _pow2_ceil(len(idxs)) - len(idxs)
            if m_pad:
                blocks = np.concatenate([blocks, np.zeros((m_pad, cap, 16), np.uint32)])
                counts = np.concatenate([counts, np.zeros(m_pad, np.int32)])
            states = sha256.sha256_batch(jnp.asarray(blocks), jnp.asarray(counts))
            parts.append((idxs, states))
        return (len(datas), parts)

    def collect(self, handle) -> list[bytes]:
        import jax

        from nydus_snapshotter_tpu.ops import sha256

        n, parts = handle
        out: list[Optional[bytes]] = [None] * n
        for idxs, states in parts:
            host = np.asarray(jax.device_get(states))
            for row, i in enumerate(idxs):
                out[i] = sha256.digest_to_bytes(host[row])
        return out  # type: ignore[return-value]


class _SectionWriter:
    """Streams the image.blob data section: alignment, batch packing,
    compression, encryption, hashing, extent accounting."""

    def __init__(self, out: _CountingWriter, opt: PackOption, compress):
        self.out = out
        self.compress = compress
        self.align = 4096 if (opt.aligned_chunk and opt.fs_version == layout.RAFS_V5) else 1
        self.batch_size = opt.batch_size
        self.hasher = hashlib.sha256()
        self.cipher: Optional[CipherRecord] = None
        self._encryptor = None
        if opt.encrypt:
            key, iv = crypto.generate_context()
            self.cipher = CipherRecord(algo=crypto.CIPHER_AES_256_CTR, key=key, iv=iv)
            self._encryptor = crypto.stream_encryptor(key, iv)
        self.coff = 0  # current offset within the data section
        self.extents: list[Optional[tuple[int, int, int]]] = []  # per unique chunk
        self.batches: list[tuple[int, int, int]] = []  # (coff, uncomp_base, usize)
        self._pending: list[tuple[int, bytes, int]] = []  # (uniq_idx, data, uoff)
        self._pending_bytes = 0

    def _write_raw(self, b: bytes) -> None:
        if self._encryptor is not None:
            b = self._encryptor.update(b)
        self.hasher.update(b)
        self.out.write(b)
        self.coff += len(b)

    def _emit(self, comp: bytes) -> int:
        pad = (-self.coff) % self.align
        if pad:
            self._write_raw(b"\x00" * pad)
        start = self.coff
        self._write_raw(comp)
        return start

    def _flush_batch(self) -> None:
        if not self._pending:
            return
        comp, cflag = self.compress(b"".join(d for _, d, _ in self._pending))
        start = self._emit(comp)
        for idx, _d, _u in self._pending:
            self.extents[idx] = (start, len(comp), cflag | CHUNK_FLAG_BATCH)
        self.batches.append((start, self._pending[0][2], self._pending_bytes))
        self._pending = []
        self._pending_bytes = 0

    def add(self, uniq_idx: int, data: bytes, uoff: int, precomp=None) -> None:
        assert uniq_idx == len(self.extents)
        self.extents.append(None)
        if self.batch_size and len(data) < self.batch_size:
            if self._pending_bytes + len(data) > self.batch_size:
                self._flush_batch()
            self._pending.append((uniq_idx, data, uoff))
            self._pending_bytes += len(data)
        else:
            self._flush_batch()
            # precomp: the chunk was compressed speculatively off-thread
            # (deterministic codec, same bytes as compressing here).
            comp, cflag = precomp if precomp is not None else self.compress(data)
            self.extents[uniq_idx] = (self._emit(comp), len(comp), cflag)

    def finish(self) -> None:
        self._flush_batch()
        if self._encryptor is not None:
            tail = self._encryptor.finalize()
            if tail:
                self.hasher.update(tail)
                self.out.write(tail)
                self.coff += len(tail)


class _SectionDigest:
    """hasher-shim over the digest the native pass computed."""

    def __init__(self) -> None:
        self._d = b""

    def digest(self) -> bytes:
        return self._d

    def hexdigest(self) -> str:
        return self._d.hex()


class _DeferredSectionWriter:
    """Blob data section assembled in ONE native pass at finish().

    During the walk, add() only records each unique chunk's source extent
    (zero-copy offsets into the caller's tar buffer; loose bytes go to a
    side buffer). finish() hands the whole extent list to
    ntpu_pack_section, which runs the per-chunk compress -> append loop
    and the section SHA-256 natively — the reference keeps this exact
    loop inside one `nydus-image create` process
    (pkg/converter/tool/builder.go:148-178), and re-entering Python per
    chunk was the dominant full-path overhead.

    Only used for layouts it reproduces byte-identically to
    _SectionWriter: chunks packed back-to-back (align 1, no batch
    packing), no encryption, lz4_block/zstd/none compressor (native zstd
    is ZSTD_compress level 3 — byte-identical to the Python lane's
    zstandard level-3 context against the same libzstd). If the native
    arm is unavailable at finish() (e.g. liblz4/libzstd vanished), the
    recorded extents replay through the Python codec — same bytes either
    way.
    """

    def __init__(self, out: _CountingWriter, opt: PackOption, compress, raw: memoryview):
        self.out = out
        self.compress = compress  # replay fallback only
        self.hasher = _SectionDigest()
        self.cipher = None
        self.coff = 0
        self.extents: list[Optional[tuple[int, int, int]]] = []
        self.batches: list[tuple[int, int, int]] = []
        self._kind = {"lz4_block": 1, "zstd": 2}.get(opt.compressor, 0)
        # codec-param slot: lz4 acceleration, or the zstd level (single
        # source constants.ZSTD_LEVEL — threads through to the native arm)
        self._accel = (
            constants.ZSTD_LEVEL if self._kind == 2 else opt.lz4_acceleration
        )
        self._cflag = {
            "lz4_block": constants.COMPRESSOR_LZ4_BLOCK,
            "zstd": constants.COMPRESSOR_ZSTD,
        }.get(opt.compressor, constants.COMPRESSOR_NONE)
        self._raw_arr = np.frombuffer(raw, dtype=np.uint8)
        self._base = self._raw_arr.ctypes.data
        self._raw_len = len(raw)
        self._items: list[tuple[int, int, int]] = []
        self._side = bytearray()

    def add(self, uniq_idx: int, data, uoff: int, precomp=None) -> None:
        assert uniq_idx == len(self._items)
        size = len(data)
        if isinstance(data, memoryview):
            off = np.frombuffer(data, dtype=np.uint8).ctypes.data - self._base
            if 0 <= off and off + size <= self._raw_len:
                self._items.append((0, off, size))
                return
            data = bytes(data)
        self._items.append((1, len(self._side), size))
        self._side += data

    def finish(self) -> None:
        from nydus_snapshotter_tpu.ops import native_cdc

        m = len(self._items)
        if m == 0:
            return
        ext = np.asarray(self._items, dtype=np.int64)
        side = np.frombuffer(self._side, dtype=np.uint8) if self._side else np.empty(0, np.uint8)
        n_threads = _pack_threads()
        res = native_cdc.pack_section(
            self._raw_arr, side, ext, self._kind, self._accel, n_threads
        )
        if res is None:
            # Replay through the Python codec (identical bytes, slower).
            hasher = hashlib.sha256()
            for src, off, size in self._items:
                buf = (
                    self._raw_arr[off : off + size]
                    if src == 0
                    else side[off : off + size]
                )
                comp, cflag = self.compress(memoryview(buf))
                self.extents.append((self.coff, len(comp), cflag))
                hasher.update(comp)
                self.out.write(comp)
                self.coff += len(comp)
            self.hasher._d = hasher.digest()
            return
        blob, comp_ext, digest = res
        self._adopt(blob, comp_ext, digest)

    def _adopt(self, blob, comp_extents, digest: bytes) -> None:
        """Adopt a native pass's assembled section (shared by finish()
        and finish_fused())."""
        self.extents = [
            (int(comp_extents[j, 0]), int(comp_extents[j, 1]), self._cflag)
            for j in range(comp_extents.shape[0])
        ]
        self.hasher._d = digest
        if blob.size:
            self.out.write(memoryview(blob))
        self.coff = int(blob.size)

    def finish_fused(self, blob, comp_extents, digest: bytes) -> None:
        """Adopt the whole-layer fused pass's output (ntpu_pack_files):
        the native call already compressed/assembled/hashed; nothing was
        ever add()ed, so the regular finish() stays a no-op."""
        self._adopt(blob, comp_extents, digest)


@dataclass
class _ChunkRef:
    """A file-extent's chunk before final record materialization."""

    digest: bytes
    size: int
    uniq_idx: int = -1  # index into the own-blob unique table
    dict_hit: Optional[ChunkRecord] = None


@dataclass
class _Meta:
    entry: fstree.FileEntry
    size: int = 0
    chunks: list[_ChunkRef] = field(default_factory=list)


def _pack_threads() -> int:
    """Worker count for the pack pipeline.

    ``NTPU_PACK_THREADS`` requests a count, but it auto-degrades to the
    core count: threads cannot help beyond the cores that exist, and the
    pooled pipeline measurably costs 13-23% over the fused single-thread
    lane when oversubscribed on one core (MULTICORE_r04). Tests that must
    exercise the threaded lanes regardless (the cross-lane byte-identity
    gate) set ``NTPU_PACK_THREADS_FORCE=1`` to bypass the clamp.
    """
    try:
        n = int(os.environ.get("NTPU_PACK_THREADS", ""))
    except ValueError:
        n = 0
    ncpu = os.cpu_count() or 1
    if n >= 1:
        if os.environ.get("NTPU_PACK_THREADS_FORCE", "") not in ("", "0"):
            return n
        return min(n, ncpu)
    return ncpu


def _tar_num(field: memoryview) -> int:
    """Tar numeric field: octal decoded inline (the ~100% case — int(_, 8)
    over the NUL-terminated, space-stripped text, exactly tarfile.nti's
    octal branch), GNU base-256 (lead byte 0x80/0xFF, e.g. >8 GiB sizes or
    pre-epoch mtimes) delegated to tarfile's decoder — one source of truth
    for the exotic branch; malformed fields raise ValueError so the fast
    scanner bails to tarfile."""
    b = bytes(field)
    if b and b[0] in (0x80, 0xFF):
        try:
            return tarfile.nti(b)
        except tarfile.InvalidHeaderError as e:
            raise ValueError(str(e)) from e
    end = b.find(0)
    s = (b if end < 0 else b[:end]).strip()
    if not s:
        return 0
    return int(s, 8)  # ValueError on garbage, as tarfile.nti raises


_TAR_PLAIN_TYPES = (b"0", b"\x00", b"1", b"2", b"3", b"4", b"5", b"6", b"7")


def _parse_pax_records(data: bytes) -> "dict[str, str] | None":
    """Decode a pax extended header block ("%d key=value\\n" records);
    None on malformed framing. Values decode utf-8/surrogateescape — the
    same round-trip tarfile uses, so binary xattrs survive."""
    out: dict[str, str] = {}
    pos = 0
    n = len(data)
    while pos < n:
        if data[pos] == 0:
            break  # zero padding after the last record
        sp = data.find(b" ", pos, pos + 20)
        if sp < 0:
            return None
        try:
            length = int(data[pos:sp])
        except ValueError:
            return None
        end = pos + length
        if length < sp - pos + 3 or end > n or data[end - 1] != 0x0A:
            return None
        eq = data.find(b"=", sp + 1, end)
        if eq < 0:
            return None
        key = data[sp + 1 : eq].decode("utf-8", "surrogateescape")
        out[key] = data[eq + 1 : end - 1].decode("utf-8", "surrogateescape")
        pos = end
    return out


def _fast_tar_members(raw: memoryview):
    """Header walk over an in-memory tar: [(TarInfo, data_offset)], or
    None when the archive needs tarfile's full machinery.

    tarfile.TarInfo.frombuf costs ~30 µs/member (field-by-field parse,
    encoding fallbacks) — ~20% of full-path convert on a node_modules-
    shaped layer. This scanner handles plain ustar/GNU members plus pax
    ``x`` extended headers (Go's archive/tar — the writer behind real
    docker layers — emits pax for xattrs/long names/big files) with
    checksum verification, and bails to tarfile for anything else: pax
    globals (g), GNU longname/longlink (L/K), sparse (S), non-ustar
    magic, truncated data, or a non-regular member carrying data. A None
    return loses nothing but the speedup.
    """
    out: list[tuple[tarfile.TarInfo, int]] = []
    pos = 0
    n = len(raw)
    saw_end = False
    pending_pax: "dict[str, str] | None" = None
    while pos + 512 <= n:
        hdr = raw[pos : pos + 512]
        hb = bytes(hdr)
        if hb[0] == 0:
            if hb.count(0) == 512:
                saw_end = True
                break  # end-of-archive
            return None
        if hb[257:263] not in (b"ustar\x00", b"ustar "):
            return None
        typ = hb[156:157]
        if typ not in _TAR_PLAIN_TYPES and typ != b"x":
            return None
        try:
            mode = _tar_num(hdr[100:108])
            uid = _tar_num(hdr[108:116])
            gid = _tar_num(hdr[116:124])
            size = _tar_num(hdr[124:136])
            mtime = _tar_num(hdr[136:148])
            chksum = _tar_num(hdr[148:156])
        except ValueError:
            return None
        if size < 0:
            # GNU base-256 can encode negative values; a negative size
            # would make the scan position stop advancing (infinite loop)
            # — bail and let tarfile reject the archive.
            return None
        if chksum != sum(hb) - sum(hb[148:156]) + 8 * 0x20:
            return None
        if typ == b"x":
            # pax extended header: records apply to the NEXT member.
            end = pos + 512 + size
            if end > n:
                return None
            pax = _parse_pax_records(bytes(raw[pos + 512 : end]))
            if pax is None:
                return None
            if any(k.startswith("GNU.sparse") for k in pax):
                # pax-sparse members need tarfile's sparse-map handling
                # (_proc_gnusparse_*): the data region is a packed map +
                # holes, not the file bytes.
                return None
            pending_pax = pax
            pos = pos + 512 + 512 * ((size + 511) // 512)
            continue
        if typ not in (b"0", b"\x00", b"7"):
            if size != 0:
                return None  # non-regular member carrying data: exotic
            data_size = 0
        else:
            data_size = size
        name = hb[:100].split(b"\x00", 1)[0].decode("utf-8", "surrogateescape")
        if hb[257:263] == b"ustar\x00":
            prefix = hb[345:500].split(b"\x00", 1)[0]
            if prefix:
                name = prefix.decode("utf-8", "surrogateescape") + "/" + name
        # tarfile semantics: a trailing slash marks a directory (even with
        # a regular typeflag) and is stripped from the stored name.
        if name.endswith("/"):
            if typ in (b"0", b"\x00"):
                typ = b"5"
            name = name.rstrip("/")
        ti = tarfile.TarInfo(name)
        ti.mode = mode
        ti.uid = uid
        ti.gid = gid
        ti.size = size
        ti.mtime = mtime
        ti.type = typ
        ti.linkname = hb[157:257].split(b"\x00", 1)[0].decode(
            "utf-8", "surrogateescape"
        )
        if typ in (b"3", b"4"):
            try:
                ti.devmajor = _tar_num(hdr[329:337])
                ti.devminor = _tar_num(hdr[337:345])
            except ValueError:
                return None  # malformed device numbers: let tarfile decide
        if pending_pax is not None:
            # Apply overrides exactly as tarfile._apply_pax_info does for
            # the fields this pipeline consumes.
            p = pending_pax
            try:
                if "path" in p:
                    # tarfile._apply_pax_info only rstrips; it never
                    # retypes on a trailing slash (that V7 rule applies to
                    # base-header names only).
                    ti.name = p["path"].rstrip("/")
                if "linkpath" in p:
                    ti.linkname = p["linkpath"]
                if "size" in p:
                    ti.size = int(p["size"])
                    if ti.size < 0:
                        # Bailing to tarfile is NOT safe here: tarfile
                        # walks backwards off the member and silently
                        # yields nothing more — a data-losing "valid"
                        # image. Reject outright.
                        raise ConvertError(
                            f"bad layer tar: negative pax size for {ti.name!r}"
                        )
                    if typ in (b"0", b"\x00", b"7"):
                        data_size = ti.size
                if "mtime" in p:
                    ti.mtime = float(p["mtime"])
                    if not math.isfinite(ti.mtime):
                        # nan/inf would escape later as a bare ValueError
                        # from int(mtime); bail to tarfile instead.
                        return None
                if "uid" in p:
                    ti.uid = int(p["uid"])
                if "gid" in p:
                    ti.gid = int(p["gid"])
            except ValueError:
                return None
            ti.pax_headers = p
            pending_pax = None
        data_off = pos + 512
        pos = data_off + 512 * ((data_size + 511) // 512)
        if pos > n:
            return None  # truncated member data: let tarfile raise
        out.append((ti, data_off))
    # Without the end-of-archive zero block the input is truncated or not
    # a tar at all (e.g. a few garbage bytes) — bail so tarfile raises the
    # proper error instead of silently converting to an empty image.
    return out if saw_end else None


def pack_stream(
    dest: BinaryIO,
    src_tar: "BinaryIO | bytes",
    opt: PackOption,
    chunk_dict=None,
    stats: "Optional[dict]" = None,
    budget=None,
    codec=None,
):
    """Stream one OCI layer tar into a nydus blob written to ``dest``.

    Reference semantics (convert_unix.go:325-539): uncompressed layer tar
    in, tar-like nydus blob out; chunk-dict hits are referenced, not stored.
    ``chunk_dict`` passes an already-loaded dict object (anything with the
    ChunkDict get/blob_id_for/bootstrap interface) so batch conversion can
    reuse one growing dict without re-parsing a bootstrap per layer;
    ``opt.chunk_dict_path`` is the file-based fallback.

    ``stats``: optional dict that accumulates per-stage wall seconds
    (in-memory fast-path semantics): ``scan`` tar walk + metadata,
    ``chunk_digest`` CDC + chunk SHA-256, ``dedup`` dedup/bookkeeping,
    ``assemble`` compression + blob append + blob digest,
    ``bootstrap`` inode/chunk-table serialization.

    ``budget``: optional :class:`parallel.pipeline.MemoryBudget` bounding
    this conversion's speculative-compression bytes in flight; batch
    conversion passes ONE budget for every concurrently packing layer so
    aggregate convert memory stays independent of layer count. ``None``
    draws from the process-wide shared budget.

    ``codec``: optional :class:`converter.codec.AdaptiveCodec` — the
    adaptive per-chunk zstd engine (probe/bypass/per-class levels/
    trained dict). ``None`` resolves it from config/env; when the engine
    is off (the default) the pack keeps the fixed-level lane and its
    byte-identity invariant, including the native deferred/fused section
    arms. An ACTIVE codec owns the chunk-frame decisions, so the pack
    routes through the Python section writer (the codec-stage interface
    a device-offloaded codec would implement too).
    """
    import io
    from time import perf_counter as _pc

    _t_chunk = 0.0
    _t_spec = 0.0  # speculative compression (counts toward 'assemble')
    _t_fused = 0.0  # whole-layer fused pass (chunk+dedup+assemble in one)

    opt.validate()
    # In-memory layers take the zero-copy path: random-access tar parse,
    # whole-file views sliced straight out of the caller's buffer (the
    # bounded-memory streaming discipline below only matters for file-like
    # sources that may not fit in RAM).
    raw: Optional[memoryview] = None
    if isinstance(src_tar, (bytes, bytearray)):
        raw = memoryview(src_tar)
        src_tar = io.BytesIO(src_tar)

    if chunk_dict is None and opt.chunk_dict_path:
        # service://<uds>[#namespace] connects a shared-dict mirror; any
        # other shape is the file-based dict as before.
        from nydus_snapshotter_tpu.parallel.dict_service import open_chunk_dict

        chunk_dict = open_chunk_dict(opt.chunk_dict_path)
    from nydus_snapshotter_tpu.converter.convert import _make_compressor

    if codec is None:
        from nydus_snapshotter_tpu.converter import codec as codec_mod

        codec = codec_mod.resolve_codec(opt)

    out = _CountingWriter(dest)
    from nydus_snapshotter_tpu.ops import native_cdc

    compress = _make_compressor(opt.compressor, opt.lz4_acceleration, codec=codec)
    align_needed = opt.aligned_chunk and opt.fs_version == layout.RAFS_V5
    if (
        raw is not None
        and opt.compressor in ("none", "lz4_block", "zstd")
        # the adaptive codec owns per-chunk frame decisions — the native
        # section arms compress at one fixed level and would bypass it
        and codec is None
        and not opt.encrypt
        and not opt.batch_size
        and not align_needed
        and native_cdc.pack_section_available()
    ):
        section: "object" = _DeferredSectionWriter(out, opt, compress, raw)
    else:
        section = _SectionWriter(out, opt, compress)
    max_chunk = cdc.CDCParams(opt.chunk_size).max_size if opt.chunking == "cdc" else opt.chunk_size
    digester = (
        _DeviceDigester(max_chunk)
        # the device batch kernel is SHA-256; blake3 always digests on the
        # host blake3 arm (native/pure-Python), whatever the backend
        if (opt.backend == "jax" or opt.digest_backend == "jax")
        and opt.digester == "sha256"
        else _HostDigester(opt.digester)
    )

    metas: dict[str, _Meta] = {}
    opaque_dirs: list[str] = []

    # Dedup state (chunk order = tar order; deterministic).
    own_chunks: dict[bytes, int] = {}
    uncomp_offsets: list[int] = []
    uoff = 0
    dict_hits: dict[bytes, ChunkRecord] = {}
    dict_blobs_used: list[str] = []

    # One digest batch in flight: (handle, [(meta, data)]) pairs.
    pending: list[tuple[_Meta, bytes]] = []
    pending_bytes = 0
    in_flight: Optional[tuple[object, list[tuple[_Meta, bytes]]]] = None

    def _process(
        batch: list[tuple[_Meta, bytes]],
        digests: list[bytes],
        comp_cache: "Optional[dict[bytes, tuple[bytes, int]]]" = None,
    ) -> None:
        nonlocal uoff
        for (meta, data), digest in zip(batch, digests):
            ref = _ChunkRef(digest=digest, size=len(data))
            if chunk_dict is not None and digest not in dict_hits and digest not in own_chunks:
                hit = chunk_dict.get(digest)
                if hit is not None:
                    dict_hits[digest] = hit
                    bid = chunk_dict.blob_id_for(hit)
                    if bid not in dict_blobs_used:
                        dict_blobs_used.append(bid)
            if digest in dict_hits:
                ref.dict_hit = dict_hits[digest]
            else:
                idx = own_chunks.get(digest)
                if idx is None:
                    idx = len(uncomp_offsets)
                    own_chunks[digest] = idx
                    uncomp_offsets.append(uoff)
                    section.add(
                        idx,
                        data,
                        uoff,
                        # pop: each unique digest reaches here exactly once;
                        # releasing the entry keeps peak RSS at one chunk,
                        # not the whole compressed blob.
                        precomp=comp_cache.pop(digest, None) if comp_cache else None,
                    )
                    uoff += len(data)
                ref.uniq_idx = idx
            meta.chunks.append(ref)

    def _dispatch() -> None:
        nonlocal pending, pending_bytes, in_flight
        if in_flight is not None:
            handle, batch = in_flight
            _process(batch, digester.collect(handle))
            in_flight = None
        if pending:
            in_flight = (digester.submit([d for _, d in pending]), pending)
            pending = []
            pending_bytes = 0

    def _drain_all() -> None:
        _dispatch()  # collects old, dispatches remainder
        _dispatch()  # collects remainder

    def _add_chunk(meta: _Meta, data: bytes, digest: Optional[bytes] = None) -> None:
        nonlocal pending_bytes
        if digest is not None:
            # the fused chunker already digested this chunk (cache-warm,
            # single native pass); dedup/write it immediately, in order
            _process([(meta, data)], [digest])
            return
        pending.append((meta, data))
        pending_bytes += len(data)
        if pending_bytes >= DIGEST_BATCH_BYTES:
            _dispatch()

    shared_chunker = IncrementalChunker(opt)
    # In-memory plan: chunk/digest work is deferred during the header walk
    # so thousands of small files (≤ one chunk each — the node_modules
    # shape) batch into a single native SHA sweep over the tar buffer
    # instead of one engine call per file. Entries stay in tar order, so
    # the blob layout and dedup state are identical to immediate
    # processing. ("small", meta, off, size) | ("file", meta, off, size)
    plan: list[tuple[str, _Meta, int, int]] = []
    params = shared_chunker._engine.params
    small_max = params.min_size if params is not None else opt.chunk_size
    defer_small = raw is not None and shared_chunker.fused

    def _walk_member(info, data_off, tf) -> None:
        path = fstree.norm_path(info.name)
        special = fstree.classify_special(path)
        if special is not None:
            kind, target = special
            if kind == "opaque":
                opaque_dirs.append(target)
            else:
                metas[target] = _Meta(entry=fstree.whiteout_entry(target))
            return
        entry = fstree.entry_from_tarinfo(tf, info, path, with_data=False)
        meta = _Meta(entry=entry)
        # A path repeated in the tar: last entry wins (as in a real
        # extraction); chunks already written for the earlier one stay in
        # the blob as dead bytes.
        metas[path] = meta
        if not (entry.is_regular and info.size > 0):
            return
        meta.size = info.size
        if data_off is not None and not getattr(info, "sparse", None):
            # Zero-copy: the member's bytes are a slice of the caller's
            # buffer (sparse members store data compacted, so they take
            # the extractfile path).
            tag = "small" if defer_small and info.size <= small_max else "file"
            plan.append((tag, meta, data_off, info.size))
            return
        f = tf.extractfile(info)
        if f is None:
            raise ConvertError(f"tar member {path!r} has no data stream")
        chunker = IncrementalChunker(opt, engine=shared_chunker._engine)
        while True:
            seg = f.read(SEGMENT_BYTES)
            if not seg:
                break
            for chunk, digest in chunker.feed(seg):
                _add_chunk(meta, chunk, digest)
        for chunk, digest in chunker.finish():
            _add_chunk(meta, chunk, digest)

    _t0 = _pc()
    members = _fast_tar_members(raw) if raw is not None else None
    if members is not None:
        for info, data_off in members:
            _walk_member(info, data_off, None)  # tf unused: data via raw
    else:
        try:
            # Random access for in-memory layers (tarfile's stream mode
            # copies every data byte through its internal block buffers).
            tf = tarfile.open(
                fileobj=src_tar, mode="r:" if raw is not None else "r|"
            )
        except tarfile.TarError as e:
            raise ConvertError(f"bad layer tar: {e}") from e
        with tf:
            try:
                for info in tf:
                    _walk_member(
                        info,
                        info.offset_data if raw is not None else None,
                        tf,
                    )
            except tarfile.TarError as e:
                raise ConvertError(f"bad layer tar: {e}") from e
    _t1 = _pc()
    if plan:
        from nydus_snapshotter_tpu.ops import native_cdc

        arr_all = np.frombuffer(raw, dtype=np.uint8)
        n_threads = _pack_threads()
        # Single-thread fast lane: ONE native call fuses chunk+digest for
        # EVERY planned file (small and large alike — a <= min_size file
        # is exactly one CDC chunk, so the unified pass subsumes the
        # batched small-file digest sweep). Cut points, digests, dedup
        # and blob bytes are bit-identical to the per-file path.
        use_multi = (
            n_threads == 1
            and shared_chunker.fused
            and params is not None
            and opt.chunking == "cdc"
            and native_cdc.chunk_digest_multi_available()
        )
        # Whole-layer fused lane: chunk + digest + first-wins dedup +
        # compress + assemble + blob hash in ONE native call (the
        # reference's entire `nydus-image create` hot loop). Applies when
        # there is no chunk dict (dict probes stay in the Python dedup
        # lane) and the storage layout is the deferred writer's.
        if (
            use_multi
            and chunk_dict is None
            and isinstance(section, _DeferredSectionWriter)
            and native_cdc.pack_files_available()
            # the walk must not have seeded any chunk state already
            # (sparse members stream through _process during the walk):
            # the fused pass owns the WHOLE dedup/storage state or none.
            and uoff == 0
            and not own_chunks
            and not pending
            and in_flight is None
            and not section._items
        ):
            ext = np.asarray(
                [(off, size) for _t, _m, off, size in plan], dtype=np.int64
            )
            _tc = _pc()
            fused = native_cdc.pack_files(
                arr_all, ext, params, section._kind, section._accel, n_threads,
                digester=opt.digester,
            )
            if fused is not None:
                digs = fused["digests"]
                sizes_arr = fused["chunk_sizes"]
                uniq_arr = fused["chunk_uniq"]
                pos = 0
                for (_tag, meta, _off, _size), nc in zip(
                    plan, fused["file_nchunks"]
                ):
                    for k in range(int(nc)):
                        meta.chunks.append(
                            _ChunkRef(
                                digest=digs[32 * (pos + k) : 32 * (pos + k + 1)],
                                size=int(sizes_arr[pos + k]),
                                uniq_idx=int(uniq_arr[pos + k]),
                            )
                        )
                    pos += int(nc)
                usz = fused["uniq_sizes"]
                if len(usz):
                    uncomp_offsets = (
                        np.concatenate([[0], np.cumsum(usz[:-1])])
                        .astype(np.int64)
                        .tolist()
                    )
                    uoff = int(usz.sum())
                section.finish_fused(
                    fused["blob"], fused["comp_extents"], fused["blob_digest"]
                )
                plan = []
                _t_fused += _pc() - _tc
        if use_multi and plan:
            ext = np.asarray(
                [(off, size) for _t, _m, off, size in plan], dtype=np.int64
            )
            _tc = _pc()
            ncuts_arr, cuts_all, digs_all = native_cdc.chunk_digest_multi(
                arr_all, ext, params, digester=opt.digester
            )
            _t_chunk += _pc() - _tc
            pos = 0
            for (tag, meta, off, size), nc in zip(plan, ncuts_arr):
                nc = int(nc)
                view = raw[off : off + size]
                s = 0
                batch = []
                dlist = []
                for k in range(nc):
                    c = int(cuts_all[pos + k])
                    batch.append((meta, view[s:c]))
                    dlist.append(digs_all[32 * (pos + k) : 32 * (pos + k + 1)])
                    s = c
                _process(batch, dlist)
                pos += nc
            plan = []  # consumed; skip the per-file paths below
        # Device full-path lane (opt.backend == "fused"): the WHOLE layer's
        # files as one two-dispatch device batch (ops/fused_convert —
        # gear+compaction, then gather+digest), host keeping only cut
        # metadata. Dedup (incl. chunk-dict probes) and compression stay
        # in the _process lane, byte-identical to the host paths.
        if (
            plan
            and opt.backend == "fused"
            and params is not None
            and opt.chunking == "cdc"
        ):
            from nydus_snapshotter_tpu.ops import fused_convert

            feng = fused_convert.FusedDeviceEngine(
                chunk_size=opt.chunk_size, digester=opt.digester
            )
            streams = [arr_all[off : off + size] for _t, _m, off, size in plan]
            _tc = _pc()
            try:
                fres = feng.process_many(streams)
            except fused_convert.FusedOverflow:
                fres = None  # pathological input: per-file paths below
            _t_chunk += _pc() - _tc
            if fres is not None:
                for (_tag, meta, off, size), fcuts, dlist in zip(
                    plan, fres.cuts, fres.digests
                ):
                    view = raw[off : off + size]
                    s = 0
                    batch = []
                    for c in fcuts:
                        batch.append((meta, view[s : int(c)]))
                        s = int(c)
                    if batch:
                        _process(batch, dlist)
                plan = []
        small_items = [
            (arr_all, off, size) for tag, _m, off, size in plan if tag == "small"
        ]
        if small_items:
            from nydus_snapshotter_tpu.ops.chunker import host_digests_for

            _tc = _pc()
            small_digests = iter(host_digests_for(opt.digester)(small_items))
            _t_chunk += _pc() - _tc

        # Within-layer parallelism for multi-core hosts (the reference gets
        # it from the builder's internal thread pool): the stage-parallel
        # pipeline (parallel/pipeline.py) chunks + digests files on a
        # worker pool, speculatively compresses each unique chunk as soon
        # as its digest exists — compression is deterministic, so racing
        # duplicate digests write identical bytes — and the ordered serial
        # walk below only dedups + assembles. Queues between stages are
        # byte-bounded and compressed bytes in flight draw from a
        # MemoryBudget (shared across layers in batch conversion), so
        # convert memory stays independent of layer size and count. Blob
        # bytes are identical to the serial path (pinned by
        # tests/test_fast_tar.py and tests/test_pipeline_determinism.py).
        comp_cache: dict[bytes, tuple[bytes, int]] = {}  # serial-path default
        file_idxs = [i for i, (tag, *_rest) in enumerate(plan) if tag == "file"]
        # Host arms only: fused/native/numpy chunking is safe to call from
        # worker threads (GIL-dropping where it matters); the jax lanes
        # keep their own double-buffered device dispatch discipline.
        pipe = None
        if (
            n_threads > 1
            and len(file_idxs) > 1
            and opt.backend in ("hybrid", "numpy")
            and opt.digest_backend != "jax"
        ):
            from nydus_snapshotter_tpu.parallel import pipeline as pipeline_mod

            pcfg = pipeline_mod.resolve_config(n_threads)
            if pcfg.enabled:
                digest_fn = None
                if not shared_chunker.fused:
                    # Non-fused engines cut without digesting; digest in
                    # the worker (same bytes → same digests as the batched
                    # host dispatch) so dedup and speculative compression
                    # can run ahead of the ordered walk.
                    from nydus_snapshotter_tpu.ops.chunker import (
                        host_digests_for as _hdf,
                    )

                    digest_fn = _hdf(opt.digester)

                def _chunk_one(i: int):
                    _tag, _meta, off, size = plan[i]
                    chunks = shared_chunker.chunk_whole(raw[off : off + size])
                    if digest_fn is not None and chunks:
                        items = []
                        s = off
                        for view, _d in chunks:
                            items.append((arr_all, s, len(view)))
                            s += len(view)
                        digs = digest_fn(items)
                        chunks = [(v, d) for (v, _), d in zip(chunks, digs)]
                    return chunks

                compress_fn = None
                compress_eligible = None
                if opt.compressor in ("lz4_block", "zstd") and not isinstance(
                    section, _DeferredSectionWriter
                ):
                    # (Deferred sections compress inside the native pass
                    # with their own thread fan-out — speculating here
                    # would do the work twice.) Per-thread codec contexts:
                    # lz4 calls are stateless, zstd contexts are not
                    # thread-safe; both codecs are deterministic.
                    from nydus_snapshotter_tpu.converter.convert import (
                        ThreadSafeCompressor,
                    )

                    # ThreadSafeCompressor also carries the encode_many
                    # batch seam: pipeline compress workers drain up to
                    # [compression] batch_chunks queued chunks into one
                    # GIL-released native batch-encode call (byte-identical
                    # frames either way).
                    compress_fn = ThreadSafeCompressor(
                        opt.compressor, opt.lz4_acceleration, codec=codec
                    )
                    batch_limit = opt.batch_size

                    def compress_eligible(digest, view):
                        if batch_limit and len(view) < batch_limit:
                            return False  # batch-packed: compressed jointly
                        if chunk_dict is not None and chunk_dict.get(digest):
                            return False  # dict hit: never stored
                        return True

                pipe = pipeline_mod.ConvertPipeline(
                    items=[(i, plan[i][3]) for i in file_idxs],
                    chunk_fn=_chunk_one,
                    compress_fn=compress_fn,
                    compress_eligible=compress_eligible,
                    config=pcfg,
                    budget=budget,
                    stats=stats,
                )
                # Serial-path equivalence: any walk-time chunks (sparse
                # members) sit in the pending digest batches and would be
                # section.add'ed before the plan's chunks — drain them now
                # so the pipelined immediate _process keeps that order.
                _drain_all()

        from contextlib import nullcontext

        with pipe if pipe is not None else nullcontext():
            for i, (tag, meta, off, size) in enumerate(plan):
                view = raw[off : off + size]
                if tag == "small":  # ≤ min_size ⇒ exactly one chunk
                    _process([(meta, view)], [next(small_digests)])
                    continue
                _tc = _pc()
                chunks = (
                    pipe.chunks_for(i)
                    if pipe is not None
                    else shared_chunker.chunk_whole(view)
                )
                _t_chunk += _pc() - _tc
                if chunks and chunks[0][1] is not None:
                    _process(
                        [(meta, c) for c, _ in chunks],
                        [d for _, d in chunks],
                        comp_cache=pipe.comp
                        if pipe is not None and pipe.compress_fn is not None
                        else comp_cache,
                    )
                else:
                    for chunk, digest in chunks:
                        _add_chunk(meta, chunk, digest)
    _t2 = _pc()
    _drain_all()
    section.finish()
    _t3 = _pc()

    blob_size = section.coff
    blob_id = section.hasher.hexdigest() if blob_size else ""
    if blob_size:
        out.write(nydus_tar.make_header(toc.ENTRY_BLOB_DATA, blob_size))

    # Synthesize root + missing parents (metadata only).
    for p in fstree.missing_parents(metas):
        metas[p] = _Meta(entry=fstree.FileEntry(path=p, mode=stat.S_IFDIR | 0o755))
    for d in opaque_dirs:
        if d not in metas:
            metas[d] = _Meta(entry=fstree.FileEntry(path=d, mode=stat.S_IFDIR | 0o755))
        metas[d].entry.flags |= fstree.INODE_FLAG_OPAQUE
        metas[d].entry.xattrs[fstree.OPAQUE_XATTR] = b"y"

    # Blob + cipher + batch tables (own blob first, then dict blobs).
    blob_table: list[BlobRecord] = []
    cipher_table: list[CipherRecord] = []
    batch_table: list[BatchRecord] = []
    blob_index_of: dict[str, int] = {}
    if blob_size:
        blob_index_of[blob_id] = 0
        blob_table.append(
            BlobRecord(
                blob_id=blob_id,
                compressed_size=blob_size,
                uncompressed_size=uoff,
                chunk_count=len(uncomp_offsets),
            )
        )
        cipher_table.append(section.cipher or CipherRecord())
        for coff_b, base_u, usize in section.batches:
            batch_table.append(BatchRecord(0, coff_b, base_u, usize))
    for bid in dict_blobs_used:
        new_idx = len(blob_table)
        blob_index_of[bid] = new_idx
        dict_idx, dict_rec = next(
            (i, b) for i, b in enumerate(chunk_dict.bootstrap.blobs) if b.blob_id == bid
        )
        blob_table.append(
            BlobRecord(
                blob_id=bid,
                compressed_size=dict_rec.compressed_size,
                uncompressed_size=dict_rec.uncompressed_size,
                chunk_count=dict_rec.chunk_count,
                flags=dict_rec.flags,
            )
        )
        cipher_table.append(chunk_dict.bootstrap.cipher_for(dict_idx) or CipherRecord())
        for b in chunk_dict.bootstrap.batches:
            if b.blob_index == dict_idx:
                batch_table.append(
                    BatchRecord(new_idx, b.compressed_offset, b.uncompressed_base, b.uncompressed_size)
                )

    # Inodes + chunk table in path-sorted order (bootstrap serialization
    # order), records resolved against the final extent table.
    inodes: list[Inode] = []
    chunk_records: list[ChunkRecord] = []
    for path in sorted(metas):
        meta = metas[path]
        inode = fstree.entry_to_inode(meta.entry)
        inode.size = meta.size
        if meta.chunks:
            inode.chunk_index = len(chunk_records)
            inode.chunk_count = len(meta.chunks)
            for ref in meta.chunks:
                if ref.dict_hit is not None:
                    hit = ref.dict_hit
                    chunk_records.append(
                        ChunkRecord(
                            digest=ref.digest,
                            blob_index=blob_index_of[chunk_dict.blob_id_for(hit)],
                            flags=hit.flags,
                            uncompressed_offset=hit.uncompressed_offset,
                            compressed_offset=hit.compressed_offset,
                            uncompressed_size=hit.uncompressed_size,
                            compressed_size=hit.compressed_size,
                        )
                    )
                else:
                    coff_c, csize, cflag = section.extents[ref.uniq_idx]
                    chunk_records.append(
                        ChunkRecord(
                            digest=ref.digest,
                            blob_index=blob_index_of[blob_id],
                            flags=cflag,
                            uncompressed_offset=uncomp_offsets[ref.uniq_idx],
                            compressed_offset=coff_c,
                            uncompressed_size=ref.size,
                            compressed_size=csize,
                        )
                    )
        inodes.append(inode)

    from nydus_snapshotter_tpu.converter.convert import match_prefetch_paths

    bootstrap = Bootstrap(
        version=opt.fs_version,
        chunk_size=opt.chunk_size,
        inodes=inodes,
        chunks=chunk_records,
        blobs=blob_table,
        ciphers=cipher_table if any(c.algo for c in cipher_table) else [],
        batches=batch_table,
        prefetch=match_prefetch_paths(inodes, opt.prefetch_patterns)
        if opt.prefetch_patterns
        else [],
    )
    boot_bytes = bootstrap.to_bytes()

    toc_entries = []
    if blob_size:
        toc_entries.append(
            toc.TOCEntry(
                name=toc.ENTRY_BLOB_DATA,
                flags=constants.COMPRESSOR_NONE,
                uncompressed_digest=section.hasher.digest(),
                compressed_offset=0,
                compressed_size=blob_size,
                uncompressed_size=blob_size,
            )
        )
    boot_off = out.tell()
    out.write(boot_bytes)
    out.write(nydus_tar.make_header(toc.ENTRY_BOOTSTRAP, len(boot_bytes)))
    toc_entries.append(
        toc.TOCEntry(
            name=toc.ENTRY_BOOTSTRAP,
            flags=constants.COMPRESSOR_NONE,
            uncompressed_digest=hashlib.sha256(boot_bytes).digest(),
            compressed_offset=boot_off,
            compressed_size=len(boot_bytes),
            uncompressed_size=len(boot_bytes),
        )
    )
    toc_bytes = toc.pack_toc(toc_entries)
    out.write(toc_bytes)
    out.write(nydus_tar.make_header(toc.ENTRY_BLOB_TOC, len(toc_bytes)))

    if stats is not None:
        stats["scan"] = stats.get("scan", 0.0) + (_t1 - _t0)
        stats["chunk_digest"] = stats.get("chunk_digest", 0.0) + _t_chunk
        # fused_pack spans chunk+dedup+assemble inside one native call
        stats["fused_pack"] = stats.get("fused_pack", 0.0) + _t_fused
        stats["dedup"] = stats.get("dedup", 0.0) + (
            _t2 - _t1 - _t_chunk - _t_spec - _t_fused
        )
        stats["assemble"] = stats.get("assemble", 0.0) + (_t3 - _t2) + _t_spec
        stats["bootstrap"] = stats.get("bootstrap", 0.0) + (_pc() - _t3)

    from nydus_snapshotter_tpu.converter.convert import PackResult

    return PackResult(
        blob_id=blob_id,
        blob_size=blob_size,
        bootstrap=boot_bytes,
        referenced_blob_ids=[b.blob_id for b in blob_table],
    )
