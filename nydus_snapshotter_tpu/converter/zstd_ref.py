"""OCIRef conversion for zstd layers: index the original blob, store nothing.

The zstd sibling of :mod:`~nydus_snapshotter_tpu.converter.zran`: the
registry keeps serving the ORIGINAL compressed layer — no duplicate
nydus blob — while the bootstrap indexes the decompressed tar so the
runtime reads files lazily. Chunk records carry
``CHUNK_FLAG_ZSTD_STREAM``: offsets address the DECOMPRESSED stream of
a whole-zstd blob, and ``BlobReader`` translates them through a mounted
:class:`~nydus_snapshotter_tpu.soci.zblob.ZstdStreamReader` (frame
index) or the in-process :class:`ZstdSequentialReader` below.

The sequential fallback differs from gzip's in one documented way: a
``ZSTD_DCtx`` cannot be checkpoint-copied the way ``decompressobj``
can, so the fallback keeps a single forward cursor — forward scans are
incremental, a backward seek re-decodes from stream start. The frame
index (``.soci.zidx``) is the real random-access path; the fallback
only serves index-less degradation, where correctness, not cost, is
the contract.
"""

from __future__ import annotations

from typing import Callable, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter.types import ConvertError, PackOption
from nydus_snapshotter_tpu.converter.zran import pack_stream_layer
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap
from nydus_snapshotter_tpu.utils import zstd as _zstd

# Chunk flag: offsets address the decompressed stream of a whole-zstd blob.
CHUNK_FLAG_ZSTD_STREAM = 0x800


class ZstdSequentialReader:
    """Index-less random access into a zstd stream: one forward decode
    cursor over caller-supplied compressed bytes.

    ``read_at(offset, size)`` returns COMPRESSED blob bytes;
    ``read_range`` returns DECOMPRESSED bytes. Forward reads resume the
    held :class:`~nydus_snapshotter_tpu.utils.zstd.StreamDecoder`;
    reading behind the cursor resets it to stream start (zstd decode
    state is not copyable — see module docstring).
    """

    _READ_STEP = 1 << 20

    def __init__(self, read_at: Callable[[int, int], bytes], compressed_size: int):
        self._read_at = read_at
        self._csize = compressed_size
        self._dec: Optional[_zstd.StreamDecoder] = None
        self._upos = 0  # decompressed bytes emitted so far
        self._cpos = 0  # compressed bytes consumed so far

    def _rewind(self) -> None:
        if self._dec is None:
            self._dec = _zstd.StreamDecoder()
        else:
            self._dec.reset()
        self._upos = self._cpos = 0

    def read_range(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        if self._dec is None or offset < self._upos:
            try:
                self._rewind()
            except _zstd.ZstdError as e:
                raise ConvertError(str(e)) from e
        out = bytearray()
        end = offset + size
        while self._upos < end:
            if self._cpos >= self._csize:
                break
            feed = self._read_at(
                self._cpos, min(self._READ_STEP, self._csize - self._cpos)
            )
            if not feed:
                break
            self._cpos += len(feed)
            try:
                chunk = self._dec.feed(feed)
            except _zstd.ZstdError as e:
                self._dec = None
                raise ConvertError(f"corrupt zstd stream: {e}") from e
            if not chunk:
                continue
            lo = max(0, offset - self._upos)
            hi = min(len(chunk), end - self._upos)
            if hi > lo:
                out += chunk[lo:hi]
            self._upos += len(chunk)
        if len(out) != size:
            raise ConvertError(
                f"zstd stream range [{offset}, +{size}) beyond decompressed end"
            )
        return bytes(out)

    def close(self) -> None:
        if self._dec is not None:
            self._dec.close()
            self._dec = None


def pack_zstd_layer(
    raw_zstd: bytes, opt: PackOption, engine=None, tar_bytes: Optional[bytes] = None
) -> Bootstrap:
    """Index an original ``.tar.zst`` layer without re-storing its data.

    Returns the layer Bootstrap whose single blob IS the original
    compressed layer (blob id = its sha256). ``tar_bytes`` lets a caller
    that already decoded the stream (the zstd index build is itself one
    full decode pass) hand the output over instead of decoding twice.
    """
    if tar_bytes is None:
        try:
            tar_bytes = _zstd.stream_decompress(raw_zstd)
        except _zstd.ZstdError as e:
            raise ConvertError(f"OCIRef layer is not valid zstd: {e}") from e
    return pack_stream_layer(
        raw_zstd, tar_bytes, opt,
        chunk_flag=CHUNK_FLAG_ZSTD_STREAM,
        blob_compressor=constants.COMPRESSOR_ZSTD,
        engine=engine,
    )
