"""Conversion surface: Pack/Merge/Unpack with the reference's option model.

Public API parity with reference pkg/converter (convert_unix.go:325,560,669;
types.go:58-145), backed by the TPU chunk/digest engine instead of the
external ``nydus-image`` binary.
"""

from nydus_snapshotter_tpu.converter.types import (  # noqa: F401
    MergeOption,
    PackOption,
    UnpackOption,
)
from nydus_snapshotter_tpu.converter.convert import (  # noqa: F401
    Merge,
    Pack,
    Unpack,
    pack_layer,
)
