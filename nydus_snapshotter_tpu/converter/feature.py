"""Engine feature detection (reference pkg/converter/tool/feature.go).

The reference probes the external ``nydus-image`` binary once by parsing
``create -h`` output (feature.go:116-146) and gates tar-rafs / batch-size /
encrypt paths on the result. Here the "builder" is the in-process engine,
so detection inspects the installed engine + environment instead — but the
same Feature surface and one-shot caching semantics are kept so converter
call-sites stay shaped like the reference.
"""

from __future__ import annotations

import threading
from enum import Enum
from typing import Optional


class Feature(str, Enum):
    TAR_RAFS = "--type tar-rafs"  # feature.go:25-38
    BATCH_SIZE = "--batch-size"
    ENCRYPT = "--encrypt"
    CDC_CHUNKING = "--chunking cdc"  # accel-only: content-defined chunking
    DEVICE_DIGEST = "--digest-device"  # batched SHA-256 on device


class Features:
    def __init__(self, features: set[Feature]):
        self._features = features

    def contains(self, feature: Feature) -> bool:
        return feature in self._features

    def __iter__(self):
        return iter(self._features)


_lock = threading.Lock()
_detected: Optional[Features] = None


def detect_features(force: bool = False) -> Features:
    """One-shot probe, cached like tool.DetectFeatures (feature.go:116)."""
    global _detected
    with _lock:
        if _detected is not None and not force:
            return _detected
        feats = {Feature.TAR_RAFS, Feature.CDC_CHUNKING}
        try:
            import jax

            jax.devices()
            feats.add(Feature.DEVICE_DIGEST)
        except Exception:  # no usable device backend: host digests only
            pass
        try:
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM  # noqa: F401

            feats.add(Feature.ENCRYPT)
        except ImportError:
            pass
        # batch (chunk-merging) packing is not implemented yet — mirrors a
        # builder without --batch-size support
        _detected = Features(feats)
        return _detected
