"""Local content-addressed blob store (containerd content.Store shape).

The reference converter and encryption paths run against containerd's
content store; this is the framework-native equivalent used by the
conversion surface, the encryption helpers, and tests: a directory of blobs
keyed ``sha256:<hex>`` with JSON label sidecars (labels back the GC refs +
the conversion-cache label, convert_unix.go:842-844).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Iterator, Optional

from nydus_snapshotter_tpu.utils import errdefs


@dataclass
class BlobInfo:
    digest: str
    size: int
    labels: dict[str, str] = field(default_factory=dict)


class LocalContentStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "blobs"), exist_ok=True)

    def _blob_path(self, digest: str) -> str:
        algo, _, hexd = digest.partition(":")
        if not hexd or algo != "sha256":
            raise errdefs.InvalidArgument(f"unsupported digest {digest!r}")
        return os.path.join(self.root, "blobs", hexd)

    def _label_path(self, digest: str) -> str:
        return self._blob_path(digest) + ".labels.json"

    # -- readers --------------------------------------------------------------

    def reader_at(self, digest: str):
        path = self._blob_path(digest)
        if not os.path.exists(path):
            raise errdefs.NotFound(f"content {digest} not found")
        return open(path, "rb")

    def read(self, digest: str) -> bytes:
        with self.reader_at(digest) as f:
            return f.read()

    def info(self, digest: str) -> BlobInfo:
        path = self._blob_path(digest)
        if not os.path.exists(path):
            raise errdefs.NotFound(f"content {digest} not found")
        labels: dict[str, str] = {}
        if os.path.exists(self._label_path(digest)):
            with open(self._label_path(digest)) as f:
                labels = json.load(f)
        return BlobInfo(digest=digest, size=os.path.getsize(path), labels=labels)

    def exists(self, digest: str) -> bool:
        return os.path.exists(self._blob_path(digest))

    def walk(self) -> Iterator[BlobInfo]:
        blob_dir = os.path.join(self.root, "blobs")
        for name in sorted(os.listdir(blob_dir)):
            if name.endswith(".labels.json"):
                continue
            yield self.info("sha256:" + name)

    # -- writers --------------------------------------------------------------

    def write_blob(
        self, data: bytes, labels: Optional[dict[str, str]] = None,
        expected_digest: str = "",
    ) -> BlobInfo:
        digest = "sha256:" + hashlib.sha256(data).hexdigest()
        if expected_digest and digest != expected_digest:
            raise errdefs.InvalidArgument(
                f"content digest mismatch: got {digest}, want {expected_digest}"
            )
        path = self._blob_path(digest)
        if not os.path.exists(path):
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                os.rename(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        if labels:
            self.update_labels(digest, labels)
        return self.info(digest)

    def update_labels(self, digest: str, labels: dict[str, str]) -> None:
        info = self.info(digest)
        merged = {**info.labels, **labels}
        # a label set to None deletes (containerd update semantics)
        merged = {k: v for k, v in merged.items() if v is not None}
        with open(self._label_path(digest), "w") as f:
            json.dump(merged, f)

    def delete(self, digest: str) -> None:
        for path in (self._blob_path(digest), self._label_path(digest)):
            if os.path.exists(path):
                os.unlink(path)
