"""OCIRef ("zran") conversion: index the original tar.gz, store nothing.

Reference semantics (``PackOption.OCIRef`` → ``create --type targz-ref``,
tool/builder.go:180-218; smoke TestPackRef): the registry keeps serving the
ORIGINAL compressed OCI layer — no duplicate nydus blob — while the
bootstrap indexes the decompressed content so the runtime can lazily read
files out of the gzip stream.

The reference's Rust builder emits a true zran index (gzip inflate
checkpoints with bit offsets via inflatePrime). CPython's zlib exposes no
inflatePrime, so random access here rides ``decompressobj.copy()``
checkpoints built *at read time*: the first touch of offset O costs a
sequential inflate up to O, every later read near any previously visited
region is O(distance-to-checkpoint). Conversion itself decompresses the
stream exactly once (as the reference does) and digests chunks through the
batched engine. The access-cost difference vs the Rust zran is documented
behavior, not an accident.

Chunk records carry CHUNK_FLAG_GZIP_STREAM: ``uncompressed_offset`` is the
position in the DECOMPRESSED stream and the owning blob is the original
``.tar.gz`` bytes.
"""

from __future__ import annotations

import gzip
import hashlib
import io
import zlib
from typing import BinaryIO, Callable, Optional

import numpy as np

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter.types import ConvertError, PackOption
from nydus_snapshotter_tpu.models import fstree
from nydus_snapshotter_tpu.models.bootstrap import (
    BlobRecord,
    Bootstrap,
    ChunkRecord,
)

# Chunk flag: offsets address the decompressed stream of a whole-gzip blob.
CHUNK_FLAG_GZIP_STREAM = 0x400

_CHECKPOINT_STEP = 8 << 20  # keep an inflate state copy every 8 MiB


class GzipStreamReader:
    """Random access into a gzip stream via decompressobj checkpoints.

    ``read_at(offset, size)`` returns COMPRESSED bytes of the blob;
    ``read_range`` returns DECOMPRESSED bytes. Checkpoints accumulate as
    regions are touched, so re-reads and forward scans are cheap; state
    lives in-process (CPython inflate state is not serializable).
    """

    _READ_STEP = 1 << 20

    def __init__(self, read_at: Callable[[int, int], bytes], compressed_size: int):
        self._read_at = read_at
        self._csize = compressed_size
        # (uncompressed_pos, compressed_pos, decompressobj, pending_tail)
        self._checkpoints: list[tuple[int, int, "zlib._Decompress", bytes]] = []

    def _best_checkpoint(self, upos: int):
        best = None
        for cp in self._checkpoints:
            if cp[0] <= upos and (best is None or cp[0] > best[0]):
                best = cp
        if best is None:
            return 0, 0, zlib.decompressobj(wbits=47), b""
        u, c, d, tail = best
        return u, c, d.copy(), tail

    def read_range(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        upos, cpos, d, pending = self._best_checkpoint(offset)
        out = bytearray()
        last_checkpoint = upos - (upos % _CHECKPOINT_STEP)
        while upos < offset + size:
            if d.eof:
                # Multi-member gzip (pigz, eStargz, concatenated members):
                # gzip.decompress() joins members, so the bootstrap spans
                # them all — restart inflate at each member boundary.
                pending = d.unused_data + pending
                if not pending and cpos >= self._csize:
                    break
                d = zlib.decompressobj(wbits=47)
            if pending:
                feed, pending = pending, b""
            elif cpos < self._csize:
                feed = self._read_at(cpos, min(self._READ_STEP, self._csize - cpos))
                if not feed:
                    break
                cpos += len(feed)
            else:
                chunk = d.flush()
                if not chunk:
                    break
                feed = b""
            if feed:
                try:
                    chunk = d.decompress(feed)
                except zlib.error as e:
                    raise ConvertError(f"corrupt gzip stream: {e}") from e
            if not chunk:
                continue
            lo = max(0, offset - upos)
            hi = min(len(chunk), offset + size - upos)
            if hi > lo:
                out += chunk[lo:hi]
            upos += len(chunk)
            # Drop a resumable state copy at step boundaries we cross.
            if upos - last_checkpoint >= _CHECKPOINT_STEP:
                last_checkpoint = upos - (upos % _CHECKPOINT_STEP)
                self._checkpoints.append((upos, cpos, d.copy(), b""))
                if len(self._checkpoints) > 64:
                    self._checkpoints.pop(0)
        if len(out) != size:
            raise ConvertError(
                f"gzip stream range [{offset}, +{size}) beyond decompressed end"
            )
        return bytes(out)


def pack_gzip_layer(
    raw_gzip: bytes, opt: PackOption, engine=None, tar_bytes: Optional[bytes] = None
) -> Bootstrap:
    """Index an original ``.tar.gz`` layer without re-storing its data.

    Returns the layer Bootstrap, whose single blob IS the original
    compressed layer (blob id = its sha256). The decompressed stream is
    chunked per-file (the reference's targz-ref chunks the uncompressed
    content) and digested through ``engine`` when supplied
    (batched/device) or hashlib otherwise. ``tar_bytes`` lets a caller
    that already inflated the stream (the soci index build is itself one
    full inflate pass) hand the output over instead of paying a second
    decompression of a multi-hundred-MiB layer.
    """
    if tar_bytes is None:
        try:
            tar_bytes = gzip.decompress(raw_gzip)
        except (OSError, EOFError, zlib.error) as e:
            raise ConvertError(f"OCIRef layer is not valid gzip: {e}") from e
    return pack_stream_layer(
        raw_gzip, tar_bytes, opt,
        chunk_flag=CHUNK_FLAG_GZIP_STREAM,
        blob_compressor=constants.COMPRESSOR_GZIP,
        engine=engine,
    )


def pack_stream_layer(
    raw: bytes,
    tar_bytes: bytes,
    opt: PackOption,
    chunk_flag: int,
    blob_compressor: int,
    engine=None,
) -> Bootstrap:
    """The format-agnostic half of OCIRef packing: chunk the DECOMPRESSED
    tar stream per file, digest, and emit a bootstrap whose single blob
    is the original compressed layer. ``chunk_flag`` marks how runtime
    reads translate decompressed offsets back to blob bytes
    (CHUNK_FLAG_GZIP_STREAM for gzip zran, CHUNK_FLAG_ZSTD_STREAM for
    the zstd frame index — converter/zstd_ref.py)."""
    opt.validate()
    if opt.encrypt:
        # The original registry blob stays authoritative and plaintext;
        # claiming encryption would mislabel it (hooks annotates encrypted
        # blobs) and consumers would decrypt plaintext into garbage.
        raise ConvertError("oci_ref cannot be combined with encrypt")

    entries: dict[str, fstree.FileEntry] = {}
    # (path, decompressed data offset, size) per regular file, chunked.
    chunk_meta: list[tuple[str, int, int]] = []
    # path -> (start, count) into chunk_meta for the LAST occurrence (tar
    # semantics: a repeated path replaces the earlier entry entirely).
    spans: dict[str, tuple[int, int]] = {}
    import tarfile as tarfile_mod

    opaque_dirs: list[str] = []
    tf = tarfile_mod.open(fileobj=io.BytesIO(tar_bytes), mode="r:")
    for info in tf:
        path = fstree._norm(info.name)
        base = path.rsplit("/", 1)[1] if path != "/" else "/"
        # Overlay markers get the same RAFS normalization as every other
        # pack path (fstree.tree_from_tar / tarfs/bootstrap.py) — literal
        # .wh. files would resurrect deleted content after Merge.
        if base == fstree.OPAQUE_MARKER:
            opaque_dirs.append(path.rsplit("/", 1)[0] or "/")
            continue
        if base.startswith(fstree.WHITEOUT_PREFIX):
            target = fstree._norm(
                path.rsplit("/", 1)[0] + "/" + base[len(fstree.WHITEOUT_PREFIX):]
            )
            entries[target] = fstree.FileEntry(
                path=target, mode=0o020000, flags=fstree.INODE_FLAG_WHITEOUT
            )
            spans.pop(target, None)
            continue
        if getattr(info, "sparse", None):
            # GNU sparse members store only the compacted data region; the
            # in-place chunk extents would read neighbouring tar bytes.
            raise ConvertError(
                f"sparse tar member {info.name!r} cannot be indexed in place"
            )
        entry = fstree.entry_from_tarinfo(tf, info, path, with_data=False)
        entries[path] = entry
        spans.pop(path, None)
        if info.isreg() and info.size > 0:
            start = len(chunk_meta)
            off = info.offset_data
            remaining = info.size
            while remaining > 0:
                step = min(opt.chunk_size, remaining)
                chunk_meta.append((path, off, step))
                off += step
                remaining -= step
            spans[path] = (start, len(chunk_meta) - start)

    for d in opaque_dirs:
        if d not in entries:
            entries[d] = fstree.FileEntry(path=d, mode=0o040755)
        entries[d].flags |= fstree.INODE_FLAG_OPAQUE
        entries[d].xattrs[fstree.OPAQUE_XATTR] = b"y"

    ordered = fstree.ensure_parents(sorted(entries.values(), key=lambda e: e.path))

    view = memoryview(tar_bytes)  # no second copy of multi-GB content
    datas = [view[o : o + s] for _, o, s in chunk_meta]
    if engine is not None:
        # the engine carries its own digester (digest_many branches on it)
        digests = engine.digest_many(datas)
    else:
        from nydus_snapshotter_tpu.ops.chunker import host_digests_for

        buf = np.frombuffer(tar_bytes, dtype=np.uint8)
        digests = host_digests_for(opt.digester)(
            [(buf, o, s) for _p, o, s in chunk_meta]
        )

    blob_id = hashlib.sha256(raw).hexdigest()

    inodes = []
    chunks: list[ChunkRecord] = []
    for e in ordered:
        inode = fstree.entry_to_inode(e)
        span = spans.get(e.path)
        if span is not None:
            start, count = span
            inode.chunk_index = len(chunks)
            inode.chunk_count = count
            inode.size = sum(s for _, _, s in chunk_meta[start : start + count])
            for (path, off, size), digest in zip(
                chunk_meta[start : start + count], digests[start : start + count]
            ):
                chunks.append(
                    ChunkRecord(
                        digest=digest,
                        blob_index=0,
                        flags=chunk_flag,
                        uncompressed_offset=off,
                        compressed_offset=off,
                        uncompressed_size=size,
                        compressed_size=size,
                    )
                )
        inodes.append(inode)

    blob = BlobRecord(
        blob_id=blob_id,
        compressed_size=len(raw),
        uncompressed_size=len(tar_bytes),
        chunk_count=len(chunks),
        flags=blob_compressor,
    )
    from nydus_snapshotter_tpu.converter.convert import match_prefetch_paths

    return Bootstrap(
        version=opt.fs_version,
        chunk_size=opt.chunk_size,
        inodes=inodes,
        chunks=chunks,
        blobs=[blob],
        prefetch=match_prefetch_paths(inodes, opt.prefetch_patterns)
        if opt.prefetch_patterns
        else [],
    )
