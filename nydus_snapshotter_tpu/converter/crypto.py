"""Blob data encryption: seekable AES-256-CTR over the blob address space.

The reference's ``--encrypt`` makes the builder encrypt blob data, with the
cipher context stored in the image metadata (the bootstrap), while key
protection comes from separately encrypting the bootstrap *layer* with
ocicrypt (pkg/encryption/encryption.go:143-253 — implemented here in
encryption/encryption.py). This module is the blob half: chunks are laid out
first, then the whole data section is transformed with AES-256-CTR keyed per
blob. CTR is length-preserving (chunk extents are unchanged) and seekable
(counter = byte_offset // 16), so the lazy-read daemon can decrypt one chunk
without touching the rest of the blob.
"""

from __future__ import annotations

import os

try:  # optional: only the --encrypt feature needs a cipher backend
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    _HAVE_CRYPTOGRAPHY = True
except ModuleNotFoundError:  # gate, don't break converter imports
    _HAVE_CRYPTOGRAPHY = False

CIPHER_NONE = 0
CIPHER_AES_256_CTR = 1

KEY_LEN = 32
IV_LEN = 16


class CryptoError(ValueError):
    pass


def generate_context() -> tuple[bytes, bytes]:
    """Fresh (key, iv) for one blob."""
    return os.urandom(KEY_LEN), os.urandom(IV_LEN)


def _ctr_at(key: bytes, iv: bytes, block_index: int):
    """CTR cipher positioned at 16-byte block ``block_index`` of the stream."""
    if not _HAVE_CRYPTOGRAPHY:
        raise CryptoError("blob encryption needs the 'cryptography' package")
    if len(key) != KEY_LEN or len(iv) != IV_LEN:
        raise CryptoError("AES-256-CTR needs a 32-byte key and 16-byte IV")
    counter = (int.from_bytes(iv, "big") + block_index) % (1 << 128)
    return Cipher(algorithms.AES(key), modes.CTR(counter.to_bytes(16, "big")))


def encrypt(data: bytes, key: bytes, iv: bytes) -> bytes:
    """Encrypt a whole blob data section (offset 0)."""
    enc = _ctr_at(key, iv, 0).encryptor()
    return enc.update(data) + enc.finalize()


def stream_encryptor(key: bytes, iv: bytes):
    """Incremental encryptor positioned at offset 0 — feed section bytes in
    order via .update(); byte-identical to ``encrypt`` over the whole
    section, and the single definition the seekable ``decrypt_range``
    counter layout is guaranteed against."""
    return _ctr_at(key, iv, 0).encryptor()


def decrypt_range(data: bytes, offset: int, key: bytes, iv: bytes) -> bytes:
    """Decrypt ``data`` that was taken from absolute blob ``offset``.

    Seeks the keystream to the enclosing 16-byte block and drops the
    intra-block prefix — the random-access read path.
    """
    dec = _ctr_at(key, iv, offset // 16).decryptor()
    skip = offset % 16
    out = dec.update(bytes(skip) + data) + dec.finalize()
    return out[skip:]
