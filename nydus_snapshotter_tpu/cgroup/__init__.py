"""Daemon cgroup management (reference pkg/cgroup)."""

from nydus_snapshotter_tpu.cgroup.cgroup import (
    Config,
    Manager,
    Mode,
    CgroupNotSupported,
    detect_mode,
)

__all__ = ["CgroupNotSupported", "Config", "Manager", "Mode", "detect_mode"]
