"""Dedicated "nydusd" cgroup with a memory limit, v1 + v2.

Reference pkg/cgroup (manager.go:24-50, cgroup.go:36-60, v1/v1.go:24-82,
v2/v2.go:41-88): daemons are corralled into ``system.slice/<name>`` with an
optional memory cap so a runaway userspace daemon can't take down the node.

The filesystem root is injectable (default ``/sys/fs/cgroup``) so tests run
against a tmpdir; mode detection mirrors containerd/cgroups: unified when
``cgroup.controllers`` exists at the root, legacy when ``memory/`` does,
unavailable otherwise.
"""

from __future__ import annotations

import enum
import logging
import os
from dataclasses import dataclass

from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)

DEFAULT_SLICE = "system.slice"
DEFAULT_ROOT = "/sys/fs/cgroup"


class CgroupNotSupported(errdefs.Unavailable):
    pass


class Mode(enum.Enum):
    UNAVAILABLE = "unavailable"
    LEGACY = "legacy"  # v1
    HYBRID = "hybrid"
    UNIFIED = "unified"  # v2


@dataclass
class Config:
    memory_limit_in_bytes: int = -1  # -1 = unlimited


def detect_mode(root: str = DEFAULT_ROOT) -> Mode:
    if not os.path.isdir(root):
        return Mode.UNAVAILABLE
    unified = os.path.exists(os.path.join(root, "cgroup.controllers"))
    legacy = os.path.isdir(os.path.join(root, "memory"))
    if unified and legacy:
        return Mode.HYBRID
    if unified:
        return Mode.UNIFIED
    if legacy:
        return Mode.LEGACY
    return Mode.UNAVAILABLE


class _CgroupV1:
    """v1: <root>/memory/<slice>/<name> (v1/v1.go:24-82)."""

    def __init__(self, root: str, slice_name: str, name: str, memory_limit: int):
        self.path = os.path.join(root, "memory", slice_name, name)
        os.makedirs(self.path, exist_ok=True)
        if memory_limit > 0:
            with open(os.path.join(self.path, "memory.limit_in_bytes"), "w") as f:
                f.write(str(memory_limit))

    def add_proc(self, pid: int) -> None:
        with open(os.path.join(self.path, "cgroup.procs"), "a") as f:
            f.write(f"{pid}\n")

    def delete(self) -> None:
        # a v1 cgroup dir with live procs can't be removed; mirror the
        # reference's best-effort delete (v1.go:64-82)
        try:
            os.rmdir(self.path)
        except OSError as e:
            logger.warning("delete cgroup %s: %s", self.path, e)


class _CgroupV2:
    """v2 unified: <root>/<slice>/<name> with memory.max (v2/v2.go:41-88)."""

    def __init__(self, root: str, slice_name: str, name: str, memory_limit: int):
        self.path = os.path.join(root, slice_name, name)
        os.makedirs(self.path, exist_ok=True)
        if memory_limit > 0:
            with open(os.path.join(self.path, "memory.max"), "w") as f:
                f.write(str(memory_limit))

    def add_proc(self, pid: int) -> None:
        with open(os.path.join(self.path, "cgroup.procs"), "a") as f:
            f.write(f"{pid}\n")

    def delete(self) -> None:
        try:
            os.rmdir(self.path)
        except OSError as e:
            logger.warning("delete cgroup %s: %s", self.path, e)


class Manager:
    def __init__(
        self,
        name: str,
        config: Config | None = None,
        root: str = DEFAULT_ROOT,
        slice_name: str = DEFAULT_SLICE,
    ):
        config = config or Config()
        mode = detect_mode(root)
        if mode is Mode.UNAVAILABLE:
            raise CgroupNotSupported("cgroups: cgroup not supported")
        logger.info("cgroup mode: %s", mode.value)
        self.name = name
        self.config = config
        if mode in (Mode.UNIFIED,):
            self.cgroup = _CgroupV2(root, slice_name, name, config.memory_limit_in_bytes)
        else:
            self.cgroup = _CgroupV1(root, slice_name, name, config.memory_limit_in_bytes)

    def add_proc(self, pid: int) -> None:
        self.cgroup.add_proc(pid)

    def delete(self) -> None:
        self.cgroup.delete()
