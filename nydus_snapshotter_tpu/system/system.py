"""System controller: REST API over a unix-domain socket.

Reference pkg/system/system.go:36-446. Endpoints:

    GET  /api/v1/daemons               — daemon + instance inventory w/ RSS, read-data
    GET  /api/v1/daemons/records       — persisted daemon records from the store
    PUT  /api/v1/daemons/upgrade       — rolling live-upgrade {nydusd_path, version, policy}
    PUT  /api/v1/prefetch              — prefetch list from the NRI plugin
    GET  /api/v1/daemons/{id}/backend  — secret-filtered storage backend config
    */*  /api/v1/dict/...               — shared chunk-dict service routes
                                          (parallel/dict_service.py), when a
                                          DictService is attached
    */*  /api/v1/fleet/...              — fleet observability plane (member
                                          registry, federated metrics,
                                          merged traces, SLO status) when a
                                          fleet.FleetPlane is attached
"""

from __future__ import annotations

import json
import logging
import os
import re
import socketserver
import threading
from http.server import BaseHTTPRequestHandler
from typing import Iterable, Optional

from nydus_snapshotter_tpu.config.daemonconfig import DaemonRuntimeConfig
from nydus_snapshotter_tpu.metrics import tool as metrics_tool
from nydus_snapshotter_tpu.prefetch import Pm

logger = logging.getLogger(__name__)

_BACKEND_RE = re.compile(r"^/api/v1/daemons/([^/]+)/backend$")


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True

    def __init__(self, sock_path: str, handler):
        super().__init__(sock_path, handler)

    # BaseHTTPRequestHandler wants a (host, port) client address.
    def finish_request(self, request, client_address):
        self.RequestHandlerClass(request, ("uds", 0), self)


class SystemController:
    def __init__(
        self,
        fs=None,
        managers: Iterable = (),
        sock_path: str = "",
        dict_service=None,
        fleet=None,
    ):
        self.fs = fs
        self.managers = list(managers)
        self.sock_path = sock_path
        # Optional parallel/dict_service.DictService: its /api/v1/dict
        # routes are served on this controller's socket too, so one UDS
        # carries both the ops surface and the shared-dict RPCs.
        self.dict_service = dict_service
        # Optional fleet.FleetPlane: member registry + /api/v1/fleet
        # surface (federated metrics, merged traces, SLO status).
        self.fleet = fleet
        self._httpd: Optional[_UnixHTTPServer] = None

    # -- handlers -------------------------------------------------------------

    def describe_daemons(self) -> list[dict]:
        """system.go describeDaemons :233-281."""
        out = []
        for mgr in self.managers:
            for d in mgr.list_daemons():
                instances = {}
                for rafs in d.instances.list():
                    instances[rafs.snapshot_id] = {
                        "snapshot_id": rafs.snapshot_id,
                        "snapshot_dir": rafs.snapshot_dir,
                        "mountpoint": rafs.mountpoint,
                        "image_id": rafs.image_id,
                    }
                pid = d.pid()
                read_data = 0.0
                try:
                    m = d.client().fs_metrics("")
                    read_data = m.get("data_read", 0) / 1024.0
                except Exception:
                    pass
                out.append({
                    "id": d.id,
                    "pid": pid,
                    "api_socket": d.states.api_socket,
                    "supervisor_path": d.states.supervisor_path,
                    "reference": d.ref_count(),
                    "mountpoint": getattr(d, "host_mountpoint", lambda: "")(),
                    "startup_cpu_utilization": getattr(d, "startup_cpu_utilization", 0.0),
                    "memory_rss_kb": metrics_tool.get_process_memory_rss_kb(pid) if pid else 0.0,
                    "read_data_kb": read_data,
                    "instances": instances,
                })
        return out

    def daemon_records(self) -> list[dict]:
        """Persisted daemon rows (the reference stubs this with 501; we can
        serve it because sqlite, unlike bbolt, allows concurrent readers)."""
        out = []
        for mgr in self.managers:
            try:
                out.extend(rec for rec in mgr.db.walk_daemons())
            except Exception:
                continue
        return out

    def upgrade_daemons(self, req: dict) -> None:
        """Rolling live-upgrade (system.go:309-446): for each daemon, run
        the takeover dance via its manager; abort on first failure."""
        nydusd_path = req.get("nydusd_path", "")
        if nydusd_path and not os.path.exists(nydusd_path):
            raise FileNotFoundError(f"no such daemon binary {nydusd_path}")
        for mgr in self.managers:
            for d in mgr.list_daemons():
                if nydusd_path:
                    d.states.nydusd_path = nydusd_path  # type: ignore[attr-defined]
                mgr.do_daemon_upgrade(d)

    def get_backend(self, daemon_id: str) -> Optional[dict]:
        """Secret-filtered backend config for ``--backend-source``
        (system.go getBackend :179-231)."""
        for mgr in self.managers:
            d = mgr.get_by_daemon_id(daemon_id)
            if d is None:
                continue
            cfg_path = d.states.config_path
            if not cfg_path or not os.path.exists(cfg_path):
                return {"type": "", "config": {}}
            cfg = DaemonRuntimeConfig.from_template(cfg_path, d.states.fs_driver)
            exposed = cfg.exposed()
            backend = exposed.get("device", {}).get("backend", exposed.get("backend", {}))
            return {"type": backend.get("type", "registry"), "config": backend}
        return None

    # -- server ---------------------------------------------------------------

    def run(self) -> None:
        os.makedirs(os.path.dirname(self.sock_path) or ".", exist_ok=True)
        try:
            os.remove(self.sock_path)
        except FileNotFoundError:
            pass
        controller = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, payload, status: int = 200):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, message: str, status: int):
                self._json({"code": "Unknown", "message": message}, status)

            def _fleet_route(self, body: bytes) -> bool:
                if not self.path.startswith("/api/v1/fleet") or controller.fleet is None:
                    return False
                status, ctype, payload = controller.fleet.handle(
                    self.command, self.path, self.headers, body
                )
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return True

            def _dict_route(self, body: bytes) -> bool:
                if not self.path.startswith("/api/v1/dict") or controller.dict_service is None:
                    return False
                status, ctype, payload = controller.dict_service.handle(
                    self.command, self.path, self.headers, body
                )
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return True

            def do_GET(self):
                try:
                    if self._fleet_route(b"") or self._dict_route(b""):
                        return
                    if self.path == "/api/v1/daemons":
                        self._json(controller.describe_daemons())
                        return
                    if self.path == "/api/v1/daemons/records":
                        self._json(controller.daemon_records())
                        return
                    if self.path == "/api/v1/traces":
                        # The snapshotter process's span ring as a Chrome
                        # trace_event document (open in Perfetto).
                        from nydus_snapshotter_tpu import trace

                        self._json(trace.chrome_trace())
                        return
                    m = _BACKEND_RE.match(self.path)
                    if m:
                        backend = controller.get_backend(m.group(1))
                        if backend is None:
                            self._error("daemon not found", 404)
                        else:
                            self._json(backend)
                        return
                    self._error("no such endpoint", 404)
                except Exception as e:
                    logger.exception("system controller GET %s", self.path)
                    self._error(str(e), 500)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    if self._fleet_route(body) or self._dict_route(body):
                        return
                    self._error("no such endpoint", 404)
                except Exception as e:
                    logger.exception("system controller POST %s", self.path)
                    self._error(str(e), 500)

            def do_DELETE(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    if self._fleet_route(body):
                        return
                    self._error("no such endpoint", 404)
                except Exception as e:
                    logger.exception("system controller DELETE %s", self.path)
                    self._error(str(e), 500)

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    if self.path == "/api/v1/prefetch":
                        Pm.set_prefetch_files(body)
                        self._json({})
                        return
                    if self.path == "/api/v1/daemons/upgrade":
                        controller.upgrade_daemons(json.loads(body or b"{}"))
                        self._json({})
                        return
                    self._error("no such endpoint", 404)
                except FileNotFoundError as e:
                    self._error(str(e), 404)
                except ValueError as e:
                    self._error(str(e), 400)
                except Exception as e:
                    logger.exception("system controller PUT %s", self.path)
                    self._error(str(e), 500)

        self._httpd = _UnixHTTPServer(self.sock_path, Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        try:
            os.remove(self.sock_path)
        except OSError:
            pass
