from nydus_snapshotter_tpu.system.system import SystemController

__all__ = ["SystemController"]
