"""Pull a single file out of a (possibly compressed) tar stream.

Reference pkg/remote/unpack.go:20-56 — used to extract the nydus bootstrap
(``image/image.boot``) from a fetched metadata layer.
"""

from __future__ import annotations

import gzip
import io
import tarfile
import zlib

from nydus_snapshotter_tpu.utils import errdefs


def decompress_stream(data: bytes) -> bytes:
    """containerd compression.DecompressStream equivalent: sniff gzip/zstd,
    fall through to plain."""
    if data[:2] == b"\x1f\x8b":
        return gzip.decompress(data)
    if data[:4] == b"\x28\xb5\x2f\xfd":
        from nydus_snapshotter_tpu.utils import zstdcompat

        if not zstdcompat.available():
            raise errdefs.Unavailable("zstd layer but no zstd implementation")
        return zstdcompat.zstandard.ZstdDecompressor().decompress(data)
    if data[:2] == b"\x78\x9c" or data[:2] == b"\x78\xda":
        return zlib.decompress(data)
    return data


def unpack(reader, source: str, target: str) -> None:
    """Stream ``reader`` (bytes or file-like tar, optionally compressed),
    find member ``source``, write its contents to path ``target``."""
    data = reader if isinstance(reader, bytes) else reader.read()
    data = decompress_stream(data)
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:") as tf:
        for member in tf:
            if member.name == source or member.name == "./" + source:
                extracted = tf.extractfile(member)
                if extracted is None:
                    break
                with open(target, "wb") as out:
                    while True:
                        buf = extracted.read(1 << 20)
                        if not buf:
                            break
                        out.write(buf)
                return
    raise errdefs.NotFound(f"not found file {source} in tar")
