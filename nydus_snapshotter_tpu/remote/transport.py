"""Pooled authenticated blob transport with redirect probing.

Reference pkg/utils/transport/pool.go:24-108: an LRU of authenticated
clients keyed by image ref; ``resolve`` probes the blob endpoint with a
``Range: bytes=0-0`` request, returning either the endpoint itself or the
redirect target (CDN URL), evicting and re-authenticating on failure.

Hardened failure handling on top of the reference:

- HTTP 429 honors the ``Retry-After`` header with one bounded in-place
  retry before the pooled client is thrown away (re-auth is expensive;
  a throttle is not an auth failure).
- On 5xx or connect failure from the upstream host, configured registry
  mirrors (config/mirrors.py hosts.toml dirs) are tried in order with
  per-host health scoring and cooldown before the error is surfaced.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.remote.mirror import MirrorRouter, split_mirror_host
from nydus_snapshotter_tpu.remote.reference import ParsedReference, registry_host
from nydus_snapshotter_tpu.remote.registry import HTTPError, RegistryClient
from nydus_snapshotter_tpu.utils import errdefs

HTTP_CLIENT_TIMEOUT = 60.0
_POOL_CAP = 3000
# Throttle pauses are bounded: a registry demanding more than this gets
# the normal evict + re-resolve path instead of a blocking sleep.
RETRY_AFTER_CAP = 5.0


class Pool:
    def __init__(
        self,
        plain_http: bool = False,
        insecure_tls: bool = False,
        mirrors_config_dir: str = "",
        sleep=time.sleep,
    ):
        self._lock = threading.Lock()
        self._clients: OrderedDict[str, RegistryClient] = OrderedDict()
        self.plain_http = plain_http
        self.insecure_tls = insecure_tls
        self.mirrors = MirrorRouter(mirrors_config_dir)
        self._sleep = sleep

    def _get(self, key: str) -> Optional[RegistryClient]:
        with self._lock:
            client = self._clients.get(key)
            if client is not None:
                self._clients.move_to_end(key)
            return client

    def _put(self, key: str, client: RegistryClient) -> None:
        with self._lock:
            self._clients[key] = client
            self._clients.move_to_end(key)
            while len(self._clients) > _POOL_CAP:
                self._clients.popitem(last=False)

    def _evict(self, key: str) -> None:
        with self._lock:
            self._clients.pop(key, None)

    def _probe(self, client: RegistryClient, repo: str, digest: str) -> str:
        """Range-probe the blob endpoint; return the final (possibly CDN)
        URL serving it (pool.go redirect :72-108)."""
        failpoint.hit("transport.probe")
        r = client.fetch_blob(repo, digest, byte_range=(0, 0))
        try:
            return r.url or f"/v2/{repo}/blobs/{digest}"
        finally:
            r.close()

    def _probe_throttled(self, client: RegistryClient, repo: str, digest: str) -> str:
        """Probe with one bounded Retry-After retry on 429: the client's
        token is still good, the registry is just shedding load."""
        try:
            return self._probe(client, repo, digest)
        except HTTPError as e:
            if e.code != 429:
                raise
            self._sleep(min(max(e.retry_after, 0.0), RETRY_AFTER_CAP))
            return self._probe(client, repo, digest)

    @staticmethod
    def _should_failover(err: BaseException) -> bool:
        """Mirror-worthy failures: server-side errors and connect-level
        failures. Auth problems and 404s must surface unchanged."""
        if isinstance(err, HTTPError):
            return err.code >= 500 or err.code == 429
        return isinstance(err, OSError)

    def _resolve_via_mirror(
        self, ref: ParsedReference, digest: str, keychain, upstream_host: str
    ) -> Optional[tuple[str, RegistryClient]]:
        for m in self.mirrors.candidates(upstream_host):
            netloc, plain = split_mirror_host(m.host)
            mclient = RegistryClient(
                netloc,
                keychain=keychain,
                plain_http=plain or self.plain_http,
                insecure_tls=self.insecure_tls,
                timeout=HTTP_CLIENT_TIMEOUT,
                headers=m.headers,
            )
            try:
                url = self._probe_throttled(mclient, ref.path, digest)
            except (HTTPError, errdefs.NydusError, OSError):
                self.mirrors.record(m, ok=False)
                continue
            self.mirrors.record(m, ok=True)
            # Subsequent fetches for this ref ride the mirror until it is
            # evicted by its own failure.
            self._put(ref.name, mclient)
            return url, mclient
        return None

    def resolve(self, ref: ParsedReference, digest: str, keychain=None) -> tuple[str, RegistryClient]:
        """(blob path, authenticated client) for ref@digest, reusing a
        cached authenticated client when its token still works; on 5xx or
        connect failure, failing over to configured registry mirrors."""
        failpoint.hit("transport.resolve")
        key = ref.name
        host = registry_host(ref.domain)
        client = self._get(key)
        if client is not None:
            try:
                return self._probe_throttled(client, ref.path, digest), client
            except (HTTPError, errdefs.NydusError, OSError):
                self._evict(key)
        client = RegistryClient(
            host, keychain=keychain, plain_http=self.plain_http,
            insecure_tls=self.insecure_tls, timeout=HTTP_CLIENT_TIMEOUT,
        )
        try:
            url = self._probe_throttled(client, ref.path, digest)
        except (HTTPError, errdefs.NydusError, OSError) as e:
            if self._should_failover(e):
                mirrored = self._resolve_via_mirror(ref, digest, keychain, host)
                if mirrored is not None:
                    return mirrored
            raise
        self._put(key, client)
        return url, client
