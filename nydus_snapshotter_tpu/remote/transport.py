"""Pooled authenticated blob transport with redirect probing.

Reference pkg/utils/transport/pool.go:24-108: an LRU of authenticated
clients keyed by image ref; ``resolve`` probes the blob endpoint with a
``Range: bytes=0-0`` request, returning either the endpoint itself or the
redirect target (CDN URL), evicting and re-authenticating on failure.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from nydus_snapshotter_tpu.remote.reference import ParsedReference, registry_host
from nydus_snapshotter_tpu.remote.registry import HTTPError, RegistryClient
from nydus_snapshotter_tpu.utils import errdefs

HTTP_CLIENT_TIMEOUT = 60.0
_POOL_CAP = 3000


class Pool:
    def __init__(self, plain_http: bool = False, insecure_tls: bool = False):
        self._lock = threading.Lock()
        self._clients: OrderedDict[str, RegistryClient] = OrderedDict()
        self.plain_http = plain_http
        self.insecure_tls = insecure_tls

    def _get(self, key: str) -> Optional[RegistryClient]:
        with self._lock:
            client = self._clients.get(key)
            if client is not None:
                self._clients.move_to_end(key)
            return client

    def _put(self, key: str, client: RegistryClient) -> None:
        with self._lock:
            self._clients[key] = client
            self._clients.move_to_end(key)
            while len(self._clients) > _POOL_CAP:
                self._clients.popitem(last=False)

    def _evict(self, key: str) -> None:
        with self._lock:
            self._clients.pop(key, None)

    def _probe(self, client: RegistryClient, repo: str, digest: str) -> str:
        """Range-probe the blob endpoint; return the final (possibly CDN)
        URL serving it (pool.go redirect :72-108)."""
        r = client.fetch_blob(repo, digest, byte_range=(0, 0))
        try:
            return r.url or f"/v2/{repo}/blobs/{digest}"
        finally:
            r.close()

    def resolve(self, ref: ParsedReference, digest: str, keychain=None) -> tuple[str, RegistryClient]:
        """(blob path, authenticated client) for ref@digest, reusing a
        cached authenticated client when its token still works."""
        key = ref.name
        host = registry_host(ref.domain)
        client = self._get(key)
        if client is not None:
            try:
                return self._probe(client, ref.path, digest), client
            except (HTTPError, errdefs.NydusError, OSError):
                self._evict(key)
        client = RegistryClient(
            host, keychain=keychain, plain_http=self.plain_http,
            insecure_tls=self.insecure_tls, timeout=HTTP_CLIENT_TIMEOUT,
        )
        url = self._probe(client, ref.path, digest)
        self._put(key, client)
        return url, client
