"""Docker schema1 manifest conversion (legacy registries).

The reference vendors containerd's schema1 puller
(pkg/remote/remotes/docker/schema1/converter.go): old registries serve
``application/vnd.docker.distribution.manifest.v1(+prettyjws)`` manifests
whose layers are listed newest-first with per-layer v1Compatibility JSON
instead of a config blob. Conversion to the OCI shape the rest of the
stack consumes requires synthesizing the image config — including
``rootfs.diff_ids``, which only exist as the sha256 of each *decompressed*
layer, so the layers must be pulled (the reference does the same; it is
the unavoidable cost of schema1).

Surface: ``is_schema1(media_type)`` and
``convert_schema1(body, fetch_blob)`` → (oci_manifest_dict, config_bytes).
Layer order is reversed to OCI's lowest-first, ``throwaway`` history
entries (schema1's empty layers) are dropped, and the synthesized config
carries architecture/os/created/config from the newest v1Compatibility
entry.
"""

from __future__ import annotations

import gzip
import hashlib
import json
import zlib
from typing import Callable

MEDIA_TYPE_SCHEMA1 = "application/vnd.docker.distribution.manifest.v1+json"
MEDIA_TYPE_SCHEMA1_SIGNED = "application/vnd.docker.distribution.manifest.v1+prettyjws"
_MEDIA_TYPE_CONFIG = "application/vnd.oci.image.config.v1+json"
_MEDIA_TYPE_MANIFEST = "application/vnd.oci.image.manifest.v1+json"
_MEDIA_TYPE_LAYER = "application/vnd.oci.image.layer.v1.tar+gzip"


class Schema1Error(ValueError):
    pass


def is_schema1(media_type: str) -> bool:
    return media_type in (MEDIA_TYPE_SCHEMA1, MEDIA_TYPE_SCHEMA1_SIGNED)


def looks_like_schema1(manifest: dict) -> bool:
    """Body-shape detection: old registries serve schema1 under generic
    content types ('application/json', or none at all)."""
    return manifest.get("schemaVersion") == 1 and "fsLayers" in manifest


def _b64url(data: str) -> bytes:
    import base64

    pad = "=" * (-len(data) % 4)
    try:
        return base64.urlsafe_b64decode(data + pad)
    except (ValueError, TypeError) as e:
        raise Schema1Error(f"bad JWS base64: {e}") from e


def canonical_digest(body: bytes, parsed: dict | None = None) -> str:
    """The registry-canonical digest of a schema1 manifest body.

    Signed (+prettyjws) manifests are digested over the JWS payload with
    signatures stripped — ``body[:formatLength] + formatTail`` from the
    first signature's protected header (docker/libtrust semantics; the
    reference inherits this via containerd's schema1 DigestFromManifest).
    Unsigned bodies digest as-is. ``parsed`` passes an already-loaded body.
    """
    if parsed is not None:
        m = parsed
    else:
        try:
            m = json.loads(body)
        except (json.JSONDecodeError, UnicodeDecodeError):
            m = None
    sigs = m.get("signatures") if isinstance(m, dict) else None
    if isinstance(sigs, list) and sigs and isinstance(sigs[0], dict):
        protected_b64 = sigs[0].get("protected")
        if isinstance(protected_b64, str):
            try:
                protected = json.loads(_b64url(protected_b64))
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                raise Schema1Error(f"bad JWS protected header: {e}") from e
            if not isinstance(protected, dict):
                raise Schema1Error("JWS protected header is not an object")
            fl = protected.get("formatLength")
            tail_b64 = protected.get("formatTail")
            if not isinstance(fl, int) or not isinstance(tail_b64, str):
                raise Schema1Error("JWS protected header missing formatLength/formatTail")
            if not 0 <= fl <= len(body):
                raise Schema1Error(f"JWS formatLength {fl} outside body")
            payload = body[:fl] + _b64url(tail_b64)
            return "sha256:" + hashlib.sha256(payload).hexdigest()
    return "sha256:" + hashlib.sha256(body).hexdigest()


def _decompress_layer(blob: bytes) -> bytes:
    """Schema1 layers are tar+gzip on the wire; tolerate plain tars the way
    containerd's DecompressStream does (some mirrors re-serve decompressed)."""
    if blob[:2] == b"\x1f\x8b":
        try:
            return gzip.decompress(blob)
        except (OSError, EOFError, zlib.error) as e:
            raise Schema1Error(f"corrupt schema1 layer gzip: {e}") from e
    return blob


def convert_schema1(
    body: bytes, fetch_blob: Callable[[str], bytes], parsed: dict | None = None
) -> tuple[dict, bytes]:
    """Convert a schema1 manifest body into (OCI manifest dict, config bytes).

    ``fetch_blob(digest)`` must return the raw layer blob — needed to
    compute diff_ids for the synthesized config; each fetched blob is
    verified against its blobSum before its hash enters the synthesized
    manifest (the reference gets the same guarantee from content-store
    ingest). Signed (+prettyjws) manifests are accepted; signatures are not
    verified (parity with the reference converter, which relies on digest
    pinning instead). ``parsed`` passes an already-json.loads'd body.
    """
    if parsed is not None:
        m = parsed
    else:
        try:
            m = json.loads(body)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise Schema1Error(f"schema1 manifest is not JSON: {e}") from e
    if not isinstance(m, dict):
        raise Schema1Error("schema1 manifest is not an object")
    if m.get("schemaVersion") != 1:
        raise Schema1Error(f"not a schema1 manifest (schemaVersion={m.get('schemaVersion')!r})")
    fs_layers = m.get("fsLayers")
    history = m.get("history")
    if not isinstance(fs_layers, list) or not isinstance(history, list):
        raise Schema1Error("schema1 manifest missing fsLayers/history")
    if len(fs_layers) != len(history):
        raise Schema1Error(
            f"schema1 fsLayers ({len(fs_layers)}) != history ({len(history)})"
        )

    compat: list[dict] = []
    for h in history:
        if not isinstance(h, dict) or not isinstance(h.get("v1Compatibility"), str):
            raise Schema1Error("schema1 history entry missing v1Compatibility")
        try:
            c = json.loads(h["v1Compatibility"])
        except json.JSONDecodeError as e:
            raise Schema1Error(f"bad v1Compatibility JSON: {e}") from e
        if not isinstance(c, dict):
            raise Schema1Error("v1Compatibility is not an object")
        compat.append(c)

    # schema1 lists newest-first; OCI wants lowest-first.
    layers: list[dict] = []
    diff_ids: list[str] = []
    layer_history: list[dict] = []
    # Real schema1 manifests repeat the identical empty-gzip layer many
    # times (pre-throwaway Docker); fetch+hash each unique digest once.
    seen: dict[str, tuple[int, str]] = {}
    for idx in range(len(fs_layers) - 1, -1, -1):
        c = compat[idx]
        cmd = (c.get("container_config") or {}).get("Cmd") or []
        entry_history = {
            "created": c.get("created", ""),
            "created_by": " ".join(x for x in cmd if isinstance(x, str))
            if isinstance(cmd, list)
            else "",
        }
        if c.get("throwaway"):
            entry_history["empty_layer"] = True
            layer_history.append(entry_history)
            continue
        layer_history.append(entry_history)
        fsl = fs_layers[idx]
        digest = fsl.get("blobSum") if isinstance(fsl, dict) else None
        if not isinstance(digest, str) or not digest:
            raise Schema1Error("schema1 fsLayer missing blobSum")
        if digest not in seen:
            if not digest.startswith("sha256:"):
                # Docker schema1 only ever produced sha256 blobSums; an
                # unknown algorithm would mean skipping verification, and
                # unverified bytes must not enter the synthesized manifest.
                raise Schema1Error(f"unsupported blobSum algorithm: {digest}")
            blob = fetch_blob(digest)
            actual = "sha256:" + hashlib.sha256(blob).hexdigest()
            if actual != digest:
                raise Schema1Error(
                    f"layer blob digest mismatch: manifest says {digest}, "
                    f"fetched {actual}"
                )
            seen[digest] = (
                len(blob),
                "sha256:" + hashlib.sha256(_decompress_layer(blob)).hexdigest(),
            )
        size, diff_id = seen[digest]
        diff_ids.append(diff_id)
        layers.append({"mediaType": _MEDIA_TYPE_LAYER, "digest": digest, "size": size})

    newest = compat[0] if compat else {}
    config = {
        "architecture": m.get("architecture", newest.get("architecture", "amd64")),
        "os": newest.get("os", "linux"),
        "created": newest.get("created", ""),
        "config": newest.get("config") or {},
        "rootfs": {"type": "layers", "diff_ids": diff_ids},
        "history": layer_history,
    }
    config_bytes = json.dumps(config, sort_keys=True).encode()
    manifest = {
        "schemaVersion": 2,
        "mediaType": _MEDIA_TYPE_MANIFEST,
        "config": {
            "mediaType": _MEDIA_TYPE_CONFIG,
            "digest": "sha256:" + hashlib.sha256(config_bytes).hexdigest(),
            "size": len(config_bytes),
        },
        "layers": layers,
    }
    return manifest, config_bytes
