"""OCI distribution v2 registry client: resolver + fetcher + pusher.

Stdlib replacement for the reference's vendored containerd docker resolver
stack (pkg/remote/remotes/docker/resolver.go): manifest HEAD/GET resolve
with Accept negotiation, blob fetch (+range), FetchByDigest,
FetchReferrers (OCI referrers API), monolithic + chunked blob push, and
the WWW-Authenticate Bearer/Basic token dance (authorizer.go semantics).
"""

from __future__ import annotations

import base64
import hashlib
import http.client
import io
import json
import re
import ssl
import threading
import urllib.parse
from dataclasses import dataclass, field
from typing import Iterable, Mapping, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.utils import errdefs

MANIFEST_ACCEPTS = (
    "application/vnd.oci.image.manifest.v1+json",
    "application/vnd.docker.distribution.manifest.v2+json",
    "application/vnd.oci.image.index.v1+json",
    "application/vnd.docker.distribution.manifest.list.v2+json",
)

_AUTH_PARAM_RE = re.compile(r'(\w+)="([^"]*)"')


class HTTPError(errdefs.NydusError):
    def __init__(self, code: int, url: str, body: bytes = b"", retry_after: float = 0.0):
        self.code = code
        self.url = url
        self.body = body
        # Parsed Retry-After (seconds); 0.0 when the response carried none.
        self.retry_after = retry_after
        super().__init__(f"HTTP {code} for {url}: {body[:200]!r}")


def parse_retry_after(value: Optional[str]) -> float:
    """Retry-After header → seconds (delta-seconds or HTTP-date form)."""
    if not value:
        return 0.0
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        import email.utils

        when = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return 0.0
    import datetime

    now = datetime.datetime.now(datetime.timezone.utc)
    return max(0.0, (when - now).total_seconds())


@dataclass
class Descriptor:
    media_type: str
    digest: str
    size: int
    annotations: dict = field(default_factory=dict)
    urls: list = field(default_factory=list)
    platform: Optional[dict] = None

    @classmethod
    def from_json(cls, obj: Mapping) -> "Descriptor":
        # Registry responses are untrusted: missing/mistyped fields must
        # surface as ValueError (the parser contract fuzzed in
        # tests/test_fuzz_parsers.py), never KeyError/TypeError.
        digest = obj.get("digest")
        if not isinstance(digest, str) or not digest:
            raise ValueError("descriptor missing string 'digest'")
        size = obj.get("size", 0)
        if isinstance(size, bool) or not isinstance(size, int):
            raise ValueError(f"descriptor size not an integer: {size!r}")
        annotations = obj.get("annotations") or {}
        urls = obj.get("urls") or []
        platform = obj.get("platform")
        if not isinstance(annotations, Mapping):
            raise ValueError("descriptor annotations not an object")
        if not isinstance(urls, list):
            raise ValueError("descriptor urls not a list")
        if platform is not None and not isinstance(platform, Mapping):
            raise ValueError("descriptor platform not an object")
        media_type = obj.get("mediaType", "")
        if not isinstance(media_type, str):
            raise ValueError("descriptor mediaType not a string")
        return cls(
            media_type=media_type,
            digest=digest,
            size=size,
            annotations=dict(annotations),
            urls=list(urls),
            platform=dict(platform) if platform is not None else None,
        )

    def to_json(self) -> dict:
        out: dict = {"mediaType": self.media_type, "digest": self.digest, "size": self.size}
        if self.annotations:
            out["annotations"] = self.annotations
        if self.urls:
            out["urls"] = self.urls
        if self.platform:
            out["platform"] = self.platform
        return out


def parse_www_authenticate(header: str) -> tuple[str, dict]:
    """('bearer'|'basic', params) from a WWW-Authenticate header."""
    scheme, _, rest = header.partition(" ")
    return scheme.lower(), dict(_AUTH_PARAM_RE.findall(rest))


class _Response:
    """Fully-read or streaming response wrapper."""

    def __init__(self, status: int, headers: Mapping[str, str], conn, resp):
        self.status = status
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.url = ""  # final URL after redirects, set by do()
        self._conn = conn
        self._resp = resp

    def read(self, n: int = -1) -> bytes:
        return self._resp.read() if n < 0 else self._resp.read(n)

    def close(self) -> None:
        try:
            self._resp.close()
        finally:
            self._conn.close()


class RegistryClient:
    """Per-host client. ``keychain`` is an auth.PassKeyChain or None."""

    def __init__(
        self,
        host: str,
        keychain=None,
        plain_http: bool = False,
        insecure_tls: bool = False,
        timeout: float = 60.0,
        headers: Optional[Mapping[str, str]] = None,
    ):
        self.host = host
        self.keychain = keychain
        self.plain_http = plain_http
        self.insecure_tls = insecure_tls
        self.timeout = timeout
        # Always-sent headers (mirror configs carry e.g. X-Registry).
        self.extra_headers = dict(headers or {})
        self._token: Optional[str] = None  # cached bearer token
        self._lock = threading.Lock()

    # -- low-level HTTP -------------------------------------------------------

    def _connect(self, netloc: str):
        if self.plain_http:
            return http.client.HTTPConnection(netloc, timeout=self.timeout)
        ctx = ssl.create_default_context()
        if self.insecure_tls:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return http.client.HTTPSConnection(netloc, timeout=self.timeout, context=ctx)

    def _raw(self, method: str, url: str, headers: Mapping[str, str], body=None) -> _Response:
        parsed = urllib.parse.urlsplit(url)
        conn = self._connect(parsed.netloc)
        path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
        try:
            conn.request(method, path, body=body, headers=dict(headers))
            resp = conn.getresponse()
        except Exception:
            conn.close()
            raise
        return _Response(resp.status, dict(resp.getheaders()), conn, resp)

    def _authorization(self) -> Optional[str]:
        if self._token:
            return f"Bearer {self._token}"
        if self.keychain is not None and not self.keychain.empty():
            if self.keychain.token_base():
                return f"Bearer {self.keychain.password}"
            raw = f"{self.keychain.username}:{self.keychain.password}".encode()
            return "Basic " + base64.b64encode(raw).decode()
        return None

    def _fetch_token(self, params: Mapping[str, str], scope: Optional[str]) -> None:
        """Bearer token fetch against the realm (authorizer.go flow)."""
        realm = params.get("realm")
        if not realm:
            raise errdefs.Unavailable("bearer challenge without realm")
        q = {}
        if params.get("service"):
            q["service"] = params["service"]
        sc = scope or params.get("scope")
        if sc:
            q["scope"] = sc
        url = realm + ("?" + urllib.parse.urlencode(q) if q else "")
        headers = {}
        if self.keychain is not None and not self.keychain.empty() and not self.keychain.token_base():
            raw = f"{self.keychain.username}:{self.keychain.password}".encode()
            headers["Authorization"] = "Basic " + base64.b64encode(raw).decode()
        r = self._raw("GET", url, headers)
        try:
            if r.status != 200:
                raise HTTPError(r.status, url, r.read(4096))
            payload = json.loads(r.read())
        finally:
            r.close()
        self._token = payload.get("token") or payload.get("access_token")
        if not self._token:
            raise errdefs.Unavailable(f"no token in auth response from {realm}")

    def do(
        self,
        method: str,
        path: str,
        headers: Optional[Mapping[str, str]] = None,
        body=None,
        scope: Optional[str] = None,
        ok: Iterable[int] = (200,),
        follow_redirects: int = 5,
        stream: bool = False,
    ) -> _Response:
        """Authenticated request with one 401-challenge retry and redirect
        following (resolver.go request.doWithRetries semantics)."""
        scheme = "http" if self.plain_http else "https"
        url = path if "://" in path else f"{scheme}://{self.host}{path}"
        hdrs = dict(self.extra_headers)
        hdrs.update(headers or {})
        for attempt in range(2):
            auth = self._authorization()
            if auth:
                hdrs["Authorization"] = auth
            elif "Authorization" in hdrs:
                del hdrs["Authorization"]
            r = self._raw(method, url, hdrs, body)
            if r.status == 401 and attempt == 0:
                challenge = r.headers.get("www-authenticate", "")
                r.close()
                schm, params = parse_www_authenticate(challenge)
                if schm == "bearer":
                    with self._lock:
                        self._token = None
                        self._fetch_token(params, scope)
                    continue
                raise HTTPError(401, url)
            while r.status in (301, 302, 303, 307, 308) and follow_redirects > 0:
                loc = r.headers.get("location", "")
                r.close()
                follow_redirects -= 1
                prev_host = urllib.parse.urlsplit(url).netloc
                url = urllib.parse.urljoin(url, loc)
                redirected = dict(hdrs)
                # Cross-origin redirects (e.g. blob CDN) must not leak auth.
                if urllib.parse.urlsplit(url).netloc != prev_host:
                    redirected.pop("Authorization", None)
                r = self._raw(method, url, redirected, body)
            r.url = url
            if r.status in ok:
                return r
            data = b"" if stream else r.read(4096)
            retry_after = parse_retry_after(r.headers.get("retry-after"))
            r.close()
            if r.status == 404:
                raise errdefs.NotFound(f"{method} {url}: 404")
            raise HTTPError(r.status, url, data, retry_after=retry_after)
        raise errdefs.Unavailable(f"auth retry exhausted for {url}")

    # -- resolver / fetcher ---------------------------------------------------

    def resolve(self, repo: str, tag_or_digest: str) -> Descriptor:
        """HEAD (falling back to GET) the manifest; return its descriptor."""
        path = f"/v2/{repo}/manifests/{tag_or_digest}"
        hdrs = {"Accept": ", ".join(MANIFEST_ACCEPTS)}
        scope = f"repository:{repo}:pull"
        try:
            r = self.do("HEAD", path, hdrs, scope=scope)
            body = b""
        except (HTTPError, errdefs.NotFound):
            r = self.do("GET", path, hdrs, scope=scope)
            body = r.read()
        try:
            digest = r.headers.get("docker-content-digest")
            size = int(r.headers.get("content-length", len(body)))
            media = r.headers.get("content-type", MANIFEST_ACCEPTS[0])
        finally:
            r.close()
        if not digest:
            if not body:
                r2 = self.do("GET", path, hdrs, scope=scope)
                body = r2.read()
                r2.close()
            digest = "sha256:" + hashlib.sha256(body).hexdigest()
            size = len(body)
        return Descriptor(media_type=media, digest=digest, size=size)

    def fetch_manifest(self, repo: str, tag_or_digest: str) -> tuple[Descriptor, bytes]:
        path = f"/v2/{repo}/manifests/{tag_or_digest}"
        r = self.do("GET", path, {"Accept": ", ".join(MANIFEST_ACCEPTS)}, scope=f"repository:{repo}:pull")
        try:
            body = r.read()
            media = r.headers.get("content-type", MANIFEST_ACCEPTS[0])
            digest = r.headers.get("docker-content-digest") or ("sha256:" + hashlib.sha256(body).hexdigest())
        finally:
            r.close()
        return Descriptor(media_type=media, digest=digest, size=len(body)), body

    def fetch_manifest_oci(
        self, repo: str, tag_or_digest: str
    ) -> tuple[Descriptor, dict, Optional[bytes]]:
        """fetch_manifest with transparent legacy-schema1 conversion.

        Returns (descriptor, manifest dict in OCI shape, synthesized config
        bytes). The config is None for native v2/OCI manifests (fetch it by
        digest as usual); for schema1 it is the synthesized config whose
        digest the converted manifest references (reference
        schema1/converter.go semantics).
        """
        from nydus_snapshotter_tpu.remote import schema1

        desc, body = self.fetch_manifest(repo, tag_or_digest)
        try:
            manifest = json.loads(body)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(f"manifest {desc.digest} is not JSON: {e}") from e
        if not isinstance(manifest, dict):
            raise ValueError(f"manifest {desc.digest} is not an object")
        # Content-Type alone is unreliable: old registries serve schema1 as
        # application/json (or no header, which fetch_manifest defaults to
        # the OCI type) — the body shape is the authority.
        if schema1.is_schema1(desc.media_type) or schema1.looks_like_schema1(manifest):
            oci_manifest, config = schema1.convert_schema1(
                body, lambda d: self.fetch_by_digest(repo, d), parsed=manifest
            )
            # Signed manifests' registry identity is the signature-stripped
            # canonical digest; the full-body fallback hash would never
            # match a later fetch-by-digest.
            desc = Descriptor(
                media_type=desc.media_type,
                digest=schema1.canonical_digest(body, parsed=manifest),
                size=desc.size,
                annotations=desc.annotations,
                urls=desc.urls,
                platform=desc.platform,
            )
            return desc, oci_manifest, config
        return desc, manifest, None

    def fetch_blob(self, repo: str, digest: str, byte_range: Optional[tuple[int, int]] = None):
        """Streaming blob fetch; ``byte_range`` is an inclusive (start, end)
        pair mapped to an HTTP Range header (stargz range reads)."""
        failpoint.hit("transport.fetch_blob")
        hdrs = {}
        ok: tuple[int, ...] = (200,)
        if byte_range is not None:
            hdrs["Range"] = f"bytes={byte_range[0]}-{byte_range[1]}"
            ok = (200, 206)
        return self.do(
            "GET", f"/v2/{repo}/blobs/{digest}", hdrs,
            scope=f"repository:{repo}:pull", ok=ok, stream=True,
        )

    def fetch_by_digest(self, repo: str, digest: str) -> bytes:
        """FetchByDigest (fetcher.go): blob endpoint, manifest fallback."""
        try:
            r = self.fetch_blob(repo, digest)
            try:
                return r.read()
            finally:
                r.close()
        except (errdefs.NotFound, HTTPError):
            _, body = self.fetch_manifest(repo, digest)
            return body

    def head_blob(self, repo: str, digest: str) -> bool:
        try:
            r = self.do("HEAD", f"/v2/{repo}/blobs/{digest}", scope=f"repository:{repo}:pull")
            r.close()
            return True
        except (errdefs.NotFound, HTTPError):
            return False

    def fetch_referrers(self, repo: str, digest: str, artifact_type: Optional[str] = None) -> list[Descriptor]:
        """OCI referrers API (fetcher.go FetchReferrers); returns manifest
        descriptors referring to ``digest``."""
        path = f"/v2/{repo}/referrers/{digest}"
        if artifact_type:
            path += "?" + urllib.parse.urlencode({"artifactType": artifact_type})
        r = self.do("GET", path, {"Accept": "application/vnd.oci.image.index.v1+json"},
                    scope=f"repository:{repo}:pull")
        try:
            index = json.loads(r.read())
        finally:
            r.close()
        return [Descriptor.from_json(m) for m in index.get("manifests", [])]

    # -- pusher ---------------------------------------------------------------

    def push_blob(self, repo: str, digest: str, data) -> None:
        """Monolithic blob upload: POST uploads/ then PUT ?digest=… ; no-op
        when the blob already exists (pusher.go)."""
        scope = f"repository:{repo}:pull,push"
        if self.head_blob(repo, digest):
            return
        r = self.do("POST", f"/v2/{repo}/blobs/uploads/", scope=scope, ok=(202,))
        location = r.headers.get("location", "")
        r.close()
        if not location:
            raise errdefs.Unavailable("upload session without Location")
        sep = "&" if "?" in location else "?"
        put_url = f"{location}{sep}digest={urllib.parse.quote(digest, safe='')}"
        if isinstance(data, (bytes, bytearray)):
            body = bytes(data)
        else:
            body = data.read()
        r = self.do("PUT", put_url, {"Content-Type": "application/octet-stream",
                                     "Content-Length": str(len(body))},
                    body=body, scope=scope, ok=(201, 204))
        r.close()

    def push_manifest(self, repo: str, tag_or_digest: str, media_type: str, body: bytes) -> str:
        r = self.do(
            "PUT", f"/v2/{repo}/manifests/{tag_or_digest}",
            {"Content-Type": media_type, "Content-Length": str(len(body))},
            body=body, scope=f"repository:{repo}:pull,push", ok=(201, 204),
        )
        digest = r.headers.get("docker-content-digest", "")
        r.close()
        return digest or ("sha256:" + hashlib.sha256(body).hexdigest())
