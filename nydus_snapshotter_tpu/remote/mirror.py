"""Registry mirror failover routing with per-host health scoring.

Consumes the containerd-style per-registry mirror directories parsed by
:mod:`nydus_snapshotter_tpu.config.mirrors` (``<dir>/<host>/hosts.toml``)
and keeps an in-process health score per mirror host: after
``failure_limit`` consecutive failures a mirror is put on cooldown for
``health_check_interval`` seconds and skipped by the candidate ordering
until the cooldown expires (reference mirrors_health.go semantics,
collapsed into the request path — no background prober needed for a
snapshotter-side transport).
"""

from __future__ import annotations

import threading
import time
import urllib.parse
from typing import Callable, Optional

from nydus_snapshotter_tpu.config.daemonconfig import MirrorConfig
from nydus_snapshotter_tpu.config.mirrors import load_mirrors_config


class HostHealth:
    """Consecutive-failure scorer with cooldown."""

    def __init__(
        self,
        failure_limit: int = 5,
        cooldown: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_limit = max(1, int(failure_limit))
        self.cooldown = float(cooldown)
        self._clock = clock
        self.consecutive_failures = 0
        self.down_until = 0.0

    def available(self) -> bool:
        return self._clock() >= self.down_until

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self.down_until = 0.0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.failure_limit:
            # Trip: cool down, then start a fresh count — a recovered
            # mirror gets a full failure budget after the cooldown.
            self.down_until = self._clock() + self.cooldown
            self.consecutive_failures = 0

    def mark_down(self, duration: Optional[float] = None) -> None:
        """Externally-sourced cooldown (e.g. the fleet registry flagging a
        member stale): trip immediately without burning the failure
        budget, so the host recovers the instant the source clears."""
        self.down_until = max(
            self.down_until,
            self._clock() + (self.cooldown if duration is None else duration),
        )
        self.consecutive_failures = 0


def split_mirror_host(mirror_host: str) -> tuple[str, bool]:
    """``https://mirror:5000`` → (netloc, plain_http)."""
    parsed = urllib.parse.urlsplit(mirror_host)
    if parsed.netloc:
        return parsed.netloc, parsed.scheme == "http"
    return mirror_host, False


class HostHealthRegistry:
    """Process-wide host → :class:`HostHealth` table.

    The converter transport (``remote/transport.Pool``), the lazy-read
    data plane (``daemon/blobcache.RegistryBlobFetcher``) and the peer
    chunk tier (``daemon/peer.PeerRouter``) all score hosts through ONE
    shared table, so a registry/mirror/peer demoted by any component is
    avoided by every other one. The first caller's limits stick for a
    host (limits are per-host deployment facts, not per-caller)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._health: dict[str, HostHealth] = {}

    def health_for(
        self, host: str, failure_limit: int = 5, cooldown: float = 5.0
    ) -> HostHealth:
        with self._lock:
            h = self._health.get(host)
            if h is None:
                h = HostHealth(
                    failure_limit=failure_limit,
                    cooldown=cooldown,
                    clock=self._clock,
                )
                self._health[host] = h
            return h

    def health(self, host: str) -> Optional[HostHealth]:
        with self._lock:
            return self._health.get(host)

    def record(self, host: str, ok: bool) -> None:
        h = self.health_for(host)
        if ok:
            h.record_success()
        else:
            h.record_failure()

    def available(self, host: str) -> bool:
        return self.health_for(host).available()

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                host: {
                    "available": h.available(),
                    "consecutive_failures": h.consecutive_failures,
                    "down_until": h.down_until,
                }
                for host, h in self._health.items()
            }


_global_health = HostHealthRegistry()


def global_health_registry() -> HostHealthRegistry:
    """The one process-wide health table (see :class:`HostHealthRegistry`).
    Components with an injected test clock build private registries
    instead, so fake-clock tests never pollute the process table."""
    return _global_health


class MirrorRouter:
    """Orders mirror candidates per upstream registry host, health-aware."""

    def __init__(
        self,
        mirrors_config_dir: str = "",
        clock: Callable[[], float] = time.monotonic,
        health_registry: Optional["HostHealthRegistry"] = None,
    ):
        self.mirrors_config_dir = mirrors_config_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._mirrors: dict[str, list[MirrorConfig]] = {}
        # Score through the process-wide table so the data plane sees the
        # same demotions; a custom clock (tests) gets a private table.
        if health_registry is not None:
            self._registry = health_registry
        elif clock is time.monotonic:
            self._registry = global_health_registry()
        else:
            self._registry = HostHealthRegistry(clock=clock)

    def mirrors_for(self, registry_host: str) -> list[MirrorConfig]:
        """Configured mirrors for ``registry_host`` (cached per host)."""
        if not self.mirrors_config_dir:
            return []
        with self._lock:
            if registry_host in self._mirrors:
                return self._mirrors[registry_host]
        mirrors = load_mirrors_config(self.mirrors_config_dir, registry_host)
        with self._lock:
            self._mirrors.setdefault(registry_host, mirrors)
            return self._mirrors[registry_host]

    def candidates(self, registry_host: str) -> list[MirrorConfig]:
        """Healthy mirrors in configured order (cooled-down hosts skipped)."""
        return [
            m
            for m in self.mirrors_for(registry_host)
            if self._health_for(m).available()
        ]

    def _health_for(self, mirror: MirrorConfig) -> HostHealth:
        return self._registry.health_for(
            mirror.host,
            failure_limit=mirror.failure_limit,
            cooldown=float(mirror.health_check_interval),
        )

    def health(self, mirror_host: str) -> Optional[HostHealth]:
        return self._registry.health(mirror_host)

    def record(self, mirror: MirrorConfig, ok: bool) -> None:
        h = self._health_for(mirror)
        if ok:
            h.record_success()
        else:
            h.record_failure()

    def invalidate(self, registry_host: Optional[str] = None) -> None:
        """Drop the cached hosts.toml parse (config reload)."""
        with self._lock:
            if registry_host is None:
                self._mirrors.clear()
            else:
                self._mirrors.pop(registry_host, None)
