"""Registry mirror failover routing with per-host health scoring.

Consumes the containerd-style per-registry mirror directories parsed by
:mod:`nydus_snapshotter_tpu.config.mirrors` (``<dir>/<host>/hosts.toml``)
and keeps an in-process health score per mirror host: after
``failure_limit`` consecutive failures a mirror is put on cooldown for
``health_check_interval`` seconds and skipped by the candidate ordering
until the cooldown expires (reference mirrors_health.go semantics,
collapsed into the request path — no background prober needed for a
snapshotter-side transport).
"""

from __future__ import annotations

import threading
import time
import urllib.parse
from typing import Callable, Optional

from nydus_snapshotter_tpu.config.daemonconfig import MirrorConfig
from nydus_snapshotter_tpu.config.mirrors import load_mirrors_config


class HostHealth:
    """Consecutive-failure scorer with cooldown."""

    def __init__(
        self,
        failure_limit: int = 5,
        cooldown: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_limit = max(1, int(failure_limit))
        self.cooldown = float(cooldown)
        self._clock = clock
        self.consecutive_failures = 0
        self.down_until = 0.0

    def available(self) -> bool:
        return self._clock() >= self.down_until

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self.down_until = 0.0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.failure_limit:
            # Trip: cool down, then start a fresh count — a recovered
            # mirror gets a full failure budget after the cooldown.
            self.down_until = self._clock() + self.cooldown
            self.consecutive_failures = 0


def split_mirror_host(mirror_host: str) -> tuple[str, bool]:
    """``https://mirror:5000`` → (netloc, plain_http)."""
    parsed = urllib.parse.urlsplit(mirror_host)
    if parsed.netloc:
        return parsed.netloc, parsed.scheme == "http"
    return mirror_host, False


class MirrorRouter:
    """Orders mirror candidates per upstream registry host, health-aware."""

    def __init__(
        self,
        mirrors_config_dir: str = "",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.mirrors_config_dir = mirrors_config_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._mirrors: dict[str, list[MirrorConfig]] = {}
        self._health: dict[str, HostHealth] = {}

    def mirrors_for(self, registry_host: str) -> list[MirrorConfig]:
        """Configured mirrors for ``registry_host`` (cached per host)."""
        if not self.mirrors_config_dir:
            return []
        with self._lock:
            if registry_host in self._mirrors:
                return self._mirrors[registry_host]
        mirrors = load_mirrors_config(self.mirrors_config_dir, registry_host)
        with self._lock:
            self._mirrors.setdefault(registry_host, mirrors)
            return self._mirrors[registry_host]

    def candidates(self, registry_host: str) -> list[MirrorConfig]:
        """Healthy mirrors in configured order (cooled-down hosts skipped)."""
        return [
            m
            for m in self.mirrors_for(registry_host)
            if self._health_for(m).available()
        ]

    def _health_for(self, mirror: MirrorConfig) -> HostHealth:
        with self._lock:
            h = self._health.get(mirror.host)
            if h is None:
                h = HostHealth(
                    failure_limit=mirror.failure_limit,
                    cooldown=float(mirror.health_check_interval),
                    clock=self._clock,
                )
                self._health[mirror.host] = h
            return h

    def health(self, mirror_host: str) -> Optional[HostHealth]:
        with self._lock:
            return self._health.get(mirror_host)

    def record(self, mirror: MirrorConfig, ok: bool) -> None:
        h = self._health_for(mirror)
        if ok:
            h.record_success()
        else:
            h.record_failure()

    def invalidate(self, registry_host: Optional[str] = None) -> None:
        """Drop the cached hosts.toml parse (config reload)."""
        with self._lock:
            if registry_host is None:
                self._mirrors.clear()
            else:
                self._mirrors.pop(registry_host, None)
