"""Docker image reference parsing/normalization.

Replaces the distribution/reference dependency the reference leans on
(pkg/remote/remote.go:101-104, pkg/resolve/resolver.go:35-44): normalize a
ref like ``ubuntu:22.04`` to ``docker.io/library/ubuntu:22.04``, split out
domain/path/tag/digest.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

DEFAULT_DOMAIN = "docker.io"
LEGACY_DEFAULT_DOMAIN = "index.docker.io"
OFFICIAL_REPO_PREFIX = "library/"
DEFAULT_TAG = "latest"

_TAG_RE = re.compile(r"^[\w][\w.-]{0,127}$")
_DIGEST_RE = re.compile(r"^[a-z0-9]+(?:[.+_-][a-z0-9]+)*:[0-9a-fA-F]{32,}$")


class InvalidReference(ValueError):
    pass


@dataclass(frozen=True)
class ParsedReference:
    domain: str
    path: str
    tag: Optional[str] = None
    digest: Optional[str] = None

    @property
    def name(self) -> str:
        return f"{self.domain}/{self.path}"

    @property
    def familiar(self) -> str:
        out = self.name
        if self.tag:
            out += f":{self.tag}"
        if self.digest:
            out += f"@{self.digest}"
        return out

    def __str__(self) -> str:  # canonical form
        return self.familiar


def _split_domain(name: str) -> tuple[str, str]:
    """Split a name into (domain, remainder) using docker's heuristic: the
    first component is a domain iff it contains '.' or ':' or is
    'localhost'."""
    i = name.find("/")
    if i == -1:
        return DEFAULT_DOMAIN, name
    first = name[:i]
    if "." in first or ":" in first or first == "localhost":
        return first, name[i + 1 :]
    return DEFAULT_DOMAIN, name


def parse_docker_ref(ref: str) -> ParsedReference:
    """Normalized parse (distribution ParseDockerRef semantics)."""
    if not ref or ref != ref.strip():
        raise InvalidReference(f"invalid reference {ref!r}")

    digest = None
    if "@" in ref:
        ref, digest = ref.rsplit("@", 1)
        if not _DIGEST_RE.match(digest):
            raise InvalidReference(f"invalid digest in reference {ref!r}")

    domain, remainder = _split_domain(ref)

    tag = None
    # A ':' after the last '/' is a tag separator (not a port).
    last_slash = remainder.rfind("/")
    colon = remainder.rfind(":")
    if colon > last_slash:
        remainder, tag = remainder[:colon], remainder[colon + 1 :]
        if not _TAG_RE.match(tag):
            raise InvalidReference(f"invalid tag {tag!r}")

    if not remainder:
        raise InvalidReference(f"empty repository path in {ref!r}")
    if domain in (DEFAULT_DOMAIN, LEGACY_DEFAULT_DOMAIN):
        domain = DEFAULT_DOMAIN
        if "/" not in remainder:
            remainder = OFFICIAL_REPO_PREFIX + remainder

    if not re.match(r"^[a-z0-9]+(?:(?:[._]|__|[-]+)[a-z0-9]+)*(?:/[a-z0-9]+(?:(?:[._]|__|[-]+)[a-z0-9]+)*)*$", remainder):
        raise InvalidReference(f"invalid repository path {remainder!r}")

    if digest is None and tag is None:
        tag = DEFAULT_TAG
    return ParsedReference(domain=domain, path=remainder, tag=tag, digest=digest)


def registry_host(domain: str) -> str:
    """Registry endpoint host for a reference domain (docker.io ->
    registry-1.docker.io, the containerd default-registry rewrite)."""
    if domain == DEFAULT_DOMAIN:
        return "registry-1.docker.io"
    return domain
