"""Remote facade: per-request resolver construction + plain-HTTP fallback.

Reference pkg/remote/remote.go:40-127. Each fetch gets a fresh
RegistryClient (tokens are short-lived; the reference rebuilds the
containerd resolver per request, remote.go:41-46). The plain-HTTP retry
heuristic flips the whole Remote to http after an error that looks like
"server gave HTTP response to HTTPS client" or a refused TLS connection
mentioning this ref's host (remote.go:96-115).
"""

from __future__ import annotations

from typing import Optional

from nydus_snapshotter_tpu.remote.reference import parse_docker_ref, registry_host
from nydus_snapshotter_tpu.remote.registry import Descriptor, RegistryClient


def _is_http_response_to_https(err: BaseException) -> bool:
    msg = str(err)
    return "HTTP response to HTTPS client" in msg or "WRONG_VERSION_NUMBER" in msg or "record layer failure" in msg


def _is_connection_refused(err: BaseException) -> bool:
    return "onnection refused" in str(err) or isinstance(err, ConnectionRefusedError)


class Remote:
    def __init__(self, keychain=None, insecure: bool = False):
        self.keychain = keychain
        self.insecure = insecure
        self.with_plain_http = False

    def client(self, ref: str) -> RegistryClient:
        parsed = parse_docker_ref(ref)
        return RegistryClient(
            registry_host(parsed.domain),
            keychain=self.keychain,
            plain_http=self.with_plain_http,
            insecure_tls=self.insecure,
        )

    def retry_with_plain_http(self, ref: str, err: Optional[BaseException]) -> bool:
        """Flip to plain HTTP when the error signature says the host speaks
        http; returns whether the caller should retry (remote.go:96-115)."""
        if err is None or not (_is_http_response_to_https(err) or _is_connection_refused(err)):
            return False
        self.with_plain_http = True
        return True

    # -- convenience wrappers (remote.go Resolve/Fetcher/Pusher) --------------

    def resolve(self, ref: str) -> Descriptor:
        parsed = parse_docker_ref(ref)
        return self.client(ref).resolve(parsed.path, parsed.digest or parsed.tag or "latest")

    def fetch_manifest(self, ref: str) -> tuple[Descriptor, bytes]:
        parsed = parse_docker_ref(ref)
        return self.client(ref).fetch_manifest(parsed.path, parsed.digest or parsed.tag or "latest")

    def fetch_blob(self, ref: str, digest: str):
        parsed = parse_docker_ref(ref)
        return self.client(ref).fetch_blob(parsed.path, digest)

    def push_blob(self, ref: str, digest: str, data) -> None:
        parsed = parse_docker_ref(ref)
        self.client(ref).push_blob(parsed.path, digest, data)
