"""Remote I/O layer (L8): registry resolver/fetcher/pusher, transport pool.

TPU-era equivalent of reference pkg/remote + pkg/resolve +
pkg/utils/transport: a stdlib OCI-distribution client (no vendored
containerd fork), with the same plain-HTTP retry heuristic
(pkg/remote/remote.go:96-115) and the pooled token-refreshing transport
(pkg/utils/transport/pool.go:24-70).
"""

from nydus_snapshotter_tpu.remote.mirror import HostHealth, MirrorRouter
from nydus_snapshotter_tpu.remote.reference import ParsedReference, parse_docker_ref
from nydus_snapshotter_tpu.remote.registry import Descriptor, RegistryClient
from nydus_snapshotter_tpu.remote.remote import Remote
from nydus_snapshotter_tpu.remote.resolve import Resolver
from nydus_snapshotter_tpu.remote.transport import Pool

__all__ = [
    "ParsedReference",
    "parse_docker_ref",
    "Descriptor",
    "RegistryClient",
    "Remote",
    "Resolver",
    "Pool",
    "MirrorRouter",
    "HostHealth",
]
