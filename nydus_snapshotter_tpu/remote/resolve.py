"""High-level blob resolver: ref + digest + snapshot labels -> stream.

Reference pkg/resolve/resolver.go:23-69: parse the ref, derive the
keychain from labels/docker-config (auth.GetRegistryKeyChain), resolve an
authenticated transport from the pool, GET the blob with retries.
"""

from __future__ import annotations

from typing import Mapping, Optional

from nydus_snapshotter_tpu.remote.reference import parse_docker_ref
from nydus_snapshotter_tpu.remote.transport import Pool
from nydus_snapshotter_tpu.utils import retry as retry_lib


class Resolver:
    def __init__(self, plain_http: bool = False, insecure_tls: bool = False):
        self._pool = Pool(plain_http=plain_http, insecure_tls=insecure_tls)

    def resolve(self, ref: str, digest: str, labels: Optional[Mapping[str, str]] = None):
        """Streaming reader over the blob ``digest`` of image ``ref``."""
        from nydus_snapshotter_tpu.auth.keychain import get_registry_keychain

        parsed = parse_docker_ref(ref)
        keychain = get_registry_keychain(parsed.domain, ref, labels or {})

        def fetch():
            _, client = self._pool.resolve(parsed, digest, keychain)
            return client.fetch_blob(parsed.path, digest)

        return retry_lib.do(fetch, attempts=3, delay=0.2)
