"""High-level blob resolver: ref + digest + snapshot labels -> stream.

Reference pkg/resolve/resolver.go:23-69: parse the ref, derive the
keychain from labels/docker-config (auth.GetRegistryKeyChain), resolve an
authenticated transport from the pool, GET the blob with retries. Retries
here are deadline- and jitter-aware: the whole retry loop fits inside one
HTTP client timeout instead of multiplying it (three 60 s attempts must
not become a 180 s hang on a dead registry).
"""

from __future__ import annotations

from typing import Mapping, Optional

from nydus_snapshotter_tpu.remote.reference import parse_docker_ref
from nydus_snapshotter_tpu.remote.transport import HTTP_CLIENT_TIMEOUT, Pool
from nydus_snapshotter_tpu.utils import retry as retry_lib


class Resolver:
    def __init__(
        self,
        plain_http: bool = False,
        insecure_tls: bool = False,
        mirrors_config_dir: str = "",
    ):
        self._pool = Pool(
            plain_http=plain_http,
            insecure_tls=insecure_tls,
            mirrors_config_dir=mirrors_config_dir,
        )

    def resolve(self, ref: str, digest: str, labels: Optional[Mapping[str, str]] = None):
        """Streaming reader over the blob ``digest`` of image ``ref``."""
        from nydus_snapshotter_tpu.auth.keychain import get_registry_keychain

        parsed = parse_docker_ref(ref)
        keychain = get_registry_keychain(parsed.domain, ref, labels or {})

        def fetch():
            _, client = self._pool.resolve(parsed, digest, keychain)
            return client.fetch_blob(parsed.path, digest)

        return retry_lib.do_with_deadline(
            fetch, deadline=HTTP_CLIENT_TIMEOUT, attempts=3, delay=0.2
        )
