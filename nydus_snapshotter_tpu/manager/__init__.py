"""Daemon lifecycle manager + liveness monitoring (reference pkg/manager)."""

from nydus_snapshotter_tpu.manager.monitor import LivenessMonitor, DeathEvent  # noqa: F401
from nydus_snapshotter_tpu.manager.manager import Manager  # noqa: F401
