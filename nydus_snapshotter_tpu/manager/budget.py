"""Restart budget / circuit breaker for daemon recovery.

A crashing daemon with the ``restart`` (or ``failover``) policy must not
turn into a hot respawn loop: each daemon gets ``max_restarts`` respawns
per sliding ``window`` seconds, with exponential backoff between them
(first respawn immediate, then ``base_delay * 2^(n-1)`` capped at
``max_delay``). When the budget is exhausted the circuit opens: the
manager degrades the daemon to passthrough instead of respawning.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional


class RestartBudget:
    def __init__(
        self,
        max_restarts: int = 3,
        window: float = 60.0,
        base_delay: float = 0.5,
        max_delay: float = 8.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_restarts < 1:
            raise ValueError("max_restarts must be >= 1")
        self.max_restarts = max_restarts
        self.window = float(window)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: dict[str, deque[float]] = {}

    def _prune_locked(self, daemon_id: str, now: float) -> "deque[float]":
        events = self._events.setdefault(daemon_id, deque())
        while events and now - events[0] > self.window:
            events.popleft()
        return events

    def next_delay(self, daemon_id: str) -> Optional[float]:
        """Consume one respawn slot. Returns the backoff to wait before
        respawning (0.0 for the first respawn in the window), or None when
        the budget is exhausted — the caller must degrade, not respawn."""
        now = self._clock()
        with self._lock:
            events = self._prune_locked(daemon_id, now)
            n = len(events)
            if n >= self.max_restarts:
                return None
            events.append(now)
        if n == 0:
            return 0.0
        return min(self.base_delay * (2 ** (n - 1)), self.max_delay)

    def exhausted(self, daemon_id: str) -> bool:
        now = self._clock()
        with self._lock:
            return len(self._prune_locked(daemon_id, now)) >= self.max_restarts

    def restarts_in_window(self, daemon_id: str) -> int:
        now = self._clock()
        with self._lock:
            return len(self._prune_locked(daemon_id, now))

    def reset(self, daemon_id: str) -> None:
        with self._lock:
            self._events.pop(daemon_id, None)
