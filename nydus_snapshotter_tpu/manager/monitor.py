"""Liveness monitor: epoll on daemon API sockets.

Reference pkg/manager/monitor.go:26-229: subscribe a connected unix socket
per daemon, watch EPOLLHUP/EPOLLERR edge-triggered; a hangup means the
daemon died — emit a death event on the notifier channel.
"""

from __future__ import annotations

import queue
import select
import socket
import threading
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class DeathEvent:
    daemon_id: str
    path: str


class LivenessMonitor:
    def __init__(self):
        self._epoll = select.epoll()
        self._lock = threading.Lock()
        self._socks: dict[int, tuple[str, str, socket.socket]] = {}  # fd -> (id, path, sock)
        self._by_id: dict[str, int] = {}
        self.events: "queue.Queue[DeathEvent]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False

    def subscribe(self, daemon_id: str, sock_path: str) -> None:
        """Connect to the daemon socket and watch for hangup
        (reference monitor.go:81-138). Exception-safe: a failed connect
        or register never leaks the socket fd."""
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(sock_path)
            s.setblocking(False)
            fd = s.fileno()
            with self._lock:
                if self._closed:
                    raise ValueError("monitor is stopped")
                if daemon_id in self._by_id:
                    self._unsubscribe_locked(daemon_id)
                self._epoll.register(fd, select.EPOLLHUP | select.EPOLLERR | select.EPOLLET)
                self._socks[fd] = (daemon_id, sock_path, s)
                self._by_id[daemon_id] = fd
        except BaseException:
            s.close()
            raise

    def unsubscribe(self, daemon_id: str) -> None:
        with self._lock:
            self._unsubscribe_locked(daemon_id)

    def _unsubscribe_locked(self, daemon_id: str) -> None:
        """Unregister from epoll AND close the socket — the single
        teardown used by explicit unsubscribe, death events, and stop(),
        so no path can leak a watched fd."""
        fd = self._by_id.pop(daemon_id, None)
        if fd is None:
            return
        try:
            self._epoll.unregister(fd)
        except (OSError, ValueError):
            pass  # fd already gone, or epoll already closed
        entry = self._socks.pop(fd, None)
        if entry is not None:
            entry[2].close()

    def run(self) -> None:
        """Event loop (reference monitor.go:191-229)."""
        with self._lock:
            if self._closed:
                raise ValueError("monitor is stopped")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                events = self._epoll.poll(timeout=0.2)
            except (OSError, ValueError):
                return
            for fd, event in events:
                if event & (select.EPOLLHUP | select.EPOLLERR):
                    with self._lock:
                        entry = self._socks.get(fd)
                        if entry is None:
                            continue
                        daemon_id, path, _ = entry
                        self._unsubscribe_locked(daemon_id)
                    self.events.put(DeathEvent(daemon_id=daemon_id, path=path))

    def stop(self) -> None:
        """Join the poll thread, drop every subscription, close the epoll
        fd. Idempotent: repeated setup/teardown in tests must not leak or
        double-close fds."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for daemon_id in list(self._by_id):
                self._unsubscribe_locked(daemon_id)
        self._epoll.close()
