"""Daemon manager: lifecycle, recovery policies, live upgrade.

Reference pkg/manager/{manager.go,daemon_adaptor.go,daemon_event.go}:
a per-fs-driver manager holding a store-backed daemon cache, wiring each
daemon into the liveness monitor, and reacting to death events according to
the recovery policy — ``restart`` respawns and re-mounts instances via the
API, ``failover`` replays the supervisor-held state/fds into a fresh daemon
via takeover (SURVEY §3.4). The same takeover dance powers live upgrade.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from nydus_snapshotter_tpu import constants, failpoint
from nydus_snapshotter_tpu.config.config import SnapshotterConfig
from nydus_snapshotter_tpu.daemon.daemon import ConfigState, Daemon
from nydus_snapshotter_tpu.daemon.types import DaemonState
from nydus_snapshotter_tpu.manager.budget import RestartBudget
from nydus_snapshotter_tpu.manager.monitor import DeathEvent, LivenessMonitor
from nydus_snapshotter_tpu.rafs.rafs import Rafs
from nydus_snapshotter_tpu.store.database import Database

logger = logging.getLogger(__name__)
from nydus_snapshotter_tpu.supervisor.supervisor import SupervisorSet
from nydus_snapshotter_tpu.utils import errdefs


class Manager:
    def __init__(
        self,
        cfg: SnapshotterConfig,
        database: Database,
        fs_driver: str = "",
        supervisor_set: Optional[SupervisorSet] = None,
    ):
        self.cfg = cfg
        self.db = database
        self.fs_driver = fs_driver or cfg.daemon.fs_driver
        self.recover_policy = cfg.daemon.recover_policy
        self._lock = threading.RLock()
        self._daemons: dict[str, Daemon] = {}
        self.monitor = LivenessMonitor()
        self.supervisors = supervisor_set or SupervisorSet(cfg.socket_root)
        self._event_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.on_death: Optional[Callable[[DeathEvent], None]] = None  # test hook
        self.cgroup_mgr = None  # optional pkg/cgroup Manager (daemon_adaptor.go:74-86)
        # Restart budget / circuit breaker: a crash-looping daemon gets
        # bounded respawns with backoff, then degrades to passthrough
        # instead of storming (knobs under [daemon] in the config TOML).
        dcfg = cfg.daemon
        self.restart_budget = RestartBudget(
            max_restarts=getattr(dcfg, "recover_max_restarts", 3),
            window=getattr(dcfg, "recover_window_secs", 60.0),
            base_delay=getattr(dcfg, "recover_backoff_secs", 0.5),
            max_delay=getattr(dcfg, "recover_backoff_max_secs", 8.0),
        )
        self.degraded: set[str] = set()
        self.on_degraded: Optional[Callable[[Daemon], None]] = None
        self._sleep: Callable[[float], None] = time.sleep

    # -- daemon book-keeping -------------------------------------------------

    def add_daemon(self, daemon: Daemon, persist: bool = True) -> None:
        with self._lock:
            if daemon.id in self._daemons:
                raise errdefs.AlreadyExists(f"daemon {daemon.id} already managed")
            self._daemons[daemon.id] = daemon
        if persist:
            self.db.save_daemon(daemon.id, daemon.states.to_dict())

    def update_daemon(self, daemon: Daemon) -> None:
        self.db.update_daemon(daemon.id, daemon.states.to_dict())

    def get_by_daemon_id(self, daemon_id: str) -> Optional[Daemon]:
        with self._lock:
            return self._daemons.get(daemon_id)

    def list_daemons(self) -> list[Daemon]:
        with self._lock:
            return list(self._daemons.values())

    def remove_daemon(self, daemon_id: str) -> None:
        with self._lock:
            self._daemons.pop(daemon_id, None)
        self.db.delete_daemon(daemon_id)

    # -- start/stop ----------------------------------------------------------

    def new_daemon(
        self,
        daemon_id: str,
        daemon_mode: str = "",
        use_supervisor: Optional[bool] = None,
    ) -> Daemon:
        """Allocate identity, sockets and workdir for a fresh daemon
        (reference daemon_adaptor.go:123-225 command/BuildDaemonCommand)."""
        os.makedirs(self.cfg.socket_root, exist_ok=True)
        workdir = os.path.join(self.cfg.root, "daemons", daemon_id)
        os.makedirs(workdir, exist_ok=True)
        if use_supervisor is None:
            use_supervisor = self.recover_policy == constants.RECOVER_POLICY_FAILOVER
        supervisor_path = ""
        if use_supervisor:
            supervisor_path = self.supervisors.new_supervisor(daemon_id).sock_path
        states = ConfigState(
            daemon_id=daemon_id,
            fs_driver=self.fs_driver,
            daemon_mode=daemon_mode or self.cfg.daemon_mode,
            api_socket=os.path.join(self.cfg.socket_root, f"{daemon_id}-api.sock"),
            log_file=os.path.join(workdir, "daemon.log"),
            workdir=workdir,
            supervisor_path=supervisor_path,
        )
        return Daemon(states)

    def start_daemon(self, daemon: Daemon, upgrade: bool = False) -> None:
        """Spawn + wait READY + subscribe liveness
        (reference daemon_adaptor.go:38-120)."""
        daemon.spawn(upgrade=upgrade)
        # Corral the daemon into the dedicated cgroup when one is managed
        # (daemon_adaptor.go:74-86).
        if self.cgroup_mgr is not None and daemon.pid:
            try:
                self.cgroup_mgr.add_proc(daemon.pid)
            except OSError as e:
                logger.warning("add daemon %s to cgroup: %s", daemon.id, e)
        daemon.client().wait_until_socket_exists()
        if not upgrade:
            daemon.wait_until_state(DaemonState.READY)
            daemon.start()
            daemon.wait_until_state(DaemonState.RUNNING)
        self.monitor.subscribe(daemon.id, daemon.states.api_socket)
        try:
            self.update_daemon(daemon)
        except errdefs.NotFound:
            pass

    def destroy_daemon(self, daemon: Daemon) -> None:
        """SIGTERM + reap + cleanup (reference manager.go:244-283)."""
        self.monitor.unsubscribe(daemon.id)
        try:
            daemon.exit()
        except (OSError, errdefs.NydusError, TimeoutError):
            pass
        daemon.terminate()
        daemon.wait()
        daemon.clear_vestige()
        self.supervisors.destroy(daemon.id)
        self.remove_daemon(daemon.id)
        self.restart_budget.reset(daemon.id)
        self.degraded.discard(daemon.id)

    # -- recovery ------------------------------------------------------------

    def recover(self) -> tuple[list[Daemon], list[Daemon]]:
        """Rebuild the daemon cache from the store after a snapshotter
        restart; split into still-live and dead daemons
        (reference manager.go:124-133, fs.go:124-193)."""
        live: list[Daemon] = []
        dead: list[Daemon] = []
        for state_dict in self.db.walk_daemons():
            states = ConfigState.from_dict(state_dict)
            if states.fs_driver != self.fs_driver:
                continue
            daemon = Daemon(states)
            self.add_daemon(daemon, persist=False)
            if daemon.state() in (DaemonState.RUNNING, DaemonState.READY):
                self.monitor.subscribe(daemon.id, states.api_socket)
                live.append(daemon)
            else:
                daemon.clear_vestige()
                dead.append(daemon)
        return live, dead

    # -- death events --------------------------------------------------------

    def run_death_handler(self) -> None:
        self.monitor.run()
        self._stop.clear()
        self._event_thread = threading.Thread(target=self._death_loop, daemon=True)
        self._event_thread.start()

    def _death_loop(self) -> None:
        import queue

        while not self._stop.is_set():
            try:
                event = self.monitor.events.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.handle_death_event(event)
            except Exception:  # keep the loop alive; error is logged
                import logging

                logging.getLogger(__name__).exception(
                    "death handling for %s failed", event.daemon_id
                )
            if self.on_death is not None:
                self.on_death(event)

    def handle_death_event(self, event: DeathEvent) -> None:
        """Dispatch per recovery policy (reference daemon_event.go:43-138),
        metered by the restart budget: bounded respawns with exponential
        backoff, then circuit-open degradation."""
        daemon = self.get_by_daemon_id(event.daemon_id)
        if daemon is None:
            return
        if self.recover_policy == constants.RECOVER_POLICY_NONE:
            return  # leave it dead
        if event.daemon_id in self.degraded:
            return  # circuit already open; no respawn
        delay = self.restart_budget.next_delay(event.daemon_id)
        if delay is None:
            self._degrade(daemon)
            return
        if delay > 0:
            logger.warning(
                "daemon %s died again; backing off %.2fs before respawn (%d/%d in window)",
                daemon.id, delay,
                self.restart_budget.restarts_in_window(daemon.id),
                self.restart_budget.max_restarts,
            )
            self._sleep(delay)
        if self.recover_policy == constants.RECOVER_POLICY_FAILOVER:
            self.do_daemon_failover(daemon)
        elif self.recover_policy == constants.RECOVER_POLICY_RESTART:
            self.do_daemon_restart(daemon)

    def is_degraded(self, daemon_id: str) -> bool:
        return daemon_id in self.degraded

    def _degrade(self, daemon: Daemon) -> None:
        """Circuit open: stop respawning, clean up the corpse, and serve
        what's on disk (nodev-style passthrough) instead of hot-looping
        on a daemon that cannot stay up."""
        logger.error(
            "daemon %s exhausted its restart budget (%d respawns/%.0fs); "
            "degrading to passthrough",
            daemon.id, self.restart_budget.max_restarts, self.restart_budget.window,
        )
        self.degraded.add(daemon.id)
        self.monitor.unsubscribe(daemon.id)
        daemon.clear_vestige()
        if self.on_degraded is not None:
            self.on_degraded(daemon)

    def do_daemon_failover(self, daemon: Daemon) -> None:
        """Supervisor-held state + fd replay into a fresh process
        (reference daemon_event.go:70-107): reap, wait for pushed state,
        respawn with --upgrade, takeover, start."""
        daemon.wait(timeout=5)
        sup = self.supervisors.get(daemon.id)
        if sup is None or not sup.wait_for_state(timeout=10):
            # No saved session — degrade to a plain restart.
            self.do_daemon_restart(daemon)
            return
        daemon.spawn(upgrade=True)
        daemon.client().wait_until_socket_exists()
        daemon.wait_until_state(DaemonState.INIT)
        daemon.takeover()
        daemon.wait_until_state(DaemonState.READY)
        daemon.start()
        daemon.wait_until_state(DaemonState.RUNNING)
        self.monitor.subscribe(daemon.id, daemon.states.api_socket)
        self.update_daemon(daemon)

    def do_daemon_restart(self, daemon: Daemon) -> None:
        """Respawn + re-mount every instance via the API
        (reference daemon_event.go:109-137)."""
        failpoint.hit("manager.restart")
        daemon.wait(timeout=5)
        daemon.clear_vestige()
        self.start_daemon(daemon)
        configs = {}
        for rafs in daemon.instances.list():
            config_path = os.path.join(daemon.states.workdir, f"{rafs.snapshot_id}.json")
            if os.path.exists(config_path):
                with open(config_path) as f:
                    configs[rafs.snapshot_id] = f.read()
        daemon.recover_rafs_instances(daemon.instances.list(), configs)

    # -- live upgrade --------------------------------------------------------

    def do_daemon_upgrade(self, daemon: Daemon) -> None:
        """Zero-downtime binary swap using the same sendfd/takeover dance
        (reference daemon_event.go:141-218)."""
        daemon.send_fd()
        try:
            daemon.exit()
        except (OSError, errdefs.NydusError):
            pass
        daemon.terminate()
        self.monitor.unsubscribe(daemon.id)
        daemon.wait(timeout=10)
        self.do_daemon_failover(daemon)

    def stop(self) -> None:
        self._stop.set()
        if self._event_thread is not None:
            self._event_thread.join(timeout=2)
        self.monitor.stop()
