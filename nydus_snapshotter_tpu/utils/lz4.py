"""LZ4 block codec via the system liblz4, with a pure-Python fallback.

lz4_block is the nydus default compressor (reference PackOption surface,
pkg/converter/types.go:62-66; passed as ``--compressor`` at
tool/builder.go:128-130). The environment ships no ``lz4`` Python module but
does ship ``liblz4.so.1``, so the fast path binds the three block-API symbols
with ctypes. When the library is absent the fallback still speaks the LZ4
block format: decompression is implemented in Python, and compression emits
a valid literals-only block (format-correct, ratio 1.0) — honest degradation
rather than a hard dependency.
"""

from __future__ import annotations

import ctypes
import ctypes.util


class LZ4Error(ValueError):
    pass


_LIB_CANDIDATES = ("liblz4.so.1", "liblz4.so", "liblz4.dylib")


def _load_lib():
    for name in _LIB_CANDIDATES:
        try:
            lib = ctypes.CDLL(name)
        except OSError:
            continue
        try:
            return _wrap(lib)
        except AttributeError:
            continue
    found = ctypes.util.find_library("lz4")
    if found:
        try:
            return _wrap(ctypes.CDLL(found))
        except (OSError, AttributeError):
            pass
    return None


def _wrap(lib):
    """Single home for the ctypes signatures (both load paths share it).

    src as c_void_p: accepts bytes directly AND raw addresses, so
    memoryview/ndarray chunks compress without a bytes() copy.
    """
    lib.LZ4_compress_default.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.LZ4_compress_default.restype = ctypes.c_int
    lib.LZ4_compress_fast.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.LZ4_compress_fast.restype = ctypes.c_int
    lib.LZ4_decompress_safe.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.LZ4_decompress_safe.restype = ctypes.c_int
    lib.LZ4_compressBound.argtypes = [ctypes.c_int]
    lib.LZ4_compressBound.restype = ctypes.c_int
    return lib


_lib = _load_lib()

_MAX_BLOCK = 0x7E000000  # LZ4_MAX_INPUT_SIZE

import threading as _threading

_tls = _threading.local()


def native_available() -> bool:
    return _lib is not None


def compress_block(data: "bytes | bytearray | memoryview", accel: int = 1) -> bytes:
    """LZ4 block compress (no frame header, like nydus per-chunk blocks).

    Accepts any contiguous buffer (memoryview chunk slices from the
    streaming packer compress without a bytes() copy). ``accel`` > 1 maps
    to LZ4_compress_fast (accel 1 is bit-identical to the default codec);
    the pure-Python fallback ignores it (literals-only either way).
    """
    size = len(data)
    if size > _MAX_BLOCK:
        raise LZ4Error(f"block of {size} bytes exceeds LZ4 max input size")
    if not size:
        return b""
    if _lib is None:
        return _compress_literals(bytes(data))
    if isinstance(data, bytes):
        src: "bytes | int" = data
    else:
        import numpy as np

        src = np.frombuffer(data, dtype=np.uint8).ctypes.data
    bound = _lib.LZ4_compressBound(size)
    # Reusable per-thread scratch: create_string_buffer zero-fills a fresh
    # allocation per call, which costs more than the compression itself on
    # 64 KiB chunks.
    dst = getattr(_tls, "scratch", None)
    if dst is None or ctypes.sizeof(dst) < bound:
        dst = ctypes.create_string_buffer(max(bound, 1 << 20))
        _tls.scratch = dst
    if accel > 1:
        n = _lib.LZ4_compress_fast(src, dst, size, bound, accel)
    else:
        n = _lib.LZ4_compress_default(src, dst, size, bound)
    if n <= 0:
        raise LZ4Error(f"LZ4 compress failed on {size}-byte block")
    return ctypes.string_at(dst, n)


def decompress_block(data: bytes, uncompressed_size: int) -> bytes:
    """LZ4 block decompress; the caller supplies the exact original size
    (stored in the chunk record, as nydus does — LZ4 blocks carry no size)."""
    if uncompressed_size == 0:
        if data:
            raise LZ4Error("non-empty block with zero uncompressed size")
        return b""
    if not data:
        raise LZ4Error("empty block with non-zero uncompressed size")
    if _lib is None:
        return _decompress_py(data, uncompressed_size)
    dst = ctypes.create_string_buffer(uncompressed_size)
    n = _lib.LZ4_decompress_safe(data, dst, len(data), uncompressed_size)
    if n < 0:
        raise LZ4Error("corrupt LZ4 block")
    if n != uncompressed_size:
        raise LZ4Error(f"LZ4 block decompressed to {n} bytes, expected {uncompressed_size}")
    return dst.raw[:n]


# ---------------------------------------------------------------------------
# Pure-Python fallback
# ---------------------------------------------------------------------------


def _compress_literals(data: bytes) -> bytes:
    """A valid LZ4 block containing only literal runs (the final sequence of
    a block legally omits the match part)."""
    out = bytearray()
    n = len(data)
    # One sequence: token literal nibble 15 + extension bytes, then literals.
    if n < 15:
        out.append(n << 4)
    else:
        out.append(0xF0)
        rem = n - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    out += data
    return bytes(out)


def _decompress_py(src: bytes, expected: int) -> bytes:
    out = bytearray()
    i = 0
    n = len(src)
    try:
        while i < n:
            token = src[i]
            i += 1
            lit = token >> 4
            if lit == 15:
                while True:
                    b = src[i]
                    i += 1
                    lit += b
                    if b != 255:
                        break
            if i + lit > n:
                raise LZ4Error("literal run overflows block")
            out += src[i : i + lit]
            i += lit
            if i >= n:
                break  # last sequence: literals only
            off = src[i] | (src[i + 1] << 8)
            i += 2
            if off == 0 or off > len(out):
                raise LZ4Error("match offset outside window")
            mlen = (token & 0xF) + 4
            if (token & 0xF) == 15:
                while True:
                    b = src[i]
                    i += 1
                    mlen += b
                    if b != 255:
                        break
            start = len(out) - off
            for k in range(mlen):  # byte-wise: matches may overlap themselves
                out.append(out[start + k])
    except IndexError as e:
        raise LZ4Error("truncated LZ4 block") from e
    if len(out) != expected:
        raise LZ4Error(f"LZ4 block decompressed to {len(out)} bytes, expected {expected}")
    return bytes(out)
