"""zstd codec via the SYSTEM libzstd, for cross-lane byte identity.

The reference's modern chunk compressor default is zstd (PackOption
surface, pkg/converter/types.go:62-66). This repo's pack paths hold a
byte-identity invariant across their arms (Python codec loop, fused
native section assembly, serial vs threaded) — but the ``zstandard``
package bundles its OWN libzstd, whose output can differ from the system
library the native engine dlopens (measured: a 1.3 MiB mixed chunk
compresses to 920,855 bytes under system 1.5.4 vs 921,118 under the
bundled build). So the Python compression lane binds the same system
``libzstd.so.1`` with ctypes; every arm then shares one codec and the
invariant holds by construction. Decompression stays on ``zstandard``
(any conforming frame decodes identically).

When the system library is absent, callers fall back to ``zstandard`` —
and the native engine's zstd arm is unavailable too (same dlopen), so
the lanes still agree with each other on any given host.
"""

from __future__ import annotations

import ctypes
import ctypes.util

from nydus_snapshotter_tpu.constants import ZSTD_LEVEL as LEVEL  # single source


class ZstdError(ValueError):
    pass


_LIB_CANDIDATES = ("libzstd.so.1", "libzstd.so", "libzstd.dylib")

_CONTENTSIZE_UNKNOWN = 2**64 - 1
_CONTENTSIZE_ERROR = 2**64 - 2


class _InBuffer(ctypes.Structure):
    _fields_ = [
        ("src", ctypes.c_void_p),
        ("size", ctypes.c_size_t),
        ("pos", ctypes.c_size_t),
    ]


class _OutBuffer(ctypes.Structure):
    _fields_ = [
        ("dst", ctypes.c_void_p),
        ("size", ctypes.c_size_t),
        ("pos", ctypes.c_size_t),
    ]


class _Api:
    # A CCtx is not concurrency-safe and each one holds a multi-MiB
    # workspace, so contexts live in a small bounded pool instead of
    # thread-locals: short-lived pool threads (the per-layer speculative
    # compression executors) would otherwise strand one leaked context
    # per dead thread. Contexts beyond the cap are freed immediately.
    # (The adaptive codec engine PINS one pooled context per compress
    # worker for its whole run — converter/codec.py — so the hot loop
    # pays neither the create nor the pool lock per chunk.)
    POOL_CAP = 8

    def __init__(self, lib: ctypes.CDLL):
        import threading

        lib.ZSTD_compressBound.restype = ctypes.c_size_t
        lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
        lib.ZSTD_isError.restype = ctypes.c_uint
        lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
        # Context-reuse lane: ZSTD_compressCCtx is documented to produce
        # the same output as one-shot ZSTD_compress at the same level,
        # without the per-call CCtx alloc/free.
        lib.ZSTD_createCCtx.restype = ctypes.c_void_p
        lib.ZSTD_freeCCtx.restype = ctypes.c_size_t
        lib.ZSTD_freeCCtx.argtypes = [ctypes.c_void_p]
        lib.ZSTD_compressCCtx.restype = ctypes.c_size_t
        lib.ZSTD_compressCCtx.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_int,
        ]
        self.lib = lib
        self._lock = threading.Lock()
        self._pool: list[int] = []
        # Decompress contexts: one-shot ZSTD_decompress allocates and
        # frees an internal DCtx per call — pooling them is the
        # decompress-path analog of the CCtx pool (lazy-read daemons
        # decode thousands of chunk frames per mount).
        self._dpool: list[int] = []
        self.dctx_reuses = 0
        self.dctx_creates = 0
        self.has_dctx = self._bind_dctx(lib)
        self.has_dict = self._bind_dict(lib)
        self.has_zdict = self._bind_zdict(lib)
        self.has_frames = self._bind_frames(lib)

    @staticmethod
    def _bind_dctx(lib) -> bool:
        try:
            lib.ZSTD_createDCtx.restype = ctypes.c_void_p
            lib.ZSTD_freeDCtx.restype = ctypes.c_size_t
            lib.ZSTD_freeDCtx.argtypes = [ctypes.c_void_p]
            lib.ZSTD_decompressDCtx.restype = ctypes.c_size_t
            lib.ZSTD_decompressDCtx.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t,
            ]
            lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
            lib.ZSTD_getFrameContentSize.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
            ]
        except AttributeError:
            return False
        return True

    @staticmethod
    def _bind_dict(lib) -> bool:
        """Digested-dictionary arms: CDict/DDict pre-process the trained
        dictionary ONCE, so per-chunk dict compression costs no dict load."""
        try:
            lib.ZSTD_createCDict.restype = ctypes.c_void_p
            lib.ZSTD_createCDict.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
            ]
            lib.ZSTD_freeCDict.restype = ctypes.c_size_t
            lib.ZSTD_freeCDict.argtypes = [ctypes.c_void_p]
            lib.ZSTD_compress_usingCDict.restype = ctypes.c_size_t
            lib.ZSTD_compress_usingCDict.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
            lib.ZSTD_createDDict.restype = ctypes.c_void_p
            lib.ZSTD_createDDict.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
            lib.ZSTD_freeDDict.restype = ctypes.c_size_t
            lib.ZSTD_freeDDict.argtypes = [ctypes.c_void_p]
            lib.ZSTD_decompress_usingDDict.restype = ctypes.c_size_t
            lib.ZSTD_decompress_usingDDict.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        except AttributeError:
            return False
        return True

    @staticmethod
    def _bind_frames(lib) -> bool:
        """Frame-walk + streaming surface for the seekable-zstd index
        (soci/zframe.py): per-frame compressed size without decoding,
        and a DStream decode for frames whose header omits the content
        size. ``ZSTD_isSkippableFrame`` is NOT bound — absent from older
        system builds (1.4.x) — the 4-byte magic check is done in
        Python instead."""
        try:
            lib.ZSTD_findFrameCompressedSize.restype = ctypes.c_size_t
            lib.ZSTD_findFrameCompressedSize.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
            ]
            # DStream == DCtx in every libzstd this binds, so the pooled
            # decompress contexts double as streaming decoders.
            lib.ZSTD_initDStream.restype = ctypes.c_size_t
            lib.ZSTD_initDStream.argtypes = [ctypes.c_void_p]
            lib.ZSTD_decompressStream.restype = ctypes.c_size_t
            lib.ZSTD_decompressStream.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(_OutBuffer),
                ctypes.POINTER(_InBuffer),
            ]
        except AttributeError:
            return False
        return True

    @staticmethod
    def _bind_zdict(lib) -> bool:
        try:
            lib.ZDICT_trainFromBuffer.restype = ctypes.c_size_t
            lib.ZDICT_trainFromBuffer.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_size_t), ctypes.c_uint,
            ]
            lib.ZDICT_isError.restype = ctypes.c_uint
            lib.ZDICT_isError.argtypes = [ctypes.c_size_t]
            lib.ZDICT_getDictID.restype = ctypes.c_uint
            lib.ZDICT_getDictID.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        except AttributeError:
            return False
        return True

    def acquire(self) -> int:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        ctx = self.lib.ZSTD_createCCtx()
        if not ctx:  # NULL on allocation failure — never hand it out
            raise ZstdError("ZSTD_createCCtx failed (out of memory)")
        return ctx

    def release(self, ctx: int) -> None:
        if not ctx:
            return  # never pool a NULL/failed context
        with self._lock:
            if len(self._pool) < self.POOL_CAP:
                self._pool.append(ctx)
                return
        self.lib.ZSTD_freeCCtx(ctx)

    def acquire_d(self) -> int:
        with self._lock:
            if self._dpool:
                self.dctx_reuses += 1
                return self._dpool.pop()
            self.dctx_creates += 1
        ctx = self.lib.ZSTD_createDCtx()
        if not ctx:
            raise ZstdError("ZSTD_createDCtx failed (out of memory)")
        return ctx

    def release_d(self, ctx: int) -> None:
        if not ctx:
            return
        with self._lock:
            if len(self._dpool) < self.POOL_CAP:
                self._dpool.append(ctx)
                return
        self.lib.ZSTD_freeDCtx(ctx)


def _load():
    for name in _LIB_CANDIDATES:
        try:
            return _Api(ctypes.CDLL(name))
        except (OSError, AttributeError):
            continue
    found = ctypes.util.find_library("zstd")
    if found:
        try:
            return _Api(ctypes.CDLL(found))
        except (OSError, AttributeError):
            pass
    return None


_API = _load()


def available() -> bool:
    """True when the system libzstd is bound (the native engine's zstd
    arm dlopens the same library, so availability matches)."""
    return _API is not None


def compress_block(data: bytes | memoryview, level: int = LEVEL) -> bytes:
    """One zstd frame via the system library — byte-identical to the
    native engine's per-chunk output (ZSTD_compressCCtx == one-shot
    ZSTD_compress at the same level, minus the per-call context cost)."""
    if _API is None:
        raise ZstdError("system libzstd not available")
    ctx = _API.acquire()
    try:
        return compress_with_ctx(ctx, data, level)
    finally:
        _API.release(ctx)


# ---------------------------------------------------------------------------
# Caller-owned contexts (per-worker reuse, converter/codec.py)
# ---------------------------------------------------------------------------


def cctx_acquire() -> int:
    """Take a compression context out of the pool for exclusive, pinned
    use (one per compress worker); return it with :func:`cctx_release`."""
    if _API is None:
        raise ZstdError("system libzstd not available")
    return _API.acquire()


def cctx_release(ctx: int) -> None:
    if _API is not None:
        _API.release(ctx)


def compress_with_ctx(ctx: int, data: bytes | memoryview, level: int = LEVEL) -> bytes:
    """One zstd frame on a caller-owned CCtx — the per-worker hot path:
    no context allocation, no pool lock. Output is byte-identical to
    :func:`compress_block` at the same level.

    This call is the byte-identity anchor for the native batched encode
    lane (chunk_engine's ``ntpu_encode_batch``, reached through
    ``ops.native_cdc.encode_batch_native``): both sides issue one-shot
    ``ZSTD_compressCCtx`` against the SAME dlopen'd system libzstd, so a
    batch of m chunks and m calls here cannot diverge frame-wise —
    differential-tested in tests/test_chunk_engine.py."""
    import numpy as np

    # zero-copy source: memoryview chunk slices of the tar buffer go
    # straight to libzstd (same contract as utils/lz4.compress_block)
    src = np.frombuffer(data, dtype=np.uint8)
    n = src.size
    cap = _API.lib.ZSTD_compressBound(n)
    buf = np.empty(cap, dtype=np.uint8)  # uninitialized: no bound memset
    w = _API.lib.ZSTD_compressCCtx(
        ctx, buf.ctypes.data, cap, src.ctypes.data, n, level
    )
    if _API.lib.ZSTD_isError(w):
        raise ZstdError(f"zstd compress failed for {n}-byte input")
    return buf[:w].tobytes()


# ---------------------------------------------------------------------------
# Trained dictionaries (ZDICT) + digested dict handles
# ---------------------------------------------------------------------------


def dict_support() -> bool:
    """True when the bound libzstd exposes the dictionary arms this
    module needs (ZDICT training + CDict/DDict digested handles)."""
    return _API is not None and _API.has_dict and _API.has_zdict and _API.has_dctx


def train_dict(samples: "list[bytes]", capacity_bytes: int) -> bytes:
    """ZDICT_trainFromBuffer over concatenated samples → dictionary bytes.

    Raises :class:`ZstdError` when training fails (too few / too uniform
    samples — callers fall back to untrained compression)."""
    if not dict_support():
        raise ZstdError("system libzstd lacks ZDICT support")
    if not samples:
        raise ZstdError("cannot train a dictionary from zero samples")
    import numpy as np

    joined = np.frombuffer(b"".join(samples), dtype=np.uint8)
    sizes = (ctypes.c_size_t * len(samples))(*[len(s) for s in samples])
    cap = max(1024, int(capacity_bytes))
    out = np.empty(cap, dtype=np.uint8)
    w = _API.lib.ZDICT_trainFromBuffer(
        out.ctypes.data, cap, joined.ctypes.data, sizes, len(samples)
    )
    if _API.lib.ZDICT_isError(w):
        raise ZstdError(
            f"ZDICT training failed over {len(samples)} samples "
            f"({joined.size} bytes)"
        )
    return out[:w].tobytes()


def dict_id_of(dict_bytes: bytes) -> int:
    """The dictionary's embedded ZDICT id (0 = not a ZDICT dictionary)."""
    if _API is None or not _API.has_zdict:
        raise ZstdError("system libzstd lacks ZDICT support")
    import numpy as np

    arr = np.frombuffer(dict_bytes, dtype=np.uint8)
    return int(_API.lib.ZDICT_getDictID(arr.ctypes.data, arr.size))


class CDict:
    """A digested compression dictionary at one level: the dictionary is
    pre-processed ONCE, so per-chunk dict compression pays no dict load."""

    def __init__(self, dict_bytes: bytes, level: int = LEVEL):
        import weakref

        import numpy as np

        if not dict_support():
            raise ZstdError("system libzstd lacks dictionary support")
        self._keep = np.frombuffer(dict_bytes, dtype=np.uint8)  # pin memory
        self.level = level
        self.handle = _API.lib.ZSTD_createCDict(
            self._keep.ctypes.data, self._keep.size, level
        )
        if not self.handle:
            raise ZstdError("ZSTD_createCDict failed")
        self._fin = weakref.finalize(self, _API.lib.ZSTD_freeCDict, self.handle)


class DDict:
    """A digested decompression dictionary (level-independent)."""

    def __init__(self, dict_bytes: bytes):
        import weakref

        import numpy as np

        if not dict_support():
            raise ZstdError("system libzstd lacks dictionary support")
        self._keep = np.frombuffer(dict_bytes, dtype=np.uint8)
        self.handle = _API.lib.ZSTD_createDDict(
            self._keep.ctypes.data, self._keep.size
        )
        if not self.handle:
            raise ZstdError("ZSTD_createDDict failed")
        self._fin = weakref.finalize(self, _API.lib.ZSTD_freeDDict, self.handle)


def compress_with_cdict(ctx: int, data: bytes | memoryview, cdict: CDict) -> bytes:
    """One dict-trained zstd frame on a caller-owned CCtx. The frame
    header carries the dictionary id, so decoding without the dictionary
    fails instead of producing garbage."""
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    n = src.size
    cap = _API.lib.ZSTD_compressBound(n)
    buf = np.empty(cap, dtype=np.uint8)
    w = _API.lib.ZSTD_compress_usingCDict(
        ctx, buf.ctypes.data, cap, src.ctypes.data, n, cdict.handle
    )
    if _API.lib.ZSTD_isError(w):
        raise ZstdError(f"zstd dict compress failed for {n}-byte input")
    return buf[:w].tobytes()


# ---------------------------------------------------------------------------
# Decompression (pooled DCtx)
# ---------------------------------------------------------------------------


def dctx_available() -> bool:
    return _API is not None and _API.has_dctx


def dctx_stats() -> dict:
    """Pool accounting for the decompress path ({'reuses', 'creates'}) —
    the profile tool's ctx-reuse micro-gate reads this."""
    if _API is None:
        return {"reuses": 0, "creates": 0}
    with _API._lock:
        return {"reuses": _API.dctx_reuses, "creates": _API.dctx_creates}


def _frame_capacity(src, n: int, max_output_size: int) -> int:
    size = _API.lib.ZSTD_getFrameContentSize(src.ctypes.data, n)
    if size == _CONTENTSIZE_ERROR:
        raise ZstdError("not a valid zstd frame")
    if size == _CONTENTSIZE_UNKNOWN:
        if max_output_size <= 0:
            raise ZstdError("could not determine content size in frame header")
        return max_output_size
    if 0 < max_output_size < int(size):
        # Same contract as the zstandard package: a frame whose declared
        # content exceeds the caller's bound is an error, not a big alloc.
        raise ZstdError(
            f"decompressed size {int(size)} would exceed max_output_size "
            f"{max_output_size}"
        )
    return max(int(size), 1)


def decompress_block(
    data: bytes | memoryview, max_output_size: int = 0, pooled: bool = True
) -> bytes:
    """One zstd frame → bytes via a pooled DCtx (``pooled=False`` forces
    a fresh context create+free per call — the micro-gate's baseline)."""
    if not dctx_available():
        raise ZstdError("system libzstd decompress contexts not available")
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    n = src.size
    if n == 0:
        raise ZstdError("empty zstd frame")
    cap = _frame_capacity(src, n, max_output_size)
    buf = np.empty(cap, dtype=np.uint8)
    if pooled:
        ctx = _API.acquire_d()
    else:
        ctx = _API.lib.ZSTD_createDCtx()
        if not ctx:
            raise ZstdError("ZSTD_createDCtx failed (out of memory)")
    try:
        w = _API.lib.ZSTD_decompressDCtx(
            ctx, buf.ctypes.data, cap, src.ctypes.data, n
        )
    finally:
        if pooled:
            _API.release_d(ctx)
        else:
            _API.lib.ZSTD_freeDCtx(ctx)
    if _API.lib.ZSTD_isError(w):
        raise ZstdError(f"zstd decompress failed for {n}-byte input")
    return buf[:w].tobytes()


def decompress_with_ddict(
    data: bytes | memoryview, ddict: DDict, max_output_size: int = 0
) -> bytes:
    """One dict-trained zstd frame → bytes (pooled DCtx + digested
    DDict). Raises when the frame needs a different dictionary."""
    if not dict_support():
        raise ZstdError("system libzstd lacks dictionary support")
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    n = src.size
    if n == 0:
        raise ZstdError("empty zstd frame")
    cap = _frame_capacity(src, n, max_output_size)
    buf = np.empty(cap, dtype=np.uint8)
    ctx = _API.acquire_d()
    try:
        w = _API.lib.ZSTD_decompress_usingDDict(
            ctx, buf.ctypes.data, cap, src.ctypes.data, n, ddict.handle
        )
    finally:
        _API.release_d(ctx)
    if _API.lib.ZSTD_isError(w):
        raise ZstdError(
            f"zstd dict decompress failed for {n}-byte input "
            "(wrong or missing dictionary?)"
        )
    return buf[:w].tobytes()


# ---------------------------------------------------------------------------
# Frame surface (seekable-zstd index, soci/zframe.py)
# ---------------------------------------------------------------------------

# Skippable-frame magic range: 0x184D2A50 .. 0x184D2A5F (little-endian on
# the wire). Checked by hand — ZSTD_isSkippableFrame is missing from the
# 1.4.x system builds this module must keep working against.
_SKIPPABLE_LO = 0x184D2A50
_SKIPPABLE_HI = 0x184D2A5F


def frames_available() -> bool:
    """True when the bound libzstd exposes the frame-walk + streaming
    surface (findFrameCompressedSize / decompressStream)."""
    return _API is not None and _API.has_frames and _API.has_dctx


def is_skippable_frame(data: bytes | memoryview, offset: int = 0) -> bool:
    """Pure-Python skippable-frame probe on the 4-byte magic at
    ``offset`` (no library call: older system builds lack the API)."""
    head = bytes(data[offset : offset + 4])
    if len(head) < 4:
        return False
    return _SKIPPABLE_LO <= int.from_bytes(head, "little") <= _SKIPPABLE_HI


def find_frame_compressed_size(data: bytes | memoryview, offset: int = 0) -> int:
    """Compressed size of the frame starting at ``offset`` — header,
    blocks and checksum — WITHOUT decoding it (skippable frames report
    their full on-wire size too). This is the frame-walk primitive: the
    whole blob's frame table falls out of repeated calls at each
    successive boundary."""
    if not frames_available():
        raise ZstdError("system libzstd lacks the frame surface")
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    if not 0 <= offset < src.size:
        raise ZstdError(f"frame offset {offset} outside {src.size}-byte blob")
    w = _API.lib.ZSTD_findFrameCompressedSize(
        src.ctypes.data + offset, src.size - offset
    )
    if _API.lib.ZSTD_isError(w):
        raise ZstdError(f"not a complete zstd frame at offset {offset}")
    return int(w)


def frame_content_size(data: bytes | memoryview, offset: int = 0):
    """Declared decompressed size of the frame at ``offset``, or ``None``
    when the header legitimately omits it (streaming-created frames;
    skippable frames report 0). Raises on a malformed header."""
    if _API is None or not _API.has_dctx:
        raise ZstdError("system libzstd not available")
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    if not 0 <= offset < src.size:
        raise ZstdError(f"frame offset {offset} outside {src.size}-byte blob")
    size = _API.lib.ZSTD_getFrameContentSize(
        src.ctypes.data + offset, src.size - offset
    )
    if size == _CONTENTSIZE_ERROR:
        raise ZstdError(f"not a valid zstd frame at offset {offset}")
    if size == _CONTENTSIZE_UNKNOWN:
        return None
    return int(size)


def stream_decompress(
    data: bytes | memoryview, max_output_size: int = 0
) -> bytes:
    """Streaming decode of one or more concatenated frames (skippable
    frames are skipped by the decoder) on a pooled context. This is the
    only decode that handles frames whose header omits the content size
    — the one-shot :func:`decompress_block` cannot size its buffer for
    those."""
    if not frames_available():
        raise ZstdError("system libzstd lacks the frame surface")
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    n = src.size
    if n == 0:
        return b""
    ctx = _API.acquire_d()
    out = bytearray()
    step = 1 << 17
    chunk = np.empty(step, dtype=np.uint8)
    try:
        w = _API.lib.ZSTD_initDStream(ctx)
        if _API.lib.ZSTD_isError(w):
            raise ZstdError("ZSTD_initDStream failed")
        ib = _InBuffer(src.ctypes.data, n, 0)
        while ib.pos < ib.size:
            ob = _OutBuffer(chunk.ctypes.data, step, 0)
            w = _API.lib.ZSTD_decompressStream(
                ctx, ctypes.byref(ob), ctypes.byref(ib)
            )
            if _API.lib.ZSTD_isError(w):
                raise ZstdError(
                    f"zstd stream decode failed at input byte {ib.pos}"
                )
            out += chunk[: ob.pos].tobytes()
            if max_output_size and len(out) > max_output_size:
                raise ZstdError(
                    f"decompressed stream exceeds max_output_size "
                    f"{max_output_size}"
                )
            if w == 0 and ib.pos >= ib.size:
                break
            if ob.pos == 0 and ib.pos >= ib.size and w != 0:
                raise ZstdError("truncated zstd frame (stream ended early)")
        if w != 0:
            raise ZstdError("truncated zstd frame (stream ended early)")
    except BaseException:
        # A context abandoned mid-frame must not rejoin the pool: the
        # next one-shot borrower would inherit its half-decoded state.
        _API.lib.ZSTD_freeDCtx(ctx)
        ctx = 0
        raise
    finally:
        _API.release_d(ctx)
    return bytes(out)


class StreamDecoder:
    """A held streaming decode cursor for sequential zstd reads.

    Unlike zlib's ``decompressobj`` a ZSTD_DCtx cannot be ``copy()``-ed,
    so the sequential fallback reader (converter/zstd_ref.py) keeps ONE
    forward cursor per blob: ``feed`` incremental compressed bytes, get
    whatever decompressed bytes they complete; ``reset`` rewinds to
    stream start (a full re-init — backward seeks re-decode from zero).
    Concatenated and skippable frames are handled by the decoder. The
    context comes from the pool and rejoins it on ``close`` after a
    clean re-init; a decode error frees it instead (never pool-poisons).
    """

    def __init__(self):
        if not frames_available():
            raise ZstdError("system libzstd lacks the frame surface")
        self._ctx = _API.acquire_d()
        self._init()

    def _init(self) -> None:
        w = _API.lib.ZSTD_initDStream(self._ctx)
        if _API.lib.ZSTD_isError(w):
            _API.lib.ZSTD_freeDCtx(self._ctx)
            self._ctx = 0
            raise ZstdError("ZSTD_initDStream failed")

    def reset(self) -> None:
        if not self._ctx:
            raise ZstdError("stream decoder is closed")
        self._init()

    def feed(self, data: bytes | memoryview) -> bytes:
        """Decode ``data`` (the next compressed bytes in stream order)
        and return every decompressed byte it completes."""
        if not self._ctx:
            raise ZstdError("stream decoder is closed")
        import numpy as np

        src = np.frombuffer(data, dtype=np.uint8)
        n = src.size
        if n == 0:
            return b""
        out = bytearray()
        step = 1 << 17
        chunk = np.empty(step, dtype=np.uint8)
        ib = _InBuffer(src.ctypes.data, n, 0)
        while True:
            ob = _OutBuffer(chunk.ctypes.data, step, 0)
            w = _API.lib.ZSTD_decompressStream(
                self._ctx, ctypes.byref(ob), ctypes.byref(ib)
            )
            if _API.lib.ZSTD_isError(w):
                _API.lib.ZSTD_freeDCtx(self._ctx)
                self._ctx = 0
                raise ZstdError(
                    f"zstd stream decode failed at input byte {ib.pos}"
                )
            out += chunk[: ob.pos].tobytes()
            if ib.pos >= ib.size and ob.pos < step:
                break
        return bytes(out)

    def close(self) -> None:
        ctx, self._ctx = self._ctx, 0
        if not ctx:
            return
        # Re-init before rejoining the pool so no borrower can inherit
        # mid-frame state; a failed init frees instead.
        w = _API.lib.ZSTD_initDStream(ctx)
        if _API.lib.ZSTD_isError(w):
            _API.lib.ZSTD_freeDCtx(ctx)
            return
        _API.release_d(ctx)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
