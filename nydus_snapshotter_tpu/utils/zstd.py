"""zstd codec via the SYSTEM libzstd, for cross-lane byte identity.

The reference's modern chunk compressor default is zstd (PackOption
surface, pkg/converter/types.go:62-66). This repo's pack paths hold a
byte-identity invariant across their arms (Python codec loop, fused
native section assembly, serial vs threaded) — but the ``zstandard``
package bundles its OWN libzstd, whose output can differ from the system
library the native engine dlopens (measured: a 1.3 MiB mixed chunk
compresses to 920,855 bytes under system 1.5.4 vs 921,118 under the
bundled build). So the Python compression lane binds the same system
``libzstd.so.1`` with ctypes; every arm then shares one codec and the
invariant holds by construction. Decompression stays on ``zstandard``
(any conforming frame decodes identically).

When the system library is absent, callers fall back to ``zstandard`` —
and the native engine's zstd arm is unavailable too (same dlopen), so
the lanes still agree with each other on any given host.
"""

from __future__ import annotations

import ctypes
import ctypes.util

from nydus_snapshotter_tpu.constants import ZSTD_LEVEL as LEVEL  # single source


class ZstdError(ValueError):
    pass


_LIB_CANDIDATES = ("libzstd.so.1", "libzstd.so", "libzstd.dylib")


class _Api:
    # A CCtx is not concurrency-safe and each one holds a multi-MiB
    # workspace, so contexts live in a small bounded pool instead of
    # thread-locals: short-lived pool threads (the per-layer speculative
    # compression executors) would otherwise strand one leaked context
    # per dead thread. Contexts beyond the cap are freed immediately.
    POOL_CAP = 8

    def __init__(self, lib: ctypes.CDLL):
        import threading

        lib.ZSTD_compressBound.restype = ctypes.c_size_t
        lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
        lib.ZSTD_isError.restype = ctypes.c_uint
        lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
        # Context-reuse lane: ZSTD_compressCCtx is documented to produce
        # the same output as one-shot ZSTD_compress at the same level,
        # without the per-call CCtx alloc/free.
        lib.ZSTD_createCCtx.restype = ctypes.c_void_p
        lib.ZSTD_freeCCtx.restype = ctypes.c_size_t
        lib.ZSTD_freeCCtx.argtypes = [ctypes.c_void_p]
        lib.ZSTD_compressCCtx.restype = ctypes.c_size_t
        lib.ZSTD_compressCCtx.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_int,
        ]
        self.lib = lib
        self._lock = threading.Lock()
        self._pool: list[int] = []

    def acquire(self) -> int:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        ctx = self.lib.ZSTD_createCCtx()
        if not ctx:  # NULL on allocation failure — never hand it out
            raise ZstdError("ZSTD_createCCtx failed (out of memory)")
        return ctx

    def release(self, ctx: int) -> None:
        if not ctx:
            return  # never pool a NULL/failed context
        with self._lock:
            if len(self._pool) < self.POOL_CAP:
                self._pool.append(ctx)
                return
        self.lib.ZSTD_freeCCtx(ctx)


def _load():
    for name in _LIB_CANDIDATES:
        try:
            return _Api(ctypes.CDLL(name))
        except (OSError, AttributeError):
            continue
    found = ctypes.util.find_library("zstd")
    if found:
        try:
            return _Api(ctypes.CDLL(found))
        except (OSError, AttributeError):
            pass
    return None


_API = _load()


def available() -> bool:
    """True when the system libzstd is bound (the native engine's zstd
    arm dlopens the same library, so availability matches)."""
    return _API is not None


def compress_block(data: bytes | memoryview, level: int = LEVEL) -> bytes:
    """One zstd frame via the system library — byte-identical to the
    native engine's per-chunk output (ZSTD_compressCCtx == one-shot
    ZSTD_compress at the same level, minus the per-call context cost)."""
    if _API is None:
        raise ZstdError("system libzstd not available")
    import numpy as np

    # zero-copy source: memoryview chunk slices of the tar buffer go
    # straight to libzstd (same contract as utils/lz4.compress_block)
    src = np.frombuffer(data, dtype=np.uint8)
    n = src.size
    cap = _API.lib.ZSTD_compressBound(n)
    buf = np.empty(cap, dtype=np.uint8)  # uninitialized: no bound memset
    ctx = _API.acquire()
    try:
        w = _API.lib.ZSTD_compressCCtx(
            ctx, buf.ctypes.data, cap, src.ctypes.data, n, level
        )
    finally:
        _API.release(ctx)
    if _API.lib.ZSTD_isError(w):
        raise ZstdError(f"zstd compress failed for {n}-byte input")
    return buf[:w].tobytes()
