"""Exponential-backoff retry (reference pkg/utils/retry/retry.go semantics:
bounded attempts, growing delay, last error surfaced), with optional
full-jitter and a wall-clock deadline so retries compose with per-request
HTTP timeouts instead of multiplying them.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Type, TypeVar

T = TypeVar("T")


class RetryError(Exception):
    def __init__(self, attempts: int, last: BaseException, deadline_exceeded: bool = False):
        why = " (deadline exceeded)" if deadline_exceeded else ""
        super().__init__(f"all {attempts} attempts failed{why}: {last}")
        self.attempts = attempts
        self.last = last
        self.deadline_exceeded = deadline_exceeded


def do(
    fn: Callable[[], T],
    attempts: int = 3,
    delay: float = 0.1,
    backoff: float = 2.0,
    max_delay: float = 5.0,
    retry_on: tuple[Type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    jitter: bool = False,
    deadline: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
    rng: Callable[[], float] = random.random,
) -> T:
    """Run fn with retries; raises RetryError wrapping the final failure.

    ``jitter`` applies full jitter — each pause is uniform in
    [0, computed delay] — so synchronized retry storms decorrelate.
    ``deadline`` is a wall-clock budget in seconds from the first attempt:
    no retry is started if its pause would overrun the budget (the retry
    loop then surfaces RetryError with ``deadline_exceeded`` set).
    Defaults leave both off, preserving historical behavior.
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    start = clock()
    cur = delay
    last: BaseException | None = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203
            last = e
            if i + 1 < attempts:
                pause = min(cur, max_delay)
                if jitter:
                    pause *= rng()
                if deadline is not None and (clock() - start) + pause >= deadline:
                    raise RetryError(i + 1, last, deadline_exceeded=True)
                sleep(pause)
                cur *= backoff
    raise RetryError(attempts, last)  # type: ignore[arg-type]


def do_with_deadline(
    fn: Callable[[], T],
    deadline: float,
    attempts: int = 3,
    delay: float = 0.1,
    backoff: float = 2.0,
    max_delay: float = 5.0,
    retry_on: tuple[Type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Callable[[], float] = random.random,
) -> T:
    """Deadline- and jitter-aware retry: the call-site default for
    transport and daemon-client retries (retries must fit inside the
    request timeout, not stack on top of it)."""
    return do(
        fn,
        attempts=attempts,
        delay=delay,
        backoff=backoff,
        max_delay=max_delay,
        retry_on=retry_on,
        sleep=sleep,
        jitter=True,
        deadline=deadline,
        clock=clock,
        rng=rng,
    )
