"""Exponential-backoff retry (reference pkg/utils/retry/retry.go semantics:
bounded attempts, growing delay, last error surfaced)."""

from __future__ import annotations

import time
from typing import Callable, Iterable, Type, TypeVar

T = TypeVar("T")


class RetryError(Exception):
    def __init__(self, attempts: int, last: BaseException):
        super().__init__(f"all {attempts} attempts failed: {last}")
        self.attempts = attempts
        self.last = last


def do(
    fn: Callable[[], T],
    attempts: int = 3,
    delay: float = 0.1,
    backoff: float = 2.0,
    max_delay: float = 5.0,
    retry_on: tuple[Type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run fn with retries; raises RetryError wrapping the final failure."""
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    cur = delay
    last: BaseException | None = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203
            last = e
            if i + 1 < attempts:
                sleep(min(cur, max_delay))
                cur *= backoff
    raise RetryError(attempts, last)  # type: ignore[arg-type]
