"""Host/system introspection (reference pkg/utils/sysinfo)."""

from __future__ import annotations

import os


def get_memory_bytes() -> int:
    """Total physical memory (sysinfo.go)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def get_kernel_version() -> str:
    return os.uname().release


def kernel_at_least(major: int, minor: int) -> bool:
    """e.g. fscache requires >= 5.19 (fs.go driver checks)."""
    parts = get_kernel_version().split(".")
    try:
        k_major, k_minor = int(parts[0]), int(parts[1].split("-")[0])
    except (ValueError, IndexError):
        return False
    return (k_major, k_minor) >= (major, minor)
