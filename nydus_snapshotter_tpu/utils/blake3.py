"""Pure-Python BLAKE3 (hash mode only) for RAFS metadata digests.

The reference toolchain's default digester is blake3 (RafsSuperFlags
HASH_BLAKE3 = 0x4; both committed fixtures under
/root/reference/pkg/filesystem/testdata carry it), so reading AND writing
real-layout bootstraps faithfully needs the algorithm. The environment
ships no `blake3` package, and the data-plane engines hash with SHA-256
(SHA-NI / Pallas), so this implementation only ever sees metadata-sized
inputs: inode digests over concatenated 32-byte child digests, symlink
targets, directory child lists. Pure Python is plenty there.

Implements the unkeyed hash with the full chunk/binary-tree structure
(chunks of 1024 bytes, largest-power-of-two left subtrees, ROOT
finalization), 32-byte output. Keyed mode / derive-key / XOF beyond 32
bytes are not needed by any caller and are omitted.

Validated in tests against the committed real v5 fixture's own digests
(empty input == the fixture's empty-dir digest, multi-chunk-list inputs
up to several KiB exercise the tree path) and by structural self-checks.
"""

from __future__ import annotations

_IV = (
    0x6A09E667,
    0xBB67AE85,
    0x3C6EF372,
    0xA54FF53A,
    0x510E527F,
    0x9B05688C,
    0x1F83D9AB,
    0x5BE0CD19,
)

_MSG_PERM = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

_CHUNK_START = 1 << 0
_CHUNK_END = 1 << 1
_PARENT = 1 << 2
_ROOT = 1 << 3

_BLOCK = 64
_CHUNK = 1024

_M32 = 0xFFFFFFFF


def _compress(cv, block_words, counter, block_len, flags):
    v = [
        cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
        _IV[0], _IV[1], _IV[2], _IV[3],
        counter & _M32, (counter >> 32) & _M32, block_len, flags,
    ]
    m = list(block_words)

    def g(a, b, c, d, mx, my):
        va = (v[a] + v[b] + mx) & _M32
        vd = v[d] ^ va
        vd = ((vd >> 16) | (vd << 16)) & _M32
        vc = (v[c] + vd) & _M32
        vb = v[b] ^ vc
        vb = ((vb >> 12) | (vb << 20)) & _M32
        va = (va + vb + my) & _M32
        vd = vd ^ va
        vd = ((vd >> 8) | (vd << 24)) & _M32
        vc = (vc + vd) & _M32
        vb = vb ^ vc
        vb = ((vb >> 7) | (vb << 25)) & _M32
        v[a], v[b], v[c], v[d] = va, vb, vc, vd

    for rnd in range(7):
        g(0, 4, 8, 12, m[0], m[1])
        g(1, 5, 9, 13, m[2], m[3])
        g(2, 6, 10, 14, m[4], m[5])
        g(3, 7, 11, 15, m[6], m[7])
        g(0, 5, 10, 15, m[8], m[9])
        g(1, 6, 11, 12, m[10], m[11])
        g(2, 7, 8, 13, m[12], m[13])
        g(3, 4, 9, 14, m[14], m[15])
        if rnd < 6:
            m = [m[p] for p in _MSG_PERM]

    return [
        v[0] ^ v[8], v[1] ^ v[9], v[2] ^ v[10], v[3] ^ v[11],
        v[4] ^ v[12], v[5] ^ v[13], v[6] ^ v[14], v[7] ^ v[15],
        v[8] ^ cv[0], v[9] ^ cv[1], v[10] ^ cv[2], v[11] ^ cv[3],
        v[12] ^ cv[4], v[13] ^ cv[5], v[14] ^ cv[6], v[15] ^ cv[7],
    ]


def _words(block: bytes):
    block = block.ljust(_BLOCK, b"\0")
    return [int.from_bytes(block[i : i + 4], "little") for i in range(0, _BLOCK, 4)]


def _chunk_output(chunk: bytes, counter: int):
    """(input_cv, last_block_words, counter, last_block_len, flags) of a
    <=1024-byte chunk — finalization deferred so the root can add ROOT."""
    blocks = [chunk[i : i + _BLOCK] for i in range(0, len(chunk), _BLOCK)] or [b""]
    cv = _IV
    for i, blk in enumerate(blocks[:-1]):
        flags = _CHUNK_START if i == 0 else 0
        cv = _compress(cv, _words(blk), counter, _BLOCK, flags)[:8]
    last = blocks[-1]
    flags = (_CHUNK_START if len(blocks) == 1 else 0) | _CHUNK_END
    return (cv, _words(last), counter, len(last), flags)


def _subtree_cv(data: bytes, counter: int):
    """Non-root 8-word chaining value of a subtree starting at chunk
    ``counter``."""
    if len(data) <= _CHUNK:
        cv, words, ctr, blen, flags = _chunk_output(data, counter)
        return _compress(cv, words, ctr, blen, flags)[:8]
    n_chunks = -(-len(data) // _CHUNK)
    left_chunks = 1 << (n_chunks - 1).bit_length() - 1
    split = left_chunks * _CHUNK
    left = _subtree_cv(data[:split], counter)
    right = _subtree_cv(data[split:], counter + left_chunks)
    return _compress(_IV, left + right, 0, _BLOCK, _PARENT)[:8]


def blake3(data: bytes) -> bytes:
    """32-byte BLAKE3 hash of ``data``."""
    if len(data) <= _CHUNK:
        cv, words, ctr, blen, flags = _chunk_output(data, 0)
        out = _compress(cv, words, ctr, blen, flags | _ROOT)
    else:
        n_chunks = -(-len(data) // _CHUNK)
        left_chunks = 1 << (n_chunks - 1).bit_length() - 1
        split = left_chunks * _CHUNK
        left = _subtree_cv(data[:split], 0)
        right = _subtree_cv(data[split:], left_chunks)
        out = _compress(_IV, left + right, 0, _BLOCK, _PARENT | _ROOT)
    return b"".join(w.to_bytes(4, "little") for w in out[:8])
