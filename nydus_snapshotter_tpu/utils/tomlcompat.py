"""TOML parser compatibility: stdlib ``tomllib`` (3.11+) with a fallback
to the API-identical ``tomli`` backport on older interpreters.

Import ``tomllib`` from here instead of directly — a missing stdlib
module must degrade to the baked-in backport, not take the whole config
layer (and everything importing it) down with an ImportError.
"""

try:  # pragma: no cover - which branch runs depends on the interpreter
    import tomllib  # noqa: F401
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib  # noqa: F401

__all__ = ["tomllib"]
