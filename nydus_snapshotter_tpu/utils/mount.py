"""mount(2)/umount(2) helpers + erofs mount (reference pkg/utils/mount,
pkg/utils/erofs).

A module-level ``backend`` hook lets tests substitute a fake mounter; the
real one shells to mount(8)/umount(8) (python has no stable mount(2)
binding and the snapshotter runs as root anyway, mirroring how
cmd/nydus-overlayfs execs mount).
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import time

from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)


class CliMounter:
    def mount(self, source: str, target: str, fstype: str, options: str = "") -> None:
        cmd = ["mount", "-t", fstype]
        if options:
            cmd += ["-o", options]
        cmd += [source, target]
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise errdefs.Unavailable(
                f"mount -t {fstype} {source} {target} failed: {r.stderr.strip()}"
            )

    def umount(self, target: str, flags: int = 0) -> None:
        cmd = ["umount"]
        if flags:  # MNT_FORCE-ish
            cmd.append("-f")
        cmd.append(target)
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise errdefs.Unavailable(f"umount {target} failed: {r.stderr.strip()}")


backend = CliMounter()


def mount(source: str, target: str, fstype: str, options: str = "") -> None:
    os.makedirs(target, exist_ok=True)
    backend.mount(source, target, fstype, options)


def umount(target: str) -> None:
    backend.umount(target)


def is_mountpoint(path: str) -> bool:
    return os.path.ismount(path)


def wait_until_unmounted(path: str, timeout: float = 10.0, interval: float = 0.1) -> None:
    """Poll until ``path`` stops being a mountpoint
    (mount.go WaitUntilUnmounted)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not os.path.ismount(path):
            return
        time.sleep(interval)
    raise errdefs.Unavailable(f"{path} still mounted after {timeout}s")


# -- erofs (pkg/utils/erofs/erofs.go) ----------------------------------------


def erofs_fscache_id(snapshot_id: str) -> str:
    """fscache domain ID for a snapshot: sha256("nydus-snapshot-<id>")
    (erofs.go:46)."""
    return hashlib.sha256(f"nydus-snapshot-{snapshot_id}".encode()).hexdigest()


def erofs_mount(bootstrap_path: str, domain_id: str, fscache_id: str, mountpoint: str) -> None:
    """Mount an EROFS image backed by fscache (erofs.go:18-44)."""
    opts = f"domain_id={domain_id},fsid={fscache_id}"
    mount(bootstrap_path, mountpoint, "erofs", opts)


def erofs_umount(mountpoint: str) -> None:
    umount(mountpoint)
