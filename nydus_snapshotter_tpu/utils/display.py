"""Human-readable formatting helpers (reference pkg/utils/display)."""

from __future__ import annotations


def byte_to_readable_iec(n: int) -> str:
    """1536 -> \"1.5 KiB\" (display.go ByteToReadableIEC)."""
    if n < 1024:
        return f"{n} B"
    value = float(n)
    for unit in ("KiB", "MiB", "GiB", "TiB", "PiB", "EiB"):
        value /= 1024.0
        if value < 1024.0:
            return f"{value:.1f} {unit}"
    return f"{value:.1f} ZiB"


def microsecond_to_readable(us: int) -> str:
    """1500000 -> \"1.5 s\" (display.go MicroSecondToReadable)."""
    if us < 1000:
        return f"{us} us"
    if us < 1000_000:
        return f"{us / 1000.0:.1f} ms"
    return f"{us / 1000_000.0:.1f} s"
