"""Signal-driven shutdown helper (reference pkg/utils/signals)."""

from __future__ import annotations

import signal
import threading
from typing import Callable, Iterable

_DEFAULT = (signal.SIGTERM, signal.SIGINT)


def setup_signal_handler(
    stop: threading.Event, extra: Iterable[int] = (), on_signal: Callable[[int], None] = None
) -> None:
    """Set ``stop`` when a termination signal arrives
    (signals.go SetupSignalHandler)."""

    def handler(signum, _frame):
        if on_signal is not None:
            on_signal(signum)
        stop.set()

    for sig in (*_DEFAULT, *extra):
        signal.signal(sig, handler)
