"""Loop-device attach/detach (reference tarfs.go:754-760 via go-losetup).

Implemented against the kernel loop-control API directly (LOOP_CTL_GET_FREE
+ LOOP_CONFIGURE/LOOP_SET_FD), with a ``losetup(8)`` CLI fallback. All entry
points honor a module-level ``backend`` hook so unit tests can substitute a
fake (mounting needs root, CI has none).
"""

from __future__ import annotations

import fcntl
import os
import struct
import subprocess
from dataclasses import dataclass
from typing import Optional

from nydus_snapshotter_tpu.utils import errdefs

LOOP_CTL_GET_FREE = 0x4C82
LOOP_SET_FD = 0x4C00
LOOP_CLR_FD = 0x4C01
LOOP_SET_STATUS64 = 0x4C04
LOOP_CONTROL = "/dev/loop-control"

LO_FLAGS_READ_ONLY = 1
LO_FLAGS_AUTOCLEAR = 4


@dataclass
class LoopDevice:
    index: int

    @property
    def path(self) -> str:
        return f"/dev/loop{self.index}"

    def detach(self) -> None:
        backend.detach(self)


class KernelBackend:
    """ioctl-based loop management (what go-losetup does)."""

    def attach(self, blob_path: str, offset: int = 0, ro: bool = True) -> LoopDevice:
        with open(LOOP_CONTROL, "rb") as ctl:
            index = fcntl.ioctl(ctl.fileno(), LOOP_CTL_GET_FREE)
        dev = LoopDevice(index)
        flags = os.O_RDONLY if ro else os.O_RDWR
        blob_fd = os.open(blob_path, flags)
        try:
            dev_fd = os.open(dev.path, flags)
            try:
                fcntl.ioctl(dev_fd, LOOP_SET_FD, blob_fd)
                # struct loop_info64: lo_device@0, lo_inode@8, lo_rdevice@16,
                # lo_offset@24, ..., lo_flags@52, lo_file_name@56
                info = bytearray(232)
                struct.pack_into("<Q", info, 24, offset)  # lo_offset
                struct.pack_into(
                    "<I", info, 52, LO_FLAGS_READ_ONLY if ro else 0
                )  # lo_flags
                name = blob_path.encode()[:63]
                info[56 : 56 + len(name)] = name  # lo_file_name
                fcntl.ioctl(dev_fd, LOOP_SET_STATUS64, bytes(info))
            finally:
                os.close(dev_fd)
        finally:
            os.close(blob_fd)
        return dev

    def detach(self, dev: LoopDevice) -> None:
        fd = os.open(dev.path, os.O_RDONLY)
        try:
            fcntl.ioctl(fd, LOOP_CLR_FD, 0)
        finally:
            os.close(fd)


class CliBackend:
    """losetup(8) fallback."""

    def attach(self, blob_path: str, offset: int = 0, ro: bool = True) -> LoopDevice:
        cmd = ["losetup", "--find", "--show"]
        if ro:
            cmd.append("--read-only")
        if offset:
            cmd += ["--offset", str(offset)]
        cmd.append(blob_path)
        out = subprocess.run(cmd, capture_output=True, text=True, check=True)
        path = out.stdout.strip()
        if not path.startswith("/dev/loop"):
            raise errdefs.Unavailable(f"losetup returned {path!r}")
        return LoopDevice(int(path[len("/dev/loop") :]))

    def detach(self, dev: LoopDevice) -> None:
        subprocess.run(["losetup", "--detach", dev.path], check=True)


backend = KernelBackend()


def attach(blob_path: str, offset: int = 0, ro: bool = True) -> LoopDevice:
    """Attach ``blob_path`` to a free loop device (thread-safety is the
    caller's job — reference holds mutexLoopDev, tarfs.go:754-760)."""
    try:
        return backend.attach(blob_path, offset=offset, ro=ro)
    except (PermissionError, FileNotFoundError) as e:
        raise errdefs.Unavailable(f"loop attach of {blob_path} failed: {e}") from e


def detach(dev: LoopDevice) -> None:
    try:
        backend.detach(dev)
    except (PermissionError, FileNotFoundError) as e:
        raise errdefs.Unavailable(f"loop detach of {dev.path} failed: {e}") from e
