"""Loop-device attach/detach (reference tarfs.go:754-760 via go-losetup).

Implemented against the kernel loop-control API directly (LOOP_CTL_GET_FREE
+ LOOP_CONFIGURE/LOOP_SET_FD), with a ``losetup(8)`` CLI fallback. All entry
points honor a module-level ``backend`` hook so unit tests can substitute a
fake (mounting needs root, CI has none).
"""

from __future__ import annotations

import fcntl
import os
import struct
import subprocess
from dataclasses import dataclass
from typing import Optional

from nydus_snapshotter_tpu.utils import errdefs

LOOP_CTL_GET_FREE = 0x4C82
LOOP_SET_FD = 0x4C00
LOOP_CLR_FD = 0x4C01
LOOP_SET_STATUS64 = 0x4C04
LOOP_GET_STATUS64 = 0x4C05
LOOP_CONTROL = "/dev/loop-control"

LO_FLAGS_READ_ONLY = 1
LO_FLAGS_AUTOCLEAR = 4


@dataclass
class LoopDevice:
    index: int

    @property
    def path(self) -> str:
        return f"/dev/loop{self.index}"

    def detach(self) -> None:
        backend.detach(self)


class KernelBackend:
    """ioctl-based loop management (what go-losetup does)."""

    def attach(
        self, blob_path: str, offset: int = 0, ro: bool = True,
    ) -> LoopDevice:
        with open(LOOP_CONTROL, "rb") as ctl:
            index = fcntl.ioctl(ctl.fileno(), LOOP_CTL_GET_FREE)
        dev = LoopDevice(index)
        flags = os.O_RDONLY if ro else os.O_RDWR
        blob_fd = os.open(blob_path, flags)
        try:
            dev_fd = os.open(dev.path, flags)
            try:
                fcntl.ioctl(dev_fd, LOOP_SET_FD, blob_fd)
                # struct loop_info64: lo_device@0, lo_inode@8, lo_rdevice@16,
                # lo_offset@24, ..., lo_flags@52, lo_file_name@56
                info = bytearray(232)
                struct.pack_into("<Q", info, 24, offset)  # lo_offset
                struct.pack_into(
                    "<I", info, 52, LO_FLAGS_READ_ONLY if ro else 0
                )  # lo_flags
                name = blob_path.encode()[:63]
                info[56 : 56 + len(name)] = name  # lo_file_name
                fcntl.ioctl(dev_fd, LOOP_SET_STATUS64, bytes(info))
            finally:
                os.close(dev_fd)
        finally:
            os.close(blob_fd)
        return dev

    def detach(self, dev: LoopDevice) -> None:
        import errno

        try:
            fd = os.open(dev.path, os.O_RDONLY)
        except OSError as e:
            if e.errno == errno.ENXIO:
                return  # already gone
            raise
        try:
            fcntl.ioctl(fd, LOOP_CLR_FD, 0)
        except OSError as e:
            # ENXIO: the device is already unbound — the kernel reaped it
            # via AUTOCLEAR when its mount went away. Idempotent success.
            if e.errno != errno.ENXIO:
                raise
        finally:
            os.close(fd)

    def backing_file(self, dev: LoopDevice) -> Optional[str]:
        """Path currently backing the device (sysfs: full, unlike
        lo_file_name's 63-byte truncation); None when unbound."""
        try:
            with open(f"/sys/block/loop{dev.index}/loop/backing_file") as f:
                return f.read().strip()
        except OSError:
            return None

    def set_autoclear(self, dev: LoopDevice) -> None:
        """Flag AUTOCLEAR on an attached device. MUST be called after a
        durable user (the erofs mount) holds the device: autoclear fires
        when the last reference drops, so setting it at attach time —
        before any mount — detaches the loop the moment the setup fd
        closes. Post-mount, the kernel reaps the loop exactly when the
        mount goes away, so crash-restarted snapshotters that unmount by
        path never strand a bound device."""
        fd = os.open(dev.path, os.O_RDONLY)
        try:
            info = bytearray(232)
            fcntl.ioctl(fd, LOOP_GET_STATUS64, info)
            flags = struct.unpack_from("<I", info, 52)[0]
            struct.pack_into("<I", info, 52, flags | LO_FLAGS_AUTOCLEAR)
            fcntl.ioctl(fd, LOOP_SET_STATUS64, bytes(info))
        finally:
            os.close(fd)


class CliBackend:
    """losetup(8) fallback."""

    def attach(
        self, blob_path: str, offset: int = 0, ro: bool = True,
    ) -> LoopDevice:
        cmd = ["losetup", "--find", "--show"]
        if ro:
            cmd.append("--read-only")
        if offset:
            cmd += ["--offset", str(offset)]
        cmd.append(blob_path)
        out = subprocess.run(cmd, capture_output=True, text=True, check=True)
        path = out.stdout.strip()
        if not path.startswith("/dev/loop"):
            raise errdefs.Unavailable(f"losetup returned {path!r}")
        return LoopDevice(int(path[len("/dev/loop") :]))

    def detach(self, dev: LoopDevice) -> None:
        subprocess.run(["losetup", "--detach", dev.path], check=True)


backend = KernelBackend()


def attach(
    blob_path: str, offset: int = 0, ro: bool = True
) -> LoopDevice:
    """Attach ``blob_path`` to a free loop device (thread-safety is the
    caller's job — reference holds mutexLoopDev, tarfs.go:754-760)."""
    try:
        return backend.attach(blob_path, offset=offset, ro=ro)
    except (PermissionError, FileNotFoundError) as e:
        raise errdefs.Unavailable(f"loop attach of {blob_path} failed: {e}") from e


def set_autoclear(dev: LoopDevice) -> None:
    """Best-effort post-mount AUTOCLEAR (see KernelBackend.set_autoclear);
    silently skipped on backends without the capability."""
    fn = getattr(backend, "set_autoclear", None)
    if fn is None:
        return
    try:
        fn(dev)
    except OSError:
        pass


def still_backed_by(dev: LoopDevice, path: str) -> bool:
    """Whether the device is still bound to ``path``.

    With AUTOCLEAR, loop lifetime belongs to the KERNEL: the device may
    have been reaped when its mount went away and even re-bound to an
    unrelated file by a later LOOP_CTL_GET_FREE. Any cached handle must
    be validated before reuse (or a mount would read the wrong backing
    file) and before detach (or LOOP_CLR_FD would land on someone else's
    live binding). Backends without introspection (test fakes) return
    "unknown" and the handle is trusted, preserving their semantics.
    """
    fn = getattr(backend, "backing_file", None)
    if fn is None:
        return True  # unknown: trust the handle (non-autoclear backends)
    try:
        bf = fn(dev)
    except OSError:
        return False
    if bf is None:
        return False  # definitely unbound
    bf = bf.removesuffix(" (deleted)")
    return bf == path or bf == os.path.realpath(path)


def detach(dev: LoopDevice) -> None:
    try:
        backend.detach(dev)
    except (PermissionError, FileNotFoundError) as e:
        raise errdefs.Unavailable(f"loop detach of {dev.path} failed: {e}") from e
