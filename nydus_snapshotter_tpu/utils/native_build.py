"""On-demand builder for the in-tree C++ targets (native/bin/*).

Build artifacts are git-ignored, so a fresh checkout has none; consumers
(ops/native_cdc.py for libchunk_engine.so, fanotify/server.py for
optimizer-server) call :func:`ensure_built` on first use. Discipline:

- build into a private temp dir and land via atomic ``os.replace`` so a
  concurrent process never dlopens/execs a half-written file;
- refuse nothing here — staleness policy is the caller's (native_cdc
  refuses a stale .so; a stale tracer binary is rebuilt below);
- remember build FAILURES on disk keyed on source mtimes, so other
  processes degrade instantly instead of each re-paying a doomed
  compile. The marker carries the compiler's stderr after the stamp
  line, so :func:`failure_reason` can tell callers WHY the library is
  unbuildable even when this process never ran the compile. Post-build
  filesystem errors leave no memo: the toolchain works, the next
  process should simply retry.
"""

from __future__ import annotations

import os
import shutil
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")

# Compiler stderr kept in the failure memo: enough for the first errors,
# bounded so a pathological template spew cannot bloat the marker.
_MEMO_STDERR_CAP = 8192


def src_stamp(src_subdir: str) -> str:
    """Newest source mtime under native/<src_subdir> ('' when unreadable)."""
    src_dir = os.path.join(_NATIVE_DIR, src_subdir)
    try:
        return str(
            max(os.path.getmtime(os.path.join(src_dir, f)) for f in os.listdir(src_dir))
        )
    except (OSError, ValueError):
        return ""


def target_path(target: str) -> str:
    return os.path.join(_NATIVE_DIR, "bin", target)


def _marker_path(target: str) -> str:
    return os.path.join(_NATIVE_DIR, "bin", f".build_failed.{target}")


def sources_newer(target: str, src_subdir: str) -> bool:
    stamp = src_stamp(src_subdir)
    try:
        return bool(stamp) and float(stamp) > os.path.getmtime(target_path(target))
    except OSError:
        return False


def failure_reason(target: str) -> str:
    """The memoized compiler error for ``target`` ('' when there is no
    failure memo). First line of the marker is the source stamp; the rest
    is the captured stderr of the failed compile — possibly from another
    process entirely, which is the point: repeat callers get the WHY
    without re-paying the doomed compile."""
    try:
        with open(_marker_path(target)) as fp:
            memo = fp.read()
    except OSError:
        return ""
    _stamp, _nl, stderr = memo.partition("\n")
    return stderr.strip()


def ensure_built(target: str, src_subdir: str) -> bool:
    """Build native/bin/<target> if missing or stale. True when the
    artifact is present and current afterwards."""
    path = target_path(target)
    if os.path.exists(path) and not sources_newer(target, src_subdir):
        return True
    marker = _marker_path(target)
    stamp = src_stamp(src_subdir)
    try:
        with open(marker) as fp:
            if fp.read().partition("\n")[0] == stamp:
                return False  # this exact source state already failed
    except OSError:
        pass
    if not shutil.which("make") or not shutil.which("g++"):
        return False
    tmp = f"bin.build.{target}.{os.getpid()}"
    try:
        stderr = ""
        try:
            proc = subprocess.run(
                ["make", "-C", _NATIVE_DIR, f"{tmp}/{target}", f"BIN_DIR={tmp}"],
                capture_output=True,
                timeout=120,
            )
            ok = proc.returncode == 0
            if not ok:
                stderr = proc.stderr.decode("utf-8", "replace")[:_MEMO_STDERR_CAP]
        except (OSError, subprocess.TimeoutExpired) as e:
            ok = False
            stderr = f"{type(e).__name__}: {e}"[:_MEMO_STDERR_CAP]
        if not ok:
            try:
                os.makedirs(os.path.dirname(marker), exist_ok=True)
                with open(marker, "w") as fp:
                    fp.write(stamp + "\n" + stderr)
            except OSError:
                pass
            return False
        os.makedirs(os.path.dirname(path), exist_ok=True)
        os.replace(os.path.join(_NATIVE_DIR, tmp, target), path)
        try:
            os.unlink(marker)
        except OSError:
            pass
        return True
    except OSError:
        return False
    finally:
        shutil.rmtree(os.path.join(_NATIVE_DIR, tmp), ignore_errors=True)
