"""On-demand builder for the in-tree C++ targets (native/bin/*).

Build artifacts are git-ignored, so a fresh checkout has none; consumers
(ops/native_cdc.py for libchunk_engine.so, fanotify/server.py for
optimizer-server) call :func:`ensure_built` on first use. Discipline:

- build into a private temp dir and land via atomic ``os.replace`` so a
  concurrent process never dlopens/execs a half-written file;
- refuse nothing here — staleness policy is the caller's (native_cdc
  refuses a stale .so; a stale tracer binary is rebuilt below);
- remember build FAILURES on disk keyed on source mtimes, so other
  processes degrade instantly instead of each re-paying a doomed
  compile. Post-build filesystem errors leave no memo: the toolchain
  works, the next process should simply retry.
"""

from __future__ import annotations

import os
import shutil
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")


def src_stamp(src_subdir: str) -> str:
    """Newest source mtime under native/<src_subdir> ('' when unreadable)."""
    src_dir = os.path.join(_NATIVE_DIR, src_subdir)
    try:
        return str(
            max(os.path.getmtime(os.path.join(src_dir, f)) for f in os.listdir(src_dir))
        )
    except (OSError, ValueError):
        return ""


def target_path(target: str) -> str:
    return os.path.join(_NATIVE_DIR, "bin", target)


def sources_newer(target: str, src_subdir: str) -> bool:
    stamp = src_stamp(src_subdir)
    try:
        return bool(stamp) and float(stamp) > os.path.getmtime(target_path(target))
    except OSError:
        return False


def ensure_built(target: str, src_subdir: str) -> bool:
    """Build native/bin/<target> if missing or stale. True when the
    artifact is present and current afterwards."""
    path = target_path(target)
    if os.path.exists(path) and not sources_newer(target, src_subdir):
        return True
    marker = os.path.join(_NATIVE_DIR, "bin", f".build_failed.{target}")
    stamp = src_stamp(src_subdir)
    try:
        with open(marker) as fp:
            if fp.read() == stamp:
                return False  # this exact source state already failed
    except OSError:
        pass
    if not shutil.which("make") or not shutil.which("g++"):
        return False
    tmp = f"bin.build.{target}.{os.getpid()}"
    try:
        try:
            ok = (
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR, f"{tmp}/{target}", f"BIN_DIR={tmp}"],
                    capture_output=True,
                    timeout=120,
                ).returncode
                == 0
            )
        except (OSError, subprocess.TimeoutExpired):
            ok = False
        if not ok:
            try:
                os.makedirs(os.path.dirname(marker), exist_ok=True)
                with open(marker, "w") as fp:
                    fp.write(stamp)
            except OSError:
                pass
            return False
        os.makedirs(os.path.dirname(path), exist_ok=True)
        os.replace(os.path.join(_NATIVE_DIR, tmp, target), path)
        try:
            os.unlink(marker)
        except OSError:
            pass
        return True
    except OSError:
        return False
    finally:
        shutil.rmtree(os.path.join(_NATIVE_DIR, tmp), ignore_errors=True)
