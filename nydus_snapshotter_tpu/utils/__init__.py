"""Shared utilities: retry, error predicates, transport helpers."""
