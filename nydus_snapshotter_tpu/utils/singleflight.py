"""Duplicate-call suppression (golang.org/x/sync/singleflight semantics).

Concurrent callers with the same key share one in-flight execution and all
receive its result (or its exception). Used by the referrer and tarfs
managers exactly like the reference (pkg/referrer/manager.go:26,
pkg/tarfs/tarfs.go singleflight use).
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class _Call:
    def __init__(self):
        self.done = threading.Event()
        self.result: Any = None
        self.err: BaseException | None = None


class Group:
    def __init__(self):
        self._mu = threading.Lock()
        self._calls: dict[str, _Call] = {}

    def do(self, key: str, fn: Callable[[], Any]) -> tuple[Any, bool]:
        """Run ``fn`` once per concurrent ``key``; returns (result, shared)
        where ``shared`` says this caller piggybacked on another's flight."""
        with self._mu:
            call = self._calls.get(key)
            if call is not None:
                leader = False
            else:
                call = _Call()
                self._calls[key] = call
                leader = True
        if not leader:
            call.done.wait()
            if call.err is not None:
                raise call.err
            return call.result, True
        try:
            call.result = fn()
            return call.result, False
        except BaseException as e:
            call.err = e
            raise
        finally:
            with self._mu:
                self._calls.pop(key, None)
            call.done.set()
