"""Error types + predicates (reference pkg/errdefs/errors.go)."""

from __future__ import annotations

import errno


class NydusError(Exception):
    """Base class for framework errors."""


class AlreadyExists(NydusError):
    pass


class NotFound(NydusError):
    pass


class ConnectionClosed(NydusError):
    pass


class InvalidArgument(NydusError):
    pass


class Unavailable(NydusError):
    pass


class FailedPrecondition(NydusError):
    pass


def is_already_exists(err: BaseException) -> bool:
    return isinstance(err, (AlreadyExists, FileExistsError)) or (
        isinstance(err, OSError) and err.errno == errno.EEXIST
    )


def is_not_found(err: BaseException) -> bool:
    return isinstance(err, (NotFound, FileNotFoundError, KeyError)) or (
        isinstance(err, OSError) and err.errno == errno.ENOENT
    )


def is_connection_closed(err: BaseException) -> bool:
    return isinstance(err, (ConnectionClosed, BrokenPipeError, ConnectionResetError)) or (
        isinstance(err, OSError) and err.errno in (errno.EPIPE, errno.ECONNRESET)
    )


def is_erofs_mounted(err: BaseException) -> bool:
    return isinstance(err, OSError) and err.errno == errno.EBUSY
