"""Reflink-aware file copy (reference KarpelesLab/reflink's Auto, used by
stargz_adaptor.go:110,122).

FICLONE clones extents on filesystems that support it (btrfs/xfs);
everything else falls back to a regular copy with metadata preserved.
"""

from __future__ import annotations

import fcntl
import os
import shutil

FICLONE = 0x40049409


def reflink(src: str, dst: str) -> None:
    """Clone src -> dst via FICLONE; raises OSError when unsupported."""
    with open(src, "rb") as fsrc, open(dst, "wb") as fdst:
        fcntl.ioctl(fdst.fileno(), FICLONE, fsrc.fileno())


def auto(src: str, dst: str) -> None:
    """reflink.Auto: try FICLONE, fall back to copy2."""
    try:
        reflink(src, dst)
        shutil.copystat(src, dst)
    except OSError:
        if os.path.exists(dst):
            os.unlink(dst)
        shutil.copy2(src, dst)
