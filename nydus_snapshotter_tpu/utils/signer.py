"""RSA-PKCS1v15-SHA256 sign/verify (reference pkg/utils/signer/signer.go).

The reference verifies bootstrap signatures with an RSA public key in PKCS#1
PEM form; ``Signer.verify`` mirrors signer.go:33-40. A ``sign`` helper (used
by tooling/tests to produce label values) accepts the matching private key.
"""

from __future__ import annotations

import hashlib
from typing import BinaryIO, Union

from nydus_snapshotter_tpu.utils import errdefs

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    from cryptography.hazmat.primitives.asymmetric.utils import Prehashed

    _HAVE_CRYPTO = True
except ImportError:  # pragma: no cover - cryptography is in the image
    _HAVE_CRYPTO = False


class SignatureError(errdefs.NydusError):
    pass


def _read_all(data: Union[bytes, BinaryIO]) -> bytes:
    return data if isinstance(data, (bytes, bytearray)) else data.read()


class Signer:
    def __init__(self, public_key_pem: bytes):
        if not _HAVE_CRYPTO:
            raise errdefs.Unavailable("cryptography module unavailable")
        try:
            key = serialization.load_pem_public_key(public_key_pem)
        except ValueError as e:
            raise SignatureError(f"cannot parse public key: {e}") from e
        if not isinstance(key, rsa.RSAPublicKey):
            raise SignatureError("bootstrap signing requires an RSA public key")
        self.public_key = key

    def verify(self, data: Union[bytes, BinaryIO], signature: bytes) -> None:
        """Raise SignatureError unless ``signature`` is a valid
        PKCS1v15-SHA256 signature over ``data`` (signer.go:33-40)."""
        digest = hashlib.sha256(_read_all(data)).digest()
        try:
            self.public_key.verify(
                signature, digest, padding.PKCS1v15(), Prehashed(hashes.SHA256())
            )
        except InvalidSignature as e:
            raise SignatureError("bootstrap signature mismatch") from e


def sign(private_key_pem: bytes, data: Union[bytes, BinaryIO]) -> bytes:
    """Produce the signature ``Signer.verify`` accepts."""
    if not _HAVE_CRYPTO:
        raise errdefs.Unavailable("cryptography module unavailable")
    key = serialization.load_pem_private_key(private_key_pem, password=None)
    digest = hashlib.sha256(_read_all(data)).digest()
    return key.sign(digest, padding.PKCS1v15(), Prehashed(hashes.SHA256()))


def generate_keypair(bits: int = 2048) -> tuple[bytes, bytes]:
    """(private_pem, public_pem) — test/tooling helper."""
    if not _HAVE_CRYPTO:
        raise errdefs.Unavailable("cryptography module unavailable")
    key = rsa.generate_private_key(public_exponent=65537, key_size=bits)
    priv = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )
    pub = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    )
    return priv, pub
