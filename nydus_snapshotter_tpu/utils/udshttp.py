"""Tiny HTTP client over a UDS path or ``host:port`` address.

The fleet plane (member registration, metrics scrape, trace pull,
``tools/ntpuctl.py``) talks to member API sockets the same way the dict
service and peer tier do — HTTP over a unix socket, falling back to TCP
when the address has no ``/``. Connections are per-call: fleet traffic
is a low-rate control plane, and a dead member must cost one bounded
dial, never a wedged keep-alive.
"""

from __future__ import annotations

import http.client
import json
import socket
from typing import Optional


class UDSHTTPConnection(http.client.HTTPConnection):
    def __init__(self, sock_path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        try:
            s.connect(self._sock_path)
        except BaseException:
            # A dead endpoint must not leak the half-made socket.
            s.close()
            raise
        self.sock = s


def is_uds(address: str) -> bool:
    return "/" in address


def connect(address: str, timeout: float = 5.0) -> http.client.HTTPConnection:
    if is_uds(address):
        return UDSHTTPConnection(address, timeout)
    host, _, port = address.rpartition(":")
    return http.client.HTTPConnection(host or "localhost", int(port), timeout=timeout)


def request(
    address: str,
    path: str,
    method: str = "GET",
    body: Optional[bytes] = None,
    headers: Optional[dict] = None,
    timeout: float = 5.0,
) -> tuple[int, bytes]:
    """One bounded request; returns (status, body). Raises OSError /
    http.client.HTTPException on transport failure."""
    conn = connect(address, timeout)
    try:
        # Connection: close — per-call connections must not park in the
        # member's keep-alive loop until GC.
        conn.request(
            method, path, body=body,
            headers={"Connection": "close", **(headers or {})},
        )
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def get_json(address: str, path: str, timeout: float = 5.0):
    status, body = request(address, path, timeout=timeout)
    if status != 200:
        raise OSError(f"{address} {path} -> {status}: {body[:120]!r}")
    return json.loads(body)


def post_json(address: str, path: str, payload, timeout: float = 5.0):
    body = json.dumps(payload).encode()
    status, out = request(
        address,
        path,
        method="POST",
        body=body,
        headers={"Content-Type": "application/json"},
        timeout=timeout,
    )
    if status not in (200, 204):
        raise OSError(f"{address} {path} -> {status}: {out[:120]!r}")
    return json.loads(out) if out else {}
