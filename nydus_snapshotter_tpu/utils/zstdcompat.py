"""``zstandard``-package compatibility layer.

Prefers the real ``zstandard`` package when it is installed. When it is
not, exposes an API-compatible shim (``ZstdCompressor``/
``ZstdDecompressor`` with the calling conventions this codebase uses)
backed by the *system* ``libzstd`` over ctypes — the same library
:mod:`nydus_snapshotter_tpu.utils.zstd` binds for the compression lane,
so the converter keeps its cross-lane byte-identity invariant.

Import ``zstandard`` from here instead of directly: a missing wheel must
degrade to the system library, not take the converter stack down with an
ImportError.
"""

from __future__ import annotations

import ctypes
import ctypes.util

try:  # pragma: no cover - branch depends on the environment
    import zstandard  # type: ignore

    _HAVE_PACKAGE = True
except ModuleNotFoundError:
    _HAVE_PACKAGE = False

_CONTENTSIZE_UNKNOWN = 2**64 - 1
_CONTENTSIZE_ERROR = 2**64 - 2


class _ShimError(Exception):
    pass


def _load_lib():
    for name in ("libzstd.so.1", "libzstd.so", "libzstd.dylib"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        found = ctypes.util.find_library("zstd")
        if not found:
            return None
        try:
            lib = ctypes.CDLL(found)
        except OSError:
            return None
    try:
        lib.ZSTD_isError.restype = ctypes.c_uint
        lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
        lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
        lib.ZSTD_getFrameContentSize.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.ZSTD_decompress.restype = ctypes.c_size_t
        lib.ZSTD_decompress.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t,
        ]
    except AttributeError:
        return None
    return lib


# Loaded unconditionally: the pooled decompress path below (used by the
# converter's chunk decode even when the real package is installed) binds
# the same system library utils/zstd.py does.
_LIB = _load_lib()

import threading as _threading

_TLS = _threading.local()


def decompress_block(data, max_output_size: int = 0) -> bytes:
    """One zstd frame → bytes WITHOUT a per-call context allocation.

    The chunk-decode hot path (converter/convert._decompress_chunk, i.e.
    every lazy read of a zstd chunk) used to construct a fresh
    ``ZstdDecompressor`` per call; this routes through the pooled system
    ``ZSTD_DCtx`` (utils/zstd.py) when available, else a per-thread
    cached package decompressor, else the one-shot shim. Any conforming
    frame decodes identically on every arm.
    """
    from nydus_snapshotter_tpu.utils import zstd as zstd_native

    if zstd_native.dctx_available():
        try:
            return zstd_native.decompress_block(data, max_output_size)
        except zstd_native.ZstdError as e:
            raise _ShimError(str(e)) from e
    if _HAVE_PACKAGE:
        dctx = getattr(_TLS, "dctx", None)
        if dctx is None:
            dctx = _TLS.dctx = zstandard.ZstdDecompressor()
        return dctx.decompress(data, max_output_size=max(max_output_size, 1))
    return _ShimDecompressor().decompress(data, max_output_size)


if not _HAVE_PACKAGE:

    class _ShimCompressor:
        def __init__(self, level: int = 3):
            from nydus_snapshotter_tpu.utils import zstd as zstd_native

            if not zstd_native.available():
                raise _ShimError("neither zstandard nor system libzstd available")
            self._level = level
            self._native = zstd_native

        def compress(self, data) -> bytes:
            return self._native.compress_block(data, self._level)

    class _ShimDecompressor:
        def __init__(self):
            if _LIB is None:
                raise _ShimError("neither zstandard nor system libzstd available")

        def decompress(self, data, max_output_size: int = 0) -> bytes:
            from nydus_snapshotter_tpu.utils import zstd as zstd_native

            if zstd_native.dctx_available():
                # Pooled DCtx fast path (no per-call context allocation).
                try:
                    return zstd_native.decompress_block(data, max_output_size)
                except zstd_native.ZstdError as e:
                    raise _ShimError(str(e)) from e
            import numpy as np

            src = np.frombuffer(data, dtype=np.uint8)
            n = src.size
            if n == 0:
                raise _ShimError("empty zstd frame")
            size = _LIB.ZSTD_getFrameContentSize(src.ctypes.data, n)
            if size == _CONTENTSIZE_ERROR:
                raise _ShimError("not a valid zstd frame")
            if size == _CONTENTSIZE_UNKNOWN:
                if max_output_size <= 0:
                    raise _ShimError(
                        "could not determine content size in frame header"
                    )
                cap = max_output_size
            else:
                cap = max(int(size), 1)
            buf = np.empty(cap, dtype=np.uint8)
            w = _LIB.ZSTD_decompress(buf.ctypes.data, cap, src.ctypes.data, n)
            if _LIB.ZSTD_isError(w):
                raise _ShimError(f"zstd decompress failed for {n}-byte input")
            return buf[:w].tobytes()

    class _Shim:
        """Module-shaped stand-in for the ``zstandard`` package."""

        ZstdError = _ShimError
        ZstdCompressor = _ShimCompressor
        ZstdDecompressor = _ShimDecompressor

    zstandard = _Shim()  # type: ignore[assignment]


def available() -> bool:
    """Whether *some* zstd implementation is usable (package or shim)."""
    if _HAVE_PACKAGE:
        return True
    from nydus_snapshotter_tpu.utils import zstd as zstd_native

    return _LIB is not None and zstd_native.available()


__all__ = ["zstandard", "available", "decompress_block"]
