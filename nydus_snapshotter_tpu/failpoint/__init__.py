"""Process-wide failpoint registry (fault-injection layer).

Named fault sites are threaded through every I/O and process boundary of
the snapshotter (``failpoint.hit("transport.fetch_blob")`` …) and do
*nothing* unless armed. Sites are armed either through the
``NYDUS_TPU_FAILPOINTS`` environment variable (parsed once at import —
see :mod:`nydus_snapshotter_tpu.failpoint.spec` for the grammar) or
programmatically via :func:`inject` / :func:`configure` / the
:func:`injected` context manager.

Zero-overhead contract: with nothing armed, :func:`hit` is a truthiness
check on an empty dict and a return — no locks, no allocation. With at
least one site armed, un-armed sites cost one additional dict miss.

The full site catalog lives in ``KNOWN_SITES`` and is documented in
``docs/robustness.md``.
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Iterator, Optional, Union

from nydus_snapshotter_tpu.analysis import runtime as _an
from nydus_snapshotter_tpu.failpoint.spec import (
    Action,
    Panic,
    SpecError,
    build_error,
    parse_action,
    parse_spec,
)

__all__ = [
    "Action",
    "ENV_VAR",
    "KNOWN_SITES",
    "Panic",
    "SpecError",
    "active",
    "clear",
    "configure",
    "configure_from_env",
    "counts",
    "hit",
    "inject",
    "injected",
]

ENV_VAR = "NYDUS_TPU_FAILPOINTS"

# Catalog of sites threaded through the codebase. Arming an unknown site
# is allowed (forward compatibility), but tools/chaos_matrix.py and the
# docs sweep this list.
KNOWN_SITES = (
    "transport.resolve",     # remote/transport.py Pool.resolve entry
    "transport.probe",       # remote/transport.py blob range-probe
    "transport.fetch_blob",  # remote/registry.py RegistryClient.fetch_blob
    "daemon.spawn",          # daemon/daemon.py Daemon.spawn
    "daemon.rpc",            # daemon/client.py NydusdClient._request
    "manager.restart",       # manager/manager.py do_daemon_restart
    "fs.mount",              # filesystem/fs.py Filesystem.mount
    "fs.umount",             # filesystem/fs.py Filesystem.umount
    "metastore.create",      # snapshot/metastore.py create_snapshot
    "metastore.commit",      # snapshot/metastore.py commit_active
    "metastore.remove",      # snapshot/metastore.py remove
    "converter.pack",        # converter/convert.py Pack dispatch
    "compress.probe",        # converter/codec.py per-chunk compressibility probe
    "compress.train",        # converter/codec.py ZDICT corpus training
    "compress.encode",       # converter/codec.py adaptive encode entry
    "pipeline.chunk",        # parallel/pipeline.py chunk-worker item entry
    "pipeline.queue",        # parallel/pipeline.py ByteBoundedQueue.put
    "pipeline.compress",     # parallel/pipeline.py compress-worker item entry
    "pipeline.assemble",     # parallel/pipeline.py ordered chunks_for fetch
    "fused.dispatch",        # ops/fused_convert.py device batch dispatch
    "blobcache.fetch",       # daemon/fetch_sched.py worker ranged-GET entry
    "blobcache.coalesce",    # daemon/fetch_sched.py miss-gap merge decision
    "blobcache.readahead",   # daemon/blobcache.py sequential window extension
    "blobcache.evict",       # cache/manager.py watermark entry eviction
    "blobcache.replay",      # daemon/fetch_sched.py prefetch-replay per file
    "snapshot.prepare",      # snapshot/async_work.py background prepare work
    "snapshot.commit",       # snapshot/snapshotter.py commit entry
    "snapshot.usage",        # snapshot/async_work.py async usage scan
    "snapshot.cleanup",      # snapshot/snapshotter.py per-dir cleanup
    "dict.insert",           # parallel/sharded_dict.py incremental insert batch
    "dict.rebuild",          # parallel/sharded_dict.py load-factor/overflow rebuild
    "dict.rpc",              # parallel/dict_service.py service request entry
    "peer.serve",            # daemon/peer.py chunk-server request entry
    "peer.fetch",            # daemon/peer.py peer-tier ranged read attempt
    "peer.admit",            # daemon/fetch_sched.py AdmissionGate.acquire entry
    "peer.member",           # daemon/peer.py membership registry refresh
    "dict.shard",            # parallel/dict_service.py per-shard batch routing
    "slo.actuate",           # metrics/slo.py lane shed/restore transition
    "soci.index",            # soci/blob.py index build / store boundary
    "soci.resolve",          # soci/blob.py read -> compressed-range resolution
    "soci.fetch",            # soci/blob.py compressed-range pull for a lazy read
    "fleet.scrape",          # metrics/federation.py per-member metrics scrape
    "fleet.collect",         # trace/aggregate.py per-member trace-ring pull
    "scenario.phase",        # scenario/orchestrator.py phase entry
    "ha.place",              # ha/placement.py PlacementController.tick entry
    "ha.replicate",          # ha/replicate.py ReplicaTailer.poll_once entry
    "ha.promote",            # ha/{placement,replicate}.py promotion transition
    "soak.wave",             # scenario/soak.py per-epoch wave entry
    "soak.evolve",           # scenario/soak.py corpus-evolution convert step
    "soak.scaleup",          # metrics/slo.py scale-up spawn attempt
    "chunk.vec",             # ops/native_cdc.py vectorized table-scan entry
    "compress.batch",        # converter/codec.py batched encode entry
    "peer.tier",             # daemon/peer.py per-tier waterfall attempt entry
    "peer.hedge",            # daemon/fetch_sched.py hedged second-request launch
    "prov.record",           # provenance/ledger.py per-extent attribution record
    "prov.compile",          # provenance/heat.py .heat compile/persist boundary
    "prov.adopt",            # provenance/heat.py peer heat-artifact adoption
)

_lock = _an.make_lock("failpoint.table")
_active: dict[str, Action] = {}
_fired: dict[str, int] = {}
_rng = random.random  # patchable for deterministic probability tests
_sleep = time.sleep


def hit(site: str) -> None:
    """Fault site marker. No-op unless ``site`` is armed."""
    if not _active:
        return
    act = _active.get(site)
    if act is None:
        return
    _fire(site, act)


def _fire(site: str, act: Action) -> None:
    with _lock:
        # Re-read under the lock: a concurrent clear()/n-shot exhaustion wins.
        act = _active.get(site)
        if act is None:
            return
        if act.prob is not None and _rng() >= act.prob:
            return
        if act.count is not None:
            act.count -= 1
            if act.count <= 0:
                _active.pop(site, None)
        _fired[site] = _fired.get(site, 0) + 1
        kind, arg = act.kind, act.arg
    # Tag the active trace span (if any) so chaos runs are attributable:
    # a span whose site fired carries `failpoints=[...]` in its attrs.
    # Lazy import: failpoint must stay import-light, and this only runs
    # when a site actually fires.
    try:
        from nydus_snapshotter_tpu import trace as _trace

        _trace.annotate_failpoint(site)
    except Exception:
        pass
    if kind == "error":
        raise build_error(arg, site)
    if kind == "delay":
        _sleep(float(arg))
        return
    if kind == "panic":
        raise Panic(arg or f"failpoint panic at {site}")


def inject(site: str, action: Union[str, Action]) -> None:
    """Arm one site. ``action`` is an Action or a spec like ``"error(OSError)*2"``."""
    if isinstance(action, str):
        action = parse_action(action)
    with _lock:
        _active[site] = action


def configure(spec: str) -> None:
    """Replace the whole table from a multi-site spec string."""
    table = parse_spec(spec)
    with _lock:
        _active.clear()
        _active.update(table)


def configure_from_env(environ=os.environ) -> bool:
    """Arm from ``NYDUS_TPU_FAILPOINTS``; returns whether anything was set.

    A malformed env spec is reported and ignored — this runs at import
    time, and a typo in a chaos knob must not take the whole snapshotter
    down harder than the fault it was trying to inject.
    """
    spec = environ.get(ENV_VAR, "")
    if not spec:
        return False
    try:
        configure(spec)
    except SpecError as e:
        import logging

        logging.getLogger(__name__).warning("ignoring bad %s: %s", ENV_VAR, e)
        return False
    return bool(_active)


def clear(site: Optional[str] = None) -> None:
    """Disarm one site, or everything (also resets fire counters)."""
    with _lock:
        if site is None:
            _active.clear()
            _fired.clear()
        else:
            _active.pop(site, None)


def active() -> dict[str, str]:
    """{site: action-spec} snapshot of the armed table."""
    with _lock:
        return {site: str(act) for site, act in _active.items()}


def counts() -> dict[str, int]:
    """{site: times fired} since the last full clear()."""
    with _lock:
        return dict(_fired)


@contextmanager
def injected(site: str, action: Union[str, Action]) -> Iterator[None]:
    """Scoped arm/disarm for tests."""
    inject(site, action)
    try:
        yield
    finally:
        clear(site)


configure_from_env()
