"""Failpoint spec grammar and action model.

A spec string arms one or more named fault sites::

    transport.fetch_blob=error(HTTPError:503)%0.5;daemon.spawn=delay(0.2);metastore.commit=panic

Grammar (informal)::

    SPECS  := SITE '=' ACTION (';' SITE '=' ACTION)*
    ACTION := KIND ['(' ARG ')'] ['%' PROB] ['*' COUNT]
    KIND   := 'error' | 'delay' | 'panic' | 'off'

``error(ExcName[:detail])`` raises the named exception at the site —
builtins (``OSError``, ``TimeoutError``, ``ConnectionResetError``, …),
``HTTPError:<code>`` from the registry client, or any
:mod:`nydus_snapshotter_tpu.utils.errdefs` class; unknown names fall back
to ``RuntimeError``. ``delay(seconds)`` sleeps. ``panic`` raises
:class:`Panic`, which derives from ``BaseException`` so ordinary
``except Exception`` recovery code cannot swallow it (Go-panic
semantics). ``%p`` fires with probability ``p``; ``*n`` disarms the site
after ``n`` firings. ``off`` is accepted and ignored (spec-level way to
comment out one site).
"""

from __future__ import annotations

import builtins
import re
from dataclasses import dataclass
from typing import Optional

_KINDS = ("error", "delay", "panic", "off")

_ACTION_RE = re.compile(
    r"^(?P<kind>[a-z]+)"
    r"(?:\((?P<arg>[^)]*)\))?"
    r"(?:%(?P<prob>[0-9.]+))?"
    r"(?:\*(?P<count>[0-9]+))?$"
)


class Panic(BaseException):
    """Injected panic — intentionally not an Exception subclass."""


class SpecError(ValueError):
    pass


@dataclass
class Action:
    kind: str
    arg: str = ""
    prob: Optional[float] = None
    count: Optional[int] = None  # remaining shots; None = unlimited

    def __str__(self) -> str:
        s = self.kind
        if self.arg:
            s += f"({self.arg})"
        if self.prob is not None:
            s += f"%{self.prob:g}"
        if self.count is not None:
            s += f"*{self.count}"
        return s


def parse_action(text: str) -> Action:
    m = _ACTION_RE.match(text.strip())
    if m is None:
        raise SpecError(f"unparsable failpoint action {text!r}")
    kind = m.group("kind")
    if kind not in _KINDS:
        raise SpecError(f"unknown failpoint action kind {kind!r} in {text!r}")
    prob = None
    if m.group("prob") is not None:
        try:
            prob = float(m.group("prob"))
        except ValueError as e:
            raise SpecError(f"bad probability in {text!r}: {e}") from None
        if not 0.0 <= prob <= 1.0:
            raise SpecError(f"probability out of [0,1] in {text!r}")
    count = int(m.group("count")) if m.group("count") is not None else None
    arg = m.group("arg") or ""
    if kind == "delay":
        try:
            float(arg)
        except ValueError:
            raise SpecError(f"delay needs a numeric argument, got {arg!r}") from None
    return Action(kind=kind, arg=arg, prob=prob, count=count)


def parse_spec(spec: str) -> dict[str, Action]:
    """``site=action;site=action`` → {site: Action}; empty items tolerated."""
    out: dict[str, Action] = {}
    for item in spec.split(";"):
        item = item.strip()
        if not item:
            continue
        site, sep, action = item.partition("=")
        site = site.strip()
        if not sep or not site:
            raise SpecError(f"failpoint item {item!r} is not 'site=action'")
        act = parse_action(action)
        if act.kind != "off":
            out[site] = act
    return out


def build_error(arg: str, site: str) -> BaseException:
    """Construct the exception described by an ``error(...)`` argument."""
    name, _, detail = arg.partition(":")
    name = name.strip() or "RuntimeError"
    detail = detail.strip()
    if name == "HTTPError":
        from nydus_snapshotter_tpu.remote.registry import HTTPError

        try:
            code = int(detail or 503)
        except ValueError:
            code = 503
        return HTTPError(code, f"failpoint://{site}")
    exc = getattr(builtins, name, None)
    if not (isinstance(exc, type) and issubclass(exc, BaseException)):
        from nydus_snapshotter_tpu.utils import errdefs

        exc = getattr(errdefs, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc(detail or f"injected at failpoint {site}")
    return RuntimeError(f"{name}({detail}) injected at failpoint {site}")
