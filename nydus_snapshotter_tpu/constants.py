"""Framework-wide constants.

Mirrors the *behavioral* constants of the reference (values surveyed from
/root/reference/internal/constant/values.go:19-55, pkg/converter/constant.go:9-30,
pkg/label/label.go:17-88) so that images, labels, and configs interoperate.
"""

# ---------------------------------------------------------------------------
# Filesystem drivers (reference internal/constant/values.go:19-30)
# ---------------------------------------------------------------------------
FS_DRIVER_FUSEDEV = "fusedev"
FS_DRIVER_FSCACHE = "fscache"
FS_DRIVER_BLOCKDEV = "blockdev"
FS_DRIVER_NODEV = "nodev"
FS_DRIVER_PROXY = "proxy"

FS_DRIVERS = (
    FS_DRIVER_FUSEDEV,
    FS_DRIVER_FSCACHE,
    FS_DRIVER_BLOCKDEV,
    FS_DRIVER_NODEV,
    FS_DRIVER_PROXY,
)

# Daemon modes (how nydusd-equivalent daemons are shared across images)
DAEMON_MODE_SHARED = "shared"
DAEMON_MODE_DEDICATED = "dedicated"
DAEMON_MODE_NONE = "none"

# Daemon recovery policies (reference config/config.go:77-110)
RECOVER_POLICY_NONE = "none"
RECOVER_POLICY_RESTART = "restart"
RECOVER_POLICY_FAILOVER = "failover"

# ---------------------------------------------------------------------------
# Defaults (reference internal/constant/values.go:32-55)
# ---------------------------------------------------------------------------
DEFAULT_ADDRESS = "/run/containerd-nydus/containerd-nydus-grpc.sock"
DEFAULT_CONFIG_PATH = "/etc/nydus/config.toml"
DEFAULT_ROOT_DIR = "/var/lib/containerd/io.containerd.snapshotter.v1.nydus"
DEFAULT_LOG_LEVEL = "info"
DEFAULT_DAEMON_MODE = DAEMON_MODE_DEDICATED
DEFAULT_FS_DRIVER = FS_DRIVER_FUSEDEV
DEFAULT_GC_PERIOD = "24h"
DEFAULT_METRICS_ADDRESS = ":9110"
DEFAULT_SYSTEM_CONTROLLER_ADDRESS = "/run/containerd-nydus/system.sock"

# The unix(7) sun_path limit that caps root-path length
# (reference config/config.go:50-59 validates root < 70 bytes).
MAX_ROOT_PATH_LEN = 70

# ---------------------------------------------------------------------------
# RAFS / conversion constants (reference pkg/converter/constant.go:9-30)
# ---------------------------------------------------------------------------
MANIFEST_OS_FEATURE_NYDUS = "nydus.remoteimage.v1"
MEDIA_TYPE_NYDUS_CONFIG = "application/vnd.nydus.image.config.v1+json"
MEDIA_TYPE_NYDUS_BLOB = "application/vnd.oci.image.layer.nydus.blob.v1"
BOOTSTRAP_FILE_NAME_IN_LAYER = "image/image.boot"

MANIFEST_NYDUS_CACHE = "containerd.io/snapshot/nydus-cache"

LAYER_ANNOTATION_FS_VERSION = "containerd.io/snapshot/nydus-fs-version"
LAYER_ANNOTATION_NYDUS_BLOB = "containerd.io/snapshot/nydus-blob"
LAYER_ANNOTATION_NYDUS_BLOB_DIGEST = "containerd.io/snapshot/nydus-blob-digest"
LAYER_ANNOTATION_NYDUS_BLOB_SIZE = "containerd.io/snapshot/nydus-blob-size"
LAYER_ANNOTATION_NYDUS_BOOTSTRAP = "containerd.io/snapshot/nydus-bootstrap"
LAYER_ANNOTATION_NYDUS_SOURCE_CHAINID = "containerd.io/snapshot/nydus-source-chainid"
LAYER_ANNOTATION_NYDUS_ENCRYPTED_BLOB = "containerd.io/snapshot/nydus-encrypted-blob"
LAYER_ANNOTATION_NYDUS_SOURCE_DIGEST = "containerd.io/snapshot/nydus-source-digest"
LAYER_ANNOTATION_NYDUS_TARGET_DIGEST = "containerd.io/snapshot/nydus-target-digest"
LAYER_ANNOTATION_NYDUS_REFERENCE_BLOB_IDS = "containerd.io/snapshot/nydus-reference-blob-ids"
LAYER_ANNOTATION_UNCOMPRESSED = "containerd.io/uncompressed"

# ---------------------------------------------------------------------------
# Snapshot labels (reference pkg/label/label.go:17-88)
# ---------------------------------------------------------------------------
# Labels set by containerd / CRI on snapshots.
CRI_IMAGE_REF = "containerd.io/snapshot/cri.image-ref"
CRI_LAYER_DIGEST = "containerd.io/snapshot/cri.layer-digest"
CRI_IMAGE_LAYERS = "containerd.io/snapshot/cri.image-layers"
CRI_MANIFEST_DIGEST = "containerd.io/snapshot/cri.manifest-digest"
TARGET_SNAPSHOT_REF = "containerd.io/snapshot.ref"

# Labels that drive the per-layer processor choice
# (reference snapshot/process.go:26-183).
NYDUS_DATA_LAYER = LAYER_ANNOTATION_NYDUS_BLOB
NYDUS_META_LAYER = LAYER_ANNOTATION_NYDUS_BOOTSTRAP
NYDUS_REF_LAYER = "containerd.io/snapshot/nydus-ref"
NYDUS_SIGNATURE = "containerd.io/snapshot/nydus-signature"
NYDUS_TARFS_LAYER = "containerd.io/snapshot/nydus-tarfs"
NYDUS_PROXY_MODE = "containerd.io/snapshot/nydus-proxy-mode"
OVERLAYFS_VOLATILE_OPT = "containerd.io/snapshot/overlay.volatile"
TARGET_IMAGE_REF = "containerd.io/snapshot/remote/image.reference"
# Dm-verity information for image/layer block devices (label.go:41-44).
NYDUS_IMAGE_BLOCK_INFO = "containerd.io/snapshot/nydus-image-block"
NYDUS_LAYER_BLOCK_INFO = "containerd.io/snapshot/nydus-layer-block"
# Registry pull credentials attached by CRI (label.go:45-48).
NYDUS_IMAGE_PULL_SECRET = "containerd.io/snapshot/pullsecret"
NYDUS_IMAGE_PULL_USERNAME = "containerd.io/snapshot/pullusername"
# Marks a snapshot holding an estargz layer (label.go:54).
STARGZ_LAYER = "containerd.io/snapshot/stargz"
# Marks a snapshot holding a seekable-OCI indexed plain gzip layer
# (soci/adaptor.py — this framework's backend, no reference equivalent).
SOCI_LAYER = "containerd.io/snapshot/ntpu-soci"
# The FormatRouter's backend decision for a soci-claimed layer
# (toc-adopt / seekable-index / zran-index), surfaced on the snapshot so
# tooling can see which lazy path each layer took (soci/router.py).
SOCI_ROUTE = "containerd.io/snapshot/ntpu-soci-route"
# Builder hint that an image should run in tarfs mode (label.go:63-65).
TARFS_HINT = "containerd.io/snapshot/tarfs-hint"

# ---------------------------------------------------------------------------
# Chunking parameters (reference pkg/converter/types.go:76-79 bounds)
# ---------------------------------------------------------------------------
CHUNK_SIZE_MIN = 0x1000  # 4 KiB
CHUNK_SIZE_MAX = 0x1000000  # 16 MiB
CHUNK_SIZE_DEFAULT = 0x100000  # 1 MiB, nydus default

# Compressor flags, bit-compatible with the reference TOC entry flags
# (reference pkg/converter/types.go:26-31).
COMPRESSOR_NONE = 0x0000_0001
COMPRESSOR_ZSTD = 0x0000_0002
COMPRESSOR_LZ4_BLOCK = 0x0000_0004

# zstd level for chunk compression — the SINGLE source: the Python codec
# lane (utils/zstd.py), the converter, and the native fused arms (level
# threaded through the pack ABI's codec-param slot) all read this, so the
# cross-lane byte-identity invariant cannot drift on a level bump.
ZSTD_LEVEL = 3
COMPRESSOR_GZIP = 0x0000_0008  # estargz chunks stay gzip streams in-place
COMPRESSOR_MASK = 0x0000_000F
