"""OCI layer encryption for nydus bootstraps (reference pkg/encryption)."""

from nydus_snapshotter_tpu.encryption.encryption import (
    ANNOTATION_ENC_KEYS_JWE,
    MEDIA_TYPE_LAYER_ENC,
    MEDIA_TYPE_LAYER_GZIP_ENC,
    MEDIA_TYPE_LAYER_ZSTD_ENC,
    decrypt_layer,
    decrypt_nydus_bootstrap,
    encrypt_layer,
    encrypt_nydus_bootstrap,
    filter_out_annotations,
)

__all__ = [
    "ANNOTATION_ENC_KEYS_JWE",
    "MEDIA_TYPE_LAYER_ENC",
    "MEDIA_TYPE_LAYER_GZIP_ENC",
    "MEDIA_TYPE_LAYER_ZSTD_ENC",
    "decrypt_layer",
    "decrypt_nydus_bootstrap",
    "encrypt_layer",
    "encrypt_nydus_bootstrap",
    "filter_out_annotations",
]
