"""Encrypt/decrypt the nydus bootstrap layer (OCI image-encryption shape).

Reference pkg/encryption/encryption.go:28-253 (itself lifted from
containerd/imgcrypt): the bootstrap layer descriptor is rewritten to an
``+encrypted`` media type, the payload is symmetrically encrypted, and the
wrapped symmetric key travels in the ``org.opencontainers.image.enc.keys.
jwe`` annotation — one wrapped copy per recipient public key.

Scheme here: AES-256-GCM for the layer payload; RSA-OAEP(SHA-256) wrapping
of a JSON ``{symkey, nonce}`` bundle per recipient (the ocicrypt JWE role).
Same annotation contract and media-type mapping as the reference, so
manifests round-trip structurally.
"""

from __future__ import annotations

import base64
import json
import os
import secrets
from typing import Optional

from nydus_snapshotter_tpu.converter.content import BlobInfo, LocalContentStore
from nydus_snapshotter_tpu.remote.registry import Descriptor
from nydus_snapshotter_tpu.utils import errdefs

try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    _HAVE_CRYPTO = True
except ImportError:  # pragma: no cover
    _HAVE_CRYPTO = False

# ocicrypt spec media types (encocispec)
MEDIA_TYPE_LAYER_ENC = "application/vnd.oci.image.layer.v1.tar+encrypted"
MEDIA_TYPE_LAYER_GZIP_ENC = "application/vnd.oci.image.layer.v1.tar+gzip+encrypted"
MEDIA_TYPE_LAYER_ZSTD_ENC = "application/vnd.oci.image.layer.v1.tar+zstd+encrypted"

ANNOTATION_ENC_KEYS_JWE = "org.opencontainers.image.enc.keys.jwe"
_ENC_ANNOTATION_PREFIX = "org.opencontainers.image.enc"

_PLAIN_TO_ENC = {
    "application/vnd.docker.image.rootfs.diff.tar.gzip": MEDIA_TYPE_LAYER_GZIP_ENC,
    "application/vnd.docker.image.rootfs.diff.tar": MEDIA_TYPE_LAYER_ENC,
    "application/vnd.oci.image.layer.v1.tar+gzip": MEDIA_TYPE_LAYER_GZIP_ENC,
    "application/vnd.oci.image.layer.v1.tar+zstd": MEDIA_TYPE_LAYER_ZSTD_ENC,
    "application/vnd.oci.image.layer.v1.tar": MEDIA_TYPE_LAYER_ENC,
    # already-encrypted types pass through (encryption.go:64-69)
    MEDIA_TYPE_LAYER_ENC: MEDIA_TYPE_LAYER_ENC,
    MEDIA_TYPE_LAYER_GZIP_ENC: MEDIA_TYPE_LAYER_GZIP_ENC,
    MEDIA_TYPE_LAYER_ZSTD_ENC: MEDIA_TYPE_LAYER_ZSTD_ENC,
}

_ENC_TO_PLAIN = {
    MEDIA_TYPE_LAYER_GZIP_ENC: "application/vnd.docker.image.rootfs.diff.tar.gzip",
    MEDIA_TYPE_LAYER_ZSTD_ENC: "application/vnd.oci.image.layer.v1.tar+zstd",
    MEDIA_TYPE_LAYER_ENC: "application/vnd.docker.image.rootfs.diff.tar",
}


class EncryptionError(errdefs.NydusError):
    pass


def _require_crypto() -> None:
    if not _HAVE_CRYPTO:
        raise errdefs.Unavailable("cryptography module unavailable")


def filter_out_annotations(annotations: Optional[dict]) -> dict:
    """Drop org.opencontainers.image.enc.* (ocicrypt FilterOutAnnotations)."""
    return {
        k: v
        for k, v in (annotations or {}).items()
        if not k.startswith(_ENC_ANNOTATION_PREFIX)
    }


def _wrap_key(recipient_pem: bytes, bundle: bytes) -> str:
    key = serialization.load_pem_public_key(recipient_pem)
    wrapped = key.encrypt(
        bundle,
        padding.OAEP(
            mgf=padding.MGF1(algorithm=hashes.SHA256()),
            algorithm=hashes.SHA256(),
            label=None,
        ),
    )
    return base64.b64encode(wrapped).decode()


def _unwrap_key(private_pem: bytes, wrapped_b64: str) -> Optional[bytes]:
    key = serialization.load_pem_private_key(private_pem, password=None)
    try:
        return key.decrypt(
            base64.b64decode(wrapped_b64),
            padding.OAEP(
                mgf=padding.MGF1(algorithm=hashes.SHA256()),
                algorithm=hashes.SHA256(),
                label=None,
            ),
        )
    except ValueError:
        return None


def encrypt_layer(
    data: bytes, desc: Descriptor, recipients: list[bytes]
) -> tuple[Descriptor, bytes]:
    """(new_desc, ciphertext) — media type remapped, wrapped keys in
    annotations (encryptLayer, encryption.go:28-86)."""
    _require_crypto()
    if not recipients:
        raise EncryptionError("no encryption recipients")
    new_media = _PLAIN_TO_ENC.get(desc.media_type)
    if new_media is None:
        raise EncryptionError(f"unsupported layer MediaType: {desc.media_type}")

    symkey = AESGCM.generate_key(256)
    nonce = secrets.token_bytes(12)
    ciphertext = AESGCM(symkey).encrypt(nonce, data, None)

    bundle = json.dumps(
        {
            "symkey": base64.b64encode(symkey).decode(),
            "nonce": base64.b64encode(nonce).decode(),
            "cipher": "AES_256_GCM",
        }
    ).encode()
    wrapped = ",".join(_wrap_key(pem, bundle) for pem in recipients)

    import hashlib

    annotations = filter_out_annotations(desc.annotations)
    annotations[ANNOTATION_ENC_KEYS_JWE] = wrapped
    new_desc = Descriptor(
        media_type=new_media,
        digest="sha256:" + hashlib.sha256(ciphertext).hexdigest(),
        size=len(ciphertext),
        annotations=annotations,
        platform=desc.platform,
    )
    return new_desc, ciphertext


def decrypt_layer(
    data: bytes, desc: Descriptor, keys: list[bytes], unwrap_only: bool = False
) -> tuple[Optional[Descriptor], Optional[bytes]]:
    """Inverse of encrypt_layer (decryptLayer, encryption.go:90-117).
    With ``unwrap_only`` the key is unwrapped (proving access) but the
    payload stays encrypted — (None, None) is returned on success."""
    _require_crypto()
    plain_media = _ENC_TO_PLAIN.get(desc.media_type)
    if plain_media is None:
        raise EncryptionError(f"unsupported layer MediaType: {desc.media_type}")
    wrapped = (desc.annotations or {}).get(ANNOTATION_ENC_KEYS_JWE, "")
    if not wrapped:
        raise EncryptionError("missing wrapped key annotation")

    bundle = None
    for candidate in wrapped.split(","):
        for pem in keys:
            bundle = _unwrap_key(pem, candidate)
            if bundle is not None:
                break
        if bundle is not None:
            break
    if bundle is None:
        raise EncryptionError("no private key could unwrap the layer key")
    if unwrap_only:
        return None, None

    params = json.loads(bundle)
    symkey = base64.b64decode(params["symkey"])
    nonce = base64.b64decode(params["nonce"])
    try:
        plaintext = AESGCM(symkey).decrypt(nonce, data, None)
    except Exception as e:
        raise EncryptionError(f"bootstrap layer decryption failed: {e}") from e

    import hashlib

    new_desc = Descriptor(
        media_type=plain_media,
        digest="sha256:" + hashlib.sha256(plaintext).hexdigest(),
        size=len(plaintext),
        annotations=filter_out_annotations(desc.annotations),
        platform=desc.platform,
    )
    return new_desc, plaintext


def encrypt_nydus_bootstrap(
    cs: LocalContentStore, desc: Descriptor, recipients: list[bytes]
) -> Descriptor:
    """EncryptNydusBootstrap (encryption.go:143-202): read the bootstrap
    layer from the content store, store the encrypted copy, return the
    rewritten descriptor."""
    data = cs.read(desc.digest)
    new_desc, ciphertext = encrypt_layer(data, desc, recipients)
    cs.write_blob(ciphertext, expected_digest=new_desc.digest)
    return new_desc


def decrypt_nydus_bootstrap(
    cs: LocalContentStore,
    desc: Descriptor,
    keys: list[bytes],
    unwrap_only: bool = False,
) -> Optional[Descriptor]:
    """DeryptNydusBootstrap (encryption.go:206-253)."""
    data = cs.read(desc.digest)
    new_desc, plaintext = decrypt_layer(data, desc, keys, unwrap_only)
    if unwrap_only or new_desc is None:
        return None
    cs.write_blob(plaintext, expected_digest=new_desc.digest)
    return new_desc
