"""On-disk / on-wire data models: RAFS bootstraps, nydus-tar framing, TOC."""
