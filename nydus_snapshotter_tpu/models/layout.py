"""RAFS on-disk magic detection.

Parity surface of reference pkg/layout/layout.go:19-76: the same magic numbers
and offsets, so bootstraps written by this framework are recognized by tools
expecting the reference layout (and vice versa for version sniffing).
"""

from __future__ import annotations

import struct

from nydus_snapshotter_tpu import constants

RAFS_V5 = "v5"
RAFS_V6 = "v6"

RAFS_V5_SUPER_VERSION = 0x500
RAFS_V5_SUPER_MAGIC = 0x5241_4653  # "RAFS"
RAFS_V6_SUPER_MAGIC = 0xE0F5_E1E2  # EROFS superblock magic
RAFS_V6_SUPER_BLOCK_SIZE = 1024 + 128 + 256
RAFS_V6_SUPER_BLOCK_OFFSET = 1024
RAFS_V6_CHUNK_INFO_OFFSET = 1024 + 128 + 24

# RafsV6 layout: 1k + SuperBlock(128) + SuperBlockExtended(256)
# RafsV5 layout: 8K superblock — read MAX_SUPER_BLOCK_SIZE to cover both.
MAX_SUPER_BLOCK_SIZE = 8 * 1024

BOOTSTRAP_FILE = constants.BOOTSTRAP_FILE_NAME_IN_LAYER  # "image/image.boot"
LEGACY_BOOTSTRAP_FILE = "image.boot"
DUMMY_MOUNTPOINT = "/dummy"


class LayoutError(ValueError):
    pass


def detect_fs_version(header: bytes) -> str:
    """Sniff RAFS version from a bootstrap header.

    Reference behavior (layout.go:60-76): v5 if the little-endian magic/version
    pair sits at offset 0; v6 if the EROFS magic sits at offset 1024.
    """
    if len(header) < 8:
        raise LayoutError("header buffer to detect_fs_version is too small")
    magic, fs_version = struct.unpack_from("<II", header, 0)
    if magic == RAFS_V5_SUPER_MAGIC and fs_version == RAFS_V5_SUPER_VERSION:
        return RAFS_V5
    if len(header) >= RAFS_V6_SUPER_BLOCK_OFFSET + 4:
        (v6_magic,) = struct.unpack_from("<I", header, RAFS_V6_SUPER_BLOCK_OFFSET)
        if v6_magic == RAFS_V6_SUPER_MAGIC:
            return RAFS_V6
    raise LayoutError("unknown file system header")


def validate_bootstrap_header(buf: bytes) -> str:
    """Detect + sanity-check a real nydus bootstrap's superblock.

    Works on actual reference-produced artifacts (the binary fixtures at
    /root/reference/pkg/filesystem/testdata): v5 validates the declared
    superblock size against the file; v6 validates the EROFS block-size
    exponent. Raises LayoutError on anything malformed — the same
    reject-bad-bootstraps posture as the reference's version sniffing +
    mount validation (layout.go:60-76).
    """
    version = detect_fs_version(buf)
    if version == RAFS_V5:
        if len(buf) < 12:
            raise LayoutError("v5 bootstrap truncated before superblock size")
        _magic, _ver, sb_size = struct.unpack_from("<III", buf, 0)
        if not 16 <= sb_size <= min(len(buf), MAX_SUPER_BLOCK_SIZE):
            raise LayoutError(f"v5 superblock size {sb_size} out of range")
    else:
        if len(buf) < RAFS_V6_SUPER_BLOCK_OFFSET + 16:
            raise LayoutError("v6 bootstrap truncated before superblock tail")
        blkszbits = buf[RAFS_V6_SUPER_BLOCK_OFFSET + 12]
        if not 9 <= blkszbits <= 12:
            raise LayoutError(f"v6 blkszbits {blkszbits} outside 9..12")
    return version
