"""EROFS on-disk image writer: kernel-mountable block images from a file
tree.

The reference's blockdev/tarfs modes hand the kernel a *real* EROFS image
produced by ``nydus-image export --block`` (invoked at
pkg/tarfs/tarfs.go:525-541, mounted with ``mount -t erofs`` at :573-662 via
pkg/utils/erofs). This module is the native equivalent: it serializes a
file tree into the EROFS on-disk format (uncompressed, compact inodes,
flat-plain data) that the in-kernel erofs driver mounts directly — no
external mkfs.erofs, no FUSE in the read path. The kernel is the format
oracle: tests loop-attach the produced image, mount it, and compare the
tree byte-for-byte.

Format notes (Linux fs/erofs/erofs_fs.h):
- 4 KiB blocks; superblock at offset 1024 (magic 0xE0F5E1E2 — the same
  magic pkg/layout detects at that offset).
- Compact (32-byte) inodes in a metadata area starting at
  ``meta_blkaddr``; an inode's nid is its 32-byte slot index.
- FLAT_PLAIN data layout everywhere: file/dir/symlink bytes live in whole
  blocks at ``raw_blkaddr``; the tail block is zero-padded on disk.
- Directories are arrays of 12-byte dirents per block, names packed after
  the dirent array, entries sorted bytewise (the kernel binary-searches,
  both across blocks by first-name and within a block).
- No xattrs/compression/chunk inodes yet: feature_compat = 0 keeps the
  checksum optional, feature_incompat = 0 keeps every consumer kernel
  compatible.
"""

from __future__ import annotations

import io
import os
import stat as statmod
import struct
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu.models.fstree import FileEntry

BLKSZ = 4096
BLKSZBITS = 12
EROFS_MAGIC = 0xE0F5E1E2
SB_OFFSET = 1024

# i_format: bit0 = 0 (compact inode), datalayout in bits 1..3
_LAYOUT_FLAT_PLAIN = 0

_FT_OF_MODE = [
    (statmod.S_ISREG, 1),
    (statmod.S_ISDIR, 2),
    (statmod.S_ISCHR, 3),
    (statmod.S_ISBLK, 4),
    (statmod.S_ISFIFO, 5),
    (statmod.S_ISSOCK, 6),
    (statmod.S_ISLNK, 7),
]

_SB = struct.Struct("<IIIBBHQQIIII16s16sIHHHBBIQB23s")
assert _SB.size == 128, _SB.size
_INODE_COMPACT = struct.Struct("<HHHHIIIIHHI")
_DIRENT = struct.Struct("<QHBB")


class ErofsError(ValueError):
    pass


def _file_type(mode: int) -> int:
    for pred, ft in _FT_OF_MODE:
        if pred(mode):
            return ft
    return 0


@dataclass
class _Node:
    entry: FileEntry
    nid: int = 0
    ino: int = 0
    nlink: int = 1
    data: bytes = b""
    raw_blkaddr: int = 0
    children: dict[bytes, "_Node"] = field(default_factory=dict)
    parent: Optional["_Node"] = None


def _build_tree(entries: list[FileEntry]) -> _Node:
    root_entry = FileEntry(path="/", mode=statmod.S_IFDIR | 0o755)
    root = _Node(entry=root_entry)
    by_path: dict[str, _Node] = {"/": root}

    def ensure_dir(path: str) -> _Node:
        node = by_path.get(path)
        if node is not None:
            if not statmod.S_ISDIR(node.entry.mode):
                raise ErofsError(f"{path} used as directory and non-directory")
            return node
        parent = ensure_dir(path.rsplit("/", 1)[0] or "/")
        node = _Node(entry=FileEntry(path=path, mode=statmod.S_IFDIR | 0o755))
        node.parent = parent
        parent.children[path.rsplit("/", 1)[1].encode()] = node
        by_path[path] = node
        return node

    for e in sorted(entries, key=lambda e: e.path):
        if e.path == "/":
            root.entry = e
            continue
        name = e.path.rsplit("/", 1)[1]
        if len(name.encode()) > 255:
            raise ErofsError(f"name too long: {name!r}")
        parent = ensure_dir(e.path.rsplit("/", 1)[0] or "/")
        existing = by_path.get(e.path)
        if existing is not None and statmod.S_ISDIR(existing.entry.mode) and e.is_dir:
            existing.entry = e  # explicit dir entry refines a placeholder
            continue
        node = _Node(entry=e)
        node.parent = parent
        parent.children[name.encode()] = node
        by_path[e.path] = node
    return root


def _dir_blocks(node: _Node, nid_of: dict[int, int]) -> bytes:
    """Serialize one directory's dirent blocks (kernel-sorted)."""
    items: list[tuple[bytes, int, int]] = [
        (b".", id(node), _file_type(node.entry.mode)),
        (b"..", id(node.parent or node), _file_type((node.parent or node).entry.mode)),
    ]
    for name, child in node.children.items():
        items.append((name, id(child), _file_type(child.entry.mode)))
    items.sort(key=lambda t: t[0])

    blocks: list[tuple[list[tuple[bytes, int, int]], int]] = []
    cur: list[tuple[bytes, int, int]] = []
    used = 0
    for name, key, ft in items:
        cost = _DIRENT.size + len(name)
        if cur and used + cost > BLKSZ:
            blocks.append((cur, used))
            cur, used = [], 0
        cur.append((name, key, ft))
        used += cost
    if cur:
        blocks.append((cur, used))

    out = io.BytesIO()
    for i, (ents, used) in enumerate(blocks):
        base = out.tell()
        nameoff = len(ents) * _DIRENT.size
        names = io.BytesIO()
        for name, key, ft in ents:
            out.write(_DIRENT.pack(nid_of[key], nameoff + names.tell(), ft, 0))
            names.write(name)
        out.write(names.getvalue())
        if i < len(blocks) - 1:
            out.write(b"\0" * (base + BLKSZ - out.tell()))
    return out.getvalue()


def build_erofs(entries: list[FileEntry], volume_name: bytes = b"ntpu-erofs") -> bytes:
    """Serialize ``entries`` into a mountable EROFS image.

    Hardlinks (``entry.hardlink_target``) share the target's inode and bump
    its nlink. Whiteouts are callers' business (overlay semantics live a
    layer up); xattrs are not yet emitted.
    """
    root = _build_tree(entries)

    # Resolve hardlinks to their target node.
    by_path: dict[str, _Node] = {}

    def index(node: _Node):
        by_path[node.entry.path] = node
        for child in node.children.values():
            index(child)

    index(root)
    alias_of: dict[int, _Node] = {}
    order: list[_Node] = []

    def collect(node: _Node):
        order.append(node)
        for name in sorted(node.children):
            collect(node.children[name])

    collect(root)

    real_nodes: list[_Node] = []
    for node in order:
        tgt_path = node.entry.hardlink_target
        if tgt_path and not node.entry.is_dir:
            # Resolve chains (a hardlink whose target is itself a hardlink)
            # to the final real inode; anything else would emit a dirent
            # pointing at an inode that never gets written.
            target = by_path.get("/" + tgt_path.lstrip("/"))
            seen_ids = {id(node)}
            while target is not None and target.entry.hardlink_target:
                if id(target) in seen_ids:
                    raise ErofsError(f"hardlink cycle via {tgt_path}")
                seen_ids.add(id(target))
                target = by_path.get(
                    "/" + target.entry.hardlink_target.lstrip("/")
                )
            if target is None or target.entry.is_dir:
                raise ErofsError(f"hardlink target missing: {tgt_path}")
            alias_of[id(node)] = target
            target.nlink += 1
        else:
            real_nodes.append(node)

    # nlink for directories: 2 + subdirectories.
    for node in real_nodes:
        if statmod.S_ISDIR(node.entry.mode):
            node.nlink = 2 + sum(
                1 for c in node.children.values() if statmod.S_ISDIR(c.entry.mode)
            )

    # Assign nids: compact inodes are 32 bytes; slot index == nid.
    meta_blkaddr = 1
    for i, node in enumerate(real_nodes):
        node.nid = i
        node.ino = i + 1
    nid_of: dict[int, int] = {}
    for node in order:
        target = alias_of.get(id(node))
        nid_of[id(node)] = (target or node).nid
    root_nid = root.nid
    if root_nid > 0xFFFF:
        raise ErofsError("root nid exceeds the superblock's le16 field")

    # Metadata area size -> first data block.
    meta_bytes = len(real_nodes) * _INODE_COMPACT.size
    meta_blocks = max(1, -(-meta_bytes // BLKSZ))
    data_blkaddr = meta_blkaddr + meta_blocks

    # Lay out data: directories then files, in nid order.
    data = io.BytesIO()

    def alloc(payload: bytes) -> int:
        if not payload:
            return 0
        addr = data_blkaddr + data.tell() // BLKSZ
        data.write(payload)
        pad = -len(payload) % BLKSZ
        data.write(b"\0" * pad)
        return addr

    for node in real_nodes:
        e = node.entry
        if statmod.S_ISDIR(e.mode):
            node.data = _dir_blocks(node, nid_of)
        elif statmod.S_ISLNK(e.mode):
            node.data = e.symlink_target.encode()
        elif statmod.S_ISREG(e.mode):
            node.data = e.data
        else:
            node.data = b""
        node.raw_blkaddr = alloc(node.data)

    # Inode table.
    meta = io.BytesIO()
    for node in real_nodes:
        e = node.entry
        i_format = (_LAYOUT_FLAT_PLAIN << 1) | 0
        if statmod.S_ISCHR(e.mode) or statmod.S_ISBLK(e.mode):
            # kernel new_encode_dev(): minor low byte | major << 8 | rest of
            # minor << 12
            major, minor = os.major(e.rdev), os.minor(e.rdev)
            i_u = (minor & 0xFF) | (major << 8) | ((minor & ~0xFF) << 12)
        else:
            i_u = node.raw_blkaddr
        # Compact (32-byte) inodes cannot represent these; wrapping would
        # produce a silently-corrupt mount, so reject loudly.
        if len(node.data) > 0xFFFFFFFF:
            raise ErofsError(f"{e.path}: size {len(node.data)} exceeds compact inode")
        if node.nlink > 0xFFFF:
            raise ErofsError(f"{e.path}: nlink {node.nlink} exceeds compact inode")
        if e.uid > 0xFFFF or e.gid > 0xFFFF:
            raise ErofsError(f"{e.path}: uid/gid exceed compact inode 16-bit fields")
        meta.write(
            _INODE_COMPACT.pack(
                i_format,
                0,  # no xattrs
                e.mode & 0xFFFF,
                node.nlink,
                len(node.data),
                0,
                i_u,
                node.ino,
                e.uid,
                e.gid,
                0,
            )
        )
    meta_payload = meta.getvalue()
    meta_payload += b"\0" * (meta_blocks * BLKSZ - len(meta_payload))

    data_payload = data.getvalue()
    total_blocks = data_blkaddr + len(data_payload) // BLKSZ

    sb = _SB.pack(
        EROFS_MAGIC,
        0,  # checksum (feature_compat bit unset -> not verified)
        0,  # feature_compat
        BLKSZBITS,
        0,  # sb_extslots
        root_nid,
        len(real_nodes),  # inos
        0,  # build_time
        0,  # build_time_nsec
        total_blocks,
        meta_blkaddr,
        0,  # xattr_blkaddr
        b"\0" * 16,  # uuid
        volume_name[:16].ljust(16, b"\0"),
        0,  # feature_incompat
        0,  # u1 (compression info)
        0,  # extra_devices
        0,  # devt_slotoff
        0,  # dirblkbits
        0,  # xattr_prefix_count
        0,  # xattr_prefix_start
        0,  # packed_nid
        0,  # xattr_filter_reserved
        b"\0" * 23,
    )
    header = bytearray(BLKSZ)
    header[SB_OFFSET : SB_OFFSET + len(sb)] = sb

    return bytes(header) + meta_payload + data_payload
