"""EROFS on-disk image writer: kernel-mountable block images from a file
tree.

The reference's blockdev/tarfs modes hand the kernel a *real* EROFS image
produced by ``nydus-image export --block`` (invoked at
pkg/tarfs/tarfs.go:525-541, mounted with ``mount -t erofs`` at :573-662 via
pkg/utils/erofs). This module is the native equivalent: it serializes a
file tree into the EROFS on-disk format that the in-kernel erofs driver
mounts directly — no external mkfs.erofs, no FUSE in the read path. The
kernel is the format oracle: tests loop-attach the produced images, mount
them, and compare the tree byte-for-byte.

Two shapes:
- **Self-contained** (``build_erofs``): file data inline in the image,
  FLAT_PLAIN layout — the blockdev whole-image export.
- **Chunk-based with a device table** (``chunk_map`` + ``device``): regular
  files become CHUNK_BASED inodes whose 8-byte chunk indexes point into an
  *external blob device* (the uncompressed layer tar, loop-attached and
  passed to the kernel via ``mount -o device=``). This is the tarfs shape:
  the tar IS the data plane, the EROFS image holds only metadata — chunk
  reads go straight from the kernel to the tar with zero copies. Tar file
  data is 512-aligned, so these images use 512-byte blocks (sub-page block
  support, Linux 6.3+).

Format notes (Linux fs/erofs/erofs_fs.h):
- Superblock at offset 1024 (magic 0xE0F5E1E2 — the same magic pkg/layout
  detects at that offset).
- Compact (32-byte) inodes in a metadata area starting at
  ``meta_blkaddr``; an inode's nid is its 32-byte slot index. Chunk
  indexes follow their inode in the slot array.
- Directories are arrays of 12-byte dirents per block, names packed after
  the dirent array, entries sorted bytewise (the kernel binary-searches,
  both across blocks by first-name and within a block).
- Inline xattrs (prefix-indexed entries after the inode; POSIX ACL names
  as exact-match indexes); no compression. feature_compat = 0 keeps the
  checksum optional; feature_incompat carries only
  CHUNKED_FILE|DEVICE_TABLE when used.
"""

from __future__ import annotations

import io
import os
import stat as statmod
import struct
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu.models.fstree import FileEntry

BLKSZ = 4096
BLKSZBITS = 12
EROFS_MAGIC = 0xE0F5E1E2
SB_OFFSET = 1024

# datalayout values (i_format bits 1..3; bit 0 = 0 for compact inodes)
_LAYOUT_FLAT_PLAIN = 0
_LAYOUT_CHUNK_BASED = 4

_CHUNK_FORMAT_INDEXES = 0x0020
_FEATURE_INCOMPAT_CHUNKED_FILE = 0x00000004
_FEATURE_INCOMPAT_DEVICE_TABLE = 0x00000008

_DEVT_SLOT_SIZE = 128
_DEVT_SLOTOFF = (SB_OFFSET + 128) // _DEVT_SLOT_SIZE  # right after the sb

_FT_OF_MODE = [
    (statmod.S_ISREG, 1),
    (statmod.S_ISDIR, 2),
    (statmod.S_ISCHR, 3),
    (statmod.S_ISBLK, 4),
    (statmod.S_ISFIFO, 5),
    (statmod.S_ISSOCK, 6),
    (statmod.S_ISLNK, 7),
]

_SB = struct.Struct("<IIIBBHQQIIII16s16sIHHHBBIQB23s")
assert _SB.size == 128, _SB.size
_INODE_COMPACT = struct.Struct("<HHHHIIIIHHI")
_DIRENT = struct.Struct("<QHBB")
_CHUNK_INDEX = struct.Struct("<HHI")  # advise, device_id, blkaddr
_DEVICE_SLOT = struct.Struct("<64sII56s")
assert _DEVICE_SLOT.size == _DEVT_SLOT_SIZE
_XATTR_IBODY_HEADER = struct.Struct("<IB7s")  # name_filter, shared_count, pad
_XATTR_ENTRY = struct.Struct("<BBH")  # name_len, name_index, value_size

# Well-known xattr name prefixes (erofs_fs.h EROFS_XATTR_INDEX_*). The
# POSIX ACL names are exact matches encoded as an index with an EMPTY
# remaining name.
_XATTR_EXACT = {
    "system.posix_acl_access": 2,
    "system.posix_acl_default": 3,
}
_XATTR_PREFIXES = [
    ("user.", 1),
    ("trusted.", 4),
    ("security.", 6),
]


def _encode_xattrs(xattrs: dict[str, bytes]) -> bytes:
    """Inline xattr ibody: header + 4-aligned entries, sorted for
    determinism. Returns b'' when there are none. Names outside the EROFS
    prefix registry are rejected — index 0 entries would be unreadable on
    the mounted filesystem, a silent data loss."""
    if not xattrs:
        return b""
    body = io.BytesIO()
    body.write(_XATTR_IBODY_HEADER.pack(0, 0, b"\0" * 7))
    for key in sorted(xattrs):
        value = xattrs[key]
        if key in _XATTR_EXACT:
            index, name = _XATTR_EXACT[key], ""
        else:
            for prefix, idx in _XATTR_PREFIXES:
                if key.startswith(prefix) and len(key) > len(prefix):
                    index, name = idx, key[len(prefix) :]
                    break
            else:
                raise ErofsError(f"xattr namespace not representable: {key!r}")
        nb = name.encode()
        if len(nb) > 0xFF or len(value) > 0xFFFF:
            raise ErofsError(f"xattr {key!r} name/value too large")
        body.write(_XATTR_ENTRY.pack(len(nb), index, len(value)))
        body.write(nb)
        body.write(value)
        body.write(b"\0" * (-(_XATTR_ENTRY.size + len(nb) + len(value)) % 4))
    return body.getvalue()


class ErofsError(ValueError):
    pass


@dataclass(frozen=True)
class ChunkedData:
    """Chunk extents for one regular file (tarfs / block-disk shapes).

    ``device_id`` 0 addresses the primary device (the image itself — the
    self-contained disk layout where tar data is appended after the
    metadata); 1 addresses the first extra device (the loop-attached tar).
    """

    size: int
    chunk_size: int  # power of two, >= block size
    offsets: list[int]  # byte offset of each chunk on the target device
    device_id: int = 1


def _file_type(mode: int) -> int:
    for pred, ft in _FT_OF_MODE:
        if pred(mode):
            return ft
    return 0


@dataclass
class _Node:
    entry: FileEntry
    nid: int = 0
    ino: int = 0
    nlink: int = 1
    data: bytes = b""
    size: int = 0
    raw_blkaddr: int = 0
    chunked: Optional[ChunkedData] = None
    xattr_body: bytes = b""
    children: dict[bytes, "_Node"] = field(default_factory=dict)
    parent: Optional["_Node"] = None

    def meta_bytes(self, blkszbits: int) -> bytes:
        """Everything after the 32-byte inode struct in this inode's slot
        run: xattr ibody, then 8-aligned chunk indexes (the kernel reads
        them at ALIGN(iloc + inode_size + xattr_isize, 8))."""
        out = io.BytesIO()
        out.write(self.xattr_body)
        if self.chunked is not None:
            pos = _INODE_COMPACT.size + out.tell()
            out.write(b"\0" * (-pos % 8))
            for off in self.chunked.offsets:
                out.write(
                    _CHUNK_INDEX.pack(0, self.chunked.device_id, off >> blkszbits)
                )
        return out.getvalue()

    def slots(self, blkszbits: int) -> int:
        total = _INODE_COMPACT.size + len(self.meta_bytes(blkszbits))
        return -(-total // _INODE_COMPACT.size)


def _build_tree(entries: list[FileEntry]) -> tuple[_Node, dict[str, "_Node"]]:
    root_entry = FileEntry(path="/", mode=statmod.S_IFDIR | 0o755)
    root = _Node(entry=root_entry)
    by_path: dict[str, _Node] = {"/": root}

    def ensure_dir(path: str) -> _Node:
        node = by_path.get(path)
        if node is not None:
            if not statmod.S_ISDIR(node.entry.mode):
                raise ErofsError(f"{path} used as directory and non-directory")
            return node
        parent = ensure_dir(path.rsplit("/", 1)[0] or "/")
        node = _Node(entry=FileEntry(path=path, mode=statmod.S_IFDIR | 0o755))
        node.parent = parent
        parent.children[path.rsplit("/", 1)[1].encode()] = node
        by_path[path] = node
        return node

    for e in sorted(entries, key=lambda e: e.path):
        if e.path == "/":
            root.entry = e
            continue
        name = e.path.rsplit("/", 1)[1]
        if len(name.encode()) > 255:
            raise ErofsError(f"name too long: {name!r}")
        parent = ensure_dir(e.path.rsplit("/", 1)[0] or "/")
        existing = by_path.get(e.path)
        if existing is not None and statmod.S_ISDIR(existing.entry.mode) and e.is_dir:
            existing.entry = e  # explicit dir entry refines a placeholder
            continue
        node = _Node(entry=e)
        node.parent = parent
        parent.children[name.encode()] = node
        by_path[e.path] = node
    return root, by_path


def _dir_blocks(node: _Node, nid_of: dict[int, int], blksz: int) -> bytes:
    """Serialize one directory's dirent blocks (kernel-sorted)."""
    items: list[tuple[bytes, int, int]] = [
        (b".", id(node), _file_type(node.entry.mode)),
        (b"..", id(node.parent or node), _file_type((node.parent or node).entry.mode)),
    ]
    for name, child in node.children.items():
        items.append((name, id(child), _file_type(child.entry.mode)))
    items.sort(key=lambda t: t[0])

    blocks: list[list[tuple[bytes, int, int]]] = []
    cur: list[tuple[bytes, int, int]] = []
    used = 0
    for name, key, ft in items:
        cost = _DIRENT.size + len(name)
        if cost > blksz:
            raise ErofsError(f"dirent {name!r} exceeds block size {blksz}")
        if cur and used + cost > blksz:
            blocks.append(cur)
            cur, used = [], 0
        cur.append((name, key, ft))
        used += cost
    if cur:
        blocks.append(cur)

    out = io.BytesIO()
    for i, ents in enumerate(blocks):
        base = out.tell()
        nameoff = len(ents) * _DIRENT.size
        names = io.BytesIO()
        for name, key, ft in ents:
            out.write(_DIRENT.pack(nid_of[key], nameoff + names.tell(), ft, 0))
            names.write(name)
        out.write(names.getvalue())
        if i < len(blocks) - 1:
            out.write(b"\0" * (base + blksz - out.tell()))
    return out.getvalue()


def build_erofs(
    entries: list[FileEntry],
    volume_name: bytes = b"ntpu-erofs",
    blkszbits: int = BLKSZBITS,
    chunk_map: Optional[dict[str, ChunkedData]] = None,
    device: Optional[tuple[bytes, int]] = None,
    total_size: Optional[int] = None,
    devices: Optional[list[tuple[bytes, int]]] = None,
) -> bytes:
    """Serialize ``entries`` into a mountable EROFS image.

    Hardlinks (``entry.hardlink_target``) share the target's inode and bump
    its nlink. Whiteouts are callers' business (overlay semantics live a
    layer up); xattrs are emitted inline (user./trusted./security.
    prefixes and POSIX ACL names — anything else raises).

    ``chunk_map`` maps paths of regular files to external-device extents
    (CHUNK_BASED inodes, data read from the blob device); ``devices`` are
    the (tag, size_bytes) of the blob devices in device-table order —
    ``ChunkedData.device_id`` N addresses ``devices[N-1]``, and the kernel
    maps them positionally from the ``-o device=`` list at mount time
    (multi-layer tarfs images carry one tar device per layer). ``device``
    is single-device sugar. Chunk offsets must be block-aligned — tarfs
    callers use ``blkszbits=9`` so 512-aligned tar data qualifies.
    """
    chunk_map = chunk_map or {}
    if devices is None:
        devices = [device] if device is not None else []
    elif device is not None:
        raise ErofsError("pass device or devices, not both")
    for cd in chunk_map.values():
        if cd.device_id > len(devices):
            raise ErofsError(
                f"chunk device_id {cd.device_id} exceeds the "
                f"{len(devices)}-entry device table"
            )
    if not 9 <= blkszbits <= 12:
        raise ErofsError(f"blkszbits {blkszbits} outside the supported 9..12")
    blksz = 1 << blkszbits

    root, by_path = _build_tree(entries)
    alias_of: dict[int, _Node] = {}
    order: list[_Node] = []

    def collect(node: _Node):
        order.append(node)
        for name in sorted(node.children):
            collect(node.children[name])

    collect(root)

    real_nodes: list[_Node] = []
    for node in order:
        tgt_path = node.entry.hardlink_target
        if tgt_path and not node.entry.is_dir:
            # Resolve chains (a hardlink whose target is itself a hardlink)
            # to the final real inode; anything else would emit a dirent
            # pointing at an inode that never gets written.
            target = by_path.get("/" + tgt_path.lstrip("/"))
            seen_ids = {id(node)}
            while target is not None and target.entry.hardlink_target:
                if id(target) in seen_ids:
                    raise ErofsError(f"hardlink cycle via {tgt_path}")
                seen_ids.add(id(target))
                target = by_path.get(
                    "/" + target.entry.hardlink_target.lstrip("/")
                )
            if target is None or target.entry.is_dir:
                raise ErofsError(f"hardlink target missing: {tgt_path}")
            alias_of[id(node)] = target
            target.nlink += 1
        else:
            real_nodes.append(node)

    # nlink for directories: 2 + subdirectories.
    for node in real_nodes:
        if statmod.S_ISDIR(node.entry.mode):
            node.nlink = 2 + sum(
                1 for c in node.children.values() if statmod.S_ISDIR(c.entry.mode)
            )

    # Attach chunked extents and validate them.
    for node in real_nodes:
        cd = chunk_map.get(node.entry.path)
        if cd is None:
            continue
        if not statmod.S_ISREG(node.entry.mode):
            raise ErofsError(f"chunk_map path {node.entry.path} is not a regular file")
        if cd.chunk_size < blksz or cd.chunk_size & (cd.chunk_size - 1):
            raise ErofsError(
                f"chunk size {cd.chunk_size:#x} must be a power of two >= {blksz}"
            )
        expected = max(0, -(-cd.size // cd.chunk_size))
        if len(cd.offsets) != expected:
            raise ErofsError(
                f"{node.entry.path}: {len(cd.offsets)} chunk offsets for "
                f"size {cd.size} (expected {expected})"
            )
        if cd.device_id == 0:
            # Primary-device extents live in this image past the metadata;
            # bounds come from the declared final image size.
            if total_size is None:
                raise ErofsError(
                    f"{node.entry.path}: primary-device chunks need total_size"
                )
            dev_size = total_size
        else:
            # in-range per the device-table guard above
            dev_size = devices[cd.device_id - 1][1]
        for k, off in enumerate(cd.offsets):
            if off % blksz:
                raise ErofsError(
                    f"{node.entry.path}: chunk offset {off:#x} not {blksz}-aligned"
                )
            extent = min(cd.chunk_size, cd.size - k * cd.chunk_size)
            if off + extent > dev_size:
                raise ErofsError(
                    f"{node.entry.path}: chunk [{off:#x}, {off + extent:#x}) "
                    f"outside the {dev_size}-byte device"
                )
        node.chunked = cd

    # Assign nids: slot index in the 32-byte-unit metadata area; xattrs and
    # chunk indexes occupy the slots right after their inode.
    meta_blkaddr_bytes = SB_OFFSET + 128
    if devices:
        meta_blkaddr_bytes = (_DEVT_SLOTOFF + len(devices)) * _DEVT_SLOT_SIZE
    meta_blkaddr = -(-meta_blkaddr_bytes // blksz)
    orphans = set(chunk_map) - set(by_path)
    if orphans:
        raise ErofsError(f"chunk_map paths not in entries: {sorted(orphans)[:3]}")
    slot = 0
    for node in real_nodes:
        node.xattr_body = _encode_xattrs(node.entry.xattrs)
        node.nid = slot
        node.ino = slot + 1
        slot += node.slots(blkszbits)
    total_slots = slot
    nid_of: dict[int, int] = {}
    for node in order:
        target = alias_of.get(id(node))
        nid_of[id(node)] = (target or node).nid
    root_nid = root.nid
    if root_nid > 0xFFFF:
        raise ErofsError("root nid exceeds the superblock's le16 field")

    meta_bytes = total_slots * _INODE_COMPACT.size
    meta_blocks = max(1, -(-meta_bytes // blksz))
    data_blkaddr = meta_blkaddr + meta_blocks

    # Lay out data: in nid order.
    data = io.BytesIO()

    def alloc(payload: bytes) -> int:
        if not payload:
            return 0
        addr = data_blkaddr + data.tell() // blksz
        data.write(payload)
        data.write(b"\0" * (-len(payload) % blksz))
        return addr

    for node in real_nodes:
        e = node.entry
        if statmod.S_ISDIR(e.mode):
            node.data = _dir_blocks(node, nid_of, blksz)
        elif statmod.S_ISLNK(e.mode):
            node.data = e.symlink_target.encode()
        elif statmod.S_ISREG(e.mode) and node.chunked is None:
            node.data = e.data
        else:
            node.data = b""
        node.size = node.chunked.size if node.chunked else len(node.data)
        node.raw_blkaddr = alloc(node.data)

    # Inode table (+ inline chunk indexes).
    meta = io.BytesIO()
    for node in real_nodes:
        e = node.entry
        if node.chunked is not None:
            layout = _LAYOUT_CHUNK_BASED
            chunkbits = node.chunked.chunk_size.bit_length() - 1 - blkszbits
            i_u = _CHUNK_FORMAT_INDEXES | chunkbits
        elif statmod.S_ISCHR(e.mode) or statmod.S_ISBLK(e.mode):
            layout = _LAYOUT_FLAT_PLAIN
            # kernel new_encode_dev(): minor low byte | major << 8 | rest of
            # minor << 12
            major, minor = os.major(e.rdev), os.minor(e.rdev)
            i_u = (minor & 0xFF) | (major << 8) | ((minor & ~0xFF) << 12)
        else:
            layout = _LAYOUT_FLAT_PLAIN
            i_u = node.raw_blkaddr
        # Compact (32-byte) inodes cannot represent these; wrapping would
        # produce a silently-corrupt mount, so reject loudly.
        if node.size > 0xFFFFFFFF:
            raise ErofsError(f"{e.path}: size {node.size} exceeds compact inode")
        if node.nlink > 0xFFFF:
            raise ErofsError(f"{e.path}: nlink {node.nlink} exceeds compact inode")
        if e.uid > 0xFFFF or e.gid > 0xFFFF:
            raise ErofsError(f"{e.path}: uid/gid exceed compact inode 16-bit fields")
        # i_xattr_icount: ibody bytes = 12 + 4*(icount-1) (erofs_fs.h).
        xattr_icount = (
            1 + (len(node.xattr_body) - _XATTR_IBODY_HEADER.size) // 4
            if node.xattr_body
            else 0
        )
        meta.write(
            _INODE_COMPACT.pack(
                (layout << 1) | 0,
                xattr_icount,
                e.mode & 0xFFFF,
                node.nlink,
                node.size,
                0,
                i_u,
                node.ino,
                e.uid,
                e.gid,
                0,
            )
        )
        body = node.meta_bytes(blkszbits)
        meta.write(body)
        meta.write(b"\0" * (-(_INODE_COMPACT.size + len(body)) % _INODE_COMPACT.size))
    meta_payload = meta.getvalue()
    meta_payload += b"\0" * (meta_blocks * blksz - len(meta_payload))

    data_payload = data.getvalue()
    total_blocks = data_blkaddr + len(data_payload) // blksz
    if total_size is not None:
        if total_size % blksz:
            raise ErofsError(f"total_size {total_size} not block-aligned")
        if total_size // blksz < total_blocks:
            raise ErofsError(
                f"total_size {total_size} smaller than the metadata+data area"
            )
        total_blocks = total_size // blksz

    feature_incompat = 0
    extra_devices = 0
    devt_slotoff = 0
    if devices:
        extra_devices = len(devices)
        devt_slotoff = _DEVT_SLOTOFF
        feature_incompat |= _FEATURE_INCOMPAT_DEVICE_TABLE
    if chunk_map:
        feature_incompat |= _FEATURE_INCOMPAT_CHUNKED_FILE

    sb = _SB.pack(
        EROFS_MAGIC,
        0,  # checksum (feature_compat bit unset -> not verified)
        0,  # feature_compat
        blkszbits,
        0,  # sb_extslots
        root_nid,
        len(real_nodes),  # inos
        0,  # build_time
        0,  # build_time_nsec
        total_blocks,
        meta_blkaddr,
        0,  # xattr_blkaddr
        b"\0" * 16,  # uuid
        volume_name[:16].ljust(16, b"\0"),
        feature_incompat,
        0,  # u1 (compression info)
        extra_devices,
        devt_slotoff,
        0,  # dirblkbits
        0,  # xattr_prefix_count
        0,  # xattr_prefix_start
        0,  # packed_nid
        0,  # xattr_filter_reserved
        b"\0" * 23,
    )
    header = bytearray(meta_blkaddr * blksz)
    header[SB_OFFSET : SB_OFFSET + len(sb)] = sb
    for i, (tag, size_bytes) in enumerate(devices):
        slot_off = (_DEVT_SLOTOFF + i) * _DEVT_SLOT_SIZE
        header[slot_off : slot_off + _DEVT_SLOT_SIZE] = _DEVICE_SLOT.pack(
            tag[:64].ljust(64, b"\0"),
            -(-size_bytes // blksz),
            0,  # mapped_blkaddr: unused with explicit chunk device ids
            b"\0" * 56,
        )

    return bytes(header) + meta_payload + data_payload


def write_erofs_disk(bootstrap, tar_path_of, out) -> int:
    """Self-contained block image: EROFS metadata + the referenced tar
    blobs appended, chunks addressing the PRIMARY device — one image,
    mountable alone (the reference's ``nydus-image export --block`` whole
    -image shape, tarfs.go:466-571; Kata direct-block volumes consume it).

    ``tar_path_of(blob_id)`` locates each referenced layer tar on disk;
    ``out`` is a seekable binary stream. Returns the data size written
    (the dm-verity tree, if any, is appended by the caller after this).
    """
    import shutil

    if not bootstrap.blobs:
        raise ErofsError("bootstrap references no blobs")

    from nydus_snapshotter_tpu.models import fstree

    entries: list[FileEntry] = []
    file_chunks: dict[str, list] = {}
    for inode in bootstrap.inodes:
        entries.append(fstree.inode_to_entry(inode, b""))
        if statmod.S_ISREG(inode.mode) and not inode.hardlink_target and inode.chunk_count:
            recs = bootstrap.chunks[
                inode.chunk_index : inode.chunk_index + inode.chunk_count
            ]
            for rec in recs:
                if rec.uncompressed_offset != rec.compressed_offset:
                    raise ErofsError(
                        f"{inode.path}: chunk not identity-mapped; only tarfs "
                        "bootstraps (the tar is the uncompressed blob) can "
                        "export to a block disk"
                    )
            file_chunks[inode.path] = recs

    def chunk_map_with(blob_base: list[int]) -> dict[str, ChunkedData]:
        cm: dict[str, ChunkedData] = {}
        for path, recs in file_chunks.items():
            size = sum(r.uncompressed_size for r in recs)
            cm[path] = ChunkedData(
                size=size,
                chunk_size=bootstrap.chunk_size,
                offsets=[blob_base[r.blob_index] + r.uncompressed_offset for r in recs],
                device_id=0,
            )
        return cm

    blob_sizes = []
    for blob in bootstrap.blobs:
        blob_sizes.append(os.path.getsize(tar_path_of(blob.blob_id)))

    # Pass 1: probe the metadata area size with zero offsets (same chunk
    # counts -> identical meta layout), then place the tars after it.
    # Probe bound: large enough for any real disk, small enough for the
    # le32 sb.blocks field (2^40 bytes / 512 = 2^31 blocks).
    probe_bound = 1 << 40
    zero_base = [0] * len(bootstrap.blobs)
    probe = build_erofs(
        entries,
        blkszbits=9,
        chunk_map=chunk_map_with(zero_base),
        total_size=probe_bound,
    )
    meta_size = len(probe)
    blob_base = []
    pos = meta_size
    for size in blob_sizes:
        pos += -pos % 512
        blob_base.append(pos)
        pos += size
    total = pos + (-pos % 512)

    img = build_erofs(
        entries,
        blkszbits=9,
        chunk_map=chunk_map_with(blob_base),
        total_size=total,
    )
    if len(img) != meta_size:
        raise ErofsError("metadata size changed between layout passes")
    start = out.tell()
    out.write(img)
    for blob, size, base in zip(bootstrap.blobs, blob_sizes, blob_base):
        out.write(b"\0" * (start + base - out.tell()))
        with open(tar_path_of(blob.blob_id), "rb") as tf:
            shutil.copyfileobj(tf, out, 1 << 20)
        if out.tell() != start + base + size:
            raise ErofsError(
                f"blob {blob.blob_id} wrote {out.tell() - start - base} bytes, "
                f"probed {size} — file changed during export"
            )
    out.write(b"\0" * (start + total - out.tell()))
    return total


def erofs_from_rafs(bootstrap, device_tag: bytes = b"") -> bytes:
    """RAFS bootstrap whose chunks index uncompressed blobs (the tarfs
    shape, tarfs/bootstrap.py) → kernel-mountable EROFS meta image with
    one device per blob, in blob-table order.

    This replaces the reference's ``nydus-image export --block`` for the
    tarfs path (tarfs.go:525-541): mount the returned image with
    ``-o device=<loop of tar 1>,device=<loop of tar 2>,…`` (the kernel
    maps the list positionally onto the device table) and file bytes are
    read straight from the layer tars. A merged multi-layer image carries
    one tar device per layer; single-layer bootstraps keep the original
    one-device shape. Chunks must be identity-mapped (uncompressed ==
    compressed offsets) and 512-aligned, which tarfs bootstraps are by
    construction. Opaque-directory xattrs (trusted.overlay.opaque) and
    whiteout char devices both carry through, so overlayfs layering over
    the mount behaves like the reference's.
    """
    from nydus_snapshotter_tpu.models import fstree

    if not bootstrap.blobs:
        raise ErofsError("tarfs export needs at least one blob")
    if device_tag and len(bootstrap.blobs) > 1:
        raise ErofsError("device_tag override only applies to one-blob images")
    entries: list[FileEntry] = []
    chunk_map: dict[str, ChunkedData] = {}
    for inode in bootstrap.inodes:
        entries.append(fstree.inode_to_entry(inode, b""))
        if not statmod.S_ISREG(inode.mode) or inode.hardlink_target or not inode.chunk_count:
            continue
        recs = bootstrap.chunks[inode.chunk_index : inode.chunk_index + inode.chunk_count]
        for rec in recs:
            if rec.uncompressed_offset != rec.compressed_offset:
                raise ErofsError(
                    f"{inode.path}: chunk not identity-mapped; "
                    "only tarfs bootstraps export to EROFS"
                )
        blob_ids = {rec.blob_index for rec in recs}
        if len(blob_ids) != 1:
            raise ErofsError(
                f"{inode.path}: chunks span blobs {sorted(blob_ids)}; "
                "tarfs files live in exactly one layer tar"
            )
        chunk_map[inode.path] = ChunkedData(
            size=inode.size,
            chunk_size=bootstrap.chunk_size,
            offsets=[rec.uncompressed_offset for rec in recs],
            device_id=recs[0].blob_index + 1,
        )
    return build_erofs(
        entries,
        blkszbits=9,
        chunk_map=chunk_map,
        devices=[
            (
                device_tag if (i == 0 and device_tag) else b.blob_id.encode(),
                b.compressed_size,
            )
            for i, b in enumerate(bootstrap.blobs)
        ],
    )
