"""Blob table-of-contents entries.

Binary-compatible with the reference's 128-byte ``TOCEntry``
(pkg/converter/types.go:147-202): little-endian, fields at the same offsets,
including the trailing alignment pad. A nydus blob that carries the
``blob-toc`` feature ends with a run of these entries describing the sections
(chunk data, inline meta, digest) inside the blob.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from nydus_snapshotter_tpu import constants

# Flags u32 | Reserved1 u32 | Name [16] | UncompressedDigest [32]
# | CompressedOffset u64 | CompressedSize u64 | UncompressedSize u64
# | Reserved2 [44] | pad to 128 (Go struct alignment).
_TOC_STRUCT = struct.Struct("<II16s32sQQQ44s4x")
TOC_ENTRY_SIZE = 128
assert _TOC_STRUCT.size == TOC_ENTRY_SIZE

# Well-known section names inside a nydus blob
# (reference pkg/converter/convert_unix.go:45-49).
ENTRY_BLOB_DATA = "image.blob"
ENTRY_BLOB_META = "blob.meta"
ENTRY_BLOB_META_HEADER = "blob.meta.header"
ENTRY_BLOB_DIGEST = "blob.digest"
ENTRY_BLOB_TOC = "rafs.blob.toc"
ENTRY_BOOTSTRAP = "image.boot"


class TOCError(ValueError):
    pass


@dataclass
class TOCEntry:
    name: str
    flags: int = 0
    uncompressed_digest: bytes = b"\x00" * 32
    compressed_offset: int = 0
    compressed_size: int = 0
    uncompressed_size: int = 0

    def compressor(self) -> int:
        c = self.flags & constants.COMPRESSOR_MASK
        if c in (
            constants.COMPRESSOR_NONE,
            constants.COMPRESSOR_ZSTD,
            constants.COMPRESSOR_LZ4_BLOCK,
        ):
            return c
        raise TOCError(f"unsupported compressor, entry flags {self.flags:#x}")

    def pack(self) -> bytes:
        name = self.name.encode()
        if len(name) > 16:
            raise TOCError(f"TOC entry name too long: {self.name!r}")
        if len(self.uncompressed_digest) != 32:
            raise TOCError("uncompressed digest must be 32 bytes")
        return _TOC_STRUCT.pack(
            self.flags,
            0,
            name.ljust(16, b"\x00"),
            self.uncompressed_digest,
            self.compressed_offset,
            self.compressed_size,
            self.uncompressed_size,
            b"\x00" * 44,
        )

    @classmethod
    def unpack(cls, buf: bytes) -> "TOCEntry":
        if len(buf) != TOC_ENTRY_SIZE:
            raise TOCError(f"TOC entry must be {TOC_ENTRY_SIZE} bytes, got {len(buf)}")
        flags, _r1, name, digest, coff, csize, usize, _r2 = _TOC_STRUCT.unpack(buf)
        return cls(
            name=name.split(b"\x00", 1)[0].decode(),
            flags=flags,
            uncompressed_digest=digest,
            compressed_offset=coff,
            compressed_size=csize,
            uncompressed_size=usize,
        )


def pack_toc(entries: list[TOCEntry]) -> bytes:
    return b"".join(e.pack() for e in entries)


def unpack_toc(buf: bytes) -> list[TOCEntry]:
    if len(buf) % TOC_ENTRY_SIZE != 0:
        raise TOCError(f"TOC size {len(buf)} not a multiple of {TOC_ENTRY_SIZE}")
    return [
        TOCEntry.unpack(buf[i : i + TOC_ENTRY_SIZE])
        for i in range(0, len(buf), TOC_ENTRY_SIZE)
    ]
