"""OCI layer tar ↔ file tree, with overlay (whiteout) semantics.

The reference delegates tar parsing to the Rust builder; here the host owns
it: an OCI layer tar becomes a list of ``FileEntry`` (metadata + bytes), the
overlay merge applies OCI whiteouts the way RAFS does (``.wh.foo`` becomes an
overlayfs char-0:0 whiteout node, ``.wh..wh..opq`` sets the opaque xattr on
its directory — so the mounted RAFS works directly as an overlayfs lowerdir),
and a tree serializes back to a deterministic tar for Unpack
(reference Unpack surface: pkg/converter/convert_unix.go:669-733).
"""

from __future__ import annotations

import io
import os
import stat
import tarfile
from dataclasses import dataclass, field
from typing import BinaryIO, Iterable, Optional

from nydus_snapshotter_tpu.models.bootstrap import (
    INODE_FLAG_HARDLINK,
    INODE_FLAG_OPAQUE,
    INODE_FLAG_SYMLINK,
    INODE_FLAG_WHITEOUT,
    Inode,
)

WHITEOUT_PREFIX = ".wh."
OPAQUE_MARKER = ".wh..wh..opq"
OPAQUE_XATTR = "trusted.overlay.opaque"


class FsTreeError(ValueError):
    pass


@dataclass
class FileEntry:
    """One node of a layer's file tree."""

    path: str  # absolute, "/" separated, no trailing slash (except root)
    mode: int = 0o644  # full st_mode including file type bits
    uid: int = 0
    gid: int = 0
    rdev: int = 0
    mtime: int = 0
    symlink_target: str = ""
    hardlink_target: str = ""
    xattrs: dict[str, bytes] = field(default_factory=dict)
    data: bytes = b""
    flags: int = 0

    @property
    def is_dir(self) -> bool:
        return stat.S_ISDIR(self.mode)

    @property
    def is_regular(self) -> bool:
        return stat.S_ISREG(self.mode) and not self.hardlink_target

    @property
    def is_whiteout(self) -> bool:
        return bool(self.flags & INODE_FLAG_WHITEOUT)

    def size(self) -> int:
        return len(self.data)


def _norm(name: str) -> str:
    name = "/" + name.strip("/")
    return name if name != "//" else "/"


# Public alias: the streaming Pack normalizes paths the same way.
norm_path = _norm


def classify_special(path: str) -> Optional[tuple[str, str]]:
    """OCI special-marker classification for one normalized member path.

    Returns ("opaque", dir_path) for ``.wh..wh..opq`` markers,
    ("whiteout", target_path) for ``.wh.<name>`` markers, None for regular
    members — the single definition of whiteout naming shared by
    ``tree_from_tar`` and the streaming Pack.
    """
    base = path.rsplit("/", 1)[1] if path != "/" else "/"
    if base == OPAQUE_MARKER:
        return ("opaque", path.rsplit("/", 1)[0] or "/")
    if base.startswith(WHITEOUT_PREFIX):
        target = _norm(path.rsplit("/", 1)[0] + "/" + base[len(WHITEOUT_PREFIX):])
        return ("whiteout", target)
    return None


def whiteout_entry(target: str) -> FileEntry:
    """The RAFS/overlayfs form of a whiteout: a char-0:0 node."""
    return FileEntry(path=target, mode=stat.S_IFCHR, rdev=0, flags=INODE_FLAG_WHITEOUT)


def missing_parents(paths: Iterable[str]) -> list[str]:
    """Directories (incl. root) a path set references but does not contain."""
    have = set(paths)
    missing: set[str] = set()
    for p in have:
        q = p
        while q != "/":
            q = q.rsplit("/", 1)[0] or "/"
            if q not in have:
                missing.add(q)
    if "/" not in have:
        missing.add("/")
    return sorted(missing)


def tree_from_tar(fileobj: BinaryIO | bytes) -> list[FileEntry]:
    """Parse an (uncompressed) OCI layer tar into file entries.

    Whiteout markers are converted to RAFS/overlayfs form here so the rest
    of the stack never sees ``.wh.`` names: ``.wh.<name>`` → char-dev 0:0
    entry with the whiteout flag; ``.wh..wh..opq`` → opaque flag + xattr on
    the containing directory entry (synthesized if the tar lacks one).
    """
    if isinstance(fileobj, (bytes, bytearray)):
        fileobj = io.BytesIO(fileobj)
    entries: dict[str, FileEntry] = {}
    opaque_dirs: list[str] = []
    with tarfile.open(fileobj=fileobj, mode="r:") as tf:
        for info in tf:
            path = _norm(info.name)
            special = classify_special(path)
            if special is not None:
                kind, target = special
                if kind == "opaque":
                    opaque_dirs.append(target)
                else:
                    entries[target] = whiteout_entry(target)
                continue
            entry = entry_from_tarinfo(tf, info, path)
            entries[path] = entry
    for d in opaque_dirs:
        if d not in entries:
            entries[d] = FileEntry(path=d, mode=stat.S_IFDIR | 0o755)
        entries[d].flags |= INODE_FLAG_OPAQUE
        entries[d].xattrs[OPAQUE_XATTR] = b"y"
    return sorted(entries.values(), key=lambda e: e.path)


def entry_from_tarinfo(
    tf: tarfile.TarFile, info: tarfile.TarInfo, path: str, with_data: bool = True
) -> FileEntry:
    # tarfile decodes pax values as utf-8 with surrogateescape; encoding back
    # the same way round-trips arbitrary binary xattrs (e.g. the
    # security.capability on ping/sudo) losslessly.
    xattrs = {
        k[len("SCHILY.xattr.") :]: (
            v.encode("utf-8", "surrogateescape") if isinstance(v, str) else v
        )
        for k, v in (info.pax_headers or {}).items()
        if k.startswith("SCHILY.xattr.")
    }
    try:
        # RAFS stores mtime as u64; a pre-epoch (negative, GNU base-256)
        # tar mtime clamps to the epoch rather than crashing serialization.
        mtime = max(0, int(info.mtime))
        if mtime > 0xFFFF_FFFF_FFFF_FFFF:
            raise ValueError("mtime exceeds u64")
    except (ValueError, OverflowError) as exc:
        # pax can smuggle nan/inf/1e300 through float(); surface the
        # documented conversion error type instead of a bare
        # ValueError/struct.error downstream.
        from nydus_snapshotter_tpu.converter.types import ConvertError

        raise ConvertError(
            f"tar member {path!r} has invalid mtime {info.mtime!r}"
        ) from exc
    e = FileEntry(
        path=path,
        uid=info.uid,
        gid=info.gid,
        mtime=mtime,
        xattrs=xattrs,
    )
    perm = info.mode & 0o7777
    if info.isdir():
        e.mode = stat.S_IFDIR | perm
    elif info.issym():
        e.mode = stat.S_IFLNK | perm
        e.symlink_target = info.linkname
        e.flags |= INODE_FLAG_SYMLINK
    elif info.islnk():
        e.mode = stat.S_IFREG | perm
        e.hardlink_target = _norm(info.linkname)
        e.flags |= INODE_FLAG_HARDLINK
    elif info.ischr():
        e.mode = stat.S_IFCHR | perm
        e.rdev = os.makedev(info.devmajor, info.devminor)
    elif info.isblk():
        e.mode = stat.S_IFBLK | perm
        e.rdev = os.makedev(info.devmajor, info.devminor)
    elif info.isfifo():
        e.mode = stat.S_IFIFO | perm
    elif info.isreg():
        e.mode = stat.S_IFREG | perm
        if with_data:
            f = tf.extractfile(info)
            e.data = f.read() if f is not None else b""
    else:
        raise FsTreeError(f"unsupported tar entry type {info.type!r} at {path}")
    return e


def ensure_parents(entries: list[FileEntry]) -> list[FileEntry]:
    """Synthesize the root and any parent directories a tar omitted."""
    by_path = {e.path: e for e in entries}
    for p in missing_parents(by_path):
        by_path[p] = FileEntry(path=p, mode=stat.S_IFDIR | 0o755)
    return sorted(by_path.values(), key=lambda e: e.path)


def apply_overlay(lower: Iterable[FileEntry], upper: Iterable[FileEntry]) -> list[FileEntry]:
    """Overlay-merge two layers (upper wins), applying whiteouts.

    Mirrors the merge semantics the reference gets from ``nydus-image merge``
    (pkg/converter/convert_unix.go:560-666): upper entries replace lower
    ones; a whiteout deletes the lower path (and subtree); an opaque
    directory hides the whole lower subtree.
    """
    merged: dict[str, FileEntry] = {e.path: e for e in lower}
    for e in upper:
        if e.is_whiteout:
            merged.pop(e.path, None)
            _drop_subtree(merged, e.path)
            continue
        if e.flags & INODE_FLAG_OPAQUE:
            _drop_subtree(merged, e.path)
        old = merged.get(e.path)
        if old is not None and old.is_dir and not e.is_dir:
            _drop_subtree(merged, e.path)
        merged[e.path] = e
    return sorted(merged.values(), key=lambda x: x.path)


def _drop_subtree(merged: dict[str, FileEntry], path: str) -> None:
    prefix = path.rstrip("/") + "/"
    for p in [p for p in merged if p.startswith(prefix)]:
        del merged[p]


def tar_from_tree(entries: list[FileEntry]) -> bytes:
    """Serialize a tree back to a deterministic tar (Unpack surface).

    Whiteout nodes are re-encoded as ``.wh.`` markers so a round trip
    reproduces OCI layer semantics.
    """
    out = io.BytesIO()
    with tarfile.open(fileobj=out, mode="w:", format=tarfile.PAX_FORMAT) as tf:
        for e in sorted(entries, key=lambda x: x.path):
            if e.path == "/":
                continue
            name = e.path.lstrip("/")
            if e.is_whiteout:
                parent, _, base = e.path.rpartition("/")
                info = tarfile.TarInfo((parent + "/" + WHITEOUT_PREFIX + base).lstrip("/"))
                info.size = 0
                tf.addfile(info)
                continue
            info = tarfile.TarInfo(name)
            info.mode = e.mode & 0o7777
            info.uid, info.gid, info.mtime = e.uid, e.gid, e.mtime
            if e.xattrs:
                info.pax_headers.update(
                    {
                        f"SCHILY.xattr.{k}": v.decode("utf-8", "surrogateescape")
                        for k, v in e.xattrs.items()
                    }
                )
            data = None
            if e.hardlink_target:
                info.type = tarfile.LNKTYPE
                info.linkname = e.hardlink_target.lstrip("/")
            elif stat.S_ISDIR(e.mode):
                info.type = tarfile.DIRTYPE
            elif stat.S_ISLNK(e.mode):
                info.type = tarfile.SYMTYPE
                info.linkname = e.symlink_target
            elif stat.S_ISCHR(e.mode):
                info.type = tarfile.CHRTYPE
                info.devmajor, info.devminor = os.major(e.rdev), os.minor(e.rdev)
            elif stat.S_ISBLK(e.mode):
                info.type = tarfile.BLKTYPE
                info.devmajor, info.devminor = os.major(e.rdev), os.minor(e.rdev)
            elif stat.S_ISFIFO(e.mode):
                info.type = tarfile.FIFOTYPE
            else:
                info.type = tarfile.REGTYPE
                info.size = len(e.data)
                data = io.BytesIO(e.data)
            tf.addfile(info, data)
    return out.getvalue()


# -- bootstrap bridging ------------------------------------------------------


def entry_to_inode(e: FileEntry) -> Inode:
    return Inode(
        path=e.path,
        mode=e.mode,
        uid=e.uid,
        gid=e.gid,
        rdev=e.rdev,
        mtime=e.mtime,
        size=len(e.data),
        flags=e.flags,
        symlink_target=e.symlink_target,
        hardlink_target=e.hardlink_target,
        xattrs=dict(e.xattrs),
    )


def inode_to_entry(inode: Inode, data: bytes = b"") -> FileEntry:
    return FileEntry(
        path=inode.path,
        mode=inode.mode,
        uid=inode.uid,
        gid=inode.gid,
        rdev=inode.rdev,
        mtime=inode.mtime,
        symlink_target=inode.symlink_target,
        hardlink_target=inode.hardlink_target,
        xattrs=dict(inode.xattrs),
        data=data,
        flags=inode.flags,
    )
