"""Writers for REAL nydus-toolchain bootstrap layouts (RAFS v5, v6).

models/nydus_real.py made real bootstraps first-class *inputs*; this
module is the other direction: serialize a bootstrap in the reference
toolchain's own on-disk layout, so images this framework converts can be
consumed by the reference ecosystem (nydusd mounts v5/v6 bootstraps
produced by `nydus-image`; pkg/filesystem/fs.go:268-431 never sees any
other format). Layout knowledge is the same field maps the reader was
validated with on the committed real fixtures; the reader is the
round-trip oracle for everything written here.

Digest semantics (reverse-engineered structurally from the v5 fixture,
where every one of its 3,517 inode digests matches):

- regular file:  H(concat of its chunk digests)   (2602/2602 fixture inodes)
- symlink:       H(target bytes)                  (212/212)
- directory:     H(concat of child digests, children sorted by name,
                 computed bottom-up)              (678/678)
- empty file / special file: H(b"")
- hardlink alias: the target inode's digest

with H = blake3 (RafsSuperFlags 0x4, the toolchain default — see
utils/blake3.py) or sha256 (0x8). `real_from_bootstrap` computes these
when bridging the framework's internal model; fixture-parsed
RealBootstraps keep their digests verbatim.

Superblock flag bits (nydus RafsSuperFlags, validated against both
fixtures: v5 carries 0x16, v6 carries 0x6):
0x1 none / 0x2 lz4_block / 0x40 gzip / 0x80 zstd compressor;
0x4 blake3 / 0x8 sha256 digester; 0x10 explicit uid/gid; 0x20 xattrs.
"""

from __future__ import annotations

import hashlib
import io
import os
import stat as statmod
import struct

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.models import layout
from nydus_snapshotter_tpu.models.nydus_real import (
    RealBlob,
    RealBootstrap,
    RealBootstrapError,
    RealChunk,
    RealInode,
    _V5_CHUNK,
    _V5_FLAG_HARDLINK,
    _V5_FLAG_SYMLINK,
    _V5_FLAG_XATTR,
    _V5_INODE,
    _V5_SB,
)
from nydus_snapshotter_tpu.utils.blake3 import blake3

__all__ = ["real_from_bootstrap", "write_real_v5", "write_real_v6"]

_FLAG_COMP_NONE = 0x1
_FLAG_COMP_LZ4 = 0x2
_FLAG_HASH_BLAKE3 = 0x4
_FLAG_HASH_SHA256 = 0x8
_FLAG_EXPLICIT_UIDGID = 0x10
_FLAG_HAS_XATTR = 0x20
_FLAG_COMP_GZIP = 0x40
_FLAG_COMP_ZSTD = 0x80

_CHUNK_FLAG_COMPRESSED = 0x1

_V5_SB_SIZE = 8192


def _align8(n: int) -> int:
    return (n + 7) & ~7


def _digester(name: str):
    if name == "blake3":
        return blake3
    if name == "sha256":
        return lambda b: hashlib.sha256(b).digest()
    raise RealBootstrapError(f"unknown digester {name!r}")


def _comp_flag_of(bootstrap) -> int:
    """Superblock compressor bit from the internal chunk flags."""
    for ck in bootstrap.chunks:
        c = ck.flags & constants.COMPRESSOR_MASK
        if c == constants.COMPRESSOR_LZ4_BLOCK:
            return _FLAG_COMP_LZ4
        if c == constants.COMPRESSOR_ZSTD:
            return _FLAG_COMP_ZSTD
        if c == constants.COMPRESSOR_GZIP:
            return _FLAG_COMP_GZIP
    return _FLAG_COMP_NONE


def real_from_bootstrap(bootstrap, digester: str = "sha256") -> RealBootstrap:
    """Bridge the framework's internal model (models/bootstrap.Bootstrap)
    into a RealBootstrap ready for the real-layout writers.

    Inode digests are computed per the reference formulas above (the
    internal model does not carry them); v5 per-inode chunk runs get
    file_offset/index fields the internal shared chunk table does not
    track. Chunk digests pass through as-is — they are sha256 from the
    pack engine, so pick digester="sha256" (the toolchain's own
    `--digester sha256` mode) unless the caller rehashed with blake3.
    """
    H = _digester(digester)

    blobs = [
        RealBlob(
            blob_id=b.blob_id,
            chunk_count=b.chunk_count,
            compressed_size=b.compressed_size,
            uncompressed_size=b.uncompressed_size,
            chunk_size=bootstrap.chunk_size,
        )
        for b in bootstrap.blobs
    ]

    # Per-blob chunk ordinals for the v5 records' index field.
    ordinal: dict[tuple[int, int], int] = {}
    per_blob: dict[int, list[int]] = {}
    for ck in bootstrap.chunks:
        per_blob.setdefault(ck.blob_index, []).append(ck.compressed_offset)
    for bi, offs in per_blob.items():
        for i, off in enumerate(sorted(set(offs))):
            ordinal[(bi, off)] = i

    by_path: dict[str, RealInode] = {}
    ino_of_path: dict[str, int] = {}
    next_ino = 1
    reals: list[RealInode] = []
    # Two passes: hardlink aliases resolve against their target inode, and
    # a tar may name the alias before the target in path order.
    ordered = sorted(bootstrap.inodes, key=lambda i: i.path)
    for ino in [i for i in ordered if not i.hardlink_target] + [
        i for i in ordered if i.hardlink_target
    ]:
        target = ino.hardlink_target
        if target:
            tpath = "/" + target.lstrip("/")
            num = ino_of_path.get(tpath)
            if num is None:
                raise RealBootstrapError(f"hardlink target missing: {target}")
        else:
            num = next_ino
            next_ino += 1
        ri = RealInode(
            path=ino.path,
            ino=num,
            mode=ino.mode,
            uid=ino.uid,
            gid=ino.gid,
            mtime=ino.mtime,
            size=ino.size,
            nlink=1,
            rdev=ino.rdev,
            flags=0,
            symlink_target=ino.symlink_target,
            xattrs=dict(ino.xattrs),
        )
        if ri.is_symlink:
            ri.flags |= _V5_FLAG_SYMLINK
            # POSIX (and the real builder): a symlink's size is its
            # target length; tar stores 0
            ri.size = len(ri.symlink_target.encode("utf-8", "surrogateescape"))
        if ri.xattrs:
            ri.flags |= _V5_FLAG_XATTR
        if target:
            # a hardlink IS its target inode: aliases carry the head's
            # attributes (v6 serializes one inode for the whole group)
            ri.flags |= _V5_FLAG_HARDLINK
            head = by_path["/" + target.lstrip("/")]
            ri.chunks = head.chunks
            ri.size = head.size
            ri.mode = head.mode
            ri.uid, ri.gid = head.uid, head.gid
            ri.mtime = head.mtime
            ri.digest = b""  # filled after head digests are computed
        elif ino.chunk_count:
            pos = 0
            for rec in bootstrap.chunks[
                ino.chunk_index : ino.chunk_index + ino.chunk_count
            ]:
                ri.chunks.append(
                    RealChunk(
                        digest=rec.digest,
                        blob_index=rec.blob_index,
                        flags=(
                            _CHUNK_FLAG_COMPRESSED
                            if (rec.flags & constants.COMPRESSOR_MASK)
                            not in (0, constants.COMPRESSOR_NONE)
                            else 0
                        ),
                        compressed_size=rec.compressed_size,
                        uncompressed_size=rec.uncompressed_size,
                        compressed_offset=rec.compressed_offset,
                        uncompressed_offset=rec.uncompressed_offset,
                        file_offset=pos,
                        index=ordinal.get(
                            (rec.blob_index, rec.compressed_offset), 0
                        ),
                    )
                )
                pos += rec.uncompressed_size
        reals.append(ri)
        by_path[ri.path] = ri
        ino_of_path[ri.path] = num
    reals.sort(key=lambda r: r.path)

    # nlink: hardlink group sizes; directories 2 + subdirectories.
    group_size: dict[int, int] = {}
    for ri in reals:
        group_size[ri.ino] = group_size.get(ri.ino, 0) + 1
    children: dict[str, list[RealInode]] = {}
    for ri in reals:
        if ri.path != "/":
            parent = ri.path.rsplit("/", 1)[0] or "/"
            children.setdefault(parent, []).append(ri)
    for ri in reals:
        if ri.is_dir:
            ri.nlink = 2 + sum(1 for c in children.get(ri.path, []) if c.is_dir)
        else:
            ri.nlink = group_size[ri.ino]

    # ino numbers follow the real builder's convention: the head's
    # 1-based slot in the v5 pre-order table (v6 images carry the same
    # numbers — fixture-verified: /etc=5, /var=22 match their v5 slots).
    probe = RealBootstrap(
        version=layout.RAFS_V5, flags=0, inodes=reals, blobs=[], chunks=[]
    )
    order, _, _ = _table_order(probe)
    slot_of: dict[int, int] = {}
    for slot, ri in enumerate(order, start=1):
        slot_of.setdefault(ri.ino, slot)
    for ri in reals:
        ri.ino = slot_of[ri.ino]
    ino_of_path = {ri.path: ri.ino for ri in reals}

    # Digests. Leaves first (files/symlinks), then hardlink aliases (their
    # head is always a non-directory, so it is final by then — an alias
    # must contribute its target's digest to its parent directory's hash,
    # not a placeholder), then directories bottom-up.
    for ri in reals:
        if ri.flags & _V5_FLAG_HARDLINK or ri.is_dir:
            continue
        if ri.is_symlink:
            ri.digest = H(ri.symlink_target.encode())
        elif ri.chunks:
            ri.digest = H(b"".join(c.digest for c in ri.chunks))
        else:
            ri.digest = H(b"")
    head_of: dict[int, RealInode] = {}
    for ri in reals:
        if not (ri.flags & _V5_FLAG_HARDLINK):
            head_of.setdefault(ri.ino, ri)
    for ri in reals:
        if ri.flags & _V5_FLAG_HARDLINK:
            ri.digest = head_of[ri.ino].digest
    # Deepest directories first; the root is depth 0, NOT the same depth
    # as "/etc" (both contain one slash) — hashing it early would fold
    # empty placeholders for every top-level subdirectory into the root
    # digest.
    depth = lambda r: 0 if r.path == "/" else r.path.count("/")  # noqa: E731
    for ri in sorted(reals, key=depth, reverse=True):
        if ri.is_dir:
            kids = sorted(children.get(ri.path, []), key=lambda k: k.path)
            ri.digest = H(b"".join(k.digest for k in kids))

    flags = (
        _comp_flag_of(bootstrap)
        | (_FLAG_HASH_BLAKE3 if digester == "blake3" else _FLAG_HASH_SHA256)
        | _FLAG_EXPLICIT_UIDGID
        | (_FLAG_HAS_XATTR if any(r.xattrs for r in reals) else 0)
    )

    # The shared chunk table (v6 shape): unique (blob, offset) locations.
    seen: set[tuple[int, int]] = set()
    shared: list[RealChunk] = []
    for ri in reals:
        if ri.flags & _V5_FLAG_HARDLINK:
            continue
        for ck in ri.chunks:
            key = (ck.blob_index, ck.compressed_offset)
            if key not in seen:
                seen.add(key)
                shared.append(ck)

    prefetch_inos = [
        ino_of_path[p if p.startswith("/") else "/" + p]
        for p in getattr(bootstrap, "prefetch", [])
        if (p if p.startswith("/") else "/" + p) in ino_of_path
    ]

    return RealBootstrap(
        version=bootstrap.version
        if bootstrap.version in (layout.RAFS_V5, layout.RAFS_V6)
        else layout.RAFS_V6,
        flags=flags,
        inodes=reals,
        blobs=blobs,
        chunks=shared,
        prefetch_inos=prefetch_inos,
    )


def _table_order(real: RealBootstrap):
    """RAFS v5 table order, matching the reference builder exactly:
    pre-order DFS over directories — each directory's children laid out
    contiguously (child_index/child_count address that run), then its
    subdirectories recursed in bytewise name order (verified slot-by-slot
    against the committed v5 fixture). Returns (ordered inodes,
    first_child_slot: {id(dir): 1-based index}, child_count)."""
    by_parent: dict[str, list[RealInode]] = {}
    root = None
    for ri in real.inodes:
        if ri.path == "/":
            root = ri
            continue
        parent = ri.path.rsplit("/", 1)[0] or "/"
        by_parent.setdefault(parent, []).append(ri)
    if root is None:
        raise RealBootstrapError("bootstrap has no root inode")
    for kids in by_parent.values():
        kids.sort(key=lambda k: k.path.rsplit("/", 1)[1].encode())

    order = [root]
    first_child: dict[int, int] = {}
    count: dict[int, int] = {}

    def emit(node: RealInode):
        kids = by_parent.get(node.path, [])
        count[id(node)] = len(kids)
        first_child[id(node)] = len(order) + 1  # 1-based table index
        order.extend(kids)
        for k in kids:
            if k.is_dir:
                emit(k)

    emit(root)
    if len(order) != len(real.inodes):
        raise RealBootstrapError(
            f"{len(real.inodes) - len(order)} inodes unreachable from the root"
        )
    return order, first_child, count


def _v5_xattr_region(xattrs: dict[str, bytes]) -> bytes:
    body = io.BytesIO()
    for key in sorted(xattrs):
        pair = key.encode("utf-8", "surrogateescape") + b"\0" + xattrs[key]
        body.write(struct.pack("<I", len(pair)))
        body.write(pair)
        body.write(b"\0" * (_align8(len(pair)) - len(pair)))
    buf = body.getvalue()
    out = struct.pack("<Q", len(buf)) + buf
    return out + b"\0" * (_align8(len(out)) - len(out))


def write_real_v5(real: RealBootstrap) -> bytes:
    """Serialize a RealBootstrap in the reference's RAFS v5 layout
    (superblock / inode table / prefetch table / blob table / extended
    blob table / inode region — the section order of the committed
    fixture). parse_real_v5 round-trips the output exactly."""
    order, first_child, child_count = _table_order(real)

    ino_by_path: dict[str, int] = {}
    for ri in order:
        ino_by_path.setdefault(ri.path, ri.ino)

    ino_bufs: list[bytes] = []
    for ri in order:
        name = "/" if ri.path == "/" else ri.path.rsplit("/", 1)[1]
        nb = name.encode("utf-8", "surrogateescape")
        if len(nb) > 0xFFFF:
            raise RealBootstrapError(f"name too long: {name!r}")
        tb = ri.symlink_target.encode("utf-8", "surrogateescape")
        # hardlink aliases carry the flag and no chunk run; their head
        # does not carry it (parse rule in parse_real_v5)
        writes_chunks = (
            ri.is_regular and not (ri.flags & _V5_FLAG_HARDLINK) and ri.chunks
        )
        if ri.path == "/":
            parent_ino = 0
        else:
            parent_path = ri.path.rsplit("/", 1)[0] or "/"
            parent_ino = ino_by_path.get(parent_path, 0)
        if ri.is_dir:
            ci, cc = first_child.get(id(ri), 0), child_count.get(id(ri), 0)
        elif writes_chunks:
            ci, cc = 0, len(ri.chunks)
        else:
            ci, cc = 0, 0
        if len(ri.digest) != 32:
            raise RealBootstrapError(f"{ri.path}: inode digest must be 32 bytes")
        buf = io.BytesIO()
        buf.write(
            _V5_INODE.pack(
                ri.digest,
                parent_ino,
                ri.ino,
                ri.uid,
                ri.gid,
                0,  # projid
                ri.mode,
                ri.size,
                (ri.size + 511) // 512,  # 512-B sectors (fixture-verified)
                ri.flags,
                ri.nlink,
                ci,
                cc,
                len(nb),
                len(tb) if ri.flags & _V5_FLAG_SYMLINK else 0,
                ri.rdev,
                0,  # pad
                ri.mtime,
                0,  # mtime_ns
                0,  # reserved
            )
        )
        buf.write(nb)
        buf.write(b"\0" * (_align8(len(nb)) - len(nb)))
        if ri.flags & _V5_FLAG_SYMLINK:
            buf.write(tb)
            buf.write(b"\0" * (_align8(len(tb)) - len(tb)))
        if ri.flags & _V5_FLAG_XATTR:
            buf.write(_v5_xattr_region(ri.xattrs))
        if writes_chunks:
            for ck in ri.chunks:
                buf.write(
                    _V5_CHUNK.pack(
                        ck.digest,
                        ck.blob_index,
                        ck.flags,
                        ck.compressed_size,
                        ck.uncompressed_size,
                        ck.compressed_offset,
                        ck.uncompressed_offset,
                        ck.file_offset,
                        ck.index,
                        0,
                    )
                )
        ino_bufs.append(buf.getvalue())

    n = len(order)
    inode_table_off = _V5_SB_SIZE
    prefetch_off = _align8(inode_table_off + 4 * n)
    prefetch_buf = b"".join(struct.pack("<I", pi) for pi in real.prefetch_inos)
    blob_table_off = _align8(prefetch_off + len(prefetch_buf))
    blob_parts = []
    for i, blob in enumerate(real.blobs):
        rec = struct.pack("<II", 0, 0) + blob.blob_id.encode("ascii")
        if i + 1 < len(real.blobs):
            rec += b"\0"
        blob_parts.append(rec)
    blob_buf = b"".join(blob_parts)
    ext_blob_off = _align8(blob_table_off + len(blob_buf))
    ext_buf = b"".join(
        struct.pack(
            "<IIQQ", b.chunk_count, 0, b.uncompressed_size, b.compressed_size
        ).ljust(64, b"\0")
        for b in real.blobs
    )
    inodes_base = _align8(ext_blob_off + len(ext_buf))

    table = []
    pos = inodes_base
    for buf in ino_bufs:
        if pos & 7:
            raise RealBootstrapError("internal: inode offset not 8-aligned")
        table.append(pos >> 3)
        pos += len(buf)

    sb = _V5_SB.pack(
        layout.RAFS_V5_SUPER_MAGIC,
        0x500,
        _V5_SB_SIZE,
        real.blobs[0].chunk_size if real.blobs else 0x100000,
        real.flags,
        len({ri.ino for ri in order}),
        inode_table_off,
        prefetch_off,
        blob_table_off,
        n,
        len(real.prefetch_inos),
        len(blob_buf),
        len(real.blobs),
        ext_blob_off,
    )

    out = io.BytesIO()
    out.write(sb)
    out.write(b"\0" * (_V5_SB_SIZE - out.tell()))
    out.write(struct.pack(f"<{n}I", *table))
    out.write(b"\0" * (prefetch_off - out.tell()))
    out.write(prefetch_buf)
    out.write(b"\0" * (blob_table_off - out.tell()))
    out.write(blob_buf)
    out.write(b"\0" * (ext_blob_off - out.tell()))
    out.write(ext_buf)
    out.write(b"\0" * (inodes_base - out.tell()))
    for buf in ino_bufs:
        out.write(buf)
    return out.getvalue()


# ---------------------------------------------------------------------------
# RAFS v6 (EROFS + nydus extensions)
# ---------------------------------------------------------------------------

# On-disk contract shared with the reader and the in-tree EROFS writer.
from nydus_snapshotter_tpu.models.erofs_image import (  # noqa: E402
    _CHUNK_INDEX,
    _DEVICE_SLOT,
    _DIRENT,
    _SB as _EROFS_SB_FULL,
    _encode_xattrs,
    _file_type,
    _XATTR_IBODY_HEADER,
)
from nydus_snapshotter_tpu.models.nydus_real import (  # noqa: E402
    _NYDUS_EXT_SB,
    _NYDUS_EXT_SB_PREFETCH,
)

_V6_BLKSZBITS = 12
_V6_BLKSZ = 1 << _V6_BLKSZBITS
_V6_DEVT_SLOTOFF = 11  # fixture: device slots right after the ext sb region
_V6_ROOT_SLOT = 128  # fixture: inodes start one block into the meta area
_V6_INODE_EXT = struct.Struct("<HHHHQIIIIQII")  # + 16 reserved bytes = 64
_V6_LAYOUT_PLAIN = 0
_V6_LAYOUT_INLINE = 2
_V6_LAYOUT_CHUNK = 4
_V6_CHUNK_FORMAT_INDEXES = 0x0020
_V6_FEAT_CHUNKED_FILE = 0x4
_V6_FEAT_DEVICE_TABLE = 0x8


class _V6Node:
    __slots__ = (
        "ri", "nid", "ino", "nlink", "dl", "iu", "inline", "data_blocks",
        "xattr_body", "chunks", "kids",
    )

    def __init__(self, ri: RealInode):
        self.ri = ri
        self.nid = 0
        self.ino = 0
        self.nlink = 1
        self.dl = _V6_LAYOUT_INLINE
        self.iu = 0
        self.inline = b""
        self.data_blocks = b""
        self.xattr_body = b""
        self.chunks: list[RealChunk] = []
        self.kids: list["_V6Node"] = []


def _v6_dir_blocks(entries: list[tuple[bytes, int, int]]) -> bytes:
    """Serialize sorted (name, nid, ftype) dirents: greedy per-block
    packing, names unpadded in the final block (so the byte length IS the
    directory size, matching the fixture's exact-tail sizes)."""
    entries = sorted(entries, key=lambda t: t[0])
    blocks: list[list[tuple[bytes, int, int]]] = []
    cur: list[tuple[bytes, int, int]] = []
    used = 0
    for name, nid, ft in entries:
        cost = _DIRENT.size + len(name)
        if cost > _V6_BLKSZ:
            raise RealBootstrapError(f"dirent {name!r} exceeds the 4 KiB block")
        if cur and used + cost > _V6_BLKSZ:
            blocks.append(cur)
            cur, used = [], 0
        cur.append((name, nid, ft))
        used += cost
    if cur:
        blocks.append(cur)
    out = io.BytesIO()
    for bi, ents in enumerate(blocks):
        base = out.tell()
        nameoff = len(ents) * _DIRENT.size
        names = io.BytesIO()
        for name, nid, ft in ents:
            out.write(_DIRENT.pack(nid, nameoff + names.tell(), ft, 0))
            names.write(name)
        out.write(names.getvalue())
        if bi < len(blocks) - 1:
            out.write(b"\0" * (base + _V6_BLKSZ - out.tell()))
    return out.getvalue()


def _v6_realign_uoffs(real: RealBootstrap) -> dict[tuple[int, int], int]:
    """(blob_index, compressed_offset) -> block-aligned uncompressed
    offset. v6 chunk indexes address 4 KiB blocks, so every chunk's
    virtual uncompressed offset must be block-aligned; bootstraps from
    the internal pack engine carry packed (unaligned) offsets, which are
    re-laid per blob in compressed-offset order — exactly the aligned
    virtual layout the real builder produces. Already-aligned inputs
    (parsed real bootstraps) map to themselves."""
    keys: dict[tuple[int, int], RealChunk] = {}
    for ri in real.inodes:
        for ck in ri.chunks:
            keys.setdefault((ck.blob_index, ck.compressed_offset), ck)
    for ck in real.chunks:
        keys.setdefault((ck.blob_index, ck.compressed_offset), ck)
    if all(ck.uncompressed_offset % _V6_BLKSZ == 0 for ck in keys.values()):
        return {k: ck.uncompressed_offset for k, ck in keys.items()}
    out: dict[tuple[int, int], int] = {}
    per_blob: dict[int, list[tuple[int, RealChunk]]] = {}
    for (bi, coff), ck in keys.items():
        per_blob.setdefault(bi, []).append((coff, ck))
    for bi, lst in per_blob.items():
        pos = 0
        for coff, ck in sorted(lst):
            out[(bi, coff)] = pos
            pos += ck.uncompressed_size
            pos += (-pos) % _V6_BLKSZ
    return out


def write_real_v6(real: RealBootstrap) -> bytes:
    """Serialize a RealBootstrap in the reference's RAFS v6 layout: a
    kernel-mountable EROFS image (extended inodes, FLAT_INLINE tails,
    CHUNK_BASED regular files, per-blob device slots) plus the nydus
    extended superblock, 256-B blob table, prefetch table, and shared
    80-B chunk table. parse_real_v6 round-trips the output; the layout
    parameters (devt slot 11, root one block into the meta area, blob
    table on the block after the device slots, 512-B-sector-free
    extended inodes) mirror the committed fixture.

    One deliberate divergence from the Rust builder: its chunk table is
    emitted in hash-map iteration order (irreproducible); this writer
    uses first-appearance order over the directory walk, which is
    deterministic and carries the identical record multiset."""
    # --- tree & head/alias resolution -----------------------------------
    by_path: dict[str, _V6Node] = {}
    root = None
    for ri in real.inodes:
        node = _V6Node(ri)
        by_path[ri.path] = node
        if ri.path == "/":
            root = node
    if root is None:
        raise RealBootstrapError("bootstrap has no root inode")
    head_of_ino: dict[int, _V6Node] = {}
    order_hint = {id(ri): i for i, ri in enumerate(real.inodes)}
    for ri in sorted(real.inodes, key=lambda r: order_hint[id(r)]):
        head_of_ino.setdefault(ri.ino, by_path[ri.path])
    for path, node in by_path.items():
        if path == "/":
            continue
        parent = by_path.get(path.rsplit("/", 1)[0] or "/")
        if parent is None:
            raise RealBootstrapError(f"orphan path {path!r}")
        parent.kids.append(node)
    for node in by_path.values():
        node.kids.sort(key=lambda k: k.ri.path.rsplit("/", 1)[1].encode())

    # nlink: dirs 2 + subdirs; files their hardlink-group size.
    group: dict[int, int] = {}
    for ri in real.inodes:
        group[ri.ino] = group.get(ri.ino, 0) + 1
    for node in by_path.values():
        node.nlink = (
            2 + sum(1 for k in node.kids if k.ri.is_dir)
            if node.ri.is_dir
            else group[node.ri.ino]
        )

    # Disk order: per directory, non-dir children first, then dir
    # children each with its whole subtree (fixture-verified).
    disk: list[_V6Node] = []

    def emit(node: _V6Node):
        disk.append(node)
        files = [
            k
            for k in node.kids
            if not k.ri.is_dir and head_of_ino[k.ri.ino] is k
        ]
        disk.extend(files)
        for k in node.kids:
            if k.ri.is_dir:
                emit(k)

    emit(root)

    # v6 chunk indexes address a per-file fixed grid: index ci covers file
    # bytes [ci*chunk_size, (ci+1)*chunk_size). Variable-size (CDC) chunk
    # runs cannot be represented — reject them loudly (the fixture's own
    # multi-chunk files sit on an exact 1 MiB grid, f_off included).
    grid = real.blobs[0].chunk_size if real.blobs else 0x100000
    for node in disk:
        run = node.ri.chunks
        for ci, ck in enumerate(run):
            want = min(grid, max(node.ri.size - ci * grid, 0)) if node.ri.size else 0
            if ck.uncompressed_size != want:
                raise RealBootstrapError(
                    f"{node.ri.path}: chunk {ci} has {ck.uncompressed_size} "
                    f"uncompressed bytes but the v6 fixed grid needs {want} "
                    f"(chunk_size {grid:#x}); RAFS v6 cannot carry variable "
                    "CDC chunks - pack with chunking='fixed' or emit v5"
                )

    uoff_of = _v6_realign_uoffs(real)

    # --- per-node bodies (sizes first; dirents need nids, done later) ---
    for node in disk:
        ri = node.ri
        node.xattr_body = _encode_xattrs(ri.xattrs)
        if ri.is_dir:
            node.dl = _V6_LAYOUT_INLINE
        elif ri.is_symlink:
            node.dl = _V6_LAYOUT_INLINE
            node.inline = ri.symlink_target.encode("utf-8", "surrogateescape")
        elif ri.is_regular:
            node.dl = _V6_LAYOUT_CHUNK
            node.chunks = list(ri.chunks)
        else:  # char/block/fifo/socket
            node.dl = _V6_LAYOUT_PLAIN
            major, minor = os.major(ri.rdev), os.minor(ri.rdev)
            node.iu = (minor & 0xFF) | (major << 8) | ((minor & ~0xFF) << 12)

    # Directory sizes need only names; serialize dirents with nid=0 to
    # size them, then re-serialize after nid assignment.
    def dir_entries(node: _V6Node, nids: bool) -> list[tuple[bytes, int, int]]:
        ents = [
            (b".", node.nid if nids else 0, 2),
            (b"..", (node_parent[id(node)].nid if nids else 0), 2),
        ]
        for k in node.kids:
            tgt = head_of_ino[k.ri.ino] if not k.ri.is_dir else k
            ents.append(
                (
                    k.ri.path.rsplit("/", 1)[1].encode("utf-8", "surrogateescape"),
                    tgt.nid if nids else 0,
                    _file_type(k.ri.mode),
                )
            )
        return ents

    node_parent: dict[int, _V6Node] = {id(root): root}
    for node in by_path.values():
        for k in node.kids:
            node_parent[id(k)] = node

    dir_sizes: dict[int, int] = {}
    for node in disk:
        if node.ri.is_dir:
            dir_sizes[id(node)] = len(_v6_dir_blocks(dir_entries(node, False)))

    # --- layout: slots, block-aligned full dir blocks -------------------
    # Geometry (fixture-shaped): sb + ext sb, device slots at slot 11,
    # blob table on the next block, prefetch right after it, meta area on
    # the block after that, inodes starting one block into it.
    n_blobs = len(real.blobs)
    devt_end = _V6_DEVT_SLOTOFF * 128 + 128 * n_blobs
    blob_table_off = devt_end + (-devt_end) % _V6_BLKSZ
    blob_table_size = 256 * n_blobs
    prefetch_off = blob_table_off + blob_table_size
    nid_of_ino = {}
    prefetch_nids: list[int] = []
    prefetch_size = 4 * len(real.prefetch_inos)
    meta_end = prefetch_off + prefetch_size
    meta_blkaddr = -(-meta_end // _V6_BLKSZ)
    meta_base = meta_blkaddr * _V6_BLKSZ

    def slot_bytes(node: _V6Node) -> tuple[int, int]:
        """(bytes after the 64-B inode in the slot run, inline tail len)."""
        extra = len(node.xattr_body)
        if node.dl == _V6_LAYOUT_CHUNK:
            pad = (-(64 + extra)) % 8
            return extra + pad + 8 * len(node.chunks), 0
        size = dir_sizes[id(node)] if node.ri.is_dir else len(node.inline)
        tail = size % _V6_BLKSZ if size else 0
        return extra + tail, tail

    pos = meta_base + _V6_ROOT_SLOT * 32
    for node in disk:
        size = (
            dir_sizes[id(node)]
            if node.ri.is_dir
            else len(node.inline)
            if node.dl == _V6_LAYOUT_INLINE
            else node.ri.size
        )
        full_blocks = size // _V6_BLKSZ if node.dl == _V6_LAYOUT_INLINE else 0
        extra, tail = slot_bytes(node)
        if full_blocks:
            # inode at a block start; its full data blocks on the block(s)
            # right after the inode's block (fixture rule for big dirs)
            pos += (-pos) % _V6_BLKSZ
            if 64 + extra > _V6_BLKSZ:
                raise RealBootstrapError(
                    f"{node.ri.path}: inline tail cannot fit one block"
                )
        elif tail and (pos % _V6_BLKSZ) + 64 + extra > _V6_BLKSZ:
            # the inline tail must not cross a block boundary
            pos += (-pos) % _V6_BLKSZ
        node.nid = (pos - meta_base) // 32
        if full_blocks:
            data_blk = (pos + 64 + extra + _V6_BLKSZ - 1) // _V6_BLKSZ
            node.iu = data_blk
            pos = (data_blk + full_blocks) * _V6_BLKSZ
        else:
            if node.dl == _V6_LAYOUT_INLINE:
                node.iu = (pos + 64 + len(node.xattr_body)) >> _V6_BLKSZBITS
            pos += 64 + extra
            pos += (-pos) % 32
    slots_end = pos

    for node in disk:
        node.ino = node.ri.ino
        nid_of_ino[node.ri.ino] = node.nid
    prefetch_nids = [
        nid_of_ino[i] for i in real.prefetch_inos if i in nid_of_ino
    ]

    # --- chunk table: first-appearance order over the disk walk ---------
    table_recs: list[RealChunk] = []
    seen_key: set[tuple[int, int]] = set()
    for node in disk:
        for ck in node.chunks:
            key = (ck.blob_index, ck.compressed_offset)
            if key not in seen_key:
                seen_key.add(key)
                table_recs.append(ck)
    chunk_table_off = slots_end + (-slots_end) % _V6_BLKSZ
    chunk_table_size = 80 * len(table_recs)
    total = chunk_table_off + chunk_table_size
    total += (-total) % _V6_BLKSZ

    # --- serialize ------------------------------------------------------
    out = bytearray(total)

    chunk_size = real.blobs[0].chunk_size if real.blobs else 0x100000
    if chunk_size & (chunk_size - 1) or not chunk_size:
        raise RealBootstrapError(f"v6 chunk size {chunk_size:#x} not a power of 2")
    chunk_bits = chunk_size.bit_length() - 1
    if chunk_bits < _V6_BLKSZBITS:
        raise RealBootstrapError(f"v6 chunk size {chunk_size:#x} below block size")

    feat = _V6_FEAT_DEVICE_TABLE if n_blobs else 0
    if any(node.dl == _V6_LAYOUT_CHUNK for node in disk):
        feat |= _V6_FEAT_CHUNKED_FILE
    sb = _EROFS_SB_FULL.pack(
        layout.RAFS_V6_SUPER_MAGIC,
        0,
        0,
        _V6_BLKSZBITS,
        0,
        root.nid,
        len(real.inodes),
        0,
        0,
        total // _V6_BLKSZ,
        meta_blkaddr,
        0,
        b"\0" * 16,
        b"\0" * 16,
        feat,
        0,
        n_blobs,
        _V6_DEVT_SLOTOFF if n_blobs else 0,
        0,
        0,
        0,
        0,
        0,
        b"\0" * 23,
    )
    out[1024 : 1024 + len(sb)] = sb
    ext = _NYDUS_EXT_SB.pack(
        real.flags,
        blob_table_off,
        blob_table_size,
        chunk_size,
        chunk_table_off,
        chunk_table_size,
    ) + _NYDUS_EXT_SB_PREFETCH.pack(
        prefetch_off if prefetch_nids else 0, 4 * len(prefetch_nids)
    )
    out[1152 : 1152 + len(ext)] = ext

    for i, blob in enumerate(real.blobs):
        slot_off = _V6_DEVT_SLOTOFF * 128 + 128 * i
        out[slot_off : slot_off + 128] = _DEVICE_SLOT.pack(
            blob.blob_id.encode("ascii")[:64].ljust(64, b"\0"),
            -(-(blob.uncompressed_size or blob.compressed_size) // _V6_BLKSZ),
            0,
            b"\0" * 56,
        )
        if blob.raw_rec:
            rec = blob.raw_rec
        else:
            # fields validated against the fixture record; +76/+80 carry
            # the constants the fixture does (features / cipher config)
            rec = (
                blob.blob_id.encode("ascii")[:64].ljust(64, b"\0")
                + struct.pack(
                    "<IIII", i, chunk_size, blob.chunk_count, 1
                )
                + struct.pack(
                    "<QQQ",
                    0x1_0000_0000,
                    blob.compressed_size,
                    blob.uncompressed_size,
                )
            ).ljust(256, b"\0")
        off = blob_table_off + 256 * i
        out[off : off + 256] = rec

    for i, nid in enumerate(prefetch_nids):
        struct.pack_into("<I", out, prefetch_off + 4 * i, nid)

    for node in disk:
        ri = node.ri
        off = meta_base + 32 * node.nid
        if node.dl == _V6_LAYOUT_CHUNK:
            iu = _V6_CHUNK_FORMAT_INDEXES | (chunk_bits - _V6_BLKSZBITS)
        else:
            iu = node.iu
        xic = (
            1 + (len(node.xattr_body) - _XATTR_IBODY_HEADER.size) // 4
            if node.xattr_body
            else 0
        )
        size = (
            dir_sizes[id(node)]
            if ri.is_dir
            else len(node.inline)
            if node.dl == _V6_LAYOUT_INLINE
            else ri.size
        )
        inode = _V6_INODE_EXT.pack(
            (node.dl << 1) | 1,
            xic,
            ri.mode & 0xFFFF,
            0,
            size,
            iu,
            node.ino,
            ri.uid,
            ri.gid,
            ri.mtime,
            0,
            node.nlink,
        ) + b"\0" * 16
        out[off : off + 64] = inode
        body = off + 64
        out[body : body + len(node.xattr_body)] = node.xattr_body
        body += len(node.xattr_body)
        if node.dl == _V6_LAYOUT_CHUNK:
            body += (-(body - off)) % 8
            for ci, ck in enumerate(node.chunks):
                uoff = uoff_of[(ck.blob_index, ck.compressed_offset)]
                struct.pack_into(
                    "<HHI",
                    out,
                    body + 8 * ci,
                    0,
                    ck.blob_index + 1,
                    uoff >> _V6_BLKSZBITS,
                )
        elif node.dl == _V6_LAYOUT_INLINE:
            data = (
                _v6_dir_blocks(dir_entries(node, True))
                if ri.is_dir
                else node.inline
            )
            nbl = len(data) // _V6_BLKSZ
            if nbl:
                dst = node.iu * _V6_BLKSZ
                out[dst : dst + nbl * _V6_BLKSZ] = data[: nbl * _V6_BLKSZ]
            tail = data[nbl * _V6_BLKSZ :]
            out[body : body + len(tail)] = tail

    for i, ck in enumerate(table_recs):
        off = chunk_table_off + 80 * i
        out[off : off + 80] = _V5_CHUNK.pack(
            ck.digest,
            ck.blob_index,
            ck.flags,
            ck.compressed_size,
            ck.uncompressed_size,
            ck.compressed_offset,
            uoff_of[(ck.blob_index, ck.compressed_offset)],
            ck.file_offset,
            ck.index,
            0,
        )

    return bytes(out)
