"""Writers for REAL nydus-toolchain bootstrap layouts (RAFS v5, v6).

models/nydus_real.py made real bootstraps first-class *inputs*; this
module is the other direction: serialize a bootstrap in the reference
toolchain's own on-disk layout, so images this framework converts can be
consumed by the reference ecosystem (nydusd mounts v5/v6 bootstraps
produced by `nydus-image`; pkg/filesystem/fs.go:268-431 never sees any
other format). Layout knowledge is the same field maps the reader was
validated with on the committed real fixtures; the reader is the
round-trip oracle for everything written here.

Digest semantics (reverse-engineered structurally from the v5 fixture,
where every one of its 3,517 inode digests matches):

- regular file:  H(concat of its chunk digests)   (2602/2602 fixture inodes)
- symlink:       H(target bytes)                  (212/212)
- directory:     H(concat of child digests, children sorted by name,
                 computed bottom-up)              (678/678)
- empty file / special file: H(b"")
- hardlink alias: the target inode's digest

with H = blake3 (RafsSuperFlags 0x4, the toolchain default — see
utils/blake3.py) or sha256 (0x8). `real_from_bootstrap` computes these
when bridging the framework's internal model; fixture-parsed
RealBootstraps keep their digests verbatim.

Superblock flag bits (nydus RafsSuperFlags, validated against both
fixtures: v5 carries 0x16, v6 carries 0x6):
0x1 none / 0x2 lz4_block / 0x40 gzip / 0x80 zstd compressor;
0x4 blake3 / 0x8 sha256 digester; 0x10 explicit uid/gid; 0x20 xattrs.
"""

from __future__ import annotations

import hashlib
import io
import stat as statmod
import struct

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.models import layout
from nydus_snapshotter_tpu.models.nydus_real import (
    RealBlob,
    RealBootstrap,
    RealBootstrapError,
    RealChunk,
    RealInode,
    _V5_CHUNK,
    _V5_FLAG_HARDLINK,
    _V5_FLAG_SYMLINK,
    _V5_FLAG_XATTR,
    _V5_INODE,
    _V5_SB,
)
from nydus_snapshotter_tpu.utils.blake3 import blake3

__all__ = ["real_from_bootstrap", "write_real_v5"]

_FLAG_COMP_NONE = 0x1
_FLAG_COMP_LZ4 = 0x2
_FLAG_HASH_BLAKE3 = 0x4
_FLAG_HASH_SHA256 = 0x8
_FLAG_EXPLICIT_UIDGID = 0x10
_FLAG_HAS_XATTR = 0x20
_FLAG_COMP_GZIP = 0x40
_FLAG_COMP_ZSTD = 0x80

_CHUNK_FLAG_COMPRESSED = 0x1

_V5_SB_SIZE = 8192


def _align8(n: int) -> int:
    return (n + 7) & ~7


def _digester(name: str):
    if name == "blake3":
        return blake3
    if name == "sha256":
        return lambda b: hashlib.sha256(b).digest()
    raise RealBootstrapError(f"unknown digester {name!r}")


def _comp_flag_of(bootstrap) -> int:
    """Superblock compressor bit from the internal chunk flags."""
    for ck in bootstrap.chunks:
        c = ck.flags & constants.COMPRESSOR_MASK
        if c == constants.COMPRESSOR_LZ4_BLOCK:
            return _FLAG_COMP_LZ4
        if c == constants.COMPRESSOR_ZSTD:
            return _FLAG_COMP_ZSTD
        if c == constants.COMPRESSOR_GZIP:
            return _FLAG_COMP_GZIP
    return _FLAG_COMP_NONE


def real_from_bootstrap(bootstrap, digester: str = "sha256") -> RealBootstrap:
    """Bridge the framework's internal model (models/bootstrap.Bootstrap)
    into a RealBootstrap ready for the real-layout writers.

    Inode digests are computed per the reference formulas above (the
    internal model does not carry them); v5 per-inode chunk runs get
    file_offset/index fields the internal shared chunk table does not
    track. Chunk digests pass through as-is — they are sha256 from the
    pack engine, so pick digester="sha256" (the toolchain's own
    `--digester sha256` mode) unless the caller rehashed with blake3.
    """
    H = _digester(digester)

    blobs = [
        RealBlob(
            blob_id=b.blob_id,
            chunk_count=b.chunk_count,
            compressed_size=b.compressed_size,
            uncompressed_size=b.uncompressed_size,
            chunk_size=bootstrap.chunk_size,
        )
        for b in bootstrap.blobs
    ]

    # Per-blob chunk ordinals for the v5 records' index field.
    ordinal: dict[tuple[int, int], int] = {}
    per_blob: dict[int, list[int]] = {}
    for ck in bootstrap.chunks:
        per_blob.setdefault(ck.blob_index, []).append(ck.compressed_offset)
    for bi, offs in per_blob.items():
        for i, off in enumerate(sorted(set(offs))):
            ordinal[(bi, off)] = i

    by_path: dict[str, RealInode] = {}
    ino_of_path: dict[str, int] = {}
    next_ino = 1
    reals: list[RealInode] = []
    for ino in sorted(bootstrap.inodes, key=lambda i: i.path):
        target = ino.hardlink_target
        if target:
            tpath = "/" + target.lstrip("/")
            num = ino_of_path.get(tpath)
            if num is None:
                raise RealBootstrapError(f"hardlink target missing: {target}")
        else:
            num = next_ino
            next_ino += 1
        ri = RealInode(
            path=ino.path,
            ino=num,
            mode=ino.mode,
            uid=ino.uid,
            gid=ino.gid,
            mtime=ino.mtime,
            size=ino.size,
            nlink=1,
            rdev=ino.rdev,
            flags=0,
            symlink_target=ino.symlink_target,
            xattrs=dict(ino.xattrs),
        )
        if ri.is_symlink:
            ri.flags |= _V5_FLAG_SYMLINK
        if ri.xattrs:
            ri.flags |= _V5_FLAG_XATTR
        if target:
            ri.flags |= _V5_FLAG_HARDLINK
            head = by_path["/" + target.lstrip("/")]
            ri.chunks = head.chunks
            ri.size = head.size
            ri.digest = b""  # filled after head digests are computed
        elif ino.chunk_count:
            pos = 0
            for rec in bootstrap.chunks[
                ino.chunk_index : ino.chunk_index + ino.chunk_count
            ]:
                ri.chunks.append(
                    RealChunk(
                        digest=rec.digest,
                        blob_index=rec.blob_index,
                        flags=(
                            _CHUNK_FLAG_COMPRESSED
                            if (rec.flags & constants.COMPRESSOR_MASK)
                            not in (0, constants.COMPRESSOR_NONE)
                            else 0
                        ),
                        compressed_size=rec.compressed_size,
                        uncompressed_size=rec.uncompressed_size,
                        compressed_offset=rec.compressed_offset,
                        uncompressed_offset=rec.uncompressed_offset,
                        file_offset=pos,
                        index=ordinal.get(
                            (rec.blob_index, rec.compressed_offset), 0
                        ),
                    )
                )
                pos += rec.uncompressed_size
        reals.append(ri)
        by_path[ri.path] = ri
        ino_of_path[ri.path] = num

    # nlink: hardlink group sizes; directories 2 + subdirectories.
    group_size: dict[int, int] = {}
    for ri in reals:
        group_size[ri.ino] = group_size.get(ri.ino, 0) + 1
    children: dict[str, list[RealInode]] = {}
    for ri in reals:
        if ri.path != "/":
            parent = ri.path.rsplit("/", 1)[0] or "/"
            children.setdefault(parent, []).append(ri)
    for ri in reals:
        if ri.is_dir:
            ri.nlink = 2 + sum(1 for c in children.get(ri.path, []) if c.is_dir)
        else:
            ri.nlink = group_size[ri.ino]

    # Digests. Leaves first (files/symlinks), then hardlink aliases (their
    # head is always a non-directory, so it is final by then — an alias
    # must contribute its target's digest to its parent directory's hash,
    # not a placeholder), then directories bottom-up.
    for ri in reals:
        if ri.flags & _V5_FLAG_HARDLINK or ri.is_dir:
            continue
        if ri.is_symlink:
            ri.digest = H(ri.symlink_target.encode())
        elif ri.chunks:
            ri.digest = H(b"".join(c.digest for c in ri.chunks))
        else:
            ri.digest = H(b"")
    head_of: dict[int, RealInode] = {}
    for ri in reals:
        if not (ri.flags & _V5_FLAG_HARDLINK):
            head_of.setdefault(ri.ino, ri)
    for ri in reals:
        if ri.flags & _V5_FLAG_HARDLINK:
            ri.digest = head_of[ri.ino].digest
    for ri in sorted(reals, key=lambda r: r.path.count("/"), reverse=True):
        if ri.is_dir:
            kids = sorted(children.get(ri.path, []), key=lambda k: k.path)
            ri.digest = H(b"".join(k.digest for k in kids))

    flags = (
        _comp_flag_of(bootstrap)
        | (_FLAG_HASH_BLAKE3 if digester == "blake3" else _FLAG_HASH_SHA256)
        | _FLAG_EXPLICIT_UIDGID
        | (_FLAG_HAS_XATTR if any(r.xattrs for r in reals) else 0)
    )

    # The shared chunk table (v6 shape): unique (blob, offset) locations.
    seen: set[tuple[int, int]] = set()
    shared: list[RealChunk] = []
    for ri in reals:
        if ri.flags & _V5_FLAG_HARDLINK:
            continue
        for ck in ri.chunks:
            key = (ck.blob_index, ck.compressed_offset)
            if key not in seen:
                seen.add(key)
                shared.append(ck)

    prefetch_inos = [
        ino_of_path[p if p.startswith("/") else "/" + p]
        for p in getattr(bootstrap, "prefetch", [])
        if (p if p.startswith("/") else "/" + p) in ino_of_path
    ]

    return RealBootstrap(
        version=bootstrap.version
        if bootstrap.version in (layout.RAFS_V5, layout.RAFS_V6)
        else layout.RAFS_V6,
        flags=flags,
        inodes=reals,
        blobs=blobs,
        chunks=shared,
        prefetch_inos=prefetch_inos,
    )


def _table_order(real: RealBootstrap):
    """RAFS v5 table order, matching the reference builder exactly:
    pre-order DFS over directories — each directory's children laid out
    contiguously (child_index/child_count address that run), then its
    subdirectories recursed in bytewise name order (verified slot-by-slot
    against the committed v5 fixture). Returns (ordered inodes,
    first_child_slot: {id(dir): 1-based index}, child_count)."""
    by_parent: dict[str, list[RealInode]] = {}
    root = None
    for ri in real.inodes:
        if ri.path == "/":
            root = ri
            continue
        parent = ri.path.rsplit("/", 1)[0] or "/"
        by_parent.setdefault(parent, []).append(ri)
    if root is None:
        raise RealBootstrapError("bootstrap has no root inode")
    for kids in by_parent.values():
        kids.sort(key=lambda k: k.path.rsplit("/", 1)[1].encode())

    order = [root]
    first_child: dict[int, int] = {}
    count: dict[int, int] = {}

    def emit(node: RealInode):
        kids = by_parent.get(node.path, [])
        count[id(node)] = len(kids)
        first_child[id(node)] = len(order) + 1  # 1-based table index
        order.extend(kids)
        for k in kids:
            if k.is_dir:
                emit(k)

    emit(root)
    if len(order) != len(real.inodes):
        raise RealBootstrapError(
            f"{len(real.inodes) - len(order)} inodes unreachable from the root"
        )
    return order, first_child, count


def _v5_xattr_region(xattrs: dict[str, bytes]) -> bytes:
    body = io.BytesIO()
    for key in sorted(xattrs):
        pair = key.encode("utf-8", "surrogateescape") + b"\0" + xattrs[key]
        body.write(struct.pack("<I", len(pair)))
        body.write(pair)
        body.write(b"\0" * (_align8(len(pair)) - len(pair)))
    buf = body.getvalue()
    out = struct.pack("<Q", len(buf)) + buf
    return out + b"\0" * (_align8(len(out)) - len(out))


def write_real_v5(real: RealBootstrap) -> bytes:
    """Serialize a RealBootstrap in the reference's RAFS v5 layout
    (superblock / inode table / prefetch table / blob table / extended
    blob table / inode region — the section order of the committed
    fixture). parse_real_v5 round-trips the output exactly."""
    order, first_child, child_count = _table_order(real)

    # ino -> first table slot: that occurrence serializes the chunk run.
    head_slot: dict[int, int] = {}
    ino_by_path: dict[str, int] = {}
    for slot, ri in enumerate(order):
        head_slot.setdefault(ri.ino, slot)
        ino_by_path.setdefault(ri.path, ri.ino)

    ino_bufs: list[bytes] = []
    for slot, ri in enumerate(order):
        name = "/" if ri.path == "/" else ri.path.rsplit("/", 1)[1]
        nb = name.encode("utf-8", "surrogateescape")
        if len(nb) > 0xFFFF:
            raise RealBootstrapError(f"name too long: {name!r}")
        tb = ri.symlink_target.encode("utf-8", "surrogateescape")
        is_alias = bool(ri.flags & _V5_FLAG_HARDLINK) and head_slot[ri.ino] != slot
        writes_chunks = (
            ri.is_regular and not (ri.flags & _V5_FLAG_HARDLINK) and ri.chunks
        )
        if ri.path == "/":
            parent_ino = 0
        else:
            parent_path = ri.path.rsplit("/", 1)[0] or "/"
            parent_ino = ino_by_path.get(parent_path, 0)
        if ri.is_dir:
            ci, cc = first_child.get(id(ri), 0), child_count.get(id(ri), 0)
        elif writes_chunks:
            ci, cc = 0, len(ri.chunks)
        else:
            ci, cc = 0, 0
        if len(ri.digest) != 32:
            raise RealBootstrapError(f"{ri.path}: inode digest must be 32 bytes")
        buf = io.BytesIO()
        buf.write(
            _V5_INODE.pack(
                ri.digest,
                parent_ino,
                ri.ino,
                ri.uid,
                ri.gid,
                0,  # projid
                ri.mode,
                ri.size,
                (ri.size + 511) // 512,  # 512-B sectors (fixture-verified)
                ri.flags,
                ri.nlink,
                ci,
                cc,
                len(nb),
                len(tb) if ri.flags & _V5_FLAG_SYMLINK else 0,
                ri.rdev,
                0,  # pad
                ri.mtime,
                0,  # mtime_ns
                0,  # reserved
            )
        )
        buf.write(nb)
        buf.write(b"\0" * (_align8(len(nb)) - len(nb)))
        if ri.flags & _V5_FLAG_SYMLINK:
            buf.write(tb)
            buf.write(b"\0" * (_align8(len(tb)) - len(tb)))
        if ri.flags & _V5_FLAG_XATTR:
            buf.write(_v5_xattr_region(ri.xattrs))
        if writes_chunks and not is_alias:
            for ck in ri.chunks:
                buf.write(
                    _V5_CHUNK.pack(
                        ck.digest,
                        ck.blob_index,
                        ck.flags,
                        ck.compressed_size,
                        ck.uncompressed_size,
                        ck.compressed_offset,
                        ck.uncompressed_offset,
                        ck.file_offset,
                        ck.index,
                        0,
                    )
                )
        ino_bufs.append(buf.getvalue())

    n = len(order)
    inode_table_off = _V5_SB_SIZE
    prefetch_off = _align8(inode_table_off + 4 * n)
    prefetch_buf = b"".join(struct.pack("<I", pi) for pi in real.prefetch_inos)
    blob_table_off = _align8(prefetch_off + len(prefetch_buf))
    blob_parts = []
    for i, blob in enumerate(real.blobs):
        rec = struct.pack("<II", 0, 0) + blob.blob_id.encode("ascii")
        if i + 1 < len(real.blobs):
            rec += b"\0"
        blob_parts.append(rec)
    blob_buf = b"".join(blob_parts)
    ext_blob_off = _align8(blob_table_off + len(blob_buf))
    ext_buf = b"".join(
        struct.pack(
            "<IIQQ", b.chunk_count, 0, b.uncompressed_size, b.compressed_size
        ).ljust(64, b"\0")
        for b in real.blobs
    )
    inodes_base = _align8(ext_blob_off + len(ext_buf))

    table = []
    pos = inodes_base
    for buf in ino_bufs:
        if pos & 7:
            raise RealBootstrapError("internal: inode offset not 8-aligned")
        table.append(pos >> 3)
        pos += len(buf)

    sb = _V5_SB.pack(
        layout.RAFS_V5_SUPER_MAGIC,
        0x500,
        _V5_SB_SIZE,
        real.blobs[0].chunk_size if real.blobs else 0x100000,
        real.flags,
        len({ri.ino for ri in order}),
        inode_table_off,
        prefetch_off,
        blob_table_off,
        n,
        len(real.prefetch_inos),
        len(blob_buf),
        len(real.blobs),
        ext_blob_off,
    )

    out = io.BytesIO()
    out.write(sb)
    out.write(b"\0" * (_V5_SB_SIZE - out.tell()))
    out.write(struct.pack(f"<{n}I", *table))
    out.write(b"\0" * (prefetch_off - out.tell()))
    out.write(prefetch_buf)
    out.write(b"\0" * (blob_table_off - out.tell()))
    out.write(blob_buf)
    out.write(b"\0" * (ext_blob_off - out.tell()))
    out.write(ext_buf)
    out.write(b"\0" * (inodes_base - out.tell()))
    for buf in ino_bufs:
        out.write(buf)
    return out.getvalue()
