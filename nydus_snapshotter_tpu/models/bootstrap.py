"""RAFS bootstrap (filesystem metadata) model: write, parse, chunk-dict.

The bootstrap is the metadata half of a RAFS image: the file tree plus the
chunk table mapping file extents to (blob, offset, size, digest) records. The
reference delegates bootstrap emission to the external Rust ``nydus-image``
binary (pkg/converter/tool/builder.go:148-178); this framework owns the format
natively so the TPU chunk engine's output — flat (offset, len, digest,
dict-ref) arrays — serializes straight into the chunk table without
host-side re-shaping.

Layout choices (TPU-first, reference-compatible where it matters):

- Superblock magics/offsets match pkg/layout/layout.go:19-31 exactly, so
  ``detect_fs_version`` interoperates: v5 = magic+version at offset 0 within
  an 8 KiB superblock; v6 = EROFS magic at offset 1024 within a
  1024+128+256-byte superblock region.
- All tables are flat fixed-width little-endian records. The chunk table is
  64 bytes/record with the SHA-256 digest first, so it maps directly into a
  ``uint32[N, 16]`` device array for HBM chunk-dict probes — no parsing on
  the hot path.
- Inode records reference a shared bytes heap for names/symlinks/xattrs.
  Inodes are sorted by path; emission is fully deterministic (same tree +
  chunks ⇒ byte-identical bootstrap), which is the reference's correctness
  bar (tests/converter_test.go:380-530).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.models import layout

# ---------------------------------------------------------------------------
# Record layouts
# ---------------------------------------------------------------------------

# Superblock body (shared between v5/v6; only its file offset differs):
# magic u32 | version u32 | features u32 | block_size u32 | chunk_size u32 |
# flags u32 | inode_count u64 | chunk_count u64 | blob_count u64 |
# inode_table_off u64 | chunk_table_off u64 | blob_table_off u64 |
# heap_off u64 | heap_size u64 | pad to 128
_SB_STRUCT = struct.Struct("<IIIIIIQQQQQQQQ")
_SB_SIZE = 128
assert _SB_STRUCT.size <= _SB_SIZE

# Optional table pointers live in the superblock's spare region (directly
# after _SB_STRUCT): cipher_table_off u64 | cipher_count u64 |
# batch_table_off u64 | batch_count u64. Each pair is meaningful only when
# its feature bit is set; zero in older bootstraps.
_SB_CIPHER_STRUCT = struct.Struct("<QQ")
_SB_CIPHER_OFF = _SB_STRUCT.size
_SB_BATCH_STRUCT = struct.Struct("<QQ")
_SB_BATCH_OFF = _SB_CIPHER_OFF + _SB_CIPHER_STRUCT.size
assert _SB_BATCH_OFF + _SB_BATCH_STRUCT.size <= _SB_SIZE
# Prefetch table pointer: u32 offset + u32 count packs into the superblock's
# last spare 8 bytes (entries are u32 inode numbers, hint order preserved —
# the reference's --prefetch-files table, consumed by nydusd at mount).
_SB_PREFETCH_STRUCT = struct.Struct("<II")
_SB_PREFETCH_OFF = _SB_BATCH_OFF + _SB_BATCH_STRUCT.size
assert _SB_PREFETCH_OFF + _SB_PREFETCH_STRUCT.size <= _SB_SIZE

# Feature bits (superblock ``features`` field).
FEATURE_CIPHER_TABLE = 0x1
FEATURE_BATCH_TABLE = 0x2
FEATURE_PREFETCH_TABLE = 0x4

_V5_HEADER_SIZE = 8 * 1024  # reference: v5 = 8K superblock region
_V6_HEADER_SIZE = layout.RAFS_V6_SUPER_BLOCK_SIZE  # 1024 + 128 + 256

# Inode record:
# ino u64 | parent u64 | mode u32 | uid u32 | gid u32 | rdev u32 |
# mtime u64 | size u64 | chunk_index u32 | chunk_count u32 |
# name_off u32 | name_len u16 | flags u16 | symlink_off u32 | symlink_len u32 |
# xattr_off u32 | xattr_len u32 | hardlink_ino u64 | pad to 96
_INODE_STRUCT = struct.Struct("<QQIIIIQQIIIHHIIIIQ")
INODE_SIZE = 96
assert _INODE_STRUCT.size <= INODE_SIZE

# Chunk record (64 B — loads as uint32[16] lanes on device):
# digest 32s | blob_index u32 | flags u32 | uncompressed_offset u64 |
# compressed_offset u64 | uncompressed_size u32 | compressed_size u32
_CHUNK_STRUCT = struct.Struct("<32sIIQQII")
CHUNK_SIZE_BYTES = 64
assert _CHUNK_STRUCT.size == CHUNK_SIZE_BYTES

# Blob record: blob_id 32s | compressed_size u64 | uncompressed_size u64 |
# chunk_count u32 | flags u32 | pad to 64
_BLOB_STRUCT = struct.Struct("<32sQQII")
BLOB_SIZE_BYTES = 64
assert _BLOB_STRUCT.size <= BLOB_SIZE_BYTES

SUPER_VERSION_V5 = layout.RAFS_V5_SUPER_VERSION
SUPER_VERSION_V6 = 0x600

# Chunk flags: low nibble carries the compressor bits (constants.COMPRESSOR_*).
CHUNK_FLAG_COMPRESSED_ZSTD = constants.COMPRESSOR_ZSTD
CHUNK_FLAG_FROM_DICT = 0x100
# Batched chunk (reference ``--batch-size``, tool/builder.go:131-134): several
# small chunks compressed as one unit. ``compressed_offset/size`` describe the
# shared batch extent in the blob; the batch's uncompressed base and size live
# in the bootstrap's batch table keyed by (blob_index, compressed_offset), so
# a bootstrap referencing only *some* members of a foreign (chunk-dict) batch
# still resolves them correctly.
CHUNK_FLAG_BATCH = 0x200

# Cipher record: algo u32 | reserved u32 | key 32s | iv 16s | pad to 64.
_CIPHER_STRUCT = struct.Struct("<II32s16s")
CIPHER_SIZE_BYTES = 64
assert _CIPHER_STRUCT.size <= CIPHER_SIZE_BYTES

# Batch record: blob_index u32 | reserved u32 | compressed_offset u64 |
# uncompressed_base u64 | uncompressed_size u64 = 32 bytes.
_BATCH_STRUCT = struct.Struct("<IIQQQ")
BATCH_SIZE_BYTES = 32
assert _BATCH_STRUCT.size == BATCH_SIZE_BYTES


class BootstrapError(ValueError):
    pass


# ---------------------------------------------------------------------------
# In-memory model
# ---------------------------------------------------------------------------


@dataclass
class ChunkRecord:
    digest: bytes  # raw sha256 (32 B) of uncompressed chunk data
    blob_index: int = 0
    flags: int = 0
    uncompressed_offset: int = 0
    compressed_offset: int = 0
    uncompressed_size: int = 0
    compressed_size: int = 0

    def pack(self) -> bytes:
        if len(self.digest) != 32:
            raise BootstrapError("chunk digest must be raw 32-byte sha256")
        return _CHUNK_STRUCT.pack(
            self.digest,
            self.blob_index,
            self.flags,
            self.uncompressed_offset,
            self.compressed_offset,
            self.uncompressed_size,
            self.compressed_size,
        )

    @classmethod
    def unpack(cls, buf: bytes) -> "ChunkRecord":
        d, bi, fl, uo, co, us, cs = _CHUNK_STRUCT.unpack(buf)
        return cls(d, bi, fl, uo, co, us, cs)


@dataclass
class BlobRecord:
    blob_id: str  # hex sha256 of the blob
    compressed_size: int = 0
    uncompressed_size: int = 0
    chunk_count: int = 0
    flags: int = 0

    def pack(self) -> bytes:
        raw = bytes.fromhex(self.blob_id)
        if len(raw) != 32:
            raise BootstrapError(f"blob id must be hex sha256: {self.blob_id!r}")
        return _BLOB_STRUCT.pack(
            raw, self.compressed_size, self.uncompressed_size, self.chunk_count, self.flags
        ).ljust(BLOB_SIZE_BYTES, b"\x00")

    @classmethod
    def unpack(cls, buf: bytes) -> "BlobRecord":
        raw, csize, usize, count, flags = _BLOB_STRUCT.unpack(buf[: _BLOB_STRUCT.size])
        return cls(raw.hex(), csize, usize, count, flags)


@dataclass
class CipherRecord:
    """Per-blob cipher context (reference ``--encrypt``: blob data is
    encrypted with the context stored in image metadata, key protection
    coming from ocicrypt-encrypting the bootstrap layer itself,
    pkg/encryption/encryption.go:143-253)."""

    algo: int = 0  # converter/crypto.CIPHER_* (0 = blob not encrypted)
    key: bytes = b""
    iv: bytes = b""

    def pack(self) -> bytes:
        if self.algo and (len(self.key) != 32 or len(self.iv) != 16):
            raise BootstrapError("cipher context needs a 32-byte key and 16-byte iv")
        return _CIPHER_STRUCT.pack(
            self.algo, 0, self.key.ljust(32, b"\x00"), self.iv.ljust(16, b"\x00")
        ).ljust(CIPHER_SIZE_BYTES, b"\x00")

    @classmethod
    def unpack(cls, buf: bytes) -> "CipherRecord":
        algo, _reserved, key, iv = _CIPHER_STRUCT.unpack(buf[: _CIPHER_STRUCT.size])
        if not algo:
            return cls()
        return cls(algo=algo, key=key, iv=iv)


@dataclass
class BatchRecord:
    """One batch extent: which blob it lives in, where its compressed bytes
    are, and the uncompressed address range its members cover."""

    blob_index: int
    compressed_offset: int
    uncompressed_base: int
    uncompressed_size: int

    def pack(self) -> bytes:
        return _BATCH_STRUCT.pack(
            self.blob_index,
            0,
            self.compressed_offset,
            self.uncompressed_base,
            self.uncompressed_size,
        )

    @classmethod
    def unpack(cls, buf: bytes) -> "BatchRecord":
        bi, _reserved, coff, base, usize = _BATCH_STRUCT.unpack(buf[: _BATCH_STRUCT.size])
        return cls(bi, coff, base, usize)


# Inode flags
INODE_FLAG_SYMLINK = 0x1
INODE_FLAG_HARDLINK = 0x2
INODE_FLAG_OPAQUE = 0x4  # overlayfs whiteout-opaque directory
INODE_FLAG_WHITEOUT = 0x8


@dataclass
class Inode:
    path: str  # absolute within image, "/" for root
    mode: int = 0o755
    uid: int = 0
    gid: int = 0
    rdev: int = 0
    mtime: int = 0
    size: int = 0
    flags: int = 0
    symlink_target: str = ""
    xattrs: dict[str, bytes] = field(default_factory=dict)
    hardlink_target: str = ""  # path of link target when FLAG_HARDLINK
    chunk_index: int = 0  # first chunk in the global chunk table
    chunk_count: int = 0
    ino: int = 0  # assigned at serialize time (1-based, path order)
    parent_ino: int = 0


def _pack_xattrs(xattrs: dict[str, bytes]) -> bytes:
    out = bytearray()
    for key in sorted(xattrs):
        kb = key.encode()
        vb = xattrs[key]
        out += struct.pack("<HI", len(kb), len(vb)) + kb + vb
    return bytes(out)


def _unpack_xattrs(buf: bytes) -> dict[str, bytes]:
    out: dict[str, bytes] = {}
    off = 0
    while off < len(buf):
        try:
            klen, vlen = struct.unpack_from("<HI", buf, off)
            off += 6
            key = buf[off : off + klen].decode()
        except (struct.error, UnicodeDecodeError) as e:
            raise BootstrapError(f"corrupt xattr region at byte {off}: {e}") from e
        off += klen
        if off + vlen > len(buf):
            raise BootstrapError("xattr value overflows its region")
        out[key] = buf[off : off + vlen]
        off += vlen
    return out


@dataclass
class Bootstrap:
    """A complete RAFS metadata image."""

    version: str = layout.RAFS_V6
    chunk_size: int = 0x100000
    inodes: list[Inode] = field(default_factory=list)
    chunks: list[ChunkRecord] = field(default_factory=list)
    blobs: list[BlobRecord] = field(default_factory=list)
    # Parallel to ``blobs`` when any blob is encrypted (algo 0 entries for
    # plaintext blobs); empty when no encryption is in play.
    ciphers: list[CipherRecord] = field(default_factory=list)
    # Batch extents for CHUNK_FLAG_BATCH chunks; empty without batching.
    batches: list[BatchRecord] = field(default_factory=list)
    # Prefetch hints: inode paths in priority order (serialized as inode
    # numbers; the runtime warms these before first access).
    prefetch: list[str] = field(default_factory=list)

    def cipher_for(self, blob_index: int) -> Optional[CipherRecord]:
        """The cipher context of blob ``blob_index`` (None = plaintext)."""
        if blob_index < len(self.ciphers) and self.ciphers[blob_index].algo:
            return self.ciphers[blob_index]
        return None

    def batch_map(self) -> dict[tuple[int, int], tuple[int, int]]:
        """(blob_index, compressed_offset) -> (uncompressed_base, size)."""
        return {
            (b.blob_index, b.compressed_offset): (b.uncompressed_base, b.uncompressed_size)
            for b in self.batches
        }

    # -- serialization ------------------------------------------------------

    def to_bytes(self) -> bytes:
        if self.version not in (layout.RAFS_V5, layout.RAFS_V6):
            raise BootstrapError(f"unknown RAFS version {self.version!r}")
        header_size = _V5_HEADER_SIZE if self.version == layout.RAFS_V5 else _V6_HEADER_SIZE

        inodes = sorted(self.inodes, key=lambda i: _path_key(i.path))
        ino_by_path = {inode.path: idx + 1 for idx, inode in enumerate(inodes)}
        if len(ino_by_path) != len(inodes):
            seen: set[str] = set()
            for inode in inodes:
                if inode.path in seen:
                    raise BootstrapError(f"duplicate inode path {inode.path!r}")
                seen.add(inode.path)

        heap = bytearray()
        inode_buf = bytearray()
        for idx, inode in enumerate(inodes):
            inode.ino = idx + 1
            parent = _parent_path(inode.path)
            if inode.path == "/":
                inode.parent_ino = 0
            else:
                try:
                    inode.parent_ino = ino_by_path[parent]
                except KeyError:
                    raise BootstrapError(f"missing parent directory inode for {inode.path!r}")
            name = ("/" if inode.path == "/" else inode.path.rsplit("/", 1)[1]).encode()
            name_off = len(heap)
            heap += name
            link = inode.symlink_target.encode()
            symlink_off = len(heap) if link else 0
            heap += link
            xattr_buf = _pack_xattrs(inode.xattrs)
            xattr_off = len(heap) if xattr_buf else 0
            heap += xattr_buf
            if inode.hardlink_target:
                try:
                    hardlink_ino = ino_by_path[inode.hardlink_target]
                except KeyError:
                    raise BootstrapError(
                        f"hardlink target {inode.hardlink_target!r} of {inode.path!r} not in tree"
                    )
            else:
                hardlink_ino = 0
            inode_buf += _INODE_STRUCT.pack(
                inode.ino,
                inode.parent_ino,
                inode.mode,
                inode.uid,
                inode.gid,
                inode.rdev,
                inode.mtime,
                inode.size,
                inode.chunk_index,
                inode.chunk_count,
                name_off,
                len(name),
                inode.flags,
                symlink_off,
                len(link),
                xattr_off,
                len(xattr_buf),
                hardlink_ino,
            ).ljust(INODE_SIZE, b"\x00")

        chunk_buf = b"".join(c.pack() for c in self.chunks)
        blob_buf = b"".join(b.pack() for b in self.blobs)

        prefetch_buf = b""
        for path in self.prefetch:
            ino = ino_by_path.get(path)
            if ino is None:
                raise BootstrapError(f"prefetch path {path!r} not in tree")
            prefetch_buf += struct.pack("<I", ino)

        if self.ciphers and len(self.ciphers) != len(self.blobs):
            raise BootstrapError(
                f"cipher table has {len(self.ciphers)} entries for "
                f"{len(self.blobs)} blobs"
            )
        has_ciphers = any(c.algo for c in self.ciphers)
        cipher_buf = b"".join(c.pack() for c in self.ciphers) if has_ciphers else b""
        batch_buf = b"".join(b.pack() for b in self.batches)

        inode_table_off = header_size
        chunk_table_off = inode_table_off + len(inode_buf)
        blob_table_off = chunk_table_off + len(chunk_buf)
        cipher_table_off = blob_table_off + len(blob_buf)
        batch_table_off = cipher_table_off + len(cipher_buf)
        prefetch_table_off = batch_table_off + len(batch_buf)
        heap_off = prefetch_table_off + len(prefetch_buf)

        magic = (
            layout.RAFS_V5_SUPER_MAGIC
            if self.version == layout.RAFS_V5
            else layout.RAFS_V6_SUPER_MAGIC
        )
        sb_version = SUPER_VERSION_V5 if self.version == layout.RAFS_V5 else SUPER_VERSION_V6
        features = (
            (FEATURE_CIPHER_TABLE if has_ciphers else 0)
            | (FEATURE_BATCH_TABLE if self.batches else 0)
            | (FEATURE_PREFETCH_TABLE if self.prefetch else 0)
        )
        sb = _SB_STRUCT.pack(
            magic,
            sb_version,
            features,
            4096,
            self.chunk_size,
            0,
            len(inodes),
            len(self.chunks),
            len(self.blobs),
            inode_table_off,
            chunk_table_off,
            blob_table_off,
            heap_off,
            len(heap),
        ).ljust(_SB_SIZE, b"\x00")
        if has_ciphers:
            sb = (
                sb[:_SB_CIPHER_OFF]
                + _SB_CIPHER_STRUCT.pack(cipher_table_off, len(self.ciphers))
                + sb[_SB_CIPHER_OFF + _SB_CIPHER_STRUCT.size :]
            )
        if self.batches:
            sb = (
                sb[:_SB_BATCH_OFF]
                + _SB_BATCH_STRUCT.pack(batch_table_off, len(self.batches))
                + sb[_SB_BATCH_OFF + _SB_BATCH_STRUCT.size :]
            )
        if self.prefetch:
            sb = (
                sb[:_SB_PREFETCH_OFF]
                + _SB_PREFETCH_STRUCT.pack(prefetch_table_off, len(self.prefetch))
                + sb[_SB_PREFETCH_OFF + _SB_PREFETCH_STRUCT.size :]
            )

        header = bytearray(header_size)
        if self.version == layout.RAFS_V5:
            header[:_SB_SIZE] = sb
        else:
            # v6: EROFS-style — superblock region at offset 1024.
            header[layout.RAFS_V6_SUPER_BLOCK_OFFSET : layout.RAFS_V6_SUPER_BLOCK_OFFSET + _SB_SIZE] = sb

        return (
            bytes(header)
            + bytes(inode_buf)
            + chunk_buf
            + blob_buf
            + cipher_buf
            + batch_buf
            + prefetch_buf
            + bytes(heap)
        )

    @classmethod
    def from_bytes(cls, buf: bytes) -> "Bootstrap":
        version = layout.detect_fs_version(buf[: layout.MAX_SUPER_BLOCK_SIZE])
        sb_off = 0 if version == layout.RAFS_V5 else layout.RAFS_V6_SUPER_BLOCK_OFFSET
        (
            _magic,
            sb_version,
            features,
            _block_size,
            chunk_size,
            _flags,
            inode_count,
            chunk_count,
            blob_count,
            inode_table_off,
            chunk_table_off,
            blob_table_off,
            heap_off,
            heap_size,
        ) = _SB_STRUCT.unpack_from(buf, sb_off)
        cipher_table_off = cipher_count = 0
        if features & FEATURE_CIPHER_TABLE:
            cipher_table_off, cipher_count = _SB_CIPHER_STRUCT.unpack_from(
                buf, sb_off + _SB_CIPHER_OFF
            )
            if cipher_count != blob_count:
                raise BootstrapError(
                    f"cipher table has {cipher_count} entries for {blob_count} blobs"
                )
        batch_table_off = batch_count = 0
        if features & FEATURE_BATCH_TABLE:
            batch_table_off, batch_count = _SB_BATCH_STRUCT.unpack_from(
                buf, sb_off + _SB_BATCH_OFF
            )
        prefetch_table_off = prefetch_count = 0
        if features & FEATURE_PREFETCH_TABLE:
            prefetch_table_off, prefetch_count = _SB_PREFETCH_STRUCT.unpack_from(
                buf, sb_off + _SB_PREFETCH_OFF
            )

        # A foreign bootstrap (e.g. one written by the Rust nydus-image) or a
        # truncated file can share the magic while carrying garbage fields —
        # validate every table against the buffer before trusting it.
        expected_version = SUPER_VERSION_V5 if version == layout.RAFS_V5 else SUPER_VERSION_V6
        if sb_version != expected_version:
            raise BootstrapError(
                f"unsupported bootstrap superblock version {sb_version:#x} "
                f"(foreign {version} bootstrap?)"
            )
        for name, off, count, rec_size in (
            ("inode", inode_table_off, inode_count, INODE_SIZE),
            ("chunk", chunk_table_off, chunk_count, CHUNK_SIZE_BYTES),
            ("blob", blob_table_off, blob_count, BLOB_SIZE_BYTES),
            ("cipher", cipher_table_off, cipher_count, CIPHER_SIZE_BYTES),
            ("batch", batch_table_off, batch_count, BATCH_SIZE_BYTES),
            ("prefetch", prefetch_table_off, prefetch_count, 4),
            ("heap", heap_off, heap_size, 1),
        ):
            if off + count * rec_size > len(buf):
                raise BootstrapError(
                    f"{name} table [{off}, +{count}*{rec_size}] overflows "
                    f"bootstrap of {len(buf)} bytes"
                )

        heap = buf[heap_off : heap_off + heap_size]

        inodes: list[Inode] = []
        paths_by_ino: dict[int, str] = {0: ""}
        hardlink_inos: list[int] = []
        for i in range(inode_count):
            rec = buf[inode_table_off + i * INODE_SIZE : inode_table_off + (i + 1) * INODE_SIZE]
            (
                ino,
                parent_ino,
                mode,
                uid,
                gid,
                rdev,
                mtime,
                size,
                chunk_index,
                cc,
                name_off,
                name_len,
                flags,
                symlink_off,
                symlink_len,
                xattr_off,
                xattr_len,
                hardlink_ino,
            ) = _INODE_STRUCT.unpack(rec[: _INODE_STRUCT.size])
            for what, off, ln in (
                ("name", name_off, name_len),
                ("symlink", symlink_off, symlink_len),
                ("xattr", xattr_off, xattr_len),
            ):
                if off + ln > heap_size:
                    raise BootstrapError(
                        f"inode record {i}: {what} heap ref [{off}, +{ln}] overflows "
                        f"heap of {heap_size} bytes"
                    )
            if name_len == 0:
                raise BootstrapError(f"inode record {i} has an empty name")
            try:
                name = heap[name_off : name_off + name_len].decode()
                parent_path = paths_by_ino[parent_ino]
            except (UnicodeDecodeError, KeyError) as e:
                raise BootstrapError(f"corrupt inode record {i}: {e}") from e
            path = "/" if name == "/" else (parent_path.rstrip("/") + "/" + name)
            paths_by_ino[ino] = path
            hardlink_inos.append(hardlink_ino)
            inodes.append(
                Inode(
                    path=path,
                    mode=mode,
                    uid=uid,
                    gid=gid,
                    rdev=rdev,
                    mtime=mtime,
                    size=size,
                    flags=flags,
                    symlink_target=heap[symlink_off : symlink_off + symlink_len].decode(
                        errors="replace"
                    ),
                    xattrs=_unpack_xattrs(heap[xattr_off : xattr_off + xattr_len]),
                    chunk_index=chunk_index,
                    chunk_count=cc,
                    ino=ino,
                    parent_ino=parent_ino,
                )
            )
        # Hardlink targets may sort after the link itself; resolve once all
        # inos are known.
        for inode, hl_ino in zip(inodes, hardlink_inos):
            if hl_ino:
                if hl_ino not in paths_by_ino:
                    raise BootstrapError(
                        f"inode {inode.path!r} hardlinks to unknown ino {hl_ino}"
                    )
                inode.hardlink_target = paths_by_ino[hl_ino]

        chunks = [
            ChunkRecord.unpack(
                buf[chunk_table_off + i * CHUNK_SIZE_BYTES : chunk_table_off + (i + 1) * CHUNK_SIZE_BYTES]
            )
            for i in range(chunk_count)
        ]
        blobs = [
            BlobRecord.unpack(
                buf[blob_table_off + i * BLOB_SIZE_BYTES : blob_table_off + (i + 1) * BLOB_SIZE_BYTES]
            )
            for i in range(blob_count)
        ]
        ciphers = [
            CipherRecord.unpack(
                buf[cipher_table_off + i * CIPHER_SIZE_BYTES : cipher_table_off + (i + 1) * CIPHER_SIZE_BYTES]
            )
            for i in range(cipher_count)
        ]
        batches = [
            BatchRecord.unpack(
                buf[batch_table_off + i * BATCH_SIZE_BYTES : batch_table_off + (i + 1) * BATCH_SIZE_BYTES]
            )
            for i in range(batch_count)
        ]
        prefetch: list[str] = []
        for i in range(prefetch_count):
            (ino,) = struct.unpack_from("<I", buf, prefetch_table_off + i * 4)
            path = paths_by_ino.get(ino)
            if not path:
                raise BootstrapError(f"prefetch entry references unknown inode {ino}")
            prefetch.append(path)
        return cls(
            version=version,
            chunk_size=chunk_size,
            inodes=inodes,
            chunks=chunks,
            blobs=blobs,
            ciphers=ciphers,
            batches=batches,
            prefetch=prefetch,
        )

    # -- views --------------------------------------------------------------

    def inode_by_path(self) -> dict[str, Inode]:
        return {i.path: i for i in self.inodes}

    def chunk_digests_u32(self) -> np.ndarray:
        """Chunk digests as a uint32[N, 8] array (device-ready dict keys)."""
        if not self.chunks:
            return np.zeros((0, 8), dtype=np.uint32)
        raw = b"".join(c.digest for c in self.chunks)
        return np.frombuffer(raw, dtype="<u4").reshape(len(self.chunks), 8).copy()

    def referenced_blob_ids(self) -> list[str]:
        """Blob ids actually referenced by chunks, in blob-table order.

        This is the dedup result surface: the reference's merge step reports
        the referenced blob digest list from merge-output.json
        (pkg/converter/tool/builder.go:278-294).
        """
        used = {c.blob_index for c in self.chunks}
        return [b.blob_id for i, b in enumerate(self.blobs) if i in used]


def _parent_path(path: str) -> str:
    if path == "/":
        return ""
    parent = path.rsplit("/", 1)[0]
    return parent if parent else "/"


def _path_key(path: str) -> tuple:
    # Depth-first order with parents before children; stable across runs.
    if path == "/":
        return ("",)
    return tuple(path.strip("/").split("/"))


# ---------------------------------------------------------------------------
# Chunk dictionary
# ---------------------------------------------------------------------------


class ChunkDict:
    """Cross-image dedup dictionary backed by a dict-image bootstrap.

    Reference semantics: ``--chunk-dict bootstrap=<path>`` hands nydus-image a
    bootstrap whose chunk table seeds dedup (tool/builder.go:122-123). Here
    the dict exposes digest→(blob_id, chunk) and a flat ``uint32[N, 8]`` key
    array for the device-resident probe table.
    """

    def __init__(self, bootstrap: Bootstrap):
        self.bootstrap = bootstrap
        self._by_digest: dict[bytes, ChunkRecord] = {}
        for chunk in bootstrap.chunks:
            self._by_digest.setdefault(chunk.digest, chunk)

    @classmethod
    def from_path(cls, path: str) -> "ChunkDict":
        from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap

        with open(path, "rb") as f:
            # `--chunk-dict bootstrap=…` accepts REAL nydus bootstraps
            # too: dedup against images the reference toolchain built.
            return cls(load_any_bootstrap(f.read()))

    def __len__(self) -> int:
        return len(self._by_digest)

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._by_digest

    def get(self, digest: bytes) -> Optional[ChunkRecord]:
        return self._by_digest.get(digest)

    def blob_id_for(self, chunk: ChunkRecord) -> str:
        return self.bootstrap.blobs[chunk.blob_index].blob_id

    def digests_u32(self) -> np.ndarray:
        return self.bootstrap.chunk_digests_u32()

    def blob_ids(self) -> list[str]:
        return [b.blob_id for b in self.bootstrap.blobs]


def parse_chunk_dict_arg(arg: str) -> str:
    """Parse the reference's chunk-dict argument form ``bootstrap=<path>``.

    Bare paths are accepted too (reference treats type prefix as optional).
    """
    if arg.startswith("bootstrap="):
        return arg[len("bootstrap=") :]
    return arg
