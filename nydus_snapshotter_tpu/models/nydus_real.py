"""Readers for REAL nydus-toolchain bootstraps (RAFS v5 + RAFS v6/EROFS).

The framework's own bootstrap format (models/bootstrap.py) shares only the
magic numbers with the reference toolchain's; everything the runtime mounts
in the reference world was produced by the Rust `nydus-image` builder.
These readers parse that actual on-disk layout down to the full inode and
chunk tables, so the framework can inspect, unpack, and serve images it
did not convert itself.

Layout knowledge was derived from the committed real artifacts
(/root/reference/pkg/filesystem/testdata/{v5-bootstrap-file-size-736032,
v6-bootstrap-chunk-pos-438272}.tar.gz) plus the reference's detection
contract (/root/reference/pkg/layout/layout.go:19-76: v5 magic 0x52414653
at offset 0, v6/EROFS magic 0xE0F5E1E2 at offset 1024). Field maps were
validated structurally on those fixtures: every offset below reproduces
the fixture's internal cross-references (table offsets/sizes, inode
counts, chunk counts, nlink/child relationships) exactly.

RAFS v5 bootstrap:
    [0x0000] superblock (8 KiB)
    [inode_table_offset] u32 per nid: inode offset >> 3
    [prefetch_table_offset] u32 inode numbers
    [blob_table_offset] (ra_offset u32, ra_size u32, 64-char hex id)+
    [extended_blob_table_offset] 64-B entries (chunk_count, sizes)
    inodes: 128-B fixed part + name (8-aligned) + symlink (8-aligned)
            + optional xattr table + chunk infos (80 B each)

RAFS v6 bootstrap = EROFS image + nydus extensions:
    [1024] EROFS superblock; meta_blkaddr, root_nid, devt_slotoff
    [1152] nydus extended superblock: flags, blob-table offset/size,
           chunk size, chunk-table offset/size (the fixture's chunk table
           sits at 438272 — the number in its filename)
    [devt_slotoff*128] device slots: 64-B blob-id tag per data blob
    [blob_table_offset] 256-B RafsV6Blob records
    [chunk_table_offset] 80-B chunk infos (v5 layout)
    inode tree: standard EROFS compact/extended inodes, dirents, and
    CHUNK_BASED data layout whose 8-B chunk indexes map uncompressed
    block addresses into the chunk table.
"""

from __future__ import annotations

import os
import stat
import struct
from dataclasses import dataclass, field

from nydus_snapshotter_tpu.models import layout

__all__ = [
    "RealBootstrapError",
    "RealInode",
    "RealChunk",
    "RealBlob",
    "RealBootstrap",
    "parse_real_bootstrap",
]


class RealBootstrapError(ValueError):
    pass


@dataclass
class RealChunk:
    digest: bytes  # 32-B chunk digest (blake3 or sha256 per sb flags)
    blob_index: int
    flags: int
    compressed_size: int
    uncompressed_size: int
    compressed_offset: int
    uncompressed_offset: int
    file_offset: int = 0
    index: int = 0


@dataclass
class RealInode:
    path: str
    ino: int
    mode: int = 0
    uid: int = 0
    gid: int = 0
    mtime: int = 0
    size: int = 0
    nlink: int = 1
    rdev: int = 0
    flags: int = 0
    digest: bytes = b""
    symlink_target: str = ""
    xattrs: dict = field(default_factory=dict)
    chunks: list = field(default_factory=list)  # list[RealChunk]

    @property
    def is_dir(self) -> bool:
        return stat.S_ISDIR(self.mode)

    @property
    def is_regular(self) -> bool:
        return stat.S_ISREG(self.mode)

    @property
    def is_symlink(self) -> bool:
        return stat.S_ISLNK(self.mode)


@dataclass
class RealBlob:
    blob_id: str
    chunk_count: int = 0
    compressed_size: int = 0
    uncompressed_size: int = 0
    chunk_size: int = 0
    # v6: the raw 256-B RafsV6Blob record as parsed, so the writer can
    # round-trip fields beyond the ones modeled here.
    raw_rec: bytes = b""


@dataclass
class RealBootstrap:
    version: str  # layout.RAFS_V5 | layout.RAFS_V6
    flags: int
    inodes: list  # list[RealInode], root first, path-discoverable order
    blobs: list  # list[RealBlob]
    chunks: list  # list[RealChunk] — v6: the shared chunk table;
    # v5: concatenation of per-inode chunk runs
    prefetch_inos: list = field(default_factory=list)

    @property
    def compressor(self) -> str:
        """Chunk codec from the superblock flags (nydus RafsSuperFlags:
        0x1 none, 0x2 lz4_block, 0x40 gzip, 0x80 zstd; both committed
        fixtures carry 0x2 — lz4)."""
        if self.flags & 0x2:
            return "lz4_block"
        if self.flags & 0x80:
            return "zstd"
        if self.flags & 0x40:
            return "gzip"
        return "none"

    def tree(self) -> dict:
        """Nested {name: node} dict reconstruction of the directory tree;
        leaves map to their RealInode."""
        root: dict = {}
        for ino in self.inodes:
            if ino.path == "/":
                continue
            parts = ino.path.lstrip("/").split("/")
            cur = root
            for p in parts[:-1]:
                nxt = cur.get(p)
                if not isinstance(nxt, dict):
                    nxt = cur[p] = {}
                cur = nxt
            cur[parts[-1]] = {} if ino.is_dir else ino
        return root

    def by_path(self) -> dict:
        return {i.path: i for i in self.inodes}

    def to_tar(self, dest, blob_data: "dict[str, bytes] | None" = None) -> int:
        """Unpack to an OCI-style tar stream (reference Unpack semantics,
        convert_unix.go:669-733, against the REAL bootstrap layout).

        Metadata (paths, modes, owners, mtimes, symlinks, xattrs,
        hardlinks, device numbers) always round-trips. File bytes are
        reconstructed when ``blob_data`` maps blob_id -> raw blob bytes;
        chunks are sliced at their compressed extents and inflated with
        the superblock's codec (per-chunk COMPRESSED flag bit0 decides
        whether a chunk is stored raw), streamed one chunk at a time —
        never the whole file in memory. Files whose blob is not provided
        are emitted as zero-filled holes of the right size so the tree
        shape survives. Hardlink aliases (repeated ino among regular
        files) become tar LNKTYPE entries pointing at the first path.
        Returns the number of members written.
        """
        import tarfile

        decompress = _make_chunk_decoder(self.compressor)
        n = 0
        seen_ino: dict[int, str] = {}
        tf = tarfile.open(fileobj=dest, mode="w", format=tarfile.PAX_FORMAT)
        with tf:
            for ino in sorted(self.inodes, key=lambda i: i.path):
                if ino.path == "/":
                    continue
                ti = tarfile.TarInfo(ino.path.lstrip("/"))
                ti.mode = ino.mode & 0o7777
                ti.uid, ti.gid = ino.uid, ino.gid
                ti.mtime = ino.mtime
                if ino.xattrs:
                    ti.pax_headers = {
                        f"SCHILY.xattr.{k}": v.decode("utf-8", "surrogateescape")
                        for k, v in ino.xattrs.items()
                    }
                if ino.is_dir:
                    ti.type = tarfile.DIRTYPE
                    tf.addfile(ti)
                elif ino.is_symlink:
                    ti.type = tarfile.SYMTYPE
                    ti.linkname = ino.symlink_target
                    tf.addfile(ti)
                elif ino.is_regular:
                    first = seen_ino.get(ino.ino)
                    if first is not None and ino.nlink > 1:
                        ti.type = tarfile.LNKTYPE
                        ti.linkname = first
                        tf.addfile(ti)
                        n += 1
                        continue
                    seen_ino[ino.ino] = ti.name
                    ti.size = ino.size
                    tf.addfile(
                        ti,
                        _ChunkStream(
                            ino, self.blobs, blob_data or {}, decompress
                        ),
                    )
                else:
                    # device/fifo/socket nodes: metadata only
                    ti.type = (
                        tarfile.CHRTYPE
                        if stat.S_ISCHR(ino.mode)
                        else tarfile.BLKTYPE
                        if stat.S_ISBLK(ino.mode)
                        else tarfile.FIFOTYPE
                    )
                    # Linux dev_t: 12-bit major, 20-bit split minor.
                    ti.devmajor = (ino.rdev >> 8) & 0xFFF
                    ti.devminor = (ino.rdev & 0xFF) | ((ino.rdev >> 12) & 0xFFF00)
                    tf.addfile(ti)
                n += 1
        return n


def _make_chunk_decoder(compressor: str):
    """Chunk codec dispatch for the superblock's compressor identity."""
    if compressor == "lz4_block":
        from nydus_snapshotter_tpu.utils import lz4 as lz4mod

        return lz4mod.decompress_block
    if compressor == "zstd":
        from nydus_snapshotter_tpu.utils.zstdcompat import zstandard

        return lambda raw, usize: zstandard.ZstdDecompressor().decompress(
            raw, max_output_size=max(usize, 1)
        )
    if compressor == "none":
        return lambda raw, usize: raw
    raise RealBootstrapError(f"unsupported bootstrap compressor {compressor!r}")


class _ChunkStream:
    """Read-only file object yielding a regular file's bytes one chunk at
    a time (tarfile copies from it in bounded blocks — whole multi-GB
    files never materialize in memory). Chunks whose blob is absent from
    ``blob_data`` yield zero-filled holes; trailing bytes beyond the
    chunk run (sparse tails) are zero-filled to the inode size."""

    def __init__(self, ino: "RealInode", blobs, blob_data, decompress):
        self._ino = ino
        self._blobs = blobs
        self._blob_data = blob_data
        self._decompress = decompress
        self._chunks = iter(ino.chunks if blob_data else ())
        self._emitted = 0  # bytes handed out so far
        self._buf = memoryview(b"")

    def _next_chunk(self) -> bool:
        ck = next(self._chunks, None)
        if ck is None:
            return False
        blob = self._blob_data.get(self._blobs[ck.blob_index].blob_id)
        if blob is None:
            data = b"\0" * ck.uncompressed_size
        else:
            raw = blob[
                ck.compressed_offset : ck.compressed_offset + ck.compressed_size
            ]
            if ck.flags & 0x1:  # BlobChunkFlags::COMPRESSED
                data = self._decompress(raw, ck.uncompressed_size)
            else:
                data = raw
        self._buf = memoryview(bytes(data))
        return True

    def read(self, n: int = -1) -> bytes:
        remaining = self._ino.size - self._emitted
        if remaining <= 0:
            return b""
        if n < 0 or n > remaining:
            n = remaining
        if not self._buf:
            if not self._next_chunk():
                # sparse tail (or no blob data at all): zero-fill
                out = b"\0" * n
                self._emitted += n
                return out
        take = min(n, len(self._buf))
        out = bytes(self._buf[:take])
        self._buf = self._buf[take:]
        self._emitted += take
        return out


# ---------------------------------------------------------------------------
# RAFS v5
# ---------------------------------------------------------------------------

# Superblock prefix (fields validated on the 736032-B fixture: table
# offsets chain exactly, entries==3517, inodes==3515).
_V5_SB = struct.Struct("<IIIIQQQQQIIIIQ")
# 128-B on-disk inode (offsets confirmed by fixture decode: root at
# inode_table[0]<<3 with mode 040755, nlink 17, child_count 21, name "/").
_V5_INODE = struct.Struct("<32sQQIIIIQQQIIIHHIIQII")
# 80-B chunk info (same record the v6 chunk table reuses).
_V5_CHUNK = struct.Struct("<32sIIIIQQQII")

_V5_FLAG_SYMLINK = 0x1
_V5_FLAG_HARDLINK = 0x2
_V5_FLAG_XATTR = 0x4


def _align8(n: int) -> int:
    return (n + 7) & ~7


def parse_real_v5(data: bytes) -> RealBootstrap:
    if len(data) < 8192:
        raise RealBootstrapError("v5 bootstrap shorter than its superblock")
    (
        magic,
        fs_version,
        sb_size,
        _block_size,
        flags,
        inodes_count,
        inode_table_off,
        prefetch_table_off,
        blob_table_off,
        inode_table_entries,
        prefetch_table_entries,
        blob_table_size,
        ext_blob_entries,
        ext_blob_off,
    ) = _V5_SB.unpack_from(data, 0)
    if magic != layout.RAFS_V5_SUPER_MAGIC:
        raise RealBootstrapError(f"bad v5 magic {magic:#x}")
    if fs_version != 0x500:
        raise RealBootstrapError(f"unsupported v5 fs_version {fs_version:#x}")
    if sb_size > len(data) or inode_table_off + 4 * inode_table_entries > len(data):
        raise RealBootstrapError("v5 inode table exceeds bootstrap size")
    if blob_table_off + blob_table_size > len(data):
        raise RealBootstrapError("v5 blob table exceeds bootstrap size")

    # Blob table: (readahead_offset u32, readahead_size u32, hex id).
    blobs: list[RealBlob] = []
    boff = blob_table_off
    bend = blob_table_off + blob_table_size
    while boff + 8 < bend:
        boff += 8  # readahead fields
        idend = boff
        while idend < bend and data[idend] not in (0,):
            idend += 1
        bid = data[boff:idend].decode("ascii", "replace")
        if bid:
            # v5 keeps the chunking granularity in the superblock's
            # block_size (1 MiB on the fixture) — surface it per blob so
            # bridged bootstraps keep a valid Bootstrap.chunk_size.
            blobs.append(RealBlob(blob_id=bid, chunk_size=_block_size))
        # ids are NUL-separated when multiple entries follow
        boff = idend + 1
    # Extended blob table: 64-B entries with chunk_count + sizes. A
    # corrupted count must not spin the loop — blobs is the real bound.
    for i in range(min(ext_blob_entries, len(blobs))):
        off = ext_blob_off + 64 * i
        if off + 24 <= len(data) and i < len(blobs):
            # Field order pinned against the fixture: the per-chunk sums
            # of the walked chunk table equal (uncompressed, compressed)
            # in THIS order exactly (77298891 / 43090887).
            cc, _r, usize, csize = struct.unpack_from("<IIQQ", data, off)
            blobs[i].chunk_count = cc
            blobs[i].compressed_size = csize
            blobs[i].uncompressed_size = usize

    n_prefetch = min(
        prefetch_table_entries,
        max(0, (len(data) - prefetch_table_off) // 4) if prefetch_table_off < len(data) else 0,
    )
    prefetch_inos = [
        struct.unpack_from("<I", data, prefetch_table_off + 4 * i)[0]
        for i in range(n_prefetch)
    ]

    table = struct.unpack_from(f"<{inode_table_entries}I", data, inode_table_off)

    entries: list[tuple[RealInode, int, int]] = []  # inode, child_index, child_count
    ino_to_entry: dict[int, int] = {}
    all_chunks: list[RealChunk] = []
    for nid, slot in enumerate(table):
        off = slot << 3
        if slot == 0 or off + 128 > len(data):
            raise RealBootstrapError(f"v5 inode table entry {nid} out of range")
        (
            digest,
            _parent,
            i_ino,
            uid,
            gid,
            _projid,
            mode,
            size,
            _blocks,
            iflags,
            nlink,
            child_index,
            child_count,
            name_size,
            symlink_size,
            rdev,
            _pad,
            mtime,
            _mtime_ns,
            _rsvd,
        ) = _V5_INODE.unpack_from(data, off)
        pos = off + 128
        name = data[pos : pos + name_size].decode("utf-8", "surrogateescape")
        pos += _align8(name_size)
        target = ""
        if iflags & _V5_FLAG_SYMLINK and symlink_size:
            target = data[pos : pos + symlink_size].split(b"\0", 1)[0].decode(
                "utf-8", "surrogateescape"
            )
            pos += _align8(symlink_size)
        xattrs: dict = {}
        if iflags & _V5_FLAG_XATTR:
            if pos + 8 > len(data):
                raise RealBootstrapError(f"v5 xattr table of inode {i_ino} truncated")
            (xsize,) = struct.unpack_from("<Q", data, pos)
            if pos + 8 + xsize > len(data):
                raise RealBootstrapError(
                    f"v5 xattr table of inode {i_ino} exceeds bootstrap"
                )
            xend = pos + 8 + xsize
            xpos = pos + 8
            while xpos + 4 <= xend:
                (esize,) = struct.unpack_from("<I", data, xpos)
                if esize == 0 or xpos + 4 + esize > xend:
                    break
                pair = data[xpos + 4 : xpos + 4 + esize]
                k, _, v = pair.partition(b"\0")
                xattrs[k.decode("utf-8", "surrogateescape")] = v
                xpos += 4 + _align8(esize)
            pos = _align8(xend)
        inode = RealInode(
            path=name,  # resolved to a full path below
            ino=i_ino,
            mode=mode,
            uid=uid,
            gid=gid,
            mtime=mtime,
            size=size,
            nlink=nlink,
            rdev=rdev,
            flags=iflags,
            digest=digest,
            symlink_target=target,
            xattrs=xattrs,
        )
        if stat.S_ISREG(mode) and not (iflags & _V5_FLAG_HARDLINK):
            for ci in range(child_count):
                coff = pos + 80 * ci
                if coff + 80 > len(data):
                    raise RealBootstrapError(
                        f"v5 chunk info of inode {i_ino} out of range"
                    )
                (
                    cdigest,
                    blob_index,
                    cflags,
                    csize,
                    usize,
                    c_off,
                    u_off,
                    f_off,
                    cindex,
                    _crsvd,
                ) = _V5_CHUNK.unpack_from(data, coff)
                ck = RealChunk(
                    digest=cdigest,
                    blob_index=blob_index,
                    flags=cflags,
                    compressed_size=csize,
                    uncompressed_size=usize,
                    compressed_offset=c_off,
                    uncompressed_offset=u_off,
                    file_offset=f_off,
                    index=cindex,
                )
                inode.chunks.append(ck)
                all_chunks.append(ck)
        entries.append((inode, child_index, child_count))
        ino_to_entry.setdefault(i_ino, nid)

    if not entries:
        raise RealBootstrapError("v5 bootstrap has no inodes")

    # Path resolution: directories carry (child_index, child_count) ranges
    # into the inode table (1-based); walk from the root entry.
    root = entries[0][0]
    root.path = "/"
    stack = [(0, "")]
    seen = {0}
    while stack:
        nid, prefix = stack.pop()
        inode, child_index, child_count = entries[nid]
        if not inode.is_dir or child_count == 0:
            continue
        if child_index < 1 or child_index - 1 + child_count > len(entries):
            # a corrupted range must not spin for billions of misses
            raise RealBootstrapError(
                f"v5 child range of {inode.path!r} exceeds inode table"
            )
        for cn in range(child_index - 1, child_index - 1 + child_count):
            if cn in seen:
                continue
            seen.add(cn)
            child = entries[cn][0]
            child.path = f"{prefix}/{child.path}"
            stack.append((cn, child.path))

    inodes = [e[0] for e in entries]
    # hardlink aliases: resolve chunk lists from their target ino
    for inode in inodes:
        if inode.flags & _V5_FLAG_HARDLINK and not inode.chunks:
            tgt = ino_to_entry.get(inode.ino)
            if tgt is not None:
                inode.chunks = entries[tgt][0].chunks

    if len({i.ino for i in inodes}) != inodes_count:
        raise RealBootstrapError(
            f"v5 inode count mismatch: superblock {inodes_count}, "
            f"walked {len({i.ino for i in inodes})}"
        )
    return RealBootstrap(
        version=layout.RAFS_V5,
        flags=flags,
        inodes=inodes,
        blobs=blobs,
        chunks=all_chunks,
        prefetch_inos=prefetch_inos,
    )


# ---------------------------------------------------------------------------
# RAFS v6 (EROFS + nydus extensions)
# ---------------------------------------------------------------------------

# The reader and the in-tree EROFS writer (models/erofs_image.py) must
# agree on the on-disk contract — share one set of struct definitions.
from nydus_snapshotter_tpu.models.erofs_image import (  # noqa: E402
    _CHUNK_INDEX as _EROFS_CHUNK_INDEX,
    _DIRENT as _EROFS_DIRENT,
    _INODE_COMPACT as _EROFS_INODE_COMPACT,
    _XATTR_ENTRY as _EROFS_XATTR_ENTRY,
    _XATTR_EXACT as _EROFS_XATTR_EXACT,
    _XATTR_PREFIXES as _EROFS_XATTR_PREFIX_LIST,
)

_EROFS_SB = struct.Struct("<IIIBBHQQIIII16s16sIHHH")
_EROFS_INODE_EXTENDED = struct.Struct("<HHHHQIIIIQIII")
_NYDUS_EXT_SB = struct.Struct("<QQIIQQ")
# ...followed by (prefetch_table_offset u64, prefetch_table_size u32) —
# decoded from the committed v6 fixture, whose ext sb carries
# (4352, 4): one u32 prefetch entry right after the blob table. Entries
# are EROFS nids (the fixture's single entry is nid 142).
_NYDUS_EXT_SB_PREFETCH = struct.Struct("<QI")

# index -> name prefix (reverse of the writer's registry).
_EROFS_XATTR_PREFIXES = {idx: prefix for prefix, idx in _EROFS_XATTR_PREFIX_LIST}
_EROFS_XATTR_PREFIXES.update({idx: name for name, idx in _EROFS_XATTR_EXACT.items()})

_EROFS_LAYOUT_FLAT_PLAIN = 0
_EROFS_LAYOUT_FLAT_INLINE = 2
_EROFS_LAYOUT_CHUNK_BASED = 4


def parse_real_v6(data: bytes) -> RealBootstrap:
    if len(data) < 4096:
        raise RealBootstrapError("v6 bootstrap shorter than its first block")
    (
        magic,
        _chksum,
        _feat_compat,
        blkszbits,
        _extslots,
        root_nid,
        inos,
        _btime,
        _btime_ns,
        _blocks,
        meta_blkaddr,
        _xattr_blkaddr,
        _uuid,
        _vol,
        _feat_incompat,
        _compr,
        extra_devices,
        devt_slotoff,
    ) = _EROFS_SB.unpack_from(data, 1024)
    if magic != layout.RAFS_V6_SUPER_MAGIC:
        raise RealBootstrapError(f"bad v6/EROFS magic {magic:#x}")
    if not 9 <= blkszbits <= 16:
        raise RealBootstrapError(f"implausible EROFS block size 2^{blkszbits}")
    blksz = 1 << blkszbits

    # nydus extended superblock directly after the EROFS one.
    (
        flags,
        blob_table_off,
        blob_table_size,
        chunk_size,
        chunk_table_off,
        chunk_table_size,
    ) = _NYDUS_EXT_SB.unpack_from(data, 1024 + 128)
    if chunk_table_off + chunk_table_size > len(data):
        raise RealBootstrapError("v6 chunk table exceeds bootstrap size")
    if chunk_table_size % 80:
        raise RealBootstrapError("v6 chunk table not a multiple of 80 bytes")
    prefetch_off, prefetch_size = _NYDUS_EXT_SB_PREFETCH.unpack_from(
        data, 1024 + 128 + _NYDUS_EXT_SB.size
    )
    prefetch_nids: list[int] = []
    if prefetch_off and prefetch_off + prefetch_size <= len(data):
        prefetch_nids = [
            struct.unpack_from("<I", data, prefetch_off + 4 * i)[0]
            for i in range(prefetch_size // 4)
        ]

    # Device slots name the data blobs.
    blobs: list[RealBlob] = []
    for i in range(extra_devices):
        off = devt_slotoff * 128 + 128 * i
        tag = data[off : off + 64].split(b"\0", 1)[0].decode("ascii", "replace")
        blobs.append(RealBlob(blob_id=tag, chunk_size=chunk_size))
    # RafsV6Blob records (256 B each) refine counts/sizes.
    n_blob_recs = blob_table_size // 256 if blob_table_size else 0
    for i in range(min(n_blob_recs, len(blobs))):
        off = blob_table_off + 256 * i
        if off + 112 > len(data):
            break
        bid = data[off : off + 64].split(b"\0", 1)[0].decode("ascii", "replace")
        _bidx, _csize_chunk, cc = struct.unpack_from("<III", data, off + 64)
        csize, usize = struct.unpack_from("<QQ", data, off + 88)
        if bid and bid != blobs[i].blob_id:
            raise RealBootstrapError("v6 blob table and device table disagree")
        blobs[i].chunk_count = cc
        blobs[i].compressed_size = csize
        blobs[i].uncompressed_size = usize
        if off + 256 <= len(data):
            blobs[i].raw_rec = data[off : off + 256]

    # Shared chunk table (80-B v5-layout records).
    chunks: list[RealChunk] = []
    by_uoff: dict[tuple[int, int], RealChunk] = {}
    for i in range(chunk_table_size // 80):
        (
            cdigest,
            blob_index,
            cflags,
            csize,
            usize,
            c_off,
            u_off,
            f_off,
            cindex,
            _crsvd,
        ) = _V5_CHUNK.unpack_from(data, chunk_table_off + 80 * i)
        ck = RealChunk(
            digest=cdigest,
            blob_index=blob_index,
            flags=cflags,
            compressed_size=csize,
            uncompressed_size=usize,
            compressed_offset=c_off,
            uncompressed_offset=u_off,
            file_offset=f_off,
            index=cindex,
        )
        chunks.append(ck)
        by_uoff[(blob_index, u_off)] = ck

    meta_base = meta_blkaddr * blksz

    def iloc(nid: int) -> int:
        return meta_base + 32 * nid

    def parse_inode(nid: int):
        off = iloc(nid)
        if off + 32 > len(data):
            raise RealBootstrapError(f"v6 inode nid {nid} out of range")
        fmt = struct.unpack_from("<H", data, off)[0]
        extended = fmt & 1
        data_layout = (fmt >> 1) & 0x7
        if extended:
            (
                _fmt,
                xattr_icount,
                mode,
                _rsv,
                size,
                u,
                ino,
                uid,
                gid,
                mtime,
                _mtime_ns,
                nlink,
                _rsv2a,
            ) = _EROFS_INODE_EXTENDED.unpack_from(data, off)
            isize = 64
        else:
            (
                _fmt,
                xattr_icount,
                mode,
                nlink,
                size,
                _rsv,
                u,
                ino,
                uid,
                gid,
                _rsv2,
            ) = _EROFS_INODE_COMPACT.unpack_from(data, off)
            mtime = 0
            isize = 32
        xattr_size = (xattr_icount - 1) * 4 + 12 if xattr_icount else 0
        return (
            data_layout,
            mode,
            size,
            u,
            ino,
            uid,
            gid,
            mtime,
            nlink,
            isize,
            xattr_size,
        )

    def parse_xattrs(nid: int, isize: int, xattr_size: int) -> dict:
        out: dict = {}
        if not xattr_size:
            return out
        base = iloc(nid) + isize
        _filter, shared_count = struct.unpack_from("<IB", data, base)
        pos = base + 12 + 4 * shared_count
        end = base + xattr_size
        while pos + 4 <= end:
            name_len, name_index, value_size = _EROFS_XATTR_ENTRY.unpack_from(
                data, pos
            )
            if name_len == 0 and value_size == 0:
                break
            nm = data[pos + 4 : pos + 4 + name_len].decode("utf-8", "surrogateescape")
            val = data[pos + 4 + name_len : pos + 4 + name_len + value_size]
            prefix = _EROFS_XATTR_PREFIXES.get(name_index, "")
            out[prefix + nm] = val
            pos += 4 + ((name_len + value_size + 3) & ~3)
        return out

    def data_region(nid, data_layout, size, u, isize, xattr_size):
        """Byte content of a FLAT_PLAIN / FLAT_INLINE inode."""
        if data_layout == _EROFS_LAYOUT_FLAT_INLINE:
            nblocks = size // blksz
            tail = size - nblocks * blksz
            parts = []
            if nblocks:
                parts.append(data[u * blksz : u * blksz + nblocks * blksz])
            if tail:
                base = iloc(nid) + isize + xattr_size
                parts.append(data[base : base + tail])
            return b"".join(parts)
        if data_layout == _EROFS_LAYOUT_FLAT_PLAIN:
            return data[u * blksz : u * blksz + size]
        raise RealBootstrapError(f"unhandled data layout {data_layout} for nid {nid}")

    def dirents(raw: bytes):
        # Each block is parsed independently (EROFS per-block dirents).
        for b0 in range(0, len(raw), blksz):
            blk = raw[b0 : b0 + blksz]
            if len(blk) < 12:
                continue
            first_nameoff = struct.unpack_from("<H", blk, 8)[0]
            count = first_nameoff // 12
            ents = [
                _EROFS_DIRENT.unpack_from(blk, 12 * i) for i in range(count)
            ]
            for i, (nid, nameoff, _ftype, _r) in enumerate(ents):
                name_end = ents[i + 1][1] if i + 1 < count else len(blk)
                name = blk[nameoff:name_end].split(b"\0", 1)[0].decode(
                    "utf-8", "surrogateescape"
                )
                yield nid, name

    inodes: list[RealInode] = []
    visited: set[int] = set()
    ino_of_nid: dict[int, int] = {}
    stack: list[tuple[int, str]] = [(root_nid, "/")]
    while stack:
        nid, path = stack.pop()
        (
            data_layout,
            mode,
            size,
            u,
            ino,
            uid,
            gid,
            mtime,
            nlink,
            isize,
            xattr_size,
        ) = parse_inode(nid)
        rdev = 0
        if stat.S_ISCHR(mode) or stat.S_ISBLK(mode):
            # i_u carries new_encode_dev(): minor low byte | major << 8
            # | high minor bits << 12
            rdev = os.makedev((u >> 8) & 0xFFF, (u & 0xFF) | ((u >> 12) & ~0xFF))
        inode = RealInode(
            path=path,
            ino=ino,
            mode=mode,
            uid=uid,
            gid=gid,
            mtime=mtime,
            size=size,
            nlink=nlink,
            rdev=rdev,
            xattrs=parse_xattrs(nid, isize, xattr_size),
        )
        inodes.append(inode)
        ino_of_nid.setdefault(nid, ino)
        if stat.S_ISDIR(mode):
            if nid in visited:
                continue
            visited.add(nid)
            for cnid, name in dirents(
                data_region(nid, data_layout, size, u, isize, xattr_size)
            ):
                if name in (".", ".."):
                    continue
                cpath = name if path == "/" else f"{path}/{name}"
                stack.append((cnid, "/" + cpath.lstrip("/")))
        elif stat.S_ISLNK(mode):
            inode.symlink_target = data_region(
                nid, data_layout, size, u, isize, xattr_size
            ).decode("utf-8", "surrogateescape")
        elif stat.S_ISREG(mode) and data_layout == _EROFS_LAYOUT_CHUNK_BASED:
            chunk_fmt = u & 0xFFFF
            cbits = blkszbits + (chunk_fmt & 0x1F)
            csz = 1 << cbits
            n_chunks = (size + csz - 1) // csz if size else 0
            idx_base = iloc(nid) + isize + xattr_size
            if idx_base + 8 * n_chunks > len(data):
                raise RealBootstrapError(
                    f"chunk indexes of {path!r} exceed bootstrap size"
                )
            for ci in range(n_chunks):
                advise, device_id, blkaddr = _EROFS_CHUNK_INDEX.unpack_from(
                    data, idx_base + 8 * ci
                )
                if blkaddr == 0xFFFFFFFF:
                    continue  # hole
                # EROFS device ids are 1-based for extra devices (0 is
                # the primary/meta device); nydus blob_index is 0-based.
                blob_index = device_id - 1 if device_id else 0
                ck = by_uoff.get((blob_index, blkaddr * blksz))
                if ck is None:
                    raise RealBootstrapError(
                        f"chunk index of {path!r} (dev {device_id}, "
                        f"blkaddr {blkaddr}) not in chunk table"
                    )
                inode.chunks.append(ck)

    if inos and len({i.ino for i in inodes}) > inos:
        raise RealBootstrapError("v6 walked more inodes than superblock count")
    return RealBootstrap(
        version=layout.RAFS_V6,
        flags=flags,
        inodes=inodes,
        blobs=blobs,
        chunks=chunks,
        # prefetch entries are nids on disk; surface them as the inode
        # numbers the rest of the model speaks (to_bootstrap resolves
        # them to paths exactly like the v5 table).
        prefetch_inos=[
            ino_of_nid[n] for n in prefetch_nids if n in ino_of_nid
        ],
    )


def to_bootstrap(real: RealBootstrap):
    """Bridge a REAL nydus bootstrap into the framework's internal model
    (models/bootstrap.Bootstrap) so every downstream surface — the
    userspace daemon, FUSE mounts, Unpack, EROFS export — can serve
    images the reference toolchain built, not only images this framework
    converted itself.

    Chunk compression flags translate from nydus BlobChunkFlags (bit0 =
    COMPRESSED) + the superblock codec identity into the framework's
    per-chunk compressor flags. Hardlink aliases (repeated ino) become
    hardlink_target references to the first path.
    """
    from nydus_snapshotter_tpu import constants
    from nydus_snapshotter_tpu.models.bootstrap import (
        INODE_FLAG_SYMLINK,
        Bootstrap,
        BlobRecord,
        ChunkRecord,
        Inode,
    )
    from nydus_snapshotter_tpu.models.bootstrap import INODE_FLAG_HARDLINK

    comp_flag = {
        "lz4_block": constants.COMPRESSOR_LZ4_BLOCK,
        "zstd": constants.COMPRESSOR_ZSTD,
        "gzip": constants.COMPRESSOR_GZIP,
        "none": constants.COMPRESSOR_NONE,
    }[real.compressor]

    chunks: list = []
    inodes: list = []
    first_path_of_ino: dict[int, str] = {}
    for ri in sorted(real.inodes, key=lambda i: i.path):
        ino = Inode(
            path=ri.path,
            mode=ri.mode,
            uid=ri.uid,
            gid=ri.gid,
            rdev=ri.rdev,
            mtime=ri.mtime,
            size=ri.size,
            symlink_target=ri.symlink_target,
            xattrs=dict(ri.xattrs),
        )
        if ri.is_symlink:
            ino.flags |= INODE_FLAG_SYMLINK
        if ri.is_regular:
            first = first_path_of_ino.get(ri.ino)
            if first is not None and ri.nlink > 1:
                ino.flags |= INODE_FLAG_HARDLINK
                ino.hardlink_target = first
                inodes.append(ino)
                continue
            first_path_of_ino[ri.ino] = ri.path
        if ri.chunks:
            ino.chunk_index = len(chunks)
            ino.chunk_count = len(ri.chunks)
            for ck in ri.chunks:
                chunks.append(
                    ChunkRecord(
                        digest=ck.digest,
                        blob_index=ck.blob_index,
                        flags=comp_flag
                        if ck.flags & 0x1
                        else constants.COMPRESSOR_NONE,
                        uncompressed_offset=ck.uncompressed_offset,
                        compressed_offset=ck.compressed_offset,
                        uncompressed_size=ck.uncompressed_size,
                        compressed_size=ck.compressed_size,
                    )
                )
        inodes.append(ino)

    blobs = [
        BlobRecord(
            blob_id=b.blob_id,
            compressed_size=b.compressed_size,
            uncompressed_size=b.uncompressed_size,
            chunk_count=b.chunk_count,
        )
        for b in real.blobs
    ]
    # v5 prefetch table: inode numbers -> paths (the runtime warm list).
    path_of_ino = {}
    for ri in real.inodes:
        path_of_ino.setdefault(ri.ino, ri.path)
    # "/" is a legitimate entry (prefetch-everything policy — and what the
    # committed v5 fixture actually carries); keep it.
    prefetch = [path_of_ino[pi] for pi in real.prefetch_inos if pi in path_of_ino]
    # Assign ino/parent_ino the way Bootstrap.to_bytes does (1-based, path
    # order): consumers of the *in-memory* bridge — the daemon's FUSE
    # layer keys nodes by ino — must see the same numbering a
    # serialize/parse round trip would produce, not zeros.
    ino_by_path = {inode.path: i + 1 for i, inode in enumerate(inodes)}
    for i, inode in enumerate(inodes):
        inode.ino = i + 1
        if inode.path == "/":
            inode.parent_ino = 0
        else:
            inode.parent_ino = ino_by_path.get(
                inode.path.rsplit("/", 1)[0] or "/", 0
            )
    return Bootstrap(
        version=real.version,
        chunk_size=real.blobs[0].chunk_size if real.blobs else 0x100000,
        inodes=inodes,
        chunks=chunks,
        blobs=blobs,
        prefetch=prefetch,
    )


def load_any_bootstrap(data: bytes):
    """Load a bootstrap in EITHER layout: this framework's native format,
    or the real nydus toolchain's v5/v6 (bridged via to_bootstrap). This
    is what lets the daemon mount — and the chunk dict dedup against —
    images the reference ecosystem built, with zero caller special-casing
    (the two formats share detection magics; the field layouts identify
    which reader owns the bytes)."""
    from nydus_snapshotter_tpu.models.bootstrap import Bootstrap, BootstrapError

    try:
        return Bootstrap.from_bytes(data)
    except (ValueError, struct.error, IndexError) as native_err:
        # BootstrapError and LayoutError are ValueError subclasses; bare
        # struct/index errors on truncated native headers must also fall
        # through to the real-format reader rather than escaping.
        try:
            return to_bootstrap(parse_real_bootstrap(data))
        except (RealBootstrapError, ValueError) as real_err:
            raise BootstrapError(
                f"not a native bootstrap ({native_err}) nor a real nydus "
                f"one ({real_err})"
            ) from native_err


def parse_real_bootstrap(data: bytes) -> RealBootstrap:
    """Dispatch on the reference detection contract
    (/root/reference/pkg/layout/layout.go:60-76)."""
    ver = layout.detect_fs_version(data)
    try:
        if ver == layout.RAFS_V5:
            return parse_real_v5(data)
        if ver == layout.RAFS_V6:
            return parse_real_v6(data)
    except RealBootstrapError:
        raise
    except (struct.error, IndexError, OverflowError, UnicodeDecodeError, MemoryError) as e:
        # Corrupt metadata must surface as the domain error, never a bare
        # struct/index crash (fuzz-pinned in test_reference_fixtures).
        raise RealBootstrapError(f"corrupt {ver} bootstrap: {e}") from e
    raise RealBootstrapError("not a RAFS bootstrap")
