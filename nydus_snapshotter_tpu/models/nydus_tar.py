"""Nydus "tar-like" blob framing.

A nydus blob is a tar-like stream where every 512-byte tar header *follows*
its data, with **no padding** between data and header: ``data | tar_header |
data | tar_header | [TOC]`` (reference pkg/converter/convert_unix.go:314-317).
Readers locate sections by walking headers backwards from the end — each
entry's data sits exactly ``hdr.size`` bytes before its header
(``seekFileByTarHeader``, convert_unix.go:162-218, ``cur - hdr.Size - 512``)
— or via the trailing TOC (``seekFileByTOC``, :220-284).

Headers are deterministic USTAR: zero mtime/uid/gid, fixed mode, no user
names — two packs of the same content are byte-identical.
"""

from __future__ import annotations

import io
import tarfile
from typing import BinaryIO, Iterator, Optional

from nydus_snapshotter_tpu.models.toc import (
    ENTRY_BLOB_TOC,
    TOC_ENTRY_SIZE,
    TOCEntry,
    unpack_toc,
)

TAR_BLOCK = 512


class TarFramingError(ValueError):
    pass


def make_header(name: str, size: int) -> bytes:
    info = tarfile.TarInfo(name=name)
    info.size = size
    info.mode = 0o444
    info.mtime = 0
    info.uid = 0
    info.gid = 0
    info.uname = ""
    info.gname = ""
    # USTAR caps size at 8 GiB - 1; larger sections use GNU base-256 size
    # encoding, which tar header parsers (incl. the reference's archive/tar)
    # accept.
    fmt = tarfile.USTAR_FORMAT if size < 8 * 1024**3 else tarfile.GNU_FORMAT
    buf = info.tobuf(format=fmt)
    if len(buf) != TAR_BLOCK:
        raise TarFramingError(f"entry {name!r} does not fit a single tar header block")
    return buf


def parse_header(buf: bytes) -> Optional[tarfile.TarInfo]:
    """Parse one 512-byte tar header; None if it isn't a valid header."""
    if len(buf) != TAR_BLOCK or buf.count(0) == TAR_BLOCK:
        return None
    try:
        return tarfile.TarInfo.frombuf(buf, encoding="utf-8", errors="surrogateescape")
    except tarfile.TarError:
        return None


def append_entry(out: BinaryIO, name: str, data: bytes) -> tuple[int, int]:
    """Append ``data | header`` (unpadded) to the stream; returns (data_offset, size)."""
    offset = out.tell()
    out.write(data)
    out.write(make_header(name, len(data)))
    return offset, len(data)


def iter_entries_backward(blob: BinaryIO, blob_size: int) -> Iterator[tuple[tarfile.TarInfo, int]]:
    """Yield (tarinfo, data_offset) for each entry, last entry first.

    Every 512-byte block reached by the walk must parse as a header — in a
    well-formed blob the walk lands exactly on offset 0. A block that fails
    to parse is corruption and raises, matching the reference's error
    propagation (convert_unix.go:181-185).
    """
    cursor = blob_size
    while cursor >= TAR_BLOCK:
        blob.seek(cursor - TAR_BLOCK)
        raw = blob.read(TAR_BLOCK)
        info = parse_header(raw)
        if info is None:
            raise TarFramingError(f"block ending at {cursor} is not a tar header")
        data_offset = cursor - TAR_BLOCK - info.size
        if data_offset < 0:
            raise TarFramingError(f"entry {info.name!r} overflows blob start")
        yield info, data_offset
        cursor = data_offset
    if cursor != 0:
        raise TarFramingError(f"{cursor} residual bytes before first entry")


def seek_file_by_tar_header(blob: BinaryIO, blob_size: int, name: str) -> Optional[tuple[int, int]]:
    """Find a section by scanning trailing tar headers; (offset, size) or None."""
    for info, data_offset in iter_entries_backward(blob, blob_size):
        if info.name == name:
            return data_offset, info.size
    return None


def read_toc(blob: BinaryIO, blob_size: int) -> Optional[list[TOCEntry]]:
    """Read the trailing TOC section if the blob carries one."""
    loc = seek_file_by_tar_header(blob, blob_size, ENTRY_BLOB_TOC)
    if loc is None:
        return None
    offset, size = loc
    if size % TOC_ENTRY_SIZE != 0:
        raise TarFramingError(f"TOC size {size} not a multiple of {TOC_ENTRY_SIZE}")
    blob.seek(offset)
    return unpack_toc(blob.read(size))


def seek_file_by_toc(blob: BinaryIO, blob_size: int, name: str) -> Optional[tuple[int, int]]:
    """Find a section via the TOC (TOC names are 16-byte-truncated)."""
    toc = read_toc(blob, blob_size)
    if toc is None:
        return None
    for entry in toc:
        if entry.name == name[:16]:
            return entry.compressed_offset, entry.compressed_size
    return None


def pack_entries(entries: list[tuple[str, bytes]]) -> bytes:
    """Convenience: frame a list of (name, data) sections into one blob."""
    out = io.BytesIO()
    for name, data in entries:
        append_entry(out, name, data)
    return out.getvalue()
