"""Blob-cache accounting and GC (reference pkg/cache)."""

from nydus_snapshotter_tpu.cache.manager import CacheManager

__all__ = ["CacheManager"]
