"""Blob-cache usage accounting + GC for the fusedev driver.

Reference pkg/cache/manager.go:33-122: blob caches live under one cache dir
as ``<blobID>`` plus suffixed companions (``.blob.data``, ``.chunk_map``,
``.blob.meta``, ``.image.disk``, ``.layer.disk``); usage is a du over the
matching files and removal deletes them all.

Beyond the reference's age-based removal, :meth:`CacheManager.gc_watermark`
bounds total cache *capacity*: whole entries (a blob plus companions) are
evicted least-recently-accessed-first until usage is back under a byte
watermark. Live ``CachedBlob`` instances survive eviction transparently —
they notice the dropped link and re-fetch (daemon/blobcache.py).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.snapshot.metastore import Usage

# Companion-file suffixes of one blob cache entry (manager.go:99-120,
# plus the seekable-OCI checkpoint indexes — soci/index.py's gzip zran
# index and soci/zindex.py's zstd frame index — and the provenance
# plane's .heat prefetch artifact (provenance/heat.py) — all of which
# must be accounted, GC'd and watermark-evicted with the blob they
# describe).
_SUFFIXES = ("", ".blob.data", ".chunk_map", ".blob.meta", ".image.disk",
             ".layer.disk", ".soci.idx", ".soci.zidx", ".heat")


class CacheManager:
    def __init__(self, cache_dir: str, period_sec: float = 0.0, enabled: bool = True):
        self.cache_dir = cache_dir
        self.enabled = enabled
        self._period = period_sec
        self._timer: Optional[threading.Timer] = None
        self._gc_stop: Optional[threading.Event] = None
        os.makedirs(cache_dir, exist_ok=True)

    def _entries(self, blob_id: str) -> list[str]:
        return [os.path.join(self.cache_dir, blob_id + sfx) for sfx in _SUFFIXES]

    def cache_usage(self, blob_id: str) -> Usage:
        usage = Usage()
        for path in self._entries(blob_id):
            try:
                st = os.lstat(path)
            except FileNotFoundError:
                continue
            usage.size += st.st_size
            usage.inodes += 1
        return usage

    def remove_blob_cache(self, blob_id: str) -> None:
        for path in self._entries(blob_id):
            try:
                os.remove(path)
            except FileNotFoundError:
                continue

    def total_usage(self) -> Usage:
        usage = Usage()
        try:
            names = os.listdir(self.cache_dir)
        except FileNotFoundError:
            return usage
        for name in names:
            try:
                st = os.lstat(os.path.join(self.cache_dir, name))
            except FileNotFoundError:
                continue
            usage.size += st.st_size
            usage.inodes += 1
        return usage

    # -- periodic GC of caches older than `max_age` --------------------------

    @staticmethod
    def _entry_id(name: str) -> str:
        """Blob id a cache file belongs to (strip the companion suffix)."""
        for sfx in _SUFFIXES:
            if sfx and name.endswith(sfx):
                return name[: -len(sfx)]
        return name

    def gc_once(self, max_age_sec: float) -> list[str]:
        """Remove whole cache *entries* (a blob plus all its companions, the
        same grouping remove_blob_cache uses) whose most recent access is
        older than max_age; returns removed paths."""
        removed: list[str] = []
        now = time.time()
        try:
            names = os.listdir(self.cache_dir)
        except FileNotFoundError:
            return removed
        newest_atime: dict[str, float] = {}
        members: dict[str, list[str]] = {}
        for name in names:
            path = os.path.join(self.cache_dir, name)
            try:
                st = os.lstat(path)
            except FileNotFoundError:
                continue
            bid = self._entry_id(name)
            members.setdefault(bid, []).append(path)
            newest_atime[bid] = max(newest_atime.get(bid, 0.0), st.st_atime)
        for bid, paths in members.items():
            if now - newest_atime[bid] <= max_age_sec:
                continue
            for path in paths:
                try:
                    os.remove(path)
                    removed.append(path)
                except OSError:
                    continue
        return removed

    # -- capacity-watermark eviction (LRU over whole entries) ----------------

    def _scan_entries(self) -> tuple[dict[str, list[str]], dict[str, float], dict[str, int]]:
        """(members, newest_atime, sizes) per blob id, one listdir pass."""
        members: dict[str, list[str]] = {}
        newest_atime: dict[str, float] = {}
        sizes: dict[str, int] = {}
        try:
            names = os.listdir(self.cache_dir)
        except FileNotFoundError:
            return members, newest_atime, sizes
        for name in names:
            path = os.path.join(self.cache_dir, name)
            try:
                st = os.lstat(path)
            except FileNotFoundError:
                continue
            bid = self._entry_id(name)
            members.setdefault(bid, []).append(path)
            newest_atime[bid] = max(newest_atime.get(bid, 0.0), st.st_atime)
            sizes[bid] = sizes.get(bid, 0) + st.st_size
        return members, newest_atime, sizes

    def gc_watermark(self, max_bytes: int, protect: Optional[set] = None) -> list[str]:
        """Evict whole cache entries, least-recently-accessed first, until
        total usage is <= ``max_bytes``; returns removed paths. ``protect``
        names blob ids that must never be evicted (e.g. currently
        mounting). Eviction under a live reader is safe: open fds keep the
        old bytes readable and the next read re-seeds the cache."""
        removed: list[str] = []
        if max_bytes <= 0:
            return removed
        members, newest_atime, sizes = self._scan_entries()
        total = sum(sizes.values())
        if total <= max_bytes:
            return removed
        from nydus_snapshotter_tpu.daemon import fetch_sched

        for bid in sorted(members, key=lambda b: newest_atime[b]):
            if total <= max_bytes:
                break
            if protect and bid in protect:
                continue
            failpoint.hit("blobcache.evict")
            entry_removed = 0
            for path in members[bid]:
                try:
                    st = os.lstat(path)
                    os.remove(path)
                except OSError:
                    continue
                entry_removed += st.st_size
                removed.append(path)
            if entry_removed:
                total -= entry_removed
                fetch_sched.EVICTED_BYTES.inc(entry_removed)
                fetch_sched.EVICTED_ENTRIES.inc()
        return removed

    def start_gc(self, max_age_sec: float, watermark_bytes: int = 0) -> None:
        if not self.enabled or self._period <= 0:
            return
        self.stop_gc()
        stop = threading.Event()
        self._gc_stop = stop

        def tick():
            if stop.is_set():
                return
            self.gc_once(max_age_sec)
            if watermark_bytes > 0:
                self.gc_watermark(watermark_bytes)
            if stop.is_set():
                return
            self._timer = threading.Timer(self._period, tick)
            self._timer.daemon = True
            self._timer.start()

        self._timer = threading.Timer(self._period, tick)
        self._timer.daemon = True
        self._timer.start()

    def stop_gc(self) -> None:
        if self._gc_stop is not None:
            self._gc_stop.set()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
