"""Driver-indexed filesystem facade.

Reference pkg/filesystem/fs.go:43-745: the layer between the snapshotter
and the daemon managers. Responsibilities reproduced here:

- ``mount``/``umount``/``wait_until_ready`` of RAFS instances: pick the
  manager by fs driver, shared vs dedicated daemon, supplement + persist
  the per-instance daemon config, ref-counted teardown (fs.go:268-500);
- startup recovery orchestration: reconnect live daemons, respawn dead
  ones and replay their mounts, retain/init the shared daemon
  (fs.go:58-194 ``NewFileSystem``);
- blob-cache usage/removal through the cache manager (fs.go:502-530);
- adaptor hooks for stargz / tarfs / referrer drivers — optional
  collaborators; each ``*_enabled()`` reflects whether one was wired in
  (stargz_adaptor.go / tarfs_adaptor.go / referer_adaptor.go).

The snapshotter only sees the duck type declared in
``snapshot.snapshotter.FilesystemLike``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
from typing import Optional

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu import trace
from nydus_snapshotter_tpu.cache.manager import CacheManager
from nydus_snapshotter_tpu.config.daemonconfig import DaemonRuntimeConfig
from nydus_snapshotter_tpu.daemon.daemon import SHARED_DAEMON_ID, Daemon
from nydus_snapshotter_tpu.daemon.types import DaemonState
from nydus_snapshotter_tpu.manager.manager import Manager
from nydus_snapshotter_tpu.rafs.rafs import Rafs, RafsCache
from nydus_snapshotter_tpu.snapshot import labels as label
from nydus_snapshotter_tpu.snapshot.metastore import Usage
from nydus_snapshotter_tpu.snapshot.mount import ExtraOption
from nydus_snapshotter_tpu.utils import errdefs

logger = logging.getLogger(__name__)


def _digest_hex(blob_digest: str) -> str:
    algo, _, hexpart = blob_digest.partition(":")
    if algo != "sha256" or len(hexpart) != 64:
        raise errdefs.InvalidArgument(f"invalid blob digest {blob_digest!r}")
    return hexpart


class Filesystem:
    def __init__(
        self,
        *,
        managers: dict[str, Manager],
        cache_mgr: CacheManager,
        root: str,
        fs_driver: str = C.DEFAULT_FS_DRIVER,
        daemon_mode: str = C.DEFAULT_DAEMON_MODE,
        daemon_config: Optional[DaemonRuntimeConfig] = None,
        verifier=None,
        stargz_resolver=None,
        stargz_adaptor=None,
        soci_resolver=None,
        soci_adaptor=None,
        tarfs_mgr=None,
        referrer_mgr=None,
        root_mountpoint: str = "",
        tarfs_export: bool = False,
        mirrors_config_dir: str = "",
    ):
        self.managers = managers
        self.cache_mgr = cache_mgr
        self.root = root
        self.fs_driver = fs_driver
        self.daemon_mode = daemon_mode
        self.daemon_config = daemon_config
        self.verifier = verifier
        self.stargz_resolver = stargz_resolver
        self.stargz_adaptor = stargz_adaptor
        self.soci_resolver = soci_resolver
        self.soci_adaptor = soci_adaptor
        self.tarfs_mgr = tarfs_mgr
        self.referrer_mgr = referrer_mgr
        self.root_mountpoint = root_mountpoint or os.path.join(root, "mnt")
        self._tarfs_export = tarfs_export
        self.mirrors_config_dir = mirrors_config_dir
        self.instances = RafsCache()
        self.shared_daemons: dict[str, Daemon] = {}  # fs_driver -> shared daemon
        self._lock = threading.RLock()  # shared-daemon create/stop only
        self._pending_mounts = 0  # in-flight mounts, guarded by _lock
        self._snap_locks: dict[str, list] = {}  # sid -> [lock, waiter count]
        self._snap_locks_mu = threading.Lock()

    @contextlib.contextmanager
    def _snapshot_lock(self, snapshot_id: str):
        """Per-snapshot lock: concurrent Prepare/Remove for ONE snapshot
        serialize, while mounts of unrelated snapshots proceed in parallel
        (a slow daemon spawn must not stall every other RPC). Entries are
        refcounted so an entry is only dropped when no thread holds or
        waits on it — a waiter must never be stranded on a popped lock."""
        with self._snap_locks_mu:
            entry = self._snap_locks.get(snapshot_id)
            if entry is None:
                entry = self._snap_locks[snapshot_id] = [threading.RLock(), 0]
            entry[1] += 1
        entry[0].acquire()
        try:
            yield
        finally:
            entry[0].release()
            with self._snap_locks_mu:
                entry[1] -= 1
                if entry[1] == 0 and self._snap_locks.get(snapshot_id) is entry:
                    self._snap_locks.pop(snapshot_id, None)

    # -- startup recovery (fs.go:58-194) -------------------------------------

    def startup(self) -> None:
        """Recover persisted daemons, replay their mounts, and ensure the
        shared daemon exists for shared-mode drivers."""
        for mgr in self.managers.values():
            live, dead = mgr.recover()
            failed: set[str] = set()
            for d in live + dead:
                if d.is_shared() or d.states.fs_driver == C.FS_DRIVER_FSCACHE:
                    self.shared_daemons.setdefault(d.states.fs_driver, d)
            for d in dead:
                try:
                    d.clear_vestige()
                    mgr.start_daemon(d)
                    self._replay_instances(mgr, d)
                except Exception:
                    logger.warning("failed to recover daemon %s, skipping", d.id)
                    # Don't leave a dead daemon registered as the shared one —
                    # that would wedge every shared-mode mount; let the
                    # fallback below spawn a fresh shared daemon instead.
                    if self.shared_daemons.get(d.states.fs_driver) is d:
                        self.shared_daemons.pop(d.states.fs_driver, None)
                    mgr.remove_daemon(d.id)
                    failed.add(d.id)
            for rafs_dict in self._walk_instances(mgr):
                rafs = Rafs.from_dict(rafs_dict)
                if rafs.daemon_id in failed:
                    # No daemon serves this snapshot anymore; drop the record
                    # so a later mount() re-creates it instead of silently
                    # handing out a mountpoint nothing backs.
                    mgr.db.delete_instance(rafs.snapshot_id)
                    continue
                self.instances.add(rafs)
        # fscache always runs through one shared daemon (fs.go:102-121); for
        # fusedev a shared daemon exists only in shared mode.
        if C.FS_DRIVER_FSCACHE in self.managers and C.FS_DRIVER_FSCACHE not in self.shared_daemons:
            self.init_shared_daemon(self.managers[C.FS_DRIVER_FSCACHE])
        if (
            self.daemon_mode == C.DAEMON_MODE_SHARED
            and self.fs_driver == C.FS_DRIVER_FUSEDEV
            and self.fs_driver in self.managers
            and self.fs_driver not in self.shared_daemons
        ):
            self.init_shared_daemon(self.managers[self.fs_driver])

    def _walk_instances(self, mgr: Manager):
        """Yield persisted instance dicts in seq (replay) order."""
        try:
            yield from (rec for rec, _seq in mgr.db.walk_instances())
        except Exception:
            return

    def _replay_instances(self, mgr: Manager, d: Daemon) -> None:
        instances = [
            Rafs.from_dict(rec)
            for rec in self._walk_instances(mgr)
            if rec.get("daemon_id") == d.id
        ]
        configs = {}
        for rafs in instances:
            cfg_path = self._instance_config_path(d, rafs.snapshot_id)
            if os.path.exists(cfg_path):
                with open(cfg_path) as f:
                    configs[rafs.snapshot_id] = f.read()
        d.recover_rafs_instances(instances, configs)

    def init_shared_daemon(self, mgr: Manager) -> Daemon:
        d = mgr.new_daemon(SHARED_DAEMON_ID, daemon_mode=C.DAEMON_MODE_SHARED)
        mgr.add_daemon(d)
        mgr.start_daemon(d)
        self.shared_daemons[mgr.fs_driver] = d
        return d

    def get_shared_daemon(self, fs_driver: str) -> Daemon:
        d = self.shared_daemons.get(fs_driver)
        if d is None:
            raise errdefs.NotFound(f"no shared daemon for driver {fs_driver}")
        return d

    def try_stop_shared_daemon(self) -> None:
        """Stop shared daemons not referenced by any snapshot
        (fs.go TryStopSharedDaemon)."""
        with self._lock:
            self._try_stop_shared_locked()

    def _try_stop_shared_locked(self) -> None:
        if self._pending_mounts > 0:
            return  # a mount may be about to attach to a shared daemon
        for fs_driver, d in list(self.shared_daemons.items()):
            if d.ref_count() == 0:
                mgr = self.managers.get(fs_driver)
                if mgr is not None:
                    mgr.destroy_daemon(d)
                self.shared_daemons.pop(fs_driver, None)

    # -- manager helpers ------------------------------------------------------

    def get_manager(self, fs_driver: str) -> Manager:
        mgr = self.managers.get(fs_driver)
        if mgr is None:
            raise errdefs.NotFound(f"no manager for filesystem driver {fs_driver!r}")
        return mgr

    def get_daemon_by_rafs(self, rafs: Rafs) -> Daemon:
        mgr = self.get_manager(rafs.fs_driver)
        d = mgr.get_by_daemon_id(rafs.daemon_id)
        if d is None:
            d = self.shared_daemons.get(rafs.fs_driver)
        if d is None:
            raise errdefs.NotFound(f"daemon {rafs.daemon_id} for snapshot {rafs.snapshot_id}")
        return d

    def get_daemon_by_id(self, daemon_id: str) -> Daemon:
        for mgr in self.managers.values():
            d = mgr.get_by_daemon_id(daemon_id)
            if d is not None:
                return d
        raise errdefs.NotFound(f"daemon {daemon_id}")

    # -- mount/umount (fs.go:268-500) ----------------------------------------

    def mount(self, snapshot_id: str, snap_labels: dict, snapshot=None) -> None:
        # Serialized per snapshot: concurrent Prepare RPCs for one snapshot
        # must not both pass the exists-check and race shared_mount/rollback.
        # The pending-mount count keeps try_stop_shared_daemon from tearing
        # the shared daemon down between get_shared_daemon and the refcount
        # attach inside shared_mount.
        with trace.span("daemon.mount", sid=snapshot_id):
            failpoint.hit("fs.mount")
            with self._lock:
                self._pending_mounts += 1
            try:
                with self._snapshot_lock(snapshot_id):
                    self._mount_locked(snapshot_id, snap_labels, snapshot)
            finally:
                with self._lock:
                    self._pending_mounts -= 1

    def _mount_locked(self, snapshot_id: str, snap_labels: dict, snapshot=None) -> None:
        if self.instances.get(snapshot_id) is not None:
            return  # instance already exists

        fs_driver = self.fs_driver
        if label.is_tarfs_data_layer(snap_labels):
            fs_driver = C.FS_DRIVER_BLOCKDEV

        shared_fusedev = (
            fs_driver == C.FS_DRIVER_FUSEDEV and self.daemon_mode == C.DAEMON_MODE_SHARED
        )
        use_shared = fs_driver == C.FS_DRIVER_FSCACHE or shared_fusedev

        image_id = snap_labels.get(C.CRI_IMAGE_REF) or snap_labels.get(
            "containerd.io/snapshot/remote/stargz.reference", ""
        )
        if not image_id and fs_driver not in (C.FS_DRIVER_NODEV, C.FS_DRIVER_PROXY):
            raise errdefs.InvalidArgument(
                f"failed to find image ref of snapshot {snapshot_id}, labels {snap_labels}"
            )

        snapshot_dir = os.path.join(self.root, "snapshots", snapshot_id)
        rafs = Rafs(
            snapshot_id=snapshot_id,
            image_id=image_id,
            fs_driver=fs_driver,
            snapshot_dir=snapshot_dir,
        )
        try:
            self._mount_rafs(rafs, fs_driver, use_shared, snap_labels, snapshot)
        except Exception:
            self.instances.remove(snapshot_id)
            # A dedicated daemon created for this mount must not leak: its
            # db record would be resurrected on every restart
            # (reference fs.go createDaemon defer DeleteDaemon).
            if rafs.daemon_id and rafs.daemon_id != SHARED_DAEMON_ID:
                mgr = self.managers.get(rafs.fs_driver)
                if mgr is not None:
                    orphan = mgr.get_by_daemon_id(rafs.daemon_id)
                    if orphan is not None:
                        # This mount's own instance may already be attached;
                        # detach it so the refcount reflects other users only.
                        orphan.remove_rafs_instance(snapshot_id)
                        if orphan.ref_count() == 0:
                            try:
                                mgr.destroy_daemon(orphan)
                            except Exception:
                                logger.exception(
                                    "failed to clean up daemon %s", rafs.daemon_id
                                )
            raise

    def _mount_rafs(self, rafs, fs_driver, use_shared, snap_labels, snapshot) -> None:
        mgr = self.get_manager(fs_driver) if fs_driver in self.managers else None

        if fs_driver in (C.FS_DRIVER_FSCACHE, C.FS_DRIVER_FUSEDEV):
            assert mgr is not None
            bootstrap = rafs.bootstrap_file()
            if use_shared:
                d = self.get_shared_daemon(fs_driver)
            else:
                d = mgr.new_daemon(f"nydusd-{rafs.snapshot_id}")
                try:
                    mgr.add_daemon(d)
                except errdefs.AlreadyExists:
                    d = mgr.get_by_daemon_id(d.id)
            # Record early so the mount() rollback can find (and destroy) a
            # dedicated daemon even when a later step here raises.
            rafs.daemon_id = d.id

            # Supplement + persist per-instance config for crash replay
            # (fs.go:340-370).
            config_json = "{}"
            if self.daemon_config is not None:
                cfg = DaemonRuntimeConfig.from_dict(
                    self.daemon_config.to_dict(), fs_driver
                )
                cfg.supplement(
                    image_ref=rafs.image_id,
                    auth=snap_labels.get(C.NYDUS_IMAGE_PULL_SECRET, ""),
                    work_dir=rafs.fscache_work_dir(),
                    mirrors_config_dir=self.mirrors_config_dir,
                )
                # Blob caches live in the cache manager's dir, so the daemon
                # knows where to find them (fs.go:335-338).
                if not cfg.backend.blob_dir:
                    cfg.backend.blob_dir = self.cache_mgr.cache_dir
                cfg_path = self._instance_config_path(d, rafs.snapshot_id)
                os.makedirs(os.path.dirname(cfg_path), exist_ok=True)
                cfg.dump(cfg_path)
                config_json = json.dumps(cfg.to_dict())

            if self.verifier is not None:
                self.verifier.verify(snap_labels, bootstrap)

            if use_shared:
                rafs.mountpoint = os.path.join(self.root_mountpoint, rafs.snapshot_id)
                if d.state() != DaemonState.RUNNING:
                    d.wait_until_state(DaemonState.RUNNING)
                d.shared_mount(rafs, bootstrap, config_json)
            else:
                rafs.mountpoint = os.path.join(rafs.snapshot_dir, "mnt")
                if d.state() == DaemonState.UNKNOWN:
                    mgr.start_daemon(d)
                # The dedicated daemon must actually SERVE its instance,
                # not just exist: attach via the mount API exactly like
                # the shared path (the reference's dedicated nydusd gets
                # its bootstrap on the command line; one API surface here
                # keeps supervisor state sync + failover replay uniform).
                d.shared_mount(rafs, bootstrap, config_json)
        elif fs_driver == C.FS_DRIVER_BLOCKDEV:
            if self.tarfs_mgr is None:
                raise errdefs.Unavailable("tarfs manager is not enabled")
            self.tarfs_mgr.mount_tar_erofs(rafs.snapshot_id, snapshot, snap_labels, rafs)
        elif fs_driver == C.FS_DRIVER_NODEV:
            pass
        elif fs_driver == C.FS_DRIVER_PROXY:
            if label.is_nydus_proxy_mode(snap_labels):
                if C.CRI_LAYER_DIGEST in snap_labels:
                    rafs.annotations[C.CRI_LAYER_DIGEST] = snap_labels[C.CRI_LAYER_DIGEST]
                rafs.annotations[C.NYDUS_PROXY_MODE] = "true"
                rafs.mountpoint = os.path.join(rafs.snapshot_dir, "fs")
        else:
            raise errdefs.InvalidArgument(f"unknown filesystem driver {fs_driver!r}")

        # Persist instance record with its replay sequence (rafs.go:112-117).
        self.instances.add(rafs)
        if mgr is not None:
            rafs.seq = mgr.db.next_instance_seq()
            mgr.db.save_instance(rafs.snapshot_id, rafs.to_dict(), rafs.seq)

    def umount(self, snapshot_id: str) -> None:
        failpoint.hit("fs.umount")
        with self._snapshot_lock(snapshot_id):
            self._umount_locked(snapshot_id)

    def _umount_locked(self, snapshot_id: str) -> None:
        rafs = self.instances.get(snapshot_id)
        if rafs is None:
            return
        fs_driver = rafs.fs_driver
        if fs_driver == C.FS_DRIVER_NODEV:
            self.instances.remove(snapshot_id)
            return
        if fs_driver in (C.FS_DRIVER_FSCACHE, C.FS_DRIVER_FUSEDEV):
            mgr = self.get_manager(fs_driver)
            d = self.get_daemon_by_rafs(rafs)
            try:
                d.shared_umount(rafs)
            except (OSError, errdefs.NydusError):
                d.remove_rafs_instance(snapshot_id)
            mgr.db.delete_instance(snapshot_id)
            if d.ref_count() == 0 and not d.is_shared():
                mgr.destroy_daemon(d)
        elif fs_driver == C.FS_DRIVER_BLOCKDEV:
            if self.tarfs_mgr is not None:
                # pass the persisted mountpoint: kernel mounts outlive the
                # process, the manager's in-memory status does not
                self.tarfs_mgr.umount_tar_erofs(snapshot_id, rafs.mountpoint)
            mgr = self.managers.get(fs_driver)
            if mgr is not None:
                mgr.db.delete_instance(snapshot_id)
        self.instances.remove(snapshot_id)

    def wait_until_ready(self, snapshot_id: str) -> None:
        with trace.span("daemon.wait_ready", sid=snapshot_id):
            rafs = self.instances.get(snapshot_id)
            if rafs is None:
                if self.daemon_mode == C.DAEMON_MODE_NONE:
                    return
                raise errdefs.NotFound(f"no instance {snapshot_id}")
            if rafs.fs_driver in (C.FS_DRIVER_FSCACHE, C.FS_DRIVER_FUSEDEV):
                # A daemon whose restart budget is exhausted never comes back:
                # serve the snapshot dirs as-is (nodev-style passthrough)
                # instead of blocking the mount path on a dead socket.
                mgr = self.managers.get(rafs.fs_driver)
                if mgr is not None and mgr.is_degraded(rafs.daemon_id):
                    return
                d = self.get_daemon_by_rafs(rafs)
                d.wait_until_state(DaemonState.RUNNING)

    def mount_point(self, snapshot_id: str) -> str:
        rafs = self.instances.get(snapshot_id)
        if rafs is None or not rafs.mountpoint:
            raise errdefs.NotFound(f"no mountpoint for snapshot {snapshot_id}")
        return rafs.mountpoint

    def bootstrap_file(self, snapshot_id: str) -> str:
        rafs = self.instances.get(snapshot_id)
        if rafs is None:
            raise errdefs.NotFound(f"no instance {snapshot_id}")
        return rafs.bootstrap_file()

    def _instance_config_path(self, d: Daemon, snapshot_id: str) -> str:
        return os.path.join(d.states.workdir, f"{snapshot_id}.json")

    def get_instance_annotations(self, snapshot_id: str) -> dict:
        """The mounted instance's annotations (tarfs block-info labels,
        proxy mode, …) — reference rafs.Annotations, consumed by the kata
        volume synthesis (mount_option.go:137-243)."""
        rafs = self.instances.get(snapshot_id)
        return dict(rafs.annotations) if rafs is not None else {}

    def tarfs_image_disk_path(self, blob_id: str) -> str:
        if self.tarfs_mgr is None:
            raise errdefs.Unavailable("tarfs support is not enabled")
        return self.tarfs_mgr.image_disk_file_path(blob_id)

    def tarfs_layer_disk_path(self, blob_id: str) -> str:
        if self.tarfs_mgr is None:
            raise errdefs.Unavailable("tarfs support is not enabled")
        return self.tarfs_mgr.layer_disk_file_path(blob_id)

    def get_instance_extra_option(self, snapshot_id: str) -> Optional[ExtraOption]:
        """Assemble the extraoption payload for the mount helper
        (mount_option.go:42-116)."""
        rafs = self.instances.get(snapshot_id)
        if rafs is None:
            return None
        config_content = "{}"
        try:
            d = self.get_daemon_by_rafs(rafs)
            cfg_path = self._instance_config_path(d, snapshot_id)
            if os.path.exists(cfg_path):
                with open(cfg_path) as f:
                    config_content = f.read()
        except errdefs.NotFound:
            pass
        fs_version = "6"
        bootstrap = rafs.bootstrap_file()
        if os.path.exists(bootstrap):
            from nydus_snapshotter_tpu.models import layout

            with open(bootstrap, "rb") as f:
                header = f.read(4096)
            try:
                fs_version = layout.detect_fs_version(header)
            except Exception:
                pass
        return ExtraOption(
            source=bootstrap,
            config=config_content,
            snapshotdir=rafs.snapshot_dir,
            fs_version=fs_version,
        )

    # -- blob cache (fs.go:502-530) ------------------------------------------

    def cache_usage(self, blob_digest: str) -> Usage:
        return self.cache_mgr.cache_usage(_digest_hex(blob_digest))

    def remove_cache(self, blob_digest: str) -> None:
        blob_id = _digest_hex(blob_digest)
        fscache = self.shared_daemons.get(C.FS_DRIVER_FSCACHE)
        if fscache is not None:
            # Unbind first so the daemon drops its handle, then reclaim the
            # on-disk cache files.
            fscache.client().unbind_blob("", blob_id)
        self.cache_mgr.remove_blob_cache(blob_id)

    # -- teardown ------------------------------------------------------------

    def teardown(self) -> None:
        # Stop the periodic cache GC first: an eviction tick racing the
        # umounts below would churn entries that are being torn down anyway.
        try:
            self.cache_mgr.stop_gc()
        except Exception:
            logger.exception("failed to stop cache GC during teardown")
        for rafs in self.instances.list():
            try:
                self.umount(rafs.snapshot_id)
            except Exception:
                logger.exception("failed to umount %s during teardown", rafs.snapshot_id)
        for mgr in self.managers.values():
            for d in mgr.list_daemons():
                try:
                    mgr.destroy_daemon(d)
                except Exception:
                    logger.exception("failed to destroy daemon %s", d.id)
        self.shared_daemons.clear()

    # -- adaptor surface (stargz / tarfs / referrer) -------------------------

    def stargz_enabled(self) -> bool:
        return self.stargz_resolver is not None

    def is_stargz_data_layer(self, snap_labels: dict):
        if not self.stargz_enabled():
            return False, None
        ref = snap_labels.get(C.CRI_IMAGE_REF, "")
        digest = snap_labels.get(C.CRI_LAYER_DIGEST, "")
        if not ref or not digest:
            return False, None
        try:
            blob = self.stargz_resolver.get_blob(ref, digest, snap_labels)
            return blob is not None, blob
        except Exception:
            return False, None

    def prepare_stargz_meta_layer(self, blob, storage_path: str, snap_labels: dict) -> None:
        if self.stargz_adaptor is None:
            raise errdefs.Unavailable("stargz support is not enabled")
        self.stargz_adaptor.prepare_meta_layer(blob, storage_path, snap_labels)

    def merge_stargz_meta_layer(self, snapshot) -> None:
        if self.stargz_adaptor is None:
            raise errdefs.Unavailable("stargz support is not enabled")
        self.stargz_adaptor.merge_meta_layer(snapshot)

    def soci_enabled(self) -> bool:
        return self.soci_resolver is not None

    def is_soci_data_layer(self, snap_labels: dict):
        """Whether this layer is claimable by the seekable-OCI backend:
        any plain gzip layer with image/digest labels qualifies — the
        whole point is that the image was never rewritten. Runs AFTER
        the nydus/stargz arms in the processor routing, so cooperative
        formats keep their richer paths."""
        if not self.soci_enabled():
            return False, None
        ref = snap_labels.get(C.CRI_IMAGE_REF, "")
        digest = snap_labels.get(C.CRI_LAYER_DIGEST, "")
        if not ref or not digest:
            return False, None
        try:
            blob = self.soci_resolver.get_blob(ref, digest, snap_labels)
            return blob is not None, blob
        except Exception:
            return False, None

    def prepare_soci_meta_layer(self, blob, storage_path: str, snap_labels: dict) -> None:
        if self.soci_adaptor is None:
            raise errdefs.Unavailable("soci support is not enabled")
        self.soci_adaptor.prepare_meta_layer(blob, storage_path, snap_labels)

    def merge_soci_meta_layer(self, snapshot) -> None:
        if self.soci_adaptor is None:
            raise errdefs.Unavailable("soci support is not enabled")
        self.soci_adaptor.merge_meta_layer(snapshot)

    def tarfs_enabled(self) -> bool:
        return self.tarfs_mgr is not None

    def tarfs_export_enabled(self) -> bool:
        return self.tarfs_mgr is not None and self._tarfs_export

    def prepare_tarfs_layer(self, snap_labels: dict, snapshot_id: str, upper_path: str) -> None:
        """Claim an OCI layer for tarfs (reference tarfs_adaptor.go:33-64):
        gate on the image's tarfs-hint annotation, kick the async blob
        process, and LABEL the snapshot as a tarfs data layer — the label
        is what routes the container-prepare to the tarfs merge/mount path
        (process.go writable-branch is_tarfs_data_layer check), so without
        it the whole tarfs runtime is unreachable from the snapshotter."""
        if self.tarfs_mgr is None:
            raise errdefs.Unavailable("tarfs support is not enabled")
        ref = snap_labels.get(C.CRI_IMAGE_REF, "")
        manifest_digest = snap_labels.get(C.CRI_MANIFEST_DIGEST, "")
        layer_digest = snap_labels.get(C.CRI_LAYER_DIGEST, "")
        # (missing ref/digest labels are rejected by prepare_layer below)
        if not self.tarfs_mgr.check_tarfs_hint_annotation(ref, manifest_digest):
            raise errdefs.InvalidArgument("this image is not recommended for tarfs")
        # concurrency is bounded inside the manager's async blob process
        # (per-ref semaphore + LRU, tarfs.py _blob_process)
        self.tarfs_mgr.prepare_layer(snap_labels, snapshot_id, upper_path)
        snap_labels[C.NYDUS_TARFS_LAYER] = layer_digest.split(":", 1)[-1]

    def merge_tarfs_layers(self, snapshot, path_fn) -> None:
        if self.tarfs_mgr is None:
            raise errdefs.Unavailable("tarfs support is not enabled")
        self.tarfs_mgr.merge_layers(snapshot, path_fn)

    def export_block_data(self, snapshot, per_layer: bool, snap_labels: dict, path_fn):
        if self.tarfs_mgr is None:
            raise errdefs.Unavailable("tarfs support is not enabled")
        return self.tarfs_mgr.export_block_data(snapshot, per_layer, snap_labels, path_fn)

    def detach_tarfs_layer(self, snapshot_id: str) -> None:
        if self.tarfs_mgr is None:
            raise errdefs.Unavailable("tarfs support is not enabled")
        self.tarfs_mgr.detach_layer(snapshot_id)

    def referrer_detect_enabled(self) -> bool:
        return self.referrer_mgr is not None

    def check_referrer(self, snap_labels: dict) -> bool:
        if self.referrer_mgr is None:
            return False
        ref = snap_labels.get(C.CRI_IMAGE_REF, "")
        manifest_digest = snap_labels.get(C.CRI_MANIFEST_DIGEST, "")
        if not ref or not manifest_digest:
            return False
        try:
            return self.referrer_mgr.check_referrer(ref, manifest_digest)
        except Exception:
            return False

    def try_fetch_metadata(self, snap_labels: dict, metadata_path: str) -> None:
        """Pull the companion image's bootstrap next to the snapshot
        (referer_adaptor.go:41-60)."""
        if self.referrer_mgr is None:
            raise errdefs.Unavailable("referrer detect is not enabled")
        ref = snap_labels.get(C.CRI_IMAGE_REF, "")
        manifest_digest = snap_labels.get(C.CRI_MANIFEST_DIGEST, "")
        if not ref or not manifest_digest:
            raise errdefs.InvalidArgument("missing image ref / manifest digest labels")
        self.referrer_mgr.try_fetch_metadata(ref, manifest_digest, metadata_path)
