"""L3 filesystem abstraction (reference pkg/filesystem)."""

from nydus_snapshotter_tpu.filesystem.fs import Filesystem

__all__ = ["Filesystem"]
