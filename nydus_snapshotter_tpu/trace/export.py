"""Span exporters: Chrome ``trace_event`` JSON, span-tree text rendering,
the slow-op flight recorder, and over-p95 trace exemplars.

``to_chrome_trace`` emits the Trace Event Format (complete ``"X"`` events
plus ``thread_name`` metadata) that chrome://tracing and Perfetto load
directly — the ``/api/v1/traces`` endpoints on the daemon and the system
controller serve exactly this document.

The :class:`SlowOpRecorder` is the flight recorder: when a ROOT span ends
over the configured threshold, the full span tree of that trace is
reconstructed from the ring buffer and logged in one message, so the
latency breakdown of a slow Prepare/Mounts/read is in the log exactly
when it happened, without anyone having scraped the endpoint in time.

The :class:`ExemplarStore` links metrics to traces: it keeps the last N
root trace ids whose duration exceeded the rolling p95 of recent roots —
the ``trace_exemplars`` field on the metrics summaries.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque

logger = logging.getLogger(__name__)

# Core args every exported event carries besides the user attrs.
_ID_KEYS = ("trace_id", "span_id", "parent_id")


def _fmt_id(v) -> str:
    """Ids are 64-bit ints internally; export them as hex strings so JSON
    consumers (Perfetto's JS heritage caps exact ints at 2^53) keep them
    exact. The empty string stands for "no parent"."""
    if isinstance(v, int):
        return format(v, "x") if v else ""
    return str(v)


def to_chrome_trace(spans) -> dict:
    """Chrome/Perfetto ``trace_event`` document for a span list."""
    pid = os.getpid()
    tids: dict[str, int] = {}
    events = []
    for sp in spans:
        tid = tids.setdefault(sp.thread, len(tids) + 1)
        args = {
            "trace_id": _fmt_id(sp.trace_id),
            "span_id": _fmt_id(sp.span_id),
            "parent_id": _fmt_id(sp.parent_id),
        }
        args.update(sp.attrs)
        events.append(
            {
                "name": sp.name,
                "cat": sp.name.split(".", 1)[0],
                "ph": "X",
                "ts": round(sp.start * 1e6, 3),  # microseconds
                "dur": round(sp.duration_ms * 1000.0, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    events.sort(key=lambda e: e["ts"])
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": name},
        }
        for name, tid in tids.items()
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def format_tree(spans, trace_id: str) -> str:
    """Indented text rendering of one trace's span tree. Spans whose
    parent has not landed in the ring (still running, or already evicted)
    are listed under a ``(detached)`` marker rather than silently lost."""
    mine = [s for s in spans if s.trace_id == trace_id]
    by_id = {s.span_id: s for s in mine}
    children: dict[str, list] = {}
    roots, detached = [], []
    for s in mine:
        if not s.parent_id:
            roots.append(s)
        elif s.parent_id in by_id:
            children.setdefault(s.parent_id, []).append(s)
        else:
            detached.append(s)
    lines: list[str] = []

    def fmt(s) -> str:
        extra = ""
        if "failpoints" in s.attrs:
            extra += f" failpoints={','.join(s.attrs['failpoints'])}"
        if "error" in s.attrs:
            extra += f" error={s.attrs['error']!r}"
        if s.attrs.get("background"):
            extra += " background"
        return f"{s.name} {s.duration_ms:.2f}ms [{_fmt_id(s.span_id)}]{extra}"

    def walk(s, depth: int) -> None:
        lines.append("  " * depth + fmt(s))
        for c in sorted(children.get(s.span_id, ()), key=lambda x: x.start):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x.start):
        walk(r, 0)
    if detached:
        lines.append("(detached)")
        for s in sorted(detached, key=lambda x: x.start):
            walk(s, 1)
    return "\n".join(lines)


class SlowOpRecorder:
    """Logs the reconstructed span tree of any root op over threshold."""

    def __init__(self, threshold_ms: float, keep: int = 32):
        self.threshold_ms = float(threshold_ms)
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=keep)

    def record(self, root, ring) -> None:
        tree = format_tree(ring.snapshot(), root.trace_id)
        logger.warning(
            "slow op %s took %.1fms (threshold %.0fms), trace %s:\n%s",
            root.name,
            root.duration_ms,
            self.threshold_ms,
            root.trace_id,
            tree,
        )
        with self._lock:
            self._records.append(
                {
                    "trace_id": _fmt_id(root.trace_id),
                    "op": root.name,
                    "duration_ms": round(root.duration_ms, 3),
                    "tree": tree,
                }
            )

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)


class ExemplarStore:
    """Rolling p95 of root durations + the last N roots that exceeded it.

    ``record`` is on the hot path (every root span ends here), so it is a
    single bounded-deque append — GIL-atomic, no lock, no sort. The p95
    and the over-p95 filter are computed lazily in :meth:`exemplars`,
    which only runs when a metrics summary is actually scraped.

    ``min_window`` roots must have been seen before anything qualifies —
    with no history every op "exceeds p95" and the exemplars are noise.
    """

    def __init__(self, window: int = 256, keep: int = 16, min_window: int = 20):
        self._keep = keep
        self._min_window = min_window
        # (duration_ms, trace_id, name) of recent roots, oldest first.
        self._roots: deque = deque(maxlen=window)

    def record(self, root) -> None:
        self._roots.append((root.duration_ms, root.trace_id, root.name))

    def exemplars(self, limit: int = 16) -> list[dict]:
        """Most recent over-p95 roots, newest first."""
        recent = list(self._roots)
        n = len(recent)
        if n < self._min_window:
            return []
        durations = sorted(d for d, _, _ in recent)
        p95 = durations[min(n - 1, int(n * 0.95))]
        out = [
            {"trace_id": _fmt_id(tid), "op": name, "duration_ms": round(d, 3)}
            for d, tid, name in reversed(recent)
            if d > p95
        ]
        return out[: min(limit, self._keep)]
