"""End-to-end request tracing: propagated spans across snapshot → daemon
→ fetch.

The ``ntpu_*`` counters/histograms can say THAT a p99 regressed; this
module says WHERE for any single request. A *span* is one timed operation
(``span("snapshot.prepare", key=...)``); spans form a tree through a
trace id + parent id carried in a :mod:`contextvars` context variable,
and — because contextvars do not cross thread-pool boundaries — carried
EXPLICITLY over every pool this codebase owns:

- ``snapshot/async_work.py``: ``PrepareBoard`` background prepares, the
  ``UsageAccountant`` scan workers and the cleanup fan-out all capture
  the submitting context, so a deferred ``wait_until_ready`` or usage
  scan is attributed to the Prepare/Commit that spawned it;
- ``parallel/pipeline.py``: stage workers adopt the converting caller's
  context (one span per worker, not per chunk — tracing must not tax the
  hot loop);
- ``daemon/fetch_sched.py``: every :class:`Flight` records the context
  that planned it, so a *background readahead* fetch shows up in the
  trace of the demand read that triggered it.

Finished spans land in a bounded lock-striped ring (:mod:`.ring`,
drop-oldest, drops exported as ``ntpu_trace_dropped_spans_total``) and
are exported three ways (:mod:`.export`): Chrome ``trace_event`` JSON on
``/api/v1/traces`` (daemon + system controller), a slow-op flight
recorder that logs the full reconstructed tree of any root op over
``slow_op_threshold_ms``, and over-p95 ``trace_exemplars`` on the metrics
summaries.

Zero-overhead contract (gated by ``tools/trace_profile.py``): with
tracing disabled, :func:`span` is one global load, one branch and a
no-op context manager — no ids, no clock reads, no allocation beyond the
kwargs dict. Sampling is decided once at the ROOT span (``sample_ratio``)
and inherited by the whole tree, so a sampled-out request costs the same
as a disabled tracer. Configuration: ``[trace]`` section
(config/config.py) overridden by ``NTPU_TRACE*`` environment variables —
the env is also how the section reaches spawned daemon processes.
"""

from __future__ import annotations

import functools
import itertools
import json
import os
import random
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass
from time import perf_counter
from typing import Iterator, Optional

from nydus_snapshotter_tpu.metrics import registry as _metrics
from nydus_snapshotter_tpu.trace.export import (
    ExemplarStore,
    SlowOpRecorder,
    _fmt_id,
    format_tree,
    to_chrome_trace,
)
from nydus_snapshotter_tpu.trace.ring import SPANS_DROPPED, LazyCounter, SpanRing

__all__ = [
    "Span",
    "SpanContext",
    "TraceRuntimeConfig",
    "annotate",
    "annotate_failpoint",
    "capture",
    "chrome_trace",
    "chrome_trace_bytes",
    "configure",
    "dropped",
    "dump_text",
    "enabled",
    "exemplars",
    "remote_context",
    "reset",
    "resolve_trace_config",
    "slow_ops",
    "snapshot_spans",
    "span",
    "start_span",
    "traced",
    "with_context",
]

DEFAULT_RING_CAPACITY = 8192
DEFAULT_SLOW_OP_MS = 1000.0

_reg = _metrics.default_registry
# Lazy: synced from the ring's per-stripe totals at scrape time, so the
# span hot path never touches a registry metric lock (see ring.LazyCounter).
SPANS_TOTAL = _reg.register(
    LazyCounter(
        "ntpu_trace_spans_total", "Spans recorded into the trace ring buffer"
    )
)
SLOW_OPS = _reg.register(
    _metrics.Counter(
        "ntpu_trace_slow_ops_total",
        "Root operations whose duration exceeded the slow-op threshold",
    )
)

_rng = random.random  # patchable for deterministic sampling tests


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclass
class TraceRuntimeConfig:
    """Resolved ``[trace]`` section (env > config > defaults)."""

    enabled: bool = True
    ring_capacity: int = DEFAULT_RING_CAPACITY
    slow_op_threshold_ms: float = DEFAULT_SLOW_OP_MS
    sample_ratio: float = 1.0


def _env_num(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, ""))
        return v if v >= 0 else default
    except ValueError:
        return default


def _global_trace_config():
    """The snapshotter's ``[trace]`` section when a global config is set;
    None in library / test / daemon-process use."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        return _cfg.get_global_config().trace
    except Exception:
        return None


def resolve_trace_config() -> TraceRuntimeConfig:
    """Resolve the tracing knobs: ``NTPU_TRACE*`` env > ``[trace]`` config
    > defaults."""
    tc = _global_trace_config()
    env = os.environ.get("NTPU_TRACE", "")
    if env:
        enabled_ = env not in ("0", "off", "false")
    else:
        got = getattr(tc, "enabled", None)
        enabled_ = True if got is None else bool(got)
    ring = int(_env_num("NTPU_TRACE_RING_CAPACITY", -1))
    if ring < 0:
        ring = getattr(tc, "ring_capacity", None) or DEFAULT_RING_CAPACITY
    slow = _env_num("NTPU_TRACE_SLOW_OP_MS", -1)
    if slow < 0:
        got = getattr(tc, "slow_op_threshold_ms", None)
        slow = DEFAULT_SLOW_OP_MS if got is None else float(got)
    sample = _env_num("NTPU_TRACE_SAMPLE_RATIO", -1)
    if sample < 0:
        got = getattr(tc, "sample_ratio", None)
        sample = 1.0 if got is None else float(got)
    return TraceRuntimeConfig(
        enabled=enabled_,
        ring_capacity=max(1, ring),
        slow_op_threshold_ms=max(0.0, slow),
        sample_ratio=min(1.0, max(0.0, sample)),
    )


# ---------------------------------------------------------------------------
# Span model + context
# ---------------------------------------------------------------------------


class Span:
    """One timed operation. To keep the per-span cost at ONE allocation,
    the span is simultaneously the record that lands in the ring, its own
    context manager, and the context value propagated to children (ids are
    read off it directly; ``span``/``sampled`` keep the
    :class:`SpanContext` reading surface).

    Ids are ints — ``(pid | boot-time) << 32 | counter`` — formatted to
    strings only at the export boundary (Chrome args, exemplars), where a
    raw 64-bit int would lose precision in JavaScript JSON consumers."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "start",
        "duration_ms",
        "attrs",
        "thread",
        "_tracer",
        "_t0",
        "_token",
    )

    sampled = True  # a live span in the context ⇒ the trace is sampled

    def __init__(self, tracer: "Tracer", name: str, trace_id: int, span_id: int, parent_id: int, attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = 0.0  # epoch seconds
        self.duration_ms = 0.0
        self.attrs = attrs
        self.thread = ""
        self._tracer = tracer

    @property
    def span(self) -> "Span":
        return self

    def __enter__(self) -> "Span":
        self.thread = _thread_name()
        self._t0 = t0 = perf_counter()
        self.start = _EPOCH_OFFSET + t0
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.duration_ms = (perf_counter() - self._t0) * 1000.0
        if exc is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        _current.reset(self._token)
        self._token = None
        self._tracer._record(self)
        return False

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def end(self, error: Optional[BaseException] = None) -> None:
        self.__exit__(type(error) if error is not None else None, error, None)


class SpanContext:
    """The unsampled sentinel's shape; live contexts are the spans
    themselves (same reading surface: ids + ``sampled`` + ``span``)."""

    __slots__ = ("trace_id", "span_id", "sampled", "span")

    def __init__(self, trace_id: int, span_id: int, sampled: bool, span: Optional[Span]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.span = span


_current: ContextVar[object] = ContextVar("ntpu_trace_ctx", default=None)
_UNSAMPLED_CTX = SpanContext(0, 0, False, None)

# Span start epochs are derived from perf_counter via this offset: one
# monotonic clock read per span edge instead of time()+perf_counter().
_EPOCH_OFFSET = time.time() - perf_counter()

_tls = threading.local()


def _thread_name() -> str:
    # threading.current_thread() costs a dict lookup + object walk per
    # call; spans on one thread all share a name, so cache it.
    try:
        return _tls.name
    except AttributeError:
        name = _tls.name = threading.current_thread().name
        return name


class _NoopSpan:
    """The disabled/unsampled-child path: one shared, stateless object."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def annotate(self, **attrs) -> None:
        pass

    def end(self, error: Optional[BaseException] = None) -> None:
        pass


_NOOP = _NoopSpan()


class _UnsampledRoot:
    """A sampled-out root still pins the unsampled decision into the
    context so the whole tree skips tracing with one roll."""

    __slots__ = ("_token",)

    def __enter__(self) -> "_UnsampledRoot":
        self._token = _current.set(_UNSAMPLED_CTX)
        return self

    def __exit__(self, *exc) -> bool:
        _current.reset(self._token)
        return False

    def annotate(self, **attrs) -> None:
        pass

    def end(self, error: Optional[BaseException] = None) -> None:
        self.__exit__(None, None, None)


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


class Tracer:
    def __init__(self, cfg: TraceRuntimeConfig):
        self.cfg = cfg
        self.ring = SpanRing(cfg.ring_capacity)
        self.recorder = SlowOpRecorder(cfg.slow_op_threshold_ms)
        self.exemplar_store = ExemplarStore()
        self._sample = cfg.sample_ratio
        # itertools.count.__next__ is atomic under the GIL — id generation
        # takes no lock on the span hot path.
        self._ids = itertools.count(1).__next__
        self._id_base = ((os.getpid() & 0xFFFF) << 48) | (
            (int(time.time()) & 0xFFFF) << 32
        )

    def _next_id(self) -> int:
        return self._id_base | self._ids()

    def span(self, name: str, attrs: dict):
        ctx = _current.get()
        if ctx is not None:
            if not ctx.sampled:
                return _NOOP
            return Span(
                self, name, ctx.trace_id, self._next_id(), ctx.span_id, attrs
            )
        # Root span: the one place the sampling decision is made.
        if self._sample < 1.0 and _rng() >= self._sample:
            return _UnsampledRoot()
        tid = self._next_id()
        return Span(self, name, tid, tid, 0, attrs)

    def _record(self, sp: Span) -> None:
        self.ring.push(sp)
        if not sp.parent_id:
            self.exemplar_store.record(sp)
            if 0 < self.cfg.slow_op_threshold_ms <= sp.duration_ms:
                SLOW_OPS.inc()
                self.recorder.record(sp, self.ring)


# ---------------------------------------------------------------------------
# Module-level API (the instrumentation surface)
# ---------------------------------------------------------------------------

_tracer: Optional[Tracer] = None
_initialized = False
_init_lock = threading.Lock()
# Totals from replaced tracers (configure()/reset() in tests and tools):
# the exported counters stay monotonic across tracer swaps.
_spans_base = 0
_drops_base = 0

SPANS_TOTAL.bind(lambda: _spans_base + (_tracer.ring.pushes() if _tracer else 0))
SPANS_DROPPED.bind(lambda: _drops_base + (_tracer.ring.dropped() if _tracer else 0))


def _retire_tracer_locked() -> None:
    """Fold the outgoing tracer's ring totals into the monotonic bases.
    Caller holds ``_init_lock``."""
    global _spans_base, _drops_base
    if _tracer is not None:
        _spans_base += _tracer.ring.pushes()
        _drops_base += _tracer.ring.dropped()


def _init() -> Optional[Tracer]:
    global _tracer, _initialized
    with _init_lock:
        if not _initialized:
            cfg = resolve_trace_config()
            _tracer = Tracer(cfg) if cfg.enabled else None
            _initialized = True
        return _tracer


def span(name: str, /, **attrs):
    """Open a span named ``name``; use as a context manager. The single
    branch on ``_tracer`` IS the disabled path. ``name`` is positional-only
    so ``name=...`` stays usable as a span attribute."""
    t = _tracer
    if t is None:
        if _initialized:
            return _NOOP
        t = _init()
        if t is None:
            return _NOOP
    return t.span(name, attrs)


def start_span(name: str, /, **attrs):
    """Imperative begin/``end()`` form of :func:`span` for call sites
    where a ``with`` block does not fit. ``end(error=...)`` closes it."""
    s = span(name, **attrs)
    s.__enter__()
    return s


def traced(name: str):
    """Decorator form of :func:`span` around a whole function/method."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def capture() -> Optional[SpanContext]:
    """The current span context, for explicit carry across a thread-pool
    boundary (pair with :func:`with_context` on the worker)."""
    return _current.get()


@contextmanager
def with_context(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Adopt a captured context on a worker thread. ``None`` (captured
    with tracing disabled, or outside any span) is a no-op."""
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


def remote_context(trace_id: int, span_id: int) -> Optional[SpanContext]:
    """Reconstruct a propagated context from wire-carried ids (the dict
    service's RPC headers): spans opened under ``with_context(...)`` on
    the serving side join the caller's trace across the socket boundary,
    so one ``convert``-rooted tree spans the service RPC. Zero/absent ids
    (caller untraced) yield None, which :func:`with_context` no-ops."""
    if not trace_id or not span_id:
        return None
    return SpanContext(int(trace_id), int(span_id), True, None)


def annotate(**attrs) -> None:
    """Attach attributes to the innermost active span, if any."""
    ctx = _current.get()
    if ctx is not None and ctx.span is not None:
        ctx.span.attrs.update(attrs)


def annotate_failpoint(site: str) -> None:
    """Mark the current span as having had a failpoint fire inside it —
    called by :mod:`nydus_snapshotter_tpu.failpoint` so chaos runs are
    traceable back to the injected fault."""
    ctx = _current.get()
    if ctx is not None and ctx.span is not None:
        ctx.span.attrs.setdefault("failpoints", []).append(site)


def configure(
    enabled: bool = True,
    ring_capacity: int = DEFAULT_RING_CAPACITY,
    slow_op_threshold_ms: float = DEFAULT_SLOW_OP_MS,
    sample_ratio: float = 1.0,
) -> Optional[Tracer]:
    """Install a tracer explicitly (tests, tools); bypasses env/config."""
    global _tracer, _initialized
    cfg = TraceRuntimeConfig(
        enabled=enabled,
        ring_capacity=max(1, ring_capacity),
        slow_op_threshold_ms=max(0.0, slow_op_threshold_ms),
        sample_ratio=min(1.0, max(0.0, sample_ratio)),
    )
    with _init_lock:
        _retire_tracer_locked()
        _tracer = Tracer(cfg) if enabled else None
        _initialized = True
        return _tracer


def reset() -> None:
    """Back to lazy env/config resolution on next use (tests)."""
    global _tracer, _initialized
    with _init_lock:
        _retire_tracer_locked()
        _tracer = None
        _initialized = False


def enabled() -> bool:
    t = _tracer if _initialized else _init()
    return t is not None


def snapshot_spans() -> list:
    t = _tracer
    return t.ring.snapshot() if t is not None else []


def dropped() -> int:
    t = _tracer
    return t.ring.dropped() if t is not None else 0


def exemplars(limit: int = 16) -> list[dict]:
    """Last N root trace ids whose duration exceeded the rolling p95 —
    the ``trace_exemplars`` field on the metrics summaries."""
    t = _tracer
    return t.exemplar_store.exemplars(limit) if t is not None else []


def slow_ops() -> list[dict]:
    """Roots the slow-op flight recorder fired for (newest last)."""
    t = _tracer
    return t.recorder.records() if t is not None else []


def chrome_trace() -> dict:
    """The ring as a Chrome/Perfetto ``trace_event`` document."""
    return to_chrome_trace(snapshot_spans())


def chrome_trace_bytes() -> bytes:
    return json.dumps(chrome_trace()).encode()


def dump_text() -> str:
    """Human-readable ring dump (``/debug/pprof/trace``)."""
    spans = snapshot_spans()
    head = [
        f"# spans={len(spans)} dropped={dropped()} "
        f"enabled={_tracer is not None}"
    ]
    seen: set = set()
    for sp in spans:
        if sp.trace_id not in seen:
            seen.add(sp.trace_id)
            head.append(f"trace {_fmt_id(sp.trace_id)}:")
            head.append(format_tree(spans, sp.trace_id))
    return "\n".join(head) + "\n"
