"""Cross-process trace aggregation: one cluster-wide Chrome trace.

Every process already exports its own span ring as a Chrome
``trace_event`` document on ``/api/v1/traces``, and trace ids already
cross process boundaries (the ``x-ntpu-trace-*`` headers on dict-service
and peer-tier RPCs). What was missing is the JOIN: a storm-rooted
``grpc.Prepare`` or ``nydusd.read`` whose children ran in another
process (a peer owner's pull-through, a dict-service merge) could only
be inspected one ring at a time.

:class:`FleetTraceCollector` pulls each registered member's ring,
rewrites the event lanes so every member gets its own process row
(members on one host share real pids with nothing to disambiguate them;
the synthetic lane pid keeps Perfetto's process grouping meaningful and
``process_name`` metadata carries the member name, component and real
pid), and merges the documents into ONE trace — spans from different
OS processes that share a trace id line up on the same timeline because
every ring stamps wall-clock epoch microseconds.

Per-member isolation mirrors the metrics federation: a member that dies
mid-pull is skipped and counted (``ntpu_fleet_scrape_errors_total``),
the merged document still serves. The ``fleet.collect`` failpoint
injects exactly that failure in chaos tests.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Iterable, Optional

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu import trace as trace_mod
from nydus_snapshotter_tpu.metrics import federation as _fed
from nydus_snapshotter_tpu.utils import udshttp

logger = logging.getLogger(__name__)

TRACES_PATH = "/api/v1/traces"


def merge_member_traces(docs: list[tuple[object, dict]]) -> dict:
    """[(member, chrome doc)] -> one merged chrome doc.

    Lane assignment is deterministic in member-name order so repeated
    pulls render identically. Each member's (pid, tid) pairs are remapped
    into its lane; ``thread_name`` metadata rides along, ``process_name``
    metadata is synthesized per member.
    """
    events = []
    meta = []
    for lane, (member, doc) in enumerate(
        sorted(docs, key=lambda md: md[0].name), start=1
    ):
        real_pids = set()
        for ev in doc.get("traceEvents", ()):
            ev = dict(ev)
            real_pids.add(ev.get("pid"))
            ev["pid"] = lane
            if ev.get("ph") == "M":
                meta.append(ev)
            else:
                ev.setdefault("args", {})
                ev["args"] = dict(ev["args"], node=member.name)
                events.append(ev)
        real = next(iter(real_pids), "?")
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": lane,
                "args": {
                    "name": f"{member.name} ({member.component}, pid {real})"
                },
            }
        )
    events.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def filter_trace(doc: dict, trace_id: str) -> dict:
    """The merged doc narrowed to one trace id (metadata rows kept for
    the lanes that still have events)."""
    events = [
        e
        for e in doc.get("traceEvents", ())
        if e.get("ph") != "M" and e.get("args", {}).get("trace_id") == trace_id
    ]
    pids = {e["pid"] for e in events}
    meta = [
        e
        for e in doc.get("traceEvents", ())
        if e.get("ph") == "M" and e.get("pid") in pids
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def trace_trees(doc: dict) -> dict[str, dict]:
    """{trace_id: {roots, spans, processes, single_tree}} over a merged
    doc — the cross-process join check the storm profile gates on."""
    by_trace: dict[str, list[dict]] = {}
    for e in doc.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    out = {}
    for tid, events in by_trace.items():
        ids = {e["args"].get("span_id") for e in events}
        roots = [e for e in events if not e["args"].get("parent_id")]
        # Single tree: every non-root's parent landed in the merged doc.
        joined = all(
            not e["args"].get("parent_id") or e["args"]["parent_id"] in ids
            for e in events
        )
        out[tid] = {
            "roots": [e["name"] for e in roots],
            "spans": len(events),
            "processes": len({e["pid"] for e in events}),
            "single_tree": bool(roots) and joined,
        }
    return out


class FleetTraceCollector:
    """Pulls every member's ring and serves the merged document.

    ``members`` is the same duck-typed listing callable the metrics
    federator takes; the local member's ring is read in-process (no
    self-HTTP hop through our own serve loop).
    """

    def __init__(
        self,
        members: Callable[[], Iterable],
        timeout_s: float = 5.0,
        local_traces: Optional[Callable[[], dict]] = None,
    ):
        self._members = members
        self.timeout_s = timeout_s
        self._local_traces = local_traces or trace_mod.chrome_trace

    def _pull(self, member) -> dict:
        failpoint.hit("fleet.collect")
        if member.local:
            return self._local_traces()
        status, body = udshttp.request(
            member.address, TRACES_PATH, timeout=self.timeout_s
        )
        if status != 200:
            raise OSError(f"{member.address} {TRACES_PATH} -> {status}")
        return json.loads(body)

    def collect(self, trace_id: str = "") -> dict:
        """The merged fleet trace (optionally narrowed to one trace id).
        Pull failures degrade: the member is counted and skipped."""
        t0 = time.perf_counter()
        docs = []
        errors = 0
        for member in self._members():
            try:
                docs.append((member, self._pull(member)))
            except Exception as e:  # noqa: BLE001 — degradation is the contract
                errors += 1
                _fed.FLEET_SCRAPE_ERRORS.labels(member.name).inc()
                logger.warning("fleet trace pull of %s failed: %s", member.name, e)
        doc = merge_member_traces(docs)
        if trace_id:
            doc = filter_trace(doc, trace_id)
        doc["fleet"] = {
            "members": len(docs),
            "errors": errors,
            "collect_ms": round((time.perf_counter() - t0) * 1000.0, 3),
        }
        return doc
