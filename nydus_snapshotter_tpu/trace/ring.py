"""Bounded lock-striped span ring buffer.

Finished spans land here (drop-oldest past capacity) and are read back by
the exporters: the ``/api/v1/traces`` Chrome-trace endpoint, the slow-op
flight recorder's tree reconstruction, and ``/debug/pprof/trace``. The
striping keeps concurrent writers (gRPC handlers, prepare-board workers,
fetch flights) off one hot lock: each writer thread hashes to a stripe
with its own lock and deque, and only readers touch every stripe.

Accounting invariant (pinned by tests/test_trace.py): for any interleaving
of pushes, ``len(ring) + ring.dropped() == total pushes`` — drop-oldest
never loses the count, and the drop total is exported as
``ntpu_trace_dropped_spans_total``.
"""

from __future__ import annotations

import threading
from collections import deque

from nydus_snapshotter_tpu.metrics import registry as _metrics


class LazyCounter(_metrics.Counter):
    """Counter whose value is pulled from a callback at read/render time.

    The span hot path must not take the registry metric lock per span;
    the ring keeps exact per-stripe totals under the stripe locks it
    already holds, and this counter folds them into the exposition only
    when someone actually looks (scrape, ``.value()``).
    """

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_)
        self._fn = None

    def bind(self, fn) -> None:
        self._fn = fn

    def _sync(self) -> None:
        if self._fn is not None:
            total = float(self._fn())
            with self._lock:
                self._values[()] = total

    def value(self, *values: str) -> float:
        self._sync()
        return super().value(*values)

    def render(self) -> str:
        self._sync()
        return super().render()


SPANS_DROPPED = _metrics.default_registry.register(
    LazyCounter(
        "ntpu_trace_dropped_spans_total",
        "Spans evicted oldest-first from the bounded trace ring buffer",
    )
)

DEFAULT_STRIPES = 8


class _Stripe:
    # Deliberately NOT instrumented by analysis.runtime (NTPU_ANALYZE):
    # the stripe lock is taken once per recorded span — the hottest lock
    # in the process — and per-acquire detector bookkeeping inside a
    # kernel-FUSE daemon's serve loop measurably destabilizes real-mount
    # timing (the takeover-storm suite wedges its 5s reader alarms).
    # The ring's concurrency invariant (len + dropped == pushes under
    # any interleaving) is pinned directly by tests/test_trace.py.
    __slots__ = ("lock", "items", "cap", "drops", "pushes")

    def __init__(self, cap: int):
        self.lock = threading.Lock()
        self.items: deque = deque()
        self.cap = cap
        self.drops = 0
        self.pushes = 0


class SpanRing:
    """Drop-oldest span store bounded at ``capacity`` spans total."""

    def __init__(self, capacity: int, stripes: int = DEFAULT_STRIPES):
        capacity = max(1, int(capacity))
        stripes = max(1, min(stripes, capacity))
        base, extra = divmod(capacity, stripes)
        # Stripe capacities sum exactly to `capacity`.
        self._stripes = [
            _Stripe(base + (1 if i < extra else 0)) for i in range(stripes)
        ]
        self.capacity = capacity

    def push(self, span) -> None:
        st = self._stripes[threading.get_ident() % len(self._stripes)]
        with st.lock:
            st.pushes += 1
            if len(st.items) >= st.cap:
                st.items.popleft()
                st.drops += 1
            st.items.append(span)

    def snapshot(self) -> list:
        """All buffered spans, oldest start first."""
        out = []
        for st in self._stripes:
            with st.lock:
                out.extend(st.items)
        out.sort(key=lambda s: s.start)
        return out

    def dropped(self) -> int:
        return sum(st.drops for st in self._stripes)

    def pushes(self) -> int:
        return sum(st.pushes for st in self._stripes)

    def clear(self) -> None:
        for st in self._stripes:
            with st.lock:
                st.items.clear()

    def __len__(self) -> int:
        return sum(len(st.items) for st in self._stripes)
