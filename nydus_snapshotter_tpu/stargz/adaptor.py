"""Filesystem-side stargz driver: TOC → bootstrap build + layer merge.

Reference pkg/filesystem/stargz_adaptor.go:

- ``prepare_meta_layer`` (:165-260): persist the TOC as
  ``stargz.index.json``, then convert it to a per-layer bootstrap named by
  the layer digest hex. The reference shells out to ``nydus-image create
  --source-type stargz_index``; here the bootstrap is emitted in-process
  via :mod:`nydus_snapshotter_tpu.stargz.index`.
- ``merge_meta_layer`` (:73-160): collect each parent layer's bootstrap
  (the file named by a bare sha256 hex) bottom-up and merge them into
  ``image.boot`` in the topmost parent's upper dir, copying sibling
  ``*.blob.meta`` files next to it for the daemon's benefit.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
from typing import Callable, Mapping, Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.converter.convert import Merge
from nydus_snapshotter_tpu.converter.types import MergeOption
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap
from nydus_snapshotter_tpu.stargz import index as stargz_index
from nydus_snapshotter_tpu.stargz.resolver import TOC_FILENAME, Blob
from nydus_snapshotter_tpu.utils import errdefs

_HEX_DIGEST = re.compile(r"^[0-9a-f]{64}$")

MERGED_BOOTSTRAP = "image.boot"


class StargzAdaptor:
    def __init__(
        self,
        upper_path_fn: Callable[[str], str],
        cache_dir: str = "",
        fs_driver: str = constants.FS_DRIVER_FUSEDEV,
        chunk_size: int = stargz_index.DEFAULT_CHUNK_SIZE,
    ):
        self.upper_path = upper_path_fn
        self.cache_dir = cache_dir
        self.fs_driver = fs_driver
        self.chunk_size = chunk_size

    # -- prepare -------------------------------------------------------------

    def prepare_meta_layer(
        self, blob: Blob, storage_path: str, _labels: Optional[Mapping[str, str]] = None
    ) -> None:
        blob_id = blob.get_digest().split(":", 1)[-1]
        os.makedirs(storage_path, exist_ok=True)
        converted = os.path.join(storage_path, blob_id)
        if os.path.exists(converted):
            return

        toc_json = blob.read_toc()
        toc_path = os.path.join(storage_path, TOC_FILENAME)
        with open(toc_path, "wb") as f:
            f.write(toc_json)
        os.chmod(toc_path, 0o440)

        import json

        bootstrap = stargz_index.bootstrap_from_toc(
            json.loads(toc_json),
            blob_id,
            chunk_size=self.chunk_size,
            blob_compressed_size=blob.size,
        )

        # blob.meta sits in the shared cache dir for fusedev, but fscache's
        # cache dir is kernel-managed so it stays beside the bootstrap
        # (stargz_adaptor.go:207-216).
        meta_dir = (
            storage_path
            if self.fs_driver == constants.FS_DRIVER_FSCACHE or not self.cache_dir
            else self.cache_dir
        )
        os.makedirs(meta_dir, exist_ok=True)
        meta_path = os.path.join(meta_dir, f"{blob_id}.blob.meta")
        with open(meta_path, "wb") as f:
            for chunk in bootstrap.chunks:
                f.write(chunk.pack())

        fd, tmp = tempfile.mkstemp(prefix="converting-stargz", dir=storage_path)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(bootstrap.to_bytes())
            os.rename(tmp, converted)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        os.chmod(converted, 0o440)

    # -- merge ---------------------------------------------------------------

    def merge_meta_layer(self, snapshot) -> None:
        if not snapshot.parent_ids:
            raise errdefs.InvalidArgument("stargz merge needs parent layers")
        merged_dir = self.upper_path(snapshot.parent_ids[0])
        merged_bootstrap = os.path.join(merged_dir, MERGED_BOOTSTRAP)
        if os.path.exists(merged_bootstrap):
            return

        bootstraps: list[str] = []
        for idx, snapshot_id in enumerate(snapshot.parent_ids):
            upper = self.upper_path(snapshot_id)
            bootstrap_name = ""
            blob_meta_name = ""
            for name in sorted(os.listdir(upper)):
                if _HEX_DIGEST.match(name):
                    bootstrap_name = name
                if name.endswith("blob.meta"):
                    blob_meta_name = name
            if not bootstrap_name:
                raise errdefs.NotFound(
                    f"can't find bootstrap for snapshot {snapshot_id}"
                )
            if blob_meta_name and idx != 0:
                shutil.copy2(
                    os.path.join(upper, blob_meta_name),
                    os.path.join(merged_dir, blob_meta_name),
                )
            # parent_ids is topmost-first: prepend for lowest-first order.
            bootstraps.insert(0, os.path.join(upper, bootstrap_name))

        if len(bootstraps) == 1:
            shutil.copy2(bootstraps[0], merged_bootstrap)
        else:
            layers = []
            for path in bootstraps:
                with open(path, "rb") as f:
                    layers.append(Bootstrap.from_bytes(f.read()))
            result = Merge(layers, MergeOption())
            fd, tmp = tempfile.mkstemp(prefix="merging-stargz", dir=merged_dir)
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(result.bootstrap)
                os.rename(tmp, merged_bootstrap)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        os.chmod(merged_bootstrap, 0o440)
