"""eStargz lazy-pull support: footer detection, ranged TOC reads.

Reference pkg/stargz/resolver.go: detect an estargz blob purely from its
trailing gzip footer (no annotation exists), then fetch the TOC tar member
``stargz.index.json`` with HTTP Range reads over the pooled, token-refreshing
registry transport (resolver.go:110-131, :133-150, :153-216).

Both footer generations are understood:

- legacy stargz, 47 bytes (resolver.go:133-150 / FooterSize :33): gzip
  member whose EXTRA field is exactly ``"%016x" % toc_offset + "STARGZ"``;
- estargz, 51 bytes: same payload wrapped in an RFC-1952 subfield with
  ID ``SG``.
"""

from __future__ import annotations

import io
import json
import struct
import tarfile
import zlib
from typing import Callable, Mapping, Optional

from nydus_snapshotter_tpu.auth import keychain as authmod
from nydus_snapshotter_tpu.remote import transport
from nydus_snapshotter_tpu.remote.reference import parse_docker_ref
from nydus_snapshotter_tpu.utils import errdefs

FOOTER_SIZE = 47  # legacy stargz
ESTARGZ_FOOTER_SIZE = 51  # estargz (subfield-framed extra)
TOC_FILENAME = "stargz.index.json"

_STARGZ_MAGIC = b"STARGZ"


class StargzError(errdefs.NydusError):
    pass


def _gzip_extra(p: bytes) -> Optional[bytes]:
    """Raw EXTRA field of the gzip member starting at ``p``, else None."""
    if len(p) < 12 or p[0] != 0x1F or p[1] != 0x8B or p[2] != 0x08:
        return None
    if not p[3] & 0x04:  # FEXTRA
        return None
    (xlen,) = struct.unpack_from("<H", p, 10)
    if 12 + xlen > len(p):
        return None
    return p[12 : 12 + xlen]


def parse_footer(p: bytes) -> tuple[int, bool]:
    """(toc_offset, ok) from a trailing footer blob (resolver.go:133-150)."""
    extra = _gzip_extra(p)
    if extra is None:
        return 0, False
    payload: bytes
    if len(extra) == 16 + len(_STARGZ_MAGIC):
        payload = extra  # legacy: bare "%016xSTARGZ"
    elif (
        len(extra) == 4 + 16 + len(_STARGZ_MAGIC)
        and extra[:2] == b"SG"
        and struct.unpack_from("<H", extra, 2)[0] == 16 + len(_STARGZ_MAGIC)
    ):
        payload = extra[4:]  # estargz: SG subfield
    else:
        return 0, False
    if payload[16:] != _STARGZ_MAGIC:
        return 0, False
    try:
        return int(payload[:16].decode(), 16), True
    except ValueError:
        return 0, False


class Blob:
    """A lazily-ranged estargz blob (resolver.go Blob :48-108)."""

    def __init__(
        self,
        ref: str,
        digest: str,
        read_at: Callable[[int, int], bytes],
        size: int,
    ):
        self.ref = ref
        self.digest = digest
        self._read_at = read_at
        self.size = size
        self._footer: Optional[tuple[int, int]] = None  # (footer_size, toc_offset)

    def get_image_reference(self) -> str:
        return self.ref

    def get_digest(self) -> str:
        return self.digest

    def read_at(self, offset: int, length: int) -> bytes:
        return self._read_at(offset, length)

    def _parse_trailer(self) -> tuple[int, int]:
        """One ranged read of the blob tail resolves both footer size
        (51-byte estargz first, legacy 47 fallback) and TOC offset."""
        if self._footer is not None:
            return self._footer
        want = min(self.size, ESTARGZ_FOOTER_SIZE)
        tail = self._read_at(self.size - want, want)
        for fsize in (ESTARGZ_FOOTER_SIZE, FOOTER_SIZE):
            if fsize > len(tail):
                continue
            off, ok = parse_footer(tail[len(tail) - fsize :])
            if ok:
                if off <= 0:
                    raise StargzError(f"invalid stargz toc offset in {self.digest}")
                self._footer = (fsize, off)
                return self._footer
        raise StargzError(f"blob {self.digest} carries no stargz footer")

    def footer_size(self) -> int:
        return self._parse_trailer()[0]

    def get_toc_offset(self) -> int:
        return self._parse_trailer()[1]

    def read_toc(self) -> bytes:
        """TOC JSON bytes (resolver.go ReadToc :65-100): range-read
        [toc_offset, size - footer), gunzip the first member only, and pull
        ``stargz.index.json`` out of the inner tar."""
        fsize, toc_offset = self._parse_trailer()
        raw = self._read_at(toc_offset, self.size - toc_offset - fsize)
        try:
            # Multistream(false): decode exactly one gzip member.
            plain = zlib.decompressobj(wbits=31).decompress(raw)
        except zlib.error as e:
            raise StargzError(f"corrupt TOC stream in {self.digest}: {e}") from e
        tf = tarfile.open(fileobj=io.BytesIO(plain), mode="r:")
        member = tf.next()
        if member is None or member.name != TOC_FILENAME:
            raise StargzError(
                f"failed to find toc from image {self.ref} blob {self.digest}"
            )
        reader = tf.extractfile(member)
        assert reader is not None
        return reader.read()

    def toc(self) -> dict:
        return json.loads(self.read_toc())


class Resolver:
    """Ranged-blob resolver over the shared transport pool
    (resolver.go:37-46, :153-216)."""

    def __init__(self, pool: Optional[transport.Pool] = None):
        self.pool = pool or transport.Pool()

    def get_blob(
        self, ref: str, digest: str, labels: Optional[Mapping[str, str]] = None
    ) -> Blob:
        parsed = parse_docker_ref(ref)
        kc = authmod.get_keychain_by_ref(ref, dict(labels or {}))
        _, client = self.pool.resolve(parsed, digest, keychain=kc)
        repo = parsed.path

        size = _blob_size(client, repo, digest)

        def read_at(offset: int, length: int) -> bytes:
            if length <= 0:
                return b""
            r = client.fetch_blob(repo, digest, byte_range=(offset, offset + length - 1))
            try:
                return r.read()
            finally:
                r.close()

        blob = Blob(ref, digest, read_at, size)
        # Footer check is the stargz detection itself (fs.go
        # IsStargzDataLayer): a plain OCI layer must fail here, cheaply,
        # not later in the prepare path.
        blob._parse_trailer()
        return blob


def _blob_size(client, repo: str, digest: str) -> int:
    """Total size via a 0-0 range probe's Content-Range (resolver.go
    getSize :206-230)."""
    r = client.fetch_blob(repo, digest, byte_range=(0, 0))
    try:
        content_range = r.headers.get("content-range") or r.headers.get(
            "Content-Range", ""
        )
    finally:
        r.close()
    if "/" not in content_range:
        raise StargzError(f"no Content-Range for blob {digest}")
    return int(content_range.rsplit("/", 1)[1])
