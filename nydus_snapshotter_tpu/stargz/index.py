"""eStargz TOC → RAFS bootstrap (the ``stargz_index`` build source).

Replaces the reference's shell-out to ``nydus-image create --source-type
stargz_index`` (pkg/filesystem/stargz_adaptor.go:227-245): the TOC already
carries per-chunk sha256 digests and compressed offsets, so the bootstrap is
emitted directly from the parsed TOC through the same ``models.bootstrap``
writer the TPU converter uses — the image blob stays the original estargz
blob, read lazily by range.

TOC shape (stargz-snapshotter estargz jtoc): ``{"version": 1, "entries":
[{name, type, size, mode, uid, gid, linkName, offset, chunkOffset,
chunkSize, chunkDigest, devMajor, devMinor, xattrs, ...}]}`` where a regular
file's extra chunks appear as subsequent ``type=="chunk"`` entries.
"""

from __future__ import annotations

import base64
import os
import stat as statmod
from dataclasses import dataclass, field
from typing import Optional

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.models import layout
from nydus_snapshotter_tpu.models.bootstrap import (
    INODE_FLAG_HARDLINK,
    INODE_FLAG_SYMLINK,
    BlobRecord,
    Bootstrap,
    ChunkRecord,
    Inode,
)
from nydus_snapshotter_tpu.utils import errdefs

DEFAULT_CHUNK_SIZE = 0x400000  # stargz_adaptor.go:237 --chunk-size


class TocError(errdefs.NydusError):
    pass


@dataclass
class TocEntry:
    name: str
    type: str
    size: int = 0
    mode: int = 0
    uid: int = 0
    gid: int = 0
    link_name: str = ""
    offset: int = 0  # compressed offset of this entry's stream in the blob
    chunk_offset: int = 0
    chunk_size: int = 0
    chunk_digest: str = ""
    digest: str = ""
    dev_major: int = 0
    dev_minor: int = 0
    xattrs: dict[str, bytes] = field(default_factory=dict)

    @classmethod
    def from_json(cls, obj: dict) -> "TocEntry":
        xattrs = {
            k: base64.b64decode(v) for k, v in (obj.get("xattrs") or {}).items()
        }
        return cls(
            name=obj.get("name", ""),
            type=obj.get("type", ""),
            size=int(obj.get("size", 0)),
            mode=int(obj.get("mode", 0)),
            uid=int(obj.get("uid", 0)),
            gid=int(obj.get("gid", 0)),
            link_name=obj.get("linkName", ""),
            offset=int(obj.get("offset", 0)),
            chunk_offset=int(obj.get("chunkOffset", 0)),
            chunk_size=int(obj.get("chunkSize", 0)),
            chunk_digest=obj.get("chunkDigest", ""),
            digest=obj.get("digest", ""),
            dev_major=int(obj.get("devMajor", 0)),
            dev_minor=int(obj.get("devMinor", 0)),
            xattrs=xattrs,
        )


def parse_toc(toc: dict) -> list[TocEntry]:
    if toc.get("version") != 1:
        raise TocError(f"unsupported stargz TOC version {toc.get('version')!r}")
    return [TocEntry.from_json(e) for e in toc.get("entries", [])]


_TYPE_BITS = {
    "dir": statmod.S_IFDIR,
    "reg": statmod.S_IFREG,
    "symlink": statmod.S_IFLNK,
    "hardlink": statmod.S_IFREG,
    "char": statmod.S_IFCHR,
    "block": statmod.S_IFBLK,
    "fifo": statmod.S_IFIFO,
}


# Go os.FileMode keeps setuid/setgid/sticky out of the low 9 permission
# bits (ModeSetuid = 1<<23, ModeSetgid = 1<<22, ModeSticky = 1<<20); the
# stargz TOC stores that representation, so translate back to Unix bits.
_GO_MODE_SETUID = 1 << 23
_GO_MODE_SETGID = 1 << 22
_GO_MODE_STICKY = 1 << 20


def _unix_perm(go_mode: int) -> int:
    perm = go_mode & 0o777
    if go_mode & _GO_MODE_SETUID:
        perm |= statmod.S_ISUID
    if go_mode & _GO_MODE_SETGID:
        perm |= statmod.S_ISGID
    if go_mode & _GO_MODE_STICKY:
        perm |= statmod.S_ISVTX
    return perm


def _norm(name: str) -> str:
    p = "/" + name.strip("/")
    return "/" if p == "/" else p


def _raw_digest(d: str) -> bytes:
    if not d.startswith("sha256:"):
        raise TocError(f"chunk digest {d!r} is not sha256")
    raw = bytes.fromhex(d[len("sha256:") :])
    if len(raw) != 32:
        raise TocError(f"bad sha256 length in {d!r}")
    return raw


def bootstrap_from_toc(
    toc: dict,
    blob_id: str,
    chunk_size: int = DEFAULT_CHUNK_SIZE,
    blob_compressed_size: int = 0,
    fs_version: str = layout.RAFS_V6,
    compressor: int = constants.COMPRESSOR_GZIP,
) -> Bootstrap:
    """Build the layer bootstrap pointing chunks at the estargz blob itself.

    ``blob_compressed_size`` (total blob size when known) bounds the last
    chunk's compressed extent; per-chunk compressed sizes are derived from
    consecutive TOC stream offsets. ``compressor`` is the per-chunk codec
    flag: gzip members for eStargz, or COMPRESSOR_ZSTD for zstd:chunked
    TOCs whose chunks are independent zstd frames at the same offsets —
    the TOC shape is identical, only the decode arm differs.
    """
    entries = parse_toc(toc)

    inodes: dict[str, Inode] = {
        "/": Inode(path="/", mode=statmod.S_IFDIR | 0o755)
    }
    chunks: list[ChunkRecord] = []
    # (chunk list index, stream offset) pairs for compressed-size fixup.
    offsets: list[tuple[int, int]] = []
    uncompressed_pos = 0

    def ensure_dir(path: str) -> None:
        if path in inodes:
            return
        parent = path.rsplit("/", 1)[0] or "/"
        if parent != path:
            ensure_dir(parent)
        inodes[path] = Inode(path=path, mode=statmod.S_IFDIR | 0o755)

    for e in entries:
        path = _norm(e.name)
        parent = path.rsplit("/", 1)[0] or "/"
        ensure_dir(parent)

        if e.type == "chunk":
            node = inodes.get(path)
            if node is None or not statmod.S_ISREG(node.mode):
                raise TocError(f"chunk entry for unknown regular file {path}")
            csize = e.chunk_size or (node.size - e.chunk_offset)
            offsets.append((len(chunks), e.offset))
            chunks.append(
                ChunkRecord(
                    digest=_raw_digest(e.chunk_digest),
                    flags=compressor,
                    uncompressed_offset=uncompressed_pos,
                    compressed_offset=e.offset,
                    uncompressed_size=csize,
                )
            )
            node.chunk_count += 1
            uncompressed_pos += csize
            continue

        bits = _TYPE_BITS.get(e.type)
        if bits is None:
            raise TocError(f"unknown TOC entry type {e.type!r} for {path}")
        mode = bits | _unix_perm(e.mode)
        inode = Inode(
            path=path,
            mode=mode,
            uid=e.uid,
            gid=e.gid,
            mtime=0,
            size=e.size,
            xattrs=e.xattrs,
        )
        if e.type == "symlink":
            inode.flags |= INODE_FLAG_SYMLINK
            inode.symlink_target = e.link_name
            inode.size = len(e.link_name)
        elif e.type == "hardlink":
            inode.flags |= INODE_FLAG_HARDLINK
            inode.hardlink_target = _norm(e.link_name)
        elif e.type in ("char", "block"):
            inode.rdev = os.makedev(e.dev_major, e.dev_minor)
        elif e.type == "reg" and e.size > 0:
            csize = e.chunk_size or e.size
            # Legacy (pre-estargz) TOCs carry no per-chunk digests, only the
            # whole-file digest; when the file is a single chunk the two are
            # the same object, so the file digest IS the chunk digest.
            digest_src = e.chunk_digest
            if not digest_src and csize >= e.size:
                digest_src = e.digest
            inode.chunk_index = len(chunks)
            inode.chunk_count = 1
            offsets.append((len(chunks), e.offset))
            chunks.append(
                ChunkRecord(
                    digest=_raw_digest(digest_src),
                    flags=compressor,
                    uncompressed_offset=uncompressed_pos,
                    compressed_offset=e.offset,
                    uncompressed_size=csize,
                )
            )
            uncompressed_pos += csize
        inodes[path] = inode

    # Derive compressed sizes from consecutive stream offsets; the final
    # chunk is bounded by the blob size (TOC region excluded upstream).
    by_offset = sorted(offsets, key=lambda t: t[1])
    for i, (ci, off) in enumerate(by_offset):
        if i + 1 < len(by_offset):
            chunks[ci].compressed_size = by_offset[i + 1][1] - off
        elif blob_compressed_size:
            chunks[ci].compressed_size = max(0, blob_compressed_size - off)

    blob = BlobRecord(
        blob_id=blob_id,
        compressed_size=blob_compressed_size,
        uncompressed_size=uncompressed_pos,
        chunk_count=len(chunks),
        flags=compressor,
    )
    ordered = sorted(inodes.values(), key=lambda i: i.path)
    return Bootstrap(
        version=fs_version,
        chunk_size=chunk_size,
        inodes=ordered,
        chunks=chunks,
        blobs=[blob],
    )
