"""eStargz lazy-pull support (reference pkg/stargz +
pkg/filesystem/stargz_adaptor.go)."""

from nydus_snapshotter_tpu.stargz.adaptor import StargzAdaptor
from nydus_snapshotter_tpu.stargz.index import (
    DEFAULT_CHUNK_SIZE,
    TocEntry,
    bootstrap_from_toc,
    parse_toc,
)
from nydus_snapshotter_tpu.stargz.resolver import (
    ESTARGZ_FOOTER_SIZE,
    FOOTER_SIZE,
    TOC_FILENAME,
    Blob,
    Resolver,
    StargzError,
    parse_footer,
)

__all__ = [
    "Blob",
    "DEFAULT_CHUNK_SIZE",
    "ESTARGZ_FOOTER_SIZE",
    "FOOTER_SIZE",
    "Resolver",
    "StargzAdaptor",
    "StargzError",
    "TOC_FILENAME",
    "TocEntry",
    "bootstrap_from_toc",
    "parse_footer",
    "parse_toc",
]
