"""Content-defined chunking: two-phase FastCDC on top of the gear hash.

Phase 1 (device, parallel): judge every byte position with the
position-independent gear hash (ops/gear.py) against two FastCDC masks,
yielding two sparse candidate-position sets.

Phase 2 (host, sequential over *candidates*, not bytes): resolve actual cut
points with min/normal/max-size rules by binary-searching the candidate
arrays — O(chunks · log candidates), microseconds per GiB, so the sequential
dependency costs nothing.

The chunk-size knob carries the reference's bounds (``--chunk-size`` must be
a power of two in 0x1000..0x1000000, pkg/converter/types.go:76-79). Fixed
-size chunking (the nydus default mode) is also provided.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.ops import gear


class CDCError(ValueError):
    pass


@dataclass(frozen=True)
class CDCParams:
    """FastCDC parameters derived from the average (normal) chunk size.

    Normalization level 2: positions before the normal size use a mask with
    two *more* bits (harder to match, biasing cuts toward normal size),
    positions after use two *fewer* bits.
    """

    avg_size: int

    def __post_init__(self):
        avg = self.avg_size
        if avg & (avg - 1) or not (
            constants.CHUNK_SIZE_MIN <= avg <= constants.CHUNK_SIZE_MAX
        ):
            raise CDCError(
                f"chunk size must be a power of two in "
                f"[{constants.CHUNK_SIZE_MIN:#x}, {constants.CHUNK_SIZE_MAX:#x}], "
                f"got {avg:#x}"
            )

    @property
    def min_size(self) -> int:
        return self.avg_size // 4

    @property
    def normal_size(self) -> int:
        return self.avg_size

    @property
    def max_size(self) -> int:
        return min(self.avg_size * 4, constants.CHUNK_SIZE_MAX * 4)

    @property
    def bits(self) -> int:
        return self.avg_size.bit_length() - 1

    @property
    def mask_small(self) -> int:  # used below normal size: harder match
        return (1 << (self.bits + 2)) - 1

    @property
    def mask_large(self) -> int:  # used above normal size: easier match
        return (1 << (self.bits - 2)) - 1


def candidates_from_hashes(hashes: np.ndarray, params: CDCParams) -> tuple[np.ndarray, np.ndarray]:
    """Sparse candidate positions for each mask from per-position hashes.

    A candidate at position ``i`` means "a chunk may end at i+1" (the hash
    covers the window ending at byte i).
    """
    h = np.asarray(hashes)
    cand_s = np.nonzero((h & np.uint32(params.mask_small)) == 0)[0]
    cand_l = np.nonzero((h & np.uint32(params.mask_large)) == 0)[0]
    return cand_s, cand_l


def resolve_cuts(
    cand_s: np.ndarray,
    cand_l: np.ndarray,
    total_len: int,
    params: CDCParams,
) -> np.ndarray:
    """Greedy FastCDC cut resolution over sparse candidates.

    Returns cut offsets (exclusive chunk ends), final ``total_len`` included.
    Bit-identical to the byte-sequential reference chunker
    (``chunk_sequential_reference``) because judged positions always lie
    >= min_size >= GEAR_WINDOW past the chunk start, where the
    position-independent hash equals the per-chunk-reset hash.
    """
    if params.min_size < gear.GEAR_WINDOW:
        raise CDCError(
            f"min chunk size {params.min_size} < gear window {gear.GEAR_WINDOW}; "
            "parallel/sequential equivalence would break"
        )
    n = total_len
    cuts = []
    start = 0
    while n - start > params.min_size:
        # Earliest small-mask candidate with length in [min, normal).
        cut = _first_candidate_in(
            cand_s, start + params.min_size - 1, min(start + params.normal_size - 1, n)
        )
        if cut is None:
            # Then large-mask candidate with length in [normal, max).
            cut = _first_candidate_in(
                cand_l, start + params.normal_size - 1, min(start + params.max_size - 1, n)
            )
        if cut is not None:
            end = cut + 1
        elif n - start > params.max_size:
            end = start + params.max_size  # forced cut
        else:
            end = n  # tail with no content cut
        cuts.append(end)
        start = end
    if n > start:
        cuts.append(n)
    return np.asarray(cuts, dtype=np.int64)


def _first_candidate_in(cand: np.ndarray, lo: int, hi: int) -> int | None:
    """First candidate position in [lo, hi), or None."""
    idx = np.searchsorted(cand, lo, side="left")
    if idx < len(cand) and cand[idx] < hi:
        return int(cand[idx])
    return None


def cuts_to_extents(cuts: np.ndarray) -> list[tuple[int, int]]:
    """[(offset, size), ...] from cut offsets."""
    out = []
    prev = 0
    for cut in cuts:
        out.append((prev, int(cut) - prev))
        prev = int(cut)
    return out


# ---------------------------------------------------------------------------
# Whole-stream helpers
# ---------------------------------------------------------------------------


_NP_WINDOW = 1 << 20


def chunk_data_np(data: bytes | np.ndarray, params: CDCParams) -> np.ndarray:
    """CPU path: cut offsets for a whole in-memory stream.

    Hashes are computed per 1 MiB window with the 31-byte tail carried
    across seams (bit-identical to whole-stream hashing) so peak memory is
    a few MiB regardless of stream length — this is the streaming Pack's
    fallback when the native chunker isn't built.
    """
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
    if arr.size == 0:
        return np.asarray([], dtype=np.int64)
    parts_s, parts_l = [], []
    for lo in range(0, arr.size, _NP_WINDOW):
        hi = min(lo + _NP_WINDOW, arr.size)
        tail = arr[max(0, lo - (gear.GEAR_WINDOW - 1)) : lo]
        if len(tail) < gear.GEAR_WINDOW - 1:
            tail = np.concatenate(
                [np.zeros(gear.GEAR_WINDOW - 1 - len(tail), dtype=np.uint8), tail]
            )
        h = gear.gear_hashes_np(arr[lo:hi], prev_tail=tail)
        cs, cl = candidates_from_hashes(h, params)
        parts_s.append(cs + lo)
        parts_l.append(cl + lo)
    cand_s = np.concatenate(parts_s)
    cand_l = np.concatenate(parts_l)
    return resolve_cuts(cand_s, cand_l, arr.size, params)


def chunk_data_jax(data: bytes | np.ndarray, params: CDCParams) -> np.ndarray:
    """Device path for a whole in-memory stream (single window)."""
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
    if arr.size == 0:
        return np.asarray([], dtype=np.int64)
    hashes = np.asarray(gear.gear_hashes_jax(arr))
    cand_s, cand_l = candidates_from_hashes(hashes, params)
    return resolve_cuts(cand_s, cand_l, arr.size, params)


def chunk_fixed(total_len: int, chunk_size: int) -> np.ndarray:
    """Fixed-size chunking (the nydus default ``--chunk-size`` behavior)."""
    if chunk_size <= 0:
        raise CDCError("chunk size must be positive")
    cuts = list(range(chunk_size, total_len, chunk_size))
    cuts.append(total_len)
    return np.asarray(cuts if total_len else [], dtype=np.int64)


# ---------------------------------------------------------------------------
# Sequential ground truth (differential-test oracle)
# ---------------------------------------------------------------------------


def chunk_sequential_reference(data: bytes, params: CDCParams) -> np.ndarray:
    """Classic byte-at-a-time FastCDC with per-chunk hash reset.

    Deliberately naive and slow — exists solely as the oracle the parallel
    two-phase pipeline must match bit-for-bit.
    """
    table = gear.gear_table()
    n = len(data)
    cuts = []
    start = 0
    while n - start > params.min_size:
        h = 0
        end = None
        scan_end = min(start + params.max_size, n)
        for i in range(start, scan_end):
            h = ((h << 1) + int(table[data[i]])) & 0xFFFFFFFF
            length = i + 1 - start
            if length < params.min_size:
                continue
            mask = params.mask_small if length < params.normal_size else params.mask_large
            if (h & mask) == 0:
                end = i + 1
                break
        if end is None:
            end = start + params.max_size if scan_end == start + params.max_size else n
        cuts.append(end)
        start = end
    if n > start:
        cuts.append(n)
    return np.asarray(cuts, dtype=np.int64)
