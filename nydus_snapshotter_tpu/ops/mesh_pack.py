"""Extent-packed per-device buffers for the sharded convert step.

The mesh dry run and scaling harness used to hand every device the WHOLE
corpus: ``sharded_convert_step`` passed the concatenated buffer through
``shard_map`` with ``in_specs=(P(), ...)``, so an n-device mesh held n
copies of a multi-GiB operand and the weak-scaling curve measured the
replication, not the partitioning (MESH_SCALING_r05: 0.214 efficiency at
8 devices). This module is the host-side planner that removes the
replication:

- The corpus is split into ``n_devices`` contiguous **byte shards** of
  ``shard_bytes = ceil(total / n)`` bytes; a chunk belongs to the device
  that owns its first byte.
- Each device's packed buffer is its shard plus a **halo**: pass-2
  gathers read ``cap_blocks * block_bytes`` bytes from each chunk start
  (the ``dynamic_slice`` span, not the chunk size), so a chunk cut right
  before a shard boundary reads into the next shard. The halo is the
  engine's maximum read span, which also guarantees no slice ever clamps
  (a clamped ``dynamic_slice`` shifts its start and corrupts in-range
  bytes — the same guard rule ops/fused_convert.layout applies).
- Every pass-2 bucket is re-partitioned so each device's rows sit in one
  contiguous block of the leading axis (``shard_map``'s layout), padded
  per device to a uniform ``rows_per_device``. Offsets are rebased to
  the packed buffer (``local``) with the absolute column kept so the
  replicated arm can run the IDENTICAL partition — the A/B then isolates
  exactly the operand layout.

Identity argument: a chunk's digest reads ``packed[dev, off - dev*S :
off - dev*S + size]`` which equals ``buf[off : off + size]`` by
construction; bytes past ``size`` are masked inside the gather kernel,
so halo content (next shard's bytes or the zero tail) never reaches a
digest. Padding rows gather from local offset 0 and are discarded on
assembly. ``tests/test_mesh_pack.py`` pins all of this against the
replicated arm and the host oracle.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

BLOCK_BYTES = 64  # SHA-256 block: pass-2 read span = cap_blocks * 64


def _pow2_ceil(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


@dataclass(frozen=True)
class ShardedBucket:
    """One pass-2 capacity class re-partitioned into per-device blocks.

    ``offsets_local``/``offsets_abs``/``sizes`` are ``i32[n_devices *
    rows_per_device]``; device d owns rows ``[d*rows_per_device, (d+1)*
    rows_per_device)`` with ``counts[d]`` live rows first (padding rows
    have size 0, offset 0, and are discarded on assembly).
    """

    cap_blocks: int
    offsets_local: np.ndarray  # i32[N] offsets into the per-device packed buffer
    offsets_abs: np.ndarray  # i32[N] absolute offsets into the concat corpus
    sizes: np.ndarray  # i32[N]
    rows_per_device: int
    counts: tuple[int, ...]  # live rows per device


@dataclass(frozen=True)
class MeshPackPlan:
    """Host-side packing plan for one sharded convert batch."""

    n_devices: int
    total_bytes: int  # valid corpus bytes (pre-padding)
    shard_bytes: int  # S: contiguous corpus bytes owned per device
    halo_bytes: int  # read-span halo appended to every shard
    pack_len: int  # uniform per-device packed buffer length (S + halo)
    buckets: list[ShardedBucket]
    order: list[tuple[int, int]] = field(default_factory=list)
    # (cap_blocks, flat row) per chunk in stream order — scatter-back map

    @property
    def bound_bytes(self) -> int:
        """The no-replication gate: per-device addressable corpus bytes
        must not exceed corpus/devices + halo."""
        return self.shard_bytes + self.halo_bytes

    def device_of(self, offset: int) -> int:
        return min(offset // self.shard_bytes, self.n_devices - 1)


def max_read_span(params, block_bytes: int = BLOCK_BYTES) -> int:
    """Largest pass-2 gather span for a CDC parameterization: the padded
    block count of a max-size chunk times the digest block width."""
    from nydus_snapshotter_tpu.ops import sha256

    return sha256.n_padded_blocks(params.max_size) * block_bytes


def plan_mesh_pack(
    buckets,
    order,
    total: int,
    n_devices: int,
    halo_bytes: int | None = None,
    block_bytes: int = BLOCK_BYTES,
) -> MeshPackPlan:
    """Re-partition a ``FusedDeviceEngine.plan_buckets`` result onto an
    ``n_devices`` byte-shard mesh.

    ``buckets``/``order`` come straight from ``plan_buckets`` (absolute
    offsets, pow2-padded live prefixes). ``halo_bytes`` defaults to the
    largest read span any bucket in the batch can issue; passing the
    engine-level ``max_read_span`` keeps the plan shape independent of
    which classes a particular corpus happened to produce.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    total = max(0, int(total))
    shard = max(1, -(-total // n_devices)) if total else 1
    max_span = max(
        (b.cap_blocks * block_bytes for b in buckets), default=block_bytes
    )
    halo = max_span if halo_bytes is None else max(int(halo_bytes), max_span)
    pack_len = shard + halo

    sharded: list[ShardedBucket] = []
    remap: dict[int, np.ndarray] = {}  # cap -> old live row -> new flat row
    for b in buckets:
        live = b.count
        offs = np.asarray(b.offsets[:live], dtype=np.int64)
        sizes = np.asarray(b.sizes[:live], dtype=np.int64)
        dev = np.minimum(offs // shard, n_devices - 1).astype(np.int64)
        if live and (np.diff(dev) < 0).any():
            # plan_buckets appends rows in stream order, so offsets (and
            # thus devices) ascend; a violation means the caller handed a
            # reordered bucket and the contiguous-block layout below
            # would silently scramble shard_map's partition.
            raise ValueError("bucket rows are not offset-ordered")
        counts = np.bincount(dev, minlength=n_devices).astype(np.int64)
        m_dev = _pow2_ceil(int(counts.max())) if live else 1
        n_rows = n_devices * m_dev
        loc = np.zeros(n_rows, dtype=np.int32)
        abso = np.zeros(n_rows, dtype=np.int32)
        szs = np.zeros(n_rows, dtype=np.int32)
        base = np.concatenate([[0], np.cumsum(counts)[:-1]])
        idx_in_dev = np.arange(live) - base[dev]
        rows = dev * m_dev + idx_in_dev
        local = offs - dev * shard
        if live:
            if local.min() < 0 or (local + b.cap_blocks * block_bytes).max() > pack_len:
                raise AssertionError(
                    "extent plan would clamp a gather: local offset span "
                    f"[{local.min()}, {(local + b.cap_blocks * block_bytes).max()}] "
                    f"outside pack_len {pack_len}"
                )
            loc[rows] = local
            abso[rows] = offs
            szs[rows] = sizes
        sharded.append(
            ShardedBucket(
                cap_blocks=b.cap_blocks,
                offsets_local=loc,
                offsets_abs=abso,
                sizes=szs,
                rows_per_device=m_dev,
                counts=tuple(int(c) for c in counts),
            )
        )
        remap[b.cap_blocks] = np.asarray(rows, dtype=np.int64)

    # old order rows index the live prefix of each bucket in append order
    seen: dict[int, int] = {}
    new_order: list[tuple[int, int]] = []
    for cap, _old_row in order:
        i = seen.get(cap, 0)
        seen[cap] = i + 1
        new_order.append((cap, int(remap[cap][i])))
    return MeshPackPlan(
        n_devices=n_devices,
        total_bytes=total,
        shard_bytes=shard,
        halo_bytes=halo,
        pack_len=pack_len,
        buckets=sharded,
        order=new_order,
    )


def pack_buffers(buf: np.ndarray, plan: MeshPackPlan) -> np.ndarray:
    """``u8[n_devices, pack_len]``: each row is that device's byte shard
    plus halo, zero-padded past the corpus tail."""
    buf = np.asarray(buf, dtype=np.uint8).reshape(-1)
    out = np.zeros((plan.n_devices, plan.pack_len), dtype=np.uint8)
    for d in range(plan.n_devices):
        lo = d * plan.shard_bytes
        hi = min(lo + plan.pack_len, plan.total_bytes, buf.size)
        if hi > lo:
            out[d, : hi - lo] = buf[lo:hi]
    return out


# ---------------------------------------------------------------------------
# No-replication gate helpers
# ---------------------------------------------------------------------------


def addressable_bytes_per_device(arr) -> dict[str, int]:
    """Bytes of ``arr`` physically resident per addressable device."""
    out: dict[str, int] = {}
    for sh in arr.addressable_shards:
        key = str(sh.device)
        out[key] = out.get(key, 0) + int(np.prod(sh.data.shape)) * sh.data.dtype.itemsize
    return out


def assert_extent_packed(arr, plan: MeshPackPlan) -> dict[str, int]:
    """The addressable-bytes gate: no device may hold more corpus bytes
    than its shard plus the halo. Returns the per-device byte map so
    harnesses can record the evidence they gated on."""
    per_dev = addressable_bytes_per_device(arr)
    for dev, nbytes in per_dev.items():
        if nbytes > plan.bound_bytes:
            raise AssertionError(
                f"operand replicated: device {dev} holds {nbytes} bytes "
                f"> corpus/devices + halo = {plan.bound_bytes}"
            )
    return per_dev


# ---------------------------------------------------------------------------
# [mesh] config resolution
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MeshRuntimeConfig:
    pack: str = "extent"  # extent | replicated
    devices: int = 0  # 0 = every local device
    halo_kib: int = 0  # 0 = auto (engine max read span)


def resolve_mesh_config() -> MeshRuntimeConfig:
    """``NTPU_MESH*`` env > ``[mesh]`` config > defaults (the same
    precedence every other section uses)."""
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        mc = _cfg.get_global_config().mesh
    except Exception:
        mc = None
    pack = os.environ.get("NTPU_MESH_PACK", "") or getattr(mc, "pack", "") or "extent"
    if pack not in ("extent", "replicated"):
        pack = "extent"

    def _env_int(name: str, fallback: int) -> int:
        try:
            v = int(os.environ.get(name, ""))
            return v if v >= 0 else fallback
        except ValueError:
            return fallback

    devices = _env_int("NTPU_MESH_DEVICES", int(getattr(mc, "devices", 0) or 0))
    halo_kib = _env_int("NTPU_MESH_HALO_KIB", int(getattr(mc, "halo_kib", 0) or 0))
    return MeshRuntimeConfig(pack=pack, devices=max(0, devices), halo_kib=max(0, halo_kib))
