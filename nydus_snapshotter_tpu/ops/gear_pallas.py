"""Pallas TPU kernel for the gear-hash candidate bitmaps.

The XLA formulation of the gear pass (ops/gear.py windowed_gear_sum +
ops/chunker._hash_bitmaps_kernel) materializes every doubling step in HBM
(~1.5 GiB/s measured on a v5e chip). This kernel keeps the whole pipeline —
mix32, the 5 log-doubling shifted adds, both mask tests, and the bitmap
pack — in VMEM, reading each input byte from HBM exactly once.

Layout: lane-major substreams. A window of T bytes is split into 128
substreams of SW = T/128 consecutive bytes; substream l lives in lane l,
successive bytes in successive sublanes (rows). The windowed sum's
"position - m" then shifts *rows* (cheap sublane slice) instead of lanes.
Each substream tile carries the 31 bytes preceding it (the previous
substream's tail, or the window's host-provided tail for lane 0) so hashes
are bit-identical to whole-stream hashing — the same seam-carry discipline
as the host windowing (ops/chunker.py).

Outputs are packed u32 bitmap words per substream ([B, SW/32, 128]);
``gear_bitmaps`` transposes them back to stream order so the host-side
candidate unpack (ops/chunker._unpack_positions) is layout-agnostic.

Reference hot loop replaced: chunking inside ``nydus-image create``
(pkg/converter/tool/builder.go:148-178).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from nydus_snapshotter_tpu.ops import gear

TAIL = gear.GEAR_WINDOW - 1  # 31
PAD = 32  # top pad rows per tile: TAIL carry rows + 1 zero row for 8-row
#          DMA alignment (Mosaic requires sublane slices aligned to 8; the
#          zero row sits 32 positions back and can never reach a valid hash)
LANES = 128
# Output rows per grid step. Tunable via NTPU_GEAR_TILE for hardware
# sweeps (suspected VMEM-pressure bound at 4096: ~6 live u32[rows,128]
# temporaries; 1024 keeps them ~3 MB total).
import os as _os

ROWS_PER_TILE = int(_os.environ.get("NTPU_GEAR_TILE", "1024"))


def _kernel(y_ref, out_s_ref, out_l_ref, scratch, sem, *, mask_s: int, mask_l: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    t = pl.program_id(1)
    r = ROWS_PER_TILE
    dma = pltpu.make_async_copy(
        y_ref.at[b, pl.ds(t * r, r + PAD), :], scratch, sem
    )
    dma.start()
    dma.wait()

    g = gear.mix32_jnp(scratch[:])  # u32[r+32, 128]
    s = g
    m = 1
    while m < gear.GEAR_WINDOW:
        shifted = jnp.concatenate(
            [jnp.zeros((m, LANES), jnp.uint32), s[:-m]], axis=0
        )
        s = s + (shifted << np.uint32(m))
        m *= 2
    h = s[PAD:]  # u32[r, 128], h[i] = gear hash ending at substream pos i

    # Pack in int32 (Mosaic has no unsigned reductions); the weighted sum of
    # distinct powers of two is the same bit pattern under two's complement.
    w = jnp.left_shift(
        jnp.int32(1), jax.lax.broadcasted_iota(jnp.int32, (1, 32, 1), 1)
    )

    def pack(bits):
        packed = jnp.sum(bits.reshape(r // 32, 32, LANES) * w, axis=1)
        return jax.lax.bitcast_convert_type(packed, jnp.uint32)

    out_s_ref[:] = pack(((h & np.uint32(mask_s)) == 0).astype(jnp.int32))
    out_l_ref[:] = pack(((h & np.uint32(mask_l)) == 0).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l", "interpret"))
def _bitmaps_lanes(y: jax.Array, mask_s: int, mask_l: int, interpret: bool = False):
    """y: u8[B, SW+32, 128] (lane-major substreams; 1 zero row + 31 tail
    rows on top) -> (u32[B, SW/32, 128], u32[B, SW/32, 128]) packed per
    substream."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bsz, swp, _ = y.shape
    sw = swp - PAD
    grid = (bsz, sw // ROWS_PER_TILE)
    out_shape = jax.ShapeDtypeStruct((bsz, sw // 32, LANES), jnp.uint32)
    out_spec = pl.BlockSpec(
        (1, ROWS_PER_TILE // 32, LANES), lambda b, t: (b, t, 0)
    )
    kernel = functools.partial(_kernel, mask_s=mask_s, mask_l=mask_l)

    def kernel_squeezed(y_ref, os_ref, ol_ref, scratch, sem):
        # out blocks arrive as [1, r/32, 128]; present 2-D views to _kernel
        class _V:
            def __init__(self, ref):
                self.ref = ref

            def __setitem__(self, idx, val):
                self.ref[0] = val

        kernel(y_ref, _V(os_ref), _V(ol_ref), scratch, sem)

    return pl.pallas_call(
        kernel_squeezed,
        grid=grid,
        out_shape=(out_shape, out_shape),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(out_spec, out_spec),
        scratch_shapes=[
            pltpu.VMEM((ROWS_PER_TILE + PAD, LANES), jnp.uint8),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(y)


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l", "n", "interpret"))
def gear_bitmaps(x: jax.Array, mask_s: int, mask_l: int, n: int, interpret: bool = False):
    """Drop-in device path for ops/chunker._hash_bitmaps_kernel.

    x: u8[B, n+31] stream-order windows with 31-byte tail prefix.
    Returns (u32[B, n//32], u32[B, n//32]) packed candidate bitmaps in
    stream order for the small/large FastCDC masks.
    """
    bsz = x.shape[0]
    sw = n // LANES
    seg = x[:, TAIL:].reshape(bsz, LANES, sw).transpose(0, 2, 1)  # [B, SW, 128]
    tails = jnp.concatenate(
        [x[:, :TAIL, None], seg[:, sw - TAIL :, : LANES - 1]], axis=2
    )  # [B, 31, 128]: 31 bytes preceding each substream
    zrow = jnp.zeros((bsz, 1, LANES), jnp.uint8)
    y = jnp.concatenate([zrow, tails, seg], axis=1)  # [B, SW+32, 128]
    bm_s, bm_l = _bitmaps_lanes(y, mask_s, mask_l, interpret=interpret)
    # substream-major words -> stream order: [B, SW/32, 128] -> [B, n/32]
    return (
        bm_s.transpose(0, 2, 1).reshape(bsz, n // 32),
        bm_l.transpose(0, 2, 1).reshape(bsz, n // 32),
    )


def supported(n: int) -> bool:
    """This kernel needs TPU and a window that tiles into lane substreams."""
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    return (
        on_tpu
        and n % (LANES * ROWS_PER_TILE) == 0
    )
