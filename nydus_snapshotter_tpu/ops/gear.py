"""Gear rolling hash — the CDC primitive, computed position-parallel.

The reference's chunker lives inside the external Rust ``nydus-image``
(invoked at pkg/converter/tool/builder.go:148-178); this framework replaces it
with a TPU-friendly decomposition:

A 32-bit gear hash ``h_i = (h_{i-1} << 1) + G[x_i]`` forgets bytes older than
32 positions (each shift drops one bit of history), so

    h_i = sum_{k=0}^{31} G[x_{i-k}] << k        (mod 2^32)

which is 32 shifted adds over a byte window — embarrassingly parallel, no
scan. Because judged cut positions always sit >= min_size >= 32 bytes past
their chunk start, this position-independent value is bit-identical to the
classic sequential FastCDC hash that resets per chunk. That equivalence is
what lets the TPU judge every position of a multi-GiB stream in parallel and
still produce exactly the boundaries the sequential CPU reference produces
(differential-tested in tests/test_chunk_engine.py).
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

# Effective window of a 32-bit gear hash: one byte of history per shift.
GEAR_WINDOW = 32

_GEAR_SEED = b"nydus-tpu-gear-v1"


@functools.cache
def gear_table() -> np.ndarray:
    """The 256-entry gear table, deterministically derived from a fixed seed.

    Any implementation (numpy, jnp, pallas, C++) regenerates the identical
    table, so cut points are reproducible across hosts and backends.
    """
    out = np.empty(256, dtype=np.uint32)
    for i in range(256):
        digest = hashlib.sha256(_GEAR_SEED + bytes([i])).digest()
        out[i] = np.frombuffer(digest[:4], dtype="<u4")[0]
    return out


def gear_hashes_np(data: np.ndarray, prev_tail: np.ndarray | None = None) -> np.ndarray:
    """CPU reference: hash at every position of ``data`` (uint8[N] -> uint32[N]).

    ``prev_tail`` is the previous GEAR_WINDOW-1 bytes of the stream when
    ``data`` is a window of a longer stream (zeros at stream start).
    """
    if prev_tail is None:
        prev_tail = np.zeros(GEAR_WINDOW - 1, dtype=np.uint8)
    if len(prev_tail) != GEAR_WINDOW - 1:
        raise ValueError(f"prev_tail must be {GEAR_WINDOW - 1} bytes")
    n = len(data)
    x = np.concatenate([prev_tail, np.asarray(data, dtype=np.uint8)])
    g = gear_table()[x]  # uint32[n + 31]
    # All arithmetic stays uint32: shifts drop high bits and adds wrap, which
    # IS the mod-2^32 gear semantics — no 8-byte temporaries (this path also
    # serves the streaming chunker's fallback, where peak RSS matters).
    h = np.zeros(n, dtype=np.uint32)
    for k in range(GEAR_WINDOW):
        start = GEAR_WINDOW - 1 - k
        h += g[start : start + n] << np.uint32(k)
    return h


@functools.partial(jax.jit, static_argnames=("n",))
def _gear_hashes_jit(x: jax.Array, n: int) -> jax.Array:
    g = jnp.asarray(gear_table())[x.astype(jnp.int32)]
    h = jnp.zeros(n, dtype=jnp.uint32)
    for k in range(GEAR_WINDOW):
        start = GEAR_WINDOW - 1 - k
        h = h + (jax.lax.dynamic_slice(g, (start,), (n,)) << np.uint32(k))
    return h


def gear_hashes_jax(data, prev_tail=None) -> jax.Array:
    """Device path: hash at every position (uint8[N] -> uint32[N]).

    32 shifted adds + one 256-entry gather; XLA fuses the adds into a few
    vector passes. Shapes are static per window size, so each window size
    compiles once.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    if prev_tail is None:
        prev_tail = jnp.zeros(GEAR_WINDOW - 1, dtype=jnp.uint8)
    prev_tail = jnp.asarray(prev_tail, dtype=jnp.uint8)
    x = jnp.concatenate([prev_tail, data])
    return _gear_hashes_jit(x, data.shape[0])
