"""Gear rolling hash — the CDC primitive, computed position-parallel.

The reference's chunker lives inside the external Rust ``nydus-image``
(invoked at pkg/converter/tool/builder.go:148-178); this framework replaces it
with a TPU-friendly decomposition:

A 32-bit gear hash ``h_i = (h_{i-1} << 1) + G[x_i]`` forgets bytes older than
32 positions (each shift drops one bit of history), so

    h_i = sum_{k=0}^{31} G[x_{i-k}] << k        (mod 2^32)

which is 32 shifted adds over a byte window — embarrassingly parallel, no
scan. Because judged cut positions always sit >= min_size >= 32 bytes past
their chunk start, this position-independent value is bit-identical to the
classic sequential FastCDC hash that resets per chunk. That equivalence is
what lets the TPU judge every position of a multi-GiB stream in parallel and
still produce exactly the boundaries the sequential CPU reference produces
(differential-tested in tests/test_chunk_engine.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Effective window of a 32-bit gear hash: one byte of history per shift.
GEAR_WINDOW = 32

# fmix32 constants (MurmurHash3 finalizer — full avalanche in 5 steps).
_MIX_C0 = np.uint32(0x9E3779B1)  # golden-ratio odd multiplier, lifts 0..255
_MIX_C1 = np.uint32(0x85EBCA6B)
_MIX_C2 = np.uint32(0xC2B2AE35)


def mix32_np(x: np.ndarray) -> np.ndarray:
    """The gear mixing function: uint32 -> uint32, elementwise.

    This IS the table derivation ("gear-v2"): ``gear_table()[b] ==
    mix32(b)``. It is arithmetic on purpose — TPU vector units have no
    per-lane table gather, so the device path computes the table value of
    every byte elementwise (6 VPU ops) while CPU paths (numpy/C++) keep the
    precomputed 256-entry table with *identical contents*. Cut points stay
    reproducible across every backend.
    """
    x = x.astype(np.uint32)
    with np.errstate(over="ignore"):
        x = (x + np.uint32(1)) * _MIX_C0
        x ^= x >> np.uint32(16)
        x *= _MIX_C1
        x ^= x >> np.uint32(13)
        x *= _MIX_C2
        x ^= x >> np.uint32(16)
    return x


def mix32_jnp(x: jax.Array) -> jax.Array:
    """mix32 on device lanes (same math as mix32_np, uint32 wraparound)."""
    x = x.astype(jnp.uint32)
    x = (x + np.uint32(1)) * _MIX_C0
    x = x ^ (x >> np.uint32(16))
    x = x * _MIX_C1
    x = x ^ (x >> np.uint32(13))
    x = x * _MIX_C2
    x = x ^ (x >> np.uint32(16))
    return x


@functools.cache
def gear_table() -> np.ndarray:
    """The 256-entry gear table: ``table[b] = mix32(b)``.

    Derived arithmetically (not from a seed file) so device kernels can
    compute entries inline instead of gathering; any implementation
    (numpy, jnp, pallas, C++) regenerates the identical table, so cut
    points are reproducible across hosts and backends.
    """
    return mix32_np(np.arange(256, dtype=np.uint32))


def gear_hashes_np(data: np.ndarray, prev_tail: np.ndarray | None = None) -> np.ndarray:
    """CPU reference: hash at every position of ``data`` (uint8[N] -> uint32[N]).

    ``prev_tail`` is the previous GEAR_WINDOW-1 bytes of the stream when
    ``data`` is a window of a longer stream (zeros at stream start).
    """
    if prev_tail is None:
        prev_tail = np.zeros(GEAR_WINDOW - 1, dtype=np.uint8)
    if len(prev_tail) != GEAR_WINDOW - 1:
        raise ValueError(f"prev_tail must be {GEAR_WINDOW - 1} bytes")
    n = len(data)
    x = np.concatenate([prev_tail, np.asarray(data, dtype=np.uint8)])
    g = gear_table()[x]  # uint32[n + 31]
    # All arithmetic stays uint32: shifts drop high bits and adds wrap, which
    # IS the mod-2^32 gear semantics — no 8-byte temporaries (this path also
    # serves the streaming chunker's fallback, where peak RSS matters).
    h = np.zeros(n, dtype=np.uint32)
    for k in range(GEAR_WINDOW):
        start = GEAR_WINDOW - 1 - k
        h += g[start : start + n] << np.uint32(k)
    return h


def windowed_gear_sum(g: jax.Array) -> jax.Array:
    """h[i] = sum_{k=0}^{31} g[i-k] << k over the last axis (zeros off the
    left edge), via log-doubling: S_1 = g, S_2m[i] = S_m[i] + S_m[i-m] << m
    — 5 shifted-add passes instead of 32 (the window sum is an associative
    prefix over a fixed 32-tap geometric kernel)."""
    s = g
    m = 1
    while m < GEAR_WINDOW:
        pad = [(0, 0)] * (s.ndim - 1) + [(m, 0)]
        shifted = jnp.pad(s, pad)[..., : s.shape[-1]]
        s = s + (shifted << np.uint32(m))
        m *= 2
    return s


@functools.partial(jax.jit, static_argnames=("n",))
def _gear_hashes_jit(x: jax.Array, n: int) -> jax.Array:
    h = windowed_gear_sum(mix32_jnp(x))
    return jax.lax.dynamic_slice(h, (GEAR_WINDOW - 1,), (n,))


def gear_hashes_jax(data, prev_tail=None) -> jax.Array:
    """Device path: hash at every position (uint8[N] -> uint32[N]).

    Elementwise mix32 (no gather — TPU VPUs have no per-lane table lookup)
    followed by the log-doubling windowed sum. Shapes are static per window
    size, so each window size compiles once.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    if prev_tail is None:
        prev_tail = jnp.zeros(GEAR_WINDOW - 1, dtype=jnp.uint8)
    prev_tail = jnp.asarray(prev_tail, dtype=jnp.uint8)
    x = jnp.concatenate([prev_tail, data])
    return _gear_hashes_jit(x, data.shape[0])
