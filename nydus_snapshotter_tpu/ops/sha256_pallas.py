"""Pallas TPU kernel for batched SHA-256.

The XLA formulation (ops/sha256.py: vmap over chunks, lax.scan over
blocks) measured 2.8 GiB/s on a v5e chip — adequate but likely layout- and
scan-overhead-bound rather than VPU-bound. This kernel pins the layout:
chunks live in lanes (8 sublanes x 128 lanes = 1024 chunks per grid step),
the eight working variables are [8, 128] vectors, and the message schedule
is a rolling 16-deep window kept as sixteen separate [8, 128] vectors.

Two backend constraints shape the round loop, learned the hard way:

- Mosaic cannot lower `dynamic_slice` on *values* — the first real-TPU
  window (DEVICE_NUMBERS.md, 2026-07-31) failed exactly there when the
  message window was a stacked [16, 8, 128] array indexed by
  ``(step*8 + r) % 16`` with a traced step.
- XLA CPU (the `interpret=True` correctness path) chokes on a fully
  64-round-unrolled body — minutes of compile even at one block
  (the same issue ops/sha256.py documents).

So: rounds run 8-per-step inside a ``fori_loop`` of 8 steps, the window
*rotates* — every round consumes ``w[0]`` and appends the (conditionally
extended) word at the tail, so all window indices are static Python ints —
and the round constant is picked by a chain of scalar selects over the
step index, so there is no K-table indexing at all. The per-block loop
is the second grid dimension: each step's 64-word block arrives via the
BlockSpec index map and the running hash state lives in the revisited
output block (the standard accumulation pattern), so the kernel contains
no dynamic ref indexing either.

Data layout in: ``u32[G, B, 16, 8, 128]`` (word-major per block, chunk
groups minor) produced by one device-side transpose from the engine's
``u32[M, B, 16]`` packing; counts ``i32[G, 8, 128]``. Out:
``u32[G, 8, 8, 128]`` (state words major) transposed back to ``u32[M, 8]``.

Same math as ops/sha256.py `_compress_unrolled` — differential-tested
equal; usable under `interpret=True` on CPU for correctness runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from nydus_snapshotter_tpu.ops import sha256 as sha_ref

LANES = 128
SUBLANES = 8
GROUP = LANES * SUBLANES  # chunks per grid step


def _rotr(x, r):
    return (x >> np.uint32(r)) | (x << np.uint32(32 - r))


_ROUND_UNROLL = 8  # rounds per fori step: compile size vs loop overhead


def _k_at(s, r: int):
    """Round constant K[s*8 + r] for traced step s, static in-step round r.

    A chain of 7 scalar selects replaces any table load — Mosaic lowers
    arith.select fine, and there is nothing to dynamic-slice.
    """
    out = jnp.uint32(sha_ref._K[r])
    for row in range(1, 8):
        out = jnp.where(s == row, np.uint32(sha_ref._K[row * 8 + r]), out)
    return out


def _kernel(blocks_ref, counts_ref, out_ref):
    """blocks_ref: u32[1, 1, 16, 8, 128] (this grid step's block);
    counts_ref: i32[1, 8, 128]; out_ref: u32[1, 8, 8, 128], revisited
    across the block grid dim — it carries the running hash state."""
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        for i in range(8):
            out_ref[0, i] = jnp.full(
                (SUBLANES, LANES), np.uint32(sha_ref._H0[i])
            )

    state = [out_ref[0, i] for i in range(8)]
    w0 = blocks_ref[0, 0]  # u32[16, 8, 128]

    def rounds8(s, carry):
        *w, a, b, c, d, e, f, g, h = carry
        # Rounds t = s*8 + r. The window rotates: at round t, w[0] is
        # W[t] for t < 16 (pure rotation of the initial 16 words) and
        # W[t-16] for t >= 16, where the schedule extension
        #   W[t] = W[t-16] + s0(W[t-15]) + W[t-7] + s1(W[t-2])
        # reads w[0], w[1], w[9], w[14]. t >= 16 iff s >= 2, uniform
        # across the unrolled step.
        extend = s >= 2
        for r in range(_ROUND_UNROLL):
            w15, w2 = w[1], w[14]
            es0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
            es1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
            wi = w[0] + jnp.where(extend, es0 + w[9] + es1, np.uint32(0))
            w = w[1:] + [wi]
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + _k_at(s, r) + wi
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            a, b, c, d, e, f, g, h = t1 + s0 + maj, a, b, c, d + t1, e, f, g
        return (*w, a, b, c, d, e, f, g, h)

    out = jax.lax.fori_loop(0, 8, rounds8, (*[w0[i] for i in range(16)], *state))
    live = j < counts_ref[0]  # chunks with fewer blocks keep their state
    for i, (new, old) in enumerate(zip(out[16:], state)):
        out_ref[0, i] = jnp.where(live, new + old, old)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sha256_groups(blocks_t: jax.Array, counts_t: jax.Array, interpret: bool = False):
    import jax.experimental.pallas as pl

    g, b = blocks_t.shape[0], blocks_t.shape[1]
    return pl.pallas_call(
        _kernel,
        grid=(g, b),
        in_specs=[
            pl.BlockSpec((1, 1, 16, SUBLANES, LANES), lambda i, j: (i, j, 0, 0, 0)),
            pl.BlockSpec((1, SUBLANES, LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, SUBLANES, LANES), lambda i, j: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g, 8, SUBLANES, LANES), jnp.uint32),
        interpret=interpret,
    )(blocks_t, counts_t)


@functools.partial(jax.jit, static_argnames=("interpret",))
def sha256_batch_pallas(
    blocks: jax.Array, nblocks: jax.Array, interpret: bool = False
) -> jax.Array:
    """Drop-in for ops/sha256.sha256_batch: u32[M,B,16] + i32[M] -> u32[M,8].

    M is padded up to a multiple of 1024 internally (pad rows carry zero
    block counts and are sliced off).
    """
    m, b, _ = blocks.shape
    pad = (-m) % GROUP
    if pad:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((pad, b, 16), jnp.uint32)], axis=0
        )
        nblocks = jnp.concatenate([nblocks, jnp.zeros(pad, jnp.int32)])
    g = (m + pad) // GROUP
    # [M, B, 16] -> [G, B, 16, 8, 128]: chunks into (sublane, lane) minors.
    blocks_t = blocks.reshape(g, SUBLANES, LANES, b, 16).transpose(0, 3, 4, 1, 2)
    counts_t = nblocks.reshape(g, SUBLANES, LANES)
    states = _sha256_groups(blocks_t, counts_t, interpret=interpret)
    # [G, 8, 8, 128] -> [M, 8]
    out = states.transpose(0, 2, 3, 1).reshape(g * GROUP, 8)
    return out[:m]


def supported(m: int) -> bool:
    """Worth dispatching: TPU backend and a batch big enough to fill at
    least one 1024-chunk group."""
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    return on_tpu and m >= GROUP
