"""Pallas TPU kernel for batched SHA-256.

The XLA formulation (ops/sha256.py: vmap over chunks, lax.scan over
blocks) measured 2.8 GiB/s on a v5e chip — adequate but likely layout- and
scan-overhead-bound rather than VPU-bound. This kernel pins the layout:
chunks live in lanes (8 sublanes x 128 lanes = 1024 chunks per grid step),
the eight working variables are [8, 128] vectors, the message schedule is a
rolling 16-deep window of [8, 128] vectors, and rounds run as a
fori_loop of 8-round unrolled steps inside a fori_loop over 64-byte
blocks (full unrolling is compile-hostile; 8x is the balance).

Data layout in: ``u32[G, B, 16, 8, 128]`` (word-major per block, chunk
groups minor) produced by one device-side transpose from the engine's
``u32[M, B, 16]`` packing; counts ``i32[G, 8, 128]``. Out:
``u32[G, 8, 8, 128]`` (state words major) transposed back to ``u32[M, 8]``.

Same math as ops/sha256.py `_compress_unrolled` — differential-tested
equal; usable under `interpret=True` on CPU for correctness runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from nydus_snapshotter_tpu.ops import sha256 as sha_ref

LANES = 128
SUBLANES = 8
GROUP = LANES * SUBLANES  # chunks per grid step


def _rotr(x, r):
    return (x >> np.uint32(r)) | (x << np.uint32(32 - r))


_ROUND_UNROLL = 8  # rounds per inner step: compile size vs loop overhead


def _kernel(k_ref, blocks_ref, counts_ref, out_ref):
    """k_ref: u32[8, 8] round constants; blocks_ref: u32[1, B, 16, 8, 128];
    counts_ref: i32[1, 8, 128]; out_ref: u32[1, 8, 8, 128].

    Rounds run in a fori_loop of 8-round unrolled steps over a stacked
    [16, 8, 128] message window — full 64-round unrolling produces a
    compile-hostile op chain (the same issue ops/sha256.py documents for
    XLA CPU), and 16 % 8 == 0 keeps every in-step window index static.
    """
    nblocks = blocks_ref.shape[1]
    counts = counts_ref[0]
    k_tab = k_ref[:]  # [step, round-in-step]
    h0 = [jnp.full((SUBLANES, LANES), np.uint32(v)) for v in sha_ref._H0]

    def block_step(j, state):
        w0 = blocks_ref[0, j]  # u32[16, 8, 128]
        a, b, c, d, e, f, g, h = state

        def rounds8(s, carry):
            w, a, b, c, d, e, f, g, h = carry
            ks = jax.lax.dynamic_index_in_dim(k_tab, s, keepdims=False)
            base = s * _ROUND_UNROLL
            for r in range(_ROUND_UNROLL):
                idx = (base + r) % 16  # static within the unrolled step
                wi = w[idx]

                def extend(w=w, idx=idx, wi=wi):
                    w15 = w[(idx - 15) % 16]
                    w2 = w[(idx - 2) % 16]
                    s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
                    s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
                    return wi + s0 + w[(idx - 7) % 16] + s1

                wi = jax.lax.cond(s >= 2, extend, lambda: wi)
                w = w.at[idx].set(wi)
                s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
                ch = (e & f) ^ (~e & g)
                t1 = h + s1 + ch + ks[r] + wi
                s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
                maj = (a & b) ^ (a & c) ^ (b & c)
                a, b, c, d, e, f, g, h = t1 + s0 + maj, a, b, c, d + t1, e, f, g
            return (w, a, b, c, d, e, f, g, h)

        _, a, b, c, d, e, f, g, h = jax.lax.fori_loop(
            0, 8, rounds8, (w0, a, b, c, d, e, f, g, h)
        )
        live = j < counts  # chunks with fewer blocks keep their state
        out = [
            jnp.where(live, new + old, old)
            for new, old in zip((a, b, c, d, e, f, g, h), state)
        ]
        return tuple(out)

    final = jax.lax.fori_loop(0, nblocks, block_step, tuple(h0))
    for i in range(8):
        out_ref[0, i] = final[i]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sha256_groups(blocks_t: jax.Array, counts_t: jax.Array, interpret: bool = False):
    import jax.experimental.pallas as pl

    g, b = blocks_t.shape[0], blocks_t.shape[1]
    k_tab = jnp.asarray(sha_ref._K).reshape(8, 8)
    return pl.pallas_call(
        _kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((8, 8), lambda i: (0, 0)),
            pl.BlockSpec((1, b, 16, SUBLANES, LANES), lambda i: (i, 0, 0, 0, 0)),
            pl.BlockSpec((1, SUBLANES, LANES), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, SUBLANES, LANES), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g, 8, SUBLANES, LANES), jnp.uint32),
        interpret=interpret,
    )(k_tab, blocks_t, counts_t)


@functools.partial(jax.jit, static_argnames=("interpret",))
def sha256_batch_pallas(
    blocks: jax.Array, nblocks: jax.Array, interpret: bool = False
) -> jax.Array:
    """Drop-in for ops/sha256.sha256_batch: u32[M,B,16] + i32[M] -> u32[M,8].

    M is padded up to a multiple of 1024 internally (pad rows carry zero
    block counts and are sliced off).
    """
    m, b, _ = blocks.shape
    pad = (-m) % GROUP
    if pad:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((pad, b, 16), jnp.uint32)], axis=0
        )
        nblocks = jnp.concatenate([nblocks, jnp.zeros(pad, jnp.int32)])
    g = (m + pad) // GROUP
    # [M, B, 16] -> [G, B, 16, 8, 128]: chunks into (sublane, lane) minors.
    blocks_t = blocks.reshape(g, SUBLANES, LANES, b, 16).transpose(0, 3, 4, 1, 2)
    counts_t = nblocks.reshape(g, SUBLANES, LANES)
    states = _sha256_groups(blocks_t, counts_t, interpret=interpret)
    # [G, 8, 8, 128] -> [M, 8]
    out = states.transpose(0, 2, 3, 1).reshape(g * GROUP, 8)
    return out[:m]


def supported(m: int) -> bool:
    """Worth dispatching: TPU backend and a batch big enough to fill at
    least one 1024-chunk group."""
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    return on_tpu and m >= GROUP
