"""ctypes bridge to the native chunk engine (native/chunk_engine).

The sequential gear chunker is the host arm of the hybrid conversion
engine: ctypes calls release the GIL, so a thread pool chunks many layer
streams concurrently while the TPU handles digest batches and dict probes.
Cut points are bit-identical to ops/cdc.py's resolution (differential-
tested in tests/test_chunk_engine.py).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from nydus_snapshotter_tpu.ops import cdc, gear

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_missing = False


def _lib_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "native", "bin", "libchunk_engine.so"
    )


def load() -> Optional[ctypes.CDLL]:
    """The shared library, or None when not built (make -C native)."""
    global _lib, _lib_missing
    with _lib_lock:
        if _lib is not None or _lib_missing:
            return _lib
        path = _lib_path()
        if not os.path.exists(path):
            _lib_missing = True
            return None
        lib = ctypes.CDLL(path)
        lib.ntpu_cdc_chunk.restype = ctypes.c_int64
        lib.ntpu_cdc_chunk.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,  # data, n
            ctypes.c_void_p,                  # table
            ctypes.c_uint32, ctypes.c_uint32,  # masks
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # min/normal/max
            ctypes.c_void_p, ctypes.c_int64,  # cuts_out, cap
        ]
        lib.ntpu_gear_hashes.restype = None
        lib.ntpu_gear_hashes.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p
        ]
        if hasattr(lib, "ntpu_dict_build"):
            lib.ntpu_dict_build.restype = ctypes.c_int64
            lib.ntpu_dict_build.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,  # digests, n
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # shards, cap, max_probe
                ctypes.c_void_p, ctypes.c_void_p,  # keys, values
            ]
        if hasattr(lib, "ntpu_dict_probe"):
            lib.ntpu_dict_probe.restype = None
            lib.ntpu_dict_probe.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,  # queries, m
                ctypes.c_void_p, ctypes.c_void_p,  # keys, values
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # shards, cap, max_probe
                ctypes.c_void_p,  # out
            ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def chunk_data_native(data: bytes | np.ndarray, params: cdc.CDCParams) -> np.ndarray:
    """Cut offsets via the native chunker (drop-in for cdc.chunk_data_np)."""
    lib = load()
    if lib is None:
        raise RuntimeError("libchunk_engine.so not built (make -C nydus_snapshotter_tpu/native)")
    arr = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.ascontiguousarray(data, dtype=np.uint8)
    )
    if arr.size == 0:
        return np.asarray([], dtype=np.int64)
    table = np.ascontiguousarray(gear.gear_table())
    cap = arr.size // max(1, params.min_size) + 2
    cuts = np.empty(cap, dtype=np.int64)
    n = lib.ntpu_cdc_chunk(
        arr.ctypes.data, arr.size,
        table.ctypes.data,
        np.uint32(params.mask_small), np.uint32(params.mask_large),
        params.min_size, params.normal_size, params.max_size,
        cuts.ctypes.data, cap,
    )
    if n < 0:
        raise RuntimeError("native chunker cut buffer overflow")
    return cuts[:n].copy()


def dict_build_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_dict_build")


def dict_build_native(
    digests: np.ndarray, n_shards: int, cap: int, max_probe: int,
    keys: np.ndarray, values: np.ndarray,
) -> bool:
    """Sequential first-wins table build into caller-zeroed keys/values.

    Returns False when a probe chain overflowed max_probe (grow cap and
    retry). Arrays must be C-contiguous with the documented dtypes.
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_dict_build"):
        raise RuntimeError("libchunk_engine.so not built or too old")
    assert digests.dtype == np.uint32 and digests.flags.c_contiguous
    assert keys.dtype == np.uint32 and keys.flags.c_contiguous
    assert values.dtype == np.int32 and values.flags.c_contiguous
    rc = lib.ntpu_dict_build(
        digests.ctypes.data, len(digests), n_shards, cap, max_probe,
        keys.ctypes.data, values.ctypes.data,
    )
    return rc == 0


def dict_probe_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_dict_probe")


def dict_probe_native(
    queries: np.ndarray, keys: np.ndarray, values: np.ndarray,
    n_shards: int, cap: int, max_probe: int,
) -> np.ndarray:
    """Probe u32[M,8] queries against a built table -> i64[M] dict indices
    (-1 = miss). The single-node latency arm of the dedup probe: XLA TPU
    gathers are element-serial (~1 µs/element measured), so the host wins
    until the dict is sharded across chips."""
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_dict_probe"):
        raise RuntimeError("libchunk_engine.so not built or too old")
    queries = np.ascontiguousarray(queries, dtype=np.uint32)
    assert keys.dtype == np.uint32 and keys.flags.c_contiguous
    assert values.dtype == np.int32 and values.flags.c_contiguous
    out = np.empty(len(queries), dtype=np.int64)
    lib.ntpu_dict_probe(
        queries.ctypes.data, len(queries),
        keys.ctypes.data, values.ctypes.data,
        n_shards, cap, max_probe,
        out.ctypes.data,
    )
    return out


def gear_hashes_native(data: bytes | np.ndarray) -> np.ndarray:
    """Per-position gear hashes (differential-test aid)."""
    lib = load()
    if lib is None:
        raise RuntimeError("libchunk_engine.so not built")
    arr = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.ascontiguousarray(data, dtype=np.uint8)
    )
    table = np.ascontiguousarray(gear.gear_table())
    out = np.empty(arr.size, dtype=np.uint32)
    lib.ntpu_gear_hashes(arr.ctypes.data, arr.size, table.ctypes.data, out.ctypes.data)
    return out
