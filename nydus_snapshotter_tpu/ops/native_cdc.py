"""ctypes bridge to the native chunk engine (native/chunk_engine).

The sequential gear chunker is the host arm of the hybrid conversion
engine: ctypes calls release the GIL, so a thread pool chunks many layer
streams concurrently while the TPU handles digest batches and dict probes.
Cut points are bit-identical to ops/cdc.py's resolution (differential-
tested in tests/test_chunk_engine.py).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from nydus_snapshotter_tpu.ops import cdc, gear

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_missing = False


def _lib_path() -> str:
    # Override hook for instrumented builds (e.g. the ASan/UBSan arm the
    # sanitizer tests load in a child process with libasan preloaded).
    override = os.environ.get("NTPU_CHUNK_ENGINE_SO")
    if override:
        return override
    return os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "native", "bin", "libchunk_engine.so"
    )


def _report_unbuildable(native_build) -> None:
    """Loud-load path: the on-disk failure memo makes repeat ensure_built
    calls degrade silently — including in processes that never ran the
    compile — so surface the memoized compiler error ONCE per process
    here, where the library is first found unusable."""
    import logging

    reason = native_build.failure_reason("libchunk_engine.so")
    if reason:
        logging.getLogger(__name__).warning(
            "libchunk_engine.so unbuildable (memoized compile failure; "
            "native arms disabled, Python lanes take over):\n%s",
            reason,
        )


def load() -> Optional[ctypes.CDLL]:
    """The shared library; built (or rebuilt if sources changed) on first
    use per process via utils.native_build (atomic rename + on-disk
    failure memo). None when unbuildable — including when an EXISTING
    .so is stale against edited sources and the rebuild failed (loading it
    would silently diverge from the Python reference semantics)."""
    from nydus_snapshotter_tpu.utils import native_build

    global _lib, _lib_missing
    with _lib_lock:
        if _lib is not None or _lib_missing:
            return _lib
        path = _lib_path()
        if os.environ.get("NTPU_CHUNK_ENGINE_SO"):
            # Explicit artifact: the caller owns its build; the default
            # engine's build/staleness gating must not veto it.
            if not os.path.exists(path):
                _lib_missing = True
                return None
        else:
            built = native_build.ensure_built("libchunk_engine.so", "chunk_engine")
            if not os.path.exists(path) or (
                not built
                and native_build.sources_newer("libchunk_engine.so", "chunk_engine")
            ):
                _lib_missing = True
                _report_unbuildable(native_build)
                return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _lib_missing = True
            return None
        lib.ntpu_cdc_chunk.restype = ctypes.c_int64
        lib.ntpu_cdc_chunk.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,  # data, n
            ctypes.c_void_p,                  # table
            ctypes.c_uint32, ctypes.c_uint32,  # masks
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # min/normal/max
            ctypes.c_void_p, ctypes.c_int64,  # cuts_out, cap
        ]
        if hasattr(lib, "ntpu_cdc_chunk_vec"):
            lib.ntpu_cdc_chunk_vec.restype = ctypes.c_int64
            lib.ntpu_cdc_chunk_vec.argtypes = list(lib.ntpu_cdc_chunk.argtypes)
        if hasattr(lib, "ntpu_cdc_active_isa"):
            lib.ntpu_cdc_active_isa.restype = ctypes.c_int64
            lib.ntpu_cdc_active_isa.argtypes = []
        if hasattr(lib, "ntpu_encode_batch"):
            lib.ntpu_encode_batch.restype = ctypes.c_int64
            lib.ntpu_encode_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,  # data, extents, m
                ctypes.c_int64, ctypes.c_int64,  # level, n_threads
                ctypes.c_void_p, ctypes.c_int64,  # out, out_cap
                ctypes.c_void_p,  # comp_extents
                ctypes.c_void_p, ctypes.c_int64,  # digests_out (nullable), algo
            ]
        lib.ntpu_gear_hashes.restype = None
        lib.ntpu_gear_hashes.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p
        ]
        if hasattr(lib, "ntpu_dict_build"):
            lib.ntpu_dict_build.restype = ctypes.c_int64
            lib.ntpu_dict_build.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,  # digests, n
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # shards, cap, max_probe
                ctypes.c_void_p, ctypes.c_void_p,  # keys, values
            ]
        if hasattr(lib, "ntpu_dict_insert"):
            lib.ntpu_dict_insert.restype = ctypes.c_int64
            lib.ntpu_dict_insert.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,  # digests, vals, k
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # shards, cap, max_probe
                ctypes.c_void_p, ctypes.c_void_p,  # keys, values
            ]
        if hasattr(lib, "ntpu_dict_upsert"):
            lib.ntpu_dict_upsert.restype = ctypes.c_int64
            lib.ntpu_dict_upsert.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,  # digests, n, base
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # shards, cap, max_probe
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # keys, values, out
            ]
        if hasattr(lib, "ntpu_dict_probe"):
            lib.ntpu_dict_probe.restype = None
            lib.ntpu_dict_probe.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,  # queries, m
                ctypes.c_void_p, ctypes.c_void_p,  # keys, values
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # shards, cap, max_probe
                ctypes.c_void_p,  # out
            ]
        if hasattr(lib, "ntpu_chunk_digest"):
            lib.ntpu_chunk_digest.restype = ctypes.c_int64
            lib.ntpu_chunk_digest.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,  # data, n
                ctypes.c_uint32, ctypes.c_uint32,  # masks
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # min/normal/max
                ctypes.c_void_p, ctypes.c_int64,  # cuts_out, cap
                ctypes.c_void_p,  # digests_out (nullable)
                ctypes.c_int64,  # algo (0=sha256, 1=blake3)
            ]
        if hasattr(lib, "ntpu_sha256_many"):
            lib.ntpu_sha256_many.restype = None
            lib.ntpu_sha256_many.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,  # data, extents (i64 pairs)
                ctypes.c_int64, ctypes.c_void_p,   # m, digests_out
            ]
        if hasattr(lib, "ntpu_blake3_many"):
            lib.ntpu_blake3_many.restype = None
            lib.ntpu_blake3_many.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,  # data, extents (i64 pairs)
                ctypes.c_int64, ctypes.c_void_p,   # m, digests_out
            ]
        if hasattr(lib, "ntpu_chunk_digest_multi"):
            lib.ntpu_chunk_digest_multi.restype = ctypes.c_int64
            lib.ntpu_chunk_digest_multi.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,  # data, extents, m
                ctypes.c_uint32, ctypes.c_uint32,  # masks
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # min/normal/max
                ctypes.c_void_p,  # file_ncuts
                ctypes.c_void_p, ctypes.c_int64,  # cuts_out, cap
                ctypes.c_void_p,  # digests_out
                ctypes.c_int64,  # algo
            ]
        if hasattr(lib, "ntpu_pack_files"):
            lib.ntpu_pack_files.restype = ctypes.c_int64
            lib.ntpu_pack_files.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,  # data, n
                ctypes.c_void_p, ctypes.c_int64,  # extents, m
                ctypes.c_uint32, ctypes.c_uint32,  # masks
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # min/normal/max
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # comp, accel, threads
                ctypes.c_void_p,  # file_nchunks
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # digests, sizes, uniq
                ctypes.c_int64,  # refs_cap
                ctypes.c_void_p,  # comp_extents
                ctypes.c_void_p, ctypes.c_int64,  # out_blob, out_cap
                ctypes.c_void_p,  # blob_digest32
                ctypes.c_void_p, ctypes.c_void_p,  # n_uniq_out, blob_size_out
                ctypes.c_int64,  # algo
            ]
        if hasattr(lib, "ntpu_pack_section"):
            lib.ntpu_pack_section.restype = ctypes.c_int64
            lib.ntpu_pack_section.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,  # src0, src1
                ctypes.c_void_p, ctypes.c_int64,   # extents (i64 triples), m
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # comp, accel, threads
                ctypes.c_void_p, ctypes.c_int64,   # out, out_cap
                ctypes.c_void_p, ctypes.c_void_p,  # comp_extents, blob_digest32
            ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def chunk_data_native(data: bytes | np.ndarray, params: cdc.CDCParams) -> np.ndarray:
    """Cut offsets via the native chunker (drop-in for cdc.chunk_data_np)."""
    lib = load()
    if lib is None:
        raise RuntimeError("libchunk_engine.so not built (make -C nydus_snapshotter_tpu/native)")
    arr = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.ascontiguousarray(data, dtype=np.uint8)
    )
    if arr.size == 0:
        return np.asarray([], dtype=np.int64)
    table = np.ascontiguousarray(gear.gear_table())
    cap = arr.size // max(1, params.min_size) + 2
    cuts = np.empty(cap, dtype=np.int64)
    n = lib.ntpu_cdc_chunk(
        arr.ctypes.data, arr.size,
        table.ctypes.data,
        np.uint32(params.mask_small), np.uint32(params.mask_large),
        params.min_size, params.normal_size, params.max_size,
        cuts.ctypes.data, cap,
    )
    if n < 0:
        raise RuntimeError("native chunker cut buffer overflow")
    return cuts[:n].copy()


def vectorized_available() -> bool:
    """The striped table-scan arm (ntpu_cdc_chunk_vec)."""
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_cdc_chunk_vec")


def cdc_active_isa() -> int:
    """Which table-scan arm ntpu_cdc_chunk_vec dispatches to on this
    host + env (2 = AVX2 striped, 1 = portable scalar; 0 = library or
    symbol absent). Differential tests assert on this, not on
    NTPU_CDC_FORCE_ISA — forcing avx2 on a non-AVX2 host silently falls
    back to scalar."""
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_cdc_active_isa"):
        return 0
    return int(lib.ntpu_cdc_active_isa())


def forced_isa() -> str:
    """NTPU_CDC_FORCE_ISA as the native kernel will see it ("avx2" /
    "scalar" / "" = host dispatch). The C++ side memoizes the env read
    at first dispatch, so flipping it mid-process has no effect —
    differential tests pin it in a child process and assert on
    cdc_active_isa() there."""
    return os.environ.get("NTPU_CDC_FORCE_ISA", "")


def chunk_data_vec_native(
    data: bytes | np.ndarray, params: cdc.CDCParams
) -> np.ndarray:
    """Cut offsets via the VECTORIZED table scanner — cut-identical to
    chunk_data_native / cdc.chunk_sequential_reference by construction
    (position-exact whole-stream candidate bitmaps resolved with the
    shared region discipline; differential-proven in
    tests/test_chunk_engine.py, resonance corpora included)."""
    from nydus_snapshotter_tpu import failpoint

    lib = load()
    if lib is None or not hasattr(lib, "ntpu_cdc_chunk_vec"):
        raise RuntimeError(
            "vectorized chunker not available "
            "(make -C nydus_snapshotter_tpu/native)"
        )
    failpoint.hit("chunk.vec")
    arr = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.ascontiguousarray(data, dtype=np.uint8)
    )
    if arr.size == 0:
        return np.asarray([], dtype=np.int64)
    table = np.ascontiguousarray(gear.gear_table())
    cap = arr.size // max(1, params.min_size) + 2
    cuts = np.empty(cap, dtype=np.int64)
    n = lib.ntpu_cdc_chunk_vec(
        arr.ctypes.data, arr.size,
        table.ctypes.data,
        np.uint32(params.mask_small), np.uint32(params.mask_large),
        params.min_size, params.normal_size, params.max_size,
        cuts.ctypes.data, cap,
    )
    if n < 0:
        raise RuntimeError("native vectorized chunker failed (overflow or OOM)")
    return cuts[:n].copy()


def vectorized_mode() -> str:
    """The ``[compression] vectorized`` knob: ``NTPU_COMPRESS_VECTORIZED``
    env > global config > ``"auto"``. auto = vectorized scan when built,
    on = require it, off = always sequential."""
    v = os.environ.get("NTPU_COMPRESS_VECTORIZED", "").strip().lower()
    if v in ("auto", "on", "off"):
        return v
    try:
        from nydus_snapshotter_tpu.config import config as _cfg

        mode = getattr(_cfg.get_global_config().compression, "vectorized", "auto")
    except Exception:
        return "auto"
    return mode if mode in ("auto", "on", "off") else "auto"


def chunk_data_best(data: bytes | np.ndarray, params: cdc.CDCParams) -> np.ndarray:
    """The hybrid backend's scan dispatch: the vectorized table scanner
    when the ``vectorized`` knob allows it and the arm is built, else the
    sequential native chunker — cut-identical either way. ``on`` without
    the arm fails loudly instead of silently degrading throughput."""
    mode = vectorized_mode()
    if mode != "off" and vectorized_available():
        return chunk_data_vec_native(data, params)
    if mode == "on":
        raise RuntimeError(
            "[compression] vectorized = on but ntpu_cdc_chunk_vec is not "
            "available (rebuild native/chunk_engine)"
        )
    return chunk_data_native(data, params)


def concat_extents(views) -> "tuple[np.ndarray, np.ndarray]":
    """Concatenate chunk views into the (buf u8, extents i64[m, 2]) pair
    the batch entry points take. One copy per chunk — the price of a
    single GIL-released native call over m independent chunks."""
    ext = np.empty((len(views), 2), dtype=np.int64)
    buf = np.empty(sum(len(v) for v in views), dtype=np.uint8)
    off = 0
    for k, v in enumerate(views):
        a = np.frombuffer(v, dtype=np.uint8)
        buf[off : off + a.size] = a
        ext[k, 0], ext[k, 1] = off, a.size
        off += a.size
    return buf, ext


def encode_batch_available() -> bool:
    """The batched per-chunk zstd encode arm (ntpu_encode_batch)."""
    from nydus_snapshotter_tpu.utils import zstd as zstd_native

    lib = load()
    return (
        lib is not None
        and hasattr(lib, "ntpu_encode_batch")
        and zstd_native.available()  # same dlopen'd system library
    )


def encode_batch_native(
    data: np.ndarray,
    extents: np.ndarray,
    level: int,
    n_threads: int = 1,
    digester: "str | None" = None,
) -> "tuple[np.ndarray, np.ndarray, bytes] | None":
    """m independent per-chunk zstd frames in ONE GIL-released call.

    extents: i64[m, 2] of (off, size) into data. Returns (payloads u8
    view of the packed frames, comp_extents i64[m, 2] of (coff, csize),
    digests bytes — 32*m of the UNCOMPRESSED chunks when ``digester``
    ("sha256"/"blake3") is set, else b""). Each frame is byte-identical
    to utils.zstd.compress_with_ctx at the same level (the codec
    engine's per-chunk lane), so batched and per-chunk paths cannot
    diverge. None when the native arm cannot run (library or system
    libzstd absent) — callers fall back to the per-chunk loop.
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_encode_batch"):
        return None
    arr = np.ascontiguousarray(data, dtype=np.uint8)
    ext = np.ascontiguousarray(extents, dtype=np.int64)
    m = ext.shape[0]
    if m == 0:
        return np.empty(0, np.uint8), np.empty((0, 2), np.int64), b""
    cap = _comp_bound_total(int(ext[:, 1].sum()), m, 2)
    out = np.empty(max(cap, 1), dtype=np.uint8)
    comp = np.empty((m, 2), dtype=np.int64)
    digests = (
        np.empty(m * 32, dtype=np.uint8) if digester is not None else None
    )
    total = lib.ntpu_encode_batch(
        arr.ctypes.data, ext.ctypes.data, m,
        level, max(1, n_threads),
        out.ctypes.data, out.size,
        comp.ctypes.data,
        digests.ctypes.data if digests is not None else None,
        DIGEST_ALGO[digester] if digester is not None else 0,
    )
    if total == -2:
        return None  # system libzstd absent: per-chunk Python path takes over
    if total < 0:
        raise RuntimeError("native batch encode failed (overflow or codec error)")
    return (
        out[:total],
        comp,
        digests.tobytes() if digests is not None else b"",
    )


def chunk_digest_available() -> bool:
    """The fused single-pass chunk+digest arm (SIMD bitmaps + SHA-NI)."""
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_chunk_digest")


DIGEST_ALGO = {"sha256": 0, "blake3": 1}


def chunk_digest_native(
    data: bytes | np.ndarray,
    params: cdc.CDCParams,
    want_digests: bool = True,
    digester: str = "sha256",
) -> tuple[np.ndarray, bytes]:
    """One native pass: cut offsets + per-chunk digests.

    The fused host arm — AVX2 position-parallel gear candidate bitmaps
    (the TPU kernel's log-doubling identity on host SIMD), bitmap cut
    resolution, then digests while the bytes are cache-warm. Cut points
    are bit-identical to chunk_data_native / cdc.chunk_data_np
    (differential-tested); ``digester`` picks the digest algorithm —
    "sha256" (SHA-NI batch) or "blake3" (8-way AVX2 leaves, the real
    toolchain's default). Uses the gear-v2 table only (mix32 inline).
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_chunk_digest"):
        raise RuntimeError("fused chunk+digest not available in libchunk_engine.so")
    arr = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.ascontiguousarray(data, dtype=np.uint8)
    )
    if arr.size == 0:
        return np.asarray([], dtype=np.int64), b""
    cap = arr.size // max(1, params.min_size) + 2
    cuts = np.empty(cap, dtype=np.int64)
    digests = np.empty(cap * 32, dtype=np.uint8) if want_digests else None
    n = lib.ntpu_chunk_digest(
        arr.ctypes.data, arr.size,
        np.uint32(params.mask_small), np.uint32(params.mask_large),
        params.min_size, params.normal_size, params.max_size,
        cuts.ctypes.data, cap,
        digests.ctypes.data if digests is not None else None,
        DIGEST_ALGO[digester],
    )
    if n < 0:
        raise RuntimeError("native fused chunker failed (cut overflow or OOM)")
    return (
        cuts[:n].copy(),
        digests[: n * 32].tobytes() if digests is not None else b"",
    )


def chunk_digest_multi_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_chunk_digest_multi")


def chunk_digest_multi(
    data: np.ndarray, extents: np.ndarray, params: cdc.CDCParams,
    digester: str = "sha256",
) -> "tuple[np.ndarray, np.ndarray, bytes]":
    """Fused chunk+digest over m (off, size) file extents in ONE native
    call (one FFI round trip / GIL drop per layer instead of per file).

    Returns (file_ncuts i64[m], cuts i64[total] file-relative exclusive
    ends concatenated in file order, digests bytes 32*total). Cut points
    and digests are bit-identical to per-file chunk_digest_native calls.
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_chunk_digest_multi"):
        raise RuntimeError("ntpu_chunk_digest_multi not available")
    arr = np.ascontiguousarray(data, dtype=np.uint8)
    ext = np.ascontiguousarray(extents, dtype=np.int64)
    m = ext.shape[0]
    if m == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64), b""
    cap = int((ext[:, 1] // max(1, params.min_size)).sum()) + 2 * m
    file_ncuts = np.empty(m, dtype=np.int64)
    cuts = np.empty(cap, dtype=np.int64)
    digests = np.empty(cap * 32, dtype=np.uint8)
    total = lib.ntpu_chunk_digest_multi(
        arr.ctypes.data, ext.ctypes.data, m,
        np.uint32(params.mask_small), np.uint32(params.mask_large),
        params.min_size, params.normal_size, params.max_size,
        file_ncuts.ctypes.data, cuts.ctypes.data, cap, digests.ctypes.data,
        DIGEST_ALGO[digester],
    )
    if total < 0:
        raise RuntimeError("native multi chunk+digest failed (overflow or OOM)")
    return file_ncuts, cuts[:total], digests[: total * 32].tobytes()


def sha256_many_native(data: np.ndarray, extents: np.ndarray) -> bytes:
    """SHA-256 of m (offset, size) extents of data in one GIL-dropping call.

    extents: i64[m, 2]. Returns 32*m digest bytes (SHA-NI when the CPU has
    it, scalar otherwise — always standard SHA-256).
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_sha256_many"):
        raise RuntimeError("ntpu_sha256_many not available in libchunk_engine.so")
    arr = np.ascontiguousarray(data, dtype=np.uint8)
    ext = np.ascontiguousarray(extents, dtype=np.int64)
    m = ext.shape[0] if ext.ndim == 2 else len(ext) // 2
    out = np.empty(m * 32, dtype=np.uint8)
    lib.ntpu_sha256_many(arr.ctypes.data, ext.ctypes.data, m, out.ctypes.data)
    return out.tobytes()


def blake3_many_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_blake3_many")


def blake3_many_native(data: np.ndarray, extents: np.ndarray) -> bytes:
    """BLAKE3 of m (offset, size) extents in one GIL-dropping call.

    The chunk digester for real-image dedup: the reference toolchain's
    default chunk digests are blake3 (RafsSuperFlags HASH_BLAKE3 on both
    committed fixtures), so chunk-dict content hits against real nydus
    images need blake3 digests at pack time. Differential oracle:
    utils/blake3.py (tests/test_blake3_digester.py).
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_blake3_many"):
        raise RuntimeError("ntpu_blake3_many not available in libchunk_engine.so")
    arr = np.ascontiguousarray(data, dtype=np.uint8)
    ext = np.ascontiguousarray(extents, dtype=np.int64)
    m = ext.shape[0] if ext.ndim == 2 else len(ext) // 2
    out = np.empty(m * 32, dtype=np.uint8)
    lib.ntpu_blake3_many(arr.ctypes.data, ext.ctypes.data, m, out.ctypes.data)
    return out.tobytes()


def _comp_bound_total(total_bytes: int, n_chunks: int, compressor: int) -> int:
    """Worst-case section size for n_chunks chunks summing total_bytes.

    Must dominate the native arm's per-chunk bound: lz4 n + n/255 + 16;
    zstd ZSTD_compressBound = n + n/256 + small (≤ 64 B lowmem margin) —
    over-provisioned here as n/128 + 128 per chunk against version drift.
    """
    if compressor == 1:
        return total_bytes + total_bytes // 255 + 16 * n_chunks
    if compressor == 2:
        return total_bytes + total_bytes // 128 + 128 * n_chunks
    return total_bytes


def pack_files_available() -> bool:
    """The whole-layer fused pack arm (chunk+digest+dedup+assemble)."""
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_pack_files")


def pack_files(
    data: np.ndarray,
    extents: np.ndarray,
    params: cdc.CDCParams,
    compressor: int,
    accel: int = 1,
    n_threads: int = 1,
    digester: str = "sha256",
):
    """One native pass over a layer's planned file extents: CDC chunking,
    per-chunk digests (``digester``: sha256 or blake3), first-wins dedup,
    per-unique compression, blob assembly, blob SHA-256 (the
    `nydus-image create` hot loop in one call; the blob ID stays SHA-256
    whatever the chunk digester). Returns None when the arm cannot run (library/liblz4 absent);
    else a dict with file_nchunks, digests, chunk_sizes, chunk_uniq,
    uniq_sizes, comp_extents, blob (np view), blob_digest. Per-chunk and
    blob bytes are bit-identical to the per-stage lanes.
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_pack_files"):
        return None
    arr = np.ascontiguousarray(data, dtype=np.uint8)
    ext = np.ascontiguousarray(extents, dtype=np.int64)
    m = ext.shape[0]
    if m == 0:
        import hashlib

        return {
            "file_nchunks": np.zeros(0, np.int64),
            "digests": b"",
            "chunk_sizes": np.zeros(0, np.int64),
            "chunk_uniq": np.zeros(0, np.int64),
            "uniq_sizes": np.zeros(0, np.int64),
            "comp_extents": np.zeros((0, 2), np.int64),
            "blob": np.zeros(0, np.uint8),
            # same contract as the separable lanes: digest of the empty blob
            "blob_digest": hashlib.sha256(b"").digest(),
        }
    sizes = ext[:, 1]
    refs_cap = int((sizes // max(1, params.min_size)).sum()) + 2 * m
    total_bytes = int(sizes.sum())
    out_cap = _comp_bound_total(total_bytes, refs_cap, compressor)
    file_nchunks = np.empty(m, np.int64)
    digests = np.empty(refs_cap * 32, np.uint8)
    chunk_sizes = np.empty(refs_cap, np.int64)
    chunk_uniq = np.empty(refs_cap, np.int64)
    comp = np.empty((refs_cap, 2), np.int64)
    blob = np.empty(max(out_cap, 1), np.uint8)
    blob_digest = np.empty(32, np.uint8)
    n_uniq = np.zeros(1, np.int64)
    blob_size = np.zeros(1, np.int64)
    total = lib.ntpu_pack_files(
        arr.ctypes.data, arr.size,
        ext.ctypes.data, m,
        np.uint32(params.mask_small), np.uint32(params.mask_large),
        params.min_size, params.normal_size, params.max_size,
        compressor, accel, max(1, n_threads),
        file_nchunks.ctypes.data,
        digests.ctypes.data, chunk_sizes.ctypes.data, chunk_uniq.ctypes.data,
        refs_cap,
        comp.ctypes.data,
        blob.ctypes.data, blob.size,
        blob_digest.ctypes.data,
        n_uniq.ctypes.data, blob_size.ctypes.data,
        DIGEST_ALGO[digester],
    )
    if total == -2:
        return None
    if total < 0:
        raise RuntimeError("native pack_files failed (overflow or OOM)")
    nu = int(n_uniq[0])
    uniq_first = np.zeros(nu, dtype=np.int64)
    # first-wins: walking refs backward records each unique's FIRST ref
    uniq_first[chunk_uniq[:total][::-1]] = np.arange(total - 1, -1, -1)
    return {
        "file_nchunks": file_nchunks,
        "digests": digests[: total * 32].tobytes(),
        "chunk_sizes": chunk_sizes[:total],
        "chunk_uniq": chunk_uniq[:total],
        "uniq_sizes": chunk_sizes[:total][uniq_first],
        "comp_extents": comp[:nu],
        "blob": blob[: int(blob_size[0])],
        "blob_digest": blob_digest.tobytes(),
    }


def pack_section_available() -> bool:
    """The fused blob-section assembly arm (compress + append + hash)."""
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_pack_section")


def pack_section(
    src0: np.ndarray,
    src1: np.ndarray,
    extents: np.ndarray,
    compressor: int,
    accel: int = 1,
    n_threads: int = 1,
) -> "tuple[np.ndarray, np.ndarray, bytes] | None":
    """Assemble the blob data section in one native pass.

    extents: i64[m, 3] of (src, off, size) — src 0 slices src0 (the tar
    buffer, zero-copy), src 1 slices src1 (staged loose bytes).
    compressor: 0 = store raw, 1 = LZ4 block (accel 1 == liblz4 default
    output, byte-identical to utils.lz4.compress_block), 2 = zstd (accel
    carries the LEVEL — pass constants.ZSTD_LEVEL; byte-identical to the
    utils.zstd system-libzstd lane at the same level). Returns
    (section_bytes, comp_extents i64[m, 2] of (coff, csize),
    sha256_of_section) — or None when the native arm cannot run
    (library/liblz4/libzstd missing), in which case the caller uses its Python
    codec loop; both paths produce identical bytes.
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_pack_section"):
        return None
    ext = np.ascontiguousarray(extents, dtype=np.int64)
    m = ext.shape[0]
    if m == 0:
        return np.empty(0, dtype=np.uint8), np.empty((0, 2), dtype=np.int64), b""
    sizes = ext[:, 2]
    cap = _comp_bound_total(int(sizes.sum()), m, compressor)
    out = np.empty(max(cap, 1), dtype=np.uint8)
    comp = np.empty((m, 2), dtype=np.int64)
    digest = np.empty(32, dtype=np.uint8)
    total = lib.ntpu_pack_section(
        src0.ctypes.data if src0.size else None,
        src1.ctypes.data if src1.size else None,
        ext.ctypes.data, m,
        compressor, accel, max(1, n_threads),
        out.ctypes.data, out.size,
        comp.ctypes.data, digest.ctypes.data,
    )
    if total == -2:
        return None  # system codec library absent: Python path takes over
    if total < 0:
        raise RuntimeError("native pack_section failed (overflow or OOM)")
    return out[:total], comp, digest.tobytes()


def dict_build_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_dict_build")


def dict_build_native(
    digests: np.ndarray, n_shards: int, cap: int, max_probe: int,
    keys: np.ndarray, values: np.ndarray,
) -> bool:
    """Sequential first-wins table build into caller-zeroed keys/values.

    Returns False when a probe chain overflowed max_probe (grow cap and
    retry). Arrays must be C-contiguous with the documented dtypes.
    """
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_dict_build"):
        raise RuntimeError("libchunk_engine.so not built or too old")
    assert digests.dtype == np.uint32 and digests.flags.c_contiguous
    assert keys.dtype == np.uint32 and keys.flags.c_contiguous
    assert values.dtype == np.int32 and values.flags.c_contiguous
    rc = lib.ntpu_dict_build(
        digests.ctypes.data, len(digests), n_shards, cap, max_probe,
        keys.ctypes.data, values.ctypes.data,
    )
    return rc == 0


def dict_insert_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_dict_insert")


def dict_insert_native(
    digests: np.ndarray, values_i32: np.ndarray,
    n_shards: int, cap: int, max_probe: int,
    keys: np.ndarray, values: np.ndarray,
) -> int:
    """Incremental insert of unique absent digests with explicit stored
    values (+1 form) into a built table — the insert-proportional growth
    arm (cost O(batch), never O(table)). Returns the deepest chain
    reached, or -1 on a max_probe overflow (caller rebuilds)."""
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_dict_insert"):
        raise RuntimeError("libchunk_engine.so not built or too old")
    assert digests.dtype == np.uint32 and digests.flags.c_contiguous
    assert values_i32.dtype == np.int32 and values_i32.flags.c_contiguous
    assert keys.dtype == np.uint32 and keys.flags.c_contiguous
    assert values.dtype == np.int32 and values.flags.c_contiguous
    return int(
        lib.ntpu_dict_insert(
            digests.ctypes.data, values_i32.ctypes.data, len(digests),
            n_shards, cap, max_probe,
            keys.ctypes.data, values.ctypes.data,
        )
    )


def dict_upsert_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_dict_upsert")


def dict_upsert_native(
    digests: np.ndarray, base: int,
    n_shards: int, cap: int, max_probe: int,
    keys: np.ndarray, values: np.ndarray,
) -> "tuple[int, int, np.ndarray] | None":
    """Fused probe-or-insert of a whole batch in one sequential pass:
    returns (depth, n_new, indices i64[n]) or None on chain overflow
    (the placed prefix carries final values — semantically idempotent,
    the caller's fallback sees those entries as ordinary hits)."""
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_dict_upsert"):
        raise RuntimeError("libchunk_engine.so not built or too old")
    assert digests.dtype == np.uint32 and digests.flags.c_contiguous
    assert keys.dtype == np.uint32 and keys.flags.c_contiguous
    assert values.dtype == np.int32 and values.flags.c_contiguous
    out = np.empty(len(digests), dtype=np.int64)
    rc = int(
        lib.ntpu_dict_upsert(
            digests.ctypes.data, len(digests), base,
            n_shards, cap, max_probe,
            keys.ctypes.data, values.ctypes.data, out.ctypes.data,
        )
    )
    if rc < 0:
        return None
    return rc >> 32, rc & 0xFFFFFFFF, out


def dict_probe_available() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "ntpu_dict_probe")


def dict_probe_native(
    queries: np.ndarray, keys: np.ndarray, values: np.ndarray,
    n_shards: int, cap: int, max_probe: int,
) -> np.ndarray:
    """Probe u32[M,8] queries against a built table -> i64[M] dict indices
    (-1 = miss). The single-node latency arm of the dedup probe: XLA TPU
    gathers are element-serial (~1 µs/element measured), so the host wins
    until the dict is sharded across chips."""
    lib = load()
    if lib is None or not hasattr(lib, "ntpu_dict_probe"):
        raise RuntimeError("libchunk_engine.so not built or too old")
    queries = np.ascontiguousarray(queries, dtype=np.uint32)
    assert keys.dtype == np.uint32 and keys.flags.c_contiguous
    assert values.dtype == np.int32 and values.flags.c_contiguous
    out = np.empty(len(queries), dtype=np.int64)
    lib.ntpu_dict_probe(
        queries.ctypes.data, len(queries),
        keys.ctypes.data, values.ctypes.data,
        n_shards, cap, max_probe,
        out.ctypes.data,
    )
    return out


def gear_hashes_native(data: bytes | np.ndarray) -> np.ndarray:
    """Per-position gear hashes (differential-test aid)."""
    lib = load()
    if lib is None:
        raise RuntimeError("libchunk_engine.so not built")
    arr = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.ascontiguousarray(data, dtype=np.uint8)
    )
    table = np.ascontiguousarray(gear.gear_table())
    out = np.empty(arr.size, dtype=np.uint32)
    lib.ntpu_gear_hashes(arr.ctypes.data, arr.size, table.ctypes.data, out.ctypes.data)
    return out
