"""BLAKE3 on device, vmapped across chunks and leaves.

The reference toolchain's default chunk digester is blake3 (RafsSuperFlags
HASH_BLAKE3 — what real nydus images carry), and unlike SHA-256 the
algorithm is tree-structured, which is exactly what wide vector hardware
wants: every 1024-byte leaf chunk compresses independently (massively
parallel across lanes), and the binary tree above them merges in
log2(leaves) fully-vectorized levels. Where the device SHA-256 scan is
serial in a message's 64-byte blocks, device blake3 is serial only in the
16 blocks WITHIN a leaf — a 1 MiB chunk exposes 1024-way parallelism per
message on top of the batch axis.

Shape discipline: one message = ``u32[C, 16, 16]`` little-endian words
(C leaves × 16 blocks × 16 words; C power-of-two capacity class), plus its
byte length. A batch is ``u32[M, C, 16, 16]`` + ``i32[M]`` lengths.
Phase 1 scans the 16 in-leaf blocks with ``vmap`` over (M, C) lanes;
phase 2 runs log2(C) parent-merge levels, each a masked pairwise compress
over the live width ("pair adjacent, odd lane promotes" — provably the
same shape as the spec's largest-power-of-two-left-subtree rule).

Flags (CHUNK_START/CHUNK_END/ROOT/PARENT) are plain u32 lane inputs
selected with ``jnp.where``, so single-leaf ROOT finalization and ragged
tails vectorize with no control flow. The compression counter is the leaf
index (u32 lanes: TPU has no u64; fine below 4 TiB messages).

Differential oracle: utils/blake3.py (the pure-Python spec implementation
validated against the committed real-fixture digests) —
tests/test_blake3_jax.py.

Reference correspondence: chunk digests inside the Rust builder
(`nydus-image create --digester blake3`), pkg/converter/tool/builder.go
surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_IV = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)

CHUNK_START = np.uint32(1 << 0)
CHUNK_END = np.uint32(1 << 1)
PARENT = np.uint32(1 << 2)
ROOT = np.uint32(1 << 3)

_PERM = [2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8]
# _SCHED[r][i] = index into the ORIGINAL block of the word G-round r uses
# at position i (round-0 identity, then PERM composed r times) — static
# indices, so the 7 rounds unroll with no traced gather.
_SCHED = [list(range(16))]
for _ in range(6):
    _SCHED.append([_SCHED[-1][p] for p in _PERM])

LEAF_BYTES = 1024
_BLOCKS_PER_LEAF = 16


def _rotr(x, r):
    return (x >> np.uint32(r)) | (x << np.uint32(32 - r))


def _g(a, b, c, d, mx, my):
    a = a + b + mx
    d = _rotr(d ^ a, 16)
    c = c + d
    b = _rotr(b ^ c, 12)
    a = a + b + my
    d = _rotr(d ^ a, 8)
    c = c + d
    b = _rotr(b ^ c, 7)
    return a, b, c, d


def _init_v(cv, counter, block_len, flags):
    return list(cv) + [
        jnp.broadcast_to(jnp.uint32(_IV[0]), counter.shape),
        jnp.broadcast_to(jnp.uint32(_IV[1]), counter.shape),
        jnp.broadcast_to(jnp.uint32(_IV[2]), counter.shape),
        jnp.broadcast_to(jnp.uint32(_IV[3]), counter.shape),
        counter,
        jnp.zeros_like(counter),  # counter high word: leaf index < 2^32
        block_len,
        flags,
    ]


def _round(v, w):
    """One BLAKE3 round: v list of 16 lanes, w(i) -> message word i
    (already schedule-permuted)."""
    v[0], v[4], v[8], v[12] = _g(v[0], v[4], v[8], v[12], w(0), w(1))
    v[1], v[5], v[9], v[13] = _g(v[1], v[5], v[9], v[13], w(2), w(3))
    v[2], v[6], v[10], v[14] = _g(v[2], v[6], v[10], v[14], w(4), w(5))
    v[3], v[7], v[11], v[15] = _g(v[3], v[7], v[11], v[15], w(6), w(7))
    v[0], v[5], v[10], v[15] = _g(v[0], v[5], v[10], v[15], w(8), w(9))
    v[1], v[6], v[11], v[12] = _g(v[1], v[6], v[11], v[12], w(10), w(11))
    v[2], v[7], v[8], v[13] = _g(v[2], v[7], v[8], v[13], w(12), w(13))
    v[3], v[4], v[9], v[14] = _g(v[3], v[4], v[9], v[14], w(14), w(15))
    return v


def _compress(cv, m, counter, block_len, flags, unroll=True):
    """One BLAKE3 compression over u32 lanes.

    cv: tuple of 8 u32 arrays; m: tuple of 16 u32 arrays; counter /
    block_len / flags: u32 arrays (broadcast). Returns the 8-word output
    chaining value (v[0:8] ^ v[8:16]).

    unroll=True: 7 rounds × 8 G mixes ≈ 450 elementwise ops flat — XLA
    TPU fuses them into a few wide vector kernels per block step.
    unroll=False: rounds in a fori_loop with the schedule as a traced
    gather — the XLA CPU backend (the interpret/differential arm) chokes
    on deep unrolled chains, same story as ops/sha256._compress_looped.
    """
    v = _init_v(cv, counter, block_len, flags)

    if unroll:
        for r in range(7):
            s = _SCHED[r]
            v = _round(v, lambda i, s=s: m[s[i]])
        return tuple(v[i] ^ v[i + 8] for i in range(8))

    sched = jnp.asarray(np.array(_SCHED, dtype=np.int32))
    mm = jnp.stack(m)

    def round_fn(r, v):
        s = sched[r]
        return tuple(_round(list(v), lambda i: mm[s[i]]))

    v = jax.lax.fori_loop(0, 7, round_fn, tuple(v))
    return tuple(v[i] ^ v[i + 8] for i in range(8))


def _leaf_cv(blocks, leaf_idx, msg_len, single_leaf, unroll=True):
    """CV of one leaf: blocks u32[16,16], scalars leaf_idx/msg_len (i32),
    single_leaf bool. Lanes whose leaf starts past msg_len produce garbage
    (masked by the tree phase)."""
    start = leaf_idx * LEAF_BYTES
    # bytes in this leaf: clamp(msg_len - start, 0, 1024); empty message
    # still processes one zero block in leaf 0.
    leaf_len = jnp.clip(msg_len - start, 0, LEAF_BYTES)
    nblocks = jnp.maximum((leaf_len + 63) // 64, 1)

    def step(carry, xs):
        cv = carry
        block_words, j = xs
        blen = jnp.clip(leaf_len - j * 64, 0, 64).astype(jnp.uint32)
        flags = jnp.uint32(0)
        flags = jnp.where(j == 0, flags | CHUNK_START, flags)
        last = j == nblocks - 1
        flags = jnp.where(last, flags | CHUNK_END, flags)
        flags = jnp.where(last & single_leaf, flags | ROOT, flags)
        m = tuple(block_words[i] for i in range(16))
        new = _compress(cv, m, leaf_idx.astype(jnp.uint32), blen, flags, unroll)
        keep = j < nblocks
        return tuple(jnp.where(keep, n, c) for n, c in zip(new, cv)), None

    init = tuple(jnp.uint32(_IV[i]) for i in range(8))
    idx = jnp.arange(_BLOCKS_PER_LEAF)
    cv, _ = jax.lax.scan(step, init, (blocks, idx))
    return jnp.stack(cv)


def _blake3_one(blocks, msg_len, unroll=True):
    """Digest one message: blocks u32[C,16,16], msg_len i32 -> u32[8]."""
    c = blocks.shape[0]
    n_leaves = jnp.maximum((msg_len + LEAF_BYTES - 1) // LEAF_BYTES, 1)
    leaf_ids = jnp.arange(c)
    cvs = jax.vmap(
        lambda b, i: _leaf_cv(b, i, msg_len, n_leaves == 1, unroll)
    )(blocks, leaf_ids)  # u32[C, 8]

    # Tree phase: "pair adjacent, odd lane promotes" — identical shape to
    # the spec's largest-power-of-two-left-subtree rule. Static halving of
    # the width; per-message live count k masks the ragged tail. ROOT goes
    # on the lane-0 merge when exactly two subtrees remain.
    k = n_leaves
    width = c
    while width > 1:
        half = width // 2
        left = cvs[0::2]  # u32[half(+1), 8] — even lanes
        right = cvs[1::2]  # u32[half, 8]    — odd lanes
        left = left[:half]
        lane = jnp.arange(half)
        is_root = (lane == 0) & (k == 2)
        flags = jnp.where(is_root, PARENT | ROOT, PARENT)
        merged = jax.vmap(
            lambda l, r, f: jnp.stack(
                _compress(
                    tuple(jnp.uint32(_IV[i]) for i in range(8)),
                    tuple(l[i] for i in range(8)) + tuple(r[i] for i in range(8)),
                    jnp.uint32(0),
                    jnp.uint32(64),
                    f,
                    unroll,
                )
            )
        )(left, right, flags)
        # odd count at this level: the dangling last subtree promotes
        has_pair = (2 * lane + 1) < k
        cvs = jnp.where(has_pair[:, None], merged, left)
        k = (k + 1) // 2
        width = half
    return cvs[0]


@functools.partial(jax.jit, static_argnames=("unroll",))
def _blake3_batch_jit(blocks: jax.Array, lengths: jax.Array, unroll: bool) -> jax.Array:
    return jax.vmap(functools.partial(_blake3_one, unroll=unroll))(blocks, lengths)


def blake3_batch(blocks: jax.Array, lengths: jax.Array) -> jax.Array:
    """Digest a batch: blocks u32[M,C,16,16] LE words, lengths i32[M]
    -> u32[M,8] little-endian digest words. The unrolled compress is for
    the TPU backend; XLA CPU gets the fori_loop arm (compile-hostile
    chains, same split as ops/sha256.sha256_batch)."""
    unroll = jax.default_backend() != "cpu"
    return _blake3_batch_jit(blocks, lengths, unroll)


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def n_leaves(length: int) -> int:
    """Leaf count of a message (≥ 1: the empty message is one leaf)."""
    return max((length + LEAF_BYTES - 1) // LEAF_BYTES, 1)


def pack_messages_np(
    msgs: list[bytes | np.ndarray], leaf_capacity: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Pack messages into a fixed-shape batch (u32[M,C,16,16], i32[M])."""
    lengths = np.asarray([len(m) for m in msgs], dtype=np.int32)
    need = max((n_leaves(int(n)) for n in lengths), default=1)
    cap = leaf_capacity or need
    if len(msgs) and need > cap:
        raise ValueError(f"message needs {need} leaves > capacity {cap}")
    # Power-of-two width: the tree phase halves the lane array per level,
    # which requires even widths all the way down (an odd width would drop
    # its dangling even lane); pow2 also bounds distinct compiled shapes.
    cap = 1 << (cap - 1).bit_length() if cap > 1 else 1
    out = np.zeros((len(msgs), cap * LEAF_BYTES), dtype=np.uint8)
    for i, m in enumerate(msgs):
        src = m if isinstance(m, np.ndarray) else np.frombuffer(m, dtype=np.uint8)
        out[i, : lengths[i]] = src
    blocks = (
        out.view("<u4")
        .astype(np.uint32)
        .reshape(len(msgs), cap, _BLOCKS_PER_LEAF, 16)
    )
    return blocks, lengths


def digest_to_bytes(words: np.ndarray) -> bytes:
    """u32[8] digest words -> canonical 32-byte little-endian digest."""
    return np.asarray(words, dtype="<u4").tobytes()


def blake3_many(msgs: list[bytes]) -> list[bytes]:
    """Digest many messages on device; returns raw 32-byte digests."""
    if not msgs:
        return []
    blocks, lengths = pack_messages_np(msgs)
    words = np.asarray(
        jax.device_get(blake3_batch(jnp.asarray(blocks), jnp.asarray(lengths)))
    )
    return [digest_to_bytes(words[i]) for i in range(len(msgs))]
