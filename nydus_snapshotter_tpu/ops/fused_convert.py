"""Fused device full-path convert: gear → cuts → gather → digest → probe.

The composition the isolated kernel benchmarks don't prove: one device
program per phase, with only KILOBYTES of metadata crossing the host
boundary between them. The multi-GiB corpus is uploaded (or generated)
on device ONCE and never comes back:

- **Pass 1 (one jit dispatch).** Gear candidate bitmaps over the whole
  buffer (ops/gear_pallas on TPU, the XLA formulation elsewhere), then
  ON-DEVICE sparse compaction: word-level ``lax.population_count`` →
  ``nonzero`` over words → bit expansion. D2H is the candidate position
  list (~KBs at real mask densities), not the N/32-byte bitmaps.
- **Host middle (microseconds).** FastCDC cut resolution over the sparse
  candidates per file (ops/cdc.resolve_cuts — O(chunks·log cands)) and
  the bucket plan (power-of-two block-capacity classes, exact counts).
  Shipping cuts through the host costs two dispatch floors but buys
  EXACT static shapes for pass 2 — an on-device resolver would force
  worst-case (~16x padded) digest compute, which loses at any batch size.
- **Pass 2 (one jit dispatch).** Per bucket: ``lax.scan`` of
  ``dynamic_slice`` gathers (byte-exact chunk starts, so no realignment
  kernel), SHA-256 padding applied with iota masks on device, the
  measured ``sha256_batch`` scan, and the chunk-dict probe
  (parallel/sharded_dict._probe_local) over every digest. D2H is
  32 B/chunk of digests + 4 B/chunk of dict hits.

Why two dispatches and not one: the digest stage's shapes depend on the
resolved cuts. Keeping resolution on device would make bucket geometry
dynamic, forcing every chunk slot to the 4 MiB max class. At the axon
tunnel's measured ~125-145 ms dispatch floor, 2 dispatches on a multi-GiB
batch cost <15% of the 2.5 GiB/s/chip budget; on a real PCIe host the
floor is microseconds.

Replaces the one-process hot loop of the reference's ``nydus-image
create`` (chunk+digest+dedup inside pkg/converter/tool/builder.go:148-178;
the chunk-dict probe at builder.go:122-123).

Differential oracle: ChunkDigestEngine(backend="numpy") — the fused path
must produce byte-identical cuts and digests (tests/test_fused_convert.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from nydus_snapshotter_tpu.ops import cdc, gear, sha256

WINDOW = 1 << 22  # pass-1 hash window (matches ops/chunker.DEFAULT_WINDOW)
TAIL = gear.GEAR_WINDOW - 1


class FusedOverflow(RuntimeError):
    """Candidate compaction capacity exceeded (pathological input) —
    callers fall back to the windowed bitmap-download path."""


def _record_dispatch(n_bytes: int, pass1_s: float, host_s: float, pass2_s: float) -> None:
    """Fused-convert stage counters next to the pipeline's
    (ntpu_convert_pipeline_*): pass1 = gear+compaction dispatch, host =
    cut resolution + bucket plan (the host arm between dispatches),
    pass2 = gather+digest+probe dispatch."""
    from nydus_snapshotter_tpu.metrics import registry as _metrics

    reg = _metrics.default_registry
    disp = reg.register(
        _metrics.Counter(
            "ntpu_fused_convert_dispatches",
            "Fused device convert batches dispatched",
        )
    )
    by_bytes = reg.register(
        _metrics.Counter(
            "ntpu_fused_convert_bytes",
            "Bytes processed by fused device convert batches",
        )
    )
    busy = reg.register(
        _metrics.Counter(
            "ntpu_fused_convert_stage_seconds",
            "Wall seconds per fused-convert stage",
            ("stage",),
        )
    )
    disp.inc()
    by_bytes.inc(n_bytes)
    busy.labels("pass1_gear").inc(pass1_s)
    busy.labels("host_resolve").inc(host_s)
    busy.labels("pass2_digest").inc(pass2_s)


def _pow2_ceil(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


# ---------------------------------------------------------------------------
# Pass 1: gear bitmaps + on-device candidate compaction
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("mask_s", "mask_l", "wcap_s", "wcap_l")
)
def _pass1(
    buffer: jax.Array,  # u8[NP], NP % WINDOW == 0
    n: jax.Array,  # i32/i64 scalar: valid bytes
    mask_s: int,
    mask_l: int,
    wcap_s: int,
    wcap_l: int,
):
    """-> (sel_s i32[wcap_s], words_s u32[wcap_s], nw_s, … same for _l).

    sel_* are ascending candidate-WORD indices (sentinel: nwords) with
    their raw bitmap words; nw_* are the true candidate-word counts — a
    count > wcap means truncation (FusedOverflow on host).
    """
    npad = buffer.shape[0]
    b = npad // WINDOW
    # windows with 31-byte seam-carry tails (row i prefixed by the last
    # 31 bytes of row i-1; row 0 by zeros — positions < min_size are
    # never judged, so the zeros can't reach a resolved cut)
    main = buffer.reshape(b, WINDOW)
    tails = jnp.concatenate(
        [jnp.zeros((1, TAIL), jnp.uint8), main[:-1, WINDOW - TAIL :]], axis=0
    )
    rows = jnp.concatenate([tails, main], axis=1)  # u8[B, TAIL+WINDOW]

    from nydus_snapshotter_tpu.ops import gear_pallas

    if gear_pallas.supported(WINDOW):
        bm_s, bm_l = gear_pallas.gear_bitmaps(rows, mask_s, mask_l, WINDOW)
    else:
        from nydus_snapshotter_tpu.ops.chunker import _hash_bitmaps_kernel

        bm_s, bm_l = _hash_bitmaps_kernel(
            rows, jnp.uint32(mask_s), jnp.uint32(mask_l), WINDOW
        )

    nwords = npad // 32
    widx_valid = jnp.arange(nwords, dtype=jnp.int32) < (n + 31) // 32

    def compact(bm, wcap):
        # Word indices + raw words, NOT byte positions: word indices stay
        # well inside int32 for any addressable buffer (device ints are
        # 32-bit without x64), and the host expands bit positions in int64.
        words = bm.reshape(nwords)
        # zero whole words beyond the valid length (window padding would
        # otherwise flood the capacity with phantom candidates)
        words = jnp.where(widx_valid, words, jnp.uint32(0))
        pc = jax.lax.population_count(words)
        (sel,) = jnp.nonzero(pc > 0, size=wcap, fill_value=nwords)
        nw = jnp.sum((pc > 0).astype(jnp.int32))
        got = jnp.where(
            sel < nwords, words[jnp.minimum(sel, nwords - 1)], jnp.uint32(0)
        )  # u32[wcap]
        return sel.astype(jnp.int32), got, nw

    sel_s, got_s, nw_s = compact(bm_s, wcap_s)
    sel_l, got_l, nw_l = compact(bm_l, wcap_l)
    return sel_s, got_s, nw_s, sel_l, got_l, nw_l


def _wcap_for(n: int, density_bits: int, floor: int = 1024) -> int:
    """Static candidate-word capacity: 4x the expected count for a
    2^-density_bits per-position hit rate, floored."""
    expected = max(1, n >> density_bits)
    return _pow2_ceil(max(floor, 4 * expected))


# ---------------------------------------------------------------------------
# Pass 2: gather + SHA pack + digest + dict probe
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Bucket:
    """One power-of-two block-capacity class of the pass-2 plan.

    offsets/sizes are pow2-padded (padding rows have size 0 and offset 0
    and are discarded on assembly); ``count`` is the live prefix.
    """

    cap_blocks: int
    offsets: np.ndarray  # i32[M] absolute byte offsets into the buffer
    sizes: np.ndarray  # i32[M]
    count: int


def _gather_pack_sha(buffer: jax.Array, offs: jax.Array, sizes: jax.Array, cap_blocks: int):
    """Gather chunks at byte-exact offsets and emit SHA-padded blocks.

    One scan step per chunk: dynamic_slice (a contiguous DMA-shaped copy,
    not an element gather), zero/0x80 padding + big-endian word build +
    64-bit length words, all via iota masks. -> u32[M, cap_blocks, 16].
    """
    capb = cap_blocks * 64
    byte_iota = jnp.arange(capb, dtype=jnp.int32)
    word_iota = jnp.arange(capb // 4, dtype=jnp.int32)

    def step(carry, xs):
        off, size = xs
        raw = jax.lax.dynamic_slice(buffer, (off,), (capb,))
        padded = jnp.where(byte_iota < size, raw, jnp.uint8(0))
        padded = jnp.where(byte_iota == size, jnp.uint8(0x80), padded)
        w = padded.reshape(-1, 4).astype(jnp.uint32)
        words = (w[:, 0] << 24) | (w[:, 1] << 16) | (w[:, 2] << 8) | w[:, 3]
        nb = (size + 8) // 64 + 1  # n_padded_blocks
        hi = (size >> 29).astype(jnp.uint32)
        lo = size.astype(jnp.uint32) << 3
        words = jnp.where(word_iota == (nb - 1) * 16 + 14, hi, words)
        words = jnp.where(word_iota == (nb - 1) * 16 + 15, lo, words)
        return carry, words.reshape(cap_blocks, 16)

    _, blocks = jax.lax.scan(step, 0, (offs, sizes))
    return blocks


def _gather_pack_b3(buffer: jax.Array, offs: jax.Array, sizes: jax.Array, cap_leaves: int):
    """Gather chunks into the blake3 batch layout u32[M, C, 16, 16].

    Simpler than the SHA pack: zero beyond the message and build
    LITTLE-endian words (blake3's byte order); lengths drive the in-kernel
    flag/tail handling, so no padding bytes or length words are embedded.
    """
    from nydus_snapshotter_tpu.ops import blake3_jax

    capb = cap_leaves * blake3_jax.LEAF_BYTES
    byte_iota = jnp.arange(capb, dtype=jnp.int32)

    def step(carry, xs):
        off, size = xs
        raw = jax.lax.dynamic_slice(buffer, (off,), (capb,))
        b = jnp.where(byte_iota < size, raw, jnp.uint8(0))
        w = b.reshape(-1, 4).astype(jnp.uint32)
        words = w[:, 0] | (w[:, 1] << 8) | (w[:, 2] << 16) | (w[:, 3] << 24)
        return carry, words.reshape(cap_leaves, 16, 16)

    _, blocks = jax.lax.scan(step, 0, (offs, sizes))
    return blocks


@functools.partial(
    jax.jit,
    static_argnames=(
        "caps", "table_cap", "depth", "digester", "pallas_probe", "probe_interpret"
    ),
)
def _pass2(
    buffer: jax.Array,
    bucket_offs: tuple[jax.Array, ...],
    bucket_sizes: tuple[jax.Array, ...],
    caps: tuple[int, ...],
    table_keys: jax.Array | None = None,  # u32[C,8] (or u32[C+W,8] padded)
    table_vals: jax.Array | None = None,  # i32[C]   (or i32[C+W,1] padded)
    table_cap: int = 0,
    depth: int = 0,
    digester: str = "sha256",
    pallas_probe: bool = False,
    probe_interpret: bool = False,
):
    """-> (tuple of u32[M_i, 8] digest states, i32[sum M_i] probe or None).

    Digest states are u32 words in the digester's natural order (big-
    endian words for sha256, little-endian for blake3); chunk-dict keys
    must be built with the same convention.
    """
    unroll = jax.default_backend() != "cpu"
    states = []
    for offs, sizes, cap in zip(bucket_offs, bucket_sizes, caps):
        if digester == "blake3":
            from nydus_snapshotter_tpu.ops import blake3_jax

            blocks = _gather_pack_b3(buffer, offs, sizes, cap)
            states.append(blake3_jax._blake3_batch_jit(blocks, sizes, unroll))
        else:
            blocks = _gather_pack_sha(buffer, offs, sizes, cap)
            counts = (sizes + 8) // 64 + 1
            states.append(sha256._sha256_batch_jit(blocks, counts, unroll))
    probe = None
    if table_keys is not None:
        allq = jnp.concatenate(states, axis=0)
        if pallas_probe:
            # DMA-pipelined Pallas probe (ops/probe_pallas): the XLA
            # gather formulation runs effectively element-serially on
            # TPU (~1 µs/element) — at full-batch chunk counts it would
            # dominate the dispatch. Tables arrive pre-padded wrap-free.
            from nydus_snapshotter_tpu.ops import probe_pallas

            slot0 = (allq[:, 1] & jnp.uint32(table_cap - 1)).astype(jnp.int32)
            wstart = slot0 & ~jnp.int32(7)
            probe = probe_pallas.probe_padded(
                table_keys, table_vals, allq, wstart, slot0 - wstart,
                depth, interpret=probe_interpret,
            )
        else:
            from nydus_snapshotter_tpu.parallel.sharded_dict import _probe_local

            probe = _probe_local(table_keys, table_vals, allq, table_cap, depth)
    return tuple(states), probe


# ---------------------------------------------------------------------------
# Host orchestration
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FusedResult:
    """Per-stream chunk extents/digests + optional dict-probe hits."""

    cuts: list[np.ndarray]  # per-stream exclusive cut ends
    digests: list[list[bytes]]  # per-stream raw 32-B sha256 digests
    probe: np.ndarray | None  # i32 over all chunks in stream order (0=miss)


class FusedDeviceEngine:
    """Full-path device convert for a batch of per-file streams.

    Mirrors ChunkDigestEngine.process_many semantics (per-file CDC with
    the engine's CDCParams, per-chunk sha256) but runs the whole batch as
    two device dispatches. ``chunk_dict`` (keys u32[C,8] / values i32[C],
    the sharded-dict single-shard layout) adds the dedup probe to pass 2.
    """

    def __init__(
        self,
        chunk_size: int = 0x100000,
        max_bucket_rows: int = 1 << 14,
        digester: str = "sha256",
    ):
        if digester not in ("sha256", "blake3"):
            raise ValueError(f"unknown digester {digester!r}")
        self.params = cdc.CDCParams(chunk_size)
        self.max_bucket_rows = max_bucket_rows
        self.digester = digester

    def _blocks_of(self, size: int) -> int:
        """Digest-layout capacity units of one chunk (SHA 64-B blocks or
        blake3 leaves) — the bucket-class axis."""
        if self.digester == "blake3":
            from nydus_snapshotter_tpu.ops import blake3_jax

            return blake3_jax.n_leaves(size)
        return sha256.n_padded_blocks(size)

    def max_read_span(self) -> int:
        """Largest pass-2 gather span any bucket can issue, in bytes —
        the guard this engine's layout() pads for, and the shard halo
        ops/mesh_pack must append to every per-device slab so a chunk
        cut at a shard boundary still gathers without clamping."""
        if self.digester == "blake3":
            from nydus_snapshotter_tpu.ops import blake3_jax

            return self._blocks_of(self.params.max_size) * blake3_jax.LEAF_BYTES
        return self._blocks_of(self.params.max_size) * 64

    # -- planning ------------------------------------------------------------

    def layout(self, arrs: list[np.ndarray]) -> tuple[np.ndarray, list[tuple[int, int]]]:
        """Concatenate streams; returns (buffer, [(offset, length)])."""
        table = []
        total = 0
        for a in arrs:
            table.append((total, a.size))
            total += a.size
        # pad to a window multiple + one max-chunk guard so pass-2
        # dynamic_slice never clamps a start (clamping would shift the
        # slice and corrupt in-range bytes)
        guard = self.params.max_size + 64
        npad = -(-max(1, total + guard) // WINDOW) * WINDOW
        # quantize to 1/8-pow2 steps: bounded compile count without the
        # full pow2 doubling (which would push a 1.1 GiB batch to 2 GiB)
        step = max(WINDOW, _pow2_ceil(npad) // 8)
        npad = -(-npad // step) * step
        # Device ints are 32-bit (no x64): pass-2 chunk offsets must
        # address the buffer with int32. Callers split larger corpora
        # into sub-2-GiB batches (bench packs per layer, far below this).
        if npad >= 1 << 31:
            raise FusedOverflow(
                f"batch of {total} bytes pads to {npad} — beyond int32 "
                "device addressing; split the batch"
            )
        buf = np.zeros(npad, dtype=np.uint8)
        pos = 0
        for a in arrs:
            buf[pos : pos + a.size] = a
            pos += a.size
        return buf, table

    def resolve(
        self,
        cand_s: np.ndarray,
        cand_l: np.ndarray,
        table: list[tuple[int, int]],
    ) -> list[np.ndarray]:
        """Per-file cut resolution over the global candidate arrays.

        Candidates judged per file always sit >= min_size-1 >= 31 bytes
        past the file start, where the 32-byte gear window lies entirely
        inside the file — so global (concatenated) hashing resolves to
        bit-identical per-file cuts (the ops/chunker seam argument).
        """
        cuts = []
        for off, length in table:
            if length == 0:
                cuts.append(np.asarray([], dtype=np.int64))
                continue
            lo_s, hi_s = np.searchsorted(cand_s, [off, off + length])
            lo_l, hi_l = np.searchsorted(cand_l, [off, off + length])
            cuts.append(
                cdc.resolve_cuts(
                    cand_s[lo_s:hi_s] - off,
                    cand_l[lo_l:hi_l] - off,
                    length,
                    self.params,
                )
            )
        return cuts

    def plan_buckets(
        self, table: list[tuple[int, int]], cuts: list[np.ndarray]
    ) -> tuple[list[Bucket], list[tuple[int, int]]]:
        """Bucket chunks by pow2 padded-block class with EXACT counts.

        Returns (buckets, flat chunk order) where the flat order is
        (bucket, row) assignments per chunk in stream order, used to
        scatter results back.
        """
        max_blocks = self._blocks_of(self.params.max_size)
        per_class: dict[int, list[tuple[int, int]]] = {}
        order: list[tuple[int, int]] = []
        for (f_off, _f_len), f_cuts in zip(table, cuts):
            prev = 0
            for cut in f_cuts:
                size = int(cut) - prev
                nb = self._blocks_of(size)
                cap = min(_pow2_ceil(nb), max_blocks)
                rows = per_class.setdefault(cap, [])
                order.append((cap, len(rows)))
                rows.append((f_off + prev, size))
                prev = int(cut)
        buckets = []
        for cap in sorted(per_class):
            rows = per_class[cap]
            m = _pow2_ceil(len(rows))
            offs = np.zeros(m, dtype=np.int32)
            sizes = np.zeros(m, dtype=np.int32)
            offs[: len(rows)] = [r[0] for r in rows]
            sizes[: len(rows)] = [r[1] for r in rows]
            buckets.append(Bucket(cap, offs, sizes, len(rows)))
        return buckets, order

    # -- execution -----------------------------------------------------------

    def candidates(self, buffer_dev: jax.Array, n: int) -> tuple[np.ndarray, np.ndarray]:
        """Pass 1 on an already-device-resident buffer."""
        p = self.params
        wcap_s = _wcap_for(n, p.bits + 2)
        wcap_l = _wcap_for(n, p.bits - 2)
        sel_s, got_s, nw_s, sel_l, got_l, nw_l = _pass1(
            buffer_dev, jnp.int32(n), p.mask_small, p.mask_large, wcap_s, wcap_l
        )
        nw_s, nw_l = int(nw_s), int(nw_l)
        if nw_s > wcap_s or nw_l > wcap_l:
            raise FusedOverflow(
                f"candidate words {nw_s}/{nw_l} exceed caps {wcap_s}/{wcap_l}"
            )
        def host_pos(sel, got, nw):
            # expand word-index + bitmap word to int64 byte positions
            sel = np.asarray(jax.device_get(sel))[:nw].astype(np.int64)
            got = np.asarray(jax.device_get(got))[:nw]
            bits = np.unpackbits(
                got.view(np.uint8).reshape(-1, 4), axis=1, bitorder="little"
            )  # [nw, 32]
            widx, bit = np.nonzero(bits)
            pos = sel[widx] * 32 + bit
            return pos[pos < n]

        return host_pos(sel_s, got_s, nw_s), host_pos(sel_l, got_l, nw_l)

    def digest_probe(
        self,
        buffer_dev: jax.Array,
        buckets: list[Bucket],
        chunk_dict: tuple[np.ndarray, np.ndarray] | None = None,
        depth: int = 8,
        probe_kernel: str = "auto",  # "auto" | "xla" | "pallas" | "pallas-interpret"
        dict_epoch: int | None = None,
    ):
        """Pass 2: per-bucket digest states + optional dict probe.

        ``probe_kernel``: auto = the DMA-pipelined Pallas probe on real
        TPU, the XLA gather elsewhere; "pallas-interpret" forces the
        Pallas lowering in interpret mode (CPU differential tests).

        ``dict_epoch``: the dict's mutation epoch (ShardedChunkDict
        ``fused_probe_tables``). Incremental inserts mutate the table
        arrays IN PLACE, so the staged-table cache must key on the epoch
        — identity alone would keep serving the pre-insert device copy.
        """
        offs = tuple(jnp.asarray(b.offsets) for b in buckets)
        sizes = tuple(jnp.asarray(b.sizes) for b in buckets)
        caps = tuple(b.cap_blocks for b in buckets)
        tk = tv = None
        table_cap = 0
        use_pallas = probe_interpret = False
        if chunk_dict is not None:
            from nydus_snapshotter_tpu.ops import probe_pallas

            if probe_kernel not in ("auto", "xla", "pallas", "pallas-interpret"):
                raise ValueError(f"unknown probe kernel {probe_kernel!r}")
            keys, vals = chunk_dict
            table_cap = keys.shape[0]
            if probe_kernel == "auto":
                use_pallas = probe_pallas.supported()
            elif probe_kernel != "xla":
                use_pallas = True
                probe_interpret = probe_kernel == "pallas-interpret"
            if use_pallas:
                tk, tv = self._padded_tables(keys, vals, depth, dict_epoch)
            else:
                tk, tv = jnp.asarray(keys), jnp.asarray(vals)
        states, probe = _pass2(
            buffer_dev, offs, sizes, caps, tk, tv, table_cap, depth,
            digester=self.digester, pallas_probe=use_pallas,
            probe_interpret=probe_interpret,
        )
        return states, probe

    def _padded_tables(
        self,
        keys: np.ndarray,
        vals: np.ndarray,
        depth: int,
        dict_epoch: int | None = None,
    ):
        """Wrap-free padded device tables for the Pallas probe, cached per
        (dict identity, depth, epoch) — padding copies tens of MB for
        million-entry dicts and repeated digest_probe calls (the bench
        loop) must not pay it, or the H2D re-upload, per dispatch. The
        epoch term invalidates staged copies when incremental inserts
        mutate the arrays in place (same identity, new contents)."""
        from nydus_snapshotter_tpu.ops import probe_pallas

        cached = getattr(self, "_table_cache", None)
        if (
            cached is not None
            and cached[0] is keys  # identity: the cache keeps them alive,
            and cached[1] is vals  # so `is` cannot alias freed objects
            and cached[2] == depth
            and cached[3] == dict_epoch
        ):
            return cached[4], cached[5]
        keys_pad, vals_pad = probe_pallas.pad_tables(keys, vals, depth)
        tk, tv = jnp.asarray(keys_pad), jnp.asarray(vals_pad)
        self._table_cache = (keys, vals, depth, dict_epoch, tk, tv)
        return tk, tv

    def _digest_bytes(self, state_row: np.ndarray) -> bytes:
        if self.digester == "blake3":
            from nydus_snapshotter_tpu.ops import blake3_jax

            return blake3_jax.digest_to_bytes(state_row)
        return sha256.digest_to_bytes(state_row)

    def process_many(
        self,
        streams: list[bytes | np.ndarray],
        chunk_dict: tuple[np.ndarray, np.ndarray] | None = None,
        depth: int = 8,
        probe_kernel: str = "auto",
        dict_epoch: int | None = None,
    ) -> FusedResult:
        from time import perf_counter as _pc

        from nydus_snapshotter_tpu import failpoint

        # Device batch boundary: chaos-testable (the stream.py caller
        # falls back to the per-file host paths on error) and timed so
        # the host-arm scheduling around the two dispatches is visible
        # next to the pipeline's stage counters.
        failpoint.hit("fused.dispatch")
        arrs = [
            np.frombuffer(s, dtype=np.uint8) if isinstance(s, (bytes, bytearray)) else s
            for s in streams
        ]
        n = sum(a.size for a in arrs)
        if n == 0:
            return FusedResult(
                cuts=[np.asarray([], dtype=np.int64) for _ in arrs],
                digests=[[] for _ in arrs],
                probe=np.zeros(0, np.int32) if chunk_dict is not None else None,
            )
        _t0 = _pc()
        buf, table = self.layout(arrs)
        buffer_dev = jnp.asarray(buf)  # committed to the default device
        cand_s, cand_l = self.candidates(buffer_dev, n)
        _t1 = _pc()
        cuts = self.resolve(cand_s, cand_l, table)
        buckets, order = self.plan_buckets(table, cuts)
        _t2 = _pc()
        states, probe = self.digest_probe(
            buffer_dev, buckets, chunk_dict, depth, probe_kernel, dict_epoch
        )
        _record_dispatch(n, _t1 - _t0, _t2 - _t1, _pc() - _t2)
        by_cap = {
            b.cap_blocks: np.asarray(jax.device_get(s))
            for b, s in zip(buckets, states)
        }
        flat_digests = [
            self._digest_bytes(by_cap[cap][row]) for cap, row in order
        ]
        probe_np = None
        if probe is not None:
            # probe ran over the concatenation of bucket rows (incl.
            # padding); remap to stream order via each bucket's row base
            probe_all = np.asarray(jax.device_get(probe))
            base = {}
            acc = 0
            for b in buckets:
                base[b.cap_blocks] = acc
                acc += len(b.offsets)
            probe_np = np.asarray(
                [probe_all[base[cap] + row] for cap, row in order], dtype=np.int32
            )
        out_digests: list[list[bytes]] = []
        pos = 0
        for f_cuts in cuts:
            out_digests.append(flat_digests[pos : pos + len(f_cuts)])
            pos += len(f_cuts)
        return FusedResult(cuts=cuts, digests=out_digests, probe=probe_np)
