"""SHA-256 on device, vmapped across many chunks.

The reference digests every chunk with SHA-256 inside the Rust builder
(digest parity surface: pkg/converter/convert_unix.go:870 uses
``digest.SHA256``). Here the compression function runs as pure uint32 jnp
lanes — TPU has no 64-bit integers, and SHA-256 is natively a 32-bit
algorithm, so state and message schedule live in uint32 exactly.

Shape discipline: one chunk = a row of 64-byte blocks (``uint32[B, 16]``
big-endian words, standard SHA padding applied host-side). A batch of chunks
is ``uint32[M, B, 16]`` + per-chunk block counts; ``lax.scan`` walks the
block axis while ``vmap`` parallelizes across chunks, so the VPU sees
M-wide vector ops per round. Chunks with fewer blocks carry masked
(ignored) tail blocks — bucketing by size class keeps the padding waste
bounded (parallel/pipeline.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x, r):
    return (x >> np.uint32(r)) | (x << np.uint32(32 - r))


def _compress_unrolled(state: jax.Array, block: jax.Array) -> jax.Array:
    """One SHA-256 compression: state u32[8] x block u32[16] -> u32[8].

    Fully unrolled — rounds and the message schedule live in registers as a
    flat chain of elementwise ops (a rolling 16-deep window replaces the
    w[64] array). The only sequential loop in the whole digest is the scan
    over blocks; XLA TPU fuses each unrolled compression into a few vector
    kernels, which keeps per-block dispatch overhead off the hot path (a
    fori_loop per round costs ~µs per iteration — 100x slower end-to-end at
    real chunk sizes). The XLA *CPU* backend chokes on this graph (LLVM
    spends minutes on the 600-op scalar chain), so CPU uses the looped
    variant below — same math, differential-tested equal.
    """
    w = [block[i] for i in range(16)]
    a, b, c, d, e, f, g, h = (state[i] for i in range(8))
    for i in range(64):
        if i < 16:
            wi = w[i]
        else:
            w15, w2 = w[(i - 15) % 16], w[(i - 2) % 16]
            s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
            s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
            wi = w[i % 16] + s0 + w[(i - 7) % 16] + s1
            w[i % 16] = wi
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + np.uint32(_K[i]) + wi
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g
    return jnp.stack([a, b, c, d, e, f, g, h]) + state


def _compress_looped(state: jax.Array, block: jax.Array) -> jax.Array:
    """Loop-structured compression for backends where unrolling is hostile
    to the compiler (XLA CPU). Same math as _compress_unrolled."""
    k = jnp.asarray(_K)

    def schedule(i, w):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> np.uint32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> np.uint32(10))
        return w.at[i].set(w[i - 16] + s0 + w[i - 7] + s1)

    w = jnp.zeros(64, dtype=jnp.uint32).at[:16].set(block)
    w = jax.lax.fori_loop(16, 64, schedule, w)

    def round_fn(i, s):
        a, b, c, d, e, f, g, h = s
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(0, 64, round_fn, tuple(state[i] for i in range(8)))
    return jnp.stack(out) + state


def _sha256_one(blocks: jax.Array, nblocks: jax.Array, unroll: bool) -> jax.Array:
    """Digest one padded message: blocks u32[B,16], nblocks i32 -> u32[8]."""
    compress = _compress_unrolled if unroll else _compress_looped

    def step(state, xs):
        block, j = xs
        new = compress(state, block)
        return jnp.where(j < nblocks, new, state), None

    idx = jnp.arange(blocks.shape[0])
    state, _ = jax.lax.scan(step, jnp.asarray(_H0), (blocks, idx))
    return state


@functools.partial(jax.jit, static_argnames=("unroll",))
def _sha256_batch_jit(blocks: jax.Array, nblocks: jax.Array, unroll: bool) -> jax.Array:
    return jax.vmap(functools.partial(_sha256_one, unroll=unroll))(blocks, nblocks)


def sha256_batch(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Digest a batch: blocks u32[M,B,16], nblocks i32[M] -> u32[M,8].

    ``NTPU_SHA_PALLAS=1`` routes large TPU batches through the Pallas
    kernel (ops/sha256_pallas.py) — opt-in until its throughput is
    measured against the XLA scan on real hardware (tools/devbench.py
    --stage sha measures both).
    """
    import os

    if os.environ.get("NTPU_SHA_PALLAS", "") not in ("", "0"):
        from nydus_snapshotter_tpu.ops import sha256_pallas

        if sha256_pallas.supported(blocks.shape[0]):
            return sha256_pallas.sha256_batch_pallas(blocks, nblocks)
    unroll = jax.default_backend() != "cpu"
    return _sha256_batch_jit(blocks, nblocks, unroll)


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def n_padded_blocks(length: int) -> int:
    """Number of 64-byte blocks after standard SHA padding."""
    return (length + 8) // 64 + 1


def pad_message_np(data: bytes | np.ndarray) -> np.ndarray:
    """Standard SHA-256 padding -> big-endian words u32[nblocks, 16]."""
    arr = np.frombuffer(bytes(data), dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    n = arr.size
    nb = n_padded_blocks(n)
    buf = np.zeros(nb * 64, dtype=np.uint8)
    buf[:n] = arr
    buf[n] = 0x80
    buf[-8:] = np.frombuffer((n * 8).to_bytes(8, "big"), dtype=np.uint8)
    return buf.view(">u4").astype(np.uint32).reshape(nb, 16)


def pack_messages_np(
    msgs: list[bytes], block_capacity: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Pack messages into a fixed-shape batch (u32[M,B,16], i32[M])."""
    counts = np.asarray([n_padded_blocks(len(m)) for m in msgs], dtype=np.int32)
    cap = block_capacity or (int(counts.max()) if len(msgs) else 1)
    if len(msgs) and int(counts.max()) > cap:
        raise ValueError(f"message needs {int(counts.max())} blocks > capacity {cap}")
    out = np.zeros((len(msgs), cap, 16), dtype=np.uint32)
    for i, m in enumerate(msgs):
        out[i, : counts[i]] = pad_message_np(m)
    return out, counts


def digest_to_bytes(state: np.ndarray) -> bytes:
    """u32[8] state -> canonical 32-byte big-endian digest."""
    return np.asarray(state, dtype=">u4").tobytes()


def sha256_many(msgs: list[bytes]) -> list[bytes]:
    """Digest many messages on device; returns raw 32-byte digests."""
    if not msgs:
        return []
    blocks, counts = pack_messages_np(msgs)
    states = np.asarray(jax.device_get(sha256_batch(jnp.asarray(blocks), jnp.asarray(counts))))
    return [digest_to_bytes(states[i]) for i in range(len(msgs))]
