"""Pallas TPU kernel for the sharded-dict hash probe.

The XLA lowering of the dict probe is a gather: ``k[slots]`` with
``slots: u32[M, D]`` against a table ``u32[C, 8]``. On TPU, XLA executes
that gather effectively element-serially (~1 µs/element measured on v5e —
parallel/sharded_dict.py's crossover note), which is why round-3's device
probe lost to the host arm by 5x. The TPU-native formulation is the one
embedding-lookup kernels use: keep the table in HBM, keep a tile of
queries in VMEM, and DMA each query's probe-chain window
(``keys[slot0 : slot0 + W]``) into VMEM scratch with K outstanding copies
so the per-query DMA latency pipelines away. All compare/select work runs
on the VPU over the W-row window; no XLA gather is ever emitted.

Table layout contract (prepared by ``pad_tables``):
- ``keys_pad  u32[C + W, 8]`` — the open-addressing table with its own
  head replicated after the end, so a chain window starting anywhere in
  ``[0, C)`` never wraps (open addressing wraps mod C; the pad makes the
  window read linear).
- ``vals_pad  i32[C + W, 1]`` — same replication for the value lanes.
- Window rows W = align8(depth + 7): DMA sublane slices start 8-aligned
  (``wstart = slot0 & ~7``), and the in-window chain offset (``slot0 & 7``)
  plus the chain depth always fits.

Correctness oracle: parallel/sharded_dict._probe_local (XLA gather
formulation) — differential-tested in tests/test_probe_pallas.py, in
interpret mode on CPU (no TPU in the dev loop; the tunnel wedges —
memory: axon-tunnel-wedges).

Reference correspondence: the chunk-dict probe inside ``nydus-image``
(pkg/converter/tool/builder.go:122-123 hands the builder a chunk dict;
the Rust builder probes it per chunk).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

PIPELINE = 4  # outstanding DMA windows per query stream


def _align8(n: int) -> int:
    return (n + 7) & ~7


def window_rows(depth: int) -> int:
    return _align8(depth + 7)


def pad_tables(keys: np.ndarray, values: np.ndarray, depth: int):
    """(keys u32[C,8], values i32[C]) -> wrap-free padded device layout."""
    w = window_rows(depth)
    keys = np.ascontiguousarray(keys, dtype=np.uint32)
    values = np.ascontiguousarray(values, dtype=np.int32).reshape(-1, 1)
    keys_pad = np.concatenate([keys, keys[:w]], axis=0)
    vals_pad = np.concatenate([values, values[:w]], axis=0)
    return keys_pad, vals_pad


def _kernel(
    wstart_ref,  # SMEM i32[Q]   (scalar prefetch: aligned window starts)
    off_ref,  # SMEM i32[Q]      (scalar prefetch: slot0 - wstart)
    q_ref,  # VMEM u32[Q, 8]     (this tile's queries)
    keys_ref,  # ANY  u32[C+W, 8]
    vals_ref,  # ANY  i32[C+W, 1]
    out_ref,  # VMEM i32[Q, 1]
    kscratch,  # VMEM u32[K, W, 8]
    vscratch,  # VMEM i32[K, W, 1]
    ksem,  # DMA sems [K]
    vsem,  # DMA sems [K]
    *,
    depth: int,
    n_queries: int,
):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    w = window_rows(depth)
    k = PIPELINE

    def start(i):
        sl = jax.lax.rem(i, k)
        ws = wstart_ref[i]
        pltpu.make_async_copy(
            keys_ref.at[pl.ds(ws, w), :], kscratch.at[sl], ksem.at[sl]
        ).start()
        pltpu.make_async_copy(
            vals_ref.at[pl.ds(ws, w), :], vscratch.at[sl], vsem.at[sl]
        ).start()

    def wait(i):
        sl = jax.lax.rem(i, k)
        pltpu.make_async_copy(
            keys_ref.at[pl.ds(wstart_ref[i], w), :], kscratch.at[sl], ksem.at[sl]
        ).wait()
        pltpu.make_async_copy(
            vals_ref.at[pl.ds(wstart_ref[i], w), :], vscratch.at[sl], vsem.at[sl]
        ).wait()

    # Prologue: fill the pipeline.
    for i in range(min(k, n_queries)):
        start(i)

    rows = jax.lax.broadcasted_iota(jnp.int32, (w, 1), 0)

    def body(i, _):
        sl = jax.lax.rem(i, k)
        wait(i)
        win_k = kscratch[sl]  # u32[W, 8]
        win_v = vscratch[sl]  # i32[W, 1]
        off = off_ref[i]
        q = q_ref[pl.ds(i, 1), :]  # u32[1, 8]
        eq = jnp.all(win_k == q, axis=1, keepdims=True)  # bool[W, 1]
        in_chain = (rows >= off) & (rows < off + depth)
        match = eq & in_chain & (win_v != 0)
        # first match in chain order: smallest matching row
        masked_rows = jnp.where(match, rows, jnp.int32(2 * w))
        rmin = jnp.min(masked_rows)
        val = jnp.sum(jnp.where(masked_rows == rmin, win_v, 0))
        res = jnp.where(rmin < 2 * w, val, 0)
        out_ref[pl.ds(i, 1), :] = jnp.full((1, 1), res, jnp.int32)

        @pl.when(i + k < n_queries)
        def _():
            start(i + k)

        return ()

    jax.lax.fori_loop(0, n_queries, body, ())


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def probe_padded(
    keys_pad: jax.Array,
    vals_pad: jax.Array,
    queries: jax.Array,
    wstart: jax.Array,
    off: jax.Array,
    depth: int,
    interpret: bool = False,
) -> jax.Array:
    """Probe queries u32[Q,8] against a pad_tables() layout -> i32[Q]."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q = queries.shape[0]
    w = window_rows(depth)
    out = pl.pallas_call(
        functools.partial(_kernel, depth=depth, n_queries=q),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(1,),
            in_specs=[
                # queries live in VMEM (the kernel loads them directly;
                # Mosaic only allows loads on VMEM/SMEM refs — the first
                # real-TPU window rejected the ANY spec here)
                pl.BlockSpec((q, 8), lambda i, *_: (0, 0)),
                # tables stay in ANY (HBM): only ever touched via DMA
                pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
                pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
            ],
            out_specs=pl.BlockSpec((q, 1), lambda i, *_: (0, 0)),
            scratch_shapes=[
                pltpu.VMEM((PIPELINE, w, 8), jnp.uint32),
                pltpu.VMEM((PIPELINE, w, 1), jnp.int32),
                pltpu.SemaphoreType.DMA((PIPELINE,)),
                pltpu.SemaphoreType.DMA((PIPELINE,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((q, 1), jnp.int32),
        interpret=interpret,
    )(wstart, off, queries, keys_pad, vals_pad)
    return out[:, 0]


def probe(
    keys: np.ndarray,
    values: np.ndarray,
    queries: np.ndarray,
    depth: int,
    interpret: bool = False,
) -> np.ndarray:
    """Convenience single-shard probe: builds the padded layout, computes
    the per-query window starts host-side, runs the kernel.
    Returns i32[M] (0 = miss; hits are dict index + 1, the table's value
    convention)."""
    cap = keys.shape[0]
    queries = np.ascontiguousarray(queries, dtype=np.uint32).reshape(-1, 8)
    slot0 = (queries[:, 1] & np.uint32(cap - 1)).astype(np.int32)
    wstart = slot0 & ~np.int32(7)
    off = slot0 - wstart
    keys_pad, vals_pad = pad_tables(keys, values, depth)
    return np.asarray(
        probe_padded(
            jnp.asarray(keys_pad),
            jnp.asarray(vals_pad),
            jnp.asarray(queries),
            jnp.asarray(wstart),
            jnp.asarray(off),
            depth,
            interpret=interpret,
        )
    )


def supported() -> bool:
    """Real-TPU availability gate (the dev/CI loop validates in interpret
    mode; the kernel path itself is for tpu backends)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
