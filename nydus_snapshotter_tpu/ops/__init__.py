"""JAX/Pallas compute kernels: gear rolling hash, CDC, SHA-256, dict probes."""
