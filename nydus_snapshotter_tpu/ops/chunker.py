"""ChunkDigestEngine: windowed hash → cut resolution → batched digests.

This is the data-plane replacement for the reference's ``nydus-image create``
hot loop (chunking + digesting inside the Rust builder,
pkg/converter/tool/builder.go:148-178), decomposed TPU-first:

1. **Hash (device, parallel).** The stream is viewed as fixed-size windows
   (static shapes ⇒ one XLA compilation per window geometry). Each window
   batch is hashed position-parallel (ops/gear.py) and judged against both
   FastCDC masks; the kernel returns *packed candidate bitmaps*
   (uint32[N/32] per mask) so device→host traffic is N/4 bits per byte, not
   4 bytes per byte of hashes. A 31-byte tail carries the rolling window
   across seams, making windowed output bit-identical to whole-stream
   hashing.
2. **Cut resolution (host, over sparse candidates).** ops/cdc.py resolves
   min/normal/max rules per file in O(chunks · log candidates).
3. **Digest (device, vmapped).** Chunks are bucketed by padded block count
   (powers of two ⇒ few compiled shapes, bounded padding waste) and
   SHA-256'd as uint32 lanes (ops/sha256.py).

Fixed-size mode (nydus default) skips phase 1/2 and goes straight to
digesting.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from nydus_snapshotter_tpu.ops import cdc, gear, sha256

DEFAULT_WINDOW = 1 << 22  # 4 MiB per device window


def _pow2_ceil(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


@dataclass(frozen=True)
class ChunkMeta:
    offset: int
    size: int
    digest: bytes  # raw sha256 of the chunk data


@functools.partial(jax.jit, static_argnames=("n",))
def _hash_bitmaps_kernel(x: jax.Array, mask_s: jax.Array, mask_l: jax.Array, n: int):
    """Batch of windows → packed candidate bitmaps.

    x: uint8[B, n + GEAR_WINDOW - 1] (window prefixed by its 31-byte tail)
    returns (uint32[B, n//32], uint32[B, n//32]) for the two masks.

    Gather-free: the gear table value of every byte is computed elementwise
    (gear.mix32_jnp — TPU VPUs have no per-lane table lookup; the measured
    gathered variant ran at 0.1 GiB/s on a v5e chip) and the 32-tap window
    sum runs as 5 log-doubling shifted adds (gear.windowed_gear_sum).
    """
    h = gear.windowed_gear_sum(gear.mix32_jnp(x))[:, gear.GEAR_WINDOW - 1 :]
    lanes = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

    def pack(bits):
        return jnp.sum(
            bits.reshape(-1, n // 32, 32).astype(jnp.uint32) * lanes, axis=-1
        )

    return pack((h & mask_s) == 0), pack((h & mask_l) == 0)


def _unpack_positions(words: np.ndarray, valid_len: int) -> np.ndarray:
    """uint32 packed bitmap → sorted candidate positions < valid_len."""
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    pos = np.nonzero(bits)[0]
    return pos[pos < valid_len]


def _cpu_count() -> int:
    import os

    return os.cpu_count() or 4


def _map_threads(fn, items: list, min_batch: int = 2) -> list:
    """Thread-pool map for GIL-dropping work (native ctypes calls, hashlib
    over large buffers); sequential below ``min_batch``."""
    if len(items) < min_batch:
        return [fn(i) for i in items]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(32, _cpu_count())) as pool:
        return list(pool.map(fn, items))


def _grouped_native_digests(
    items: list[tuple[np.ndarray, int, int]], native_fn
) -> list[bytes]:
    """Fan (array, offset, size) items out to GIL-dropping native batch calls.

    Groups runs of extents sharing a source array, then splits long runs
    into ~cpu_count sub-groups so one large stream still fans out across
    cores (each sub-group is an independent native call; order-preserving
    concat keeps digest order). ``native_fn(arr, extents_i64) -> bytes``
    is the 32-B-per-extent batch contract shared by ntpu_sha256_many and
    ntpu_blake3_many.
    """
    groups: list[tuple[np.ndarray, list[tuple[int, int]]]] = []
    for arr, off, size in items:
        if groups and groups[-1][0] is arr:
            groups[-1][1].append((off, size))
        else:
            groups.append((arr, [(off, size)]))
    ncpu = _cpu_count()
    if ncpu > 1 and len(groups) < ncpu:
        per = max(8, -(-len(items) // ncpu))
        groups = [
            (arr, exts[i : i + per])
            for arr, exts in groups
            for i in range(0, len(exts), per)
        ]
    flat = _map_threads(
        lambda g: native_fn(g[0], np.asarray(g[1], dtype=np.int64)), groups
    )
    return [
        blob[32 * i : 32 * (i + 1)] for blob in flat for i in range(len(blob) // 32)
    ]


def _host_digests(items: list[tuple[np.ndarray, int, int]]) -> list[bytes]:
    """Threaded host SHA-256 over (array, offset, size) extents.

    Routes through the native SHA-NI batch call when the engine is built
    (≥ 8 items: below that hashlib — which also drops the GIL for buffers
    > 2 KiB — beats the FFI round trip); both arms scale across cores
    (the crossover arm for small batches where the device scan is
    latency-bound).
    """
    import hashlib

    from nydus_snapshotter_tpu.ops import native_cdc

    lib = native_cdc.load()
    if lib is not None and hasattr(lib, "ntpu_sha256_many") and len(items) >= 8:
        return _grouped_native_digests(items, native_cdc.sha256_many_native)

    def one(item: tuple[np.ndarray, int, int]) -> bytes:
        arr, off, size = item
        return hashlib.sha256(memoryview(arr)[off : off + size]).digest()

    return _map_threads(one, items, min_batch=8)


def _host_digests_blake3(items: list[tuple[np.ndarray, int, int]]) -> list[bytes]:
    """Threaded host BLAKE3 over (array, offset, size) extents.

    Same fan-out as :func:`_host_digests` via the shared grouped-batch
    helper, hashing with the native blake3 arm (ntpu_blake3_many) when the
    engine is built — with no minimum-batch gate, because the fallback is
    the pure-Python spec implementation (~3 orders slower than hashlib, so
    the FFI round trip always wins). Needed when packing with
    ``digester="blake3"`` so chunk digests match the reference toolchain's
    default and dedup against REAL nydus images gets content hits
    (reference tool/builder.go:122-123 chunk-dict probes are digest-keyed).
    """
    from nydus_snapshotter_tpu.ops import native_cdc

    lib = native_cdc.load()
    if lib is not None and hasattr(lib, "ntpu_blake3_many"):
        return _grouped_native_digests(items, native_cdc.blake3_many_native)

    from nydus_snapshotter_tpu.utils import blake3 as pyb3

    def one(item: tuple[np.ndarray, int, int]) -> bytes:
        arr, off, size = item
        return pyb3.blake3(bytes(memoryview(arr)[off : off + size]))

    return _map_threads(one, items, min_batch=8)


def host_digests_for(digester: str):
    """The (array, offset, size)-extents digest fan-out for an algorithm —
    the single selector pack paths use instead of branching inline."""
    return _host_digests_blake3 if digester == "blake3" else _host_digests


class ChunkDigestEngine:
    """Chunk + digest byte streams on device (or numpy for differential runs).

    Parameters mirror the reference's PackOption knobs: ``chunk_size``
    (power-of-two average; pkg/converter/types.go:76-79) and the chunking
    mode — ``cdc`` (content-defined, the accel feature) or ``fixed`` (nydus
    default fixed-size chunks).
    """

    def __init__(
        self,
        chunk_size: int = 0x100000,
        mode: str = "cdc",
        backend: str = "jax",
        window: int = DEFAULT_WINDOW,
        digest_backend: str | None = None,
        digester: str = "sha256",
    ):
        if mode not in ("cdc", "fixed"):
            raise ValueError(f"unknown chunking mode {mode!r}")
        if backend not in ("jax", "numpy", "hybrid", "fused"):
            raise ValueError(f"unknown backend {backend!r}")
        if window % 32:
            raise ValueError("window must be a multiple of 32")
        self.chunk_size = chunk_size
        self.mode = mode
        self.backend = backend
        self.window = window
        # hybrid: native/sequential boundaries + threaded host SHA — the
        # latency arm of the crossover (device kernels win only on bulk
        # batches; SURVEY §7 hard-part #3 fallback)
        self.digest_backend = digest_backend or (
            "host" if backend == "hybrid" else "jax" if backend == "fused" else backend
        )
        if self.digest_backend not in ("jax", "numpy", "host"):
            raise ValueError(f"unknown digest backend {self.digest_backend!r}")
        if digester not in ("sha256", "blake3"):
            raise ValueError(f"unknown digester {digester!r}")
        # blake3 = the reference toolchain's default chunk digester
        # (RafsSuperFlags HASH_BLAKE3). digest_backend="jax" routes blake3
        # through the device tree kernel (_digests_bucketed_b3 /
        # ops/blake3_jax); other backends use the host arm (native
        # ntpu_blake3_many / pure-Python spec impl). The SHA-NI *fused*
        # chunk+digest arms are sha-specific and gate off (_fused_available).
        self.digester = digester
        self.params = cdc.CDCParams(chunk_size) if mode == "cdc" else None

    # -- boundaries ---------------------------------------------------------

    def boundaries(self, data: bytes | np.ndarray) -> np.ndarray:
        """Cut offsets for one stream (exclusive ends, last == len)."""
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
        if self.mode == "fixed":
            return cdc.chunk_fixed(arr.size, self.chunk_size)
        if arr.size == 0:
            return np.asarray([], dtype=np.int64)
        if self.backend == "hybrid":
            from nydus_snapshotter_tpu.ops import native_cdc

            if native_cdc.available():
                # chunk_data_best: vectorized striped table scan when the
                # [compression] vectorized knob allows it and the arm is
                # built, sequential otherwise — cut-identical either way.
                return native_cdc.chunk_data_best(arr, self.params)
            return cdc.chunk_data_np(arr, self.params)
        if self.backend == "numpy":
            return cdc.chunk_data_np(arr, self.params)
        cand_s, cand_l = self._candidates_windowed(arr)
        return cdc.resolve_cuts(cand_s, cand_l, arr.size, self.params)

    # Smallest device window: the Pallas kernel's lane*tile granularity
    # (ops/gear_pallas.py); also bounds distinct compiled shapes.
    MIN_WINDOW = 1 << 19

    def _dispatch_windows(self, arr: np.ndarray):
        """Enqueue the device hash of one stream; returns an opaque handle
        for :meth:`_collect_windows`. Dispatch is ASYNC (jax queues the
        upload + kernel), so callers can enqueue stream i+1 before
        collecting stream i — the double-buffered infeed discipline: the
        device crunches the next stream while the host unpacks/resolves
        the previous one."""
        # Shrink the window for small streams: a 512 KiB buffer hashed in a
        # fixed 4 MiB window wastes 8x device compute on zero padding (the
        # streaming pack drains ~2*max_size buffers). Power-of-two windows
        # in [MIN_WINDOW, self.window] keep the compile count logarithmic.
        w = min(self.window, max(self.MIN_WINDOW, _pow2_ceil(max(1, arr.size))))
        tail_len = gear.GEAR_WINDOW - 1
        n_windows = (arr.size + w - 1) // w
        # Window rows prefixed with the previous window's 31-byte tail; the
        # final window zero-padded to the static shape. The batch dim is
        # padded to a power of two so XLA compiles O(log) distinct shapes,
        # not one per stream length.
        n_rows = _pow2_ceil(n_windows)
        rows = np.zeros((n_rows, tail_len + w), dtype=np.uint8)
        for i in range(n_windows):
            lo = i * w
            hi = min(lo + w, arr.size)
            rows[i, tail_len : tail_len + hi - lo] = arr[lo:hi]
            if lo:
                rows[i, :tail_len] = arr[lo - tail_len : lo]
        from nydus_snapshotter_tpu.ops import gear_pallas

        if gear_pallas.supported(w):
            bm_s, bm_l = gear_pallas.gear_bitmaps(
                jnp.asarray(rows), self.params.mask_small, self.params.mask_large, w
            )
        else:
            bm_s, bm_l = _hash_bitmaps_kernel(
                jnp.asarray(rows),
                jnp.uint32(self.params.mask_small),
                jnp.uint32(self.params.mask_large),
                w,
            )
        return bm_s, bm_l, w, n_windows

    def _collect_windows(
        self, handle, arr: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        bm_s, bm_l, w, n_windows = handle
        bm_s, bm_l = np.asarray(jax.device_get(bm_s)), np.asarray(jax.device_get(bm_l))
        parts_s, parts_l = [], []
        for i in range(n_windows):
            valid = min(w, arr.size - i * w)
            parts_s.append(_unpack_positions(bm_s[i], valid) + i * w)
            parts_l.append(_unpack_positions(bm_l[i], valid) + i * w)
        return np.concatenate(parts_s), np.concatenate(parts_l)

    def _candidates_windowed(self, arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return self._collect_windows(self._dispatch_windows(arr), arr)

    # -- digesting ----------------------------------------------------------

    def digests(self, data: bytes | np.ndarray, cuts: np.ndarray) -> list[bytes]:
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
        extents = cdc.cuts_to_extents(cuts)
        if self.digester == "blake3":
            items = [(arr, o, s) for o, s in extents]
            if self.digest_backend == "jax":
                return self._digests_bucketed_b3(items)
            return _host_digests_blake3(items)
        if self.digest_backend == "numpy":
            import hashlib

            return [hashlib.sha256(arr[o : o + s].tobytes()).digest() for o, s in extents]
        if self.digest_backend == "host":
            return _host_digests([(arr, o, s) for o, s in extents])
        return self._digests_bucketed(arr, extents)

    def _digests_bucketed(self, arr: np.ndarray, extents: list[tuple[int, int]]) -> list[bytes]:
        """Bucket chunks by power-of-two padded block count, digest per bucket."""
        out: list[bytes | None] = [None] * len(extents)
        if not extents:
            return []
        # Power-of-two capacity classes bound the number of compiled shapes;
        # clamping to the engine's static max chunk size stops the top class
        # from doubling the scan length (a max_size chunk is 65537 blocks —
        # rounding to 131072 would double compile and run time) while keeping
        # shapes identical across calls.
        max_chunk = self.params.max_size if self.params else self.chunk_size
        max_blocks = sha256.n_padded_blocks(max_chunk)
        buckets: dict[int, list[int]] = {}
        for idx, (_off, size) in enumerate(extents):
            nb = sha256.n_padded_blocks(size)
            cap = min(1 << (nb - 1).bit_length() if nb > 1 else 1, max_blocks)
            buckets.setdefault(cap, []).append(idx)
        for cap, idxs in sorted(buckets.items()):
            msgs = [arr[extents[i][0] : extents[i][0] + extents[i][1]].tobytes() for i in idxs]
            blocks, counts = sha256.pack_messages_np(msgs, block_capacity=cap)
            # Pad the batch dim to a power of two (dummy rows have zero
            # blocks, so the scan leaves them at H0 and they're discarded) —
            # bounds compile count like the window batching above.
            m_pad = _pow2_ceil(len(msgs)) - len(msgs)
            if m_pad:
                blocks = np.concatenate([blocks, np.zeros((m_pad, cap, 16), np.uint32)])
                counts = np.concatenate([counts, np.zeros(m_pad, np.int32)])
            states = np.asarray(
                jax.device_get(sha256.sha256_batch(jnp.asarray(blocks), jnp.asarray(counts)))
            )
            for row, i in enumerate(idxs):
                out[i] = sha256.digest_to_bytes(states[row])
        return out  # type: ignore[return-value]

    def _digests_bucketed_b3(
        self, items: list[tuple[np.ndarray, int, int]]
    ) -> list[bytes]:
        """Device BLAKE3: bucket chunks by power-of-two leaf count, digest
        per bucket (ops/blake3_jax — leaves parallel across lanes, log-depth
        tree merge). The blake3 analog of :meth:`_digests_bucketed`; takes
        (array, offset, size) items so call sites hand over zero-copy views
        (the only copy is pack_messages_np's write into the padded batch)."""
        from nydus_snapshotter_tpu.ops import blake3_jax

        out: list[bytes | None] = [None] * len(items)
        if not items:
            return []
        max_chunk = self.params.max_size if self.params else self.chunk_size
        max_leaves = _pow2_ceil(blake3_jax.n_leaves(max_chunk))
        buckets: dict[int, list[int]] = {}
        for idx, (_arr, _off, size) in enumerate(items):
            cap = min(_pow2_ceil(blake3_jax.n_leaves(size)), max_leaves)
            buckets.setdefault(cap, []).append(idx)
        for cap, idxs in sorted(buckets.items()):
            msgs = [items[i][0][items[i][1] : items[i][1] + items[i][2]] for i in idxs]
            blocks, lengths = blake3_jax.pack_messages_np(msgs, leaf_capacity=cap)
            m_pad = _pow2_ceil(len(msgs)) - len(msgs)
            if m_pad:
                blocks = np.concatenate(
                    [blocks, np.zeros((m_pad,) + blocks.shape[1:], np.uint32)]
                )
                lengths = np.concatenate([lengths, np.zeros(m_pad, np.int32)])
            words = np.asarray(
                jax.device_get(
                    blake3_jax.blake3_batch(jnp.asarray(blocks), jnp.asarray(lengths))
                )
            )
            for row, i in enumerate(idxs):
                out[i] = blake3_jax.digest_to_bytes(words[row])
        return out  # type: ignore[return-value]

    def boundaries_many(self, arrs: list[np.ndarray]) -> list[np.ndarray]:
        """Per-stream cut offsets for many streams (thread-parallel on the
        hybrid backend: the native chunker drops the GIL)."""
        if self.backend == "hybrid":
            return _map_threads(self.boundaries, arrs)
        if self.backend == "jax" and self.mode == "cdc":
            # Double-buffered device sweep: keep at most DEPTH streams
            # in flight (async dispatch), collecting/resolving in order —
            # the device works on stream i+1 while the host resolves
            # stream i, with device/host memory bounded at DEPTH streams
            # instead of the whole batch.
            DEPTH = 2
            from collections import deque

            nonempty = deque((i, a) for i, a in enumerate(arrs) if a.size)
            inflight: deque = deque()
            out: list[np.ndarray] = [
                np.asarray([], dtype=np.int64) for _ in arrs
            ]
            while nonempty or inflight:
                while nonempty and len(inflight) < DEPTH:
                    i, a = nonempty.popleft()
                    inflight.append((i, a, self._dispatch_windows(a)))
                i, a, h = inflight.popleft()
                cand_s, cand_l = self._collect_windows(h, a)
                out[i] = cdc.resolve_cuts(cand_s, cand_l, a.size, self.params)
            return out
        return [self.boundaries(a) for a in arrs]

    def digest_all(
        self,
        arrs: list[np.ndarray],
        per_file_extents: list[list[tuple[int, int]]],
    ) -> list[bytes]:
        """Flat digests for pre-computed per-file extents, in file order.

        One global pass across every file — a single bucketed device batch
        or one host thread-pool sweep, instead of a tiny batch per file.
        """
        if not arrs:
            return []
        if self.digester == "blake3":
            items = [
                (arr, o, s)
                for arr, extents in zip(arrs, per_file_extents)
                for o, s in extents
            ]
            if self.digest_backend == "jax":
                return self._digests_bucketed_b3(items)
            return _host_digests_blake3(items)
        if self.digest_backend == "host":
            return _host_digests(
                [
                    (arr, o, s)
                    for arr, extents in zip(arrs, per_file_extents)
                    for o, s in extents
                ]
            )
        if self.digest_backend == "numpy":
            import hashlib

            return [
                hashlib.sha256(arr[o : o + s].tobytes()).digest()
                for arr, extents in zip(arrs, per_file_extents)
                for o, s in extents
            ]
        # one global bucketed device batch across every file
        offsets = []
        total = 0
        for arr in arrs:
            offsets.append(total)
            total += arr.size
        joined = np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
        flat_extents = [
            (off + o, s)
            for off, extents in zip(offsets, per_file_extents)
            for o, s in extents
        ]
        return self._digests_bucketed(joined, flat_extents)

    def digest_many(self, datas: list[bytes]) -> list[bytes]:
        """Batched digests of pre-delimited chunks (no CDC) — the tarfs /
        index build sources, where boundaries come from the tar layout."""
        if not datas:
            return []
        if self.digester == "blake3":
            items = [(np.frombuffer(d, dtype=np.uint8), 0, len(d)) for d in datas]
            if self.digest_backend == "jax":
                return self._digests_bucketed_b3(items)
            return _host_digests_blake3(items)
        if self.digest_backend == "numpy":
            import hashlib

            return [hashlib.sha256(d).digest() for d in datas]
        if self.digest_backend == "host":
            return _host_digests(
                [(np.frombuffer(d, dtype=np.uint8), 0, len(d)) for d in datas]
            )
        arr = np.frombuffer(b"".join(datas), dtype=np.uint8)
        extents = []
        off = 0
        for d in datas:
            extents.append((off, len(d)))
            off += len(d)
        return self._digests_bucketed(arr, extents)

    # -- end to end ---------------------------------------------------------

    def process(self, data: bytes | np.ndarray) -> list[ChunkMeta]:
        """Chunk one stream and digest every chunk."""
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else data
        cuts = self.boundaries(arr)
        digests = self.digests(arr, cuts)
        return [
            ChunkMeta(offset=o, size=s, digest=d)
            for (o, s), d in zip(cdc.cuts_to_extents(cuts), digests)
        ]

    def process_many(self, streams: list[bytes]) -> list[list[ChunkMeta]]:
        """Per-file chunking (nydus chunks each file independently).

        Boundaries run per stream (thread-parallel on the hybrid backend:
        the native chunker drops the GIL), then ALL chunks are digested in
        one global pass — a single big device batch or one host thread-pool
        sweep, instead of a tiny batch per file.
        """
        if not streams:
            return []
        arrs = [
            np.frombuffer(s, dtype=np.uint8) if isinstance(s, (bytes, bytearray)) else s
            for s in streams
        ]
        if self.backend == "fused" and self.mode == "cdc":
            out = self._process_many_device_fused(arrs)
            if out is not None:
                return out
        if self._fused_available():
            return self._process_many_fused(arrs)
        all_cuts = self.boundaries_many(arrs)

        per_file_extents = [cdc.cuts_to_extents(c) for c in all_cuts]
        flat_digests = self.digest_all(arrs, per_file_extents)
        out: list[list[ChunkMeta]] = []
        pos = 0
        for extents in per_file_extents:
            metas = [
                ChunkMeta(offset=o, size=s, digest=flat_digests[pos + i])
                for i, (o, s) in enumerate(extents)
            ]
            pos += len(extents)
            out.append(metas)
        return out

    def _fused_available(self) -> bool:
        """Single-pass native chunk+digest (SIMD bitmaps + SHA-NI): the
        host latency arm's fast path — chunk bytes digested cache-warm,
        one GIL-dropping call per stream."""
        if not (
            self.mode == "cdc"
            and self.backend == "hybrid"
            and self.digest_backend == "host"
            # the fused arm digests with SHA-NI or 8-way-AVX2 blake3; both
            # route through the native algo dispatch (ntpu_chunk_digest)
            and self.digester in ("sha256", "blake3")
        ):
            return False
        from nydus_snapshotter_tpu.ops import native_cdc

        return native_cdc.chunk_digest_available()

    def _process_many_device_fused(
        self, arrs: list[np.ndarray]
    ) -> list[list[ChunkMeta]] | None:
        """Full-path device composition (ops/fused_convert): the whole
        batch as two device dispatches — gear+compaction, then
        gather+digest — with only candidate/cut metadata on the host.
        Returns None on candidate-capacity overflow (pathological input)
        so process_many falls through to the windowed device path."""
        from nydus_snapshotter_tpu.ops import fused_convert

        eng = fused_convert.FusedDeviceEngine(
            chunk_size=self.chunk_size, digester=self.digester
        )
        try:
            res = eng.process_many(arrs)
        except fused_convert.FusedOverflow:
            return None
        return [
            [
                ChunkMeta(offset=o, size=s, digest=d)
                for (o, s), d in zip(cdc.cuts_to_extents(cuts), digests)
            ]
            for cuts, digests in zip(res.cuts, res.digests)
        ]

    def _process_many_fused(self, arrs: list[np.ndarray]) -> list[list[ChunkMeta]]:
        from nydus_snapshotter_tpu.ops import native_cdc

        def one(arr: np.ndarray) -> list[ChunkMeta]:
            cuts, digests = native_cdc.chunk_digest_native(
                arr, self.params, digester=self.digester
            )
            start = 0
            metas = []
            for i, c in enumerate(cuts):
                metas.append(
                    ChunkMeta(
                        offset=start,
                        size=int(c) - start,
                        digest=digests[32 * i : 32 * (i + 1)],
                    )
                )
                start = int(c)
            return metas

        return _map_threads(one, arrs)
