"""converter hooks tests: layer conversion, manifest rewrite, cs proxy,
feature detection (reference convert_unix.go:822-1219, cs_proxy_unix.go,
tool/feature.go)."""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import tarfile
import urllib.request

import pytest

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu.converter import convert
from nydus_snapshotter_tpu.converter.content import LocalContentStore
from nydus_snapshotter_tpu.converter.cs_proxy import ContentStoreProxy
from nydus_snapshotter_tpu.converter.feature import Feature, detect_features
from nydus_snapshotter_tpu.converter.hooks import (
    convert_image,
    is_nydus_blob,
    is_nydus_bootstrap,
    is_nydus_image,
    layer_convert_func,
    merge_layers,
)
from nydus_snapshotter_tpu.converter.types import MergeOption, PackOption
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap
from nydus_snapshotter_tpu.remote.registry import Descriptor


def make_layer_tar(files: dict[str, bytes]) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:", format=tarfile.GNU_FORMAT) as tf:
        for name, data in files.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mode = 0o644
            tf.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def publish_oci_image(cs: LocalContentStore, layer_files: list[dict[str, bytes]]):
    """Write layers (gzip), config, manifest into the content store."""
    layers = []
    diff_ids = []
    for files in layer_files:
        tar = make_layer_tar(files)
        blob = gzip.compress(tar, mtime=0)
        info = cs.write_blob(blob)
        layers.append(
            {
                "mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
                "digest": info.digest,
                "size": info.size,
            }
        )
        diff_ids.append("sha256:" + hashlib.sha256(tar).hexdigest())
    config = {
        "architecture": "amd64",
        "os": "linux",
        "rootfs": {"type": "layers", "diff_ids": diff_ids},
        "history": [{"created_by": f"layer {i}"} for i in range(len(layers))],
    }
    cfg_body = json.dumps(config).encode()
    cfg_info = cs.write_blob(cfg_body)
    manifest = {
        "schemaVersion": 2,
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "config": {
            "mediaType": "application/vnd.oci.image.config.v1+json",
            "digest": cfg_info.digest,
            "size": cfg_info.size,
        },
        "layers": layers,
    }
    body = json.dumps(manifest).encode()
    info = cs.write_blob(body)
    return Descriptor(
        media_type="application/vnd.oci.image.manifest.v1+json",
        digest=info.digest,
        size=info.size,
    )


@pytest.fixture()
def cs(tmp_path):
    return LocalContentStore(str(tmp_path / "content"))


def _pack_opt():
    return PackOption(backend="numpy", compressor="none", chunking="fixed")


class TestLayerConvert:
    def test_converts_oci_layer_to_nydus_blob(self, cs):
        tar = make_layer_tar({"etc/app": b"config"})
        blob = gzip.compress(tar, mtime=0)
        info = cs.write_blob(blob)
        desc = Descriptor(
            media_type="application/vnd.oci.image.layer.v1.tar+gzip",
            digest=info.digest,
            size=info.size,
        )
        new_desc = layer_convert_func(_pack_opt())(cs, desc)
        assert new_desc is not None
        assert new_desc.media_type == C.MEDIA_TYPE_NYDUS_BLOB
        assert is_nydus_blob(new_desc)
        assert cs.exists(new_desc.digest)
        # conversion cache label left on the source
        assert cs.info(desc.digest).labels[C.LAYER_ANNOTATION_NYDUS_TARGET_DIGEST] == new_desc.digest

    def test_conversion_cache_is_a_noop(self, cs):
        tar = make_layer_tar({"f": b"x"})
        blob = gzip.compress(tar, mtime=0)
        info = cs.write_blob(blob)
        desc = Descriptor("application/vnd.oci.image.layer.v1.tar+gzip", info.digest, info.size)
        fn = layer_convert_func(_pack_opt())
        first = fn(cs, desc)
        count_before = len(list(cs.walk()))
        second = fn(cs, desc)
        assert second.digest == first.digest
        assert len(list(cs.walk())) == count_before  # nothing new written

    def test_skips_non_layer_and_nydus_types(self, cs):
        fn = layer_convert_func(_pack_opt())
        assert fn(cs, Descriptor("application/weird", "sha256:" + "0" * 64, 1)) is None
        nydus = Descriptor(
            C.MEDIA_TYPE_NYDUS_BLOB, "sha256:" + "0" * 64, 1,
            annotations={C.LAYER_ANNOTATION_NYDUS_BLOB: "true"},
        )
        assert fn(cs, nydus) is None


class TestConvertImage:
    def test_full_image_conversion(self, cs):
        manifest_desc = publish_oci_image(
            cs,
            [{"bin/sh": b"#!/bin/sh", "etc/one": b"1"}, {"etc/two": b"2"}],
        )
        new_desc = convert_image(
            cs, manifest_desc, _pack_opt(), MergeOption(oci=True)
        )
        manifest = json.loads(cs.read(new_desc.digest))
        assert is_nydus_image(manifest)
        layers = [Descriptor.from_json(o) for o in manifest["layers"]]
        assert all(is_nydus_blob(d) for d in layers[:-1])
        boot_desc = layers[-1]
        assert is_nydus_bootstrap(boot_desc)
        assert boot_desc.annotations[C.LAYER_ANNOTATION_FS_VERSION] == "6"
        # bootstrap layer is a gzip'd nydus-tar stream carrying the bootstrap
        # (convert_manifest forces with_tar, convert_unix.go:956)
        boot_gz = cs.read(boot_desc.digest)
        bs = convert.bootstrap_from_bootstrap_layer(gzip.decompress(boot_gz))
        paths = {i.path for i in bs.inodes}
        assert {"/bin/sh", "/etc/one", "/etc/two"} <= paths
        # config diffIDs rewritten: one per layer incl. bootstrap
        config = json.loads(cs.read(manifest["config"]["digest"]))
        assert len(config["rootfs"]["diff_ids"]) == len(manifest["layers"])
        assert config["history"][-1]["comment"] == "Nydus Bootstrap Layer"
        # GC labels on the manifest
        labels = cs.info(new_desc.digest).labels
        assert any(k.startswith("containerd.io/gc.ref.content.l.") for k in labels)

    def test_already_nydus_image_untouched(self, cs):
        manifest_desc = publish_oci_image(cs, [{"a": b"1"}])
        once = convert_image(cs, manifest_desc, _pack_opt(), MergeOption(oci=True))
        twice = convert_image(cs, once, _pack_opt(), MergeOption(oci=True))
        assert twice.digest == once.digest


class TestMergeLayers:
    def test_bootstrap_and_blob_descs(self, cs):
        opt = _pack_opt()
        descs = []
        for files in ({"x": b"x" * 100}, {"y": b"y" * 100}):
            tar = make_layer_tar(files)
            stream, result = convert.pack_layer(tar, opt)
            info = cs.write_blob(stream)
            descs.append(
                Descriptor(
                    C.MEDIA_TYPE_NYDUS_BLOB, info.digest, info.size,
                    annotations={C.LAYER_ANNOTATION_NYDUS_BLOB: "true"},
                )
            )
        boot_desc, blob_descs = merge_layers(cs, descs, MergeOption(with_tar=False, oci=True))
        assert boot_desc.media_type == "application/vnd.oci.image.layer.v1.tar+gzip"
        assert is_nydus_bootstrap(boot_desc)
        assert len(blob_descs) == 2
        assert all(d.media_type == C.MEDIA_TYPE_NYDUS_BLOB for d in blob_descs)


class TestContentStoreProxy:
    def test_serves_blob_ranges(self, cs):
        info = cs.write_blob(b"0123456789abcdef")
        proxy = ContentStoreProxy(cs)
        proxy.start()
        try:
            url = proxy.blob_url(info.digest, offset=4, size=6)
            with urllib.request.urlopen(url) as r:
                assert r.read() == b"456789"
            with urllib.request.urlopen(proxy.blob_url(info.digest)) as r:
                assert r.read() == b"0123456789abcdef"
        finally:
            proxy.stop()

    def test_unknown_blob_404(self, cs):
        proxy = ContentStoreProxy(cs)
        proxy.start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(proxy.blob_url("sha256:" + "9" * 64))
        finally:
            proxy.stop()


class TestFeatures:
    def test_detect_features_cached(self):
        f1 = detect_features(force=True)
        f2 = detect_features()
        assert f1 is f2
        assert f1.contains(Feature.TAR_RAFS)
        assert f1.contains(Feature.CDC_CHUNKING)
        # ENCRYPT tracks whether a cipher backend is importable here.
        import importlib.util

        assert f1.contains(Feature.ENCRYPT) == (
            importlib.util.find_spec("cryptography") is not None
        )
        assert not f1.contains(Feature.BATCH_SIZE)


class TestSmallUtils:
    def test_reflink_auto_falls_back_to_copy(self, tmp_path):
        from nydus_snapshotter_tpu.utils.reflink import auto

        src = tmp_path / "src"
        src.write_bytes(b"payload")
        dst = tmp_path / "dst"
        auto(str(src), str(dst))
        assert dst.read_bytes() == b"payload"

    def test_sysinfo(self):
        from nydus_snapshotter_tpu.utils import sysinfo

        assert sysinfo.get_memory_bytes() > 0
        assert sysinfo.kernel_at_least(3, 0)
        assert not sysinfo.kernel_at_least(99, 0)

    def test_version(self):
        from nydus_snapshotter_tpu import version

        assert version.VERSION in version.pretty()

    def test_export_shim(self):
        from nydus_snapshotter_tpu import export

        assert callable(export.build_stack)
