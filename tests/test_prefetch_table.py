"""Prefetch tables: pattern matching at pack time, warm-up at mount time.

Reference flow: access traces feed ``--prefetch-files`` into the builder
(docs/optimize_nydus_image.md), the bootstrap carries a prefetch table, and
nydusd warms those files at mount (daemon_adaptor.go:179-185 passes the
list; the blobcache metric reports prefetch_data_amount)."""

import io
import json
import os
import tarfile
import time

import numpy as np
import pytest

from nydus_snapshotter_tpu.converter.convert import (
    Merge,
    match_prefetch_paths,
    pack_layer,
)
from nydus_snapshotter_tpu.converter.types import MergeOption, PackOption
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap, BootstrapError

from tests.test_converter import build_tar, _rand

RNG = np.random.default_rng(0xFE7C)


class TestBootstrapTable:
    def test_roundtrip_preserves_order(self):
        src = build_tar(
            [("bin/app", _rand(10_000)), ("etc/conf", b"k=v"), ("var/log", b"x")],
            dirs=["bin", "etc", "var"],
        )
        opt = PackOption(chunk_size=0x1000, prefetch_patterns="etc\nbin/app\n")
        _, res = pack_layer(src, opt)
        bs = Bootstrap.from_bytes(res.bootstrap)
        assert bs.prefetch == ["/etc/conf", "/bin/app"]
        # re-serialize: identical table
        assert Bootstrap.from_bytes(bs.to_bytes()).prefetch == bs.prefetch

    def test_directory_pattern_expands_to_files(self):
        inodes = Bootstrap.from_bytes(
            pack_layer(
                build_tar(
                    [("app/a", b"1"), ("app/sub/b", b"2"), ("other/c", b"3")],
                    dirs=["app", "app/sub", "other"],
                ),
                PackOption(chunk_size=0x1000),
            )[1].bootstrap
        ).inodes
        assert match_prefetch_paths(inodes, "app") == ["/app/a", "/app/sub/b"]
        assert match_prefetch_paths(inodes, "/") == ["/app/a", "/app/sub/b", "/other/c"]
        assert match_prefetch_paths(inodes, "missing\napp/sub/") == ["/app/sub/b"]

    def test_unknown_prefetch_inode_rejected_on_parse(self):
        src = build_tar([("f", b"data")])
        _, res = pack_layer(src, PackOption(chunk_size=0x1000, prefetch_patterns="f"))
        buf = bytearray(res.bootstrap)
        # find the prefetch table: single u32 entry; corrupt it to a huge ino
        bs = Bootstrap.from_bytes(bytes(buf))
        assert bs.prefetch == ["/f"]
        import struct

        # superblock at 1024 for v6; prefetch off/count at _SB offset 120
        off, count = struct.unpack_from("<II", buf, 1024 + 120)
        assert count == 1
        struct.pack_into("<I", buf, off, 9999)
        with pytest.raises(BootstrapError):
            Bootstrap.from_bytes(bytes(buf))

    def test_merge_carries_patterns(self):
        b1, _ = pack_layer(build_tar([("a/x", _rand(5000))], dirs=["a"]),
                           PackOption(chunk_size=0x1000))
        b2, _ = pack_layer(build_tar([("b/y", _rand(5000))], dirs=["b"]),
                           PackOption(chunk_size=0x1000))
        m = Merge([b1, b2], MergeOption(prefetch_patterns="b\na/x"))
        bs = Bootstrap.from_bytes(m.bootstrap)
        assert bs.prefetch == ["/b/y", "/a/x"]


class TestDaemonWarmup:
    def test_mount_warms_prefetch_and_reports_amount(self, tmp_path):
        from nydus_snapshotter_tpu.converter.convert import blob_data_from_layer_blob
        from tests.test_fusedev import _spawn_daemon

        payload = RNG.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
        src = build_tar(
            [("warm/data.bin", payload), ("cold/other.bin", _rand(100_000))],
            dirs=["warm", "cold"],
        )
        blob, res = pack_layer(
            src, PackOption(chunk_size=0x1000, prefetch_patterns="warm\n")
        )
        blob_dir = tmp_path / "blobs"
        blob_dir.mkdir()
        (blob_dir / res.blob_id).write_bytes(blob_data_from_layer_blob(blob))
        boot = tmp_path / "image.boot"
        boot.write_bytes(res.bootstrap)
        mp = tmp_path / "mnt"
        mp.mkdir()

        proc, cli = _spawn_daemon(str(tmp_path), "prefetch-d")
        try:
            cfg = json.dumps(
                {"device": {"backend": {"config": {"blob_dir": str(blob_dir)}}}}
            )
            cli.mount(str(mp), str(boot), cfg)
            deadline = time.time() + 10
            amount = 0
            while time.time() < deadline:
                amount = cli.cache_metrics().get("prefetch_data_amount", 0)
                if amount >= len(payload):
                    break
                time.sleep(0.1)
            assert amount == len(payload), (
                f"prefetch warmed {amount} bytes, wanted {len(payload)}"
            )
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestTraceToPatterns:
    def test_trace_file_closes_the_optimizer_loop(self, tmp_path):
        """fanotify trace -> prefetch patterns -> packed table, end to end
        (the reference's optimize_nydus_image.md flow)."""
        from nydus_snapshotter_tpu.prefetch.prefetch import patterns_from_trace

        trace = tmp_path / "app:latest"
        trace.write_text(
            "/rootfs/bin/app\n/rootfs/etc/conf\n/rootfs/bin/app\n\n/rootfs/lib/so\n"
        )
        patterns = patterns_from_trace(str(trace), strip_prefix="/rootfs")
        assert patterns == "/bin/app\n/etc/conf\n/lib/so"

        src = build_tar(
            [("bin/app", _rand(4000)), ("etc/conf", b"k=v"), ("lib/so", _rand(2000)),
             ("bin/unused", b"cold")],
            dirs=["bin", "etc", "lib"],
        )
        _, res = pack_layer(
            src, PackOption(chunk_size=0x1000, prefetch_patterns=patterns)
        )
        bs = Bootstrap.from_bytes(res.bootstrap)
        assert bs.prefetch == ["/bin/app", "/etc/conf", "/lib/so"]
