"""Converter parity features: lz4_block, batch chunk packing, blob encryption.

Closes the PackOption surface against the reference builder knobs
(``--compressor lz4_block``, ``--batch-size``, ``--encrypt`` —
pkg/converter/tool/builder.go:128-141, types.go:58-90): the full
fs_version x compressor x batch x encrypt x chunk-dict matrix must
round-trip byte-exact, and the storage-level effects (shared batch extents,
actually-encrypted blob bytes, cipher context travel through Merge) are
asserted directly.
"""

import io
import itertools
import os
import tarfile

import importlib.util

import pytest

# Blob encryption needs a cipher backend; without it the --encrypt arms
# are skipped (converter/crypto.py gates the same way).
HAS_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(not HAS_CRYPTO, reason="cryptography not installed")
ENC_ARMS = [False, True] if HAS_CRYPTO else [False]

from nydus_snapshotter_tpu.converter import Merge, MergeOption, Pack, PackOption, Unpack
from nydus_snapshotter_tpu.converter.convert import (
    blob_data_from_layer_blob,
    bootstrap_from_layer_blob,
    pack_layer,
)
from nydus_snapshotter_tpu.converter.types import ConvertError
from nydus_snapshotter_tpu.models.bootstrap import CHUNK_FLAG_BATCH, Bootstrap
from nydus_snapshotter_tpu.utils import lz4

from tests.test_converter import build_tar, tar_tree, _rand


def small_files_tar() -> bytes:
    """Many sub-4K files (batch candidates) plus one big file."""
    files = [(f"cfg/file-{i}", _rand(200 + 37 * i)) for i in range(12)]
    files.append(("data/big", _rand(120_000)))
    return build_tar(files, dirs=["cfg", "data"])


def roundtrip(src: bytes, opt: PackOption) -> tuple[bytes, "Bootstrap", dict]:
    blob, res = pack_layer(src, opt)
    bs = Bootstrap.from_bytes(res.bootstrap)
    out_tar = Unpack(bs, {res.blob_id: blob_data_from_layer_blob(blob)})
    return blob, bs, tar_tree(out_tar)


class TestLz4:
    def test_block_roundtrip(self):
        for data in (b"", b"a", b"repetition " * 4096, os.urandom(70_000)):
            assert lz4.decompress_block(lz4.compress_block(data), len(data)) == data

    def test_fallback_interops_with_native(self):
        data = b"the quick brown fox " * 500
        native = lz4.compress_block(data)
        assert lz4._decompress_py(native, len(data)) == data
        literals = lz4._compress_literals(data)
        assert lz4.decompress_block(literals, len(data)) == data

    def test_corrupt_block_rejected(self):
        comp = lz4.compress_block(b"payload " * 1000)
        with pytest.raises(lz4.LZ4Error):
            lz4.decompress_block(comp[: len(comp) // 2], 8000)
        with pytest.raises(lz4.LZ4Error):
            lz4.decompress_block(comp, 17)

    def test_pack_with_lz4(self):
        # Highly compressible content so real lz4 must shrink the blob (the
        # literals-only fallback would keep it >= uncompressed).
        files = [(f"f/{i}", b"compress-me " * 2000) for i in range(4)]
        src = build_tar(files, dirs=["f"])
        _blob, bs, tree = roundtrip(src, PackOption(compressor="lz4_block", backend="numpy"))
        assert tree == tar_tree(src)
        blob_rec = bs.blobs[0]
        assert blob_rec.compressed_size < blob_rec.uncompressed_size


class TestBatchPacking:
    def test_small_chunks_share_extents(self):
        src = small_files_tar()
        opt = PackOption(batch_size=0x1000, backend="numpy", compressor="zstd")
        _blob, bs, tree = roundtrip(src, opt)
        assert tree == tar_tree(src)
        batched = [c for c in bs.chunks if c.flags & CHUNK_FLAG_BATCH]
        assert batched, "no chunk carries the batch flag"
        # Several chunks share one compressed extent.
        extents = {(c.compressed_offset, c.compressed_size) for c in batched}
        assert len(extents) < len(batched)
        # Big-file chunks stay unbatched.
        unbatched = [c for c in bs.chunks if not c.flags & CHUNK_FLAG_BATCH]
        assert unbatched

    def test_batch_reduces_blob_size_on_small_files(self):
        # Many tiny similar files: per-chunk zstd can't exploit cross-file
        # redundancy; a shared batch can.
        files = [(f"f/{i}", (b"common-prefix " * 20) + bytes([i])) for i in range(64)]
        src = build_tar(files, dirs=["f"])
        _b1, bs1, _ = roundtrip(src, PackOption(backend="numpy", compressor="zstd"))
        _b2, bs2, _ = roundtrip(
            src, PackOption(backend="numpy", compressor="zstd", batch_size=0x10000)
        )
        assert bs2.blobs[0].compressed_size < bs1.blobs[0].compressed_size

    def test_partial_reference_into_dict_batch(self, tmp_path):
        # Regression: a dict blob built WITH batching, and a new layer whose
        # content matches only the MIDDLE member of one dict batch. The new
        # bootstrap carries that single batched record; without the batch
        # table the base would be mis-derived and reads silently corrupt.
        members = [(f"d/m{i}", bytes([65 + i]) * (600 + i * 7)) for i in range(5)]
        dict_src = build_tar(members, dirs=["d"])
        dict_blob, dict_res = pack_layer(
            dict_src, PackOption(backend="numpy", batch_size=0x1000, compressor="zstd")
        )
        dict_path = tmp_path / "dict.boot"
        dict_path.write_bytes(dict_res.bootstrap)

        middle = members[2][1]
        src = build_tar([("x/only-middle", middle)], dirs=["x"])
        blob, res = pack_layer(
            src, PackOption(backend="numpy", chunk_dict_path=str(dict_path))
        )
        assert dict_res.blob_id in res.referenced_blob_ids
        out = Unpack(
            res.bootstrap,
            {
                res.blob_id: blob_data_from_layer_blob(blob),
                dict_res.blob_id: blob_data_from_layer_blob(dict_blob),
            },
        )
        assert tar_tree(out)["/x/only-middle"][1] == middle

        # Same through Merge: merged bootstrap must carry the batch table.
        merged = Merge([blob], MergeOption(chunk_dict_path=str(dict_path)))
        bs = Bootstrap.from_bytes(merged.bootstrap)
        assert bs.batches, "merged bootstrap lost the batch table"
        out2 = Unpack(
            bs,
            {
                res.blob_id: blob_data_from_layer_blob(blob),
                dict_res.blob_id: blob_data_from_layer_blob(dict_blob),
            },
        )
        assert tar_tree(out2)["/x/only-middle"][1] == middle

    def test_batch_size_validation(self):
        with pytest.raises(ConvertError):
            PackOption(batch_size=0x1001).validate()
        with pytest.raises(ConvertError):
            PackOption(batch_size=0x800).validate()
        PackOption(batch_size=0x1000).validate()
        PackOption(batch_size=0).validate()


@requires_crypto
class TestEncryption:
    def test_blob_bytes_are_encrypted(self):
        payload = b"SECRET-MARKER-0123456789" * 400
        src = build_tar([("s/secret", payload)], dirs=["s"])
        opt = PackOption(encrypt=True, compressor="none", backend="numpy")
        blob, bs, tree = roundtrip(src, opt)
        assert tree == tar_tree(src)
        assert bs.ciphers and bs.ciphers[0].algo != 0
        data = blob_data_from_layer_blob(blob)
        assert b"SECRET-MARKER" not in data
        # cipher context round-trips through bootstrap serialization
        bs2 = Bootstrap.from_bytes(bs.to_bytes())
        assert bs2.ciphers[0].key == bs.ciphers[0].key
        assert bs2.ciphers[0].iv == bs.ciphers[0].iv

    def test_merge_carries_cipher(self):
        lower = build_tar([("a/f1", _rand(9_000))], dirs=["a"])
        upper = build_tar([("b/f2", _rand(7_000))], dirs=["b"])
        opt = PackOption(encrypt=True, backend="numpy")
        blob_l, res_l = pack_layer(lower, opt)
        blob_u, res_u = pack_layer(upper, opt)
        merged = Merge([blob_l, blob_u], MergeOption())
        bs = Bootstrap.from_bytes(merged.bootstrap)
        assert len(bs.ciphers) == len(bs.blobs)
        assert all(c.algo != 0 for c in bs.ciphers)
        out = Unpack(
            bs,
            {
                res_l.blob_id: blob_data_from_layer_blob(blob_l),
                res_u.blob_id: blob_data_from_layer_blob(blob_u),
            },
        )
        tree = tar_tree(out)
        assert tree["/a/f1"][1] == tar_tree(lower)["/a/f1"][1]
        assert tree["/b/f2"][1] == tar_tree(upper)["/b/f2"][1]

    def test_mixed_encrypted_and_plain_layers(self):
        lower = build_tar([("a/f1", _rand(9_000))], dirs=["a"])
        upper = build_tar([("b/f2", _rand(7_000))], dirs=["b"])
        blob_l, res_l = pack_layer(lower, PackOption(encrypt=True, backend="numpy"))
        blob_u, res_u = pack_layer(upper, PackOption(encrypt=False, backend="numpy"))
        merged = Merge([blob_l, blob_u], MergeOption())
        bs = Bootstrap.from_bytes(merged.bootstrap)
        algos = {b.blob_id: c.algo for b, c in zip(bs.blobs, bs.ciphers)}
        assert algos[res_l.blob_id] != 0
        assert algos[res_u.blob_id] == 0
        out = Unpack(
            bs,
            {
                res_l.blob_id: blob_data_from_layer_blob(blob_l),
                res_u.blob_id: blob_data_from_layer_blob(blob_u),
            },
        )
        assert tar_tree(out)["/a/f1"][1] == tar_tree(lower)["/a/f1"][1]


class TestFullMatrix:
    @pytest.mark.parametrize("fs_version", ["v5", "v6"])
    def test_matrix_roundtrip(self, fs_version):
        src = small_files_tar()
        want = tar_tree(src)
        for comp, batch, enc in itertools.product(
            ["none", "zstd", "lz4_block"], [0, 0x1000], ENC_ARMS
        ):
            opt = PackOption(
                fs_version=fs_version,
                compressor=comp,
                batch_size=batch,
                encrypt=enc,
                backend="numpy",
            )
            _blob, _bs, tree = roundtrip(src, opt)
            assert tree == want, (fs_version, comp, batch, enc)

    def test_matrix_with_chunk_dict(self, tmp_path):
        # Dict layer shares content with the packed layer; dict hits must
        # survive batch+encrypt packing of the new blob.
        shared = _rand(30_000)
        dict_src = build_tar([("d/shared", shared)], dirs=["d"])
        dict_blob, dict_res = pack_layer(dict_src, PackOption(backend="numpy"))
        dict_bs_path = tmp_path / "dict.boot"
        dict_bs_path.write_bytes(dict_res.bootstrap)

        src = build_tar(
            [("x/shared", shared), ("x/own", _rand(10_000))]
            + [(f"x/tiny-{i}", _rand(300)) for i in range(8)],
            dirs=["x"],
        )
        for comp, batch, enc in itertools.product(["zstd"], [0, 0x1000], ENC_ARMS):
            opt = PackOption(
                chunk_dict_path=str(dict_bs_path),
                compressor=comp,
                batch_size=batch,
                encrypt=enc,
                backend="numpy",
            )
            blob, res = pack_layer(src, opt)
            assert dict_res.blob_id in res.referenced_blob_ids
            out = Unpack(
                res.bootstrap,
                {
                    res.blob_id: blob_data_from_layer_blob(blob),
                    dict_res.blob_id: blob_data_from_layer_blob(dict_blob),
                },
            )
            tree = tar_tree(out)
            assert tree["/x/shared"][1] == shared
