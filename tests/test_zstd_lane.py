"""zstd compressor lane: native fused arm + cross-lane byte identity.

The reference's modern chunk compressor default is zstd. The fused native
section assembly (ntpu_pack_section compressor=2, dlopen'd system
libzstd at level 3) and the Python codec lane (utils/zstd.py binding the
SAME system library) must produce byte-identical blobs — the invariant
that caught a real divergence: the ``zstandard`` package bundles its own
libzstd whose frames can differ from the system build (a 1.3 MiB mixed
chunk: 920,855 vs 921,118 bytes).
"""

from __future__ import annotations

import io
import random
import tarfile

import numpy as np
import pytest

# The whole point of this module is comparing the BUNDLED zstandard build
# against the system library — without the package there is nothing to
# compare, so skip (the converter itself runs on utils/zstdcompat).
zstandard = pytest.importorskip("zstandard")

from nydus_snapshotter_tpu.converter.convert import (
    Pack,
    Unpack,
    bootstrap_from_layer_blob,
)
from nydus_snapshotter_tpu.converter.types import PackOption
from nydus_snapshotter_tpu.ops import native_cdc
from nydus_snapshotter_tpu.utils import zstd as zstd_native


def _mixed_payload():
    rng = random.Random(12)
    return (b"The quick brown fox. " * 20000) + bytes(
        rng.randrange(256) for _ in range(1_500_000)
    )


def _mktar(payload):
    b = io.BytesIO()
    with tarfile.open(fileobj=b, mode="w") as tf:
        ti = tarfile.TarInfo("z.bin")
        ti.size = len(payload)
        tf.addfile(ti, io.BytesIO(payload))
    return b.getvalue()


class TestZstdLane:
    def test_inmemory_and_streaming_pack_identical(self):
        # The in-memory path takes the fused native zstd arm; the
        # file-like path replays the Python codec — bytes must match.
        payload = _mixed_payload()
        tarb = _mktar(payload)
        d1, d2 = io.BytesIO(), io.BytesIO()
        r1 = Pack(d1, tarb, PackOption(compressor="zstd"))
        r2 = Pack(d2, io.BytesIO(tarb), PackOption(compressor="zstd"))
        assert r1.blob_id == r2.blob_id
        assert d1.getvalue() == d2.getvalue()

    def test_zstd_roundtrip(self):
        payload = _mixed_payload()
        d = io.BytesIO()
        r = Pack(d, _mktar(payload), PackOption(compressor="zstd"))
        assert r.blob_size < len(payload)  # the text half compresses
        out = Unpack(
            bootstrap_from_layer_blob(d.getvalue()).to_bytes(),
            {r.blob_id: d.getvalue()},
        )
        got = tarfile.open(fileobj=io.BytesIO(out)).extractfile("z.bin").read()
        assert got == payload

    @pytest.mark.skipif(
        not (zstd_native.available() and native_cdc.pack_section_available()),
        reason="system libzstd or native engine unavailable",
    )
    def test_native_section_matches_python_codec_and_threads(self):
        payload = _mixed_payload()
        arr = np.frombuffer(payload, dtype=np.uint8)
        ext = np.asarray(
            [(0, 0, 1_340_756), (0, 1_340_756, len(payload) - 1_340_756)],
            dtype=np.int64,
        )
        from nydus_snapshotter_tpu import constants

        lvl = constants.ZSTD_LEVEL  # the codec-param slot carries the level
        serial = native_cdc.pack_section(arr, np.empty(0, np.uint8), ext, 2, lvl, 1)
        threaded = native_cdc.pack_section(arr, np.empty(0, np.uint8), ext, 2, lvl, 4)
        assert serial is not None and threaded is not None
        assert serial[0].tobytes() == threaded[0].tobytes()
        # per-chunk frames equal the Python lane (same system library)
        for (coff, csize), (o, s) in zip(serial[1].tolist(), [(0, 1_340_756), (1_340_756, len(payload) - 1_340_756)]):
            frame = serial[0][coff : coff + csize].tobytes()
            assert frame == zstd_native.compress_block(payload[o : o + s])
            # and any conforming decompressor reads it back
            assert zstandard.decompress(frame) == payload[o : o + s]

    @pytest.mark.skipif(
        not zstd_native.available(), reason="system libzstd unavailable"
    )
    def test_utils_zstd_frames_decode(self):
        for n in (0, 1, 1000, 1 << 20):
            data = bytes(range(256)) * (n // 256) + b"x" * (n % 256)
            frame = zstd_native.compress_block(data)
            assert zstandard.decompress(frame) == data
