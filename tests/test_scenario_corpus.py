"""Scenario corpus generators: determinism, CDC-resonance properties,
degenerate tree shapes, corrupt-blob CRC rejection (ISSUE 14 satellite).

Every generator must be a pure function of its seed/parameters — the
scenario engine's serial-replay identity gate depends on it — and the
adversarial generators must actually have the adversarial property they
claim (resonance proven against the FastCDC engine AND the byte-at-a-
time sequential oracle, corruption proven rejected by the peer tier's
CRC frame).
"""

from __future__ import annotations

import hashlib
import io
import os
import tarfile

import numpy as np
import pytest

from nydus_snapshotter_tpu.ops.cdc import (
    CDCParams,
    chunk_data_np,
    chunk_sequential_reference,
)
from nydus_snapshotter_tpu.scenario import corpus


def _tar_names(data: bytes) -> list:
    with tarfile.open(fileobj=io.BytesIO(data)) as tf:
        return [m.name for m in tf.getmembers()]


class TestDeterminism:
    def test_incompressible_deterministic(self):
        assert corpus.incompressible_layer(7, 1) == corpus.incompressible_layer(7, 1)
        assert corpus.incompressible_layer(7, 1) != corpus.incompressible_layer(8, 1)

    def test_compressible_deterministic(self):
        assert corpus.compressible_layer(3, 1) == corpus.compressible_layer(3, 1)

    def test_tiny_files_deterministic(self):
        a = corpus.tiny_files_layer(5, 500)
        assert a == corpus.tiny_files_layer(5, 500)
        assert a != corpus.tiny_files_layer(6, 500)

    def test_huge_file_deterministic(self):
        assert (
            corpus.single_huge_file_layer(9, 2)
            == corpus.single_huge_file_layer(9, 2)
        )

    def test_resonant_deterministic(self):
        a = corpus.cdc_resonant_data(3, 64 << 10, 0x1000, "min")
        assert a == corpus.cdc_resonant_data(3, 64 << 10, 0x1000, "min")
        assert a != corpus.cdc_resonant_data(4, 64 << 10, 0x1000, "min")

    def test_real_trees_deterministic(self):
        t1 = corpus.members_to_tar(corpus.real_tree_members())
        assert t1 == corpus.members_to_tar(corpus.real_tree_members())
        t2 = corpus.members_to_tar(corpus.real_tree2_members())
        assert t2 == corpus.members_to_tar(corpus.real_tree2_members())
        assert t1 != t2


class TestRealTrees:
    def test_tree2_is_real_derived_subgraph(self):
        """Tree2's paths are a subset of tree1's (a sibling sharing the
        real base — no synthesized paths), with some files diverged."""
        m1 = corpus.load_manifest(corpus.MANIFEST_TREE1)
        m2 = corpus.load_manifest(corpus.MANIFEST_TREE2)
        paths1 = {e["path"] for e in m1["entries"]}
        paths2 = {e["path"] for e in m2["entries"]}
        assert paths2 < paths1
        assert m2["dropped"] > 0 and m2["changed"] > 0
        assert m2["inodes"] == len(m2["entries"])
        assert "derivation" in m2

    def test_shared_paths_share_content_changed_do_not(self):
        """Same (path, gen) synthesizes identical bytes across trees —
        the mechanism cross-tree dedup rides on; gen=1 entries diverge."""
        m2 = corpus.load_manifest(corpus.MANIFEST_TREE2)
        changed = next(
            e for e in m2["entries"] if e.get("gen") and e["size"] > 0
        )
        same = next(
            e
            for e in m2["entries"]
            if not e.get("gen") and e.get("chunks") and e["size"] > 0
        )
        assert corpus.synth_content(same["path"], 0, same["size"]) == \
            corpus.synth_content(same["path"], 0, same["size"])
        assert corpus.synth_content(changed["path"], 1, changed["size"]) != \
            corpus.synth_content(changed["path"], 0, changed["size"])

    def test_cross_tree_dedup_ratio(self):
        """Real-vs-real: tree2 against tree1's REAL-v6-round-trip dict.
        Deterministic corpus + fixed grid => a stable, substantial ratio
        strictly below 1 (the changed/dropped delta is real)."""
        r = corpus.cross_tree_dedup()
        assert 0.3 <= r["dedup_ratio"] < 1.0
        assert r["dict_chunks"] > 0
        assert "caveat" in r and "synthesized" in r["caveat"]


class TestCdcResonance:
    @pytest.mark.parametrize("avg", [0x1000, 0x4000])
    def test_min_mode_every_chunk_cuts_at_min_size(self, avg):
        params = CDCParams(avg)
        data = corpus.cdc_resonant_data(11, 16 * params.min_size, avg, "min")
        cuts = chunk_data_np(data, params)
        sizes = np.diff(np.concatenate([[0], cuts]))
        assert set(sizes[:-1].tolist()) == {params.min_size}
        assert sizes[-1] <= params.min_size

    def test_max_mode_no_content_cut_ever_fires(self):
        params = CDCParams(0x1000)
        data = corpus.cdc_resonant_data(11, 4 * params.max_size + 100, 0x1000, "max")
        cuts = chunk_data_np(data, params)
        sizes = np.diff(np.concatenate([[0], cuts]))
        assert set(sizes[:-1].tolist()) == {params.max_size}

    def test_resonance_holds_on_sequential_oracle(self):
        """The property is an engine property, not a quirk of the
        two-phase pipeline: the byte-at-a-time reference chunker agrees."""
        params = CDCParams(0x1000)
        data = corpus.cdc_resonant_data(2, 8 * params.min_size, 0x1000, "min")
        seq = chunk_sequential_reference(data, params)
        sizes = np.diff(np.concatenate([[0], seq]))
        assert set(sizes[:-1].tolist()) == {params.min_size}

    def test_min_mode_maximizes_chunk_count(self):
        params = CDCParams(0x1000)
        n = 32 * params.min_size
        resonant = corpus.cdc_resonant_data(1, n, 0x1000, "min")
        random_data = np.random.default_rng(1).integers(
            0, 256, n, dtype=np.uint8
        ).tobytes()
        assert len(chunk_data_np(resonant, params)) > len(
            chunk_data_np(random_data, params)
        )

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            corpus.cdc_resonant_data(1, 4096, 0x1000, "sideways")


class TestDegenerateTrees:
    def test_tiny_files_layer_shape(self):
        n = 2000
        data = corpus.tiny_files_layer(3, n)
        names = _tar_names(data)
        assert len(names) == n
        with tarfile.open(fileobj=io.BytesIO(data)) as tf:
            sizes = [m.size for m in tf.getmembers()]
        assert max(sizes) <= 64 and min(sizes) >= 1

    def test_single_huge_file_layer_shape(self):
        data = corpus.single_huge_file_layer(3, 2)
        with tarfile.open(fileobj=io.BytesIO(data)) as tf:
            members = tf.getmembers()
        assert len(members) == 1
        assert members[0].size == 2 << 20

    def test_incompressible_really_is(self):
        import zlib

        data = corpus.incompressible_layer(5, 1)
        assert len(zlib.compress(data, 6)) > 0.95 * len(data)

    def test_compressible_really_is(self):
        import zlib

        data = corpus.compressible_layer(5, 1)
        assert len(zlib.compress(data, 6)) < 0.5 * len(data)


class TestCorruptVariants:
    @pytest.mark.parametrize("mode", ["flip", "truncate", "zero"])
    def test_corrupt_differs_and_is_deterministic(self, mode):
        data = corpus.incompressible_layer(1, 1)
        bad = corpus.corrupt_variant(data, 9, mode)
        assert bad != data
        assert bad == corpus.corrupt_variant(data, 9, mode)
        if mode == "truncate":
            assert len(bad) < len(data)
        else:
            assert len(bad) == len(data)

    def test_empty_and_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            corpus.corrupt_variant(b"", 1, "flip")
        with pytest.raises(ValueError):
            corpus.corrupt_variant(b"x", 1, "shuffle")

    def test_peer_crc_rejects_corrupt_blob(self, tmp_path):
        """The hostile-peer contract end to end: a peer serving a
        corrupted payload under a stale CRC header is rejected by the
        requester's CRC check, the fetcher falls back to the origin, and
        the requester's cache holds the TRUE bytes."""
        from nydus_snapshotter_tpu.daemon import peer
        from nydus_snapshotter_tpu.daemon.blobcache import CachedBlob
        from nydus_snapshotter_tpu.daemon.fetch_sched import FetchConfig
        from nydus_snapshotter_tpu.scenario.orchestrator import CorruptPeerServer

        blob = corpus.incompressible_layer(2, 1)
        blob_id = "cd" * 32
        owner = CachedBlob(
            str(tmp_path / "owner"), blob_id,
            lambda off, size: blob[off : off + size], blob_size=len(blob),
            config=FetchConfig(fetch_workers=1, merge_gap=0, readahead=0),
        )
        owner.read_at(0, len(blob))  # warmed: serves cover hits
        export = peer.PeerExport()
        export.register(blob_id, owner)
        srv = CorruptPeerServer(
            peer.PeerChunkServer(export, pull_through=True), seed=4
        )
        addr = str(tmp_path / "peer.sock")
        srv.run(addr)
        try:
            router = peer.PeerRouter([addr], self_address="")
            fetcher = peer.PeerAwareFetcher(
                blob_id, lambda off, size: blob[off : off + size], router,
                timeout_s=5.0,
            )
            requester = CachedBlob(
                str(tmp_path / "req"), blob_id, fetcher.read_range,
                blob_size=len(blob),
                config=FetchConfig(fetch_workers=1, merge_gap=0, readahead=0),
            )
            got = requester.read_at(0, len(blob))
            requester.close()
            assert srv.corrupted > 0, "hostile peer never served"
            assert hashlib.sha256(got).hexdigest() == hashlib.sha256(blob).hexdigest()
            # The poisoned payload must never land in the cache file.
            cache_file = str(tmp_path / "req" / f"{blob_id}.blob.data")
            if os.path.exists(cache_file):
                with open(cache_file, "rb") as f:
                    cached = f.read()
                assert cached[: len(blob)] == blob
        finally:
            srv.stop()
            owner.close()
