"""L10 observability tests: metric primitives + text format, /proc tool,
collectors over a fake manager, metrics HTTP listener, system controller
REST over UDS, prefetch manager, pprof listener.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import time

import pytest

from nydus_snapshotter_tpu.metrics import data
from nydus_snapshotter_tpu.metrics import tool as mtool
from nydus_snapshotter_tpu.metrics.collector import (
    DaemonResourceCollector,
    SnapshotterMetricsCollector,
    record_daemon_event,
    snapshot_timer,
)
from nydus_snapshotter_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    TTLGauge,
)
from nydus_snapshotter_tpu.metrics.serve import MetricsServer
from nydus_snapshotter_tpu.prefetch import Pm
from nydus_snapshotter_tpu.system.system import SystemController


# ------------------------------------------------------------------ primitives


def test_counter_render():
    r = Registry()
    c = r.register(Counter("events_total", "Events.", ("kind",)))
    c.labels("start").inc()
    c.labels("start").inc(2)
    c.labels("stop").inc()
    text = r.render()
    assert '# TYPE events_total counter' in text
    assert 'events_total{kind="start"} 3' in text
    assert 'events_total{kind="stop"} 1' in text


def test_gauge_set_and_remove():
    g = Gauge("g", "G.", ("image",))
    g.labels("a").set(1.5)
    assert g.value("a") == 1.5
    g.remove("a")
    assert g.value("a") is None


def test_ttl_gauge_expiry():
    clock = [0.0]
    g = TTLGauge("t", "T.", ("id",), ttl_sec=10.0, clock=lambda: clock[0])
    g.labels("d1").set(1)
    assert 't{id="d1"} 1' in g.render()
    clock[0] = 11.0
    assert 'd1' not in g.render()


def test_histogram_buckets_and_timer():
    h = Histogram("lat_ms", "Latency.", ("op",), buckets=(1, 10, 100))
    h.labels("prepare").observe(5)
    h.labels("prepare").observe(50)
    text = h.render()
    assert 'lat_ms_bucket{op="prepare",le="1"} 0' in text
    assert 'lat_ms_bucket{op="prepare",le="10"} 1' in text
    assert 'lat_ms_bucket{op="prepare",le="100"} 2' in text
    assert 'lat_ms_bucket{op="prepare",le="+Inf"} 2' in text
    assert 'lat_ms_count{op="prepare"} 2' in text
    with h.labels("remove").time_ms():
        pass
    assert 'lat_ms_count{op="remove"} 1' in h.render()


def test_snapshot_timer_records():
    with snapshot_timer("prepare"):
        pass
    assert "snapshotter_snapshot_operation_elapsed_milliseconds" in (
        data.SnapshotEventElapsedHists.render()
    )


# ----------------------------------------------------------------- /proc tools


def test_proc_stat_self():
    st = mtool.read_process_stat(os.getpid())
    assert st.threads >= 1
    assert st.utime >= 0
    assert mtool.get_process_memory_rss_kb(os.getpid()) > 1000
    assert mtool.get_fd_count(os.getpid()) > 0
    assert mtool.run_time_seconds(os.getpid()) >= 0


def test_cpu_sampler():
    s = mtool.CPUSampler(os.getpid())
    s.sample()
    sum(i * i for i in range(200000))  # burn some cpu
    util = s.sample()
    assert util >= 0.0


# ------------------------------------------------------------------ collectors


class _FakeDaemonStates:
    api_socket = "/tmp/api.sock"
    supervisor_path = ""
    config_path = ""
    fs_driver = "fusedev"


class _FakeDaemon:
    def __init__(self, id_="d1"):
        self.id = id_
        self.states = _FakeDaemonStates()

        class _Instances:
            @staticmethod
            def list():
                return []

        self.instances = _Instances()

    def pid(self):
        return os.getpid()

    def state(self):
        from nydus_snapshotter_tpu.daemon.types import DaemonState

        return DaemonState.RUNNING

    def ref_count(self):
        return 0

    def client(self):
        raise ConnectionError("no daemon in tests")


class _FakeManager:
    def __init__(self):
        self._daemons = [_FakeDaemon()]

    def list_daemons(self):
        return self._daemons

    def get_by_daemon_id(self, daemon_id):
        for d in self._daemons:
            if d.id == daemon_id:
                return d
        return None


def test_snapshotter_collector(tmp_path):
    (tmp_path / "blob1").write_bytes(b"x" * 2048)
    c = SnapshotterMetricsCollector(str(tmp_path))
    c.collect()
    assert data.CacheUsage.value() == 2.0  # KiB
    assert data.MemoryUsage.value() > 0


def test_daemon_resource_collector():
    DaemonResourceCollector([_FakeManager()]).collect()
    assert data.DaemonCount.value() == 1
    assert data.DaemonRSS.value("d1") > 0


def test_record_daemon_event():
    record_daemon_event("d9", "start")
    assert data.DaemonEvent.value("d9", "start") is not None


# -------------------------------------------------------------- HTTP listeners


def test_metrics_http_listener(tmp_path):
    server = MetricsServer(managers=[_FakeManager()], cache_dir=str(tmp_path))
    server.serve("127.0.0.1:0")
    try:
        server.collect_once()
        host, port = server._httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=5)
        conn.request("GET", "/v1/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert "snapshotter_memory_usage_kilobytes" in body
        assert "nydusd_counts" in body
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()
    finally:
        server.stop()


def _uds_request(sock_path: str, method: str, path: str, body: bytes = b"") -> tuple[int, bytes]:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.settimeout(5)
        s.connect(sock_path)
        req = f"{method} {path} HTTP/1.1\r\nHost: uds\r\nContent-Length: {len(body)}\r\n\r\n".encode() + body
        s.sendall(req)
        resp = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            resp += chunk
            if b"\r\n\r\n" in resp:
                head, _, rest = resp.partition(b"\r\n\r\n")
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        want = int(line.split(b":")[1])
                        if len(rest) >= want:
                            return int(head.split()[1]), rest[:want]
        status = int(resp.split()[1]) if resp else 0
        return status, b""
    finally:
        s.close()


def test_system_controller(tmp_path):
    sock = str(tmp_path / "system.sock")
    sc = SystemController(managers=[_FakeManager()], sock_path=sock)
    sc.run()
    try:
        status, body = _uds_request(sock, "GET", "/api/v1/daemons")
        assert status == 200
        daemons = json.loads(body)
        assert daemons[0]["id"] == "d1"
        assert daemons[0]["memory_rss_kb"] > 0
        assert daemons[0]["pid"] == os.getpid()

        # prefetch PUT feeds the global map
        Pm.reset()
        payload = json.dumps([{"image": "ghcr.io/a/b:v1", "prefetch": "/bin;/usr/bin"}]).encode()
        status, _ = _uds_request(sock, "PUT", "/api/v1/prefetch", payload)
        assert status == 200
        assert Pm.get_prefetch_info("ghcr.io/a/b:v1") == "/bin;/usr/bin"

        # bad prefetch body -> 400
        status, _ = _uds_request(sock, "PUT", "/api/v1/prefetch", b"{not json")
        assert status == 400

        # backend of unknown daemon -> 404
        status, _ = _uds_request(sock, "GET", "/api/v1/daemons/nope/backend")
        assert status == 404
        # backend of known daemon (no config file) -> empty backend
        status, body = _uds_request(sock, "GET", "/api/v1/daemons/d1/backend")
        assert status == 200 and json.loads(body)["config"] == {}

        # upgrade with a bad binary path -> 404
        status, _ = _uds_request(
            sock, "PUT", "/api/v1/daemons/upgrade",
            json.dumps({"nydusd_path": "/no/such/bin"}).encode(),
        )
        assert status == 404
    finally:
        sc.stop()
        Pm.reset()


def test_backend_secret_filtering(tmp_path):
    from nydus_snapshotter_tpu.config.daemonconfig import DaemonRuntimeConfig

    cfg = DaemonRuntimeConfig.from_dict(
        {"device": {"backend": {"type": "registry", "config": {
            "auth": "c2VjcmV0", "scheme": "https", "host": "reg.example.com"}}}},
        "fusedev",
    )
    cfg_path = str(tmp_path / "cfg.json")
    cfg.dump(cfg_path)

    mgr = _FakeManager()
    mgr._daemons[0].states.config_path = cfg_path
    sock = str(tmp_path / "system2.sock")
    sc = SystemController(managers=[mgr], sock_path=sock)
    sc.run()
    try:
        status, body = _uds_request(sock, "GET", "/api/v1/daemons/d1/backend")
        assert status == 200
        assert b"c2VjcmV0" not in body  # secret scrubbed
        assert b"reg.example.com" in body
    finally:
        sc.stop()


def test_prefetch_manager():
    Pm.reset()
    Pm.set_prefetch_files(json.dumps([{"image": "x", "prefetch": "/a"}]))
    assert Pm.get_prefetch_info("x") == "/a"
    assert Pm.get_prefetch_info("y") == ""
    Pm.delete("x")
    assert Pm.get_prefetch_info("x") == ""
    with pytest.raises((ValueError, KeyError)):
        Pm.set_prefetch_files(b"{}")
    Pm.reset()


def test_pprof_listener():
    from nydus_snapshotter_tpu.pprof import new_pprof_http_listener

    httpd = new_pprof_http_listener("127.0.0.1:0")
    try:
        host, port = httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=5)
        conn.request("GET", "/debug/pprof/threads")
        resp = conn.getresponse()
        assert resp.status == 200
        assert b"thread " in resp.read()
        conn.request("GET", "/debug/pprof/heap")
        resp = conn.getresponse()
        assert resp.status == 200 and b"gc_counts" in resp.read()
        conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
