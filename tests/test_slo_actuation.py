"""SLO-driven admission actuation: lane caps/shedding on the gate,
the actuator's escalate/restore ladder, the member-side follower,
demand-read protection in the blobcache, and chaos on slo.actuate."""

import threading
import time

import pytest

from nydus_snapshotter_tpu import failpoint
from nydus_snapshotter_tpu.daemon import fetch_sched
from nydus_snapshotter_tpu.daemon.fetch_sched import (
    DEMAND,
    PEER_SERVE,
    PREFETCH,
    READAHEAD,
    AdmissionGate,
    LaneShedError,
)
from nydus_snapshotter_tpu.metrics.slo import (
    SloActuationFollower,
    SloActuator,
    SloEngine,
    SloObjective,
    SloSpecError,
    resolve_slo_actuation,
)
from nydus_snapshotter_tpu.parallel.pipeline import MemoryBudget


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.clear()
    yield
    failpoint.clear()


def mk_gate(**kw):
    kw.setdefault("budget", MemoryBudget(8 << 20))
    kw.setdefault("max_concurrent", 4)
    kw.setdefault("name", "t")
    return AdmissionGate(**kw)


class FakeEngine:
    """Engine stand-in: tests drive breach/burn state directly."""

    def __init__(self):
        self.b: list = []
        self.burn = 0.0

    def breached(self):
        return list(self.b)

    def max_burn_short(self):
        return self.burn


class TestGateLaneActuation:
    def test_shed_lane_rejects_immediately(self):
        g = mk_gate()
        g.set_lane_cap(PEER_SERVE, 0)
        with pytest.raises(LaneShedError):
            g.acquire(100, lane=PEER_SERVE)
        assert g.lane_state()["peer_serve"]["shed_total"] == 1
        # demand is untouched
        g.acquire(100, lane=DEMAND)
        g.release(100, lane=DEMAND)

    def test_queued_waiter_rejected_when_lane_sheds(self):
        g = mk_gate(max_concurrent=1)
        g.acquire(10, lane=DEMAND)  # occupy the only slot
        err: list = []

        def waiter():
            try:
                g.acquire(10, lane=PREFETCH)
            except LaneShedError as e:
                err.append(e)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)  # it is queued now
        g.set_lane_cap(PREFETCH, 0)
        t.join(timeout=5)
        assert not t.is_alive() and err
        g.release(10, lane=DEMAND)

    def test_partial_cap_bounds_lane_in_service(self):
        g = mk_gate(max_concurrent=8)
        g.set_lane_cap(READAHEAD, 1)
        g.acquire(10, lane=READAHEAD)
        blocked = threading.Event()

        def second():
            g.acquire(10, lane=READAHEAD)
            blocked.set()
            g.release(10, lane=READAHEAD)

        t = threading.Thread(target=second, daemon=True)
        t.start()
        assert not blocked.wait(0.3)  # capped at 1 in service
        g.release(10, lane=READAHEAD)
        assert blocked.wait(5)  # released slot admits the waiter
        t.join()

    def test_restore_reopens_lane(self):
        g = mk_gate()
        g.set_lane_cap(PEER_SERVE, 0)
        g.set_lane_cap(PEER_SERVE, None)
        g.acquire(10, lane=PEER_SERVE)
        g.release(10, lane=PEER_SERVE)

    def test_demand_lane_not_actuatable(self):
        g = mk_gate()
        with pytest.raises(ValueError):
            g.set_lane_cap(DEMAND, 0)

    def test_release_lane_accounting(self):
        g = mk_gate()
        g.acquire(10, lane=PREFETCH)
        assert g.lane_state()["prefetch"]["in_service"] == 1
        g.release(10, lane=PREFETCH)
        assert g.lane_state()["prefetch"]["in_service"] == 0

    def test_snapshot_carries_actuation_view(self):
        g = mk_gate()
        g.set_lane_cap(PREFETCH, 0)
        snap = g.snapshot()
        assert snap["lane_caps"]["prefetch"] == 0
        assert snap["lane_caps"]["demand"] is None


class TestDemandProtection:
    def test_demand_read_survives_shed_background_flight(self, tmp_path):
        """A demand read that piggybacks on a readahead flight the
        actuation shed must REPLAN at demand priority, not fail."""
        from nydus_snapshotter_tpu.daemon.blobcache import CachedBlob
        from nydus_snapshotter_tpu.daemon.fetch_sched import FetchConfig

        blob = bytes(range(256)) * 2048  # 512 KiB
        gate = mk_gate()
        cb = CachedBlob(
            str(tmp_path / "c"), "ee" * 32,
            lambda off, size: blob[off:off + size], blob_size=len(blob),
            config=FetchConfig(fetch_workers=2, merge_gap=0,
                               readahead=128 << 10),
            gate=gate,
        )
        try:
            gate.set_lane_cap(READAHEAD, 0)
            gate.set_lane_cap(PREFETCH, 0)
            # sequential reads spawn readahead flights that shed; demand
            # bytes must still come back correct
            got = b"".join(
                cb.read_at(off, 64 << 10) for off in range(0, len(blob), 64 << 10)
            )
            assert got == blob
            # prefetch warming degrades (contained), never demand
            flights = cb.warm(0, 64 << 10)
            assert all(
                f.error is None or isinstance(f.error, LaneShedError)
                for f in flights
            )
        finally:
            cb.close()

    def test_peer_serve_read_fails_fast_when_shed(self, tmp_path):
        from nydus_snapshotter_tpu.daemon.blobcache import CachedBlob
        from nydus_snapshotter_tpu.daemon.fetch_sched import FetchConfig

        blob = b"x" * (64 << 10)
        gate = mk_gate()
        cb = CachedBlob(
            str(tmp_path / "c"), "ff" * 32,
            lambda off, size: blob[off:off + size], blob_size=len(blob),
            config=FetchConfig(fetch_workers=1, merge_gap=0, readahead=0),
            gate=gate,
        )
        try:
            gate.set_lane_cap(PEER_SERVE, 0)
            with pytest.raises(OSError):
                cb.read_at(0, 1024, lane=PEER_SERVE)
            gate.set_lane_cap(PEER_SERVE, None)
            assert cb.read_at(0, 1024) == blob[:1024]
        finally:
            cb.close()


class TestActuator:
    def test_escalates_one_lane_per_tick_and_restores_in_reverse(self):
        g = mk_gate()
        eng = FakeEngine()
        act = SloActuator(eng, gate=g)
        eng.b = ["obj"]
        e1 = act.tick()
        assert (e1["action"], e1["lane"]) == ("shed", "peer_serve")
        e2 = act.tick()
        assert (e2["action"], e2["lane"]) == ("shed", "prefetch")
        e3 = act.tick()
        assert (e3["action"], e3["lane"]) == ("shed", "readahead")
        assert act.tick() is None  # ladder exhausted, holds
        assert act.state()["shed_lanes"] == ["peer_serve", "prefetch", "readahead"]
        eng.b, eng.burn = [], 0.5
        r1 = act.tick()
        assert (r1["action"], r1["lane"]) == ("restore", "readahead")
        assert act.tick()["lane"] == "prefetch"
        assert act.tick()["lane"] == "peer_serve"
        assert act.tick() is None
        assert act.state()["shed_lanes"] == []

    def test_no_restore_while_burn_high(self):
        g = mk_gate()
        eng = FakeEngine()
        act = SloActuator(eng, gate=g, restore_burn=1.0)
        eng.b = ["obj"]
        act.tick()
        eng.b, eng.burn = [], 1.5  # breach cleared but burn still hot
        assert act.tick() is None
        assert act.state()["shed_depth"] == 1

    def test_demand_lane_rejected_in_config(self):
        with pytest.raises(SloSpecError):
            SloActuator(FakeEngine(), gate=mk_gate(), shed_lanes=["demand"])
        with pytest.raises(SloSpecError):
            SloActuator(FakeEngine(), gate=mk_gate(), shed_lanes=["bogus"])

    def test_slo_actuate_chaos_surfaces(self):
        g = mk_gate()
        eng = FakeEngine()
        act = SloActuator(eng, gate=g)
        eng.b = ["obj"]
        with failpoint.injected("slo.actuate", "error(OSError:chaos)*1"):
            with pytest.raises(OSError, match="chaos"):
                act.tick()
        # one-shot: the next tick actuates (the fleet loop catches and
        # retries next round — this pins that the fault doesn't wedge)
        assert act.tick()["action"] == "shed"

    def test_actuations_metered(self):
        from nydus_snapshotter_tpu.metrics.slo import SLO_ACTUATIONS

        base = SLO_ACTUATIONS.value("shed", "peer_serve")
        eng = FakeEngine()
        act = SloActuator(eng, gate=mk_gate())
        eng.b = ["obj"]
        act.tick()
        assert SLO_ACTUATIONS.value("shed", "peer_serve") == base + 1


class TestEngineActuatorLoop:
    def test_real_engine_breach_drives_shed_and_restore(self):
        """End-to-end on a real engine with a controlled clock: a latency
        regression on the histogram sheds lanes; recovery restores."""
        from nydus_snapshotter_tpu.metrics import registry as _metrics

        reg = _metrics.Registry()
        hist = reg.register(_metrics.Histogram(
            "ntpu_slo_test_op_ms", "t", ("op",)))
        clock = [0.0]
        obj = SloObjective(
            name="t", metric="ntpu_slo_test_op_ms", labels={"op": "x"},
            threshold_ms=50.0, target=0.9, window_secs=10.0,
            long_window_factor=2.0, burn_threshold=2.0,
        )
        from nydus_snapshotter_tpu.metrics.slo import local_source

        eng = SloEngine([obj], source=local_source(reg),
                        clock=lambda: clock[0])
        g = mk_gate()
        act = SloActuator(eng, gate=g, clock=lambda: clock[0])
        # healthy traffic
        for _ in range(10):
            for _i in range(5):
                hist.labels("x").observe(5.0)
            eng.tick()
            act.tick()
            clock[0] += 5
        assert act.state()["shed_depth"] == 0
        # regression: every op over threshold
        for _ in range(10):
            for _i in range(5):
                hist.labels("x").observe(500.0)
            eng.tick()
            act.tick()
            clock[0] += 5
        assert act.state()["shed_depth"] > 0
        assert eng.status()["breaches"]
        with pytest.raises(LaneShedError):
            g.acquire(1, lane=PEER_SERVE)
        # recovery
        for _ in range(20):
            for _i in range(20):
                hist.labels("x").observe(5.0)
            eng.tick()
            act.tick()
            clock[0] += 5
        assert act.state()["shed_depth"] == 0
        g.acquire(1, lane=PEER_SERVE)
        g.release(1, lane=PEER_SERVE)


class TestFollower:
    def test_follower_applies_and_clears_published_state(self):
        g = mk_gate()
        published = {"shed_lanes": ["peer_serve"]}
        f = SloActuationFollower("unused", gate=g, fetch=lambda: dict(published))
        assert f.poll_once()
        with pytest.raises(LaneShedError):
            g.acquire(1, lane=PEER_SERVE)
        published["shed_lanes"] = ["peer_serve", "prefetch"]
        assert f.poll_once()
        with pytest.raises(LaneShedError):
            g.acquire(1, lane=PREFETCH)
        published["shed_lanes"] = []
        assert f.poll_once()
        g.acquire(1, lane=PEER_SERVE)
        g.release(1, lane=PEER_SERVE)

    def test_poll_failure_keeps_last_state(self):
        g = mk_gate()
        state = {"fail": False}

        def fetch():
            if state["fail"]:
                raise OSError("controller down")
            return {"shed_lanes": ["prefetch"]}

        f = SloActuationFollower("unused", gate=g, fetch=fetch)
        f.poll_once()
        state["fail"] = True
        assert not f.poll_once()  # unchanged, no flap
        with pytest.raises(LaneShedError):
            g.acquire(1, lane=PREFETCH)

    def test_stop_restores_everything(self):
        g = mk_gate()
        f = SloActuationFollower(
            "unused", gate=g, fetch=lambda: {"shed_lanes": ["peer_serve"]}
        )
        f.poll_once()
        f.stop()
        g.acquire(1, lane=PEER_SERVE)
        g.release(1, lane=PEER_SERVE)

    def test_follower_never_sheds_demand(self):
        g = mk_gate()
        f = SloActuationFollower(
            "unused", gate=g, fetch=lambda: {"shed_lanes": ["demand", "prefetch"]}
        )
        f.poll_once()
        g.acquire(1, lane=DEMAND)
        g.release(1, lane=DEMAND)
        with pytest.raises(LaneShedError):
            g.acquire(1, lane=PREFETCH)


class TestConfigResolution:
    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("NTPU_SLO_ACTUATE", "1")
        monkeypatch.setenv("NTPU_SLO_SHED_LANES", "peer_serve,readahead")
        monkeypatch.setenv("NTPU_SLO_RESTORE_BURN", "0.5")
        actuate, lanes, restore = resolve_slo_actuation()
        assert actuate
        assert lanes == ["peer_serve", "readahead"]
        assert restore == 0.5

    def test_config_section_validation(self):
        from nydus_snapshotter_tpu.config.config import ConfigError, load_config

        with pytest.raises(ConfigError, match="demand"):
            load_config(overrides={"slo": {"shed_lanes": ["demand"]}})
        with pytest.raises(ConfigError, match="restore_burn"):
            load_config(overrides={"slo": {"restore_burn": -1.0}})
        cfg = load_config(overrides={"slo": {
            "actuate": True, "shed_lanes": ["peer_serve"], "restore_burn": 0.8,
        }})
        assert cfg.slo.actuate and cfg.slo.restore_burn == 0.8

    def test_peer_membership_validation(self):
        from nydus_snapshotter_tpu.config.config import ConfigError, load_config

        with pytest.raises(ConfigError, match="membership"):
            load_config(overrides={"peer": {"membership": "gossip"}})
        cfg = load_config(overrides={"peer": {
            "membership": "fleet", "membership_refresh_secs": 0.5,
        }})
        assert cfg.peer.membership == "fleet"

    def test_build_actuator_off_by_default(self, monkeypatch):
        from nydus_snapshotter_tpu.metrics.slo import build_actuator

        monkeypatch.delenv("NTPU_SLO_ACTUATE", raising=False)
        assert build_actuator(SloEngine([])) is None
        monkeypatch.setenv("NTPU_SLO_ACTUATE", "1")
        act = build_actuator(SloEngine([]))
        assert isinstance(act, SloActuator)
